// bigdl_tpu native runtime — host-side data plane.
//
// The reference ships native code for everything off the JVM hot path
// (BigDL-core: MKL gemm wrappers, MKL-DNN, bigquant int8 gemm, OpenCV
// image ops — SURVEY.md §2.3).  On TPU the *compute* replacements are
// XLA/Pallas, but the host-side runtime around the chip keeps the same
// split: the pieces below are the feeding path (image decode/augment,
// minibatch assembly, fp16 wire codec) where C++ beats Python by
// releasing the GIL and touching memory once.
//
// Exposed as a plain C ABI consumed via ctypes (no pybind11 in the
// image); every function is thread-safe and operates on caller-owned
// buffers.

#include <cstdint>
#include <cstring>
#include <cmath>
#include <algorithm>
#include <thread>
#include <vector>

extern "C" {

// --------------------------------------------------------------------------
// fp16 codec — FP16CompressedTensor parity («bigdl»/parameters/
// FP16CompressedTensor.scala truncates to sign+exp+7 mantissa bits; we
// keep IEEE half with round-to-nearest-even, strictly more accurate on
// the same 16-bit budget)
// --------------------------------------------------------------------------

static inline uint16_t f32_to_f16(float f) {
    uint32_t x;
    std::memcpy(&x, &f, 4);
    uint32_t sign = (x >> 16) & 0x8000u;
    int32_t  exp  = (int32_t)((x >> 23) & 0xffu) - 127 + 15;
    uint32_t mant = x & 0x7fffffu;
    if (exp >= 0x1f) {                      // inf / nan / overflow
        uint16_t m = (((x >> 23) & 0xffu) == 0xffu && mant) ? 0x200u : 0u;
        return (uint16_t)(sign | 0x7c00u | m);
    }
    if (exp <= 0) {                         // subnormal / underflow
        if (exp < -10) return (uint16_t)sign;
        mant |= 0x800000u;
        uint32_t shift = (uint32_t)(14 - exp);
        uint32_t half = mant >> shift;
        uint32_t rem  = mant & ((1u << shift) - 1u);
        uint32_t mid  = 1u << (shift - 1);
        if (rem > mid || (rem == mid && (half & 1u))) half++;
        return (uint16_t)(sign | half);
    }
    uint32_t half = ((uint32_t)exp << 10) | (mant >> 13);
    uint32_t rem = mant & 0x1fffu;
    if (rem > 0x1000u || (rem == 0x1000u && (half & 1u))) half++;
    return (uint16_t)(sign | half);
}

static inline float f16_to_f32(uint16_t h) {
    uint32_t sign = (uint32_t)(h & 0x8000u) << 16;
    uint32_t exp  = (h >> 10) & 0x1fu;
    uint32_t mant = h & 0x3ffu;
    uint32_t x;
    if (exp == 0) {
        if (mant == 0) { x = sign; }
        else {
            exp = 127 - 15 + 1;
            while (!(mant & 0x400u)) { mant <<= 1; exp--; }
            mant &= 0x3ffu;
            x = sign | (exp << 23) | (mant << 13);
        }
    } else if (exp == 0x1f) {
        x = sign | 0x7f800000u | (mant << 13);
    } else {
        x = sign | ((exp - 15 + 127) << 23) | (mant << 13);
    }
    float f;
    std::memcpy(&f, &x, 4);
    return f;
}

void fp16_compress(const float* src, uint16_t* dst, int64_t n) {
    for (int64_t i = 0; i < n; i++) dst[i] = f32_to_f16(src[i]);
}

void fp16_decompress(const uint16_t* src, float* dst, int64_t n) {
    for (int64_t i = 0; i < n; i++) dst[i] = f16_to_f32(src[i]);
}

// --------------------------------------------------------------------------
// minibatch assembly — shuffled row gather (+ optional normalize) in one
// memory pass; the multi-threaded variant splits rows across threads
// with the GIL released on the Python side
// --------------------------------------------------------------------------

void gather_rows(const float* src, const int64_t* idx, float* dst,
                 int64_t n_rows, int64_t row_len) {
    for (int64_t i = 0; i < n_rows; i++)
        std::memcpy(dst + i * row_len, src + idx[i] * row_len,
                    (size_t)row_len * 4);
}

void gather_rows_mt(const float* src, const int64_t* idx, float* dst,
                    int64_t n_rows, int64_t row_len, int n_threads) {
    if (n_threads <= 1 || n_rows < 2 * n_threads) {
        gather_rows(src, idx, dst, n_rows, row_len);
        return;
    }
    std::vector<std::thread> pool;
    int64_t chunk = (n_rows + n_threads - 1) / n_threads;
    for (int t = 0; t < n_threads; t++) {
        int64_t lo = t * chunk, hi = std::min(n_rows, lo + chunk);
        if (lo >= hi) break;
        pool.emplace_back([=] {
            gather_rows(src + 0, idx + lo, dst + lo * row_len,
                        hi - lo, row_len);
        });
    }
    for (auto& th : pool) th.join();
}

// gather uint8 rows and convert to normalized float in one pass:
// dst = (u8 - mean[c]) / std[c], channel-major rows (C*H*W)
void gather_normalize_u8(const uint8_t* src, const int64_t* idx, float* dst,
                         int64_t n_rows, int64_t channels, int64_t hw,
                         const float* mean, const float* stdev) {
    int64_t row_len = channels * hw;
    for (int64_t i = 0; i < n_rows; i++) {
        const uint8_t* in = src + idx[i] * row_len;
        float* out = dst + i * row_len;
        for (int64_t c = 0; c < channels; c++) {
            float m = mean[c], inv = 1.0f / stdev[c];
            const uint8_t* ic = in + c * hw;
            float* oc = out + c * hw;
            for (int64_t p = 0; p < hw; p++)
                oc[p] = ((float)ic[p] - m) * inv;
        }
    }
}

// --------------------------------------------------------------------------
// image ops — the OpenCV-JNI replacements (CHW float32 images)
// --------------------------------------------------------------------------

// bilinear resize, CHW float32 (align_corners=false, OpenCV-compatible
// half-pixel centers)
void resize_bilinear_chw(const float* src, float* dst,
                         int64_t c, int64_t in_h, int64_t in_w,
                         int64_t out_h, int64_t out_w) {
    float sy = (float)in_h / (float)out_h;
    float sx = (float)in_w / (float)out_w;
    for (int64_t y = 0; y < out_h; y++) {
        float fy = ((float)y + 0.5f) * sy - 0.5f;
        int64_t y0 = (int64_t)std::floor(fy);
        float wy = fy - (float)y0;
        int64_t y0c = std::clamp(y0, (int64_t)0, in_h - 1);
        int64_t y1c = std::clamp(y0 + 1, (int64_t)0, in_h - 1);
        for (int64_t x = 0; x < out_w; x++) {
            float fx = ((float)x + 0.5f) * sx - 0.5f;
            int64_t x0 = (int64_t)std::floor(fx);
            float wx = fx - (float)x0;
            int64_t x0c = std::clamp(x0, (int64_t)0, in_w - 1);
            int64_t x1c = std::clamp(x0 + 1, (int64_t)0, in_w - 1);
            for (int64_t ch = 0; ch < c; ch++) {
                const float* p = src + ch * in_h * in_w;
                float v00 = p[y0c * in_w + x0c];
                float v01 = p[y0c * in_w + x1c];
                float v10 = p[y1c * in_w + x0c];
                float v11 = p[y1c * in_w + x1c];
                float top = v00 + (v01 - v00) * wx;
                float bot = v10 + (v11 - v10) * wx;
                dst[ch * out_h * out_w + y * out_w + x] =
                    top + (bot - top) * wy;
            }
        }
    }
}

// crop a (c, h, w) window starting at (y, x)
void crop_chw(const float* src, float* dst,
              int64_t c, int64_t in_h, int64_t in_w,
              int64_t y, int64_t x, int64_t out_h, int64_t out_w) {
    for (int64_t ch = 0; ch < c; ch++)
        for (int64_t r = 0; r < out_h; r++)
            std::memcpy(dst + (ch * out_h + r) * out_w,
                        src + (ch * in_h + (y + r)) * in_w + x,
                        (size_t)out_w * 4);
}

// horizontal flip in place-safe form (src != dst)
void hflip_chw(const float* src, float* dst,
               int64_t c, int64_t h, int64_t w) {
    for (int64_t ch = 0; ch < c; ch++)
        for (int64_t r = 0; r < h; r++) {
            const float* in = src + (ch * h + r) * w;
            float* out = dst + (ch * h + r) * w;
            for (int64_t x = 0; x < w; x++) out[x] = in[w - 1 - x];
        }
}

// per-channel normalize in place: data = (data - mean[c]) / std[c]
void normalize_chw(float* data, int64_t c, int64_t hw,
                   const float* mean, const float* stdev) {
    for (int64_t ch = 0; ch < c; ch++) {
        float m = mean[ch], inv = 1.0f / stdev[ch];
        float* p = data + ch * hw;
        for (int64_t i = 0; i < hw; i++) p[i] = (p[i] - m) * inv;
    }
}

int native_abi_version() { return 1; }

}  // extern "C"
