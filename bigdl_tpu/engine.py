"""Engine — process-level resource singleton.

Rebuild of «bigdl»/utils/Engine.scala + ThreadPool.scala.  The reference's
Engine detects node/core counts from the Spark conf, builds the task/model
thread pools with MKL pinning, and validates required Spark properties
(SURVEY.md §3.1).  On TPU none of that machinery survives: XLA owns the
chip's parallelism, so ``Engine.init`` reduces to

* optional multi-host bring-up (``jax.distributed.initialize``) driven by
  launcher env vars (the ``spark-submit``-compatibility path: one JAX
  process per executor slot),
* building the global ``jax.sharding.Mesh`` that DistriOptimizer shards
  over (the analogue of ``Engine.nodeNumber * Engine.coreNumber``),
* the singleton guard (``bigdl.check.singleton``) against double init.

The mesh axes are created up front with seams for more than data
parallelism (``data``, optionally ``model``/``seq``) even though the
reference implements synchronous data parallelism only (SURVEY.md §2.4).
"""

from __future__ import annotations

import os
from typing import Optional, Sequence
from bigdl_tpu.obs import names


class _EngineState:
    initialized = False
    node_number = 1
    core_number = 1
    mesh = None           # jax.sharding.Mesh, data axis at minimum
    engine_type = "xla"   # reference: mklblas | mkldnn; here always XLA


class Engine:
    _state = _EngineState()

    # ------------------------------------------------------------------ init
    @classmethod
    def init(
        cls,
        node_number: Optional[int] = None,
        core_number: Optional[int] = None,
        backend: Optional[str] = None,
        mesh_shape: Optional[dict] = None,
    ):
        """Initialise the engine.

        Reference behavior («bigdl»/utils/Engine.scala): parse executor
        count/cores from SparkConf, build thread pools, check the singleton
        guard.  Here: initialise JAX distributed if launcher env says so,
        then build the device mesh.

        Args:
          node_number / core_number: accepted for API parity; on TPU the
            "core" pool is XLA's business, so these only gate the default
            mesh size when running on CPU with forced host devices.
          backend: "tpu" | "cpu" | None (auto).
          mesh_shape: optional dict of axis name -> size, e.g.
            ``{"data": 8}`` or ``{"data": 4, "model": 2}``.  Defaults to
            all devices on one ``data`` axis (the reference's only
            parallelism, SURVEY.md §2.4).
        """
        import time

        import jax

        from bigdl_tpu.config import config, refresh_from_env

        # launchers export BIGDL_* after import but before init — honor
        # them (read-at-call-time contract; configure() overrides win)
        refresh_from_env()
        t_init = time.perf_counter()
        # same contract for the fault-injection plan: a BIGDL_FAULT_PLAN
        # exported before init must be live before the first optimizer
        from bigdl_tpu.resilience.faults import get_injector

        get_injector()
        # elastic preemption: SIGTERM/SIGINT finish the in-flight step,
        # write an emergency checkpoint, and exit EXIT_PREEMPTED so the
        # supervisor restarts from it (resilience/elastic.py); installed
        # here because init is the one choke point every launcher hits
        if config.preemption_handler:
            from bigdl_tpu.resilience.elastic import (
                install_preemption_handler,
            )

            install_preemption_handler()
        if cls._state.initialized and config.check_singleton:
            # bigdl.check.singleton analogue
            raise RuntimeError(
                "Engine.init called twice with BIGDL_CHECK_SINGLETON set; "
                "the reference forbids two BigDL contexts in one process."
            )

        # spark-submit compatibility: if the launcher exported coordinator
        # env vars, join the multi-host world (SURVEY.md §2.5 "TPU-native
        # equivalent").
        if config.coordinator_address and not cls._state.initialized:
            jax.distributed.initialize(
                coordinator_address=config.coordinator_address,
                num_processes=config.num_processes,
                process_id=config.process_id,
            )

        devices = jax.devices(backend) if backend else jax.devices()
        n = len(devices)
        cls._state.node_number = node_number or n
        cls._state.core_number = core_number or 1
        cls._state.mesh = cls.build_mesh(mesh_shape, devices=devices)
        cls._state.engine_type = "xla"
        cls._state.initialized = True
        # bring-up telemetry: mesh bring-up dominates cold start on
        # multi-host, and "how long did init take, on what" is the first
        # question a slow-start incident asks (no-op tracer when off)
        from bigdl_tpu import obs

        obs.get_tracer().complete(
            "engine.init", t_init, time.perf_counter() - t_init,
            devices=n, platform=devices[0].platform if devices else None,
            mesh={a: int(s) for a, s in
                  zip(cls._state.mesh.axis_names,
                      cls._state.mesh.devices.shape)},
            processes=config.num_processes)
        # the trace-merge alignment anchor (obs/aggregate.py): in a
        # multi-host world this fires right after
        # jax.distributed.initialize returned on EVERY process — the
        # closest thing the program has to a simultaneous global event,
        # so per-host wall clocks are aligned on it when shards merge
        obs.get_tracer().event(
            "engine.init_barrier", host=config.process_id,
            processes=config.num_processes, devices=n)
        obs.get_registry().counter(
            names.ENGINE_INITS_TOTAL, "Engine.init calls").inc()
        # live telemetry plane: bring the per-host /metrics + /healthz
        # endpoint up with the engine when BIGDL_OBS_PORT is set (unset:
        # one config read, no thread, no socket).  init is the choke
        # point every launcher hits, so the endpoint exists before the
        # first step — a supervisor can watch bring-up, not only steps
        from bigdl_tpu.obs import server as _obs_server

        _obs_server.ensure_server()
        # continuous profiler (obs/prof.py): the sampler daemon starts
        # with the engine when BIGDL_PROF_HZ > 0 (unset: one config
        # read, no thread — the pinned off path)
        from bigdl_tpu.obs import prof as _obs_prof

        _obs_prof.get_profiler()
        return cls

    # singleton-ish accessors -------------------------------------------------
    @classmethod
    def is_initialized(cls) -> bool:
        return cls._state.initialized

    @classmethod
    def node_number(cls) -> int:
        return cls._state.node_number

    @classmethod
    def core_number(cls) -> int:
        return cls._state.core_number

    @classmethod
    def mesh(cls):
        if cls._state.mesh is None:
            cls.init()
        return cls._state.mesh

    @classmethod
    def reset(cls):
        """Test hook: drop the singleton (no reference analogue) and the
        fault injector's fire-once counters with it.  A pending
        preemption request is dropped too (the signal handlers stay
        installed — they are idempotent and process-global)."""
        from bigdl_tpu.obs import server as obs_server
        from bigdl_tpu.resilience.elastic import clear_preemption
        from bigdl_tpu.resilience.faults import reset_injector

        reset_injector()
        clear_preemption()
        # release the live-telemetry socket with the engine (tests
        # re-init with different ports; a later init rebuilds it)
        obs_server.stop_server()
        cls._state = _EngineState()

    # ------------------------------------------------------------------ mesh
    @staticmethod
    def build_mesh(mesh_shape: Optional[dict] = None, devices: Optional[Sequence] = None):
        """Build a ``jax.sharding.Mesh``.

        Default: 1-D ``('data',)`` mesh over all devices — the TPU-native
        replacement for the reference's "one Spark partition per executor"
        world (SURVEY.md §2.4 row 1).  Extra axes (model/seq/expert) are
        accepted to leave the seams open for parallelism the reference does
        not have.
        """
        import jax
        import numpy as np
        from jax.sharding import Mesh

        devices = list(devices if devices is not None else jax.devices())
        if not mesh_shape:
            mesh_shape = {"data": len(devices)}
        axis_names = tuple(mesh_shape.keys())
        sizes = tuple(mesh_shape.values())
        total = int(np.prod(sizes))
        if total != len(devices):
            raise ValueError(
                f"mesh shape {mesh_shape} needs {total} devices, have {len(devices)}"
            )
        dev_array = np.asarray(devices).reshape(sizes)
        return Mesh(dev_array, axis_names)

    # ------------------------------------------------- spark-conf parity shim
    @staticmethod
    def create_spark_conf() -> dict:
        """Reference: Engine.createSparkConf loads dist/conf/spark-bigdl.conf
        (locality off, min-resources-ratio 1.0, speculation off — SURVEY.md
        §3.1).  The rebuild keeps the spelling so launch scripts keep
        working; on TPU these become env hints for the per-executor JAX
        process launcher.
        """
        return {
            "spark.shuffle.reduceLocality.enabled": "false",
            "spark.scheduler.minRegisteredResourcesRatio": "1.0",
            "spark.speculation": "false",
        }
