"""graftlint core: findings, suppression, baseline, and the driver.

The linter is one AST pass per file plus cross-file finalizers.  Rule
packs (``jax_rules``, ``concurrency``, ``registry_rules``) implement::

    class Pack:
        rules: dict[rule_id -> one-line description]
        def visit_module(self, mod: ModuleInfo) -> list[Finding]
        def finalize(self) -> list[Finding]     # cross-file rules

Findings carry ``rule`` + ``path:line`` + message.  Two suppression
layers sit between a raw finding and a nonzero exit:

* **inline comments** — ``# graftlint: disable=RD003`` on the finding's
  line (or the line above) silences the named rule(s) there;
  ``# graftlint: disable-file=CC002`` anywhere in a file silences the
  rule file-wide;
* **the baseline file** — accepted legacy findings, checked in as
  ``.graftlint-baseline.json``.  Matching is content-addressed
  (rule + path + hash of the stripped source line + occurrence index),
  so findings survive unrelated line drift but expire when the
  offending line changes or disappears.  New findings always fail.
"""

from __future__ import annotations

import ast
import dataclasses
import hashlib
import json
import os
import re
from typing import Dict, List, Optional, Sequence, Tuple

#: rule id -> one-line description, merged from the packs at import
ALL_RULES: Dict[str, str] = {
    "GL000": "file does not parse (syntax error)",
}


@dataclasses.dataclass(frozen=True)
class Finding:
    rule: str
    path: str          # posix relpath from the lint root
    line: int
    message: str

    def render(self) -> str:
        return f"{self.path}:{self.line}: {self.rule} {self.message}"


@dataclasses.dataclass
class ModuleInfo:
    """One parsed file handed to every rule pack."""

    path: str          # absolute
    relpath: str       # posix, relative to the lint root
    text: str
    lines: List[str]
    tree: ast.AST
    is_library: bool   # framework code (bigdl_tpu/**, not config.py)

    def finding(self, rule: str, node, message: str) -> Finding:
        line = getattr(node, "lineno", 0) if not isinstance(node, int) \
            else node
        return Finding(rule, self.relpath, line, message)


# ------------------------------------------------------------------ AST util
def dotted_name(node) -> Optional[str]:
    """``a.b.c`` for Name/Attribute chains, else None."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def str_const(node) -> Optional[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


def walk_with_parents(tree):
    """Yield ``(node, parents)`` with ``parents`` innermost-last."""
    stack = [(tree, ())]
    while stack:
        node, parents = stack.pop()
        yield node, parents
        for child in ast.iter_child_nodes(node):
            stack.append((child, parents + (node,)))


# ------------------------------------------------------------ file discovery
def collect_files(paths: Sequence[str], root: str) -> List[str]:
    """Every ``*.py`` under ``paths`` (files or directories), sorted,
    __pycache__ and dot-directories excluded."""
    out = []
    for p in paths:
        ap = os.path.join(root, p) if not os.path.isabs(p) else p
        if os.path.isfile(ap):
            if ap.endswith(".py"):
                out.append(ap)
            continue
        for dirpath, dirnames, filenames in os.walk(ap):
            dirnames[:] = sorted(
                d for d in dirnames
                if d != "__pycache__" and not d.startswith("."))
            for f in sorted(filenames):
                if f.endswith(".py"):
                    out.append(os.path.join(dirpath, f))
    return sorted(dict.fromkeys(out))


def load_module(path: str, root: str,
                lib_mode: str = "auto") -> Tuple[Optional[ModuleInfo],
                                                 Optional[Finding]]:
    relpath = os.path.relpath(path, root).replace(os.sep, "/")
    with open(path, encoding="utf-8") as fh:
        text = fh.read()
    if lib_mode == "auto":
        is_library = (relpath.startswith("bigdl_tpu/")
                      and relpath != "bigdl_tpu/config.py")
    else:
        is_library = bool(lib_mode)
    try:
        tree = ast.parse(text, filename=relpath)
    except SyntaxError as e:
        return None, Finding("GL000", relpath, e.lineno or 0,
                             f"syntax error: {e.msg}")
    return ModuleInfo(path, relpath, text, text.splitlines(), tree,
                      is_library), None


# -------------------------------------------------------------- suppression
_DIRECTIVE_RE = re.compile(
    r"#\s*graftlint:\s*(disable-file|disable)(?:=([A-Za-z0-9_,\s]+))?")


def _directive_rules(match) -> Optional[frozenset]:
    """None means "all rules"."""
    if match.group(2) is None:
        return None
    return frozenset(r.strip() for r in match.group(2).split(",")
                     if r.strip())


def apply_suppressions(findings: List[Finding],
                       modules: Dict[str, ModuleInfo]) -> List[Finding]:
    """Drop findings silenced by inline ``# graftlint:`` comments."""
    per_file: Dict[str, Tuple[dict, Optional[frozenset], dict]] = {}
    for relpath, mod in modules.items():
        line_rules: Dict[int, Optional[frozenset]] = {}
        file_rules: set = set()
        file_all = False
        for i, line in enumerate(mod.lines, start=1):
            m = _DIRECTIVE_RE.search(line)
            if not m:
                continue
            rules = _directive_rules(m)
            if m.group(1) == "disable-file":
                if rules is None:
                    file_all = True
                else:
                    file_rules |= rules
            else:
                line_rules[i] = rules
        per_file[relpath] = (line_rules, file_rules, file_all)
    out = []
    for f in findings:
        line_rules, file_rules, file_all = per_file.get(
            f.path, ({}, set(), False))
        if file_all or f.rule in file_rules:
            continue
        suppressed = False
        for ln in (f.line, f.line - 1):
            rules = line_rules.get(ln, "absent")
            if rules == "absent":
                continue
            if rules is None or f.rule in rules:
                suppressed = True
                break
        if not suppressed:
            out.append(f)
    return out


# ----------------------------------------------------------------- baseline
BASELINE_VERSION = 1
DEFAULT_BASELINE = ".graftlint-baseline.json"


def _context_hash(mod: Optional[ModuleInfo], line: int) -> str:
    """12 hex chars of the stripped source line — the content address a
    baseline entry matches on, so findings survive line drift."""
    text = ""
    if mod is not None and 1 <= line <= len(mod.lines):
        text = mod.lines[line - 1].strip()
    return hashlib.sha1(text.encode("utf-8")).hexdigest()[:12]


def _keyed(findings: List[Finding],
           modules: Dict[str, ModuleInfo]) -> List[Tuple[tuple, Finding]]:
    """Pair each finding with its (rule, path, context, index) key;
    ``index`` disambiguates identical lines in one file."""
    seen: Dict[tuple, int] = {}
    out = []
    for f in sorted(findings, key=lambda f: (f.path, f.line, f.rule)):
        base = (f.rule, f.path, _context_hash(modules.get(f.path), f.line))
        idx = seen.get(base, 0)
        seen[base] = idx + 1
        out.append((base + (idx,), f))
    return out


def write_baseline(path: str, findings: List[Finding],
                   modules: Dict[str, ModuleInfo]):
    entries = [{"rule": k[0], "path": k[1], "context": k[2], "index": k[3],
                "message": f.message}
               for k, f in _keyed(findings, modules)]
    doc = {"version": BASELINE_VERSION, "findings": entries}
    tmp = path + ".tmp"
    with open(tmp, "w", encoding="utf-8") as fh:
        json.dump(doc, fh, indent=1, sort_keys=True)
        fh.write("\n")
    os.replace(tmp, path)


def load_baseline(path: str) -> Optional[List[dict]]:
    if not os.path.exists(path):
        return None
    with open(path, encoding="utf-8") as fh:
        doc = json.load(fh)
    if not isinstance(doc, dict) or doc.get("version") != BASELINE_VERSION:
        raise ValueError(f"{path}: unsupported baseline schema")
    return list(doc.get("findings", ()))


def apply_baseline(findings: List[Finding],
                   modules: Dict[str, ModuleInfo],
                   entries: List[dict]
                   ) -> Tuple[List[Finding], List[dict]]:
    """Split into (fresh findings, stale baseline entries).  A baseline
    entry absorbs at most one matching finding; entries that match
    nothing are stale (the violation was fixed — expire them with
    ``--write-baseline``)."""
    budget: Dict[tuple, int] = {}
    for e in entries:
        key = (e["rule"], e["path"], e["context"], int(e.get("index", 0)))
        budget[key] = budget.get(key, 0) + 1
    fresh = []
    for key, f in _keyed(findings, modules):
        if budget.get(key, 0) > 0:
            budget[key] -= 1
        else:
            fresh.append(f)
    stale = []
    for e in entries:
        key = (e["rule"], e["path"], e["context"], int(e.get("index", 0)))
        if budget.get(key, 0) > 0:
            budget[key] -= 1
            stale.append(e)
    return fresh, stale


# ------------------------------------------------------------------- driver
class Linter:
    """Parse every file once, run the packs, return raw findings
    (suppression comments already honored; baseline is the CLI's job so
    the API stays side-effect free)."""

    def __init__(self, paths: Sequence[str], root: Optional[str] = None,
                 rules: Optional[Sequence[str]] = None,
                 lib_mode: str = "auto", packs=None):
        self.root = os.path.abspath(root or os.getcwd())
        self.paths = list(paths)
        self.rules = set(rules) if rules else None
        self.lib_mode = lib_mode
        if packs is None:
            from bigdl_tpu.analysis.concurrency import ConcurrencyRules
            from bigdl_tpu.analysis.jax_rules import JaxRules
            from bigdl_tpu.analysis.registry_rules import RegistryRules

            packs = [JaxRules(), ConcurrencyRules(), RegistryRules()]
        self.packs = packs
        self.modules: Dict[str, ModuleInfo] = {}

    def run(self) -> List[Finding]:
        findings: List[Finding] = []
        for path in collect_files(self.paths, self.root):
            mod, err = load_module(path, self.root, self.lib_mode)
            if err is not None:
                findings.append(err)
                continue
            self.modules[mod.relpath] = mod
            for pack in self.packs:
                findings.extend(pack.visit_module(mod))
        for pack in self.packs:
            findings.extend(pack.finalize())
        if self.rules is not None:
            findings = [f for f in findings if f.rule in self.rules]
        findings = apply_suppressions(findings, self.modules)
        findings.sort(key=lambda f: (f.path, f.line, f.rule, f.message))
        return findings
