"""graftlint JX rules: JAX tracing hazards.

The bug class pytest can't see: code that is *correct* on eager numpy
but recompiles every step, silently syncs the host, or leaks tracers
once it runs under ``jax.jit`` — the failures that cost a 13x serve
throughput collapse before anyone notices.  All checks are syntactic
and deliberately conservative: a "traced scope" is a function the
module itself hands to a tracing transform (decorator, wrapping call,
or a ``lax`` control-flow body), and value tracking is a simple
forward taint from the traced function's non-static parameters.

* **JX001 host-sync-in-traced** — ``float()/int()/bool()``,
  ``np.asarray``/``np.array``, ``.item()/.tolist()``,
  ``.block_until_ready()`` or ``jax.device_get`` applied to a
  parameter-derived value inside a traced scope.  At best this is a
  per-call device sync; at trace time it is a concretization error or
  a silent constant-folding of live data.
* **JX002 tracer-leak** — storing a parameter-derived value on
  ``self``, a ``global`` or a ``nonlocal`` from inside a traced scope.
  The stored tracer outlives the trace and poisons the next one.
* **JX003 jit-in-loop** — constructing ``jax.jit``/``pmap``/
  ``shard_map`` (call or decorated def) inside a ``for``/``while``
  body: every iteration mints a fresh callable with an empty compile
  cache.
* **JX004 unhashable-static-arg** — a ``static_argnums``/
  ``static_argnames`` parameter whose default or call-site value is a
  list/dict/set display: unhashable statics raise, and per-value
  hashing of ad-hoc containers recompiles on every new object.
* **JX005 tracer-branch** — Python ``if``/``while`` on a
  parameter-derived value inside a traced scope (``is``/``is None``
  tests and string compares exempt — those are static trace-time
  switches; ``.shape``/``.ndim``/``.dtype`` access is static too).
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set

from bigdl_tpu.analysis import core
from bigdl_tpu.analysis.core import Finding, ModuleInfo, dotted_name

RULES = {
    "JX001": "host sync / concretization of a traced value",
    "JX002": "tracer stored on self/global/nonlocal from a traced scope",
    "JX003": "jit/pmap/shard_map constructed inside a loop body",
    "JX004": "unhashable object fed to a static jit argument",
    "JX005": "Python branch on a traced value",
}
core.ALL_RULES.update(RULES)

# transforms whose function argument is traced (and whose construction
# in a loop is a recompile hazard)
_TRACE_WRAPPERS = {"jit", "pjit", "pmap", "vmap", "shard_map", "remat",
                   "xmap", "grad", "value_and_grad"}
# jit-cache owners: constructing these per-iteration is JX003 (vmap /
# grad construction is cheap — tracing happens at call time)
_CACHE_WRAPPERS = {"jit", "pjit", "pmap", "shard_map"}
# lax control-flow HOFs: (callable-argument positions)
_LAX_HOFS = {"fori_loop": (2,), "while_loop": (0, 1), "scan": (0,),
             "cond": (1, 2, 3), "switch": (1,), "map": (0,),
             "associative_scan": (0,)}
# attribute reads that are static even on a tracer
_STATIC_ATTRS = {"shape", "ndim", "dtype", "size", "aval", "sharding",
                 "weak_type"}
_HOST_SYNC_METHODS = {"item", "tolist", "block_until_ready"}
_NUMPY_FUNCS = {"asarray", "array", "copy", "save", "savez"}


def _is_jax_module(name: Optional[str]) -> bool:
    return name is not None and (name == "jax" or name.startswith("jax."))


class _ModuleScan:
    """Per-module import/alias resolution."""

    def __init__(self, tree: ast.AST):
        self.numpy_aliases: Set[str] = set()
        self.jax_aliases: Set[str] = {"jax"}
        self.lax_aliases: Set[str] = set()
        self.from_jax: Set[str] = set()     # names imported from jax*
        self.partial_names: Set[str] = {"partial"}
        for node in ast.walk(tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    alias = a.asname or a.name.split(".")[0]
                    if a.name == "numpy":
                        # host numpy only — jax.numpy stays on device
                        self.numpy_aliases.add(alias)
                    elif a.name == "jax":
                        self.jax_aliases.add(alias)
                    elif a.name == "jax.lax":
                        self.lax_aliases.add(alias)
            elif isinstance(node, ast.ImportFrom):
                modname = node.module or ""
                for a in node.names:
                    alias = a.asname or a.name
                    if modname == "jax" and a.name == "lax":
                        self.lax_aliases.add(alias)
                    elif _is_jax_module(modname):
                        self.from_jax.add(alias)
                    elif modname == "functools" and a.name == "partial":
                        self.partial_names.add(alias)

    # ---------------------------------------------------- wrapper kinds
    def wrapper_kind(self, node) -> Optional[str]:
        """'jit', 'vmap', ... when ``node`` is a tracing transform
        expression (possibly through ``partial``)."""
        name = dotted_name(node)
        if name is not None:
            head, _, last = name.rpartition(".")
            if last in _TRACE_WRAPPERS:
                if head:
                    root = head.split(".")[0]
                    if root in self.jax_aliases or root in self.lax_aliases \
                            or _is_jax_module(head):
                        return last
                elif name in self.from_jax:
                    return last
            return None
        if isinstance(node, ast.Call):
            # partial(jax.jit, ...) / functools.partial(jax.jit, ...)
            fname = dotted_name(node.func)
            if fname and fname.split(".")[-1] in self.partial_names \
                    and node.args:
                return self.wrapper_kind(node.args[0])
            # jax.jit(f, ...) used as a decorator factory result —
            # @jax.jit(...) appears as Call(func=jax.jit)
            return self.wrapper_kind(node.func)
        return None

    def lax_hof_positions(self, call: ast.Call):
        name = dotted_name(call.func)
        if name is None:
            return None
        head, _, last = name.rpartition(".")
        if last not in _LAX_HOFS:
            return None
        root = head.split(".")[0] if head else ""
        if root in self.lax_aliases or head.endswith("lax") \
                or (root in self.jax_aliases and "lax" in head):
            return _LAX_HOFS[last]
        return None

    def is_numpy_call(self, call: ast.Call) -> bool:
        name = dotted_name(call.func)
        if not name or "." not in name:
            return False
        head, _, last = name.rpartition(".")
        return head in self.numpy_aliases and last in _NUMPY_FUNCS


def _static_params(call_or_dec, scan: _ModuleScan,
                   func: Optional[ast.AST]) -> Set[str]:
    """Parameter names declared static via static_argnums/argnames on a
    jit decorator/wrapping call."""
    out: Set[str] = set()
    node = call_or_dec
    calls = []
    while isinstance(node, ast.Call):
        calls.append(node)
        fname = dotted_name(node.func)
        if fname and fname.split(".")[-1] in scan.partial_names \
                and node.args:
            node = node.args[0]
        else:
            break
    params = []
    if func is not None and isinstance(func, (ast.FunctionDef,
                                              ast.AsyncFunctionDef)):
        a = func.args
        params = [p.arg for p in a.posonlyargs + a.args]
    for call in calls:
        for kw in call.keywords:
            if kw.arg == "static_argnames":
                for v in ast.walk(kw.value):
                    s = core.str_const(v)
                    if s:
                        out.add(s)
            elif kw.arg == "static_argnums":
                nums = []
                if isinstance(kw.value, ast.Constant) \
                        and isinstance(kw.value.value, int):
                    nums = [kw.value.value]
                elif isinstance(kw.value, (ast.Tuple, ast.List)):
                    nums = [e.value for e in kw.value.elts
                            if isinstance(e, ast.Constant)
                            and isinstance(e.value, int)]
                for n in nums:
                    if 0 <= n < len(params):
                        out.add(params[n])
    return out


class JaxRules:
    """The JX pack (stateless across files — every rule is per-module)."""

    rules = RULES

    def finalize(self) -> List[Finding]:
        return []

    def visit_module(self, mod: ModuleInfo) -> List[Finding]:
        scan = _ModuleScan(mod.tree)
        findings: List[Finding] = []
        traced: Dict[ast.AST, Set[str]] = {}   # func node -> static params
        func_defs: Dict[str, List[ast.AST]] = {}
        parents: Dict[ast.AST, ast.AST] = {}
        for node in ast.walk(mod.tree):
            for child in ast.iter_child_nodes(node):
                parents[child] = node
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                func_defs.setdefault(node.name, []).append(node)

        def mark(func_node, statics: Set[str]):
            if isinstance(func_node, (ast.FunctionDef,
                                      ast.AsyncFunctionDef, ast.Lambda)):
                prev = traced.get(func_node)
                traced[func_node] = (statics if prev is None
                                    else prev & statics)

        def resolve_func(expr) -> List[ast.AST]:
            if isinstance(expr, ast.Lambda):
                return [expr]
            if isinstance(expr, ast.Name):
                return func_defs.get(expr.id, [])
            return []

        # ---------------------------------------------- mark traced scopes
        wrapped_names: Dict[str, tuple] = {}  # jitted alias -> (call, func)
        for node in ast.walk(mod.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                for dec in node.decorator_list:
                    if scan.wrapper_kind(dec):
                        mark(node, _static_params(dec, scan, node))
            if not isinstance(node, ast.Call):
                continue
            kind = scan.wrapper_kind(node.func)
            if kind and node.args:
                for fn in resolve_func(node.args[0]):
                    mark(fn, _static_params(node, scan, fn))
                # g = jax.jit(f, static_argnums=...) — remember the alias
                par = parents.get(node)
                if isinstance(par, ast.Assign) and len(par.targets) == 1 \
                        and isinstance(par.targets[0], ast.Name):
                    tgt = resolve_func(node.args[0])
                    wrapped_names[par.targets[0].id] = (
                        node, tgt[0] if tgt else None)
            hof = scan.lax_hof_positions(node)
            if hof is not None:
                for pos in hof:
                    if pos < len(node.args):
                        for fn in resolve_func(node.args[pos]):
                            mark(fn, set())

        # decorated defs also own a wrapped name (their own)
        for fns in func_defs.values():
            for fn in fns:
                if fn in traced and isinstance(fn, ast.FunctionDef):
                    for dec in fn.decorator_list:
                        if scan.wrapper_kind(dec):
                            wrapped_names.setdefault(fn.name, (dec, fn))

        # ------------------------------------------------ per-scope checks
        for fn, statics in traced.items():
            findings.extend(self._check_traced(mod, scan, fn, statics))

        # ------------------------------------------------ JX003 jit-in-loop
        for node in ast.walk(mod.tree):
            hazard = None
            if isinstance(node, ast.Call) \
                    and scan.wrapper_kind(node.func) in _CACHE_WRAPPERS:
                hazard = node
            elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                    and any(scan.wrapper_kind(d) in _CACHE_WRAPPERS
                            for d in node.decorator_list):
                hazard = node
            if hazard is None:
                continue
            cur = parents.get(node)
            inner = node
            while cur is not None:
                if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef,
                                    ast.Lambda)) and inner is not node:
                    break  # construction deferred into a callable: fine
                if isinstance(cur, (ast.For, ast.While)) \
                        and inner in (cur.body + getattr(cur, "orelse", [])):
                    findings.append(mod.finding(
                        "JX003", hazard,
                        "jit-in-loop: a tracing transform constructed "
                        "inside a loop body gets a fresh compile cache "
                        "every iteration; hoist it out of the loop"))
                    break
                inner, cur = cur, parents.get(cur)

        # ---------------------------------------- JX004 unhashable statics
        for alias, (call, fn) in wrapped_names.items():
            statics = _static_params(call, scan, fn)
            if not statics or fn is None:
                continue
            a = fn.args
            pos_params = [p.arg for p in a.posonlyargs + a.args]
            defaults = a.defaults
            for p, d in zip(pos_params[len(pos_params) - len(defaults):],
                            defaults):
                if p in statics and isinstance(
                        d, (ast.List, ast.Dict, ast.Set)):
                    findings.append(mod.finding(
                        "JX004",
                        d, f"static arg {p!r} of {fn.name!r} defaults to "
                        "an unhashable container; jit static args must "
                        "hash (use a tuple or a frozen dataclass)"))
            # call sites of the wrapped alias feeding containers
            static_idx = {pos_params.index(p) for p in statics
                          if p in pos_params}
            for node in ast.walk(mod.tree):
                if not (isinstance(node, ast.Call)
                        and isinstance(node.func, ast.Name)
                        and node.func.id == alias):
                    continue
                for i, arg in enumerate(node.args):
                    if i in static_idx and isinstance(
                            arg, (ast.List, ast.Dict, ast.Set)):
                        findings.append(mod.finding(
                            "JX004", arg,
                            f"unhashable container passed to static arg "
                            f"#{i} of jitted {alias!r}; every new object "
                            "recompiles (pass a tuple)"))
                for kw in node.keywords:
                    if kw.arg in statics and isinstance(
                            kw.value, (ast.List, ast.Dict, ast.Set)):
                        findings.append(mod.finding(
                            "JX004", kw.value,
                            f"unhashable container passed to static arg "
                            f"{kw.arg!r} of jitted {alias!r}; every new "
                            "object recompiles (pass a tuple)"))
        return findings

    # ------------------------------------------------------------ taint
    def _check_traced(self, mod: ModuleInfo, scan: _ModuleScan, fn,
                      statics: Set[str]) -> List[Finding]:
        findings: List[Finding] = []
        if isinstance(fn, ast.Lambda):
            params = {a.arg for a in fn.args.args + fn.args.posonlyargs}
        else:
            a = fn.args
            params = {p.arg for p in a.posonlyargs + a.args + a.kwonlyargs}
            if a.vararg:
                params.add(a.vararg.arg)
        tainted = {p for p in params - statics if p != "self"}
        globals_declared: Set[str] = set()
        nonlocals_declared: Set[str] = set()

        def contains_taint(expr) -> bool:
            """Does ``expr`` reference a tainted name OUTSIDE a static
            attribute chain (``x.shape``...) or a ``len()`` call?"""
            if isinstance(expr, ast.Attribute) \
                    and expr.attr in _STATIC_ATTRS:
                return False
            if isinstance(expr, ast.Call):
                fname = dotted_name(expr.func)
                if fname == "len":
                    return False
            if isinstance(expr, ast.Name):
                return expr.id in tainted
            return any(contains_taint(c)
                       for c in ast.iter_child_nodes(expr))

        def target_names(target):
            if isinstance(target, ast.Name):
                yield target.id
            elif isinstance(target, (ast.Tuple, ast.List)):
                for e in target.elts:
                    yield from target_names(e)
            elif isinstance(target, ast.Starred):
                yield from target_names(target.value)

        body = fn.body if isinstance(fn.body, list) else [fn.body]
        # statements in source order so taint flows forward
        for stmt in body:
            for node in ast.walk(stmt):
                if isinstance(node, ast.Global):
                    globals_declared.update(node.names)
                elif isinstance(node, ast.Nonlocal):
                    nonlocals_declared.update(node.names)
                elif isinstance(node, (ast.FunctionDef,
                                       ast.AsyncFunctionDef, ast.Lambda)):
                    # nested defs run at trace time too: their params
                    # typically carry tracers (lax bodies, helpers)
                    args = node.args
                    tainted.update(
                        p.arg for p in args.posonlyargs + args.args
                        if p.arg != "self")
                elif isinstance(node, ast.Assign):
                    if contains_taint(node.value):
                        for t in node.targets:
                            tainted.update(target_names(t))
                elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
                    if node.value is not None \
                            and contains_taint(node.value):
                        tainted.update(target_names(node.target))
                elif isinstance(node, ast.For):
                    if contains_taint(node.iter):
                        tainted.update(target_names(node.target))
        # pass 2: report hazards with the final taint set
        for stmt in body:
            for node in ast.walk(stmt):
                findings.extend(self._taint_hazards(
                    mod, scan, node, contains_taint,
                    globals_declared, nonlocals_declared))
        return findings

    def _taint_hazards(self, mod, scan, node, contains_taint,
                       globals_declared, nonlocals_declared):
        out = []
        if isinstance(node, ast.Call):
            fname = dotted_name(node.func)
            if fname in ("float", "int", "bool", "complex") and node.args \
                    and contains_taint(node.args[0]):
                out.append(mod.finding(
                    "JX001", node,
                    f"{fname}() on a traced value forces host "
                    "concretization inside a traced scope; keep it on "
                    "device (jnp ops) or hoist the read out of the jit"))
            elif fname and fname.rpartition(".")[2] == "device_get" \
                    and node.args and contains_taint(node.args[0]):
                out.append(mod.finding(
                    "JX001", node,
                    "jax.device_get inside a traced scope blocks on the "
                    "device; move the fetch outside the traced function"))
            elif scan.is_numpy_call(node) and node.args \
                    and contains_taint(node.args[0]):
                out.append(mod.finding(
                    "JX001", node,
                    "numpy call on a traced value pulls it to the host "
                    "(sync + constant-fold); use jax.numpy inside "
                    "traced code"))
            elif isinstance(node.func, ast.Attribute) \
                    and node.func.attr in _HOST_SYNC_METHODS \
                    and contains_taint(node.func.value):
                out.append(mod.finding(
                    "JX001", node,
                    f".{node.func.attr}() on a traced value is a host "
                    "sync inside a traced scope; return the value and "
                    "read it outside the jit"))
        elif isinstance(node, (ast.Assign, ast.AugAssign)):
            targets = node.targets if isinstance(node, ast.Assign) \
                else [node.target]
            if node.value is not None and contains_taint(node.value):
                for t in targets:
                    if isinstance(t, ast.Attribute) \
                            and isinstance(t.value, ast.Name) \
                            and t.value.id == "self":
                        out.append(mod.finding(
                            "JX002", node,
                            f"traced value stored on self.{t.attr} from "
                            "inside a traced scope — the tracer outlives "
                            "the trace; return it instead"))
                    elif isinstance(t, ast.Name) and (
                            t.id in globals_declared
                            or t.id in nonlocals_declared):
                        out.append(mod.finding(
                            "JX002", node,
                            f"traced value stored in "
                            f"{'global' if t.id in globals_declared else 'nonlocal'}"
                            f" {t.id!r} from inside a traced scope; "
                            "return it instead"))
        elif isinstance(node, (ast.If, ast.While)):
            test = node.test
            if self._is_tracer_branch(test, contains_taint):
                out.append(mod.finding(
                    "JX005", node,
                    "Python branch on a traced value — either a "
                    "trace-time error or a silent shape-specialized "
                    "recompile; use lax.cond/jnp.where or hoist the "
                    "decision to a static argument"))
        return out

    def _is_tracer_branch(self, test, contains_taint) -> bool:
        if isinstance(test, ast.BoolOp):
            return any(self._is_tracer_branch(v, contains_taint)
                       for v in test.values)
        if isinstance(test, ast.UnaryOp) and isinstance(test.op, ast.Not):
            return self._is_tracer_branch(test.operand, contains_taint)
        if isinstance(test, ast.Compare):
            # `x is None` / `x is not None` and string compares are the
            # static trace-time switch idiom — exempt
            if all(isinstance(op, (ast.Is, ast.IsNot)) for op in test.ops):
                return False
            for comp in [test.left] + test.comparators:
                if isinstance(comp, ast.Constant) \
                        and isinstance(comp.value, (str, type(None))):
                    return False
            return any(contains_taint(c)
                       for c in [test.left] + test.comparators)
        if isinstance(test, ast.Name):
            return contains_taint(test)
        return False
