"""graftlint CLI — ``python -m bigdl_tpu.analysis.lint [paths...]``.

The repo-native static-analysis pass: JAX tracing hazards (JX*),
thread/lock discipline (CC*), and config/metric registry drift (RD*).
Exit 0 means zero unsuppressed findings; any fresh finding (not in the
baseline, not silenced by a ``# graftlint: disable=`` comment) exits 1.

Workflow::

    python -m bigdl_tpu.analysis.lint bigdl_tpu scripts   # the CI gate
    python -m bigdl_tpu.analysis.lint --list-rules
    python -m bigdl_tpu.analysis.lint --rules CC001,CC002 bigdl_tpu
    python -m bigdl_tpu.analysis.lint --write-baseline    # accept legacy

The baseline (``.graftlint-baseline.json``) holds accepted legacy
findings keyed by rule + path + a hash of the offending source line, so
entries survive unrelated edits but expire when the line changes.
Stale entries are reported (and dropped by ``--write-baseline``) —
never silently kept.  Triage help: ``scripts/tpu_debug.py`` and the
"Static analysis" section of MIGRATION.md.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time
from typing import List, Optional

from bigdl_tpu.analysis import core
from bigdl_tpu.analysis.core import (DEFAULT_BASELINE, Finding, Linter,
                                     apply_baseline, load_baseline,
                                     write_baseline)

DEFAULT_PATHS = ("bigdl_tpu", "scripts")


def run_lint(paths=DEFAULT_PATHS, root: Optional[str] = None,
             baseline: Optional[str] = DEFAULT_BASELINE,
             rules=None, lib_mode: str = "auto", packs=None):
    """Library entry point: returns ``(fresh, stale, linter)`` where
    ``fresh`` are unsuppressed non-baseline findings and ``stale`` are
    baseline entries that no longer match anything."""
    linter = Linter(paths, root=root, rules=rules, lib_mode=lib_mode,
                    packs=packs)
    findings = linter.run()
    stale: List[dict] = []
    if baseline:
        bpath = baseline if os.path.isabs(baseline) else os.path.join(
            linter.root, baseline)
        entries = load_baseline(bpath)
        if entries is not None:
            findings, stale = apply_baseline(findings, linter.modules,
                                             entries)
    return findings, stale, linter


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m bigdl_tpu.analysis.lint",
        description="graftlint: JAX hazards, concurrency discipline and "
                    "registry drift")
    ap.add_argument("paths", nargs="*", default=None,
                    help=f"files/dirs to lint (default: "
                         f"{' '.join(DEFAULT_PATHS)})")
    ap.add_argument("--root", default=None,
                    help="repo root paths are relative to (default: cwd)")
    ap.add_argument("--baseline", default=DEFAULT_BASELINE,
                    help="baseline file of accepted legacy findings "
                         f"(default: {DEFAULT_BASELINE})")
    ap.add_argument("--no-baseline", action="store_true",
                    help="ignore the baseline: report every finding")
    ap.add_argument("--write-baseline", action="store_true",
                    help="accept the current findings into --baseline "
                         "(drops stale entries) and exit 0")
    ap.add_argument("--rules", default=None,
                    help="comma-separated rule ids to run (default: all)")
    ap.add_argument("--json", action="store_true",
                    help="machine-readable output")
    ap.add_argument("--list-rules", action="store_true",
                    help="print every rule id and exit")
    args = ap.parse_args(argv)

    if args.list_rules:
        # importing the packs populates core.ALL_RULES
        from bigdl_tpu.analysis import (concurrency,  # noqa: F401
                                        jax_rules, registry_rules)

        for rule, desc in sorted(core.ALL_RULES.items()):
            print(f"{rule}  {desc}")
        return 0

    rules = ([r.strip() for r in args.rules.split(",") if r.strip()]
             if args.rules else None)
    t0 = time.perf_counter()
    paths = args.paths or list(DEFAULT_PATHS)
    baseline = None if args.no_baseline else args.baseline
    if args.write_baseline:
        linter = Linter(paths, root=args.root, rules=rules)
        findings = linter.run()
        bpath = args.baseline if os.path.isabs(args.baseline) else \
            os.path.join(linter.root, args.baseline)
        write_baseline(bpath, findings, linter.modules)
        print(f"[graftlint] baseline: {len(findings)} finding(s) "
              f"accepted into {args.baseline}")
        return 0

    fresh, stale, linter = run_lint(paths, root=args.root,
                                    baseline=baseline, rules=rules)
    dt = time.perf_counter() - t0
    if args.json:
        print(json.dumps({
            "findings": [f.__dict__ for f in fresh],
            "stale_baseline": stale,
            "files": len(linter.modules),
            "seconds": round(dt, 3),
        }, indent=1, sort_keys=True))
    else:
        for f in fresh:
            print(f.render())
        for e in stale:
            print(f"[graftlint] stale baseline entry: {e['rule']} "
                  f"{e['path']} ({e.get('message', '')[:60]}) — fixed? "
                  "run --write-baseline to expire it")
        status = "clean" if not fresh else f"{len(fresh)} finding(s)"
        print(f"[graftlint] {status}: {len(linter.modules)} files in "
              f"{dt:.2f}s"
              + (f", {len(stale)} stale baseline entries" if stale
                 else ""))
    return 1 if fresh else 0


if __name__ == "__main__":
    sys.exit(main())
