"""graftlint CC rules: thread/lock discipline.

The stack runs real threads in production — the obs HTTP server, the
stream producer, the serving engine loop, the background checkpoint
writer, the supervisor watchdog — and the deadlocks/races they can
produce never show up in a single-threaded pytest run.  These rules
are intraprocedural with one level of honesty: lock acquisitions are
``with``-statements over *known* locks (attributes assigned
``threading.Lock/RLock/Condition`` in the class, or module-level
ones), and call effects propagate through same-class / same-module
calls to a fixpoint.

* **CC001 lock-order-cycle** — a global graph over "held A while
  acquiring B" edges (direct ``with`` nesting plus calls made while
  holding a lock, using each callee's may-acquire summary).  Any cycle
  — including re-acquiring a non-reentrant ``Lock`` you already hold —
  is a latent deadlock: two threads entering the cycle from different
  edges stall forever.
* **CC002 unlocked-shared-write** — an attribute written on ``self``
  from a thread entry point (a method handed to
  ``threading.Thread(target=...)`` or a ``Thread`` subclass ``run``,
  plus everything those reach through self-calls) without holding one
  of the class's locks, when the same attribute is also written from
  non-thread methods.  That's a write-write race on CPython and a
  torn invariant everywhere else.
* **CC003 bare-acquire** — ``lock.acquire()`` without a matching
  ``finally: lock.release()``: any exception between the two leaks the
  lock and wedges every later waiter.  Use ``with``.
"""

from __future__ import annotations

import ast
import dataclasses
from typing import Dict, List, Optional, Set, Tuple

from bigdl_tpu.analysis import core
from bigdl_tpu.analysis.core import Finding, ModuleInfo, dotted_name

RULES = {
    "CC001": "inconsistent lock acquisition order (deadlock cycle)",
    "CC002": "shared attribute written from a thread without its lock",
    "CC003": "lock.acquire() without try/finally release",
}
core.ALL_RULES.update(RULES)

_LOCK_CTORS = {"Lock": "lock", "RLock": "rlock", "Condition": "cond"}


def _lock_ctor_kind(call) -> Optional[str]:
    if not isinstance(call, ast.Call):
        return None
    name = dotted_name(call.func)
    if name is None:
        return None
    last = name.rpartition(".")[2]
    return _LOCK_CTORS.get(last)


@dataclasses.dataclass
class _FuncSummary:
    key: str                                   # "relpath::Class.m"
    acquires: List[Tuple[str, int, tuple]]     # (lock, line, held-at)
    calls: List[Tuple[str, int, tuple]]        # (callee key, line, held)
    writes: List[Tuple[str, int, bool]]        # (attr, line, under lock)


class _ClassInfo:
    def __init__(self, relpath: str, name: str):
        self.relpath = relpath
        self.name = name
        self.lock_attrs: Dict[str, str] = {}   # attr -> kind
        self.methods: Dict[str, ast.AST] = {}
        self.entries: Set[str] = set()

    def lock_id(self, attr: str) -> str:
        return f"{self.relpath}::{self.name}.{attr}"


class ConcurrencyRules:
    """The CC pack.  CC002/CC003 report per module; CC001 accumulates a
    global lock graph and reports in :meth:`finalize`."""

    rules = RULES

    def __init__(self):
        # (from_lock, to_lock) -> (path, line) of the inner acquisition
        self.edges: Dict[Tuple[str, str], Tuple[str, int]] = {}
        self.lock_kinds: Dict[str, str] = {}

    # ------------------------------------------------------------ visit
    def visit_module(self, mod: ModuleInfo) -> List[Finding]:
        findings: List[Finding] = []
        module_locks: Dict[str, str] = {}
        for node in mod.tree.body:
            if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name):
                kind = _lock_ctor_kind(node.value)
                if kind:
                    module_locks[node.targets[0].id] = kind
                    self.lock_kinds[f"{mod.relpath}::"
                                    f"{node.targets[0].id}"] = kind

        classes: List[_ClassInfo] = []
        module_funcs: Dict[str, ast.AST] = {}
        for node in mod.tree.body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                module_funcs[node.name] = node
            elif isinstance(node, ast.ClassDef):
                classes.append(self._scan_class(mod, node))

        # build per-function summaries
        summaries: Dict[str, _FuncSummary] = {}
        for cls in classes:
            for mname, fn in cls.methods.items():
                key = f"{mod.relpath}::{cls.name}.{mname}"
                summaries[key] = self._summarize(
                    mod, fn, key, cls, module_locks, module_funcs,
                    findings)
        for fname, fn in module_funcs.items():
            key = f"{mod.relpath}::{fname}"
            summaries[key] = self._summarize(
                mod, fn, key, None, module_locks, module_funcs, findings)

        # may-acquire fixpoint through same-module calls
        may: Dict[str, Set[str]] = {
            k: {l for l, _, _ in s.acquires} for k, s in summaries.items()}
        changed = True
        while changed:
            changed = False
            for k, s in summaries.items():
                for callee, _, _ in s.calls:
                    extra = may.get(callee, set()) - may[k]
                    if extra:
                        may[k] |= extra
                        changed = True

        # lock-order edges (held -> acquired), direct and through calls
        for k, s in summaries.items():
            for lock, line, held in s.acquires:
                for h in held:
                    self.edges.setdefault((h, lock), (mod.relpath, line))
            for callee, line, held in s.calls:
                for lock in may.get(callee, ()):
                    for h in held:
                        self.edges.setdefault((h, lock),
                                              (mod.relpath, line))

        # CC002: unlocked writes from thread-entry closures
        for cls in classes:
            findings.extend(self._check_shared_writes(
                mod, cls, summaries))
        return findings

    # ------------------------------------------------------- class scan
    def _scan_class(self, mod: ModuleInfo, node: ast.ClassDef) -> _ClassInfo:
        cls = _ClassInfo(mod.relpath, node.name)
        thread_base = any(
            (dotted_name(b) or "").rpartition(".")[2] == "Thread"
            for b in node.bases)
        for item in node.body:
            if not isinstance(item, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                continue
            cls.methods[item.name] = item
            for sub in ast.walk(item):
                if isinstance(sub, ast.Assign):
                    kind = _lock_ctor_kind(sub.value)
                    if kind:
                        for t in sub.targets:
                            if isinstance(t, ast.Attribute) \
                                    and isinstance(t.value, ast.Name) \
                                    and t.value.id == "self":
                                cls.lock_attrs[t.attr] = kind
                                self.lock_kinds[cls.lock_id(t.attr)] = kind
                if isinstance(sub, ast.Call):
                    fname = dotted_name(sub.func) or ""
                    if fname.rpartition(".")[2] == "Thread":
                        for kw in sub.keywords:
                            if kw.arg == "target" \
                                    and isinstance(kw.value, ast.Attribute) \
                                    and isinstance(kw.value.value, ast.Name) \
                                    and kw.value.value.id == "self":
                                cls.entries.add(kw.value.attr)
        if thread_base and "run" in cls.methods:
            cls.entries.add("run")
        return cls

    # -------------------------------------------------------- summaries
    def _resolve_lock(self, expr, cls: Optional[_ClassInfo],
                      module_locks: Dict[str, str],
                      relpath: str) -> Optional[str]:
        if isinstance(expr, ast.Attribute) \
                and isinstance(expr.value, ast.Name) \
                and expr.value.id == "self" and cls is not None \
                and expr.attr in cls.lock_attrs:
            return cls.lock_id(expr.attr)
        if isinstance(expr, ast.Name) and expr.id in module_locks:
            return f"{relpath}::{expr.id}"
        return None

    def _summarize(self, mod: ModuleInfo, fn, key: str,
                   cls: Optional[_ClassInfo],
                   module_locks: Dict[str, str],
                   module_funcs: Dict[str, ast.AST],
                   findings: List[Finding]) -> _FuncSummary:
        s = _FuncSummary(key, [], [], [])
        relpath = mod.relpath
        acquire_sites: List[Tuple[str, ast.AST]] = []
        finally_releases: List[Tuple[str, ast.AST]] = []
        parents: Dict[ast.AST, ast.AST] = {}

        def visit(node, held: tuple):
            for child in ast.iter_child_nodes(node):
                parents[child] = node
            if isinstance(node, ast.With):
                inner = held
                for item in node.items:
                    lock = self._resolve_lock(
                        item.context_expr, cls, module_locks, relpath)
                    if lock:
                        s.acquires.append((lock, node.lineno, inner))
                        inner = inner + (lock,)
                for b in node.body:
                    visit(b, inner)
                return
            if isinstance(node, ast.Call):
                # same-class / same-module call targets
                if isinstance(node.func, ast.Attribute) \
                        and isinstance(node.func.value, ast.Name) \
                        and node.func.value.id == "self" \
                        and cls is not None \
                        and node.func.attr in cls.methods:
                    s.calls.append((f"{relpath}::{cls.name}."
                                    f"{node.func.attr}",
                                    node.lineno, held))
                elif isinstance(node.func, ast.Name) \
                        and node.func.id in module_funcs:
                    s.calls.append((f"{relpath}::{node.func.id}",
                                    node.lineno, held))
                # CC003 bookkeeping
                if isinstance(node.func, ast.Attribute):
                    lock = self._resolve_lock(
                        node.func.value, cls, module_locks, relpath)
                    if lock and node.func.attr == "acquire":
                        acquire_sites.append((lock, node))
                    elif lock and node.func.attr == "release":
                        cur = parents.get(node)
                        while cur is not None and not isinstance(
                                cur, (ast.FunctionDef,
                                      ast.AsyncFunctionDef)):
                            if isinstance(cur, ast.Try):
                                finally_releases.append((lock, cur))
                            cur = parents.get(cur)
            if isinstance(node, (ast.Assign, ast.AugAssign)):
                targets = node.targets if isinstance(node, ast.Assign) \
                    else [node.target]
                for t in targets:
                    if isinstance(t, ast.Attribute) \
                            and isinstance(t.value, ast.Name) \
                            and t.value.id == "self" \
                            and cls is not None \
                            and t.attr not in cls.lock_attrs:
                        s.writes.append((t.attr, node.lineno, bool(held)))
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.Lambda)) and node is not fn:
                return  # nested callables run on their own schedule
            for child in ast.iter_child_nodes(node):
                visit(child, held)

        for stmt in fn.body:
            parents[stmt] = fn
            visit(stmt, ())

        # CC003: every acquire needs a finally-release of the same lock
        # somewhere in this function (the idiom puts the acquire just
        # BEFORE the try, so an ancestor walk would miss it — a
        # function-wide match is the honest granularity here)
        protected_locks = {l for l, _ in finally_releases}
        for lock, node in acquire_sites:
            if lock not in protected_locks:
                findings.append(mod.finding(
                    "CC003", node,
                    f"{lock.rpartition('::')[2]}.acquire() without a "
                    "try/finally release — an exception here wedges "
                    "every later waiter; use `with`"))
        return s

    # ------------------------------------------------- CC002 evaluation
    def _check_shared_writes(self, mod: ModuleInfo, cls: _ClassInfo,
                             summaries: Dict[str, _FuncSummary]
                             ) -> List[Finding]:
        if not cls.entries or not cls.lock_attrs:
            return []
        # closure of methods reachable from the thread entries
        entry_closure: Set[str] = set()
        stack = [m for m in cls.entries if m in cls.methods]
        prefix = f"{mod.relpath}::{cls.name}."
        while stack:
            m = stack.pop()
            if m in entry_closure:
                continue
            entry_closure.add(m)
            s = summaries.get(prefix + m)
            if s is None:
                continue
            for callee, _, _ in s.calls:
                if callee.startswith(prefix):
                    stack.append(callee[len(prefix):])
        # attributes also written outside the entry closure (+ __init__)
        outside_writers: Dict[str, str] = {}
        for mname in cls.methods:
            if mname in entry_closure or mname == "__init__":
                continue
            s = summaries.get(prefix + mname)
            if s is None:
                continue
            for attr, _, _ in s.writes:
                outside_writers.setdefault(attr, mname)
        findings = []
        for mname in sorted(entry_closure):
            s = summaries.get(prefix + mname)
            if s is None:
                continue
            for attr, line, under_lock in s.writes:
                if under_lock or attr not in outside_writers:
                    continue
                findings.append(Finding(
                    "CC002", mod.relpath, line,
                    f"self.{attr} written from thread entry path "
                    f"{cls.name}.{mname}() without holding a class lock "
                    f"({' / '.join(sorted(cls.lock_attrs))}), but also "
                    f"written by {cls.name}.{outside_writers[attr]}() — "
                    "write-write race"))
        return findings

    # --------------------------------------------------- CC001 finalize
    def finalize(self) -> List[Finding]:
        findings = []
        # self-cycles: re-acquiring a non-reentrant lock you hold
        graph: Dict[str, Set[str]] = {}
        for (a, b), (path, line) in sorted(self.edges.items()):
            if a == b:
                if self.lock_kinds.get(a) == "lock":
                    findings.append(Finding(
                        "CC001", path, line,
                        f"{a.rpartition('::')[2]} is acquired while "
                        "already held and is a non-reentrant "
                        "threading.Lock — guaranteed self-deadlock"))
                continue
            graph.setdefault(a, set()).add(b)

        # cycles among distinct locks: report every edge inside an SCC
        sccs = _tarjan(graph)
        for scc in sccs:
            if len(scc) < 2:
                continue
            members = set(scc)
            pretty = " -> ".join(
                sorted(l.rpartition("::")[2] for l in members))
            for (a, b), (path, line) in sorted(self.edges.items()):
                if a in members and b in members and a != b:
                    findings.append(Finding(
                        "CC001", path, line,
                        f"lock-order cycle [{pretty}]: "
                        f"{b.rpartition('::')[2]} acquired here while "
                        f"holding {a.rpartition('::')[2]}, but another "
                        "path acquires them in the opposite order — "
                        "pick one global order"))
        return findings


def _tarjan(graph: Dict[str, Set[str]]) -> List[List[str]]:
    """Iterative Tarjan SCC (the linter must not recurse its way past
    Python's stack limit on a big lock graph)."""
    index: Dict[str, int] = {}
    low: Dict[str, int] = {}
    on_stack: Set[str] = set()
    stack: List[str] = []
    sccs: List[List[str]] = []
    counter = [0]
    nodes = sorted(set(graph) | {v for vs in graph.values() for v in vs})

    for root in nodes:
        if root in index:
            continue
        work = [(root, iter(sorted(graph.get(root, ()))))]
        index[root] = low[root] = counter[0]
        counter[0] += 1
        stack.append(root)
        on_stack.add(root)
        while work:
            node, it = work[-1]
            advanced = False
            for succ in it:
                if succ not in index:
                    index[succ] = low[succ] = counter[0]
                    counter[0] += 1
                    stack.append(succ)
                    on_stack.add(succ)
                    work.append((succ, iter(sorted(graph.get(succ, ())))))
                    advanced = True
                    break
                if succ in on_stack:
                    low[node] = min(low[node], index[succ])
            if advanced:
                continue
            work.pop()
            if work:
                parent = work[-1][0]
                low[parent] = min(low[parent], low[node])
            if low[node] == index[node]:
                scc = []
                while True:
                    w = stack.pop()
                    on_stack.discard(w)
                    scc.append(w)
                    if w == node:
                        break
                sccs.append(scc)
    return sccs
