"""graftlint RD rules: config and metric registry drift.

Two registries anchor the operational surface: ``bigdl_tpu/config.py``
declares every ``BIGDL_*`` environment variable the framework honours,
and ``bigdl_tpu/obs/names.py`` declares every published ``bigdl_*``
metric family.  Drift — a module minting its own env spelling or metric
name — is invisible until a dashboard quietly reads zeros.  These rules
pin both registries closed:

* **RD001 undeclared-env-read** — a ``BIGDL_*`` env var is read
  (``os.environ[...]`` / ``.get`` / ``os.getenv``) but not declared in
  ``config.py``.  Harness bootstrap vars (``config.HARNESS_ENV``) are
  allowed in scripts only.
* **RD002 raw-env-read-in-library** — framework code outside
  ``config.py`` reads a ``BIGDL_*`` var from the environment directly
  instead of through the config object; the read bypasses
  ``configure()`` overrides and the documented resolution order.
* **RD003 unregistered-metric-name** — a ``bigdl_*`` name is minted or
  spelled without a declaration in ``obs/names.py`` (histogram
  ``_bucket``/``_sum``/``_count`` derivations and the declared
  ``KNOWN_STRINGS`` non-metric spellings are fine); library mint sites
  must use the names constants, not literals.
* **RD004 unrendered-undocumented-metric** — a declared metric is
  neither rendered by ``obs/report.py`` nor documented in its spec.
* **RD005 metric-shape-mismatch** — a mint site disagrees with the
  declared kind or label set of the metric it mints.
* **RD006 span-name-literal** — a ``.span(...)`` / ``.event(...)`` /
  ``.complete(...)`` call in ``bigdl_tpu/serving/`` (or in any module
  importing ``bigdl_tpu.serving.spans``) names its span with a string
  literal instead of a ``serving/spans.py`` constant; a typo'd literal
  silently forks the request-trace timeline the same way a typo'd
  metric name forks a dashboard.
* **RD007 missing-or-illegal-fleet-policy** — every family in
  ``obs/names.py`` must carry a legal fleet aggregation policy for the
  hierarchical rollup tier (``obs/rollup.py``): counters and
  histograms are additive (``sum`` only — declaring anything else is
  flagged), while a gauge must *explicitly* pick ``max``/``min``/
  ``last``.  A ``sum`` gauge is almost always a unit error (summing
  ratios, summing per-host clocks); the rare legitimate one — a count
  published as a gauge — opts in with an inline
  ``# graftlint: disable=RD007``.

* **RD008 implicit-selfobs-policy** — the observability plane's own
  families (``bigdl_prof_*`` continuous-profiler self-metrics,
  ``bigdl_bundle_*`` debug-bundle accounting) exist to be fleet-rolled
  — a misconfigured high-rate profiler is only visible if its overhead
  gauge rides the rollup tier — so every one must spell its fleet
  policy *explicitly*: counters/histograms write ``policy='sum'``
  (where ordinary families may rely on the additive default), gauges
  declare theirs as usual (RD007 already forces that).  A new
  ``bigdl_prof_*``/``bigdl_bundle_*`` family therefore cannot land
  without a conscious rollup decision in ``obs/names.py``.

Env var *writes* are exempt everywhere: exporting ``BIGDL_*`` into a
child's environment is the supervisor/harness contract.
"""

from __future__ import annotations

import ast
import os
import re
from typing import Dict, List, Optional, Set, Tuple

from bigdl_tpu.analysis import core
from bigdl_tpu.analysis.core import (Finding, ModuleInfo, dotted_name,
                                     str_const)

RULES = {
    "RD001": "BIGDL_* env var read but not declared in config.py",
    "RD002": "raw BIGDL_* env read in library code (use config)",
    "RD003": "bigdl_* metric name not declared in obs/names.py",
    "RD004": "declared metric neither rendered by report.py nor documented",
    "RD005": "mint site disagrees with the declared metric kind/labels",
    "RD006": "serving span/event named by a string literal "
             "(use bigdl_tpu/serving/spans.py constants)",
    "RD007": "metric family missing a legal fleet aggregation policy "
             "(gauges must declare max/min/last; sum gauges opt in)",
    "RD008": "bigdl_prof_*/bigdl_bundle_* self-metric family relies on "
             "an implicit fleet policy (spell policy='sum' out)",
}
core.ALL_RULES.update(RULES)

#: the fleet-policy vocabulary (mirrors obs/names.py POLICIES) and the
#: subset a gauge may declare without an explicit RD007 opt-in
_POLICIES = ("sum", "max", "min", "last")
_GAUGE_POLICIES = ("max", "min", "last")
#: the self-observability families RD008 holds to an *explicit*-policy
#: standard (the profiling + debug-bundle planes)
_SELFOBS_PREFIXES = ("bigdl_prof_", "bigdl_bundle_")

# metric-name shape: no trailing/double underscore (tempdir prefixes
# like "bigdl_serve_smoke_" are spellings, not families)
_METRIC_RE = re.compile(r"bigdl_[a-z0-9]+(?:_[a-z0-9]+)*")
_ENV_HELPERS = {"_env_bool", "_env_int", "_env_opt_int", "_env_float",
                "_env_str"}
_MINT_METHODS = {"counter", "gauge", "histogram"}
_HIST_SUFFIXES = ("_bucket", "_sum", "_count")
_SPAN_METHODS = {"span", "event", "complete"}
_SPANS_MODULE = "bigdl_tpu.serving.spans"


def _pkg_root() -> str:
    return os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


class _DeclaredMetric:
    def __init__(self, name, kind, labels, const, line, doc,
                 policy=None):
        self.name = name
        self.kind = kind
        self.labels = labels
        self.const = const
        self.line = line
        self.doc = doc
        self.policy = policy


def parse_config_declarations(path: str) -> Tuple[Set[str], Set[str]]:
    """(declared env vars, harness bootstrap vars) from config.py."""
    declared: Set[str] = set()
    harness: Set[str] = set()
    if not os.path.exists(path):
        return declared, harness
    with open(path, encoding="utf-8") as fh:
        tree = ast.parse(fh.read())
    for node in ast.walk(tree):
        if isinstance(node, ast.Call) and isinstance(node.func, ast.Name) \
                and node.func.id in _ENV_HELPERS and node.args:
            v = str_const(node.args[0])
            if v:
                declared.add(v)
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name) \
                and node.targets[0].id == "HARNESS_ENV":
            for e in ast.walk(node.value):
                v = str_const(e)
                if v:
                    harness.add(v)
    return declared, harness


def parse_names_registry(path: str) -> Tuple[Dict[str, _DeclaredMetric],
                                             Set[str]]:
    """Declared metric specs + KNOWN_STRINGS from obs/names.py (AST —
    the linter must work on a tree that doesn't import)."""
    declared: Dict[str, _DeclaredMetric] = {}
    known: Set[str] = set()
    if not os.path.exists(path):
        return declared, known
    with open(path, encoding="utf-8") as fh:
        tree = ast.parse(fh.read())
    for node in tree.body:
        if not (isinstance(node, ast.Assign) and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)):
            continue
        const = node.targets[0].id
        if const == "KNOWN_STRINGS":
            for e in ast.walk(node.value):
                v = str_const(e)
                if v:
                    known.add(v)
            continue
        call = node.value
        if not (isinstance(call, ast.Call)
                and isinstance(call.func, ast.Name)
                and call.func.id == "_m" and call.args):
            continue
        name = str_const(call.args[0])
        if not name:
            continue
        kind = str_const(call.args[1]) if len(call.args) > 1 else None
        labels: Tuple[str, ...] = ()
        doc = ""
        policy = None
        if len(call.args) > 2 and isinstance(call.args[2],
                                             (ast.Tuple, ast.List)):
            labels = tuple(str_const(e) or "" for e in call.args[2].elts)
        if len(call.args) > 4:
            doc = str_const(call.args[4]) or ""
        if len(call.args) > 5:
            policy = str_const(call.args[5])
        for kw in call.keywords:
            if kw.arg == "labels" and isinstance(kw.value,
                                                 (ast.Tuple, ast.List)):
                labels = tuple(str_const(e) or "" for e in kw.value.elts)
            elif kw.arg == "doc":
                doc = str_const(kw.value) or ""
            elif kw.arg == "kind":
                kind = str_const(kw.value)
            elif kw.arg == "policy":
                policy = str_const(kw.value)
        declared[name] = _DeclaredMetric(name, kind, labels, const,
                                         node.lineno, doc, policy)
    return declared, known


class RegistryRules:
    """The RD pack.  Registry locations default to the real tree and
    are injectable so rule unit tests can point at fixtures."""

    rules = RULES

    def __init__(self, config_path: Optional[str] = None,
                 names_path: Optional[str] = None,
                 report_path: Optional[str] = None):
        root = _pkg_root()
        self.config_path = config_path or os.path.join(root, "config.py")
        self.names_path = names_path or os.path.join(root, "obs",
                                                     "names.py")
        self.report_path = report_path or os.path.join(root, "obs",
                                                       "report.py")
        self.declared_env, self.harness_env = parse_config_declarations(
            self.config_path)
        self.metrics, self.known_strings = parse_names_registry(
            self.names_path)
        self._names_lines: Optional[List[str]] = None

    # --------------------------------------------------------- helpers
    def _metric_declared(self, name: str) -> bool:
        if name in self.metrics or name in self.known_strings:
            return True
        for suffix in _HIST_SUFFIXES:
            if name.endswith(suffix):
                spec = self.metrics.get(name[: -len(suffix)])
                if spec is not None and spec.kind == "histogram":
                    return True
        return False

    def _names_module_aliases(self, tree) -> Tuple[Set[str], Set[str]]:
        """(module aliases of obs.names, constants imported from it)."""
        mod_aliases: Set[str] = set()
        const_imports: Set[str] = set()
        for node in ast.walk(tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    if a.name == "bigdl_tpu.obs.names":
                        mod_aliases.add(a.asname or "names")
            elif isinstance(node, ast.ImportFrom):
                if node.module == "bigdl_tpu.obs.names":
                    for a in node.names:
                        const_imports.add(a.asname or a.name)
                elif node.module == "bigdl_tpu.obs":
                    for a in node.names:
                        if a.name == "names":
                            mod_aliases.add(a.asname or "names")
        return mod_aliases, const_imports

    # ----------------------------------------------------------- visit
    def visit_module(self, mod: ModuleInfo) -> List[Finding]:
        findings: List[Finding] = []
        is_names_file = os.path.abspath(mod.path) == os.path.abspath(
            self.names_path)
        findings.extend(self._check_env_reads(mod))
        if not is_names_file:
            findings.extend(self._check_metric_names(mod))
        findings.extend(self._check_span_literals(mod))
        return findings

    # ---------------------------------------------------- span literals
    def _imports_spans(self, tree) -> bool:
        for node in ast.walk(tree):
            if isinstance(node, ast.Import):
                if any(a.name == _SPANS_MODULE for a in node.names):
                    return True
            elif isinstance(node, ast.ImportFrom):
                if node.module == _SPANS_MODULE:
                    return True
                if node.module == "bigdl_tpu.serving" and any(
                        a.name == "spans" for a in node.names):
                    return True
        return False

    def _check_span_literals(self, mod: ModuleInfo) -> List[Finding]:
        """RD006: span-name registry drift — the serving tier (and any
        module that opted into ``serving/spans.py`` by importing it)
        must name its tracer spans/events from the constants."""
        rel = mod.relpath.replace(os.sep, "/")
        in_serving = "bigdl_tpu/serving/" in rel or rel.startswith(
            "serving/")
        if not in_serving and not self._imports_spans(mod.tree):
            return []
        findings = []
        for node in ast.walk(mod.tree):
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr in _SPAN_METHODS and node.args):
                continue
            name = str_const(node.args[0])
            if name is None:
                continue
            findings.append(mod.finding(
                "RD006", node,
                f"span/event {name!r} named by a string literal — name "
                "it in bigdl_tpu/serving/spans.py and reference the "
                "constant (a typo'd literal forks the request-trace "
                "timeline silently)"))
        return findings

    # -------------------------------------------------------- env reads
    def _check_env_reads(self, mod: ModuleInfo) -> List[Finding]:
        findings = []
        for node, parents in core.walk_with_parents(mod.tree):
            key = None
            if isinstance(node, ast.Subscript) \
                    and isinstance(node.ctx, ast.Load) \
                    and dotted_name(node.value) in ("os.environ",
                                                    "environ"):
                key = str_const(node.slice)
            elif isinstance(node, ast.Call):
                fname = dotted_name(node.func)
                if fname in ("os.getenv",) and node.args:
                    key = str_const(node.args[0])
                elif isinstance(node.func, ast.Attribute) \
                        and node.func.attr == "get" \
                        and dotted_name(node.func.value) in (
                            "os.environ", "environ") and node.args:
                    key = str_const(node.args[0])
            if not key or not key.startswith("BIGDL_"):
                continue
            if key in self.harness_env:
                if mod.is_library:
                    findings.append(mod.finding(
                        "RD001", node,
                        f"harness bootstrap var {key} read from library "
                        "code; it is a scripts-only contract"))
                continue
            if key not in self.declared_env:
                findings.append(mod.finding(
                    "RD001", node,
                    f"{key} read from the environment but not declared "
                    "in bigdl_tpu/config.py — declare a config field "
                    "(or add it to HARNESS_ENV) so `config.describe()` "
                    "stays the single source of truth"))
            elif mod.is_library:
                findings.append(mod.finding(
                    "RD002", node,
                    f"raw os.environ read of {key} in framework code; "
                    "read it through bigdl_tpu.config (configure() "
                    "overrides and refresh_from_env() are bypassed "
                    "here)"))
        return findings

    # ---------------------------------------------------- metric names
    def _resolve_metric_arg(self, expr, consts: Dict[str, ast.AST],
                            mod_aliases: Set[str],
                            const_imports: Set[str]
                            ) -> Tuple[Optional[str], str]:
        """(metric name, 'literal'|'const'|'unknown') for a mint call's
        first argument."""
        s = str_const(expr)
        if s is not None:
            return s, "literal"
        if isinstance(expr, ast.Starred):
            expr = expr.value
            if isinstance(expr, ast.Name) and expr.id in consts:
                v = consts[expr.id]
                if isinstance(v, (ast.Tuple, ast.List)) and v.elts:
                    return self._resolve_metric_arg(
                        v.elts[0], consts, mod_aliases, const_imports)
            return None, "unknown"
        if isinstance(expr, ast.Attribute) \
                and isinstance(expr.value, ast.Name) \
                and expr.value.id in mod_aliases:
            for spec in self.metrics.values():
                if spec.const == expr.attr:
                    return spec.name, "const"
            return None, "badconst"
        if isinstance(expr, ast.Name):
            if expr.id in const_imports:
                for spec in self.metrics.values():
                    if spec.const == expr.id:
                        return spec.name, "const"
                return None, "badconst"
            if expr.id in consts:
                return self._resolve_metric_arg(
                    consts[expr.id], consts, mod_aliases, const_imports)
        return None, "unknown"

    def _check_metric_names(self, mod: ModuleInfo) -> List[Finding]:
        findings = []
        mod_aliases, const_imports = self._names_module_aliases(mod.tree)
        consts: Dict[str, ast.AST] = {}
        for node in mod.tree.body:
            if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name):
                consts[node.targets[0].id] = node.value

        mint_literal_lines: Set[Tuple[int, str]] = set()
        for node in ast.walk(mod.tree):
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr in _MINT_METHODS and node.args):
                continue
            name, form = self._resolve_metric_arg(
                node.args[0], consts, mod_aliases, const_imports)
            if form == "badconst":
                findings.append(mod.finding(
                    "RD003", node,
                    "metric constant does not exist in "
                    "bigdl_tpu/obs/names.py"))
                continue
            if name is None or not name.startswith("bigdl_"):
                continue
            spec = self.metrics.get(name)
            if spec is None:
                findings.append(mod.finding(
                    "RD003", node,
                    f"metric {name!r} minted but not declared in "
                    "bigdl_tpu/obs/names.py — declare it there (kind, "
                    "labels, cardinality ceiling, doc)"))
                mint_literal_lines.add((node.lineno, name))
                continue
            if form == "literal" and mod.is_library:
                findings.append(mod.finding(
                    "RD003", node,
                    f"metric {name!r} minted from a string literal in "
                    "framework code; mint from the "
                    f"bigdl_tpu.obs.names.{spec.const} constant"))
            # RD005: declared shape must match the mint site
            if node.func.attr != spec.kind:
                findings.append(mod.finding(
                    "RD005", node,
                    f"{name} is declared a {spec.kind} but minted with "
                    f".{node.func.attr}()"))
            for kw in node.keywords:
                if kw.arg != "labels":
                    continue
                if isinstance(kw.value, (ast.Tuple, ast.List)):
                    got = tuple(str_const(e) or "?"
                                for e in kw.value.elts)
                    if set(got) != set(spec.labels):
                        findings.append(mod.finding(
                            "RD005", node,
                            f"{name} is declared with labels "
                            f"{spec.labels!r} but minted with "
                            f"{got!r}"))

        # every exact bigdl_* spelling must be a declared family, a
        # histogram derivation of one, or a KNOWN_STRINGS entry
        for node in ast.walk(mod.tree):
            s = str_const(node)
            if s is None or not _METRIC_RE.fullmatch(s):
                continue
            if self._metric_declared(s):
                continue
            if (node.lineno, s) in mint_literal_lines:
                continue  # already reported as an undeclared mint
            findings.append(mod.finding(
                "RD003", node,
                f"bigdl_* spelling {s!r} is not a declared metric "
                "family (bigdl_tpu/obs/names.py) — declare it, or add "
                "it to names.KNOWN_STRINGS if it is not a metric"))
        return findings

    # -------------------------------------------------------- finalize
    def _names_rel(self) -> str:
        """The registry's path as findings (and inline suppressions)
        see it: cut at the ``bigdl_tpu`` package component, else
        repo-root-relative (fixture registries under ``tests/``)."""
        names_rel = self.names_path.replace(os.sep, "/")
        parts = names_rel.split("/")
        for i, part in enumerate(parts):
            if part == "bigdl_tpu":
                return "/".join(parts[i:])
        return os.path.relpath(
            self.names_path,
            os.path.dirname(_pkg_root())).replace(os.sep, "/")

    def finalize(self) -> List[Finding]:
        findings = []
        report_text = ""
        if os.path.exists(self.report_path):
            with open(self.report_path, encoding="utf-8") as fh:
                report_text = fh.read()
        names_rel = self._names_rel()
        for spec in sorted(self.metrics.values(), key=lambda s: s.line):
            rendered = (spec.name in report_text
                        or spec.const in report_text)
            if not rendered and not spec.doc.strip():
                findings.append(Finding(
                    "RD004", names_rel, spec.line,
                    f"{spec.name} is declared but neither rendered by "
                    "obs/report.py nor documented (doc=...) — an "
                    "operator can't discover what it means"))
            findings.extend(self._check_policy(spec, names_rel))
        return findings

    def _rd007_suppressed(self, line: int, rule: str = "RD007") -> bool:
        """Inline ``# graftlint: disable=<rule>`` on the declaration (or
        the line above) — honored here because the registry file is
        usually *not* among the linted modules, so the core suppression
        pass never sees its comments."""
        if self._names_lines is None:
            try:
                with open(self.names_path, encoding="utf-8") as fh:
                    self._names_lines = fh.read().splitlines()
            except OSError:
                self._names_lines = []
        for ln in (line, line - 1):
            if not 1 <= ln <= len(self._names_lines):
                continue
            m = core._DIRECTIVE_RE.search(self._names_lines[ln - 1])
            if m and m.group(1) == "disable":
                rules = core._directive_rules(m)
                if rules is None or rule in rules:
                    return True
        return False

    def _check_policy(self, spec, names_rel: str) -> List[Finding]:
        """RD007: the fleet aggregation policy contract every family
        must satisfy before the rollup tier may merge it."""
        if spec.kind not in ("counter", "gauge", "histogram"):
            return []  # kind errors are names.py's own ValueError
        if self._rd007_suppressed(spec.line):
            return []
        p = spec.policy
        if spec.kind in ("counter", "histogram"):
            if p is not None and p != "sum":
                return [Finding(
                    "RD007", names_rel, spec.line,
                    f"{spec.name}: a {spec.kind} merges additively "
                    f"across the fleet — policy {p!r} is illegal "
                    "(omit it or declare 'sum')")]
            # RD008: the self-observability planes may not lean on the
            # additive default — a new bigdl_prof_*/bigdl_bundle_*
            # family lands with its rollup decision written down
            if p is None and spec.name.startswith(_SELFOBS_PREFIXES) \
                    and not self._rd007_suppressed(spec.line, "RD008"):
                return [Finding(
                    "RD008", names_rel, spec.line,
                    f"{spec.name}: {spec.kind} in the "
                    "profiling/debug-bundle plane relies on the "
                    "implicit additive policy — these families feed "
                    "the fleet rollup that makes a misconfigured "
                    "profiler visible, so spell policy='sum' "
                    "explicitly")]
            return []
        # gauges: an explicit, legal policy is the whole point
        if p is None:
            return [Finding(
                "RD007", names_rel, spec.line,
                f"{spec.name}: gauge declares no fleet aggregation "
                "policy — the rollup tier cannot guess whether the "
                "fleet value is the max, min or newest host; declare "
                "policy='max'|'min'|'last'")]
        if p not in _POLICIES:
            return [Finding(
                "RD007", names_rel, spec.line,
                f"{spec.name}: unknown fleet policy {p!r} "
                f"(legal: {', '.join(_POLICIES)})")]
        if p not in _GAUGE_POLICIES:
            return [Finding(
                "RD007", names_rel, spec.line,
                f"{spec.name}: policy='sum' on a gauge is almost "
                "always a unit error (summing ratios or clocks); if "
                "this gauge really is an additive count, opt in with "
                "an inline '# graftlint: disable=RD007'")]
        return []
