"""bigdl_tpu.analysis — graftlint, the repo-native static-analysis pass.

The bug classes that cost the most here never fail a unit test: a host
sync inside a jitted body (13x serve throughput collapse, PR 13), an
inconsistent lock order between the obs registry and a serving thread,
an env var or metric name minted ad hoc that no dashboard ever sees.
``graftlint`` encodes those as AST rules over this repo's own idioms:

* :mod:`bigdl_tpu.analysis.jax_rules` — JX001..JX005: host-sync /
  tracer-leak / jit-in-loop / unhashable-static / tracer-branch;
* :mod:`bigdl_tpu.analysis.concurrency` — CC001..CC003: lock-order
  cycles, unlocked shared writes from thread entry points, bare
  ``acquire()``;
* :mod:`bigdl_tpu.analysis.registry_rules` — RD001..RD005: ``BIGDL_*``
  env reads outside ``config.py``, metric names outside
  ``obs/names.py``, undocumented/unrendered metrics, mint-shape drift.

CLI: ``python -m bigdl_tpu.analysis.lint bigdl_tpu scripts`` (also
``scripts/run-tests.sh --lint``).  Gated in tier-1 by
``tests/test_lint.py::test_repo_is_clean``.
"""

from bigdl_tpu.analysis.core import Finding, Linter

__all__ = ["Finding", "Linter", "run_lint"]


def run_lint(*args, **kwargs):
    """Lazy alias for :func:`bigdl_tpu.analysis.lint.run_lint` (the
    submodule is imported on demand so ``python -m
    bigdl_tpu.analysis.lint`` doesn't double-import it)."""
    from bigdl_tpu.analysis.lint import run_lint as _run

    return _run(*args, **kwargs)
