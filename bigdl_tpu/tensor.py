"""Tensor façade — the BigDL ``Tensor[T]`` op surface over jnp arrays.

Rebuild of ⟦«bigdl»/tensor/DenseTensor.scala⟧ (SURVEY.md §2.1 "Tensor
core"; §7 build-order step 1; VERDICT r2 #8).  The reference tensor is a
*mutable*, 1-based, strided JVM array; layers mutate it in place and
user code leans on ``narrow``/``select``/``copy``/``fill``/``resize``
and friends.

TPU-first design: the math lives in immutable ``jnp`` arrays (XLA owns
layout and fusion — strides/storage-offset machinery is deleted), and
this façade restores the *API contract* only: a thin mutable wrapper
whose "mutation" rebinds the wrapped array.  That preserves observable
BigDL semantics (aliasing of whole tensors via ``set``, in-place-style
builder returns) at the API edge while keeping every op jit-friendly —
a ``Tensor`` auto-converts via ``__array__``/``__jax_array__`` so it
can be passed straight into layers, criterions and optimizers.

1-based conventions follow the reference exactly where its API leaks
them: ``narrow``/``select``/``transpose`` dims and start indices,
``max``/``min`` returned indices, ``setValue``/``valueAt``.
"""

from __future__ import annotations

from typing import Callable, Optional, Sequence

import numpy as np


def _jnp():
    import jax.numpy as jnp

    return jnp


class Tensor:
    """Mutable façade over an immutable ``jnp.ndarray``."""

    __array_priority__ = 100  # numpy defers binary ops to Tensor

    def __init__(self, *sizes, dtype=None):
        jnp = _jnp()
        if len(sizes) == 1 and not isinstance(sizes[0], (int, np.integer)):
            # Tensor(ndarray-like) — wrap; lists default to float32,
            # typed arrays keep their dtype unless overridden
            data = sizes[0]
            if dtype is None and not hasattr(data, "dtype"):
                dtype = jnp.float32
            self._a = jnp.asarray(data, dtype)
        elif sizes:
            self._a = jnp.zeros(tuple(int(s) for s in sizes),
                                dtype or jnp.float32)
        else:
            self._a = jnp.zeros((), dtype or jnp.float32)

    # ------------------------------------------------------------ bridges
    @classmethod
    def from_ndarray(cls, a) -> "Tensor":
        return cls(np.asarray(a))

    def to_ndarray(self) -> np.ndarray:
        return np.asarray(self._a)

    def __array__(self, dtype=None):
        a = np.asarray(self._a)
        return a.astype(dtype) if dtype is not None else a

    def __jax_array__(self):
        return self._a

    @property
    def data(self):
        """The wrapped jnp array (read point for jit code)."""
        return self._a

    # ---------------------------------------------------------- shape api
    def size(self, dim: Optional[int] = None):
        """Reference: size() -> Array[Int]; size(d) 1-based."""
        if dim is None:
            return tuple(self._a.shape)
        return self._a.shape[dim - 1]

    def dim(self) -> int:
        return self._a.ndim

    n_dimension = dim
    nDimension = property(lambda self: self._a.ndim)

    def n_element(self) -> int:
        return int(self._a.size)

    nElement = n_element

    @property
    def shape(self):
        return self._a.shape

    @property
    def dtype(self):
        return self._a.dtype

    def is_empty(self) -> bool:
        return self._a.size == 0

    def is_scalar(self) -> bool:
        return self._a.ndim == 0

    # ------------------------------------------------------ slicing (1-based)
    def narrow(self, dim: int, index: int, size: int) -> "Tensor":
        """Reference: narrow(dim, index, size), both 1-based; shares no
        storage (XLA arrays are immutable — use set()/copy() to write
        back)."""
        jnp = _jnp()
        start = [0] * self._a.ndim
        sizes = list(self._a.shape)
        start[dim - 1] = index - 1
        sizes[dim - 1] = size
        return Tensor(
            jnp.asarray(
                self._a[tuple(slice(s, s + n) for s, n in zip(start, sizes))]
            )
        )

    def select(self, dim: int, index: int) -> "Tensor":
        """Reference: select(dim, index) — drops ``dim`` (1-based)."""
        idx = [slice(None)] * self._a.ndim
        idx[dim - 1] = index - 1
        return Tensor(self._a[tuple(idx)])

    def index_select(self, dim: int, indices) -> "Tensor":
        jnp = _jnp()
        ix = jnp.asarray(np.asarray(indices, np.int64) - 1)
        return Tensor(jnp.take(self._a, ix, axis=dim - 1))

    def view(self, *sizes) -> "Tensor":
        if len(sizes) == 1 and isinstance(sizes[0], (list, tuple)):
            sizes = tuple(sizes[0])
        return Tensor(self._a.reshape(sizes))

    reshape = view

    def squeeze(self, dim: Optional[int] = None) -> "Tensor":
        jnp = _jnp()
        if dim is None:
            self._a = jnp.squeeze(self._a)
        elif self._a.shape[dim - 1] == 1:
            self._a = jnp.squeeze(self._a, axis=dim - 1)
        return self

    def unsqueeze(self, dim: int) -> "Tensor":
        jnp = _jnp()
        self._a = jnp.expand_dims(self._a, dim - 1)
        return self

    def t(self) -> "Tensor":
        assert self._a.ndim == 2, "t() expects a 2D tensor"
        return Tensor(self._a.T)

    def transpose(self, dim1: int, dim2: int) -> "Tensor":
        jnp = _jnp()
        return Tensor(jnp.swapaxes(self._a, dim1 - 1, dim2 - 1))

    def clone(self) -> "Tensor":
        jnp = _jnp()
        return Tensor(jnp.array(self._a, copy=True))

    def contiguous(self) -> "Tensor":
        return self  # XLA arrays are always logically contiguous

    # ------------------------------------------------- mutation (rebinding)
    def set(self, other: "Tensor") -> "Tensor":
        """Reference: set(other) — alias other's storage.  The façade
        rebinds to the same underlying array (true aliasing of the
        whole tensor)."""
        self._a = other._a if isinstance(other, Tensor) else _jnp().asarray(other)
        return self

    def copy(self, src) -> "Tensor":
        """Reference: copy(src) — overwrite contents elementwise."""
        jnp = _jnp()
        src_a = src._a if isinstance(src, Tensor) else jnp.asarray(src)
        self._a = jnp.asarray(src_a, self._a.dtype).reshape(self._a.shape)
        return self

    def fill(self, value) -> "Tensor":
        jnp = _jnp()
        self._a = jnp.full_like(self._a, value)
        return self

    def zero(self) -> "Tensor":
        return self.fill(0)

    def resize(self, *sizes) -> "Tensor":
        """Reference: resize keeps content when the element count
        matches, else reallocates (zeros)."""
        jnp = _jnp()
        if len(sizes) == 1 and isinstance(sizes[0], (list, tuple)):
            sizes = tuple(sizes[0])
        sizes = tuple(int(s) for s in sizes)
        if int(np.prod(sizes)) == self._a.size:
            self._a = self._a.reshape(sizes)
        else:
            self._a = jnp.zeros(sizes, self._a.dtype)
        return self

    def resize_as(self, other: "Tensor") -> "Tensor":
        return self.resize(*other.shape)

    resizeAs = resize_as

    def set_value(self, *args) -> "Tensor":
        """setValue(d1, ..., dn, value) — 1-based indices."""
        *idx, value = args
        ix = tuple(int(i) - 1 for i in idx)
        self._a = self._a.at[ix].set(value)
        return self

    setValue = set_value

    def value_at(self, *idx):
        ix = tuple(int(i) - 1 for i in idx)
        return self._a[ix].item()

    valueAt = value_at

    # ------------------------------------------------------------- math
    def _coerce(self, other):
        return other._a if isinstance(other, Tensor) else other

    def add(self, other) -> "Tensor":
        self._a = self._a + self._coerce(other)
        return self

    def sub(self, other) -> "Tensor":
        self._a = self._a - self._coerce(other)
        return self

    def mul(self, scalar) -> "Tensor":
        self._a = self._a * self._coerce(scalar)
        return self

    def div(self, other) -> "Tensor":
        self._a = self._a / self._coerce(other)
        return self

    def cmul(self, other) -> "Tensor":
        return self.mul(other)

    def cdiv(self, other) -> "Tensor":
        return self.div(other)

    def pow(self, n) -> "Tensor":
        self._a = self._a ** n
        return self

    def sqrt(self) -> "Tensor":
        self._a = _jnp().sqrt(self._a)
        return self

    def exp(self) -> "Tensor":
        self._a = _jnp().exp(self._a)
        return self

    def log(self) -> "Tensor":
        self._a = _jnp().log(self._a)
        return self

    def abs(self) -> "Tensor":
        self._a = _jnp().abs(self._a)
        return self

    def add_mm(self, m1, m2) -> "Tensor":
        """addmm: self += m1 @ m2."""
        self._a = self._a + self._coerce(m1) @ self._coerce(m2)
        return self

    addmm = add_mm

    def mm(self, m1, m2) -> "Tensor":
        self._a = self._coerce(m1) @ self._coerce(m2)
        return self

    def mv(self, m, v) -> "Tensor":
        self._a = self._coerce(m) @ self._coerce(v)
        return self

    def dot(self, other):
        return float(_jnp().vdot(self._a, self._coerce(other)))

    def sum(self, dim: Optional[int] = None):
        if dim is None:
            return float(self._a.sum())
        jnp = _jnp()
        return Tensor(jnp.sum(self._a, axis=dim - 1, keepdims=True))

    def mean(self, dim: Optional[int] = None):
        if dim is None:
            return float(self._a.mean())
        jnp = _jnp()
        return Tensor(jnp.mean(self._a, axis=dim - 1, keepdims=True))

    def max(self, dim: Optional[int] = None):
        """max() -> scalar; max(dim) -> (values, 1-based indices) —
        reference convention."""
        jnp = _jnp()
        if dim is None:
            return float(self._a.max())
        vals = jnp.max(self._a, axis=dim - 1, keepdims=True)
        idx = jnp.argmax(self._a, axis=dim - 1, keepdims=True) + 1
        return Tensor(vals), Tensor(idx)

    def min(self, dim: Optional[int] = None):
        jnp = _jnp()
        if dim is None:
            return float(self._a.min())
        vals = jnp.min(self._a, axis=dim - 1, keepdims=True)
        idx = jnp.argmin(self._a, axis=dim - 1, keepdims=True) + 1
        return Tensor(vals), Tensor(idx)

    def norm(self, p: int = 2):
        jnp = _jnp()
        return float(jnp.sum(jnp.abs(self._a) ** p) ** (1.0 / p))

    # ------------------------------------------------------ apply1 / map
    def apply1(self, fn: Callable[[float], float]) -> "Tensor":
        """Reference: apply1(f) — elementwise host-side function.  Runs
        on host (numpy vectorize): it exists for API parity, not the
        hot path — jit code should use jnp ops."""
        jnp = _jnp()
        a = np.asarray(self._a)
        self._a = jnp.asarray(np.vectorize(fn)(a).astype(a.dtype))
        return self

    def map(self, other: "Tensor", fn: Callable[[float, float], float]) -> "Tensor":
        jnp = _jnp()
        a = np.asarray(self._a)
        b = np.asarray(self._coerce(other))
        self._a = jnp.asarray(np.vectorize(fn)(a, b).astype(a.dtype))
        return self

    # ----------------------------------------- gather / scatter / masked
    # (reference Tensor.scala user-facing surface — VERDICT r3 item 9)

    def gather(self, dim: int, index) -> "Tensor":
        """Reference: gather(dim, index) — index holds 1-based positions
        along ``dim``; output has index's shape."""
        jnp = _jnp()
        ix = jnp.asarray(np.asarray(index, np.int64) - 1)
        return Tensor(jnp.take_along_axis(self._a, ix, axis=dim - 1))

    def scatter(self, dim: int, index, src) -> "Tensor":
        """Reference: scatter(dim, index, src) — writes src values at
        the 1-based positions in index along ``dim`` (in place)."""
        jnp = _jnp()
        ix = np.asarray(index, np.int64) - 1
        srcv = self._coerce(src)
        d = dim - 1
        grids = np.indices(ix.shape)
        loc = [grids[k] for k in range(ix.ndim)]
        loc[d] = ix
        self._a = self._a.at[tuple(loc)].set(
            jnp.asarray(srcv)[tuple(grids)] if np.ndim(srcv) else srcv)
        return self

    def masked_fill(self, mask, value) -> "Tensor":
        """Reference: maskedFill(mask, value) — in place where mask != 0."""
        jnp = _jnp()
        m = jnp.asarray(self._coerce(mask)) != 0
        self._a = jnp.where(m, jnp.asarray(value, self._a.dtype), self._a)
        return self

    def masked_select(self, mask) -> "Tensor":
        """Reference: maskedSelect — 1-D tensor of elements where
        mask != 0 (host-side: output size is data-dependent)."""
        m = np.asarray(self._coerce(mask)) != 0
        return Tensor(np.asarray(self._a)[m])

    def masked_copy(self, mask, src) -> "Tensor":
        """Reference: maskedCopy — write src's elements (in order) into
        the mask-selected positions (host-side, in place)."""
        jnp = _jnp()
        a = np.array(self._a)
        m = np.asarray(self._coerce(mask)) != 0
        s = np.asarray(self._coerce(src)).reshape(-1)
        a[m] = s[: int(m.sum())]
        self._a = jnp.asarray(a)
        return self

    def index_fill(self, dim: int, indices, value) -> "Tensor":
        jnp = _jnp()
        ix = np.asarray(indices, np.int64) - 1
        idx = [slice(None)] * self._a.ndim
        idx[dim - 1] = jnp.asarray(ix)
        self._a = self._a.at[tuple(idx)].set(value)
        return self

    def index_copy(self, dim: int, indices, src) -> "Tensor":
        jnp = _jnp()
        ix = np.asarray(indices, np.int64) - 1
        idx = [slice(None)] * self._a.ndim
        idx[dim - 1] = jnp.asarray(ix)
        self._a = self._a.at[tuple(idx)].set(jnp.asarray(self._coerce(src)))
        return self

    def index_add(self, dim: int, indices, src) -> "Tensor":
        jnp = _jnp()
        ix = np.asarray(indices, np.int64) - 1
        idx = [slice(None)] * self._a.ndim
        idx[dim - 1] = jnp.asarray(ix)
        self._a = self._a.at[tuple(idx)].add(jnp.asarray(self._coerce(src)))
        return self

    # --------------------------------------------- more reference math
    def cmax(self, other) -> "Tensor":
        jnp = _jnp()
        self._a = jnp.maximum(self._a, self._coerce(other))
        return self

    def cmin(self, other) -> "Tensor":
        jnp = _jnp()
        self._a = jnp.minimum(self._a, self._coerce(other))
        return self

    def clamp(self, min_value, max_value) -> "Tensor":
        jnp = _jnp()
        self._a = jnp.clip(self._a, min_value, max_value)
        return self

    def sign(self) -> "Tensor":
        self._a = _jnp().sign(self._a)
        return self

    def floor(self) -> "Tensor":
        self._a = _jnp().floor(self._a)
        return self

    def ceil(self) -> "Tensor":
        self._a = _jnp().ceil(self._a)
        return self

    def addcmul(self, scalar, t1, t2) -> "Tensor":
        """self += scalar * t1 * t2 (reference addcmul)."""
        self._a = self._a + scalar * self._coerce(t1) * self._coerce(t2)
        return self

    def addcdiv(self, scalar, t1, t2) -> "Tensor":
        self._a = self._a + scalar * self._coerce(t1) / self._coerce(t2)
        return self

    def addr(self, v1, v2) -> "Tensor":
        """Outer product v1 (m) x v2 (n) added into self (m, n)."""
        jnp = _jnp()
        self._a = self._a + jnp.outer(jnp.asarray(self._coerce(v1)),
                                      jnp.asarray(self._coerce(v2)))
        return self

    def topk(self, k: int, dim: Optional[int] = None, increase: bool = False):
        """Reference: topk(k, dim, increase) -> (values, 1-based
        indices); smallest-k when ``increase`` (the reference default
        sorts ascending=smallest first when increase=true)."""
        jnp = _jnp()
        d = (self._a.ndim if dim is None else dim) - 1
        a = self._a if increase else -self._a
        order = jnp.argsort(a, axis=d)
        take = [slice(None)] * self._a.ndim
        take[d] = slice(0, k)
        idx = order[tuple(take)]
        vals = jnp.take_along_axis(self._a, idx, axis=d)
        return Tensor(vals), Tensor((idx + 1).astype(_jnp().float32))

    def sort(self, dim: Optional[int] = None, descending: bool = False):
        jnp = _jnp()
        d = (self._a.ndim if dim is None else dim) - 1
        order = jnp.argsort(-self._a if descending else self._a, axis=d)
        vals = jnp.take_along_axis(self._a, order, axis=d)
        return Tensor(vals), Tensor((order + 1).astype(jnp.float32))

    def nonzero(self) -> "Tensor":
        """1-based (nnz, ndim) coordinates (host-side: size is
        data-dependent)."""
        return Tensor(np.argwhere(np.asarray(self._a) != 0) + 1)

    def expand(self, *sizes) -> "Tensor":
        jnp = _jnp()
        if len(sizes) == 1 and isinstance(sizes[0], (list, tuple)):
            sizes = tuple(sizes[0])
        return Tensor(jnp.broadcast_to(self._a, tuple(int(s) for s in sizes)))

    def repeat_tensor(self, *sizes) -> "Tensor":
        jnp = _jnp()
        if len(sizes) == 1 and isinstance(sizes[0], (list, tuple)):
            sizes = tuple(sizes[0])
        return Tensor(jnp.tile(self._a, tuple(int(s) for s in sizes)))

    def split(self, size: int, dim: int = 1):
        """Chunks of ``size`` along 1-based dim (last may be smaller)."""
        d = dim - 1
        n = self._a.shape[d]
        outs = []
        idx = [slice(None)] * self._a.ndim
        for s in range(0, n, size):
            idx[d] = slice(s, min(s + size, n))
            outs.append(Tensor(self._a[tuple(idx)]))
        return outs

    def chunk(self, n_chunks: int, dim: int = 1):
        d = dim - 1
        size = -(-self._a.shape[d] // n_chunks)
        return self.split(size, dim)

    # ------------------------------------------------- random fills
    def uniform(self, a: float = 0.0, b: float = 1.0) -> "Tensor":
        from bigdl_tpu.common import RandomGenerator

        jnp = _jnp()
        self._a = jnp.asarray(
            RandomGenerator.RNG.uniform(a, b, self._a.shape)
            .astype(self._a.dtype))
        return self

    def normal(self, mean: float = 0.0, stdv: float = 1.0) -> "Tensor":
        from bigdl_tpu.common import RandomGenerator

        jnp = _jnp()
        self._a = jnp.asarray(
            (RandomGenerator.RNG.normal(mean, stdv, self._a.shape))
            .astype(self._a.dtype))
        return self

    def bernoulli(self, p: float = 0.5) -> "Tensor":
        from bigdl_tpu.common import RandomGenerator

        jnp = _jnp()
        self._a = jnp.asarray(
            (RandomGenerator.RNG.uniform(0, 1, self._a.shape) < p)
            .astype(self._a.dtype))
        return self

    # reference camelCase spellings
    maskedFill = masked_fill
    maskedSelect = masked_select
    maskedCopy = masked_copy
    indexSelect = index_select
    indexFill = index_fill
    indexCopy = index_copy
    indexAdd = index_add
    repeatTensor = repeat_tensor

    # -------------------------------------------------------- operators
    def __add__(self, other):
        return Tensor(self._a + self._coerce(other))

    __radd__ = __add__

    def __sub__(self, other):
        return Tensor(self._a - self._coerce(other))

    def __rsub__(self, other):
        return Tensor(self._coerce(other) - self._a)

    def __mul__(self, other):
        return Tensor(self._a * self._coerce(other))

    __rmul__ = __mul__

    def __truediv__(self, other):
        return Tensor(self._a / self._coerce(other))

    def __neg__(self):
        return Tensor(-self._a)

    def __getitem__(self, item):
        out = self._a[item]
        return Tensor(out) if getattr(out, "ndim", 0) else out.item()

    def __len__(self):
        return self._a.shape[0]

    def __eq__(self, other):
        if isinstance(other, Tensor):
            return (self._a.shape == other._a.shape
                    and bool((self._a == other._a).all()))
        return NotImplemented

    def __hash__(self):
        return id(self)

    def __repr__(self):
        return f"Tensor(shape={tuple(self._a.shape)}, dtype={self._a.dtype})\n{np.asarray(self._a)}"

    # reference spellings
    indexSelect = index_select


def randn(*sizes) -> Tensor:
    """Tensor filled from the seedable RandomGenerator (reference:
    Tensor[Float](...).randn())."""
    from bigdl_tpu.common import RandomGenerator

    return Tensor(RandomGenerator.RNG.normal(0.0, 1.0, tuple(sizes))
                  .astype(np.float32))


def rand(*sizes) -> Tensor:
    from bigdl_tpu.common import RandomGenerator

    return Tensor(RandomGenerator.RNG.uniform(0.0, 1.0, tuple(sizes))
                  .astype(np.float32))
