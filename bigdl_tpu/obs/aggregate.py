"""Multi-host trace aggregation — N per-host shards, one timeline.

Each process's :class:`~bigdl_tpu.obs.trace.Tracer` writes a private
``<app>.h<host>.<pid>.<seq>.events.jsonl`` shard into the (shared)
trace directory; nothing at runtime ever crosses hosts.  This module is
the offline half: it merges every shard in a directory into ONE
Perfetto-loadable Chrome ``trace_event`` JSON, with

* **host-tagged spans** — every merged event carries ``host``/``pid``
  in its args and renders under a ``host<h> pid<p>`` process track;
* **clock alignment on a shared barrier** — hosts' wall clocks disagree
  (NTP skew is routinely milliseconds, and the per-process
  ``time.time()`` anchor adds more).  ``Engine.init`` emits an
  ``engine.init_barrier`` instant event right after the multi-host
  bring-up (``jax.distributed.initialize`` returns on every process
  only once all have joined — the closest thing a JAX program has to a
  global barrier), so shifting each shard to make the barrier events
  coincide removes the skew instead of baking it silently into the
  timeline.  The applied per-shard offsets are preserved in
  ``otherData.offsets_s`` — the skew stays *visible*;
* shards with no barrier event merge unaligned (offset 0) and are
  flagged, never dropped.

CLI::

    python -m bigdl_tpu.obs.aggregate TRACE_DIR [-o merged.trace.json]

TensorFlow's system paper made the cross-worker timeline the debugging
tool for "which worker stalled the collective?"; this is that tool for
the DistriOptimizer pod-slice runs in MULTICHIP_r*.json.
"""

from __future__ import annotations

import json
import os
from typing import List, Optional

# the alignment anchor Engine.init emits after multi-host bring-up
BARRIER_EVENT = "engine.init_barrier"


class Shard:
    """One per-process events shard: parsed records + identity."""

    def __init__(self, path: str, records: List[dict]):
        self.path = path
        self.records = records
        first = records[0] if records else {}
        self.host = int(first.get("host", 0))
        self.pid = int(first.get("pid", 0))
        self.offset_s = 0.0
        self.aligned = False

    def barrier_wall(self, barrier: str = BARRIER_EVENT) -> Optional[float]:
        """Wall time of the FIRST barrier event in this shard (restarts
        re-emit it; the first is the bring-up one)."""
        for rec in self.records:
            if rec.get("name") == barrier:
                return float(rec["wall_time"])
        return None


def read_shards(trace_dir: str) -> List[Shard]:
    """Every ``*.events.jsonl`` shard in a directory, malformed lines
    skipped (a crash mid-write loses at most its last line)."""
    shards = []
    for fn in sorted(os.listdir(trace_dir)):
        if not fn.endswith(".events.jsonl"):
            continue
        recs = []
        with open(os.path.join(trace_dir, fn), encoding="utf-8") as fh:
            for line in fh:
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except json.JSONDecodeError:
                    continue  # torn tail line
                if isinstance(rec, dict) and "wall_time" in rec:
                    recs.append(rec)
        if recs:
            shards.append(Shard(os.path.join(trace_dir, fn), recs))
    return shards


def align_shards(shards: List[Shard],
                 barrier: str = BARRIER_EVENT) -> List[Shard]:
    """Compute per-shard clock offsets so every shard's barrier event
    lands at the same merged instant (the latest barrier wall time is
    the reference — offsets stay additive-positive for the laggards'
    view, and the choice is arbitrary for correctness)."""
    walls = {}
    for s in shards:
        w = s.barrier_wall(barrier)
        if w is not None:
            walls[id(s)] = w
    if walls:
        ref = max(walls.values())
        for s in shards:
            w = walls.get(id(s))
            if w is not None:
                s.offset_s = ref - w
                s.aligned = True
    return shards


def merge_shards(shards: List[Shard], barrier: str = BARRIER_EVENT) -> dict:
    """Merge aligned shards into one Chrome ``trace_event`` document.

    Chrome pids must be small ints and hosts may reuse OS pids, so each
    shard gets a synthetic process id with a ``host<h> pid<p>``
    process_name; original identities ride in every event's args."""
    if not shards:
        raise ValueError("no trace shards to merge")
    align_shards(shards, barrier)
    t0 = min(rec["wall_time"] + s.offset_s
             for s in shards for rec in s.records)
    meta, events = [], []
    for i, s in enumerate(sorted(shards, key=lambda s: (s.host, s.pid,
                                                        s.path))):
        cpid = i + 1
        meta.append({"name": "process_name", "ph": "M", "pid": cpid,
                     "tid": 0,
                     "args": {"name": f"host{s.host} pid{s.pid}"
                              + ("" if s.aligned else " (unaligned)")}})
        meta.append({"name": "process_sort_index", "ph": "M", "pid": cpid,
                     "tid": 0, "args": {"sort_index": s.host}})
        for rec in s.records:
            ts = round((rec["wall_time"] + s.offset_s - t0) * 1e6, 3)
            args = dict(rec.get("attrs") or {})
            args["host"] = s.host
            args["pid"] = s.pid
            ev = {"name": rec["name"], "ts": ts, "pid": cpid,
                  "tid": int(rec.get("tid", 1)), "args": args}
            if rec.get("kind") == "span":
                ev["ph"] = "X"
                ev["dur"] = round(float(rec.get("dur_s", 0.0)) * 1e6, 3)
            else:
                ev["ph"] = "i"
                ev["s"] = "t"
            events.append(ev)
    # a monotone timeline: Perfetto tolerates disorder, humans and the
    # monotonicity tests do not
    events.sort(key=lambda e: e["ts"])
    return {
        "traceEvents": meta + events,
        "displayTimeUnit": "ms",
        "otherData": {
            "merged_shards": len(shards),
            "barrier": barrier,
            "wall_epoch": t0,
            "offsets_s": {
                f"host{s.host}/pid{s.pid}": round(s.offset_s, 6)
                for s in shards},
            "unaligned": [f"host{s.host}/pid{s.pid}"
                          for s in shards if not s.aligned],
        },
    }


def merge_trace_dir(trace_dir: str, out_path: Optional[str] = None,
                    barrier: str = BARRIER_EVENT) -> dict:
    """Merge every shard under ``trace_dir``; write the merged Chrome
    trace (atomic replace) when ``out_path`` is given.  Returns a
    summary dict (shards, events, offsets, output path)."""
    shards = read_shards(trace_dir)
    doc = merge_shards(shards, barrier=barrier)
    if out_path:
        tmp = out_path + ".tmp"
        with open(tmp, "w", encoding="utf-8") as fh:
            json.dump(doc, fh, default=str)
        os.replace(tmp, out_path)
    return {
        "shards": len(shards),
        "hosts": sorted({s.host for s in shards}),
        "events": sum(len(s.records) for s in shards),
        "offsets_s": doc["otherData"]["offsets_s"],
        "unaligned": doc["otherData"]["unaligned"],
        "out": out_path,
    }


def main(argv=None) -> int:
    import argparse

    ap = argparse.ArgumentParser(
        prog="python -m bigdl_tpu.obs.aggregate",
        description="Merge per-host trace shards into one Perfetto "
                    "timeline with barrier clock alignment.")
    ap.add_argument("trace_dir", help="directory holding *.events.jsonl "
                                      "shards (BIGDL_TRACE_DIR)")
    ap.add_argument("-o", "--out", default=None,
                    help="merged Chrome trace path "
                         "(default: TRACE_DIR/merged.trace.json)")
    ap.add_argument("--barrier", default=BARRIER_EVENT,
                    help=f"alignment event name (default {BARRIER_EVENT})")
    args = ap.parse_args(argv)
    out = args.out or os.path.join(args.trace_dir, "merged.trace.json")
    try:
        summary = merge_trace_dir(args.trace_dir, out, barrier=args.barrier)
    except ValueError as e:
        print(json.dumps({"error": str(e)}))
        return 1
    print(json.dumps(summary))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
