"""Multi-host trace aggregation — N per-host shards, one timeline.

Each process's :class:`~bigdl_tpu.obs.trace.Tracer` writes a private
``<app>.h<host>.<pid>.<seq>.events.jsonl`` shard into the (shared)
trace directory; nothing at runtime ever crosses hosts.  This module is
the offline half: it merges every shard in a directory into ONE
Perfetto-loadable Chrome ``trace_event`` JSON, with

* **host-tagged spans** — every merged event carries ``host``/``pid``
  in its args and renders under a ``host<h> pid<p>`` process track;
* **clock alignment on a shared barrier** — hosts' wall clocks disagree
  (NTP skew is routinely milliseconds, and the per-process
  ``time.time()`` anchor adds more).  ``Engine.init`` emits an
  ``engine.init_barrier`` instant event right after the multi-host
  bring-up (``jax.distributed.initialize`` returns on every process
  only once all have joined — the closest thing a JAX program has to a
  global barrier), so shifting each shard to make the barrier events
  coincide removes the skew instead of baking it silently into the
  timeline.  The applied per-shard offsets are preserved in
  ``otherData.offsets_s`` — the skew stays *visible*;
* shards with no barrier event merge unaligned (offset 0) and are
  flagged, never dropped.

CLI::

    python -m bigdl_tpu.obs.aggregate TRACE_DIR [-o merged.trace.json]

TensorFlow's system paper made the cross-worker timeline the debugging
tool for "which worker stalled the collective?"; this is that tool for
the DistriOptimizer pod-slice runs in MULTICHIP_r*.json.
"""

from __future__ import annotations

import concurrent.futures
import json
import math
import os
import time
from typing import List, Optional
from bigdl_tpu.obs import names
from bigdl_tpu.resilience.retry import RetryBudget, backoff_delay

# the alignment anchor Engine.init emits after multi-host bring-up
BARRIER_EVENT = "engine.init_barrier"

# the per-host step span the straggler detector keys on (the
# dispatch -> resolved-loss wall time both optimizers emit)
STEP_SPAN = "computing"


class Shard:
    """One per-process events shard: parsed records + identity."""

    def __init__(self, path: str, records: List[dict]):
        self.path = path
        self.records = records
        first = records[0] if records else {}
        self.host = int(first.get("host", 0))
        self.pid = int(first.get("pid", 0))
        self.offset_s = 0.0
        self.aligned = False

    def barrier_wall(self, barrier: str = BARRIER_EVENT) -> Optional[float]:
        """Wall time of the FIRST barrier event in this shard (restarts
        re-emit it; the first is the bring-up one)."""
        for rec in self.records:
            if rec.get("name") == barrier:
                return float(rec["wall_time"])
        return None


def read_shards(trace_dir: str) -> List[Shard]:
    """Every ``*.events.jsonl`` shard in a directory, malformed lines
    skipped (a crash mid-write loses at most its last line)."""
    shards = []
    for fn in sorted(os.listdir(trace_dir)):
        if not fn.endswith(".events.jsonl"):
            continue
        recs = []
        with open(os.path.join(trace_dir, fn), encoding="utf-8") as fh:
            for line in fh:
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except json.JSONDecodeError:
                    continue  # torn tail line
                if isinstance(rec, dict) and "wall_time" in rec:
                    recs.append(rec)
        if recs:
            shards.append(Shard(os.path.join(trace_dir, fn), recs))
    return shards


def align_shards(shards: List[Shard],
                 barrier: str = BARRIER_EVENT) -> List[Shard]:
    """Compute per-shard clock offsets so every shard's barrier event
    lands at the same merged instant (the latest barrier wall time is
    the reference — offsets stay additive-positive for the laggards'
    view, and the choice is arbitrary for correctness)."""
    walls = {}
    for s in shards:
        w = s.barrier_wall(barrier)
        if w is not None:
            walls[id(s)] = w
    if walls:
        ref = max(walls.values())
        for s in shards:
            w = walls.get(id(s))
            if w is not None:
                s.offset_s = ref - w
                s.aligned = True
    return shards


def _p50(values: List[float]) -> Optional[float]:
    if not values:
        return None
    vs = sorted(values)
    return vs[min(len(vs) - 1, max(0, math.ceil(0.5 * len(vs)) - 1))]


def detect_stragglers(shards: List[Shard],
                      factor: Optional[float] = None) -> dict:
    """Cross-host straggler detection over the merged timeline.

    Two signals from the per-host ``computing`` (step) spans:

    * **host-level skew** — a host whose step-time p50 exceeds the
      cross-host median of p50s by ``factor`` is flagged (the chronic
      straggler that drags every synchronous collective);
    * **per-step skew** — for every step present on >= 2 hosts, a host
      slower than that step's cross-host median by ``factor`` counts
      one ``bigdl_straggler_steps_total{host}`` increment (the
      intermittent straggler a p50 hides).

    ``factor`` defaults to ``BIGDL_STRAGGLER_FACTOR`` (1.5); <= 1
    disables.  Works on wall durations only — clock *offsets* (which
    the barrier alignment removes) cannot fake a slow duration.
    Returns ``{factor, hosts: {host: {p50, steps, straggler_steps}},
    median_p50, stragglers: [host, ...]}``."""
    if factor is None:
        from bigdl_tpu.config import refresh_from_env

        factor = refresh_from_env().obs.straggler_factor
    factor = float(factor)
    # host -> {step -> [durs]} and host -> [durs]
    by_host: dict = {}
    by_host_step: dict = {}
    for s in shards:
        for rec in s.records:
            if rec.get("kind") != "span" or rec.get("name") != STEP_SPAN:
                continue
            dur = float(rec.get("dur_s", 0.0))
            by_host.setdefault(s.host, []).append(dur)
            step = (rec.get("attrs") or {}).get("step")
            if step is not None:
                by_host_step.setdefault(int(step), {}).setdefault(
                    s.host, []).append(dur)
    hosts = {h: {"p50": _p50(durs), "steps": len(durs),
                 "straggler_steps": 0}
             for h, durs in by_host.items()}
    out = {"factor": factor, "hosts": hosts, "median_p50": None,
           "stragglers": []}
    if factor <= 1.0 or len(hosts) < 2:
        return out
    median = _p50([v["p50"] for v in hosts.values()
                   if v["p50"] is not None])
    out["median_p50"] = median
    if median:
        out["stragglers"] = sorted(
            h for h, v in hosts.items()
            if v["p50"] is not None and v["p50"] > median * factor)
    for step, per_host in by_host_step.items():
        if len(per_host) < 2:
            continue
        step_durs = {h: _p50(d) for h, d in per_host.items()}
        step_median = _p50(list(step_durs.values()))
        if not step_median:
            continue
        for h, d in step_durs.items():
            if d > step_median * factor:
                hosts[h]["straggler_steps"] += 1
    # surface the counts as the labeled counter so in-process callers
    # (tests, a supervisor aggregating between launches) can scrape them
    if any(v["straggler_steps"] for v in hosts.values()) \
            or out["stragglers"]:
        from bigdl_tpu import obs

        counter = obs.get_registry().counter(
            names.STRAGGLER_STEPS_TOTAL,
            "Steps on which a host exceeded the cross-host median step "
            "time by BIGDL_STRAGGLER_FACTOR", labels=("host",))
        for h, v in hosts.items():
            if v["straggler_steps"]:
                counter.labels(host=h).inc(v["straggler_steps"])
    return out


def merge_shards(shards: List[Shard], barrier: str = BARRIER_EVENT) -> dict:
    """Merge aligned shards into one Chrome ``trace_event`` document.

    Chrome pids must be small ints and hosts may reuse OS pids, so each
    shard gets a synthetic process id with a ``host<h> pid<p>``
    process_name; original identities ride in every event's args."""
    if not shards:
        raise ValueError("no trace shards to merge")
    align_shards(shards, barrier)
    t0 = min(rec["wall_time"] + s.offset_s
             for s in shards for rec in s.records)
    meta, events = [], []
    for i, s in enumerate(sorted(shards, key=lambda s: (s.host, s.pid,
                                                        s.path))):
        cpid = i + 1
        meta.append({"name": "process_name", "ph": "M", "pid": cpid,
                     "tid": 0,
                     "args": {"name": f"host{s.host} pid{s.pid}"
                              + ("" if s.aligned else " (unaligned)")}})
        meta.append({"name": "process_sort_index", "ph": "M", "pid": cpid,
                     "tid": 0, "args": {"sort_index": s.host}})
        for rec in s.records:
            ts = round((rec["wall_time"] + s.offset_s - t0) * 1e6, 3)
            args = dict(rec.get("attrs") or {})
            args["host"] = s.host
            args["pid"] = s.pid
            ev = {"name": rec["name"], "ts": ts, "pid": cpid,
                  "tid": int(rec.get("tid", 1)), "args": args}
            if rec.get("kind") == "span":
                ev["ph"] = "X"
                ev["dur"] = round(float(rec.get("dur_s", 0.0)) * 1e6, 3)
            else:
                ev["ph"] = "i"
                ev["s"] = "t"
            events.append(ev)
    # cross-host straggler detection rides the merge: each flagged host
    # gets one `straggler` instant event at the end of the timeline so
    # the skew is ON the Perfetto view, not only in the summary
    stragglers = detect_stragglers(shards)
    host_cpid = {}
    for i, s in enumerate(sorted(shards, key=lambda s: (s.host, s.pid,
                                                        s.path))):
        host_cpid.setdefault(s.host, i + 1)
    end_ts = events[-1]["ts"] if events else 0.0
    for h in stragglers["stragglers"]:
        info = stragglers["hosts"].get(h, {})
        events.append({
            "name": "straggler", "ph": "i", "s": "g", "ts": end_ts,
            "pid": host_cpid.get(h, 1), "tid": 0,
            "args": {"host": h, "p50_s": info.get("p50"),
                     "median_p50_s": stragglers["median_p50"],
                     "factor": stragglers["factor"],
                     "straggler_steps": info.get("straggler_steps")}})
    # a monotone timeline: Perfetto tolerates disorder, humans and the
    # monotonicity tests do not
    events.sort(key=lambda e: e["ts"])
    return {
        "traceEvents": meta + events,
        "displayTimeUnit": "ms",
        "otherData": {
            "merged_shards": len(shards),
            "barrier": barrier,
            "wall_epoch": t0,
            "offsets_s": {
                f"host{s.host}/pid{s.pid}": round(s.offset_s, 6)
                for s in shards},
            "unaligned": [f"host{s.host}/pid{s.pid}"
                          for s in shards if not s.aligned],
            "stragglers": stragglers,
        },
    }


def merge_trace_dir(trace_dir: str, out_path: Optional[str] = None,
                    barrier: str = BARRIER_EVENT) -> dict:
    """Merge every shard under ``trace_dir``; write the merged Chrome
    trace (atomic replace) when ``out_path`` is given.  Returns a
    summary dict (shards, events, offsets, output path)."""
    shards = read_shards(trace_dir)
    doc = merge_shards(shards, barrier=barrier)
    if out_path:
        tmp = out_path + ".tmp"
        with open(tmp, "w", encoding="utf-8") as fh:
            json.dump(doc, fh, default=str)
        os.replace(tmp, out_path)
    return {
        "shards": len(shards),
        "hosts": sorted({s.host for s in shards}),
        "events": sum(len(s.records) for s in shards),
        "offsets_s": doc["otherData"]["offsets_s"],
        "unaligned": doc["otherData"]["unaligned"],
        "stragglers": doc["otherData"]["stragglers"]["stragglers"],
        "out": out_path,
    }


# ------------------------------------------------------------ live fleet
class ShardTailer:
    """Incremental ``metrics.*.jsonl`` tailing — the *live* reader for
    the shards the registry snapshots append to.

    A full re-read per refresh is O(run length); a dashboard refreshing
    every second needs O(new lines).  Each poll seeks every shard to
    its stored offset, consumes only complete new lines (a torn tail
    line stays unconsumed until its newline lands — the same
    torn-write tolerance the offline readers have), and keeps the
    newest parsed snapshot per shard.  A shard that shrank (truncated
    or replaced between runs) is re-read from zero."""

    def __init__(self, directory: str):
        self.directory = directory
        self._offsets: dict = {}
        self.latest: dict = {}   # shard filename -> newest snapshot

    def poll(self) -> dict:
        """Consume new lines from every shard; returns ``latest``."""
        if not self.directory or not os.path.isdir(self.directory):
            return self.latest
        for fn in sorted(os.listdir(self.directory)):
            if not (fn.startswith("metrics.") and fn.endswith(".jsonl")):
                continue
            path = os.path.join(self.directory, fn)
            try:
                size = os.path.getsize(path)
                offset = self._offsets.get(fn, 0)
                if size < offset:
                    offset = 0  # truncated/replaced: start over
                if size == offset:
                    continue
                with open(path, "rb") as fh:
                    fh.seek(offset)
                    chunk = fh.read(size - offset)
            except OSError:
                continue
            # only complete lines advance the offset
            consumed = chunk.rfind(b"\n") + 1
            if consumed <= 0:
                continue
            self._offsets[fn] = offset + consumed
            for line in chunk[:consumed].splitlines():
                line = line.strip()
                if not line:
                    continue
                try:
                    snap = json.loads(line.decode("utf-8"))
                except (json.JSONDecodeError, UnicodeDecodeError):
                    continue
                if isinstance(snap, dict) and "metrics" in snap:
                    snap.setdefault("shard", fn)
                    self.latest[fn] = snap
        return self.latest


class FleetAggregator:
    """One in-memory fleet snapshot from N hosts, while they run.

    Two sources, same output shape:

    * **peer scraping** — ``BIGDL_OBS_PEERS="h0:8080,h1:8080"`` (or a
      peers list): each refresh GETs every peer's ``/healthz`` and
      ``/metrics`` (parsed by :func:`~bigdl_tpu.obs.metrics.
      parse_prometheus`), so the snapshot is as fresh as the scrape;
    * **shard tailing** — no peers: incrementally tail the
      ``metrics.*.jsonl`` shards under ``metrics_dir`` (each host's
      snapshot writer appends there), as stale as the hosts' last
      flush but needing only a shared filesystem.

    ``snapshot()`` returns ``{mode, hosts: {host: {status, step,
    step_age_s, goodput_ratio, alerts, source}}, alerts: [...],
    metrics: {name: [{labels, value, source}]}, errors: {source:
    reason}, stale: {source: reason}}`` — what ``report --watch``
    renders and the autoscaling policy loop reads.  ``fetch`` is
    injectable for tests (no sockets); ``clock`` is injectable so the
    sims run staleness detection on virtual time.

    Staleness contract: every ok peer's ``/healthz`` ``time`` is
    compared against this scraper's clock; a skew past
    ``stale_after_s`` (``BIGDL_STALE_AFTER_S``) flags the host stale —
    its metrics are *excluded* from ``snapshot()``/rollup merges and
    *accounted* in ``bigdl_fleet_stale_hosts``, never silently folded
    into fleet percentiles.  Failed scrapes count stale the same way."""

    def __init__(self, peers=None, metrics_dir: Optional[str] = None,
                 fetch=None, timeout_s: float = 2.0,
                 max_workers: int = 16,
                 retry_budget: Optional[RetryBudget] = None,
                 stale_after_s: Optional[float] = None,
                 clock=None):
        if isinstance(peers, str):
            peers = [p.strip() for p in peers.split(",") if p.strip()]
        self.peers = list(peers or [])
        self.metrics_dir = metrics_dir
        self.timeout_s = float(timeout_s)
        self.max_workers = max(1, int(max_workers))
        self.last_scrape_s: Optional[float] = None
        self.last_stale: dict = {}
        self._clock = clock or time.time
        if stale_after_s is None:
            try:
                from bigdl_tpu.config import refresh_from_env

                stale_after_s = refresh_from_env().obs.stale_after_s
            except Exception:  # noqa: BLE001 — config must not sink this
                stale_after_s = 30.0
        self.stale_after_s = float(stale_after_s)
        self._fetch = fetch or self._http_fetch
        # the serving router's shared token bucket, reused here: one
        # flaky peer gets a second chance, a partitioned fleet does NOT
        # double the scrape cycle (the bucket drains after ~burst
        # retries and every further down peer costs one timeout, same
        # as before retries existed)
        self.retry_budget = retry_budget or RetryBudget(
            ratio=0.1, burst=4.0)
        self._tailer = (ShardTailer(metrics_dir)
                        if metrics_dir and not self.peers else None)

    @classmethod
    def from_config(cls) -> "FleetAggregator":
        from bigdl_tpu.config import refresh_from_env

        cfg = refresh_from_env().obs
        return cls(peers=cfg.obs_peers,
                   metrics_dir=cfg.metrics_dir or cfg.trace_dir)

    def _http_fetch(self, url: str) -> str:
        import urllib.request

        with urllib.request.urlopen(url, timeout=self.timeout_s) as r:
            return r.read().decode("utf-8")

    # ------------------------------------------------------ peer scrape
    def _scrape_once(self, base: str, out: dict) -> None:
        out["health"] = json.loads(self._fetch(base + "/healthz"))
        from bigdl_tpu.obs.metrics import parse_prometheus

        out["metrics"] = parse_prometheus(self._fetch(base + "/metrics"))
        out["ok"] = True

    def scrape_peer(self, addr: str) -> dict:
        """One peer's ``/healthz`` + ``/metrics`` (metrics parse errors
        are loud per the parse_prometheus contract; transport errors
        mark the peer down, they never raise).  A transport failure
        gets ONE more attempt after a jittered backoff while the shared
        :class:`~bigdl_tpu.resilience.retry.RetryBudget` grants a token
        — so a single flaky peer doesn't flap the fleet snapshot, but a
        partition (every peer failing) drains the bucket and degrades
        to single attempts instead of doubling the cycle."""
        base = addr if addr.startswith("http") else f"http://{addr}"
        out = {"addr": addr, "ok": False, "health": None, "metrics": None}
        t0 = time.perf_counter()
        self.retry_budget.record_request()
        try:
            self._scrape_once(base, out)
            out["latency_s"] = time.perf_counter() - t0
            return out
        except Exception as e:  # noqa: BLE001 — a dead peer is data
            out["error"] = f"{type(e).__name__}: {e}"
        if self.retry_budget.try_spend():
            time.sleep(backoff_delay(1, base=0.02, cap=0.2))
            try:
                self._scrape_once(base, out)
                out.pop("error", None)
            except Exception as e:  # noqa: BLE001 — still down
                out["error"] = f"{type(e).__name__}: {e}"
        out["latency_s"] = time.perf_counter() - t0
        return out

    @staticmethod
    def _error_reason(error: Optional[str]) -> str:
        """Fold a scrape error string into the bounded ``reason`` label
        of ``bigdl_fleet_scrape_errors_total``."""
        e = (error or "").lower()
        if "timeout" in e:
            return "timeout"
        if "refused" in e or "connection" in e:
            return "refused"
        if "valueerror" in e or "jsondecode" in e or "exposition" in e:
            return "protocol"
        return "error"

    def _classify_stale(self, scraped: List[dict]) -> None:
        """Annotate each scrape result with ``stale``/``stale_reason``
        (skewed clock past ``stale_after_s``, or a failed scrape) and
        publish the pipeline's meta-observability: per-host scrape
        latency and staleness gauges, error-reason counters, the
        excluded-host count."""
        from bigdl_tpu import obs

        reg = obs.get_registry()
        lat = reg.gauge(names.FLEET_SCRAPE_LATENCY_SECONDS,
                        names.spec(
                            names.FLEET_SCRAPE_LATENCY_SECONDS).doc,
                        labels=("host",))
        skew_g = reg.gauge(names.FLEET_HOST_STALENESS_SECONDS,
                           names.spec(
                               names.FLEET_HOST_STALENESS_SECONDS).doc,
                           labels=("host",))
        errs = reg.counter(names.FLEET_SCRAPE_ERRORS_TOTAL,
                           names.spec(
                               names.FLEET_SCRAPE_ERRORS_TOTAL).doc,
                           labels=("reason",))
        now = self._clock()
        stale: dict = {}
        for peer in scraped:
            addr = peer.get("addr", "?")
            if peer.get("latency_s") is not None:
                lat.labels(host=addr).set(peer["latency_s"])
            if not peer.get("ok"):
                reason = self._error_reason(peer.get("error"))
                errs.labels(reason=reason).inc()
                peer["stale"] = True
                peer["stale_reason"] = reason
                stale[addr] = peer.get("error") or reason
                continue
            peer["stale"] = False
            h = peer.get("health") or {}
            t_host = h.get("time")
            if t_host is None:
                continue
            skew = abs(now - float(t_host))
            skew_g.labels(host=addr).set(skew)
            if self.stale_after_s > 0 and skew > self.stale_after_s:
                peer["stale"] = True
                peer["stale_reason"] = f"clock skew {skew:.1f}s"
                stale[addr] = peer["stale_reason"]
        self.last_stale = stale
        reg.gauge(names.FLEET_STALE_HOSTS,
                  names.spec(names.FLEET_STALE_HOSTS).doc).set(
            len(stale))

    def scrape_peers(self, addrs) -> List[dict]:
        """One scrape cycle over ``addrs``, concurrently on a bounded
        thread pool (results in input order).

        Serially, a partitioned fleet costs N × timeout per cycle —
        40 unreachable peers at the 2s default is an 80s scrape, long
        past any policy interval.  Concurrently each peer's timeout
        runs on its own worker, so a cycle costs
        ``ceil(N / max_workers) × timeout`` worst-case.  The cycle
        wall clock is published as ``bigdl_fleet_scrape_seconds`` (and
        kept on ``last_scrape_s``) so a scrape that crowds its policy
        interval is visible before it starves the controller."""
        addrs = list(addrs)
        if not addrs:
            return []
        t0 = time.perf_counter()
        if len(addrs) == 1:
            out = [self.scrape_peer(addrs[0])]
        else:
            workers = min(self.max_workers, len(addrs))
            with concurrent.futures.ThreadPoolExecutor(
                    max_workers=workers,
                    thread_name_prefix="bigdl-fleet-scrape") as pool:
                out = list(pool.map(self.scrape_peer, addrs))
        self.last_scrape_s = time.perf_counter() - t0
        from bigdl_tpu import obs

        obs.get_registry().gauge(
            names.FLEET_SCRAPE_SECONDS,
            "Wall seconds of the last full fleet peer-scrape cycle"
        ).set(self.last_scrape_s)
        self._classify_stale(out)
        return out

    # --------------------------------------------------------- snapshot
    def snapshot(self) -> dict:
        fleet = {"mode": "peers" if self.peers else "shards",
                 "hosts": {}, "alerts": [], "metrics": {}, "errors": {},
                 "stale": {}}
        if self.peers:
            for scraped in self.scrape_peers(self.peers):
                addr = scraped["addr"]
                if not scraped["ok"]:
                    fleet["errors"][addr] = scraped.get("error", "down")
                    fleet["stale"][addr] = scraped.get(
                        "stale_reason", "down")
                    continue
                h = scraped["health"] or {}
                host = h.get("host", addr)
                entry = {
                    "status": h.get("status"), "step": h.get("step"),
                    "step_age_s": h.get("step_age_s"),
                    "goodput_ratio": h.get("goodput_ratio"),
                    "queue_depth": None,
                    "alerts": h.get("alerts") or [],
                    "heartbeat": h.get("heartbeat"), "source": addr}
                fleet["hosts"][str(host)] = entry
                if scraped.get("stale"):
                    # skewed clock: the host row stays visible (flagged)
                    # but its samples never reach the fleet merge — a
                    # stale host pollutes no percentile
                    entry["status"] = "stale"
                    entry["stale"] = True
                    fleet["stale"][addr] = scraped.get(
                        "stale_reason", "stale")
                    continue
                for a in h.get("alerts") or []:
                    fleet["alerts"].append(dict(a, host=host))
                for s in scraped["metrics"]["samples"]:
                    fleet["metrics"].setdefault(s["name"], []).append(
                        {"labels": s["labels"], "value": s["value"],
                         "source": addr})
                    # the streaming/serving backlog, on the host row —
                    # the signal the autoscaling policy loop scales on
                    if s["name"] in (names.STREAM_BUFFER_DEPTH,
                                     names.SERVE_QUEUE_DEPTH):
                        entry["queue_depth"] = max(
                            entry["queue_depth"] or 0.0, s["value"])
        elif self._tailer is not None:
            for fn, snap in sorted(self._tailer.poll().items()):
                host = snap.get("host", fn)
                entry = fleet["hosts"].setdefault(str(host), {
                    "status": "shard", "step": None, "step_age_s": None,
                    "goodput_ratio": None, "queue_depth": None,
                    "alerts": [], "source": fn})
                for name, fam in (snap.get("metrics") or {}).items():
                    for s in fam.get("samples", []):
                        value = s.get("value", s.get("count"))
                        fleet["metrics"].setdefault(name, []).append(
                            {"labels": s.get("labels") or {},
                             "value": value, "source": fn})
                        if name == names.GOODPUT_RATIO:
                            entry["goodput_ratio"] = value
                        elif name in (names.STREAM_BUFFER_DEPTH,
                                      names.SERVE_QUEUE_DEPTH):
                            entry["queue_depth"] = max(
                                entry["queue_depth"] or 0.0, value)
                        elif name == names.ALERT_ACTIVE and value:
                            rule = (s.get("labels") or {}).get("rule")
                            entry["alerts"].append({"rule": rule})
                            fleet["alerts"].append(
                                {"rule": rule, "host": host})
        fleet["n_hosts"] = len(fleet["hosts"])
        fleet["scrape_s"] = self.last_scrape_s
        return fleet


def fleet_snapshot() -> dict:
    """One live fleet snapshot from the ambient config (peers when
    ``BIGDL_OBS_PEERS`` is set, shard tailing otherwise)."""
    return FleetAggregator.from_config().snapshot()


def main(argv=None) -> int:
    import argparse

    ap = argparse.ArgumentParser(
        prog="python -m bigdl_tpu.obs.aggregate",
        description="Merge per-host trace shards into one Perfetto "
                    "timeline with barrier clock alignment.")
    ap.add_argument("trace_dir", help="directory holding *.events.jsonl "
                                      "shards (BIGDL_TRACE_DIR)")
    ap.add_argument("-o", "--out", default=None,
                    help="merged Chrome trace path "
                         "(default: TRACE_DIR/merged.trace.json)")
    ap.add_argument("--barrier", default=BARRIER_EVENT,
                    help=f"alignment event name (default {BARRIER_EVENT})")
    args = ap.parse_args(argv)
    out = args.out or os.path.join(args.trace_dir, "merged.trace.json")
    try:
        summary = merge_trace_dir(args.trace_dir, out, barrier=args.barrier)
    except ValueError as e:
        print(json.dumps({"error": str(e)}))
        return 1
    print(json.dumps(summary))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
