"""Live telemetry plane — per-host /metrics, /healthz, /trace endpoints.

Everything the obs stack built in PRs 2–6 lands as JSONL shards read
*after* the run; a production job must be watchable *while it runs*
(the TensorFlow system paper's stance: supervision decisions are driven
by continuously exported runtime signals, not offline log analysis).
This module is that live surface: one stdlib HTTP server per host on a
daemon thread, enabled via ``BIGDL_OBS_PORT`` and serving

* ``GET /metrics`` — Prometheus text exposition of the live process
  registry (plus any extra registries the optimizers register, e.g.
  the driver-phase timers), straight from the same one-lock
  ``snapshot_state()`` reads the file snapshots use — a scrape racing
  a training step can never see a torn histogram;
* ``GET /healthz`` — JSON liveness: the last resolved step + its age
  (the stamp the supervisor's hang watchdog keys on), live goodput
  ratio, active alerts (obs/alerts.py), and the heartbeat peer census;
* ``GET /trace?last=K`` — the newest K records of the PR 3
  flight-recorder ring (``[]`` when tracing is off).

Lifecycle contract (the PR 4 coordinator-port bug class, closed for
good): the serving thread and every per-request thread are daemons, the
server is torn down by atexit / ``Engine.reset`` / ``obs.reset``, and
``BIGDL_OBS_PORT=0`` binds an ephemeral port (the actually-bound port
is exposed as ``server.port`` and, when ``BIGDL_OBS_PORT_FILE`` is set,
written there atomically — how a supervisor finds an ephemeral child
endpoint).  Unset, this module holds no thread and no socket: the
disabled path is one ``None`` check.
"""

from __future__ import annotations

import atexit
import http.server
import json
import logging
import os
import threading
import time
import urllib.parse
import weakref
from typing import List, Optional
from bigdl_tpu.obs import names

log = logging.getLogger("bigdl_tpu.obs")

_lock = threading.Lock()
_server: Optional["ObsServer"] = None
_server_key = None
_atexit_registered = False
# extra registries (weakrefs) concatenated into /metrics — the
# optimizers register their private phase-timer registries here
_extras: List = []

# the step-advance stamp: (step, wall_time) written by both optimizers'
# resolve path — ONE tuple rebind (atomic under the GIL), no lock, no
# device read.  /healthz derives step age from it; the supervisor's
# hang watchdog classifies a stale stamp as a hung child.
_step_stamp = (None, None)


def note_step(step: int):
    """Stamp one resolved training step (both optimizers call this per
    step; the elastic retry path re-stamps the restored step so a
    rewound counter never looks like a stall)."""
    global _step_stamp
    _step_stamp = (int(step), time.time())


def last_step():
    """``(step, wall_time)`` of the newest stamp (``(None, None)``
    before the first resolved step)."""
    return _step_stamp


def clear_step():
    """Test hook: drop the stamp."""
    global _step_stamp
    _step_stamp = (None, None)


def register_registry(registry):
    """Expose an extra ``/metrics`` provider (held by weakref — a dead
    optimizer never pins its registry here).  Anything duck-typed to
    ``to_prometheus() -> str`` registers: a :class:`MetricsRegistry`,
    or a :class:`~bigdl_tpu.obs.rollup.RollupAggregator` — registering
    a rollup turns this host's endpoint into an aggregation tier (an
    upstream scrape transparently drives the downstream shard
    scrape)."""
    with _lock:
        _extras[:] = [r for r in _extras if r() is not None]
        if not any(r() is registry for r in _extras):
            _extras.append(weakref.ref(registry))


def _extra_registries():
    with _lock:
        return [r() for r in _extras if r() is not None]


# ----------------------------------------------------------- payloads
def metrics_text() -> str:
    """The full Prometheus exposition ``/metrics`` serves (process
    registry + registered extras).  One failing extra provider — a
    registered rollup whose downstream shard scrape blows up — costs
    its own section only, never the process registry's exposition."""
    from bigdl_tpu import obs

    parts = [obs.get_registry().to_prometheus()]
    for r in _extra_registries():
        try:
            parts.append(r.to_prometheus())
        except Exception:  # noqa: BLE001 — isolate provider failures
            log.exception("obs.server: extra /metrics provider %r "
                          "failed; serving without it", r)
    return "".join(parts)


def trace_tail(last: int = 64) -> list:
    """The newest ``last`` flight-recorder records (``[]`` when tracing
    is off)."""
    from bigdl_tpu import obs

    recent = obs.get_tracer().recent()
    return recent[-max(1, int(last)):] if recent else []


def _heartbeat_census() -> Optional[dict]:
    """Per-peer heartbeat ages out of the ``bigdl_heartbeat_age_seconds``
    gauges the monitor publishes (None when no heartbeat monitor ever
    ran in this process)."""
    from bigdl_tpu import obs

    for fam in obs.get_registry().families():
        if fam.name == names.HEARTBEAT_AGE_SECONDS:
            census = {}
            for key, child in fam.child_items():
                labels = dict(zip(fam.labelnames, key))
                census[labels.get("host", "?")] = round(child.value, 3)
            return census or None
    return None


def _bundle_writes() -> int:
    """Total debug bundles this process has written (summed across
    triggers from the live counter; 0 before the first)."""
    from bigdl_tpu import obs

    total = 0.0
    for fam in obs.get_registry().families():
        if fam.name == names.BUNDLE_WRITES_TOTAL:
            for _key, child in fam.child_items():
                total += child.value
    return int(total)


def health_payload() -> dict:
    """The ``/healthz`` JSON body (also directly callable — the unit
    tests and an in-process supervisor skip the HTTP hop)."""
    from bigdl_tpu import obs
    from bigdl_tpu.config import config

    now = time.time()
    step, stamped = _step_stamp
    ledger = obs.get_ledger()
    ratio = ledger.live_ratio() if ledger.enabled else None
    from bigdl_tpu.obs import alerts

    active_alerts = alerts.get_engine().active()
    from bigdl_tpu.obs import prof

    prof_obj = prof.current()
    step_age = None if stamped is None else round(now - stamped, 3)
    status = "idle" if step is None else "ok"
    if step_age is not None and config.hang_timeout > 0 \
            and step_age > config.hang_timeout:
        status = "stalled"
    srv = _server
    return {
        "status": status,
        "host": int(config.process_id),
        "pid": os.getpid(),
        "attempt": int(config.elastic_attempt),
        "time": now,
        "port": srv.port if srv is not None else None,
        "uptime_s": (round(now - srv.started, 3)
                     if srv is not None else None),
        "step": step,
        "step_age_s": step_age,
        "goodput_ratio": (None if ratio is None
                          else round(min(1.0, ratio), 6)),
        "alerts": active_alerts,
        "heartbeat": _heartbeat_census(),
        # continuous profiling plane: overhead ratio (None = profiler
        # off) + bundles written — what report --watch surfaces so a
        # misconfigured high-rate profiler is visible at fleet level
        "prof_overhead": (round(prof_obj.overhead_ratio(), 6)
                          if prof_obj.enabled else None),
        "bundles": _bundle_writes(),
    }


# ------------------------------------------------------------- server
class _Handler(http.server.BaseHTTPRequestHandler):
    server_version = "bigdl-obs/1"
    protocol_version = "HTTP/1.1"

    def log_message(self, fmt, *args):  # noqa: A003 — stdlib signature
        log.debug("obs.server: " + fmt, *args)

    def _send(self, code: int, body: bytes, content_type: str):
        self.send_response(code)
        self.send_header("Content-Type", content_type)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _send_json(self, obj, code: int = 200):
        self._send(code, json.dumps(obj, default=str).encode("utf-8"),
                   "application/json")

    def do_GET(self):  # noqa: N802 — stdlib spelling
        try:
            url = urllib.parse.urlsplit(self.path)
            if url.path == "/metrics":
                self._send(200, metrics_text().encode("utf-8"),
                           "text/plain; version=0.0.4; charset=utf-8")
            elif url.path == "/healthz":
                self._send_json(health_payload())
            elif url.path == "/trace":
                q = urllib.parse.parse_qs(url.query)
                key = q.get("request", [None])[0]
                if key is not None:
                    # one kept request trace by trace id / request id,
                    # straight out of the tail sampler's bounded ring
                    from bigdl_tpu.obs import reqtrace

                    entry = reqtrace.get_collector().find(key)
                    if entry is None:
                        self._send_json(
                            {"error": f"no kept trace for {key!r} "
                                      "(dropped by the tail sampler, "
                                      "evicted from the ring, or never "
                                      "seen)"}, 404)
                    else:
                        self._send_json(entry)
                else:
                    last = int(q.get("last", ["64"])[0])
                    self._send_json(trace_tail(last))
            elif url.path == "/profilez":
                # the continuous profiler's current state: folded
                # collapsed stacks (?format=collapsed for the raw
                # flamegraph text) or the JSON snapshot
                from bigdl_tpu.obs import prof

                q = urllib.parse.parse_qs(url.query)
                if q.get("format", [None])[0] == "collapsed":
                    self._send(200,
                               prof.current().render_collapsed()
                               .encode("utf-8"),
                               "text/plain; charset=utf-8")
                else:
                    self._send_json(prof.current().snapshot())
            elif url.path == "/debugz":
                # on-demand black-box capture: build one bundle NOW
                # and report it + the full inventory.  With no
                # BIGDL_BUNDLE_DIR the build fails cleanly and the
                # (empty) inventory still renders.
                from bigdl_tpu.obs import bundle

                body = {"bundle": None, "error": None}
                try:
                    body["bundle"] = bundle.build_bundle(
                        reason="GET /debugz", trigger="http")
                except Exception as e:  # noqa: BLE001 — report, don't 500
                    body["error"] = f"{type(e).__name__}: {e}"
                body["inventory"] = bundle.inventory()
                self._send_json(body,
                                200 if body["error"] is None else 503)
            elif url.path == "/":
                self._send_json(
                    {"endpoints": ["/metrics", "/healthz",
                                   "/trace?last=K",
                                   "/trace?request=ID",
                                   "/profilez", "/debugz"]})
            else:
                self._send_json({"error": f"no route {url.path}"}, 404)
        except (BrokenPipeError, ConnectionResetError):
            pass  # scraper went away mid-response
        except Exception as e:  # noqa: BLE001 — a scrape must not die ugly
            log.exception("obs.server: %s failed", self.path)
            try:
                self._send_json({"error": f"{type(e).__name__}: {e}"},
                                500)
            except OSError:
                pass


class ObsServer:
    """One per-host endpoint: a ``ThreadingHTTPServer`` with daemon
    request threads, served from a daemon thread."""

    def __init__(self, port: int, host: str = "0.0.0.0",
                 port_file: Optional[str] = None):
        self.httpd = http.server.ThreadingHTTPServer((host, int(port)),
                                                     _Handler)
        self.httpd.daemon_threads = True
        self.port = int(self.httpd.server_address[1])
        self.port_file = port_file
        self.started = time.time()
        if port_file:
            # atomic replace: a watching supervisor never reads a torn
            # port number
            tmp = port_file + ".tmp"
            with open(tmp, "w", encoding="utf-8") as fh:
                fh.write(str(self.port))
            os.replace(tmp, port_file)
        self._thread = threading.Thread(
            target=self.httpd.serve_forever,
            kwargs={"poll_interval": 0.25},
            name="bigdl-obs-server", daemon=True)
        self._thread.start()
        log.info("obs.server: live telemetry on port %d "
                 "(/metrics /healthz /trace)", self.port)

    def url(self, path: str = "/healthz") -> str:
        return f"http://127.0.0.1:{self.port}{path}"

    def close(self):
        """Stop serving and release the socket (idempotent)."""
        try:
            self.httpd.shutdown()
            self.httpd.server_close()
        except Exception:  # noqa: BLE001 — interpreter teardown
            pass
        if self._thread.is_alive():
            self._thread.join(timeout=2.0)


# ---------------------------------------------------------- singleton
def ensure_server() -> Optional[ObsServer]:
    """The process endpoint — built when ``BIGDL_OBS_PORT`` is set,
    ``None`` otherwise (no thread, no socket: the disabled path is this
    one config read).  Rebuilt when the port config changes; a bind
    failure logs and disables rather than killing training."""
    global _server, _server_key, _atexit_registered
    from bigdl_tpu.config import refresh_from_env

    cfg = refresh_from_env().obs
    key = (cfg.obs_port, cfg.obs_port_file)
    with _lock:
        if key == _server_key:
            return _server
        if _server is not None:
            _server.close()
            _server = None
        _server_key = key
        if cfg.obs_port is not None:
            try:
                _server = ObsServer(cfg.obs_port,
                                    port_file=cfg.obs_port_file)
            except OSError as e:
                log.warning("obs.server: cannot bind port %s (%s) — "
                            "live telemetry disabled for this process",
                            cfg.obs_port, e)
                _server = None
            if _server is not None and not _atexit_registered:
                atexit.register(stop_server)
                _atexit_registered = True
        return _server


def get_server() -> Optional[ObsServer]:
    """The running server, if any (never builds one)."""
    return _server


def stop_server():
    """Tear the endpoint down (atexit / Engine.reset / obs.reset
    hook); the next :func:`ensure_server` rebuilds from live config."""
    global _server, _server_key
    with _lock:
        if _server is not None:
            _server.close()
            _server = None
        _server_key = None
