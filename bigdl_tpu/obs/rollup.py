"""Hierarchical fleet rollup — a tier that scrapes N hosts and
re-exposes ONE policy-merged ``/metrics`` exposition.

The flat scrape plane (``obs/aggregate.py``) pulls every host's full
exposition into one process; at 1000 hosts that is 1000 sockets, 1000
parses and an unbounded series count in a single aggregator.  This
module is the tiering layer on top: a :class:`RollupAggregator` owns a
*shard* of hosts, folds their parsed expositions into one merged sample
set under each family's declared fleet aggregation policy
(``obs/names.py``), and re-exposes the merge as a normal Prometheus
text body — so a *root* aggregator scrapes leaf aggregators exactly the
way a leaf scrapes hosts, and a 1000-host fleet costs each node ~√N
fan-in.

Correctness contract (pinned by ``sim/invariants.py``):

* ``sum`` families (counters, histogram ``_bucket``/``_sum``/
  ``_count`` samples) merge additively — cumulative bucket counts are
  integers and sum exactly, so a fleet quantile derived from the
  two-tier merge is **bit-identical** to the flat single-tier merge
  (the float ``_sum`` sample alone may differ in its last ulp, since
  float addition is not associative across tiers — quantiles never
  read it);
* ``max``/``min`` fold to the worst host and compose associatively
  across tiers; ``last`` keeps the newest value in scrape order;
* label cardinality is bounded per family by top-K-by-value — dropped
  series fold into an ``other`` bucket (policy-merged, so an ``other``
  histogram series is still exact over its members) and are counted in
  ``bigdl_rollup_series_dropped_total{family}``;
* exemplars ride through the merge newest-timestamp-wins;
* stale hosts (skewed clock / failed scrape — see
  ``FleetAggregator``) are excluded from the merge and accounted in
  ``bigdl_fleet_stale_hosts``, never silently folded in.
"""

from __future__ import annotations

import json
import logging
import time
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from bigdl_tpu.obs import names
from bigdl_tpu.obs.metrics import (MetricsRegistry, _base_family,
                                   render_exposition)

log = logging.getLogger("bigdl_tpu.obs")

#: label value dropped series fold into under the top-K bound
OTHER = "other"


def _series_key(labels: dict) -> Tuple[Tuple[str, str], ...]:
    return tuple(sorted(labels.items()))


def _fold(policy: str, old: float, new: float) -> float:
    if policy == "sum":
        return old + new
    if policy == "max":
        return max(old, new)
    if policy == "min":
        return min(old, new)
    return new  # "last": newest in scrape order wins


def merge_parsed(parsed_list: Sequence[dict]) -> dict:
    """Fold a list of :func:`~bigdl_tpu.obs.metrics.parse_prometheus`
    documents (one per live host, scrape order) into one merged
    document under each family's declared fleet policy.  Undeclared
    sample names merge with ``last`` semantics rather than inventing an
    additive meaning for a foreign gauge."""
    families: Dict[str, dict] = {}
    merged: Dict[tuple, dict] = {}
    order: List[tuple] = []
    for parsed in parsed_list:
        if not parsed:
            continue
        for fname, meta in (parsed.get("families") or {}).items():
            cur = families.setdefault(fname, {})
            for k, v in meta.items():
                cur.setdefault(k, v)
        for s in parsed.get("samples") or []:
            name = s["name"]
            labels = dict(s.get("labels") or {})
            key = (name, _series_key(labels))
            policy = names.fleet_policy(name) or "last"
            cur = merged.get(key)
            if cur is None:
                cur = {"name": name, "labels": labels,
                       "value": float(s["value"])}
                merged[key] = cur
                order.append(key)
            else:
                cur["value"] = _fold(policy, cur["value"],
                                     float(s["value"]))
            ex = s.get("exemplar")
            if ex is not None:
                old = cur.get("exemplar")
                if old is None or float(ex.get("ts") or 0.0) >= \
                        float(old.get("ts") or 0.0):
                    cur["exemplar"] = ex
    return {"families": families,
            "samples": [merged[k] for k in order]}


def bound_cardinality(parsed: dict, top_k: Optional[int]
                      ) -> Tuple[dict, Dict[str, int]]:
    """Cap each family at ``top_k`` label sets, keeping the top-K
    by value (histograms rank by their ``_count``) and folding the
    remainder into one ``other`` series per family under the family
    policy.  Returns ``(bounded_doc, {family: n_dropped})``; a
    falsy ``top_k`` is a no-op (the exactness probes compare
    unbounded merges)."""
    if not top_k or top_k <= 0:
        return parsed, {}
    families = parsed.get("families") or {}
    # logical series: histogram _bucket/_sum/_count lines group under
    # their base family with the `le` label ignored, so keep/fold
    # decisions stay consistent across a histogram's derived samples
    groups: Dict[str, Dict[tuple, List[dict]]] = {}
    for s in parsed.get("samples") or []:
        base = _base_family(s["name"], families)
        skey = _series_key({k: v for k, v in
                            (s.get("labels") or {}).items() if k != "le"})
        groups.setdefault(base, {}).setdefault(skey, []).append(s)

    def _rank(entry) -> float:
        _, ss = entry
        for s in ss:
            if s["name"].endswith("_count"):
                return abs(float(s["value"]))
        return max(abs(float(s["value"])) for s in ss)

    out: List[dict] = []
    dropped: Dict[str, int] = {}
    for base, by_series in groups.items():
        entries = list(by_series.items())
        if len(entries) <= top_k or all(not k for k, _ in entries):
            for _, ss in entries:
                out.extend(ss)
            continue
        entries.sort(key=_rank, reverse=True)
        keep, fold = entries[:top_k], entries[top_k:]
        for _, ss in keep:
            out.extend(ss)
        dropped[base] = len(fold)
        folded: Dict[tuple, dict] = {}
        folded_order: List[tuple] = []
        for _, ss in fold:
            for s in ss:
                labels = {k: (v if k == "le" else OTHER)
                          for k, v in (s.get("labels") or {}).items()}
                fkey = (s["name"], _series_key(labels))
                policy = names.fleet_policy(s["name"]) or "last"
                cur = folded.get(fkey)
                if cur is None:
                    folded[fkey] = {"name": s["name"], "labels": labels,
                                    "value": float(s["value"])}
                    folded_order.append(fkey)
                else:
                    cur["value"] = _fold(policy, cur["value"],
                                         float(s["value"]))
        out.extend(folded[k] for k in folded_order)
    return {"families": families, "samples": out}, dropped


def fleet_quantile(parsed: dict, family: str, q: float,
                   **match_labels) -> Optional[float]:
    """Quantile upper bound from a merged document's cumulative
    ``<family>_bucket`` samples (the same first-bucket-past-target rule
    report.py uses) — how a fleet p99 is derived from either a flat or
    a hierarchical merge for the exactness probe."""
    buckets: Dict[float, float] = {}
    bucket_name = family + "_bucket"
    for s in parsed.get("samples") or []:
        if s["name"] != bucket_name:
            continue
        labels = s.get("labels") or {}
        if any(labels.get(k) != str(v) for k, v in match_labels.items()):
            continue
        try:
            le = float(labels.get("le", "nan"))
        except ValueError:
            le = float("inf")  # "+Inf"
        buckets[le] = buckets.get(le, 0.0) + float(s["value"])
    total = buckets.get(float("inf"), 0.0)
    if total <= 0:
        return None
    target = q * total
    for le in sorted(b for b in buckets if b != float("inf")):
        if buckets[le] >= target:
            return le
    return float("inf")


def shard_addrs(addrs: Sequence[str], shard_size: int) -> List[List[str]]:
    """Contiguous shards (order preserved — ``last`` policies then
    compose identically tiered or flat)."""
    shard_size = max(1, int(shard_size))
    addrs = list(addrs)
    return [addrs[i:i + shard_size]
            for i in range(0, len(addrs), shard_size)]


class RollupAggregator:
    """One rollup node: scrape my shard, merge under policy, re-expose.

    ``to_prometheus()`` makes a rollup registrable on a host's obs
    server exactly like an extra registry
    (:func:`bigdl_tpu.obs.server.register_registry`) — an upstream
    scrape of this node transparently drives a downstream shard scrape
    and gets the merge plus the node's self-metrics (tracked series,
    drop counters, memory) in one body."""

    def __init__(self, peers=None, fetch: Optional[Callable] = None,
                 timeout_s: float = 2.0, max_workers: int = 16,
                 top_k: Optional[int] = None,
                 stale_after_s: Optional[float] = None,
                 clock: Optional[Callable[[], float]] = None,
                 name: str = "rollup0",
                 refresh_on_scrape: bool = True):
        from bigdl_tpu.config import refresh_from_env
        from bigdl_tpu.obs.aggregate import FleetAggregator

        cfg = refresh_from_env().obs
        self.name = name
        self.top_k = cfg.rollup_top_k if top_k is None else int(top_k)
        self.refresh_on_scrape = bool(refresh_on_scrape)
        self._clock = clock or time.time
        self._agg = FleetAggregator(
            peers=peers, fetch=fetch, timeout_s=timeout_s,
            max_workers=max_workers, stale_after_s=stale_after_s,
            clock=clock)
        # self-metrics live in a private registry appended to the
        # exposition — the meta-observability of the pipeline itself
        self.registry = MetricsRegistry()
        self._merged: dict = {"families": {}, "samples": []}
        self.stale: Dict[str, str] = {}
        self.n_live = 0
        self.last_scrape_s: Optional[float] = None

    @property
    def peers(self) -> List[str]:
        return self._agg.peers

    # ------------------------------------------------------------ cycle
    def refresh(self) -> dict:
        """One scrape+merge cycle over my shard: scrape every peer,
        drop stale/failed hosts (accounted, never folded in), merge the
        live remainder under policy, bound cardinality, publish
        self-metrics.  Returns the merged document."""
        scraped = self._agg.scrape_peers(self._agg.peers)
        self.stale = dict(self._agg.last_stale)
        live = [p for p in scraped
                if p.get("ok") and not p.get("stale")]
        self.n_live = len(live)
        self.last_scrape_s = self._agg.last_scrape_s
        merged = merge_parsed([p.get("metrics") for p in live])
        merged, dropped = bound_cardinality(merged, self.top_k)
        self._merged = merged
        tracked = len(merged["samples"])
        self.registry.gauge(
            names.ROLLUP_SERIES_TRACKED,
            names.spec(names.ROLLUP_SERIES_TRACKED).doc).set(tracked)
        drop_fam = self.registry.counter(
            names.ROLLUP_SERIES_DROPPED_TOTAL,
            names.spec(names.ROLLUP_SERIES_DROPPED_TOTAL).doc,
            labels=("family",))
        for family, n in dropped.items():
            drop_fam.labels(family=family).inc(n)
        self.registry.gauge(
            names.ROLLUP_MEMORY_BYTES,
            names.spec(names.ROLLUP_MEMORY_BYTES).doc).set(
            self.memory_bytes())
        self.registry.gauge(
            names.FLEET_STALE_HOSTS,
            names.spec(names.FLEET_STALE_HOSTS).doc).set(len(self.stale))
        return merged

    def memory_bytes(self) -> int:
        """Approximate bytes of merged-series state this node holds
        (the self-scrape bound the sim probe asserts against)."""
        total = 0
        for s in self._merged["samples"]:
            total += 64 + len(s["name"])
            total += sum(len(k) + len(str(v))
                         for k, v in (s.get("labels") or {}).items())
        return total

    @property
    def tracked_series(self) -> int:
        return len(self._merged["samples"])

    # ------------------------------------------------------- exposition
    def to_prometheus(self) -> str:
        """The merged shard exposition plus this node's self-metrics —
        one text body an upstream tier scrapes like any host."""
        if self.refresh_on_scrape:
            self.refresh()
        return render_exposition(self._merged) + \
            self.registry.to_prometheus()

    def health(self) -> dict:
        """A ``/healthz``-shaped payload so an upstream
        ``FleetAggregator`` scrapes a rollup node with the same
        two-fetch contract it uses on hosts."""
        return {"status": "ok", "host": self.name, "role": "rollup",
                "time": self._clock(), "hosts": self.n_live,
                "stale": len(self.stale), "step": None,
                "goodput_ratio": None, "alerts": [], "heartbeat": None}


def tier_fetch(leaves: Sequence[RollupAggregator]) -> Callable[[str], str]:
    """An injectable ``fetch`` routing ``http://<leaf-name>:9100/...``
    to the in-process leaf rollups — how the sim (and the smoke) wires
    a root aggregator over leaf aggregators without sockets."""
    by_name = {leaf.name: leaf for leaf in leaves}

    def fetch(url: str) -> str:
        rest = url.split("//", 1)[-1]
        host, _, path = rest.partition("/")
        leaf = by_name.get(host.rsplit(":", 1)[0])
        if leaf is None:
            raise ConnectionRefusedError(f"no rollup node for {url}")
        if path.startswith("healthz"):
            return json.dumps(leaf.health())
        if path.startswith("metrics"):
            return leaf.to_prometheus()
        raise ValueError(f"no route {url}")

    return fetch


def build_tiers(addrs: Sequence[str], fetch: Callable[[str], str],
                shard_size: Optional[int] = None,
                top_k: Optional[int] = None,
                stale_after_s: Optional[float] = None,
                clock: Optional[Callable[[], float]] = None,
                timeout_s: float = 2.0, max_workers: int = 16
                ) -> Tuple[RollupAggregator, List[RollupAggregator]]:
    """Assemble a two-tier pipeline over ``addrs``: leaf rollups of
    ``shard_size`` hosts each (default ``BIGDL_ROLLUP_SHARD``), one
    root rollup over the leaves.  Returns ``(root, leaves)``; call
    ``root.refresh()`` to drive a full fleet cycle."""
    from bigdl_tpu.config import refresh_from_env

    cfg = refresh_from_env().obs
    if shard_size is None:
        shard_size = cfg.rollup_shard
    leaves = [
        RollupAggregator(peers=shard, fetch=fetch, timeout_s=timeout_s,
                         max_workers=max_workers, top_k=top_k,
                         stale_after_s=stale_after_s, clock=clock,
                         name=f"rollup{i}")
        for i, shard in enumerate(shard_addrs(addrs, shard_size))]
    root = RollupAggregator(
        peers=[f"{leaf.name}:9100" for leaf in leaves],
        fetch=tier_fetch(leaves), timeout_s=timeout_s,
        max_workers=max_workers, top_k=top_k,
        stale_after_s=stale_after_s, clock=clock, name="rollup-root")
    return root, leaves
