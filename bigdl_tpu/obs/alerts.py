"""Declarative alert / SLO engine over the live metrics registry.

The obs stack *measures* everything (goodput, numerics, stragglers,
checkpoint failures); nothing *decides* anything.  This module is the
decision half: a rule pack evaluated on the goodput window tick — the
host-side hook both optimizers already pay for, so alerting adds zero
new device syncs — with a firing/resolved lifecycle:

=============  =========================================================
rule type      fires when
=============  =========================================================
``threshold``  the metric's worst sample ``op`` value (e.g. goodput
               ratio below target, a peer heartbeat age past budget)
``absence``    the metric has no sample at all (a signal that should
               exist, doesn't)
``rate``       the counter moved by more than ``value`` since the last
               evaluation (non-finite spike, straggler flagged,
               checkpoint write failure)
``burn_rate``  the SLO error budget burns faster than ``threshold``×
               sustainable: ``(1 - ratio) / (1 - slo) >= threshold``
=============  =========================================================

Every rule carries ``for`` (consecutive breached evaluations before
firing — one flaky window is not a page), ``resolve_for`` (consecutive
*good* evaluations before resolving, default 1 — raising it keeps a
gauge that blips good for one window inside the SAME episode instead
of splitting it into two pages) and ``severity``.  Each firing opens a
new per-rule **episode**: a monotonically increasing id stamped on the
``firing`` transition and echoed on its matching ``resolved`` — the
fleet simulator's exactly-once-per-episode invariant pairs transitions
on it, and a sink consumer can dedupe on ``(rule, episode)``.  On a
fire/resolve transition the engine emits ``alert.firing`` /
``alert.resolved`` trace events, increments ``bigdl_alerts_total
{rule,severity}`` / ``bigdl_alerts_resolved_total{rule}``, mirrors
``bigdl_alert_active{rule}`` gauges (what ``/healthz`` and the fleet
aggregator read), and appends the transition to the optional
``BIGDL_ALERT_SINK`` (JSONL file, or an http(s):// webhook POST).

Rules come from ``BIGDL_ALERT_RULES`` — an inline JSON list or a path
to one — replacing the default pack below; everything is plain host
arithmetic over the registry, unit-testable with a synthetic clock.
"""

from __future__ import annotations

import itertools
import json
import logging
import operator
import threading
import time
from typing import Callable, List, Optional
from bigdl_tpu.obs import names

log = logging.getLogger("bigdl_tpu.obs")

RULE_TYPES = ("threshold", "absence", "rate", "burn_rate")
OPS = {"<": operator.lt, "<=": operator.le, ">": operator.gt,
       ">=": operator.ge, "==": operator.eq, "!=": operator.ne}

_FIRED_META = (names.ALERTS_TOTAL,
               "Alert firing transitions, by rule and severity")
_RESOLVED_META = (names.ALERTS_RESOLVED_TOTAL,
                  "Alert resolved transitions, by rule")
_ACTIVE_META = (names.ALERT_ACTIVE,
                "1 while the rule is firing, 0 otherwise")


def burn_rate(ratio: Optional[float], slo: float) -> float:
    """SLO error-budget burn multiple.

    With an SLO of ``slo`` (e.g. goodput ratio >= 0.9) the error budget
    is ``1 - slo``; a window observing ``ratio`` burns it at
    ``(1 - ratio) / (1 - slo)`` times the sustainable rate — burn 1.0
    exactly exhausts the budget at the SLO boundary, 2.0 halves the
    time to exhaustion.  ``slo >= 1`` means zero budget: any shortfall
    is infinite burn."""
    if ratio is None:
        return 0.0
    bad = max(0.0, 1.0 - float(ratio))
    budget = 1.0 - float(slo)
    if budget <= 0.0:
        return float("inf") if bad > 0 else 0.0
    return bad / budget


def default_rules(heartbeat_timeout: float = 60.0) -> List[dict]:
    """The default pack: one rule per failure mode the earlier PRs can
    already *measure* but nothing *watched*."""
    return [
        {"name": "goodput_below_target", "type": "threshold",
         "metric": names.GOODPUT_RATIO, "op": "<", "value": 0.5,
         "for": 2, "severity": "warning"},
        {"name": "goodput_slo_burn", "type": "burn_rate",
         "metric": names.GOODPUT_WINDOW_RATIO, "slo": 0.5,
         "threshold": 1.5, "for": 2, "severity": "warning"},
        {"name": "nonfinite_spike", "type": "rate",
         "metric": names.NONFINITE_SKIPS_TOTAL, "op": ">", "value": 0,
         "for": 1, "severity": "critical"},
        {"name": "straggler_flagged", "type": "rate",
         "metric": names.STRAGGLER_STEPS_TOTAL, "op": ">", "value": 0,
         "for": 1, "severity": "warning"},
        {"name": "checkpoint_write_failure", "type": "rate",
         "metric": names.CHECKPOINT_WRITE_FAILURES_TOTAL, "op": ">",
         "value": 0, "for": 1, "severity": "critical"},
        {"name": "stale_peer_heartbeat", "type": "threshold",
         "metric": names.HEARTBEAT_AGE_SECONDS, "op": ">",
         "value": max(1.0, float(heartbeat_timeout)) * 0.5,
         "for": 1, "severity": "warning"},
        # overlapped step (ISSUE 11): the bucketed exchange should hide
        # most of the wire under backward — a sustained exposed-comm
        # share past half the budget means the buckets are too coarse
        # (or comm outruns backward entirely); inert on runs without
        # the overlap gauges (threshold rules never fire on absence)
        {"name": "exposed_comm_high", "type": "threshold",
         "metric": names.OVERLAP_EXPOSED_COMM_FRACTION, "op": ">",
         "value": 0.5, "for": 2, "severity": "warning"},
        # serving tier (ISSUE 12): the LM engine publishes the fraction
        # of recent requests completing within BIGDL_SERVE_SLO_MS as a
        # ratio gauge; burning the 1% error budget at 2x+ sustainable
        # means the p99 SLO is on track to be blown — the serving
        # analogue of goodput_slo_burn.  Inert on non-serving runs
        # (burn_rate rules never fire on an absent metric)
        {"name": "serve_latency_slo_burn", "type": "burn_rate",
         "metric": names.SERVE_LATENCY_SLO_RATIO, "slo": 0.99,
         "threshold": 2.0, "for": 2, "severity": "warning"},
    ]


def load_rules(spec: Optional[str],
               heartbeat_timeout: float = 60.0) -> List[dict]:
    """Resolve ``BIGDL_ALERT_RULES``: inline JSON (starts with ``[``)
    or a file path; None/empty = the default pack.  Every rule is
    validated loudly — a typo'd pack must fail at build, not silently
    never fire."""
    if not spec:
        rules = default_rules(heartbeat_timeout)
    else:
        text = spec if spec.lstrip()[:1] in ("[", "{") else \
            open(spec, encoding="utf-8").read()
        rules = json.loads(text)
    if not isinstance(rules, list):
        raise ValueError(f"alert rules must be a JSON list, got "
                         f"{type(rules).__name__}")
    for r in rules:
        kind = r.get("type", "threshold")
        if kind not in RULE_TYPES:
            raise ValueError(f"rule {r.get('name')!r}: unknown type "
                             f"{kind!r}; one of {RULE_TYPES}")
        if not r.get("name"):
            raise ValueError(f"alert rule missing a name: {r}")
        if not r.get("metric"):
            raise ValueError(f"rule {r['name']!r}: missing metric")
        if kind in ("threshold", "rate"):
            if r.get("op", ">") not in OPS:
                raise ValueError(f"rule {r['name']!r}: op {r.get('op')!r}"
                                 f" not in {sorted(OPS)}")
            if "value" not in r:
                raise ValueError(f"rule {r['name']!r}: missing value")
        if kind == "burn_rate" and "slo" not in r:
            raise ValueError(f"rule {r['name']!r}: burn_rate needs slo")
        r.setdefault("type", kind)
        r.setdefault("for", 1)
        r.setdefault("resolve_for", 1)
        if int(r["resolve_for"]) < 1:
            raise ValueError(f"rule {r['name']!r}: resolve_for must be "
                             f">= 1, got {r['resolve_for']!r}")
        r.setdefault("severity", "warning")
    return rules


# ------------------------------------------------------------- engine
class AlertEngine:
    """Evaluate a rule pack against a registry; track lifecycle."""

    # per-engine identity for the bundle dedupe key: (rule, episode)
    # alone collides across engines (the fleet sim runs one real
    # engine per synthetic host in one process)
    _UIDS = itertools.count()

    def __init__(self, rules: List[dict], registry=None,
                 sink: Optional[str] = None,
                 clock: Callable[[], float] = time.time):
        self.uid = next(AlertEngine._UIDS)
        self.rules = list(rules)
        self._registry = registry
        self.sink = sink
        self._clock = clock
        self._lock = threading.Lock()
        # `episode` is the per-rule firing ordinal: incremented when a
        # firing transition opens, echoed on the matching resolve —
        # the identity the exactly-once-per-episode invariant pairs on.
        # `good` is the consecutive-clean streak gating the resolve
        # (the symmetric half of the `for` firing debounce).
        self._state = {r["name"]: {"breaches": 0, "good": 0,
                                   "firing": False, "since": None,
                                   "value": None, "labels": None,
                                   "episode": 0}
                       for r in self.rules}
        # rate baselines are primed at engine build: counts that exist
        # NOW are history (an engine rebuilt mid-run must not re-page
        # old increments), while a counter that first *appears* later —
        # families register lazily on first increment — is a genuine
        # spike measured from zero, not swallowed as history
        self._prev_rate: dict = {}
        for r in self.rules:
            if r.get("type") == "rate":
                samples = self._samples(r["metric"], r.get("labels"))
                self._prev_rate[r["name"]] = sum(v for v, _ in samples)

    def registry(self):
        if self._registry is not None:
            return self._registry
        from bigdl_tpu import obs

        return obs.get_registry()

    # ------------------------------------------------------ resolution
    def _samples(self, metric: str, want_labels: Optional[dict]):
        """[(value, labels)] for every child of ``metric`` whose labels
        contain ``want_labels`` (histograms contribute their count)."""
        out = []
        for fam in self.registry().families():
            if fam.name != metric:
                continue
            for key, child in fam.child_items():
                labels = dict(zip(fam.labelnames, key))
                if want_labels and any(labels.get(k) != str(v)
                                       for k, v in want_labels.items()):
                    continue
                value = (child.count if fam.kind == "histogram"
                         else child.value)
                out.append((float(value), labels))
        return out

    def _worst(self, metric, want_labels, op_name: str):
        """The sample most likely to breach: max for ``>``-ish ops, min
        for ``<``-ish."""
        samples = self._samples(metric, want_labels)
        if not samples:
            return None, None
        pick = min if op_name in ("<", "<=") else max
        return pick(samples, key=lambda s: s[0])

    # ------------------------------------------------------ evaluation
    def _breach(self, rule: dict):
        """-> (breached, value, labels) for one rule, one evaluation."""
        kind = rule["type"]
        metric = rule["metric"]
        want = rule.get("labels")
        if kind == "absence":
            samples = self._samples(metric, want)
            return (not samples), None, want
        if kind == "burn_rate":
            value, labels = self._worst(metric, want, "<")
            if value is None:
                return False, None, None
            burn = burn_rate(value, rule["slo"])
            return burn >= float(rule.get("threshold", 1.0)), \
                round(burn, 4), labels
        op = OPS[rule.get("op", ">")]
        if kind == "rate":
            samples = self._samples(metric, want)
            if not samples:
                return False, None, None
            total = sum(v for v, _ in samples)
            prev = self._prev_rate.get(rule["name"], 0.0)
            self._prev_rate[rule["name"]] = total
            delta = total - prev
            return op(delta, float(rule["value"])), delta, None
        value, labels = self._worst(metric, want, rule.get("op", ">"))
        if value is None:
            return False, None, None
        return op(value, float(rule["value"])), value, labels

    def evaluate(self, now: Optional[float] = None) -> List[dict]:
        """One evaluation pass; returns the transition records (one per
        rule that fired or resolved this pass)."""
        now = self._clock() if now is None else now
        transitions = []
        with self._lock:
            for rule in self.rules:
                st = self._state[rule["name"]]
                try:
                    breached, value, labels = self._breach(rule)
                except Exception:  # noqa: BLE001 — one bad rule must not
                    log.exception("alert rule %r evaluation failed",
                                  rule["name"])  # kill the pack
                    continue
                st["value"], st["labels"] = value, labels
                if breached:
                    st["breaches"] += 1
                    st["good"] = 0
                    if not st["firing"] and \
                            st["breaches"] >= int(rule.get("for", 1)):
                        st["firing"] = True
                        st["since"] = now
                        st["episode"] += 1
                        transitions.append(self._transition(
                            "firing", rule, st, now))
                else:
                    st["breaches"] = 0
                    st["good"] += 1
                    # resolve only after `resolve_for` consecutive good
                    # evaluations: a gauge that blips good for one
                    # window mid-incident stays inside the SAME episode
                    # instead of paging a second firing for it
                    if st["firing"] and st["good"] >= int(
                            rule.get("resolve_for", 1)):
                        st["firing"] = False
                        transitions.append(self._transition(
                            "resolved", rule, st, now))
                        st["since"] = None
        for t in transitions:
            self._emit(t)
        return transitions

    def _transition(self, state: str, rule: dict, st: dict,
                    now: float) -> dict:
        return {"state": state, "rule": rule["name"],
                "severity": rule["severity"], "type": rule["type"],
                "metric": rule["metric"], "value": st["value"],
                "labels": st["labels"], "ts": now,
                "since": st["since"], "episode": st["episode"]}

    def _emit(self, t: dict):
        from bigdl_tpu import obs

        reg = self.registry()
        if t["state"] == "firing":
            reg.counter(*_FIRED_META,
                        labels=("rule", "severity")).labels(
                rule=t["rule"], severity=t["severity"]).inc()
            reg.gauge(*_ACTIVE_META, labels=("rule",)).labels(
                rule=t["rule"]).set(1.0)
            log.warning("ALERT firing: %s [%s] %s=%r %s", t["rule"],
                        t["severity"], t["metric"], t["value"],
                        t["labels"] or "")
        else:
            reg.counter(*_RESOLVED_META, labels=("rule",)).labels(
                rule=t["rule"]).inc()
            reg.gauge(*_ACTIVE_META, labels=("rule",)).labels(
                rule=t["rule"]).set(0.0)
            log.info("alert resolved: %s (%s=%r)", t["rule"],
                     t["metric"], t["value"])
        obs.get_tracer().event(f"alert.{t['state']}", rule=t["rule"],
                               severity=t["severity"],
                               metric=t["metric"], value=t["value"],
                               labels=t["labels"],
                               episode=t["episode"])
        if t["state"] == "firing":
            # black-box capture at the moment of trouble: one debug
            # bundle per (engine, rule, episode), rate-limited per
            # rule, only when BIGDL_BUNDLE_DIR is set — and best
            # effort: a full disk must not break the page itself
            try:
                from bigdl_tpu.obs import bundle

                bundle.on_alert_firing(t, engine_uid=self.uid)
            except Exception:  # noqa: BLE001 — bundling never blocks alerts
                log.exception("alert bundle capture failed for %s",
                              t["rule"])
        if self.sink:
            _sink_write(self.sink, t)

    def active(self) -> List[dict]:
        """The currently-firing alerts (what ``/healthz`` reports)."""
        with self._lock:
            out = []
            for rule in self.rules:
                st = self._state[rule["name"]]
                if st["firing"]:
                    out.append({"rule": rule["name"],
                                "severity": rule["severity"],
                                "metric": rule["metric"],
                                "value": st["value"],
                                "labels": st["labels"],
                                "since": st["since"],
                                "episode": st["episode"]})
            return out


def _count_sink_failure():
    from bigdl_tpu import obs

    obs.get_registry().counter(
        names.ALERT_SINK_FAILURES_TOTAL,
        "Alert transitions the sink failed to accept (after the "
        "retry)").inc()


def _sink_write(sink: str, record: dict, timeout: Optional[float] = None):
    """Deliver one transition to the sink — JSONL append, or webhook
    POST for http(s):// targets.  Best-effort but BOUNDED: the POST
    carries a connect/read timeout (``BIGDL_ALERT_SINK_TIMEOUT``) and
    one retry after the shared jittered backoff
    (:func:`~bigdl_tpu.resilience.retry.backoff_delay` — the immediate
    hot re-POST this used to do just hit the same wedged receiver
    inside the same failure window), so a dead receiver costs the
    goodput window tick at most two timeouts + a sub-second backoff —
    and the loss is visible in ``bigdl_alert_sink_failures_total``,
    never only a log line."""
    payload = json.dumps(record, default=str)
    if sink.startswith(("http://", "https://")):
        if timeout is None:
            from bigdl_tpu.config import config

            timeout = config.obs.alert_sink_timeout
        import urllib.request

        from bigdl_tpu.resilience.retry import backoff_delay

        last = None
        for attempt in range(1, 3):  # one retry, jittered backoff
            req = urllib.request.Request(
                sink, data=payload.encode("utf-8"),
                headers={"Content-Type": "application/json"})
            try:
                urllib.request.urlopen(req, timeout=timeout).close()
                return
            except Exception as e:  # noqa: BLE001 — counted below
                last = e
                if attempt < 2:
                    time.sleep(backoff_delay(attempt, base=0.1, cap=0.5))
        _count_sink_failure()
        log.warning("alert sink %s failed twice (timeout %.1fs): %s",
                    sink, timeout, last)
        return
    try:
        with open(sink, "a", encoding="utf-8") as fh:
            fh.write(payload + "\n")
    except Exception as e:  # noqa: BLE001
        _count_sink_failure()
        log.warning("alert sink %s failed: %s", sink, e)


# ---------------------------------------------------------- singleton
_lock = threading.Lock()
_engine: Optional[AlertEngine] = None
_engine_key = None


def get_engine() -> AlertEngine:
    """The process alert engine, built from the live config and rebuilt
    when the rule pack / sink changes."""
    global _engine, _engine_key
    from bigdl_tpu.config import refresh_from_env

    cfg = refresh_from_env()
    key = (cfg.obs.alert_rules, cfg.obs.alert_sink,
           cfg.heartbeat_timeout)
    with _lock:
        if _engine is None or key != _engine_key:
            _engine_key = key
            _engine = AlertEngine(
                load_rules(cfg.obs.alert_rules, cfg.heartbeat_timeout),
                sink=cfg.obs.alert_sink)
        return _engine


def maybe_evaluate() -> List[dict]:
    """Best-effort evaluation tick — rides the goodput window tick
    inside the training loop, so it must never raise."""
    try:
        return get_engine().evaluate()
    except Exception:  # noqa: BLE001 — alerting must not break training
        log.exception("alert evaluation failed")
        return []


def reset_engine():
    """Test hook: drop the singleton; the next :func:`get_engine`
    rebuilds from the live config."""
    global _engine, _engine_key
    with _lock:
        _engine = None
        _engine_key = None
