"""Continuous sampling profiler — always-on, span-attributed, capped.

The observability stack can *detect* trouble (alerts, request traces,
fleet rollups) but could not answer "what was the process actually
doing when the alert fired" — by the time a human attaches a profiler,
the p99 spike is gone.  This module is the standard closing move: a
daemon thread walks ``sys._current_frames()`` at ``BIGDL_PROF_HZ`` and
folds every thread's stack into a bounded collapsed-stack table, so a
profile is *always* available — to ``GET /profilez``, to the debug
bundles (obs/bundle.py), and to the report's "profiles" section.

Two properties make it safe to leave on in production:

* **Span attribution.**  Each sampled stack is prefixed with the
  innermost live span name of its thread (the tracer's per-thread
  phase stack, :func:`bigdl_tpu.obs.trace.current_phase`), so output
  reads ``serve.decode_step;engine.py:_step;...  61`` — "the decode
  step spends 61% here" — not anonymous frames.  Threads outside any
  span fold under ``(no span)``.
* **Hard overhead cap.**  The cumulative sampling-work ratio
  (seconds spent walking/folding / wall seconds) is published as
  ``bigdl_prof_overhead_ratio`` and checked *before* every sample:
  over ``BIGDL_PROF_BUDGET`` the sample is skipped (and counted in
  ``bigdl_prof_skipped_total``) until the ratio recovers.  A
  misconfigured 10 kHz profiler degrades to the budget, never past it.

Off by default: ``BIGDL_PROF_HZ`` unset/<=0 yields the shared
:data:`NULL_PROFILER` — no thread, no clock reads, the disabled path
is one config read (the same null-object contract as NULL_TRACER).
"""

from __future__ import annotations

import json
import logging
import os
import sys
import threading
import time
from typing import Dict, Optional

from bigdl_tpu.obs import names, trace

log = logging.getLogger("bigdl_tpu.obs")

#: bounded fold table: distinct collapsed stacks kept before new ones
#: fold into the per-phase ``(other)`` bucket
MAX_STACKS = 2048
#: frames walked per sampled stack (deeper stacks truncate at the root)
MAX_DEPTH = 64
#: label attributed to a sampled thread with no live span
NO_SPAN = "(no span)"
#: overflow stack suffix once the fold table is full
OTHER = "(other)"


def _frame_label(frame) -> str:
    """``file.py:func`` — base name only; full paths explode the fold
    table across venvs without adding attribution value."""
    code = frame.f_code
    return f"{os.path.basename(code.co_filename)}:{code.co_name}"


class NullProfiler:
    """No-op profiler with the full :class:`SamplingProfiler` surface —
    the pinned zero-overhead off path (no thread, no state)."""

    __slots__ = ()
    enabled = False
    hz = 0.0

    def snapshot(self) -> dict:
        return {"enabled": False, "hz": 0.0, "samples": 0,
                "skipped": 0, "overhead_ratio": 0.0, "stacks": 0,
                "phases": {}, "collapsed": []}

    def render_collapsed(self) -> str:
        return ""

    def close(self):
        pass


NULL_PROFILER = NullProfiler()


class SamplingProfiler:
    """One daemon thread sampling every live thread's stack at ``hz``.

    All mutation happens on the sampler thread; readers
    (:meth:`snapshot`, the /profilez handler, bundle builds) copy
    under the lock.  The sampler never touches the thread it runs on.
    """

    enabled = True

    def __init__(self, hz: float, budget: float = 0.01):
        self.hz = float(hz)
        self.budget = float(budget)
        self._lock = threading.Lock()
        self._counts: Dict[str, int] = {}
        # (phase, leaf frame) -> samples: the "top self-time frames per
        # phase" table the report renders
        self._self: Dict[tuple, int] = {}
        self._samples = 0
        self._skipped = 0
        self._work_s = 0.0
        self._started = time.perf_counter()
        self._stop = threading.Event()
        from bigdl_tpu import obs

        reg = obs.get_registry()
        self._samples_c = reg.counter(
            names.PROF_SAMPLES_TOTAL,
            "Stack samples folded into the collapsed-stack table")
        self._skipped_c = reg.counter(
            names.PROF_SKIPPED_TOTAL,
            "Samples skipped by the overhead budget")
        self._overhead_g = reg.gauge(
            names.PROF_OVERHEAD_RATIO,
            "Profiler self-overhead ratio (work seconds / wall seconds)")
        self._stacks_g = reg.gauge(
            names.PROF_STACKS,
            "Distinct collapsed stacks in the bounded fold table")
        self._thread = threading.Thread(
            target=self._run, name="bigdl-prof", daemon=True)
        self._thread.start()
        log.info("obs.prof: continuous profiler on at %.1f Hz "
                 "(budget %.3f)", self.hz, self.budget)

    # -------------------------------------------------------------- core
    def overhead_ratio(self) -> float:
        wall = time.perf_counter() - self._started
        return self._work_s / max(wall, 1e-9)

    def _run(self):
        period = 1.0 / max(self.hz, 1e-6)
        me = threading.get_ident()
        while not self._stop.wait(period):
            ratio = self.overhead_ratio()
            self._overhead_g.set(ratio)
            if ratio > self.budget:
                # the hard cap: over budget, the profiler degrades to
                # bookkeeping-only until the ratio recovers
                self._skipped += 1
                self._skipped_c.inc()
                continue
            t0 = time.perf_counter()
            try:
                self._sample(me)
            except Exception:  # noqa: BLE001 — profiling never kills a host
                log.exception("obs.prof: sample failed; continuing")
            self._work_s += time.perf_counter() - t0

    def _sample(self, me: int):
        frames = sys._current_frames()
        with self._lock:
            for ident, frame in frames.items():
                if ident == me:
                    continue
                phase = trace.current_phase(ident) or NO_SPAN
                parts = []
                leaf = _frame_label(frame)
                f = frame
                while f is not None and len(parts) < MAX_DEPTH:
                    parts.append(_frame_label(f))
                    f = f.f_back
                # root-first, phase as the fold root
                key = phase + ";" + ";".join(reversed(parts))
                if key not in self._counts \
                        and len(self._counts) >= MAX_STACKS:
                    key = phase + ";" + OTHER
                self._counts[key] = self._counts.get(key, 0) + 1
                sk = (phase, leaf)
                self._self[sk] = self._self.get(sk, 0) + 1
            self._samples += 1
        self._samples_c.inc()
        self._stacks_g.set(len(self._counts))

    # ------------------------------------------------------------ readers
    def snapshot(self, top: int = 8) -> dict:
        """JSON-able profile state: totals, overhead, and the top
        self-time frames per phase (what the report + bundles carry)."""
        with self._lock:
            counts = dict(self._counts)
            self_t = dict(self._self)
            samples, skipped = self._samples, self._skipped
        phases: Dict[str, dict] = {}
        for (phase, leaf), n in self_t.items():
            p = phases.setdefault(phase, {"samples": 0, "frames": {}})
            p["samples"] += n
            p["frames"][leaf] = p["frames"].get(leaf, 0) + n
        for p in phases.values():
            p["frames"] = sorted(p["frames"].items(),
                                 key=lambda kv: -kv[1])[:max(1, top)]
        collapsed = sorted(counts.items(), key=lambda kv: -kv[1])
        return {
            "enabled": True,
            "hz": self.hz,
            "budget": self.budget,
            "samples": samples,
            "skipped": skipped,
            "overhead_ratio": round(self.overhead_ratio(), 6),
            "stacks": len(counts),
            "phases": phases,
            "collapsed": [f"{k} {v}" for k, v in collapsed],
        }

    def render_collapsed(self) -> str:
        """The folded-stack text format every flamegraph tool eats:
        one ``stack count`` line per distinct collapsed stack."""
        with self._lock:
            counts = sorted(self._counts.items(), key=lambda kv: -kv[1])
        return "".join(f"{k} {v}\n" for k, v in counts)

    def close(self):
        """Stop the sampler thread (idempotent)."""
        self._stop.set()
        if self._thread.is_alive():
            self._thread.join(timeout=2.0)


# ------------------------------------------------------------- singleton
_lock = threading.Lock()
_profiler = NULL_PROFILER
_profiler_key = None


def get_profiler():
    """The process profiler — a :class:`SamplingProfiler` when
    ``BIGDL_PROF_HZ`` > 0, the shared :data:`NULL_PROFILER` otherwise
    (no thread ever starts on the off path).  Rebuilt when the
    hz/budget config changes."""
    global _profiler, _profiler_key
    from bigdl_tpu.config import refresh_from_env

    cfg = refresh_from_env().obs
    key = (cfg.prof_hz, cfg.prof_budget)
    with _lock:
        if key == _profiler_key:
            return _profiler
        if _profiler is not NULL_PROFILER:
            _profiler.close()
        _profiler_key = key
        _profiler = (SamplingProfiler(cfg.prof_hz, cfg.prof_budget)
                     if cfg.prof_hz > 0 else NULL_PROFILER)
        return _profiler


def current():
    """The live profiler WITHOUT building one — cheap reads (health
    payloads, report columns) must not start a sampler thread as a
    side effect."""
    return _profiler


def reset_profiler():
    """Test hook: stop the sampler; the next accessor rebuilds."""
    global _profiler, _profiler_key
    with _lock:
        if _profiler is not NULL_PROFILER:
            _profiler.close()
        _profiler = NULL_PROFILER
        _profiler_key = None


def write_profile(out_dir: str, stem: str) -> Optional[str]:
    """One ``<stem>.profile.json`` shard in ``out_dir`` (the obs.flush
    hook — how an offline report gets the run's folded profile); None
    when the profiler is off or has no samples yet."""
    prof = _profiler
    if prof is NULL_PROFILER:
        return None
    snap = prof.snapshot()
    if not snap["samples"]:
        return None
    path = os.path.join(out_dir, stem + ".profile.json")
    tmp = path + ".tmp"
    with open(tmp, "w", encoding="utf-8") as fh:
        json.dump(snap, fh)
    os.replace(tmp, path)
    return path
