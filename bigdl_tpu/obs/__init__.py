"""bigdl_tpu.obs — unified observability layer.

One subsystem, three instruments, threaded through the whole training
stack (optimizers, engine, serializer, resilience, bench):

* :mod:`bigdl_tpu.obs.trace` — contextvar-nested span tracer exporting
  Chrome ``trace_event`` JSON (Perfetto-viewable) + JSONL structured
  events.  ``BIGDL_TRACE_DIR=/dir`` turns it on;
* :mod:`bigdl_tpu.obs.metrics` — labeled Counter/Gauge/Histogram
  registry with Prometheus text exposition and JSONL snapshots
  (``BIGDL_METRICS_DIR=/dir``); ``optim.Metrics`` delegates here;
* :mod:`bigdl_tpu.obs.runtime` — compile-event tracking, step-time
  p50/p95/p99 reservoirs, host RSS + device memory stats;
* :mod:`bigdl_tpu.obs.collectives` — wire-byte accounting for every
  programmed collective, from static shapes only;
* :mod:`bigdl_tpu.obs.aggregate` — offline merge of N per-host trace
  shards into one clock-aligned Perfetto timeline (CLI);
* :mod:`bigdl_tpu.obs.report` — run-report CLI over trace/metrics dirs;
* :mod:`bigdl_tpu.obs.regress` — perf-regression gate against the
  BENCH_r*.json trajectory + flight-recorder bundles;
* :mod:`bigdl_tpu.obs.health` — per-layer grad/param/update-ratio
  telemetry computed inside the jitted train step, non-finite
  localization, and the numerics anomaly detector
  (``BIGDL_HEALTH_EVERY``);
* :mod:`bigdl_tpu.obs.goodput` — wall-clock goodput ledger: productive
  step time vs. badput causes (compile, checkpoints, data waits,
  startup, supervisor backoff, restart rework), per-attempt JSONL
  shards aggregated across restarts/hosts, and the per-window
  input/compute/comm/host bottleneck classifier;
* :mod:`bigdl_tpu.obs.server` — the live telemetry plane: per-host
  ``/metrics`` + ``/healthz`` + ``/trace`` HTTP endpoints on a daemon
  thread (``BIGDL_OBS_PORT``; port 0 = ephemeral; unset = no thread,
  no socket);
* :mod:`bigdl_tpu.obs.alerts` — declarative alert/SLO rules
  (threshold / absence / rate / burn-rate) evaluated on the goodput
  window tick, with a firing/resolved lifecycle, trace events,
  ``bigdl_alerts_total`` counters and an optional file/webhook sink.

Everything is off by default with a no-op fast path: disabled, the
train loop sees one shared null context manager per span site and adds
zero host-device synchronizations.  Resolution follows the fault
injector's read-at-call-time contract — ``BIGDL_TRACE_DIR`` exported
after import but before the optimizer runs is honored, and the tracer
is rebuilt whenever the directory changes.
"""

from __future__ import annotations

import atexit
import json
import os
import threading

from bigdl_tpu.obs.metrics import DEFAULT_BUCKETS, MetricsRegistry
from bigdl_tpu.obs.runtime import (
    Reservoir,
    RuntimeStats,
    all_device_memory_stats,
    device_memory_stats,
    hlo_cost_analysis,
    host_rss_bytes,
    instrument_jit,
)
from bigdl_tpu.obs.trace import NULL_TRACER, NullTracer, Tracer
from bigdl_tpu.obs import names

__all__ = [
    "DEFAULT_BUCKETS", "MetricsRegistry", "Reservoir", "RuntimeStats",
    "NullTracer", "Tracer", "NULL_TRACER",
    "active", "get_tracer", "get_registry", "get_runtime", "get_ledger",
    "instrument_jit", "host_rss_bytes", "device_memory_stats",
    "all_device_memory_stats",
    "flush", "reset",
]

_lock = threading.Lock()
_tracer = NULL_TRACER
_tracer_dir = None
_registry = MetricsRegistry()
_runtime: RuntimeStats = None
_atexit_registered = False


def _obs_config():
    from bigdl_tpu.config import refresh_from_env

    return refresh_from_env().obs


def active() -> bool:
    """Is any observability output enabled (BIGDL_OBS / BIGDL_TRACE_DIR
    / BIGDL_METRICS_DIR / BIGDL_OBS_PORT)?"""
    return _obs_config().active


def get_tracer():
    """The process tracer — a recording :class:`Tracer` bound to
    ``config.obs.trace_dir``, or the shared :data:`NULL_TRACER` when
    tracing is off.  Rebuilt when the directory changes."""
    global _tracer, _tracer_dir, _atexit_registered
    cfg = _obs_config()
    d = cfg.trace_dir
    with _lock:
        if d != _tracer_dir:
            if _tracer is not NULL_TRACER:
                _tracer.close()
            _tracer_dir = d
            _tracer = (Tracer(d, ring_size=cfg.flight_spans)
                       if d else NULL_TRACER)
            if d and not _atexit_registered:
                atexit.register(_atexit_close)
                _atexit_registered = True
        return _tracer


def _atexit_close():
    try:
        _tracer.close()
    except Exception:  # noqa: BLE001 — interpreter teardown
        pass


def _atexit_flush():
    """Last-chance shard flush: a run that dies on an unhandled
    exception or a fatal-signal ``SystemExit`` (the elastic preemption
    path) must still land its metrics snapshot and trace shard for the
    post-mortem — previously only ``optimize()``'s finally flushed.
    Registered at import; atexit is LIFO so the tracer's close hook
    (registered later, at first tracer build) runs first — Tracer.flush
    after close is explicitly safe.  No-op when observability is off."""
    try:
        if _obs_config().active:
            flush()
    except Exception:  # noqa: BLE001 — interpreter teardown
        pass


atexit.register(_atexit_flush)


def get_registry() -> MetricsRegistry:
    """The process-global metrics registry (always real — counters are
    host-side dict math; only file output is gated on config)."""
    return _registry


def get_runtime() -> RuntimeStats:
    """The process-global runtime profile (reservoirs sized from
    ``config.obs.reservoir_size`` at first use)."""
    global _runtime
    with _lock:
        if _runtime is None:
            _runtime = RuntimeStats(_obs_config().reservoir_size)
        return _runtime


def get_ledger():
    """The process goodput ledger (obs/goodput.py) — recording when
    observability is active, the shared no-op otherwise."""
    from bigdl_tpu.obs import goodput

    return goodput.get_ledger()


def publish_runtime(registry: MetricsRegistry = None,
                    runtime: RuntimeStats = None) -> dict:
    """Mirror the runtime snapshot into registry gauges (step-time
    percentiles, compile counters, memory) and return it."""
    registry = registry if registry is not None else _registry
    runtime = runtime if runtime is not None else get_runtime()
    snap = runtime.snapshot()
    st = snap["step_time_s"]
    g = registry.gauge(
        names.STEP_TIME_SECONDS,
        "Observed train-step completion time (dispatch -> resolved loss)",
        labels=("quantile",))
    for q in ("p50", "p95", "p99"):
        if st[q] is not None:
            g.labels(quantile=q).set(st[q])
    registry.gauge(
        names.JIT_COMPILE_COUNT,
        "Distinct jit compile events (new arg signatures)").set(
        snap["compile"]["count"])
    registry.gauge(
        names.JIT_COMPILE_SECONDS_TOTAL,
        "Wall seconds spent blocked on jit trace+compile").set(
        snap["compile"]["total_s"])
    # HLO-derived step FLOPs (compiled.cost_analysis(), normalized per
    # train step) and, when the chip's peak is known, observed MFU
    sf = snap.get("step_flops")
    if sf:
        registry.gauge(
            names.STEP_FLOPS,
            "HLO cost-analysis FLOPs of one compiled train step").set(sf)
        p50 = st["p50"]
        if runtime.peak_flops and p50:
            registry.gauge(
                names.MFU,
                "Model FLOPs utilization: HLO step FLOPs / (p50 step "
                "time * peak chip FLOPs)").set(
                sf / (p50 * runtime.peak_flops))
    rss = snap.get("host_rss_bytes")
    if rss:
        registry.gauge(names.HOST_RSS_BYTES,
                       "Driver-process resident set size").set(rss)
    dm = snap.get("device_memory")
    if dm:
        dg = registry.gauge(names.DEVICE_MEMORY_BYTES,
                            "Device 0 memory stats", labels=("stat",))
        for k, v in dm.items():
            dg.labels(stat=k).set(v)
    dma = snap.get("device_memory_all")
    if dma:
        hg = registry.gauge(
            names.HBM_PEAK_BYTES,
            "Peak HBM bytes in use, per local device",
            labels=("device",))
        for i, stats in dma.items():
            peak = stats.get("peak_bytes_in_use",
                             stats.get("bytes_in_use"))
            if peak is not None:
                hg.labels(device=i).set(peak)
    return snap


def flush(extra_registries=()) -> dict:
    """End-of-run export: publish runtime stats into the registry, write
    the Prometheus + JSONL metric snapshot (``metrics_dir``, falling
    back to ``trace_dir``), and flush the Chrome trace.  No-op when
    observability is off."""
    cfg = _obs_config()
    if not cfg.active:
        return {}
    publish_runtime()
    # the goodput ledger publishes its attempt-local classification
    # (bigdl_goodput_ratio / bigdl_badput_seconds_total) BEFORE the
    # snapshot is written so the shard carries the final numbers
    ledger = get_ledger()
    ledger.publish(_registry)
    paths = {}
    out_dir = cfg.metrics_dir or cfg.trace_dir
    if out_dir:
        paths = _registry.write_snapshot(out_dir,
                                         extra_registries=extra_registries)
        # the crash-flush gap, closed: the kept request-trace ring and
        # the folded profile used to live only in memory — a SIGTERM'd
        # run lost both.  This flush runs on the same atexit path as
        # the metrics snapshot, so they land with it.
        from bigdl_tpu.config import config as _cfg
        from bigdl_tpu.obs import prof, reqtrace

        stem = f"h{int(_cfg.process_id)}.{os.getpid()}"
        kept = reqtrace.get_collector().completed()
        if kept:
            rt = os.path.join(out_dir, f"reqtraces.{stem}.json")
            tmp = rt + ".tmp"
            with open(tmp, "w", encoding="utf-8") as fh:
                json.dump(kept, fh, default=str)
            os.replace(tmp, rt)
            paths["reqtraces"] = rt
        pp = prof.write_profile(out_dir, f"prof.{stem}")
        if pp:
            paths["profile"] = pp
    tracer = get_tracer()
    tracer.flush()
    if tracer is not NULL_TRACER:
        paths["trace"] = tracer.trace_path
        paths["events"] = tracer.jsonl_path
    gp = ledger.flush()
    if gp:
        paths["goodput"] = gp
    return paths


def reset():
    """Test hook: close the tracer, drop the registry and runtime
    singletons, tear down the live telemetry server, and reset the
    alert engine + step stamp.  The next accessor rebuilds from the
    current config."""
    global _tracer, _tracer_dir, _runtime, _registry
    with _lock:
        if _tracer is not NULL_TRACER:
            try:
                _tracer.close()
            except Exception:  # noqa: BLE001 — half-torn test dirs
                pass
        _tracer = NULL_TRACER
        _tracer_dir = None
        _registry = MetricsRegistry()
        _runtime = None
    from bigdl_tpu.obs import (alerts, bundle, goodput, prof, reqtrace,
                               server)

    goodput.reset_ledger()
    server.stop_server()
    server.clear_step()
    alerts.reset_engine()
    reqtrace.reset_collector()
    prof.reset_profiler()
    bundle.reset()
