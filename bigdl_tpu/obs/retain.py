"""Downsampling retention store — bounded history for fleet metrics.

The live plane (``aggregate``/``rollup``) answers "what is the fleet
doing *now*"; nothing answers "what was it doing two minutes ago"
without re-running a report over JSONL shards.  This store keeps a
small, fixed-budget history of selected series so ``report --watch``
can render trend sparklines and the smoke can run cross-run regression
checks:

* three rings per series — ``raw`` (every ingested point), ``10s`` and
  ``1m`` downsamples — each a fixed-capacity deque
  (``BIGDL_RETAIN_POINTS``), evictions counted in
  ``bigdl_retain_evictions_total{ring}``;
* downsampling folds the points inside one resolution bucket under the
  family's fleet aggregation policy (``obs/names.py``): ``max``/``min``
  keep the bucket's worst point, ``sum``/``last`` keep the newest —
  correct for cumulative counters, where last-in-bucket *is* the
  bucket's value;
* a hard series budget (``BIGDL_RETAIN_SERIES``): past it, new series
  are rejected (memory stays fixed) rather than evicting history;
* torn-write-safe persistence: one JSONL line appended per ingest
  batch under ``BIGDL_METRICS_DIR`` (``retain.jsonl``), replayed on
  load with a torn trailing line skipped — the same contract the trace
  shard reader honors.
"""

from __future__ import annotations

import collections
import json
import logging
import os
from typing import Dict, List, Optional, Sequence, Tuple

from bigdl_tpu.obs import names

log = logging.getLogger("bigdl_tpu.obs")

#: (ring name, bucket seconds); raw keeps every point
RINGS: Tuple[Tuple[str, float], ...] = (("raw", 0.0), ("10s", 10.0),
                                        ("1m", 60.0))

_SPARK_BLOCKS = "▁▂▃▄▅▆▇█"


def sparkline(values: Sequence[float], width: int = 24) -> str:
    """A unicode block sparkline of the newest ``width`` values
    (empty string for no data; a flat series renders mid-blocks)."""
    vals = [float(v) for v in values][-int(width):]
    if not vals:
        return ""
    lo, hi = min(vals), max(vals)
    if hi <= lo:
        return _SPARK_BLOCKS[3] * len(vals)
    span = hi - lo
    return "".join(
        _SPARK_BLOCKS[min(len(_SPARK_BLOCKS) - 1,
                          int((v - lo) / span * len(_SPARK_BLOCKS)))]
        for v in vals)


def _series_id(name: str, labels: Optional[dict]) -> str:
    if not labels:
        return name
    body = ",".join(f"{k}={labels[k]}" for k in sorted(labels))
    return f"{name}{{{body}}}"


class RetentionStore:
    """Fixed-budget, policy-downsampled ring store for fleet series."""

    def __init__(self, max_series: Optional[int] = None,
                 points_per_ring: Optional[int] = None,
                 directory: Optional[str] = None, registry=None):
        from bigdl_tpu.config import refresh_from_env

        cfg = refresh_from_env().obs
        self.max_series = (cfg.retain_series if max_series is None
                           else int(max_series))
        self.points = (cfg.retain_points if points_per_ring is None
                       else int(points_per_ring))
        self.directory = directory
        self._registry = registry
        # series id -> ring name -> deque of (t, value)
        self._series: Dict[str, Dict[str, collections.deque]] = {}
        self._policy: Dict[str, str] = {}
        self._rejected = 0
        self._pending: List[list] = []

    # ------------------------------------------------------------ write
    def _rings(self, sid: str) -> Optional[Dict[str, collections.deque]]:
        rings = self._series.get(sid)
        if rings is None:
            if len(self._series) >= self.max_series:
                self._rejected += 1
                return None
            rings = {ring: collections.deque()
                     for ring, _ in RINGS}
            self._series[sid] = rings
        return rings

    def ingest(self, t: float, name: str, value: float,
               labels: Optional[dict] = None, persist: bool = True):
        """Record one point.  Downsampled rings fold the point into
        their current resolution bucket under the family policy; full
        rings evict their oldest point (counted)."""
        sid = _series_id(name, labels)
        rings = self._rings(sid)
        if rings is None:
            return
        policy = self._policy.get(sid)
        if policy is None:
            policy = names.fleet_policy(name) or "last"
            self._policy[sid] = policy
        t, value = float(t), float(value)
        for ring, bucket_s in RINGS:
            dq = rings[ring]
            if bucket_s > 0 and dq:
                last_t, last_v = dq[-1]
                if int(t // bucket_s) == int(last_t // bucket_s):
                    # same resolution bucket: fold, don't append
                    if policy == "max":
                        value_f = max(last_v, value)
                    elif policy == "min":
                        value_f = min(last_v, value)
                    else:  # sum/last: newest point carries the bucket
                        value_f = value
                    dq[-1] = (t, value_f)
                    continue
            if len(dq) >= self.points:
                dq.popleft()
                self._evicted(ring)
            dq.append((t, value))
        self._counter(names.RETAIN_POINTS_TOTAL).inc()
        self._gauge(names.RETAIN_SERIES).set(len(self._series))
        if persist:
            self._pending.append(
                [round(t, 6), name, labels or {}, value])

    def ingest_snapshot(self, t: float, fleet: dict):
        """Convenience for the watch loop: retain the fleet-level
        trend signals out of one ``FleetAggregator.snapshot()``."""
        hosts = (fleet.get("hosts") or {}).values()
        depths = [h.get("queue_depth") for h in hosts
                  if h.get("queue_depth") is not None]
        ratios = [h.get("goodput_ratio")
                  for h in (fleet.get("hosts") or {}).values()
                  if h.get("goodput_ratio") is not None]
        if depths:
            self.ingest(t, names.SERVE_QUEUE_DEPTH, sum(depths))
        if ratios:
            self.ingest(t, names.GOODPUT_RATIO, min(ratios))
        scrape_s = fleet.get("scrape_s")
        if scrape_s is not None:
            self.ingest(t, names.FLEET_SCRAPE_SECONDS, scrape_s)
        self.ingest(t, names.FLEET_STALE_HOSTS,
                    len(fleet.get("stale") or {}))
        self.flush()

    # ------------------------------------------------------------- read
    def series(self, name: str, labels: Optional[dict] = None,
               ring: str = "raw") -> List[Tuple[float, float]]:
        rings = self._series.get(_series_id(name, labels))
        if rings is None or ring not in rings:
            return []
        return list(rings[ring])

    def spark(self, name: str, labels: Optional[dict] = None,
              ring: str = "raw", width: int = 24) -> str:
        return sparkline([v for _, v in self.series(name, labels, ring)],
                         width=width)

    def summary(self) -> dict:
        """Per-series last/min/max over the raw ring — the cross-run
        regression surface the smoke banks."""
        out = {}
        for sid, rings in sorted(self._series.items()):
            vals = [v for _, v in rings["raw"]]
            if not vals:
                continue
            out[sid] = {"last": vals[-1], "min": min(vals),
                        "max": max(vals), "n": len(vals),
                        "n_10s": len(rings["10s"]),
                        "n_1m": len(rings["1m"])}
        return out

    @property
    def n_series(self) -> int:
        return len(self._series)

    @property
    def rejected_series(self) -> int:
        return self._rejected

    # ------------------------------------------------------ persistence
    def flush(self):
        """Append pending points as ONE complete JSONL line (atomic
        enough: a torn tail is skipped by :meth:`load`, never a torn
        middle — appends are whole lines)."""
        if not self._pending or not self.directory:
            self._pending = []
            return
        os.makedirs(self.directory, exist_ok=True)
        path = os.path.join(self.directory, "retain.jsonl")
        line = json.dumps({"points": self._pending}) + "\n"
        with open(path, "a", encoding="utf-8") as fh:
            fh.write(line)
        self._pending = []

    def load(self) -> int:
        """Replay persisted points (torn trailing line skipped).
        Returns the number of points replayed."""
        if not self.directory:
            return 0
        path = os.path.join(self.directory, "retain.jsonl")
        if not os.path.isfile(path):
            return 0
        n = 0
        with open(path, "rb") as fh:
            data = fh.read()
        for raw in data.split(b"\n"):
            if not raw.strip():
                continue
            try:
                batch = json.loads(raw.decode("utf-8"))
            except (ValueError, UnicodeDecodeError):
                continue  # torn tail (or foreign junk): skip, keep going
            for t, name, labels, value in batch.get("points") or []:
                self.ingest(t, name, value, labels or None,
                            persist=False)
                n += 1
        return n

    # ------------------------------------------------------------ meta
    def _reg(self):
        if self._registry is not None:
            return self._registry
        from bigdl_tpu import obs

        return obs.get_registry()

    def _counter(self, name):
        return self._reg().counter(name, names.spec(name).doc,
                                   labels=names.spec(name).labels)

    def _gauge(self, name):
        return self._reg().gauge(name, names.spec(name).doc)

    def _evicted(self, ring: str):
        self._reg().counter(
            names.RETAIN_EVICTIONS_TOTAL,
            names.spec(names.RETAIN_EVICTIONS_TOTAL).doc,
            labels=("ring",)).labels(ring=ring).inc()
