"""Training-health telemetry — per-layer numerics, computed on device.

The host side of the stack became observable in the previous obs PRs
(spans, metrics, multi-host merge, collective bytes); this module makes
the *model numerics* observable — the three numbers an operator of a
long run watches per layer:

* **gradient norm** — exploding/vanishing layers, pre-clip;
* **parameter norm** — weight drift, weight-decay sanity;
* **update-to-weight ratio** — ``||Δw|| / ||w||``, the classic
  learning-rate health signal (~1e-3 is healthy for SGD-family).

Everything is **pure device math appended to the jitted train step**:
per-layer squared norms stacked into ONE small ``(L, 4)`` f32 array
(``[grad_sq, param_sq, update_sq, nonfinite_grad_count]`` per layer)
returned as an extra step output.  The driver fetches it every
``BIGDL_HEALTH_EVERY`` steps — one host transfer per K steps when on,
and when off the step compiles WITHOUT the extra output (identical
signature, zero added transfers).  In the sharded (ZeRO) path the
per-layer partial sums are ``psum``'d across the mesh, so every host
reports **global** norms — the per-layer reconstruction obligation that
sharded weight-update schemes create (arXiv:2004.13336).

On top of the raw stats:

* **non-finite localization** — when the PR 1 non-finite guard trips,
  column 3 (non-finite gradient element count per layer) names the
  offending layer(s); the driver emits a ``health.nonfinite_layers``
  trace event carrying the first offender + the full list, and bumps
  ``bigdl_nonfinite_layers_total{layer}``;
* a **numerics anomaly detector** mirroring the slow-step detector: a
  loss or global-grad-norm observation above ``rolling median *
  BIGDL_HEALTH_SPIKE_FACTOR`` emits a ``health.anomaly`` trace event
  and bumps ``bigdl_numerics_anomalies_total{kind}``.

A "layer" is one parameter leaf of the model's params pytree, named by
its tree path (e.g. ``"0/weight"``) — the same flatten order
``ravel_pytree`` gives the flat ZeRO vector, so the local (tree) and
sharded (flat) stats agree layer-for-layer.
"""

from __future__ import annotations

import collections
import logging
from typing import List, Optional, Sequence

import numpy as np
from bigdl_tpu.obs import names as mnames

log = logging.getLogger("bigdl_tpu.obs")

# columns of the stacked per-layer stats array
GRAD_SQ, PARAM_SQ, UPDATE_SQ, NONFINITE = 0, 1, 2, 3


def layer_names(params_tree) -> List[str]:
    """Tree-path name per parameter leaf, in ``tree_flatten`` (==
    ``ravel_pytree``) order — the label vocabulary of every per-layer
    metric this module emits."""
    import jax

    flat, _ = jax.tree_util.tree_flatten_with_path(params_tree)
    names = []
    for path, _leaf in flat:
        names.append("/".join(
            str(getattr(k, "key", getattr(k, "idx", k))) for k in path))
    return names


def layer_sizes(params_tree) -> List[int]:
    """Element count per leaf, same order as :func:`layer_names`."""
    import jax

    return [int(np.size(x)) for x in jax.tree.leaves(params_tree)]


# ------------------------------------------------------------ device math
def tree_layer_stats(grad_tree, params_tree, new_params_tree):
    """LocalOptimizer path: per-leaf ``[grad_sq, param_sq, update_sq,
    nonfinite_count]`` stacked to ``(L, 4)`` f32.  Pure jax — traces
    into the jitted step, no host reads."""
    import jax
    import jax.numpy as jnp

    rows = []
    for g, p, q in zip(jax.tree.leaves(grad_tree),
                       jax.tree.leaves(params_tree),
                       jax.tree.leaves(new_params_tree)):
        gf = g.astype(jnp.float32)
        pf = p.astype(jnp.float32)
        df = q.astype(jnp.float32) - pf
        rows.append(jnp.stack([
            jnp.sum(gf * gf),
            jnp.sum(pf * pf),
            jnp.sum(df * df),
            jnp.sum((~jnp.isfinite(gf)).astype(jnp.float32)),
        ]))
    return jnp.stack(rows)


def flat_shard_stats(gshard, wshard, new_wshard, shard_offset, boundaries,
                     axis, positions=None):
    """DistriOptimizer (ZeRO) path: each device holds a contiguous shard
    of the flat vector starting at ``shard_offset`` (traced).  Layers
    occupy contiguous flat ranges (``ravel_pytree`` concatenates in
    leaves order), so a flat position's layer index is
    ``searchsorted(boundaries, idx)`` with ``boundaries`` the cumulative
    layer end offsets.  Per-layer partial sums via ``segment_sum``, then
    ONE ``(L, 4)`` psum over the data axis makes every host's stats
    **global** — pad positions past the true size land in an extra
    dropped segment.

    ``positions`` (optional, traced int32, same length as the shard)
    overrides the contiguous-shard assumption: the bucketed overlap
    exchange leaves each device owning one chunk of every bucket, so
    the caller hands the per-position flat coordinates over directly."""
    import jax
    import jax.numpy as jnp

    n_layers = int(boundaries.shape[0])
    shard_len = gshard.shape[0]
    idx = positions if positions is not None else \
        jax.lax.iota(jnp.int32, shard_len) + shard_offset
    seg = jnp.searchsorted(boundaries, idx, side="right")

    def seg_sum(v):
        return jax.ops.segment_sum(
            v, seg, num_segments=n_layers + 1)[:n_layers]

    gf = gshard.astype(jnp.float32)
    wf = wshard.astype(jnp.float32)
    df = new_wshard.astype(jnp.float32) - wf
    stats = jnp.stack([
        seg_sum(gf * gf),
        seg_sum(wf * wf),
        seg_sum(df * df),
        seg_sum((~jnp.isfinite(gf)).astype(jnp.float32)),
    ], axis=1)
    return jax.lax.psum(stats, axis)


# ------------------------------------------------------------ host analysis
def nonfinite_layers(stats: np.ndarray,
                     names: Sequence[str]) -> List[str]:
    """Names of layers with any non-finite gradient element, flat-layout
    order (the first entry is the first offender)."""
    arr = np.asarray(stats)
    return [names[i] for i in range(min(len(names), arr.shape[0]))
            if arr[i, NONFINITE] > 0]


def summarize(stats: np.ndarray, names: Sequence[str],
              eps: float = 1e-12) -> dict:
    """Derived per-layer numbers from one fetched ``(L, 4)`` array:
    ``{layer: {grad_norm, param_norm, update_ratio, nonfinite}}`` plus
    the global gradient norm."""
    arr = np.asarray(stats, np.float64)
    layers = {}
    for i, name in enumerate(names[: arr.shape[0]]):
        gsq, psq, usq, nf = arr[i]
        layers[name] = {
            "grad_norm": float(np.sqrt(gsq)),
            "param_norm": float(np.sqrt(psq)),
            "update_ratio": float(np.sqrt(usq) / (np.sqrt(psq) + eps)),
            "nonfinite": int(nf) if np.isfinite(nf) else -1,
        }
    with np.errstate(invalid="ignore"):
        global_grad = float(np.sqrt(arr[:, GRAD_SQ].sum()))
    return {"layers": layers, "global_grad_norm": global_grad}


class HealthMonitor:
    """Driver-side half: owns the fetch cadence, the metric/trace/
    TensorBoard fan-out, non-finite localization, and the anomaly
    detector.  Created by the optimizer only when
    ``config.obs.health_every > 0`` — its absence IS the disabled fast
    path (no fetch sites exist at all)."""

    def __init__(self, names: Sequence[str], every: int, registry=None,
                 tracer=None, summary=None, window: int = 64,
                 spike_factor: float = 10.0):
        from bigdl_tpu import obs
        from bigdl_tpu.obs.trace import NULL_TRACER

        self.names = list(names)
        self.every = max(1, int(every))
        self.registry = registry if registry is not None \
            else obs.get_registry()
        self.tracer = tracer if tracer is not None else NULL_TRACER
        self.summary = summary
        self.spike_factor = float(spike_factor)
        self.fetches = 0          # device->host health transfers, total
        self.anomalies = 0
        self._loss_window: collections.deque = collections.deque(
            maxlen=max(8, int(window)))
        self._gnorm_window: collections.deque = collections.deque(
            maxlen=max(8, int(window)))
        self.last: Optional[dict] = None
        self._grad_gauge = self.registry.gauge(
            mnames.GRAD_NORM,
            "Per-layer global gradient L2 norm (pre-clip)",
            labels=("layer",))
        self._param_gauge = self.registry.gauge(
            mnames.PARAM_NORM, "Per-layer parameter L2 norm",
            labels=("layer",))
        self._ratio_gauge = self.registry.gauge(
            mnames.UPDATE_RATIO,
            "Per-layer ||update|| / ||param|| ratio", labels=("layer",))
        self._gnorm_hist = self.registry.histogram(
            mnames.GLOBAL_GRAD_NORM,
            "Global (all-layer) gradient L2 norm per health sample",
            buckets=(1e-4, 1e-3, 1e-2, 0.1, 0.3, 1.0, 3.0, 10.0, 30.0,
                     100.0, 1e3, 1e4))
        self._nonfinite_ctr = self.registry.counter(
            mnames.NONFINITE_LAYERS_TOTAL,
            "Non-finite-gradient steps attributed per layer",
            labels=("layer",))
        self._anomaly_ctr = self.registry.counter(
            mnames.NUMERICS_ANOMALIES_TOTAL,
            "Loss / grad-norm spikes vs the rolling median",
            labels=("kind",))

    # ------------------------------------------------------------- cadence
    def wants(self, step: int, ok: bool = True) -> bool:
        """Fetch this step's health array?  Every K steps — and always
        when the non-finite guard tripped (localization is the whole
        point of that fetch)."""
        return (not ok) or step % self.every == 0

    # ------------------------------------------------------------- ingest
    def on_step(self, step: int, stats, ok: bool, loss: float):
        """Called at loss-resolve time with the step's device-resident
        health array.  Fetches it only when :meth:`wants` says so; the
        loss-spike check is free (the loss is already host-side)."""
        self._spike("loss_spike", self._loss_window, step, loss)
        if stats is None or not self.wants(step, ok):
            return None
        arr = np.asarray(stats)   # THE device->host health transfer
        self.fetches += 1
        summ = summarize(arr, self.names)
        self.last = {"step": step, **summ}
        for name, row in summ["layers"].items():
            # a NaN gauge carries no information (the non-finite counter
            # below is the signal for that); keep the last finite value
            for gauge, key in ((self._grad_gauge, "grad_norm"),
                               (self._param_gauge, "param_norm"),
                               (self._ratio_gauge, "update_ratio")):
                if np.isfinite(row[key]):
                    gauge.labels(layer=name).set(row[key])
        g = summ["global_grad_norm"]
        if np.isfinite(g):
            self._gnorm_hist.observe(g)
            self._spike("grad_norm_spike", self._gnorm_window, step, g)
        if self.summary is not None:
            add = getattr(self.summary, "add_health", None)
            if add is not None:
                add(step, summ["layers"])
        if not ok:
            self._report_nonfinite(step, arr, loss)
        return summ

    def _report_nonfinite(self, step: int, arr: np.ndarray, loss: float):
        bad = nonfinite_layers(arr, self.names)
        first = bad[0] if bad else None
        counts = {self.names[i]: int(arr[i, NONFINITE])
                  for i in range(min(len(self.names), arr.shape[0]))
                  if arr[i, NONFINITE] > 0}
        if not bad:
            # grads finite but the loss was not — attribute to the loss
            first = "<loss>"
        log.warning(
            "non-finite localization at step %d: first offender %s "
            "(all: %s)", step, first, bad or "loss only")
        self.tracer.event("health.nonfinite_layers", step=step,
                          first=first, layers=bad, counts=counts,
                          loss=loss)
        for name in (bad or [first]):
            self._nonfinite_ctr.labels(layer=name).inc()

    def _spike(self, kind: str, window: collections.deque, step: int,
               value: float):
        """Rolling-median spike detector (mirrors the slow-step
        detector: 8-observation warmup, factor from config, structured
        event + counter)."""
        if self.spike_factor <= 0 or value is None \
                or not np.isfinite(value):
            return
        v = abs(float(value))
        if len(window) >= 8:
            med = float(np.median(window))
            if med > 0 and v > med * self.spike_factor:
                self.anomalies += 1
                log.warning("numerics anomaly at step %d: %s %.6g vs "
                            "rolling median %.6g (> %gx)", step, kind, v,
                            med, self.spike_factor)
                self.tracer.event("health.anomaly", kind=kind, step=step,
                                  value=v, median=med,
                                  factor=self.spike_factor)
                self._anomaly_ctr.labels(kind=kind).inc()
        window.append(v)


def monitor_from_config(params_tree, tracer=None, summary=None):
    """The optimizer's entry point: a :class:`HealthMonitor` when
    ``BIGDL_HEALTH_EVERY`` > 0, else None (the step then builds without
    the health output — same compiled signature as a health-less
    build)."""
    from bigdl_tpu.config import refresh_from_env

    cfg = refresh_from_env().obs
    if cfg.health_every <= 0:
        return None
    return HealthMonitor(layer_names(params_tree), cfg.health_every,
                         tracer=tracer, summary=summary,
                         window=cfg.health_window,
                         spike_factor=cfg.health_spike_factor)
