"""Collective-traffic accounting — bytes on the wire, from static shapes.

EQuARX's (PAPERS.md) case for quantized collectives is a byte count;
this module makes the rebuild state its own: every collective the
training stack programs (DistriOptimizer's ZeRO-1 exchange, the ring /
pipeline ppermutes, MoE's all_to_all pair, tensor-parallel placement)
is accounted **from static shapes at trace/build time** — never by
reading a device value, so instrumentation adds zero host-device
synchronizations.

Cost model: the standard ring-algorithm per-device wire bytes for an
``n``-way collective over a ``payload``-byte global operand —

====================  =======================================
op                    bytes sent per device
====================  =======================================
all-reduce (psum)     ``2 * payload * (n-1) / n``
reduce-scatter        ``payload * (n-1) / n``
all-gather            ``payload * (n-1) / n``
all-to-all            ``payload * (n-1) / n``
ppermute              ``payload`` per hop
====================  =======================================

Hierarchical meshes (``data_axes=('dcn', 'ici')``) are accounted with
``n`` = the product of the axis sizes — the single-ring upper bound;
XLA's hierarchical lowering moves fewer bytes over DCN, so the counter
is conservative, never flattering.

Two surfaces:

* :func:`record` — one-shot accounting (the parallel wrappers call it
  at trace time): increments ``bigdl_collective_bytes_total{op,dtype}``
  and emits a ``collective`` trace event when tracing is on;
* :class:`StepFootprint` — the per-step form DistriOptimizer builds
  once at step-build time (children pre-bound, gauges published) and
  ``commit()``s per resolved step: a handful of locked float adds on
  the host, nothing on the device.
"""

from __future__ import annotations

import math
from typing import Optional
from bigdl_tpu.obs import names

# jax dtypes numpy can't name, plus the common spellings — fall back to
# numpy's itemsize for everything else
_DTYPE_BYTES = {
    "bfloat16": 2, "float16": 2, "half": 2,
    "float32": 4, "float": 4, "int32": 4, "uint32": 4,
    "float64": 8, "int64": 8, "uint64": 8,
    "int16": 2, "uint16": 2,
    "int8": 1, "uint8": 1, "bool": 1,
    "float8_e4m3fn": 1, "float8_e5m2": 1, "float8_e4m3": 1,
    "float8_e4m3b11_fnuz": 1, "float8_e5m2fnuz": 1, "float8_e4m3fnuz": 1,
    "float4_e2m1fn": 1,
}


def dtype_bytes(dtype) -> int:
    """Bytes per element for a dtype given as dtype object, scalar type
    (``jnp.bfloat16`` is a class, not a dtype), or string."""
    name = dtype if isinstance(dtype, str) else getattr(dtype, "name", None)
    if name is None:
        import numpy as np

        # scalar types (jnp.bfloat16 & co): ml_dtypes registers them
        # with numpy, so np.dtype() resolves where str() would not
        return int(np.dtype(dtype).itemsize)
    b = _DTYPE_BYTES.get(name)
    if b is not None:
        return b
    import numpy as np

    return int(np.dtype(name).itemsize)


def all_reduce_bytes(n_elems: int, dtype, axis_size: int) -> float:
    """psum / pmean / pmin / pmax: ring all-reduce wire bytes per
    device (reduce-scatter + all-gather phases)."""
    if axis_size <= 1:
        return 0.0
    return 2.0 * n_elems * dtype_bytes(dtype) * (axis_size - 1) / axis_size


def reduce_scatter_bytes(n_elems: int, dtype, axis_size: int) -> float:
    """psum_scatter over a ``n_elems`` global operand."""
    if axis_size <= 1:
        return 0.0
    return float(n_elems) * dtype_bytes(dtype) * (axis_size - 1) / axis_size


def all_gather_bytes(n_elems: int, dtype, axis_size: int) -> float:
    """all_gather producing a ``n_elems`` global result (each device
    ships its shard around the ring ``n-1`` times)."""
    if axis_size <= 1:
        return 0.0
    return float(n_elems) * dtype_bytes(dtype) * (axis_size - 1) / axis_size


def all_to_all_bytes(n_elems: int, dtype, axis_size: int) -> float:
    """all_to_all of a ``n_elems`` per-device operand: every device
    keeps 1/n locally and ships the rest."""
    if axis_size <= 1:
        return 0.0
    return float(n_elems) * dtype_bytes(dtype) * (axis_size - 1) / axis_size


def ppermute_bytes(n_elems: int, dtype, hops: int = 1) -> float:
    """ppermute: the full per-device payload moves every hop."""
    return float(n_elems) * dtype_bytes(dtype) * max(0, hops)


def int8_blockwise_exchange_bytes(padded_elems: int, axis_size: int,
                                  block: int) -> dict:
    """Wire bytes of the round-5 quantize-once exchange (one all_to_all
    pair): int8 payload + f32 per-block scales.  ``padded_elems`` must
    be divisible by ``axis_size * block`` (the optimizer pads to that
    quantum).  Kept as the historical a2a-shaped model; the staged ring
    the optimizer now runs moves the same totals
    (:func:`staged_ring_exchange_bytes`)."""
    n_blocks = padded_elems // axis_size // block
    return {
        "int8": all_to_all_bytes(padded_elems, "int8", axis_size),
        "float32": all_to_all_bytes(axis_size * n_blocks, "float32",
                                    axis_size),
    }


def fp8_blockwise_exchange_bytes(padded_elems: int, axis_size: int,
                                 block: int,
                                 dtype: str = "float8_e4m3fn") -> dict:
    """fp8 analogue of :func:`int8_blockwise_exchange_bytes`: 1-byte
    payload + f32 per-block scales through one all_to_all pair."""
    n_blocks = padded_elems // axis_size // block
    return {
        dtype: all_to_all_bytes(padded_elems, dtype, axis_size),
        "float32": all_to_all_bytes(axis_size * n_blocks, "float32",
                                    axis_size),
    }


def staged_ring_exchange_bytes(padded_elems: int, axis_size: int,
                               block: int, dtype: str) -> dict:
    """Per-device wire bytes of the in-reduce staged ring
    (``parallel/wire.reduce_scatter``): the partial for every chunk
    rides ``n-1`` hops, each hop shipping one ``padded/n``-element
    payload in the wire dtype plus (for the scaled dtypes) its
    ``padded/(n*block)`` f32 scales — the per-hop scale overhead is the
    price of re-quantizing inside the reduction.  Totals equal the
    quantize-once all_to_all model: in-reduce staging costs no extra
    bytes, it moves the SAME bytes through every reduction stage."""
    n = int(axis_size)
    if n <= 1:
        return {dtype: 0.0}
    chunk = padded_elems // n
    hops = n - 1
    out = {dtype: float(hops * chunk) * dtype_bytes(dtype)}
    if dtype not in ("bfloat16", "float16", "float32"):
        out["float32"] = float(hops * (chunk // block)) * 4.0
    return out


_SAVINGS_META = (
    names.COLLECTIVE_WIRE_SAVINGS_RATIO,
    "Uncompressed exchange bytes over what the configured wire "
    "actually ships, per exchange path (grad = DistriOptimizer's "
    "ZeRO-1 exchange, tp/moe/ring = the opt-in compressed wires)",
)


def record_savings(path: str, baseline_bytes: float, wire_bytes: float,
                   registry=None) -> float:
    """Publish the EQuARX headline gauge for one exchange path:
    ``baseline_bytes`` (what the uncompressed exchange would ship) over
    ``wire_bytes`` (what the configured wire ships).  Returns the
    ratio (1.0 when nothing is compressed or nothing moves)."""
    ratio = (float(baseline_bytes) / float(wire_bytes)
             if wire_bytes else 1.0)
    if registry is None:
        from bigdl_tpu import obs

        registry = obs.get_registry()
    registry.gauge(*_SAVINGS_META, labels=("path",)).labels(
        path=path).set(ratio)
    return ratio


# --------------------------------------------------------------- recording
_COUNTER_META = (
    names.COLLECTIVE_BYTES_TOTAL,
    "Wire bytes programmed into collectives, from static shapes "
    "(ring-algorithm cost model; no device reads)",
)
_GAUGE_META = (
    names.COLLECTIVE_BYTES_PER_STEP,
    "Static per-train-step wire bytes of the optimizer's collective "
    "footprint",
)


def _counter(registry=None):
    if registry is None:
        from bigdl_tpu import obs

        registry = obs.get_registry()
    return registry.counter(*_COUNTER_META, labels=("op", "dtype"))


def record(op: str, dtype, nbytes: float, *, axis_size: Optional[int] = None,
           registry=None) -> float:
    """One-shot accounting: add ``nbytes`` to the labeled counter and
    emit a ``collective`` trace event (no-op tracer when tracing is
    off).  Called by the parallel wrappers at trace time — under jit
    that is once per compile, eagerly once per call."""
    name = getattr(dtype, "name", None) or str(dtype)
    nbytes = float(nbytes)
    _counter(registry).labels(op=op, dtype=name).inc(nbytes)
    from bigdl_tpu import obs

    tracer = obs.get_tracer()
    if tracer.enabled:
        attrs = {"op": op, "dtype": name, "bytes": round(nbytes, 1)}
        if axis_size is not None:
            attrs["axis_size"] = int(axis_size)
        tracer.event("collective", **attrs)
    return nbytes


class StepFootprint:
    """The static collective byte budget of ONE train step.

    Built host-side while the jitted step is assembled (all shapes are
    static there), then ``commit()``-ed once per resolved step by the
    driver loop.  Children are pre-bound so the hot path is a few
    locked float adds."""

    def __init__(self):
        self.entries: list = []   # [(op, dtype, bytes_per_step)]
        self._bound: list = []    # [(counter_child, bytes)]

    def add(self, op: str, dtype, nbytes: float) -> "StepFootprint":
        name = getattr(dtype, "name", None) or str(dtype)
        nbytes = float(nbytes)
        if nbytes > 0:
            self.entries.append((op, name, nbytes))
        return self

    def total(self) -> float:
        return math.fsum(b for _, _, b in self.entries)

    def by_op(self) -> dict:
        out: dict = {}
        for op, name, b in self.entries:
            key = f"{op}:{name}"
            out[key] = out.get(key, 0.0) + b
        return out

    def bind(self, registry=None) -> "StepFootprint":
        """Resolve counter children once and publish the static
        per-step gauges; idempotent re-binds replace the cache."""
        if registry is None:
            from bigdl_tpu import obs

            registry = obs.get_registry()
        counter = _counter(registry)
        gauge = registry.gauge(*_GAUGE_META, labels=("op", "dtype"))
        merged: dict = {}
        for op, name, b in self.entries:
            merged[(op, name)] = merged.get((op, name), 0.0) + b
        self._bound = []
        for (op, name), b in merged.items():
            self._bound.append((counter.labels(op=op, dtype=name), b))
            gauge.labels(op=op, dtype=name).set(b)
        return self

    def commit(self):
        """Account one executed step (driver loop, per resolved step)."""
        for child, b in self._bound:
            child.inc(b)
