"""Goodput ledger — wall-clock accounting & bottleneck attribution.

PR 5 made training elastic; this module measures what elasticity (and
everything else) *costs*.  Every interval of a training run's life is
classified into productive step time vs. a badput cause:

==================   ======================================================
cause                interval
==================   ======================================================
``step``             one resolved train step (dispatch -> observed loss)
``compile``          blocked on jit trace + XLA compile (first signature)
``checkpoint_save``  the synchronous part of a checkpoint write
``checkpoint_restore`` loading a checkpoint (resume, retry reload)
``data_wait``        the driver blocked on the input pipeline
``eval``             validation triggered mid-run
``startup``          ledger birth -> the first dispatched step
``supervisor_backoff`` the restart supervisor sleeping between launches
``rework``           steps re-executed after a restart: a ``step`` whose
                     number is <= the pre-crash high-water mark (stamped
                     by the elastic resume path) is re-tagged ``rework``
==================   ======================================================

Records persist as per-attempt JSONL shards
(``goodput.h<host>.<pid>.a<attempt>.jsonl``) under ``BIGDL_METRICS_DIR``
— host- and attempt-tagged like the metrics shards, flushed by
``obs.flush()`` and the PR 5 atexit hook, so a crashed attempt still
lands its ledger — and :func:`aggregate_goodput` folds N shards into
ONE cross-restart, cross-host goodput ratio.  The pre-crash high-water
mark itself comes from the *previous attempt's shard*: ``stamp_resume``
scans the ledger directory (plus this process's in-memory records, for
the in-process retry path) for the max step ever reached, so replayed
steps between the restored step and that mark count as ``rework``.

The per-window bottleneck classifier
(:meth:`GoodputLedger._window_tick`, every ``BIGDL_GOODPUT_WINDOW``
productive steps) attributes the window to ``input_bound`` /
``compute_bound`` / ``comm_bound`` / ``host_bound`` from the same
interval stream plus the static per-step wire bytes
(obs/collectives.py) and publishes the one-hot ``bigdl_bottleneck``
gauge + a ``goodput.bottleneck`` trace event.

Everything is host-side arithmetic stamped at span boundaries the
optimizers already time — zero new device syncs; with observability off
every call lands on the shared :data:`NULL_LEDGER` no-op.
"""

from __future__ import annotations

import json
import logging
import os
import threading
import time
from typing import Dict, List, Optional
from bigdl_tpu.obs import names

log = logging.getLogger("bigdl_tpu.obs")

# productive + badput causes, most-specific first: when intervals
# overlap (the first step's `step` record contains its compile; the
# startup window contains the restore), the elementary segment is
# charged to the HIGHEST-priority covering cause, so no second is ever
# double-counted and nesting resolves to the most specific explanation
PRIORITY = {
    "checkpoint_restore": 9,
    "checkpoint_save": 8,
    "compile": 7,
    "rework": 6,
    "eval": 5,
    "data_wait": 4,
    "supervisor_backoff": 3,
    "startup": 2,
    "step": 1,
}
CAUSES = tuple(PRIORITY)
BADPUT_CAUSES = tuple(c for c in CAUSES if c != "step")
BOTTLENECKS = ("input_bound", "compute_bound", "comm_bound", "host_bound")

_RATIO_META = (
    names.GOODPUT_RATIO,
    "Productive step seconds over total accounted wall seconds "
    "(this attempt)",
)
_BADPUT_META = (
    names.BADPUT_SECONDS_TOTAL,
    "Non-productive wall seconds, by cause (goodput ledger)",
)
_BOTTLENECK_META = (
    names.BOTTLENECK,
    "One-hot per-window bottleneck classification "
    "(input/compute/comm/host bound)",
)
_REWORK_META = (
    names.REWORK_STEPS_TOTAL,
    "Steps re-executed after a restart (restored step -> pre-crash "
    "high-water mark)",
)
_WINDOW_RATIO_META = (
    names.GOODPUT_WINDOW_RATIO,
    "Good share of the last classifier window's wall clock "
    "(1 - badput/wall; badput = input waits, compiles, checkpoints) "
    "— the live SLO burn-rate signal",
)


def _default_host_id() -> int:
    try:
        from bigdl_tpu.config import config

        return int(config.process_id)
    except Exception:  # noqa: BLE001 — the ledger must never fail bring-up
        return 0


def _attempt_from_env() -> int:
    try:
        from bigdl_tpu.config import refresh_from_env

        return int(refresh_from_env().elastic_attempt)
    except Exception:  # noqa: BLE001 — the ledger must never fail bring-up
        return 0


class NullLedger:
    """No-op ledger with the full :class:`GoodputLedger` surface — the
    disabled fast path (shared instance, no clock reads)."""

    __slots__ = ()
    enabled = False
    high_water = 0

    def record(self, kind, start_perf, dur_s, step=None, **attrs):
        pass

    def note_host_seconds(self, seconds):
        pass

    def set_comm_bytes_per_step(self, nbytes):
        pass

    def set_exposed_comm_bytes_per_step(self, nbytes):
        pass

    def set_high_water(self, step):
        pass

    def stamp_resume(self, restored_step=None):
        return 0

    def live_ratio(self):
        return None

    def publish(self, registry=None):
        pass

    def flush(self):
        return None

    def close(self):
        pass

    def records(self):
        return []


NULL_LEDGER = NullLedger()


class GoodputLedger:
    """Recording ledger bound to one output directory + attempt."""

    enabled = True

    def __init__(self, directory: Optional[str], host_id: int = None,
                 attempt: int = None):
        self.host_id = (_default_host_id() if host_id is None
                        else int(host_id))
        self.attempt = (_attempt_from_env() if attempt is None
                        else int(attempt))
        self.pid = os.getpid()
        self.directory = directory
        self.path = None
        if directory:
            os.makedirs(directory, exist_ok=True)
            self.path = os.path.join(
                directory,
                f"goodput.h{self.host_id}.{self.pid}.a{self.attempt}.jsonl")
        self._lock = threading.Lock()
        # wall + perf anchors, exactly like the tracer: records carry
        # wall time so cross-attempt/host aggregation has one axis
        self._epoch_wall = time.time()
        self._epoch_perf = time.perf_counter()
        self._records: List[dict] = []
        self._unflushed: List[dict] = []
        self.high_water = 0          # rework watermark (pre-crash max step)
        self._max_step_seen = 0
        self._saw_step = False
        self.comm_bytes_per_step = 0.0
        # bucketed overlap (ISSUE 11): the share of the static comm
        # budget NOT hidden under backward; None = no overlap model,
        # the classifier then charges the full budget
        self.exposed_comm_bytes_per_step = None
        # running productive/badput seconds — the O(1) live goodput
        # ratio the /healthz endpoint and the alert engine read between
        # full classifications (rework excluded: replay is badput)
        self._productive_s = 0.0
        self._badput_s = 0.0
        # windowed bottleneck classifier accumulators
        self._win_step_s = 0.0
        self._win_wait_s = 0.0
        self._win_host_s = 0.0
        self._win_badput_s = 0.0
        self._win_wall0 = self._epoch_perf
        self._win_steps = 0
        self._win_first_step = None
        self._published_badput: Dict[str, float] = {}
        self._append({"kind": "attempt_start", "wall": self._epoch_wall,
                      "start_perf": self._epoch_perf})

    # ------------------------------------------------------------ internals
    def _wall(self, perf_t: float) -> float:
        return self._epoch_wall + (perf_t - self._epoch_perf)

    def _append(self, rec: dict):
        rec.setdefault("host", self.host_id)
        rec.setdefault("pid", self.pid)
        rec.setdefault("attempt", self.attempt)
        with self._lock:
            self._records.append(rec)
            self._unflushed.append(rec)

    # ------------------------------------------------------------------ API
    def record(self, kind: str, start_perf: float, dur_s: float,
               step: Optional[int] = None, **attrs):
        """Account one wall-clock interval from a ``perf_counter()``
        start + duration (the driver already holds both at every span
        boundary — no extra clock reads on the hot path)."""
        if kind not in PRIORITY:
            raise ValueError(f"unknown goodput cause {kind!r}; "
                             f"one of {CAUSES}")
        if kind == "step":
            if not self._saw_step:
                # everything from ledger birth to the first dispatched
                # step is startup badput (minus whatever more specific
                # intervals — compile, restore — the classifier carves
                # out of the window)
                self._saw_step = True
                startup_s = max(0.0, self._wall(start_perf)
                                - self._epoch_wall)
                self._append({"kind": "startup", "wall": self._epoch_wall,
                              "dur_s": round(startup_s, 9)})
                self._badput_s += startup_s
                self._win_badput_s += startup_s
            if step is not None and step <= self.high_water:
                kind = "rework"
            if step is not None:
                self._max_step_seen = max(self._max_step_seen, int(step))
        rec = {"kind": kind, "wall": self._wall(start_perf),
               "dur_s": round(float(dur_s), 9)}
        if step is not None:
            rec["step"] = int(step)
        if attrs:
            rec["attrs"] = attrs
        self._append(rec)
        if kind == "step":
            self._productive_s += float(dur_s)
        else:
            # every non-step cause — waits, compiles, checkpoints,
            # eval, backoff, rework replay — burns the live budget
            self._badput_s += float(dur_s)
        if kind in ("step", "rework"):
            self._win_step_s += float(dur_s)
            self._win_steps += 1
            if self._win_first_step is None:
                self._win_first_step = step
            self._maybe_window_tick(step)
        elif kind == "data_wait":
            self._win_wait_s += float(dur_s)
            self._win_badput_s += float(dur_s)
        else:
            self._win_badput_s += float(dur_s)

    def note_host_seconds(self, seconds: float):
        """Driver-side per-step overhead (batch prep + device_put +
        dispatch bookkeeping) — feeds the ``host_bound`` share of the
        window classifier without becoming a wall-accounting cause (in
        pipelined steady state it overlaps device compute)."""
        self._win_host_s += max(0.0, float(seconds))

    def set_comm_bytes_per_step(self, nbytes: float):
        """Static per-step collective wire bytes (the DistriOptimizer
        footprint total) — the comm-seconds estimate is
        ``bytes / (BIGDL_WIRE_GBPS * 1e9)``."""
        self.comm_bytes_per_step = float(nbytes)

    def set_exposed_comm_bytes_per_step(self, nbytes):
        """Bucketed-overlap model (ISSUE 11): with K exchange buckets,
        the first K-1 launches ride under the remaining backward — only
        this many bytes are EXPOSED wall time.  The window classifier's
        comm-seconds estimate then uses the exposed bytes, so hiding
        the wire actually moves the ``comm_bound`` verdict.  ``None``
        disables the model (monolithic exchange: everything exposed)."""
        self.exposed_comm_bytes_per_step = (
            None if nbytes is None else float(nbytes))

    def set_high_water(self, step: int):
        """Steps at or below this mark recorded from now on are
        ``rework`` (re-execution after a restart)."""
        self.high_water = max(self.high_water, int(step))

    def stamp_resume(self, restored_step: Optional[int] = None) -> int:
        """Called by the elastic resume paths after a checkpoint load:
        stamp the prior run's max step (from earlier attempts' shards
        in the ledger directory, and from this process's own records
        for the in-process retry path) as the rework high-water mark."""
        hw = self._max_step_seen
        if self.directory:
            try:
                hw = max(hw, prior_high_water(self.directory))
            except OSError:
                pass
        if hw:
            self.set_high_water(hw)
        self._append({"kind": "resume", "wall": time.time(),
                      "restored_step": restored_step,
                      "high_water": self.high_water})
        if hw:
            log.info("goodput: resume at step %s with pre-crash "
                     "high-water mark %d — replayed steps count as "
                     "rework badput", restored_step, self.high_water)
        return self.high_water

    def live_ratio(self) -> Optional[float]:
        """Cheap running goodput ratio for ``/healthz`` and the alert
        engine — O(1), no boundary sweep.  Two live bounds exist:
        ``productive/elapsed`` over-counts under async pipelining (a
        dispatch→resolve step span absorbs the next batch's input
        wait), while ``1 - badput/elapsed`` over-counts unattributed
        gaps — so the tighter of the two is served.  The exact
        boundary-sweep classification still happens at publish/flush
        time (too expensive per scrape)."""
        elapsed = time.time() - self._epoch_wall
        if elapsed <= 0:
            return None
        bound_productive = self._productive_s / elapsed
        bound_badput = 1.0 - self._badput_s / elapsed
        return max(0.0, min(1.0, bound_productive, bound_badput))

    def records(self) -> List[dict]:
        with self._lock:
            return list(self._records)

    # ----------------------------------------------------- window classifier
    def _maybe_window_tick(self, step):
        from bigdl_tpu.config import config

        window = config.obs.goodput_window
        if window <= 0 or self._win_steps < window:
            return
        step_s, wait_s = self._win_step_s, self._win_wait_s
        host_s, n = self._win_host_s, self._win_steps
        badput_s = self._win_badput_s
        first = self._win_first_step
        now_perf = time.perf_counter()
        win_wall = now_perf - self._win_wall0
        self._win_wall0 = now_perf
        self._win_step_s = self._win_wait_s = self._win_host_s = 0.0
        self._win_badput_s = 0.0
        self._win_steps = 0
        self._win_first_step = None
        comm_s = 0.0
        # the overlap model narrows the comm estimate to the EXPOSED
        # bytes (what backward cannot hide); monolithic runs charge the
        # full static budget as before
        comm_bytes = (self.comm_bytes_per_step
                      if self.exposed_comm_bytes_per_step is None
                      else self.exposed_comm_bytes_per_step)
        if config.obs.wire_gbps > 0 and comm_bytes:
            comm_s = n * comm_bytes / (config.obs.wire_gbps * 1e9)
        verdict = classify_bottleneck(step_s, wait_s, comm_s, host_s)
        from bigdl_tpu import obs

        registry = obs.get_registry()
        gauge = registry.gauge(*_BOTTLENECK_META, labels=("class",))
        for label in BOTTLENECKS:
            gauge.labels(**{"class": label}).set(
                1.0 if label == verdict["label"] else 0.0)
        # live SLO signals for the alert engine and /healthz: the
        # window's own good share of wall clock (recovers the moment a
        # starved window ends) and the cheap cumulative ratio.  NOT
        # step/(step+wait): under async pipelining the dispatch→resolve
        # step span absorbs the next batch's wait, so that quotient
        # floors near 0.5 in a fully starved run — 1 - badput/wall
        # measures what actually burned the window
        if win_wall > 0:
            registry.gauge(*_WINDOW_RATIO_META).set(
                round(max(0.0, min(1.0, 1.0 - badput_s / win_wall)), 6))
        lr = self.live_ratio()
        if lr is not None:
            registry.gauge(*_RATIO_META).set(round(lr, 6))
        tracer = obs.get_tracer()
        if tracer.enabled:
            tracer.event("goodput.bottleneck", window=n,
                         first_step=first, step=step, **verdict)
            # HBM counter track rides the same periodic host-side hook
            # (satellite: per-device peak bytes over time in the trace)
            from bigdl_tpu.obs.runtime import all_device_memory_stats

            hbm = all_device_memory_stats()
            if hbm:
                tracer.counter("hbm_peak_bytes", **{
                    f"d{i}": s.get("peak_bytes_in_use", 0)
                    for i, s in hbm.items()})
        # the alert engine rides the same tick: pure host arithmetic
        # over the registry, zero new device syncs (obs/alerts.py)
        from bigdl_tpu.obs import alerts

        alerts.maybe_evaluate()

    # -------------------------------------------------------------- export
    def publish(self, registry=None):
        """Mirror this attempt's classification into the registry:
        the ``bigdl_goodput_ratio`` gauge, ``bigdl_badput_seconds_total
        {cause}`` (monotonic — repeated publishes only add deltas) and
        ``bigdl_rework_steps_total``."""
        if registry is None:
            from bigdl_tpu import obs

            registry = obs.get_registry()
        summary = classify_records(self.records())
        if summary["total_s"] <= 0:
            return summary
        registry.gauge(*_RATIO_META).set(summary["goodput_ratio"])
        badput = registry.counter(*_BADPUT_META, labels=("cause",))
        for cause, secs in summary["badput_s"].items():
            prev = self._published_badput.get(cause, 0.0)
            if secs > prev:
                badput.labels(cause=cause).inc(secs - prev)
                self._published_badput[cause] = secs
        if summary["rework_steps"]:
            prev = self._published_badput.get("__rework_steps__", 0)
            delta = summary["rework_steps"] - prev
            if delta > 0:
                registry.counter(*_REWORK_META).inc(delta)
                self._published_badput["__rework_steps__"] = \
                    summary["rework_steps"]
        return summary

    def flush(self):
        """Append the unflushed records to the JSONL shard (crash-safe:
        at most the torn last line is lost, which the readers skip)."""
        if not self.path:
            return None
        with self._lock:
            pending, self._unflushed = self._unflushed, []
        if pending:
            try:
                with open(self.path, "a", encoding="utf-8") as fh:
                    for rec in pending:
                        fh.write(json.dumps(rec, default=str) + "\n")
            except OSError as e:  # a full shared volume must not kill
                log.warning("goodput shard write failed: %s", e)
        return self.path

    def close(self):
        self.flush()


# ------------------------------------------------------------ classification
def classify_bottleneck(step_s: float, wait_s: float, comm_s: float = 0.0,
                        host_s: float = 0.0, *,
                        input_threshold: float = 0.3,
                        comm_threshold: float = 0.4,
                        host_threshold: float = 0.3) -> dict:
    """Attribute one window to input / comm / host / compute.

    ``step_s`` is observed device-step wall time, ``wait_s`` the input
    stall next to it; ``comm_s`` the *estimated* collective share of
    ``step_s`` (static wire bytes / assumed bandwidth) and ``host_s``
    the driver-overhead share.  Precedence mirrors how you would fix
    them: a starved input pipeline masks everything else, then the
    wire, then the driver; what remains is the chip."""
    total = step_s + wait_s
    input_frac = wait_s / total if total > 0 else 0.0
    comm_frac = min(1.0, comm_s / step_s) if step_s > 0 else 0.0
    host_frac = min(1.0, host_s / step_s) if step_s > 0 else 0.0
    if total <= 0:
        label = "compute_bound"
    elif input_frac >= input_threshold:
        label = "input_bound"
    elif comm_frac >= comm_threshold:
        label = "comm_bound"
    elif host_frac >= host_threshold:
        label = "host_bound"
    else:
        label = "compute_bound"
    return {"label": label,
            "input_fraction": round(input_frac, 4),
            "comm_fraction": round(comm_frac, 4),
            "host_fraction": round(host_frac, 4),
            "step_s": round(step_s, 6), "wait_s": round(wait_s, 6)}


def classify_records(records: List[dict]) -> dict:
    """Fold one shard's interval records into seconds-by-cause.

    A boundary sweep over the (possibly overlapping, possibly nested)
    intervals: each elementary segment between consecutive interval
    edges is charged to the highest-:data:`PRIORITY` cause covering it,
    so the first step's embedded compile counts as ``compile`` (not
    double-counted as step) and a restore inside the startup window
    counts as ``checkpoint_restore``.  Wall time inside the attempt
    span covered by NO interval lands in ``unknown_s`` — visible, never
    silently productive.  Marker records (``attempt_start``/``resume``)
    extend the span but carry no duration."""
    intervals = []
    span_lo, span_hi = None, None
    rework_steps = set()
    for rec in records:
        wall = rec.get("wall")
        if wall is None:
            continue
        wall = float(wall)
        dur = float(rec.get("dur_s", 0.0) or 0.0)
        kind = rec.get("kind")
        lo, hi = wall, wall + max(0.0, dur)
        span_lo = lo if span_lo is None else min(span_lo, lo)
        span_hi = hi if span_hi is None else max(span_hi, hi)
        if kind in PRIORITY and dur > 0:
            intervals.append((lo, hi, kind))
            if kind == "rework" and rec.get("step") is not None:
                rework_steps.add((rec.get("host", 0), int(rec["step"])))
    seconds = {c: 0.0 for c in CAUSES}
    steps = sum(1 for rec in records if rec.get("kind") == "step")
    if span_lo is None:
        return {"seconds": seconds, "total_s": 0.0, "productive_s": 0.0,
                "badput_s": {}, "unknown_s": 0.0, "goodput_ratio": None,
                "steps": 0, "rework_steps": 0}
    # boundary sweep: O(edges * intervals) — offline analysis over at
    # most a few thousand records per shard
    edges = sorted({e for lo, hi, _ in intervals for e in (lo, hi)}
                   | {span_lo, span_hi})
    covered = 0.0
    for a, b in zip(edges, edges[1:]):
        if b <= a:
            continue
        best = None
        for lo, hi, kind in intervals:
            if lo <= a and hi >= b:
                if best is None or PRIORITY[kind] > PRIORITY[best]:
                    best = kind
        if best is not None:
            seconds[best] += b - a
            covered += b - a
    total = span_hi - span_lo
    unknown = max(0.0, total - covered)
    productive = seconds["step"]
    badput = {c: round(s, 6) for c, s in seconds.items()
              if c != "step" and s > 0}
    return {
        "seconds": {c: round(s, 6) for c, s in seconds.items()},
        "total_s": round(total, 6),
        "productive_s": round(productive, 6),
        "badput_s": badput,
        "unknown_s": round(unknown, 6),
        "goodput_ratio": (productive / total) if total > 0 else None,
        "steps": steps,
        "rework_steps": len(rework_steps),
    }


# ------------------------------------------------------------ shard reading
def read_ledger_shards(directory: str) -> List[dict]:
    """Every ``goodput.*.jsonl`` shard under ``directory`` —
    ``[{path, host, pid, attempt, records}]``, torn tail lines skipped
    (a crashed attempt's partial shard still aggregates)."""
    shards = []
    if not directory or not os.path.isdir(directory):
        return shards
    for fn in sorted(os.listdir(directory)):
        if not (fn.startswith("goodput.") and fn.endswith(".jsonl")):
            continue
        recs = []
        with open(os.path.join(directory, fn), encoding="utf-8") as fh:
            for line in fh:
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except json.JSONDecodeError:
                    continue  # torn tail of a crashed writer
                if isinstance(rec, dict):
                    recs.append(rec)
        if recs:
            first = recs[0]
            shards.append({"path": os.path.join(directory, fn),
                           "host": int(first.get("host", 0)),
                           "pid": int(first.get("pid", 0)),
                           "attempt": int(first.get("attempt", 0)),
                           "records": recs})
    return shards


def prior_high_water(directory: str) -> int:
    """The max step any ledger shard in ``directory`` ever recorded —
    the pre-crash high-water mark a resumed attempt reworks up to."""
    hw = 0
    for shard in read_ledger_shards(directory):
        for rec in shard["records"]:
            if rec.get("kind") in ("step", "rework") \
                    and rec.get("step") is not None:
                hw = max(hw, int(rec["step"]))
    return hw


def aggregate_goodput(directory: str) -> Optional[dict]:
    """Cross-attempt, cross-host goodput: classify every shard
    independently (each has its own wall-clock span, so two attempts'
    spans never overlap-cancel) and sum the seconds.  Returns None when
    the directory holds no ledger shards."""
    shards = read_ledger_shards(directory)
    if not shards:
        return None
    seconds = {c: 0.0 for c in CAUSES}
    total = productive = unknown = 0.0
    steps = rework_steps = 0
    per_attempt = []
    for shard in shards:
        s = classify_records(shard["records"])
        for c in CAUSES:
            seconds[c] += s["seconds"].get(c, 0.0)
        total += s["total_s"]
        productive += s["productive_s"]
        unknown += s["unknown_s"]
        steps += s["steps"]
        rework_steps += s["rework_steps"]
        per_attempt.append({
            "host": shard["host"], "attempt": shard["attempt"],
            "pid": shard["pid"], "total_s": s["total_s"],
            "goodput_ratio": s["goodput_ratio"], "steps": s["steps"]})
    badput = {c: round(s, 6) for c, s in seconds.items()
              if c != "step" and s > 0}
    return {
        "attempts": len({(s["host"], s["attempt"], s["pid"])
                         for s in shards}),
        "hosts": sorted({s["host"] for s in shards}),
        "total_s": round(total, 6),
        "productive_s": round(productive, 6),
        "badput_s": badput,
        "unknown_s": round(unknown, 6),
        "goodput_ratio": (productive / total) if total > 0 else None,
        "steps": steps,
        "rework_steps": rework_steps,
        "per_attempt": per_attempt,
    }


# ----------------------------------------------------------------- singleton
_lock = threading.Lock()
_ledger = NULL_LEDGER
_ledger_key = None


def get_ledger():
    """The process ledger — a recording :class:`GoodputLedger` when
    observability is active (shard under ``metrics_dir``, falling back
    to ``trace_dir``; in-memory only when neither is set), else the
    shared :data:`NULL_LEDGER`.  Rebuilt when the directory changes."""
    global _ledger, _ledger_key
    from bigdl_tpu.config import refresh_from_env

    cfg = refresh_from_env().obs
    key = (cfg.active, cfg.metrics_dir or cfg.trace_dir,
           _attempt_from_env())
    with _lock:
        if key != _ledger_key:
            if _ledger is not NULL_LEDGER:
                try:
                    _ledger.close()
                except Exception:  # noqa: BLE001 — half-torn test dirs
                    pass
            _ledger_key = key
            _ledger = (GoodputLedger(key[1], attempt=key[2])
                       if key[0] else NULL_LEDGER)
        return _ledger


def reset_ledger():
    """Test hook: close and drop the singleton; the next
    :func:`get_ledger` rebuilds from the live config."""
    global _ledger, _ledger_key
    with _lock:
        if _ledger is not NULL_LEDGER:
            try:
                _ledger.close()
            except Exception:  # noqa: BLE001
                pass
        _ledger = NULL_LEDGER
        _ledger_key = None
