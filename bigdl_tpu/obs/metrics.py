"""Labeled Counter / Gauge / Histogram registry.

The reference aggregated driver metrics through Spark accumulators and
printed averages («bigdl»/optim/Metrics.scala); this registry is the
rebuild's production surface for the same numbers and everything new
(resilience counters, checkpoint writes, compile events):

* three instrument kinds — monotonic :class:`Counter`, settable
  :class:`Gauge`, bucketed :class:`Histogram` — each optionally
  labeled (one family, lazily-created children per label combination);
* Prometheus text exposition (:meth:`MetricsRegistry.to_prometheus`)
  scrape-able or snapshot-to-file, plus JSON-able
  :meth:`MetricsRegistry.snapshot` appended as JSONL for log pipelines;
* thread-safe (the background checkpoint writer counts too), no
  third-party client library.

``optim/metrics.py::Metrics`` delegates here — the reference's phase
timers become one ``bigdl_phase_seconds`` histogram family labeled by
phase, keeping the exact Scala metric names as label values.
"""

from __future__ import annotations

import bisect
import json
import os
import re
import threading
import time
from typing import Dict, Optional, Sequence, Tuple

# driver-phase oriented defaults: sub-ms host work up to multi-second
# compiles/checkpoints
DEFAULT_BUCKETS = (0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1,
                   0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0)


def _strict() -> bool:
    """Is the strict metric registry on (``BIGDL_OBS_STRICT=1`` /
    ``config.obs.strict``)?  Read at call time so tests and harnesses
    can toggle it without rebuilding the registry."""
    try:
        from bigdl_tpu.config import refresh_from_env

        return bool(refresh_from_env().obs.strict)
    except Exception:  # noqa: BLE001 — metrics must never sink the host
        return False


def _declared_spec(name: str):
    """The obs/names.py spec for a ``bigdl_*`` family (None when the
    name is foreign — private registries may mint what they like)."""
    if not name.startswith("bigdl_"):
        return None
    from bigdl_tpu.obs import names as _names

    return _names.REGISTRY.get(name)


def _escape(v) -> str:
    return (str(v).replace("\\", r"\\").replace("\n", r"\n")
            .replace('"', r'\"'))


def _escape_help(v: str) -> str:
    """HELP-line escaping (exposition format: backslash and newline
    only — quotes stay literal on comment lines)."""
    return str(v).replace("\\", r"\\").replace("\n", r"\n")


def _fmt(v: float) -> str:
    """Prometheus sample value: integers without a trailing .0 noise.
    Non-finite values render as the exposition-format spellings
    (``NaN`` / ``+Inf`` / ``-Inf``) instead of crashing the scrape."""
    f = float(v)
    if f != f:
        return "NaN"
    if f in (float("inf"), float("-inf")):
        return "+Inf" if f > 0 else "-Inf"
    return repr(int(f)) if f == int(f) else repr(f)


class Counter:
    """Monotonic counter child."""

    kind = "counter"

    def __init__(self, lock: threading.Lock):
        self._lock = lock
        self.value = 0.0

    def inc(self, amount: float = 1.0):
        if amount < 0:
            raise ValueError(f"counters only go up, got {amount}")
        with self._lock:
            self.value += amount
        return self

    def _zero(self):
        self.value = 0.0


class Gauge:
    """Settable gauge child."""

    kind = "gauge"

    def __init__(self, lock: threading.Lock):
        self._lock = lock
        self.value = 0.0

    def set(self, value: float):
        with self._lock:
            self.value = float(value)
        return self

    def inc(self, amount: float = 1.0):
        with self._lock:
            self.value += amount
        return self

    def _zero(self):
        self.value = 0.0


class Histogram:
    """Bucketed histogram child (per-bucket counts; cumulative form is
    produced at exposition time)."""

    kind = "histogram"

    def __init__(self, lock: threading.Lock,
                 buckets: Sequence[float] = DEFAULT_BUCKETS):
        self._lock = lock
        self.bounds = tuple(sorted(float(b) for b in buckets))
        self.bucket_counts = [0] * (len(self.bounds) + 1)  # last = +Inf
        self.count = 0
        self.sum = 0.0
        # bucket index -> (labels, value, unix ts): the most recent
        # exemplar per bucket, exposed in OpenMetrics ``# {...} v ts``
        # syntax so a histogram sample links back to a concrete trace
        self.exemplars: Dict[int, tuple] = {}

    def observe(self, value: float, exemplar: Optional[dict] = None):
        """Record ``value``; ``exemplar`` (a small ``{label: value}``
        dict, e.g. ``{"trace_id": ...}``) attaches to the bucket the
        observation lands in, newest-wins."""
        v = float(value)
        i = bisect.bisect_left(self.bounds, v)
        with self._lock:
            self.bucket_counts[i] += 1
            self.count += 1
            self.sum += v
            if exemplar:
                self.exemplars[i] = (dict(exemplar), v, time.time())
        return self

    def exemplar_items(self) -> Dict[int, tuple]:
        with self._lock:
            return dict(self.exemplars)

    @property
    def mean(self) -> float:
        return self.sum / self.count if self.count else 0.0

    def snapshot_state(self):
        """One-lock consistent read: ``(bucket_counts, count, sum)``
        from a single lock acquisition, so a scrape racing concurrent
        ``observe()`` calls can never expose a ``_sum``/``_count`` pair
        that disagrees with the bucket counts (the +Inf cumulative
        bucket always equals ``_count`` within one sample)."""
        with self._lock:
            return list(self.bucket_counts), self.count, self.sum

    def _cumulative_from(self, counts):
        out, acc = [], 0
        for b, c in zip(self.bounds + (float("inf"),), counts):
            acc += c
            out.append((b, acc))
        return out

    def cumulative(self):
        """[(upper_bound, cumulative_count), ...] ending with +Inf."""
        counts, _, _ = self.snapshot_state()
        return self._cumulative_from(counts)

    def _zero(self):
        self.bucket_counts = [0] * (len(self.bounds) + 1)
        self.count = 0
        self.sum = 0.0
        self.exemplars = {}


_KINDS = {"counter": Counter, "gauge": Gauge, "histogram": Histogram}


class _Family:
    """One named metric family: fixed label names, lazily-created
    children per label-value combination."""

    def __init__(self, name: str, help: str, kind: str,
                 labelnames: Tuple[str, ...] = (),
                 buckets: Optional[Sequence[float]] = None,
                 max_children: Optional[int] = None):
        self.name = name
        self.help = help
        self.kind = kind
        self.labelnames = tuple(labelnames)
        self.buckets = tuple(buckets) if buckets else DEFAULT_BUCKETS
        # label-cardinality ceiling from the obs/names.py spec —
        # enforced only under BIGDL_OBS_STRICT so a production fleet
        # degrades to an over-wide family instead of crashing
        self.max_children = max_children
        self._lock = threading.Lock()
        self._children: Dict[tuple, object] = {}

    def labels(self, **labelvalues):
        if set(labelvalues) != set(self.labelnames):
            raise ValueError(
                f"{self.name}: labels {sorted(labelvalues)} do not match "
                f"declared {sorted(self.labelnames)}")
        key = tuple(str(labelvalues[n]) for n in self.labelnames)
        with self._lock:
            child = self._children.get(key)
            if child is None:
                if self.max_children is not None \
                        and len(self._children) >= self.max_children \
                        and _strict():
                    raise ValueError(
                        f"{self.name}: label cardinality ceiling "
                        f"{self.max_children} exceeded (new combination "
                        f"{key!r}); an unbounded label eats the scrape "
                        "surface — raise the ceiling in "
                        "bigdl_tpu/obs/names.py only if the fan-out is "
                        "really bounded")
                cls = _KINDS[self.kind]
                child = (cls(self._lock, self.buckets)
                         if self.kind == "histogram" else cls(self._lock))
                self._children[key] = child
            return child

    # label-less convenience: family acts as its single child
    def _solo(self):
        if self.labelnames:
            raise ValueError(
                f"{self.name} is labeled {self.labelnames}; use .labels()")
        return self.labels()

    def inc(self, amount: float = 1.0):
        return self._solo().inc(amount)

    def set(self, value: float):
        return self._solo().set(value)

    def observe(self, value: float, exemplar: Optional[dict] = None):
        return self._solo().observe(value, exemplar=exemplar)

    def child_items(self):
        with self._lock:
            return list(self._children.items())

    def clear(self):
        """Zero every child (test/reset hook; children stay registered
        so held references keep working)."""
        for _, child in self.child_items():
            with self._lock:
                child._zero()


class MetricsRegistry:
    """Named families + exposition.  Registration is idempotent for an
    identical (kind, labelnames) signature and loud for a conflicting
    one."""

    def __init__(self):
        self._lock = threading.Lock()
        self._families: Dict[str, _Family] = {}

    def _family(self, name, help, kind, labels=(), buckets=None) -> _Family:
        with self._lock:
            fam = self._families.get(name)
            if fam is not None:
                if fam.kind != kind or fam.labelnames != tuple(labels):
                    raise ValueError(
                        f"metric {name!r} already registered as "
                        f"{fam.kind}{fam.labelnames}, not {kind}{tuple(labels)}")
                return fam
            spec = _declared_spec(name)
            if spec is None and name.startswith("bigdl_") and _strict():
                raise ValueError(
                    f"metric {name!r} is not declared in "
                    "bigdl_tpu/obs/names.py and BIGDL_OBS_STRICT is on; "
                    "declare it there (kind, labels, cardinality "
                    "ceiling, doc) so the registry stays the single "
                    "source of truth")
            if spec is not None and _strict() and (
                    spec.kind != kind
                    or set(spec.labels) != set(labels)):
                raise ValueError(
                    f"metric {name!r} declared as {spec.kind}"
                    f"{spec.labels} in bigdl_tpu/obs/names.py but "
                    f"registered as {kind}{tuple(labels)}")
            fam = _Family(name, help, kind, tuple(labels), buckets,
                          max_children=(spec.cardinality
                                        if spec is not None else None))
            self._families[name] = fam
            return fam

    def counter(self, name: str, help: str = "", labels=()) -> _Family:
        return self._family(name, help, "counter", labels)

    def gauge(self, name: str, help: str = "", labels=()) -> _Family:
        return self._family(name, help, "gauge", labels)

    def histogram(self, name: str, help: str = "", labels=(),
                  buckets: Optional[Sequence[float]] = None) -> _Family:
        return self._family(name, help, "histogram", labels, buckets)

    def families(self):
        with self._lock:
            return list(self._families.values())

    # -------------------------------------------------------------- export
    def to_prometheus(self) -> str:
        """Prometheus text exposition format 0.0.4.

        Every family gets BOTH a ``# HELP`` and a ``# TYPE`` line (a
        help-less registration falls back to its own name): real
        scrapers reject or mislabel families exposed bare, and the
        parity tests hold the reader (:func:`parse_prometheus`) and
        this writer to the same contract."""
        lines = []
        for fam in sorted(self.families(), key=lambda f: f.name):
            lines.append(
                f"# HELP {fam.name} {_escape_help(fam.help or fam.name)}")
            lines.append(f"# TYPE {fam.name} {fam.kind}")
            for key, child in sorted(fam.child_items()):
                pairs = [f'{n}="{_escape(v)}"'
                         for n, v in zip(fam.labelnames, key)]
                base = "{" + ",".join(pairs) + "}" if pairs else ""
                if fam.kind == "histogram":
                    # one consistent read per scrape: buckets, _sum and
                    # _count come from the SAME locked snapshot (a
                    # concurrent add() can otherwise land between the
                    # bucket copy and the sum/count reads)
                    counts, count, total = child.snapshot_state()
                    exs = child.exemplar_items()
                    for i, (bound, acc) in enumerate(
                            child._cumulative_from(counts)):
                        le = "+Inf" if bound == float("inf") else _fmt(bound)
                        bpairs = pairs + [f'le="{le}"']
                        line = (f"{fam.name}_bucket"
                                f"{{{','.join(bpairs)}}} {acc}")
                        ex = exs.get(i)
                        if ex is not None:
                            # OpenMetrics exemplar: ``# {labels} v ts``
                            exl, exv, exts = ex
                            body = ",".join(f'{k}="{_escape(v)}"'
                                            for k, v in exl.items())
                            line += (f" # {{{body}}} {_fmt(exv)} "
                                     f"{exts:.3f}")
                        lines.append(line)
                    lines.append(f"{fam.name}_sum{base} {_fmt(total)}")
                    lines.append(f"{fam.name}_count{base} {count}")
                else:
                    lines.append(f"{fam.name}{base} {_fmt(child.value)}")
        return "\n".join(lines) + ("\n" if lines else "")

    def snapshot(self) -> dict:
        """JSON-able snapshot of every family."""
        metrics = {}
        for fam in self.families():
            samples = []
            for key, child in sorted(fam.child_items()):
                labels = dict(zip(fam.labelnames, key))
                if fam.kind == "histogram":
                    counts, count, total = child.snapshot_state()
                    samples.append(
                        {"labels": labels, "count": count,
                         "sum": total,
                         "buckets": [
                             ["+Inf" if b == float("inf") else b, c]
                             for b, c in child._cumulative_from(counts)]})
                else:
                    samples.append({"labels": labels, "value": child.value})
            metrics[fam.name] = {"type": fam.kind, "help": fam.help,
                                 "samples": samples}
        return {"ts": time.time(), "metrics": metrics}

    def write_snapshot(self, directory: str, extra_registries=(),
                       host_id: int = None):
        """Write ``metrics.h<host>.<pid>.prom`` (atomic replace — always
        a complete, parseable exposition) and append one JSON line to
        ``metrics.h<host>.<pid>.jsonl``.  ``extra_registries`` are
        concatenated into the same exposition (e.g. an optimizer's
        private phase-timer registry).  The host rank in the stem keeps
        N hosts writing one shared metrics volume collision-free."""
        if host_id is None:
            from bigdl_tpu.obs.trace import _default_host_id

            host_id = _default_host_id()
        os.makedirs(directory, exist_ok=True)
        pid = os.getpid()
        stem = f"metrics.h{host_id}.{pid}"
        prom_path = os.path.join(directory, stem + ".prom")
        text = self.to_prometheus() + "".join(
            r.to_prometheus() for r in extra_registries)
        tmp = prom_path + ".tmp"
        with open(tmp, "w", encoding="utf-8") as fh:
            fh.write(text)
        os.replace(tmp, prom_path)
        jsonl_path = os.path.join(directory, stem + ".jsonl")
        snap = self.snapshot()
        for r in extra_registries:
            snap["metrics"].update(r.snapshot()["metrics"])
        snap["host"] = host_id
        snap["pid"] = pid
        with open(jsonl_path, "a", encoding="utf-8") as fh:
            fh.write(json.dumps(snap, default=str) + "\n")
        return {"prom": prom_path, "jsonl": jsonl_path}

    def reset(self):
        """Drop every family (test hook)."""
        with self._lock:
            self._families.clear()


# ------------------------------------------------------------- reader
# one exposition sample line: name, optional {labels}, value
_SAMPLE_RE = re.compile(
    r'^([a-zA-Z_:][a-zA-Z0-9_:]*)(?:\{(.*)\})?\s+(\S+)$')
_LABEL_RE = re.compile(r'([a-zA-Z_][a-zA-Z0-9_]*)="((?:[^"\\]|\\.)*)"')
_UNESCAPE = {r"\\": "\\", r"\"": '"', r"\n": "\n"}


def _unescape_label(v: str) -> str:
    return re.sub(r'\\(.)', lambda m: _UNESCAPE.get(m.group(0),
                                                    m.group(1)), v)


def _parse_value(v: str) -> float:
    low = v.lower()
    if low in ("nan",):
        return float("nan")
    if low in ("+inf", "inf"):
        return float("inf")
    if low == "-inf":
        return float("-inf")
    return float(v)


_EXEMPLAR_RE = re.compile(r'^\{(.*)\}\s+(\S+)(?:\s+(\S+))?$')


def _parse_exemplar(tail: str) -> dict:
    """Parse the OpenMetrics exemplar tail ``{labels} value [ts]``."""
    m = _EXEMPLAR_RE.match(tail.strip())
    if not m:
        raise ValueError(f"bad exemplar: {tail!r}")
    labelbody, value, ts = m.groups()
    out = {"labels": {k: _unescape_label(v)
                      for k, v in _LABEL_RE.findall(labelbody or "")},
           "value": _parse_value(value)}
    if ts:
        out["ts"] = float(ts)
    return out


def parse_prometheus(text: str) -> dict:
    """Parse text exposition back into
    ``{"families": {name: {"type", "help"}}, "samples": [{"name",
    "labels", "value"}]}`` — the reader half of :meth:`to_prometheus`.

    This is what the fleet aggregator uses on a peer's ``/metrics``
    body and what the parity tests round-trip through; histogram
    ``_bucket``/``_sum``/``_count`` lines appear as their literal
    sample names.  Malformed lines raise — a scrape that parses must
    parse *completely* (silently-dropped samples are how dashboards
    lie)."""
    families: Dict[str, dict] = {}
    samples = []
    for raw in text.splitlines():
        line = raw.strip()
        if not line:
            continue
        if line.startswith("# HELP "):
            rest = line[len("# HELP "):].split(" ", 1)
            families.setdefault(rest[0], {})["help"] = \
                rest[1] if len(rest) > 1 else ""
            continue
        if line.startswith("# TYPE "):
            rest = line[len("# TYPE "):].split(" ", 1)
            families.setdefault(rest[0], {})["type"] = \
                rest[1] if len(rest) > 1 else ""
            continue
        if line.startswith("#"):
            continue
        m = _SAMPLE_RE.match(line)
        exemplar = None
        if not m and " # " in line:
            # OpenMetrics exemplar syntax: the sample proper, then
            # `` # {labels} value [ts]`` — split it off and parse both
            body, _, tail = line.partition(" # ")
            m = _SAMPLE_RE.match(body.strip())
            if m:
                exemplar = _parse_exemplar(tail)
        if not m:
            raise ValueError(f"bad exposition line: {line!r}")
        name, labelbody, value = m.groups()
        labels = {k: _unescape_label(v)
                  for k, v in _LABEL_RE.findall(labelbody or "")}
        entry = {"name": name, "labels": labels,
                 "value": _parse_value(value)}
        if exemplar is not None:
            entry["exemplar"] = exemplar
        samples.append(entry)
    return {"families": families, "samples": samples}


def _base_family(name: str, families: dict) -> str:
    """The family a sample line belongs to for HELP/TYPE grouping —
    histogram ``_bucket``/``_sum``/``_count`` samples group under the
    declared histogram family, everything else under itself."""
    for suffix in ("_bucket", "_sum", "_count"):
        if name.endswith(suffix):
            base = name[: -len(suffix)]
            if families.get(base, {}).get("type") == "histogram":
                return base
    return name


def render_exposition(parsed: dict) -> str:
    """Render :func:`parse_prometheus` output (or anything of the same
    shape) back to text exposition format — the writer half of the
    reader, used by the rollup tier to re-expose a policy-merged fleet
    of parsed scrapes as ONE ``/metrics`` body.

    Round-trip contract: ``parse_prometheus(render_exposition(p))``
    preserves every family type/help, every sample (name, labels,
    value) and every exemplar — so a root aggregator scraping a leaf
    rollup sees exactly what the leaf merged, bit for bit through
    :func:`_fmt`."""
    families = parsed.get("families") or {}
    by_family: Dict[str, list] = {}
    order: list = []
    for s in parsed.get("samples") or []:
        base = _base_family(s["name"], families)
        if base not in by_family:
            by_family[base] = []
            order.append(base)
        by_family[base].append(s)
    # families with declared type/help but no samples still expose
    # their header lines (a scraper learns the family exists)
    for name in families:
        if name not in by_family:
            by_family[name] = []
            order.append(name)
    lines = []
    for base in sorted(order):
        meta = families.get(base) or {}
        lines.append(
            f"# HELP {base} {_escape_help(meta.get('help') or base)}")
        lines.append(f"# TYPE {base} {meta.get('type') or 'untyped'}")
        for s in by_family[base]:
            pairs = [f'{k}="{_escape(v)}"'
                     for k, v in (s.get("labels") or {}).items()]
            body = "{" + ",".join(pairs) + "}" if pairs else ""
            line = f"{s['name']}{body} {_fmt(s['value'])}"
            ex = s.get("exemplar")
            if ex is not None:
                exl = ",".join(f'{k}="{_escape(v)}"'
                               for k, v in (ex.get("labels") or {}).items())
                line += f" # {{{exl}}} {_fmt(ex['value'])}"
                if ex.get("ts") is not None:
                    line += f" {float(ex['ts']):.3f}"
            lines.append(line)
    return "\n".join(lines) + ("\n" if lines else "")


def sample_value(parsed: dict, name: str, **labels) -> Optional[float]:
    """First sample named ``name`` whose labels contain ``labels`` (a
    convenience over :func:`parse_prometheus` output)."""
    for s in parsed["samples"]:
        if s["name"] == name and all(
                s["labels"].get(k) == str(v) for k, v in labels.items()):
            return s["value"]
    return None
