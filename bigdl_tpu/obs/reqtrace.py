"""Request-scoped distributed tracing with tail-based sampling.

The serving data plane's only latency signal used to be aggregate
histograms — when p99 regressed, nothing said whether a request lost
its time in the router queue, a retry after a 503 shed, a drain-handoff
replay, a preemption refold, prefill bucketing, or decode itself.  This
module is the Dapper-style fix, sized for the existing obs stack:

* :class:`RequestTraceContext` — one trace id (+ optional parent span
  and a force-keep flag) created at the first hop (router ``route()``,
  ``RouterServer``, or ``LMEngine.submit`` for in-process callers) and
  propagated across HTTP hops in the ``X-Bigdl-Trace`` header as
  ``<trace_id>:<parent>:<flags>``;
* :class:`ReqTraceCollector` — per-process buffer of lifecycle hop
  spans keyed by trace id.  Spans are **buffered, not emitted**, until
  the request completes; the completion point then makes the
  tail-sampling decision:

  - **keep always** when the request errored, retried, was preempted,
    was handed off, violated its SLO, or carries the forced-keep
    header flag (anomalies are exactly what tail sampling exists to
    catch);
  - otherwise **keep probabilistically** at ``BIGDL_REQTRACE_SAMPLE``,
    decided by a deterministic hash of the trace id so every host in a
    distributed topology keeps or drops the *same* traces without
    coordination.

  Kept spans are emitted through the ordinary ``obs/trace.py`` tracer
  (so ``obs/aggregate.py``'s clock-aligned Perfetto merge shows the
  cross-host request flow) and the completed trace is retained in a
  bounded ring served by ``/trace?request=<id>`` on the obs server.

``BIGDL_REQTRACE_SAMPLE=0`` (the default) disables the subsystem
entirely: no contexts are created, no buffers touched, and the decode
hot path (`LMEngine._step`) is byte-for-byte the untraced code — the
engine only marks admission/prefill/preemption/completion boundaries,
and only when a request actually carries a context.
"""

from __future__ import annotations

import collections
import hashlib
import threading
import time
import uuid
from typing import Dict, List, Optional, Tuple

#: the HTTP propagation header: ``<trace_id>:<parent_span>:<flags>``
TRACE_HEADER = "X-Bigdl-Trace"

#: sampling reasons a kept trace may carry (the label set of
#: ``bigdl_reqtrace_sampled_total``)
KEEP_REASONS = ("error", "retry", "preempt", "slo", "handoff", "forced",
                "sampled")


class RequestTraceContext:
    """One request's trace identity, cheap enough to ride every hop."""

    __slots__ = ("trace_id", "parent", "keep")

    def __init__(self, trace_id: str, parent: Optional[int] = None,
                 keep: bool = False):
        self.trace_id = str(trace_id)
        self.parent = parent
        self.keep = bool(keep)

    def to_header(self) -> str:
        parent = "" if self.parent is None else str(self.parent)
        flags = "k" if self.keep else ""
        return f"{self.trace_id}:{parent}:{flags}"

    @classmethod
    def from_header(cls, value: Optional[str]
                    ) -> Optional["RequestTraceContext"]:
        """Tolerant parse of the ``X-Bigdl-Trace`` header (None / a
        malformed value -> None — a bad trace header must never fail a
        request)."""
        if not value:
            return None
        parts = str(value).strip().split(":")
        tid = parts[0].strip()
        if not tid:
            return None
        parent = None
        if len(parts) > 1 and parts[1].strip():
            try:
                parent = int(parts[1])
            except ValueError:
                parent = None
        flags = parts[2] if len(parts) > 2 else ""
        return cls(tid, parent=parent, keep="k" in flags)

    def __repr__(self):
        return (f"RequestTraceContext({self.trace_id!r}, "
                f"parent={self.parent}, keep={self.keep})")


def _hash01(trace_id: str) -> float:
    """Deterministic uniform-[0,1) hash of a trace id — every process
    in the topology maps the same id to the same number, so the
    probabilistic keep/drop agrees fleet-wide without coordination."""
    h = hashlib.sha256(trace_id.encode("utf-8")).digest()
    return int.from_bytes(h[:8], "big") / float(1 << 64)


class ReqTraceCollector:
    """Per-process span buffer + tail sampler + completed-trace ring."""

    def __init__(self, sample: float = 0.0, ring_size: int = 256):
        self.sample = max(0.0, min(1.0, float(sample)))
        self.ring_size = max(1, int(ring_size))
        self.enabled = self.sample > 0.0
        self._lock = threading.Lock()
        self._buffers: Dict[str, List[tuple]] = {}
        self._ring: "collections.OrderedDict[str, dict]" = \
            collections.OrderedDict()
        # first-finish keep/drop decisions, memoized per trace so the
        # router's flush and the engine's flush of the SAME trace agree
        # (and count the sampler metrics once)
        self._decided: "collections.OrderedDict[str, Tuple[bool, str]]" \
            = collections.OrderedDict()
        from bigdl_tpu import obs
        from bigdl_tpu.obs import names

        reg = obs.get_registry()
        self._sampled = reg.counter(
            names.REQTRACE_SAMPLED_TOTAL,
            "Request traces kept by the tail sampler, by keep reason",
            labels=("reason",))
        self._dropped = reg.counter(
            names.REQTRACE_DROPPED_TOTAL,
            "Completed request traces dropped by the tail sampler")
        self._evicted = reg.counter(
            names.REQTRACE_RING_EVICTED_TOTAL,
            "Kept request traces evicted from the bounded ring")
        self._active = reg.gauge(
            names.REQTRACE_ACTIVE_TRACES,
            "Request traces currently open (begun, not yet sampled)")

    # ----------------------------------------------------------- lifecycle
    def new_context(self) -> RequestTraceContext:
        return RequestTraceContext(uuid.uuid4().hex[:16])

    def _open(self, trace_id: str) -> Optional[list]:
        """(Re)open the span buffer for a trace — callers hold the
        lock.  A trace the sampler already DROPPED stays dropped
        (returns None); a KEPT trace may re-open so an in-process
        drain-handoff replay's spans merge into the same ring entry."""
        buf = self._buffers.get(trace_id)
        if buf is None:
            decided = self._decided.get(trace_id)
            if decided is not None and not decided[0]:
                return None
            buf = self._buffers[trace_id] = []
            self._active.inc()
        return buf

    def begin(self, ctx: RequestTraceContext) -> None:
        """Open a span buffer for ``ctx`` (idempotent per trace)."""
        if not self.enabled or ctx is None:
            return
        with self._lock:
            self._open(ctx.trace_id)

    def span(self, ctx: Optional[RequestTraceContext], name: str,
             start_mono: float, dur_s: float, **attrs) -> None:
        """Buffer one lifecycle hop span (``start_mono`` on the
        ``time.monotonic()`` clock the serving tier stamps with)."""
        if not self.enabled or ctx is None:
            return
        with self._lock:
            buf = self._open(ctx.trace_id)
            if buf is not None:
                buf.append((str(name), float(start_mono),
                            max(0.0, float(dur_s)), attrs))

    def peek(self, ctx: RequestTraceContext) -> List[dict]:
        """The still-buffered spans of an *unfinished* trace (the sim's
        lost-request dump; an already-sampled trace answers from the
        ring instead)."""
        if ctx is None:
            return []
        with self._lock:
            buf = self._buffers.get(ctx.trace_id)
            if buf is not None:
                return [dict(name=n, start=s, dur_s=d, **a)
                        for n, s, d, a in buf]
            entry = self._ring.get(ctx.trace_id)
            return list(entry["spans"]) if entry else []

    # ------------------------------------------------------------ sampling
    def _reason(self, ctx, error, retries, preempted, slo_violation,
                handoff) -> Optional[str]:
        if error:
            return "error"
        if handoff:
            return "handoff"
        if preempted:
            return "preempt"
        if retries:
            return "retry"
        if slo_violation:
            return "slo"
        if ctx.keep:
            return "forced"
        if _hash01(ctx.trace_id) < self.sample:
            return "sampled"
        return None

    def finish(self, ctx: Optional[RequestTraceContext], *,
               request: Optional[str] = None,
               error: Optional[str] = None, retries: int = 0,
               preempted: bool = False, slo_violation: bool = False,
               handoff: bool = False, e2e_s: Optional[float] = None
               ) -> Tuple[bool, Optional[str]]:
        """One completion point flushing its buffered spans through the
        tail sampler.  Returns ``(kept, reason)``.

        A trace may finish more than once in one process (the engine's
        ``_complete`` and the router's ``route()`` both flush their own
        hops) — the first finish decides keep/drop and counts the
        sampler metrics; later finishes reuse the decision and merge
        their spans into the same ring entry."""
        if not self.enabled or ctx is None:
            return False, None
        with self._lock:
            buf = self._buffers.pop(ctx.trace_id, None)
            if buf is not None:
                self._active.inc(-1.0)
            decided = self._decided.get(ctx.trace_id)
            first = decided is None
            if first:
                reason = self._reason(ctx, error, retries, preempted,
                                      slo_violation, handoff)
                decided = (reason is not None, reason)
                self._decided[ctx.trace_id] = decided
                while len(self._decided) > 4 * self.ring_size:
                    self._decided.popitem(last=False)
            kept, reason = decided
            if first:
                if kept:
                    self._sampled.labels(reason=reason).inc()
                else:
                    self._dropped.inc()
            if not kept:
                return False, reason
            ctx.keep = True      # later hops/hosts inherit the decision
            spans = self._emit(ctx, buf or [], request)
            entry = self._ring.get(ctx.trace_id)
            if entry is None:
                entry = {"trace": ctx.trace_id, "request": request,
                         "reason": reason, "error": error,
                         "retries": int(retries), "e2e_s": e2e_s,
                         "spans": []}
                self._ring[ctx.trace_id] = entry
                while len(self._ring) > self.ring_size:
                    self._ring.popitem(last=False)
                    self._evicted.inc()
            else:
                entry["request"] = entry["request"] or request
                entry["error"] = entry["error"] or error
                entry["retries"] = max(entry["retries"], int(retries))
                if e2e_s is not None:
                    entry["e2e_s"] = e2e_s
            entry["spans"].extend(spans)
            return True, reason

    def _emit(self, ctx, buf, request) -> List[dict]:
        """Emit buffered spans through the process tracer (monotonic ->
        perf_counter conversion happens here, once) and return their
        ring-entry dicts."""
        from bigdl_tpu import obs

        tracer = obs.get_tracer()
        off_perf = time.perf_counter() - time.monotonic()
        off_wall = time.time() - time.monotonic()
        out = []
        for name, start_mono, dur_s, attrs in buf:
            if tracer.enabled:
                tracer.complete(name, start_mono + off_perf, dur_s,
                                trace=ctx.trace_id, request=request,
                                **attrs)
            out.append(dict(name=name,
                            start=round(start_mono + off_wall, 6),
                            dur_s=round(dur_s, 9), **attrs))
        return out

    # ------------------------------------------------------------- lookup
    def find(self, key: str) -> Optional[dict]:
        """A kept completed trace by trace id or request id (newest
        match wins), for ``/trace?request=<id>``."""
        key = str(key)
        with self._lock:
            entry = self._ring.get(key)
            if entry is not None:
                return dict(entry)
            for e in reversed(self._ring.values()):
                if str(e.get("request")) == key:
                    return dict(e)
        return None

    def completed(self) -> List[dict]:
        """Every kept completed trace in the ring, oldest first."""
        with self._lock:
            return [dict(e) for e in self._ring.values()]

    def stats(self) -> dict:
        with self._lock:
            return {"enabled": self.enabled, "sample": self.sample,
                    "ring_size": self.ring_size,
                    "open": len(self._buffers),
                    "kept": len(self._ring),
                    "sampled": {
                        r: int(self._sampled.labels(reason=r).value)
                        for r in KEEP_REASONS
                        if self._sampled.labels(reason=r).value},
                    "dropped": int(self._dropped._solo().value)}


#: the shared disabled collector (no metrics minted, nothing buffered)
class _NullCollector:
    enabled = False
    sample = 0.0

    def new_context(self):
        return RequestTraceContext(uuid.uuid4().hex[:16])

    def begin(self, ctx):
        pass

    def span(self, ctx, name, start_mono, dur_s, **attrs):
        pass

    def peek(self, ctx):
        return []

    def finish(self, ctx, **kw):
        return False, None

    def find(self, key):
        return None

    def completed(self):
        return []

    def stats(self):
        return {"enabled": False, "sample": 0.0}


NULL_COLLECTOR = _NullCollector()

_lock = threading.Lock()
_collector = NULL_COLLECTOR
_collector_key = None


def get_collector():
    """The process collector, rebuilt when ``BIGDL_REQTRACE_SAMPLE`` /
    ``BIGDL_REQTRACE_RING`` change; the shared :data:`NULL_COLLECTOR`
    while sampling is off (no state, no metrics)."""
    global _collector, _collector_key
    from bigdl_tpu.config import refresh_from_env

    cfg = refresh_from_env().obs
    key = (cfg.reqtrace_sample, cfg.reqtrace_ring)
    with _lock:
        if key != _collector_key:
            _collector_key = key
            _collector = (ReqTraceCollector(cfg.reqtrace_sample,
                                            cfg.reqtrace_ring)
                          if cfg.reqtrace_sample > 0.0
                          else NULL_COLLECTOR)
        return _collector


def reset_collector():
    """Test hook (wired into ``obs.reset()``): drop the collector so
    the next accessor rebuilds from live config."""
    global _collector, _collector_key
    with _lock:
        _collector = NULL_COLLECTOR
        _collector_key = None


__all__ = ["TRACE_HEADER", "KEEP_REASONS", "RequestTraceContext",
           "ReqTraceCollector", "NULL_COLLECTOR", "get_collector",
           "reset_collector"]
