"""Span tracer — Chrome ``trace_event`` JSON + JSONL structured events.

The reference's only timeline attribution was the driver-side phase
averages in «bigdl»/optim/Metrics.scala; averages cannot answer "where
did *this* slow step spend its time".  This tracer gives the training
stack nested wall-clock spans:

* contextvar-based nesting — spans opened inside a span become its
  children automatically, per thread/task, with deterministic ids
  (a per-tracer monotonic counter, no uuids);
* two export formats per run: a Chrome ``trace_event`` JSON file
  (open in Perfetto / ``chrome://tracing``) and a JSONL stream of
  structured span/event records for log pipelines;
* thread-safe — the background checkpoint writer and the training
  thread record into the same tracer (each gets its own Chrome tid).

Off by default: when ``BIGDL_TRACE_DIR`` is unset, callers get the
shared :data:`NULL_TRACER` whose ``span()`` returns one reusable no-op
context manager — no allocation, no clock reads, no device syncs.
"""

from __future__ import annotations

import collections
import contextlib
import contextvars
import itertools
import json
import os
import threading
import time


def _default_host_id() -> int:
    """This process's host rank: the launcher's BIGDL_PROCESS_ID via the
    config object (0 in single-host runs).  The tag is what lets
    :mod:`bigdl_tpu.obs.aggregate` attribute merged spans to hosts."""
    try:
        from bigdl_tpu.config import config

        return int(config.process_id)
    except Exception:  # noqa: BLE001 — tracing must never fail bring-up
        return 0

# the active span id for the current thread/task (None at top level)
_CURRENT: contextvars.ContextVar = contextvars.ContextVar(
    "bigdl_obs_span", default=None)

# live span NAMES per thread, innermost last — what the sampling
# profiler (obs/prof.py) attributes its stacks to.  _CURRENT carries
# only the span *id* (all the nesting logic needs), so the name stack
# is kept separately: one dict keyed by thread ident holding a plain
# list.  Push/pop are single list ops under the GIL; the profiler
# thread reads racily (a sample landing inside a push/pop window lands
# in the adjacent phase — one sample of noise, by design).
_PHASES: dict = {}


def current_phase(ident: int):
    """Innermost live span name for thread ``ident`` (None when that
    thread is not inside any recorded span) — the profiler's
    attribution read.  Never raises: the stack may vanish between the
    membership check and the index (thread exiting a span)."""
    try:
        return _PHASES[ident][-1]
    except (KeyError, IndexError):
        return None


def _push_phase(name: str) -> int:
    ident = threading.get_ident()
    _PHASES.setdefault(ident, []).append(name)
    return ident


def _pop_phase(ident: int):
    try:
        stack = _PHASES[ident]
        stack.pop()
        if not stack:
            del _PHASES[ident]
    except (KeyError, IndexError):  # torn by a concurrent reset
        pass


class _NullSpan:
    """Reusable no-op context manager — the disabled fast path."""

    __slots__ = ()

    def __enter__(self):
        return None

    def __exit__(self, *exc):
        return False


_NULL_SPAN = _NullSpan()


class NullTracer:
    """No-op tracer with the full :class:`Tracer` surface."""

    __slots__ = ()
    enabled = False

    def span(self, name, **attrs):
        return _NULL_SPAN

    def event(self, name, **attrs):
        pass

    def complete(self, name, start_perf, duration_s, **attrs):
        pass

    def counter(self, name, **values):
        pass

    def recent(self):
        return []

    def flush(self):
        pass

    def close(self):
        pass


NULL_TRACER = NullTracer()


class Tracer:
    """Recording tracer bound to one output directory.

    File names carry pid + a process-wide monotonic counter so two
    tracers created in the same second (fast tests, retries) can never
    collide or interleave.
    """

    enabled = True
    _FILE_SEQ = itertools.count()

    def __init__(self, trace_dir: str, app_name: str = "bigdl_tpu",
                 host_id: int = None, ring_size: int = 512):
        os.makedirs(trace_dir, exist_ok=True)
        self.pid = os.getpid()
        self.host_id = (_default_host_id() if host_id is None
                        else int(host_id))
        # host rank in the stem: N hosts share one trace_dir (a mounted
        # volume) without shard-name collisions even at equal pids
        stem = (f"{app_name}.h{self.host_id}.{self.pid}."
                f"{next(Tracer._FILE_SEQ)}")
        self.trace_path = os.path.join(trace_dir, stem + ".trace.json")
        self.jsonl_path = os.path.join(trace_dir, stem + ".events.jsonl")
        self._lock = threading.Lock()
        self._ids = itertools.count(1)
        self._events: list = []
        self._tids: dict = {}
        self._closed = False
        # flight recorder: the last `ring_size` structured records stay
        # in memory for postmortem bundles (obs/regress.py) and the
        # slow-step detector's child-span breakdown
        self._recent: collections.deque = collections.deque(
            maxlen=max(1, int(ring_size)))
        # one wall-clock anchor + perf_counter timeline: Chrome wants a
        # monotonic microsecond ts, the JSONL wants wall time
        self._epoch_wall = time.time()
        self._epoch_perf = time.perf_counter()
        self._jsonl = open(self.jsonl_path, "a", encoding="utf-8")
        self._events.append({"name": "process_name", "ph": "M",
                             "pid": self.pid, "tid": 0,
                             "args": {"name":
                                      f"{app_name} host{self.host_id}"}})

    # ------------------------------------------------------------- internals
    def _tid(self) -> int:
        ident = threading.get_ident()
        with self._lock:
            tid = self._tids.get(ident)
            if tid is None:
                tid = len(self._tids) + 1
                self._tids[ident] = tid
                self._events.append(
                    {"name": "thread_name", "ph": "M", "pid": self.pid,
                     "tid": tid,
                     "args": {"name": threading.current_thread().name}})
            return tid

    def _record(self, chrome_ev: dict, jsonl_rec: dict = None):
        line = None
        if jsonl_rec is not None:
            # every structured record carries its origin: the aggregator
            # groups shards and tags merged spans by (host, pid)
            jsonl_rec["host"] = self.host_id
            jsonl_rec["pid"] = self.pid
            line = json.dumps(jsonl_rec, default=str) + "\n"
        with self._lock:
            if self._closed:
                return
            self._events.append(chrome_ev)
            if line is not None:
                self._recent.append(jsonl_rec)
                self._jsonl.write(line)

    def recent(self) -> list:
        """The flight-recorder ring: the newest records (oldest first),
        bounded by ``ring_size``."""
        with self._lock:
            return list(self._recent)

    def _ts_us(self, perf_t: float) -> float:
        return round((perf_t - self._epoch_perf) * 1e6, 3)

    def _wall(self, perf_t: float) -> float:
        return self._epoch_wall + (perf_t - self._epoch_perf)

    # ------------------------------------------------------------------ API
    @contextlib.contextmanager
    def span(self, name: str, **attrs):
        """Timed nested span; yields its deterministic span id."""
        sid = next(self._ids)
        parent = _CURRENT.get()
        token = _CURRENT.set(sid)
        ident = _push_phase(name)
        tid = self._tid()
        t0 = time.perf_counter()
        try:
            yield sid
        finally:
            _CURRENT.reset(token)
            _pop_phase(ident)
            dur = time.perf_counter() - t0
            self._record(
                {"name": name, "ph": "X", "ts": self._ts_us(t0),
                 "dur": round(dur * 1e6, 3), "pid": self.pid, "tid": tid,
                 "args": attrs},
                {"kind": "span", "name": name, "id": sid, "parent": parent,
                 "tid": tid, "wall_time": self._wall(t0),
                 "dur_s": round(dur, 9), "attrs": attrs})

    def event(self, name: str, **attrs):
        """Instant (zero-duration) structured event."""
        t = time.perf_counter()
        tid = self._tid()
        self._record(
            {"name": name, "ph": "i", "s": "t", "ts": self._ts_us(t),
             "pid": self.pid, "tid": tid, "args": attrs},
            {"kind": "event", "name": name, "id": next(self._ids),
             "parent": _CURRENT.get(), "tid": tid,
             "wall_time": self._wall(t), "attrs": attrs})

    def complete(self, name: str, start_perf: float, duration_s: float,
                 **attrs):
        """Retroactive span from a ``perf_counter()`` start + duration —
        for phases measured outside the contextvar flow (e.g. the
        pipelined loss readback that resolves one iteration late)."""
        tid = self._tid()
        self._record(
            {"name": name, "ph": "X", "ts": self._ts_us(start_perf),
             "dur": round(duration_s * 1e6, 3), "pid": self.pid,
             "tid": tid, "args": attrs},
            {"kind": "span", "name": name, "id": next(self._ids),
             "parent": _CURRENT.get(), "tid": tid,
             "wall_time": self._wall(start_perf),
             "dur_s": round(duration_s, 9), "attrs": attrs})

    def counter(self, name: str, **values):
        """Chrome counter track (e.g. host RSS over time)."""
        t = time.perf_counter()
        self._record({"name": name, "ph": "C", "ts": self._ts_us(t),
                      "pid": self.pid, "tid": 0, "args": values})

    def flush(self):
        """Write the full Chrome trace JSON (atomic replace) and flush
        the JSONL stream.  Safe to call repeatedly; the trace file is
        valid after every flush."""
        with self._lock:
            events = list(self._events)
            if not self._jsonl.closed:
                self._jsonl.flush()
        doc = {"traceEvents": events, "displayTimeUnit": "ms",
               "otherData": {"pid": self.pid, "host_id": self.host_id,
                             "wall_epoch": self._epoch_wall}}
        tmp = self.trace_path + ".tmp"
        with open(tmp, "w", encoding="utf-8") as fh:
            json.dump(doc, fh, default=str)
        os.replace(tmp, self.trace_path)

    def close(self):
        """Flush and stop recording (idempotent)."""
        if self._closed:
            return
        self.flush()
        with self._lock:
            self._closed = True
            if not self._jsonl.closed:
                self._jsonl.close()
