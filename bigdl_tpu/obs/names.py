"""Single source of truth for every published ``bigdl_*`` metric name.

Every metric family the framework mints — counters, gauges, histograms,
across obs/serving/resilience/optim/ops/dataset — is declared HERE,
once, with its kind, label names, a label-cardinality ceiling, a
one-line doc and a **fleet aggregation policy**.  Mint sites reference
these constants instead of string literals, which buys three
guarantees:

* a typo'd or ad-hoc metric name is an ImportError / lint failure, not
  a silently-forked time series;
* ``BIGDL_OBS_STRICT=1`` makes :class:`~bigdl_tpu.obs.metrics.
  MetricsRegistry` reject any ``bigdl_*`` registration that is not
  declared here (or whose kind/labels disagree), and cap each family at
  its declared label cardinality — the runtime enforcement of the same
  contract;
* ``graftlint`` rule RD003/RD005 (``bigdl_tpu/analysis``) statically
  pins every mint site in the tree to this registry, RD004 requires
  each declared name to be rendered by ``obs/report.py`` or documented,
  and RD007 requires each family's fleet aggregation policy to be a
  legal policy/kind pair.

The ``cardinality`` ceiling is the maximum number of label-value
combinations (children) the family may grow: a scrape surface is only
as cheap as its widest family, and an unbounded label (request id,
float bucket, raw exception text) is the classic way a registry eats
the host.  Label-less families have ceiling 1.

The ``policy`` is how a fleet tier (``obs/rollup.py``) folds one family
across hosts into a single merged sample per label set:

* ``sum`` — counters and histogram buckets, always (cumulative bucket
  counts sum exactly, so a fleet quantile derived from merged buckets
  is bit-identical to the flat merge — the rollup correctness
  invariant).  A ``sum`` **gauge** is legal only as an explicit opt-in
  (an additive level like a queue depth or a replica count), marked
  with an inline ``# graftlint: disable=RD007`` — by default a summed
  gauge is the classic fleet-dashboard lie (a "p99" that is really a
  sum of p99s).
* ``max`` / ``min`` — worst-host semantics (ages, norms, depths /
  floors like goodput and SLO ratios).
* ``last`` — whole-fleet constants where any live host's value is the
  fleet value (static per-step byte footprints, plan shapes).
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Optional, Tuple


@dataclasses.dataclass(frozen=True)
class MetricSpec:
    """Declared shape of one metric family."""

    name: str
    kind: str                    # "counter" | "gauge" | "histogram"
    labels: Tuple[str, ...]      # declared label names, order-free
    cardinality: int             # max label-value combinations
    doc: str                     # one-line purpose (RD004 contract)
    policy: str = "sum"          # fleet aggregation policy (RD007)


#: name -> :class:`MetricSpec` for every declared family
REGISTRY: Dict[str, MetricSpec] = {}

_KINDS = ("counter", "gauge", "histogram")

#: legal fleet aggregation policies (RD007 contract)
POLICIES = ("sum", "max", "min", "last")

#: policies a gauge may declare without a lint opt-in
GAUGE_POLICIES = ("max", "min", "last")


def _m(name: str, kind: str, labels: Tuple[str, ...] = (),
       cardinality: int = 1, doc: str = "",
       policy: Optional[str] = None) -> str:
    if kind not in _KINDS:
        raise ValueError(f"{name}: bad kind {kind!r}")
    if name in REGISTRY:
        raise ValueError(f"duplicate metric declaration {name!r}")
    if labels and cardinality <= 1:
        raise ValueError(f"{name}: labeled metric needs a ceiling > 1")
    if policy is None:
        # counters and histogram buckets merge additively by
        # definition; a gauge has no defensible default
        if kind == "gauge":
            raise ValueError(f"{name}: gauge needs an explicit fleet "
                             f"aggregation policy (one of {POLICIES})")
        policy = "sum"
    if policy not in POLICIES:
        raise ValueError(f"{name}: bad policy {policy!r} "
                         f"(one of {POLICIES})")
    if kind in ("counter", "histogram") and policy != "sum":
        raise ValueError(f"{name}: {kind} families merge by 'sum' "
                         f"only, got policy {policy!r}")
    REGISTRY[name] = MetricSpec(name, kind, tuple(labels),
                                int(cardinality), doc, policy)
    return name


# --------------------------------------------------------------- runtime
STEP_TIME_SECONDS = _m(
    "bigdl_step_time_seconds", "gauge", ("quantile",), 4,
    "Observed train-step completion time percentiles", policy="max")
JIT_COMPILE_COUNT = _m(
    "bigdl_jit_compile_count", "gauge", policy="max",
    doc="Distinct jit compile events (new arg signatures)")
JIT_COMPILE_SECONDS_TOTAL = _m(
    "bigdl_jit_compile_seconds_total", "gauge", policy="max",
    doc="Wall seconds spent blocked on jit trace+compile")
STEP_FLOPS = _m(
    "bigdl_step_flops", "gauge", policy="last",
    doc="HLO cost-analysis FLOPs of one compiled train step")
MFU = _m(
    "bigdl_mfu", "gauge", policy="min",
    doc="Model FLOPs utilization vs the chip's peak")
HOST_RSS_BYTES = _m(
    "bigdl_host_rss_bytes", "gauge", policy="max",
    doc="Driver-process resident set size")
DEVICE_MEMORY_BYTES = _m(
    "bigdl_device_memory_bytes", "gauge", ("stat",), 16,
    "Device 0 memory stats, per allocator stat", policy="max")
HBM_PEAK_BYTES = _m(
    "bigdl_hbm_peak_bytes", "gauge", ("device",), 64,
    "Peak HBM bytes in use, per local device", policy="max")
ENGINE_INITS_TOTAL = _m(
    "bigdl_engine_inits_total", "counter",
    doc="Engine.init calls in this process")

# --------------------------------------------------------------- optim
PHASE_SECONDS = _m(
    "bigdl_phase_seconds", "histogram", ("phase",), 24,
    "Driver phase timers (the reference's optim.Metrics)")
OVERLAP_BUCKETS = _m(
    "bigdl_overlap_buckets", "gauge", policy="last",
    doc="Gradient-exchange buckets in the overlap plan")
OVERLAP_EXPOSED_COMM_FRACTION = _m(
    "bigdl_overlap_exposed_comm_fraction", "gauge", policy="max",
    doc="Exposed (non-overlapped) comm seconds / step seconds")
OVERLAP_EXPOSED_COMM_SECONDS = _m(
    "bigdl_overlap_exposed_comm_seconds", "gauge", policy="max",
    doc="Exposed comm seconds per step after overlap")
RETRY_ATTEMPTS_TOTAL = _m(
    "bigdl_retry_attempts_total", "counter",
    ("classification", "error"), 64,
    "Classified-retry attempts, by failure class and error type")
CHECKPOINT_WRITE_FAILURES_TOTAL = _m(
    "bigdl_checkpoint_write_failures_total", "counter",
    doc="Checkpoint writes that raised (sync or background writer)")
PREEMPTIONS_TOTAL = _m(
    "bigdl_preemptions_total", "counter",
    doc="SIGTERM/SIGINT preemptions handled by the elastic exit path")
SLOW_STEPS_TOTAL = _m(
    "bigdl_slow_steps_total", "counter",
    doc="Steps slower than median * BIGDL_SLOW_STEP_FACTOR")
NONFINITE_SKIPS_TOTAL = _m(
    "bigdl_nonfinite_skips_total", "counter",
    doc="Weight updates skipped by the non-finite step guard")

# --------------------------------------------------------------- kernels
KERNEL_FALLBACKS_TOTAL = _m(
    "bigdl_kernel_fallbacks_total", "counter", ("site",), 16,
    "Kernel dispatches that fell back to the reference path")
TUNER_CACHE_HITS_TOTAL = _m(
    "bigdl_tuner_cache_hits_total", "counter",
    doc="Tuner decisions served from the cache")
TUNER_CACHE_MISSES_TOTAL = _m(
    "bigdl_tuner_cache_misses_total", "counter",
    doc="Tuner cache misses (fresh searches)")
TUNER_MEASUREMENTS_TOTAL = _m(
    "bigdl_tuner_measurements_total", "counter",
    doc="Wall-clock candidate probes run by the auto-tuner")
TUNER_DECISIONS_TOTAL = _m(
    "bigdl_tuner_decisions_total", "counter", ("site", "impl"), 64,
    "Auto-tuner dispatch decisions, by call site and chosen impl")

# --------------------------------------------------------------- wire
COLLECTIVE_BYTES_TOTAL = _m(
    "bigdl_collective_bytes_total", "counter", ("op", "dtype"), 64,
    "Wire bytes programmed into collectives, from static shapes")
COLLECTIVE_BYTES_PER_STEP = _m(
    "bigdl_collective_bytes_per_step", "gauge", ("op", "dtype"), 64,
    "Static per-train-step wire bytes of the collective footprint",
    policy="last")
COLLECTIVE_WIRE_SAVINGS_RATIO = _m(
    "bigdl_collective_wire_savings_ratio", "gauge", ("path",), 8,
    "Uncompressed exchange bytes over what the wire actually ships",
    policy="min")

# --------------------------------------------------------------- goodput
GOODPUT_RATIO = _m(
    "bigdl_goodput_ratio", "gauge", policy="min",
    doc="Productive step seconds over total accounted wall seconds")
GOODPUT_WINDOW_RATIO = _m(
    "bigdl_goodput_window_ratio", "gauge", policy="min",
    doc="Good share of the last classifier window's wall clock")
BADPUT_SECONDS_TOTAL = _m(
    "bigdl_badput_seconds_total", "counter", ("cause",), 16,
    "Non-productive wall seconds, by cause (goodput ledger)")
BOTTLENECK = _m(
    "bigdl_bottleneck", "gauge", ("class",), 8,
    "One-hot per-window bottleneck classification", policy="max")
REWORK_STEPS_TOTAL = _m(
    "bigdl_rework_steps_total", "counter",
    doc="Steps re-executed after a restart")
STRAGGLER_STEPS_TOTAL = _m(
    "bigdl_straggler_steps_total", "counter", ("host",), 1024,
    "Cross-host straggler detections, by slow host")

# --------------------------------------------------------------- health
GRAD_NORM = _m(
    "bigdl_grad_norm", "gauge", ("layer",), 4096,
    "Per-layer gradient norm (BIGDL_HEALTH_EVERY)", policy="max")
PARAM_NORM = _m(
    "bigdl_param_norm", "gauge", ("layer",), 4096,
    "Per-layer parameter norm", policy="max")
UPDATE_RATIO = _m(
    "bigdl_update_ratio", "gauge", ("layer",), 4096,
    "Per-layer update-to-param norm ratio", policy="max")
GLOBAL_GRAD_NORM = _m(
    "bigdl_global_grad_norm", "histogram",
    doc="Global gradient norm distribution")
NONFINITE_LAYERS_TOTAL = _m(
    "bigdl_nonfinite_layers_total", "counter", ("layer",), 4096,
    "Layers whose grads went NaN/inf, by layer")
NUMERICS_ANOMALIES_TOTAL = _m(
    "bigdl_numerics_anomalies_total", "counter", ("kind",), 8,
    "Loss / grad-norm spikes vs the rolling median")

# --------------------------------------------------------------- alerts
ALERTS_TOTAL = _m(
    "bigdl_alerts_total", "counter", ("rule", "severity"), 64,
    "Alert firing transitions, by rule and severity")
ALERTS_RESOLVED_TOTAL = _m(
    "bigdl_alerts_resolved_total", "counter", ("rule",), 64,
    "Alert resolved transitions, by rule")
ALERT_ACTIVE = _m(
    "bigdl_alert_active", "gauge", ("rule",), 64,
    "1 while the rule is firing, 0 otherwise", policy="max")
ALERT_SINK_FAILURES_TOTAL = _m(
    "bigdl_alert_sink_failures_total", "counter",
    doc="Alert sink deliveries that failed after retry")

# --------------------------------------------------------------- resilience
HEARTBEAT_AGE_SECONDS = _m(
    "bigdl_heartbeat_age_seconds", "gauge", ("host",), 1024,
    "Seconds since each peer's last heartbeat touch", policy="max")
PEER_LOST_TOTAL = _m(
    "bigdl_peer_lost_total", "counter",
    doc="PeerLostError raised for silent heartbeat peers")
RESUMES_TOTAL = _m(
    "bigdl_resumes_total", "counter", ("resize",), 32,
    "Checkpoint resumes, by world-size transition (e.g. 2to1)")
SUPERVISOR_RESTARTS_TOTAL = _m(
    "bigdl_supervisor_restarts_total", "counter", ("kind",), 8,
    "Supervisor child restarts, by failure kind")
AUTOSCALE_DECISIONS_TOTAL = _m(
    "bigdl_autoscale_decisions_total", "counter",
    ("direction", "reason"), 32,
    "Autoscale policy decisions, by direction and firing rule")

# --------------------------------------------------------------- fleet
FLEET_SCRAPE_SECONDS = _m(
    "bigdl_fleet_scrape_seconds", "gauge", policy="max",
    doc="Wall seconds of the last full fleet peer-scrape cycle "
        "(bounded-pool concurrent scrape, FleetAggregator.scrape_peers)")
FLEET_SCRAPE_LATENCY_SECONDS = _m(
    "bigdl_fleet_scrape_latency_seconds", "gauge", ("host",), 1024,
    "Per-host wall seconds of the last scrape round trip "
    "(/healthz + /metrics, including the retry when one was spent)",
    policy="max")
FLEET_HOST_STALENESS_SECONDS = _m(
    "bigdl_fleet_host_staleness_seconds", "gauge", ("host",), 1024,
    "Per-host |scraper clock - host /healthz clock| skew; hosts past "
    "BIGDL_STALE_AFTER_S are excluded from fleet merges", policy="max")
# additive level across the fleet tiers — an explicit sum-gauge opt-in
FLEET_STALE_HOSTS = _m(  # graftlint: disable=RD007
    "bigdl_fleet_stale_hosts", "gauge", policy="sum",
    doc="Hosts excluded from the last fleet merge as stale "
        "(skewed clock or staleness past BIGDL_STALE_AFTER_S) — "
        "never silently folded into fleet percentiles")
FLEET_SCRAPE_ERRORS_TOTAL = _m(
    "bigdl_fleet_scrape_errors_total", "counter", ("reason",), 8,
    "Failed per-host scrapes by reason (timeout/refused/protocol), "
    "surfaced without failing the round")

# --------------------------------------------------------------- rollup
# tracked-series level sums across rollup tiers — explicit opt-in
ROLLUP_SERIES_TRACKED = _m(  # graftlint: disable=RD007
    "bigdl_rollup_series_tracked", "gauge", policy="sum",
    doc="Distinct (family, label-set) series the rollup tier is "
        "currently carrying in its merged exposition")
ROLLUP_SERIES_DROPPED_TOTAL = _m(
    "bigdl_rollup_series_dropped_total", "counter", ("family",), 128,
    "Series folded into the 'other' bucket by the top-K cardinality "
    "bound, by family — the fleet-p99-looks-wrong triage counter")
ROLLUP_MEMORY_BYTES = _m(
    "bigdl_rollup_memory_bytes", "gauge", policy="max",
    doc="Approximate bytes the rollup tier holds for its merged "
        "series state (self-scrape of the aggregator)")

# --------------------------------------------------------------- retain
RETAIN_POINTS_TOTAL = _m(
    "bigdl_retain_points_total", "counter",
    doc="Samples ingested by the downsampling retention store")
RETAIN_EVICTIONS_TOTAL = _m(
    "bigdl_retain_evictions_total", "counter", ("ring",), 4,
    "Points evicted from a retention ring (raw/10s/1m) at capacity")
RETAIN_SERIES = _m(
    "bigdl_retain_series", "gauge", policy="max",
    doc="Distinct series the retention store currently tracks "
        "(bounded by BIGDL_RETAIN_SERIES)")

# --------------------------------------------------------------- checkpoint
CHECKPOINT_SNAPSHOT_SECONDS = _m(
    "bigdl_checkpoint_snapshot_seconds", "gauge", policy="max",
    doc="Blocking device-to-host snapshot span of the last checkpoint")
CHECKPOINT_WRITE_SECONDS = _m(
    "bigdl_checkpoint_write_seconds", "gauge", policy="max",
    doc="Serialize+fsync span of the last checkpoint write")
CHECKPOINT_WRITES_TOTAL = _m(
    "bigdl_checkpoint_writes_total", "counter",
    doc="Completed checkpoint writes")
CHECKPOINT_VERIFY_FAILURES_TOTAL = _m(
    "bigdl_checkpoint_verify_failures_total", "counter",
    doc="Checkpoint read-back verifications that failed")

# --------------------------------------------------------------- streaming
# fleet-wide buffered-records level is additive — explicit opt-in
STREAM_BUFFER_DEPTH = _m(  # graftlint: disable=RD007
    "bigdl_stream_buffer_depth", "gauge", policy="sum",
    doc="Records buffered between the stream producer and the trainer")
STREAM_BACKPRESSURE_WAITS_TOTAL = _m(
    "bigdl_stream_backpressure_waits_total", "counter",
    doc="Producer blocks on a full stream buffer")
STREAM_OFFSET = _m(
    "bigdl_stream_offset", "gauge", policy="min",
    doc="Last source offset handed to the trainer")
STREAM_WATERMARK = _m(
    "bigdl_stream_watermark", "gauge", policy="max",
    doc="Highest source offset the producer has ingested")
STREAM_LAG_RECORDS = _m(
    "bigdl_stream_lag_records", "gauge", policy="max",
    doc="Producer watermark minus trainer offset")
STREAM_RECORDS_TOTAL = _m(
    "bigdl_stream_records_total", "counter",
    doc="Records handed to the trainer, exactly-once audited")

# --------------------------------------------------------------- serving
SERVE_REQUESTS_TOTAL = _m(
    "bigdl_serve_requests_total", "counter", ("engine", "status"), 16,
    "Completed serve requests, by engine and outcome")
REQUEST_LATENCY_SECONDS = _m(
    "bigdl_request_latency_seconds", "histogram", ("engine", "kind"), 16,
    "Request latency by engine and kind (ttft/per_token/e2e)")
SERVE_TOKENS_TOTAL = _m(
    "bigdl_serve_tokens_total", "counter",
    doc="Tokens decoded by the LM engine")
# fleet decode throughput is additive across engines — explicit opt-in
SERVE_TOKENS_PER_SECOND = _m(  # graftlint: disable=RD007
    "bigdl_serve_tokens_per_second", "gauge", policy="sum",
    doc="Rolling decode throughput")
SERVE_BATCH_OCCUPANCY = _m(
    "bigdl_serve_batch_occupancy", "gauge", policy="max",
    doc="Fraction of decode slots / micro-batch rows in use")
# fleet queue pressure is additive across replicas — explicit opt-in
SERVE_QUEUE_DEPTH = _m(  # graftlint: disable=RD007
    "bigdl_serve_queue_depth", "gauge", policy="sum",
    doc="Requests waiting in the bounded admission queue")
SERVE_KV_PAGES_IN_USE = _m(
    "bigdl_serve_kv_pages_in_use", "gauge", policy="max",
    doc="Pages allocated from the paged KV cache pool")
SERVE_ADMISSION_WAITS_TOTAL = _m(
    "bigdl_serve_admission_waits_total", "counter",
    doc="Client submits that blocked on a full request queue")
SERVE_PREEMPTIONS_TOTAL = _m(
    "bigdl_serve_preemptions_total", "counter",
    doc="In-flight sequences evicted to free KV pages")
SERVE_LATENCY_SLO_RATIO = _m(
    "bigdl_serve_latency_slo_ratio", "gauge", policy="min",
    doc="Share of recent requests inside the e2e latency SLO")
SERVE_DECODE_ATTN_MS = _m(
    "bigdl_serve_decode_attn_ms", "gauge", policy="max",
    doc="Mean decode-attention kernel milliseconds per step")
SERVE_DECODE_HBM_BYTES_PER_TOKEN = _m(
    "bigdl_serve_decode_hbm_bytes_per_token", "gauge", policy="max",
    doc="Modeled HBM traffic per decoded token")
SERVE_REJECTS_TOTAL = _m(
    "bigdl_serve_rejects_total", "counter",
    doc="Admissions rejected 503 + Retry-After (queue full past the "
        "admission timeout, or the engine is draining)")

# --------------------------------------------------------------- router
ROUTER_REQUESTS_TOTAL = _m(
    "bigdl_router_requests_total", "counter", ("outcome",), 8,
    "Routed requests by final outcome (ok / shed / failed)")
ROUTER_RETRIES_TOTAL = _m(
    "bigdl_router_retries_total", "counter",
    doc="Re-placements after a transient replica failure (each one "
        "spent a retry-budget token)")
ROUTER_SHED_TOTAL = _m(
    "bigdl_router_shed_total", "counter",
    doc="Requests shed 503 + Retry-After on an exhausted retry budget "
        "or no eligible replica")
ROUTER_HANDOFFS_TOTAL = _m(
    "bigdl_router_handoffs_total", "counter",
    doc="Checkpointed decodes replayed exactly-once off a draining "
        "replica")
ROUTER_DRAINS_TOTAL = _m(
    "bigdl_router_drains_total", "counter",
    doc="Replica drain cycles the router completed")
ROUTER_AFFINITY_HITS_TOTAL = _m(
    "bigdl_router_affinity_hits_total", "counter",
    doc="Placements that landed on the session's bound replica (the "
        "multi-turn KV prefix stayed resident)")
# replica counts sum across routers in a multi-router fleet — opt-in
ROUTER_REPLICAS = _m(  # graftlint: disable=RD007
    "bigdl_router_replicas", "gauge", ("state",), 4,
    "Replicas by router-observed state (up / draining / down)",
    policy="sum")
ROUTER_RETRY_BUDGET_TOKENS = _m(
    "bigdl_router_retry_budget_tokens", "gauge", policy="min",
    doc="Tokens left in the router's shared retry-budget bucket")
ROUTER_STALE_EXCLUDED_TOTAL = _m(
    "bigdl_router_stale_excluded_total", "counter",
    doc="Placement snapshots that marked a replica ineligible because "
        "its host clock skew (staleness_s signal) exceeded "
        "BIGDL_STALE_AFTER_S — the skewed-clock half of fleet "
        "staleness, applied to routing")

# --------------------------------------------------------------- rollout
SERVE_WEIGHT_SWAPS_TOTAL = _m(
    "bigdl_serve_weight_swaps_total", "counter", ("version",), 64,
    "Live weight hot-swaps the engine completed, by promoted version "
    "(one device_put + pointer flip between decode steps — slots, "
    "page tables and in-flight decodes survive)")
ROLLOUT_REJECTED_TOTAL = _m(
    "bigdl_rollout_rejected_total", "counter", ("reason",), 8,
    "Published checkpoints the rollout watcher refused before touching "
    "serving state (manifest verify failed: torn / corrupt / checksum "
    "mismatch / missing pair) — counted and event-stamped, never "
    "loaded")
ROLLOUT_CANARY_DIVERGENCE = _m(
    "bigdl_rollout_canary_divergence", "gauge", policy="max",
    doc="Worst token-level divergence of the canary version's pinned-"
        "prompt replay vs the incumbent (fraction of mismatched "
        "tokens; the auto-rollback signal next to SLO burn)")
ROLLOUT_CANARY_STATE = _m(
    "bigdl_rollout_canary_state", "gauge", policy="max",
    doc="CanaryController phase (0 = idle, 1 = canarying, 2 = rolling "
        "back)")
ROLLOUT_ROLLBACKS_TOTAL = _m(
    "bigdl_rollout_rollbacks_total", "counter", ("reason",), 8,
    "Canary auto-rollback episodes, by the signal that fired "
    "(slo_burn / divergence) — hysteresis-gated, so one noisy window "
    "cannot flap promote/rollback")
ROLLOUT_VERSION_MISMATCH_TOTAL = _m(
    "bigdl_rollout_version_mismatch_total", "counter",
    doc="Drain-handoff replays refused because the absorbing replica "
        "serves a different weight version than the checkpoint pinned "
        "— the request re-queues toward a version-exact replica "
        "instead of silently breaking the bit-equal replay contract")

# --------------------------------------------------------------- reqtrace
REQTRACE_SAMPLED_TOTAL = _m(
    "bigdl_reqtrace_sampled_total", "counter", ("reason",), 8,
    "Request traces kept by the tail sampler, by keep reason "
    "(error/retry/preempt/slo/handoff/forced always keep; 'sampled' "
    "is the probabilistic BIGDL_REQTRACE_SAMPLE tail)")
REQTRACE_DROPPED_TOTAL = _m(
    "bigdl_reqtrace_dropped_total", "counter",
    doc="Completed request traces dropped by the tail sampler "
        "(clean requests past the sampling probability)")
REQTRACE_RING_EVICTED_TOTAL = _m(
    "bigdl_reqtrace_ring_evicted_total", "counter",
    doc="Kept request traces evicted from the bounded completed-trace "
        "ring (BIGDL_REQTRACE_RING)")
REQTRACE_ACTIVE_TRACES = _m(
    "bigdl_reqtrace_active_traces", "gauge", policy="max",
    doc="Request traces currently open — begun, not yet through the "
        "tail sampler")

# ---------------------------------------------- profiling / debug bundles
PROF_SAMPLES_TOTAL = _m(
    "bigdl_prof_samples_total", "counter", policy="sum",
    doc="Stack samples the continuous profiler folded into the "
        "collapsed-stack table (BIGDL_PROF_HZ)")
PROF_SKIPPED_TOTAL = _m(
    "bigdl_prof_skipped_total", "counter", policy="sum",
    doc="Profiler samples skipped because the self-overhead ratio "
        "exceeded BIGDL_PROF_BUDGET (the hard overhead cap)")
PROF_OVERHEAD_RATIO = _m(
    "bigdl_prof_overhead_ratio", "gauge", policy="max",
    doc="Profiler self-overhead: cumulative sampling-work seconds / "
        "wall seconds since the profiler started")
PROF_STACKS = _m(
    "bigdl_prof_stacks", "gauge", policy="max",
    doc="Distinct collapsed stacks held in the profiler's bounded "
        "fold table (overflow folds into the 'other' stack)")
BUNDLE_WRITES_TOTAL = _m(
    "bigdl_bundle_writes_total", "counter", ("trigger",), 6,
    "Debug bundles written, by trigger (alert / supervisor / http / "
    "manual)", policy="sum")
BUNDLE_ERRORS_TOTAL = _m(
    "bigdl_bundle_errors_total", "counter", policy="sum",
    doc="Debug-bundle builds that failed (the trigger path never "
        "propagates — a bundle failure must not kill serving)")
BUNDLE_LAST_WRITE_SECONDS = _m(
    "bigdl_bundle_last_write_seconds", "gauge", policy="max",
    doc="Wall-clock timestamp of the newest debug bundle this host "
        "wrote (0 until the first bundle)")

#: ``bigdl_``-prefixed spellings that are NOT metric families — process
#: names, trace categories, logger names — so the RD003 "every bigdl_*
#: literal must be declared" rule knows they are deliberate.
KNOWN_STRINGS = frozenset({
    "bigdl_tpu",            # tracer process name / root logger name
    "bigdl_tpu_net",        # caffe export net name
    "bigdl_obs_span",       # Chrome trace category
    "bigdl_flight_recorder",  # postmortem bundle stem
})


def spec(name: str) -> MetricSpec:
    """The declared spec for ``name`` (KeyError when undeclared)."""
    return REGISTRY[name]


def is_declared(name: str) -> bool:
    """Is ``name`` a declared family, or a histogram-derived sample
    (``_bucket``/``_sum``/``_count``) of one?"""
    if name in REGISTRY:
        return True
    for suffix in ("_bucket", "_sum", "_count"):
        if name.endswith(suffix):
            base = name[: -len(suffix)]
            s = REGISTRY.get(base)
            if s is not None and s.kind == "histogram":
                return True
    return False


def fleet_policy(name: str) -> Optional[str]:
    """The fleet aggregation policy for a sample name as it appears on
    the wire — histogram-derived ``_bucket``/``_sum``/``_count``
    samples merge by ``sum`` like their family; ``None`` for
    undeclared names (the rollup tier passes those through with
    ``last`` semantics rather than inventing a merge)."""
    s = REGISTRY.get(name)
    if s is not None:
        return s.policy
    for suffix in ("_bucket", "_sum", "_count"):
        if name.endswith(suffix):
            base = REGISTRY.get(name[: -len(suffix)])
            if base is not None and base.kind == "histogram":
                return "sum"
    return None
