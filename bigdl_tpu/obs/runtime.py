"""Runtime profiling — compile tracking, step-time reservoirs, memory.

The ROADMAP's "as fast as the hardware allows" needs measurement before
optimization; this module gives the driver the three numbers every perf
PR argues from:

* **compile events** — :func:`instrument_jit` wraps a jitted callable
  and tells a first call on a new arg signature (trace + XLA compile —
  the call blocks for the whole compilation) from a cached dispatch
  (async, returns in microseconds).  An unexpected recompile in a
  steady-state loop shows up as an extra compile event;
* **step-time reservoirs** — :class:`Reservoir` keeps the most recent N
  observations (deterministic ring, no sampling RNG) and reports
  nearest-rank p50/p95/p99;
* **memory** — host RSS from ``/proc`` and, when the backend exposes
  it, per-device HBM stats via ``Device.memory_stats()``.

Everything here is host-side bookkeeping: no ``block_until_ready``, no
device readbacks — instrumentation never adds a host-device sync.
"""

from __future__ import annotations

import math
import os
import threading
import time
from typing import Optional, Sequence

DEFAULT_RESERVOIR = 4096
_PCTS = (0.5, 0.95, 0.99)


class Reservoir:
    """Ring buffer of the most recent ``size`` observations with
    nearest-rank percentiles.  Deterministic: same inputs, same
    percentiles — no random replacement."""

    def __init__(self, size: int = DEFAULT_RESERVOIR):
        self.size = max(1, int(size))
        self._buf: list = []
        self._idx = 0
        self.count = 0
        self.total = 0.0
        self._lock = threading.Lock()

    def add(self, value: float):
        v = float(value)
        with self._lock:
            self.count += 1
            self.total += v
            if len(self._buf) < self.size:
                self._buf.append(v)
            else:
                self._buf[self._idx] = v
                self._idx = (self._idx + 1) % self.size

    def percentiles(self, qs: Sequence[float] = _PCTS) -> dict:
        """{q: nearest-rank value} over the retained window; None when
        empty."""
        with self._lock:
            buf = sorted(self._buf)
        out = {}
        for q in qs:
            if not buf:
                out[q] = None
            else:
                k = min(len(buf) - 1, max(0, math.ceil(q * len(buf)) - 1))
                out[q] = buf[k]
        return out

    def summary(self) -> dict:
        p = self.percentiles()
        return {"p50": p[0.5], "p95": p[0.95], "p99": p[0.99],
                "count": self.count,
                "total_s": round(self.total, 6),
                "mean": self.total / self.count if self.count else None}


def host_rss_bytes() -> Optional[int]:
    """Resident set size of this process, or None when unknowable."""
    try:
        with open("/proc/self/statm") as fh:
            pages = int(fh.read().split()[1])
        return pages * os.sysconf("SC_PAGE_SIZE")
    except (OSError, ValueError, IndexError):
        try:
            import resource

            return resource.getrusage(resource.RUSAGE_SELF).ru_maxrss * 1024
        except Exception:  # noqa: BLE001 — best-effort on exotic hosts
            return None


def device_memory_stats(device=None) -> Optional[dict]:
    """``Device.memory_stats()`` of the first local device (TPU backends
    report bytes_in_use / peak_bytes_in_use; CPU returns None)."""
    try:
        import jax

        dev = device if device is not None else jax.local_devices()[0]
        stats = dev.memory_stats()
        return dict(stats) if stats else None
    except Exception:  # noqa: BLE001 — absent backend / no jax yet
        return None


_HBM_KEYS = ("bytes_in_use", "peak_bytes_in_use", "bytes_limit")


def all_device_memory_stats() -> Optional[dict]:
    """HBM stats for EVERY local device — ``{device_index: {bytes_in_use,
    peak_bytes_in_use, bytes_limit}}``.  Device 0 alone hides exactly
    the failure a sharded trainer cares about (one chip's allocator
    running hot while its peers idle); host-side allocator reads, no
    device sync.  None when no device reports stats (CPU)."""
    try:
        import jax

        devices = jax.local_devices()
    except Exception:  # noqa: BLE001 — absent backend / no jax yet
        return None
    out = {}
    for i, dev in enumerate(devices):
        try:
            stats = dev.memory_stats()
        except Exception:  # noqa: BLE001 — backend without stats
            stats = None
        if stats:
            out[i] = {k: stats[k] for k in _HBM_KEYS if k in stats}
    return out or None


class RuntimeStats:
    """Aggregated runtime profile for one process."""

    def __init__(self, reservoir_size: int = DEFAULT_RESERVOIR):
        self.step_times = Reservoir(reservoir_size)
        self.dispatch_times = Reservoir(reservoir_size)
        self.compile_count = 0
        self.compile_seconds = 0.0
        self.compile_events: list = []  # first 64, [{name, seconds}]
        # HLO-derived costs per instrumented fn ({name: {flops,
        # bytes_accessed}}, from compiled.cost_analysis()); step_flops
        # normalizes the newest one to a single train step and
        # peak_flops (set by bench.py from the chip spec) turns it into
        # an MFU gauge at publish time
        self.costs: dict = {}
        self.step_flops: Optional[float] = None
        self.peak_flops: Optional[float] = None
        self._lock = threading.Lock()

    def record_step(self, seconds: float):
        """Observed completion time of one train step (dispatch ->
        resolved loss)."""
        self.step_times.add(seconds)

    def record_compile(self, name: str, seconds: float):
        with self._lock:
            self.compile_count += 1
            self.compile_seconds += float(seconds)
            if len(self.compile_events) < 64:
                self.compile_events.append(
                    {"name": name, "seconds": round(float(seconds), 6)})

    def record_dispatch(self, name: str, seconds: float):
        del name  # one reservoir: dispatch cost is fn-agnostic
        self.dispatch_times.add(seconds)

    def record_cost(self, name: str, cost: dict,
                    steps_per_call: float = 1.0):
        """HLO cost analysis of one compiled fn.  ``steps_per_call``
        normalizes a scanned body (bench runs N steps per call) to
        per-train-step FLOPs."""
        with self._lock:
            self.costs[name] = dict(cost)
            flops = cost.get("flops")
            if flops:
                self.step_flops = float(flops) / max(1.0,
                                                     float(steps_per_call))

    def snapshot(self, memory: bool = True) -> dict:
        out = {
            "step_time_s": self.step_times.summary(),
            "dispatch_time_s": self.dispatch_times.summary(),
            "compile": {"count": self.compile_count,
                        "total_s": round(self.compile_seconds, 6),
                        "events": list(self.compile_events)},
            "cost": {k: dict(v) for k, v in self.costs.items()},
            "step_flops": self.step_flops,
        }
        if memory:
            out["host_rss_bytes"] = host_rss_bytes()
            dm = device_memory_stats()
            if dm is not None:
                out["device_memory"] = {
                    k: dm[k] for k in _HBM_KEYS if k in dm}
            dma = all_device_memory_stats()
            if dma is not None:
                out["device_memory_all"] = dma
        return out

    def reset(self):
        self.__init__(self.step_times.size)


def tree_signature(tree) -> tuple:
    """Hashable (shape, dtype) signature of a pytree of arrays — the
    key a jit cache would retrace on.  Host-side metadata only: reading
    ``.shape``/``.dtype`` never syncs the device."""
    import jax

    sig = []
    for leaf in jax.tree.leaves(tree):
        if hasattr(leaf, "shape") and hasattr(leaf, "dtype"):
            sig.append((tuple(leaf.shape), str(leaf.dtype)))
        else:
            sig.append((type(leaf).__name__,))
    return tuple(sig)


def abstract_args(args, kwargs):
    """``ShapeDtypeStruct`` mirror of an arg tree — host-side metadata
    only (shape/dtype reads never sync the device).  Captured BEFORE a
    donating call so :func:`hlo_cost_analysis` can lower afterwards."""
    try:
        import jax

        abstract = lambda a: (jax.ShapeDtypeStruct(a.shape, a.dtype)
                              if hasattr(a, "shape") and hasattr(a, "dtype")
                              else a)
        return jax.tree.map(abstract, (args, kwargs))
    except Exception:  # noqa: BLE001 — telemetry must never sink a step
        return None


def hlo_cost_analysis(fn, abstract) -> Optional[dict]:
    """``compiled.cost_analysis()`` of a jitted callable for one arg
    signature — the compiler's own FLOPs/bytes count for the program it
    actually built, vs whatever analytic model the caller believes.

    ``abstract`` is the :func:`abstract_args` capture.  Called right
    after the first real call, ``lower().compile()`` reuses the cached
    executable — the cost is one retrace, not a second XLA compile.
    Best-effort: any failure (non-jit callable, backend without cost
    analysis) returns None."""
    lower = getattr(fn, "lower", None)
    if lower is None or abstract is None:
        return None
    try:
        a_args, a_kw = abstract
        ca = lower(*a_args, **a_kw).compile().cost_analysis()
        if isinstance(ca, (list, tuple)):
            ca = ca[0] if ca else None
        if not ca:
            return None
        out = {}
        if ca.get("flops"):
            out["flops"] = float(ca["flops"])
        if ca.get("bytes accessed"):
            out["bytes_accessed"] = float(ca["bytes accessed"])
        return out or None
    except Exception:  # noqa: BLE001 — telemetry must never sink a step
        return None


def instrument_jit(fn, name: str = "jit", stats: Optional[RuntimeStats] = None,
                   tracer=None, steps_per_call: float = 1.0, ledger=None):
    """Wrap a jitted callable: a call on an unseen arg signature is a
    compile event (its wall time ≈ trace + compile, because jit blocks
    the first call), a seen one is a cached dispatch.  The signature is
    computed BEFORE the call — donated buffers are deleted by it.  The
    first compile also records the program's HLO-derived FLOPs/bytes
    (``steps_per_call`` normalizes a scanned N-step body) and stamps a
    ``compile`` badput interval into the goodput ``ledger``."""
    seen = set()

    def wrapped(*args, **kwargs):
        sig = tree_signature((args, kwargs))
        first = sig not in seen
        # abstract arg metadata is captured before the call — the call
        # deletes donated buffers, cost analysis lowers from the mirror
        abstract = abstract_args(args, kwargs) \
            if first and stats is not None else None
        t0 = time.perf_counter()
        out = fn(*args, **kwargs)
        dt = time.perf_counter() - t0
        if first:
            seen.add(sig)
            if stats is not None:
                stats.record_compile(name, dt)
                cost = hlo_cost_analysis(fn, abstract)
                if cost is not None:
                    stats.record_cost(name, cost,
                                      steps_per_call=steps_per_call)
            if tracer is not None:
                tracer.complete(f"{name}.compile", t0, dt,
                                signatures=len(seen))
            if ledger is not None:
                ledger.record("compile", t0, dt)
        elif stats is not None:
            stats.record_dispatch(name, dt)
        return out

    wrapped.__wrapped__ = fn
    wrapped.__name__ = getattr(fn, "__name__", name)
    return wrapped
