"""Perf-regression gate — "did this PR make steps slower?" as code.

Compares a fresh ``bench.py`` result (its ``extras.obs_runtime``
step-time percentiles, falling back to the headline
``extras.step_time_s`` / ``value`` for pre-obs artifacts) against the
repo's ``BENCH_r*.json`` trajectory:

* the baseline is the **best** comparable round on the **same
  platform** (cpu-fallback rounds never gate a TPU run or vice versa —
  their step times differ by orders of magnitude by design);
* a violation is ``fresh_step_time > best * tolerance`` (or the
  throughput mirror, ``fresh_value * tolerance < best_value``), with
  ``tolerance`` from ``BIGDL_REGRESS_TOLERANCE`` (default 1.5 — the CPU
  stand-in is noisy; tighten it on real chips);
* on violation the gate dumps a **flight-recorder bundle** for the
  postmortem: the live tracer's last-K span ring (or, offline, the tail
  of the newest events shard in ``--trace-dir``), the metrics registry
  snapshot, the runtime profile, and the verdict itself.

CLI::

    python -m bigdl_tpu.obs.regress --fresh BENCH.json --trajectory REPO \
        [--tolerance 1.5] [--flight-dir DIR] [--trace-dir DIR] [--json]

Exit code 1 on violation, 0 on pass / no comparable baseline.
``bench.py`` runs the same gate in-process when
``BIGDL_REGRESS_TRAJECTORY`` is exported (verdict lands in
``extras.regression``).
"""

from __future__ import annotations

import glob
import json
import os
import re
import time
from typing import List, Optional
from bigdl_tpu.obs import names

_ROUND_RE = re.compile(r"BENCH_r(\d+)\.json$")


def _default_tolerance() -> float:
    from bigdl_tpu.config import refresh_from_env

    return refresh_from_env().obs.regress_tolerance


def _entry_from_result(result: dict, source: str = "fresh",
                       round_no: Optional[int] = None) -> Optional[dict]:
    """Normalise one bench result dict into a comparable entry."""
    if not isinstance(result, dict) or "extras" not in result:
        return None
    ex = result.get("extras") or {}
    rt = ex.get("obs_runtime") or {}
    step = rt.get("step_time_p50_s")
    if step is None:
        step = ex.get("step_time_s")
    return {
        "source": source,
        "round": round_no,
        "platform": result.get("platform"),
        "value": result.get("value"),
        "step_time_s": step,
        "step_time_p95_s": rt.get("step_time_p95_s"),
        "compile_count": rt.get("compile_count"),
    }


def load_trajectory(path: Optional[str]) -> List[dict]:
    """Every ``BENCH_r*.json`` under ``path`` (a repo dir), oldest
    first.  Driver artifacts wrap the result under ``"parsed"``; bare
    result files work too.  An unset/empty/absent path is a valid
    "no trajectory yet" state (fresh repo, unexported
    ``BIGDL_REGRESS_TRAJECTORY``) and yields ``[]`` — the gate then
    reports a clean ``no_baseline`` verdict instead of raising."""
    if not path:
        return []
    entries = []
    for fn in sorted(glob.glob(os.path.join(path, "BENCH_r*.json"))):
        m = _ROUND_RE.search(fn)
        rnd = int(m.group(1)) if m else None
        try:
            with open(fn, encoding="utf-8") as fh:
                doc = json.load(fh)
        except (OSError, json.JSONDecodeError):
            continue
        result = doc.get("parsed") if isinstance(doc, dict) else None
        if result is None:
            result = doc
        e = _entry_from_result(result, source=os.path.basename(fn),
                               round_no=rnd)
        if e is not None:
            entries.append(e)
    entries.sort(key=lambda e: (e["round"] is None, e["round"]))
    return entries


def check(fresh, trajectory: Optional[List[dict]],
          tolerance: Optional[float] = None) -> dict:
    """Compare a fresh bench result (dict or pre-normalised entry)
    against the trajectory.  Returns a verdict dict with ``status`` in
    ``{"pass", "violation", "no_baseline"}``.  ``trajectory=None`` or
    ``[]`` (no baseline recorded yet) is a clean ``no_baseline``."""
    if tolerance is None:
        tolerance = _default_tolerance()
    trajectory = trajectory or []
    cur = (fresh if fresh is not None and "source" in fresh
           else _entry_from_result(fresh or {}))
    verdict = {"status": "no_baseline", "tolerance": tolerance,
               "current": cur, "baseline": None, "violations": []}
    if cur is None:
        verdict["violations"].append("fresh result is not a bench dict")
        verdict["status"] = "violation"
        return verdict
    peers = [e for e in trajectory
             if e["platform"] == cur["platform"]
             and (e["step_time_s"] is not None or e["value"] is not None)]
    if not peers:
        return verdict
    step_peers = [e for e in peers if e["step_time_s"]]
    val_peers = [e for e in peers if e["value"]]
    base_step = min(step_peers, key=lambda e: e["step_time_s"]) \
        if step_peers else None
    base_val = max(val_peers, key=lambda e: e["value"]) if val_peers else None
    verdict["baseline"] = {
        "step_time_s": base_step["step_time_s"] if base_step else None,
        "step_round": base_step["source"] if base_step else None,
        "value": base_val["value"] if base_val else None,
        "value_round": base_val["source"] if base_val else None,
        "rounds_compared": len(peers),
    }
    compared = False
    if base_step and cur.get("step_time_s"):
        compared = True
        ratio = cur["step_time_s"] / base_step["step_time_s"]
        verdict["step_time_ratio"] = round(ratio, 4)
        if ratio > tolerance:
            verdict["violations"].append(
                f"step time {cur['step_time_s']:.6g}s is {ratio:.2f}x the "
                f"trajectory best {base_step['step_time_s']:.6g}s "
                f"({base_step['source']}) > tolerance {tolerance}x")
    if base_val and cur.get("value"):
        compared = True
        ratio = base_val["value"] / cur["value"]
        verdict["throughput_ratio"] = round(ratio, 4)
        if ratio > tolerance:
            verdict["violations"].append(
                f"throughput {cur['value']:.6g} is {ratio:.2f}x below the "
                f"trajectory best {base_val['value']:.6g} "
                f"({base_val['source']}) > tolerance {tolerance}x")
    if not compared:
        return verdict
    verdict["status"] = "violation" if verdict["violations"] else "pass"
    return verdict


# ------------------------------------------------------------ flight recorder
def _tail_shard_records(trace_dir: str, k: int) -> list:
    """Offline fallback: the last ``k`` records of the newest events
    shard under ``trace_dir``."""
    from bigdl_tpu.obs.aggregate import read_shards

    try:
        shards = read_shards(trace_dir)
    except OSError:
        return []
    if not shards:
        return []
    newest = max(shards, key=lambda s: os.path.getmtime(s.path))
    return newest.records[-k:]


def flight_bundle(reason: str = "", trace_dir: Optional[str] = None,
                  metrics_dir: Optional[str] = None) -> dict:
    """The postmortem bundle: last-K spans (live ring buffer first,
    newest on-disk shard as the offline fallback), metrics snapshot
    (live registry first, newest on-disk ``metrics.*.jsonl`` snapshot
    offline), runtime profile."""
    from bigdl_tpu import obs

    spans = obs.get_tracer().recent()
    source = "ring_buffer"
    if not spans and trace_dir:
        from bigdl_tpu.config import refresh_from_env

        k = refresh_from_env().obs.flight_spans
        spans = _tail_shard_records(trace_dir, k)
        source = "shard_tail"
    metrics = obs.get_registry().snapshot()
    metrics_source = "registry"
    if not metrics.get("metrics") and (metrics_dir or trace_dir):
        from bigdl_tpu.obs.report import load_metric_snapshots

        snaps = load_metric_snapshots(metrics_dir or trace_dir)
        if snaps:
            metrics = max(snaps, key=lambda s: s.get("ts", 0))
            metrics_source = "disk_snapshot"
    from bigdl_tpu.obs.runtime import host_rss_bytes

    return {
        "kind": "bigdl_flight_recorder",
        "ts": time.time(),
        "reason": reason,
        "spans_source": source if spans else "none",
        "spans": spans,
        "metrics": metrics,
        "metrics_source": metrics_source,
        # memory=False: a postmortem dump must never block on a device
        # backend (the hung-tunnel failure mode this repo knows well)
        "runtime": obs.get_runtime().snapshot(memory=False),
        "host_rss_bytes": host_rss_bytes(),
        # training-health columns (obs/health.py): the postmortem's
        # first numerics questions — which layer's norms moved, which
        # went non-finite, what anomalies fired — pre-extracted from
        # the same metrics snapshot + span ring
        "health": _health_columns(metrics, spans),
    }


_HEALTH_FAMILIES = (names.GRAD_NORM, names.PARAM_NORM,
                    names.UPDATE_RATIO, names.GLOBAL_GRAD_NORM,
                    names.NONFINITE_LAYERS_TOTAL,
                    names.NUMERICS_ANOMALIES_TOTAL, names.STEP_FLOPS,
                    names.MFU)


def _health_columns(metrics: dict, spans: list) -> dict:
    fams = (metrics or {}).get("metrics") or {}
    out = {"metrics": {name: fams[name]["samples"]
                       for name in _HEALTH_FAMILIES if name in fams}}
    out["events"] = [r for r in (spans or [])
                     if str(r.get("name", "")).startswith("health.")]
    return out


def dump_flight_recorder(out_dir: str, verdict: dict,
                         trace_dir: Optional[str] = None,
                         metrics_dir: Optional[str] = None) -> str:
    """Write ``flight.<pid>.<ts>.json`` with the bundle + verdict."""
    os.makedirs(out_dir, exist_ok=True)
    bundle = flight_bundle(
        reason="; ".join(verdict.get("violations", [])) or "manual",
        trace_dir=trace_dir, metrics_dir=metrics_dir)
    bundle["verdict"] = verdict
    path = os.path.join(
        out_dir, f"flight.{os.getpid()}.{int(time.time())}.json")
    tmp = path + ".tmp"
    with open(tmp, "w", encoding="utf-8") as fh:
        json.dump(bundle, fh, default=str)
    os.replace(tmp, path)
    return path


def gate(fresh, trajectory_dir: Optional[str],
         tolerance: Optional[float] = None,
         flight_dir: Optional[str] = None,
         trace_dir: Optional[str] = None,
         metrics_dir: Optional[str] = None) -> dict:
    """check() against the dir's BENCH_r*.json; on violation, dump the
    flight-recorder bundle (when ``flight_dir`` is given) and record its
    path in the verdict."""
    verdict = check(fresh, load_trajectory(trajectory_dir),
                    tolerance=tolerance)
    if verdict["status"] == "violation" and flight_dir:
        try:
            verdict["flight_recorder"] = dump_flight_recorder(
                flight_dir, verdict, trace_dir=trace_dir,
                metrics_dir=metrics_dir)
        except OSError as e:
            verdict["flight_recorder_error"] = str(e)
    return verdict


def main(argv=None) -> int:
    import argparse
    import sys

    ap = argparse.ArgumentParser(
        prog="python -m bigdl_tpu.obs.regress",
        description="Gate a fresh bench result against the BENCH_r*.json "
                    "trajectory; exit 1 on regression.")
    ap.add_argument("--fresh", required=True,
                    help="fresh bench JSON file ('-' reads stdin)")
    ap.add_argument("--trajectory", default=".",
                    help="dir holding BENCH_r*.json (default: cwd)")
    ap.add_argument("--tolerance", type=float, default=None,
                    help="slowdown factor that trips the gate "
                         "(default BIGDL_REGRESS_TOLERANCE=1.5)")
    ap.add_argument("--flight-dir", default=None,
                    help="dump a flight-recorder bundle here on violation")
    ap.add_argument("--trace-dir", default=None,
                    help="trace dir whose newest shard seeds the bundle's "
                         "span tail when no live tracer exists")
    ap.add_argument("--metrics-dir", default=None,
                    help="metrics dir whose newest snapshot seeds the "
                         "bundle offline (default: trace dir)")
    ap.add_argument("--json", action="store_true",
                    help="print the full verdict JSON (default: summary)")
    args = ap.parse_args(argv)
    raw = (sys.stdin.read() if args.fresh == "-"
           else open(args.fresh, encoding="utf-8").read())
    doc = json.loads(raw)
    fresh = doc.get("parsed") if isinstance(doc, dict) and "parsed" in doc \
        else doc
    verdict = gate(fresh, args.trajectory, tolerance=args.tolerance,
                   flight_dir=args.flight_dir, trace_dir=args.trace_dir,
                   metrics_dir=args.metrics_dir)
    if args.json:
        print(json.dumps(verdict, default=str))
    else:
        print(f"regression gate: {verdict['status']} "
              f"(tolerance {verdict['tolerance']}x)")
        for v in verdict["violations"]:
            print(f"  VIOLATION: {v}")
        if verdict.get("flight_recorder"):
            print(f"  flight recorder: {verdict['flight_recorder']}")
    return 1 if verdict["status"] == "violation" else 0


if __name__ == "__main__":
    raise SystemExit(main())
