"""Run report — one readable answer from a run's trace/metrics dirs.

``python -m bigdl_tpu.obs.report TRACE_DIR [--metrics-dir DIR]`` reads
the per-host ``*.events.jsonl`` shards (and the ``metrics.*.jsonl``
snapshots when present) and renders what a postmortem asks first:

* per-host step-time percentiles (from the ``computing`` spans — the
  dispatch→resolved-loss wall time the reservoirs also see);
* compile events (count + wall seconds blocked);
* collective wire bytes by op/dtype, per-step footprint and the
  int8-vs-f32 savings ratio;
* resilience events (retries, non-finite skips, checkpoint failures);
* slow-step anomalies and the slowest spans per host;
* training health (obs/health.py): per-layer grad norm / param norm /
  update ratio gauges, non-finite layer attributions, numerics
  anomalies;
* goodput (obs/goodput.py): the cross-attempt, cross-host wall-clock
  ledger — goodput ratio, badput seconds by cause (compile,
  checkpoints, data waits, startup, supervisor backoff, restart
  rework), the window bottleneck classification, and cross-host
  straggler flags;
* kernel auto-tuner (ops/autotune.py): dispatch decisions by site and
  chosen impl, cache hit/miss/measurement traffic, and the recent
  ``tuner.decision`` events with their provenance (cache / model /
  measured / corrupt_cache).

* alerts (obs/alerts.py): fired/resolved transition counts per rule,
  currently-firing rules, and the recent ``alert.firing`` /
  ``alert.resolved`` events.

``--json`` emits the machine-readable report instead of text — the
same dict ``build_report`` returns, so CI and ``obs/regress.py``
consume reports without scraping the rendered text.

``--watch`` turns the report into a refreshing terminal view topped by
a live fleet header: with ``BIGDL_OBS_PEERS`` (or ``--peers``) set it
scrapes each host's live ``/healthz`` + ``/metrics`` endpoint
(obs/server.py); otherwise it incrementally tails the metrics shards.
``--once`` renders a single frame (CI), ``--interval`` sets the
refresh period.
"""

from __future__ import annotations

import json
import math
import os
from typing import List, Optional

from bigdl_tpu.obs.aggregate import read_shards
from bigdl_tpu.obs import names

_PCTS = (0.5, 0.95, 0.99)


def _nearest_rank(values: List[float], q: float) -> Optional[float]:
    if not values:
        return None
    vs = sorted(values)
    k = min(len(vs) - 1, max(0, math.ceil(q * len(vs)) - 1))
    return vs[k]


def load_metric_snapshots(metrics_dir: str) -> List[dict]:
    """Latest JSONL snapshot per metrics shard (one per host/pid)."""
    snaps = []
    if not metrics_dir or not os.path.isdir(metrics_dir):
        return snaps
    for fn in sorted(os.listdir(metrics_dir)):
        if not (fn.startswith("metrics.") and fn.endswith(".jsonl")):
            continue
        last = None
        with open(os.path.join(metrics_dir, fn), encoding="utf-8") as fh:
            for line in fh:
                line = line.strip()
                if line:
                    try:
                        last = json.loads(line)
                    except json.JSONDecodeError:
                        continue
        if last:
            last.setdefault("shard", fn)
            snaps.append(last)
    return snaps


def _metric_samples(snaps: List[dict], name: str) -> list:
    """[(labels, value_or_histdict, host), ...] across all snapshots."""
    out = []
    for snap in snaps:
        fam = (snap.get("metrics") or {}).get(name)
        if not fam:
            continue
        for s in fam.get("samples", []):
            out.append((s.get("labels") or {}, s, snap.get("host", 0)))
    return out


def build_report(trace_dir: str, metrics_dir: Optional[str] = None,
                 bundle_dir: Optional[str] = None) -> dict:
    shards = read_shards(trace_dir)
    snaps = load_metric_snapshots(metrics_dir or trace_dir)

    hosts: dict = {}
    resilience: dict = {}
    slow_steps: list = []
    ckpt_async_writes = 0
    ckpt_snapshots = 0
    compile_events: list = []
    nonfinite_events: list = []
    anomaly_events: list = []
    tuner_events: list = []
    alert_events: list = []
    autoscale_events: list = []
    fleet_events: list = []
    reqtrace_spans: dict = {}
    for sh in shards:
        key = f"host{sh.host}/pid{sh.pid}"
        h = hosts.setdefault(key, {
            "host": sh.host, "pid": sh.pid, "records": 0,
            "step_times": [], "spans": []})
        h["records"] += len(sh.records)
        for rec in sh.records:
            name = rec.get("name", "")
            if rec.get("kind") == "span":
                dur = float(rec.get("dur_s", 0.0))
                attrs = rec.get("attrs") or {}
                h["spans"].append((name, dur, attrs.get("step")))
                # request-trace hop spans (obs/reqtrace.py) carry a
                # `trace` attr — group them per trace id so the
                # cross-host flow of one request reassembles here
                tid = attrs.get("trace")
                if tid and name.startswith("req."):
                    e = reqtrace_spans.setdefault(
                        tid, {"request": None, "spans": [],
                              "hosts": set()})
                    if e["request"] is None:
                        e["request"] = attrs.get("request")
                    e["spans"].append((name, dur))
                    e["hosts"].add(sh.host)
                if name == "computing":
                    h["step_times"].append(dur)
                if name == "checkpoint.write_async":
                    ckpt_async_writes += 1
                elif name == "checkpoint.snapshot":
                    ckpt_snapshots += 1
                if name.endswith(".compile"):
                    compile_events.append(
                        {"host": sh.host, "name": name,
                         "seconds": round(dur, 4)})
            else:
                if name.startswith("resilience."):
                    resilience[name] = resilience.get(name, 0) + 1
                elif name == "slow_step":
                    a = dict(rec.get("attrs") or {})
                    a["host"] = sh.host
                    slow_steps.append(a)
                elif name == "health.nonfinite_layers":
                    a = dict(rec.get("attrs") or {})
                    a["host"] = sh.host
                    nonfinite_events.append(a)
                elif name == "health.anomaly":
                    a = dict(rec.get("attrs") or {})
                    a["host"] = sh.host
                    anomaly_events.append(a)
                elif name == "tuner.decision":
                    a = dict(rec.get("attrs") or {})
                    a["host"] = sh.host
                    tuner_events.append(a)
                elif name in ("alert.firing", "alert.resolved"):
                    a = dict(rec.get("attrs") or {})
                    a["host"] = sh.host
                    a["state"] = name.split(".", 1)[1]
                    a["wall_time"] = rec.get("wall_time")
                    alert_events.append(a)
                elif name in ("elastic.autoscale", "supervisor.backoff",
                              "elastic.stream_restore"):
                    a = dict(rec.get("attrs") or {})
                    a["host"] = sh.host
                    a["event"] = name
                    a["wall_time"] = rec.get("wall_time")
                    autoscale_events.append(a)
                elif name == "fleet.scenario":
                    a = dict(rec.get("attrs") or {})
                    a["wall_time"] = rec.get("wall_time")
                    fleet_events.append(a)

    per_host = {}
    for key, h in hosts.items():
        st = h["step_times"]
        slowest = sorted(h["spans"], key=lambda t: -t[1])[:5]
        per_host[key] = {
            "records": h["records"],
            "steps": len(st),
            "step_time_s": {
                "p50": _nearest_rank(st, 0.5),
                "p95": _nearest_rank(st, 0.95),
                "p99": _nearest_rank(st, 0.99),
                "max": max(st) if st else None,
            },
            "slowest_spans": [
                {"name": n, "dur_s": round(d, 6), "step": s}
                for n, d, s in slowest],
        }

    # ---- collective bytes from the metric snapshots ------------------
    coll_total: dict = {}
    for labels, s, _host in _metric_samples(
            snaps, names.COLLECTIVE_BYTES_TOTAL):
        key = f"{labels.get('op', '?')}:{labels.get('dtype', '?')}"
        coll_total[key] = coll_total.get(key, 0.0) + float(
            s.get("value", 0.0))
    coll_step: dict = {}
    for labels, s, _host in _metric_samples(
            snaps, names.COLLECTIVE_BYTES_PER_STEP):
        key = f"{labels.get('op', '?')}:{labels.get('dtype', '?')}"
        coll_step[key] = float(s.get("value", 0.0))
    savings = [float(s.get("value", 0.0)) for _l, s, _h in _metric_samples(
        snaps, names.COLLECTIVE_WIRE_SAVINGS_RATIO)]
    savings_by_path: dict = {}
    for labels, s, _host in _metric_samples(
            snaps, names.COLLECTIVE_WIRE_SAVINGS_RATIO):
        savings_by_path[labels.get("path", "grad")] = float(
            s.get("value", 0.0))

    compile_count = sum(
        float(s.get("value", 0.0)) for _l, s, _h in _metric_samples(
            snaps, names.JIT_COMPILE_COUNT))

    # ---- training health (obs/health.py) -----------------------------
    def _by_layer(metric):
        out = {}
        for labels, s, _host in _metric_samples(snaps, metric):
            out[labels.get("layer", "?")] = float(s.get("value", 0.0))
        return out

    def _summed(metric, key):
        out = {}
        for labels, s, _host in _metric_samples(snaps, metric):
            k = labels.get(key, "?")
            out[k] = out.get(k, 0.0) + float(s.get("value", 0.0))
        return out

    step_flops = [float(s.get("value", 0.0))
                  for _l, s, _h in _metric_samples(snaps,
                                                   names.STEP_FLOPS)]
    mfu = [float(s.get("value", 0.0))
           for _l, s, _h in _metric_samples(snaps, names.MFU)]

    # ---- goodput ledger (obs/goodput.py) -----------------------------
    from bigdl_tpu.obs import goodput as G
    from bigdl_tpu.obs.aggregate import detect_stragglers

    gp = G.aggregate_goodput(metrics_dir or trace_dir)
    if gp is not None:
        # bottleneck: prefer the run's own windowed gauge (it saw live
        # comm/host fractions); fall back to re-deriving the input
        # share from the ledger when no window ever ticked
        label, source = None, None
        for labels, s, _host in _metric_samples(snaps, names.BOTTLENECK):
            if float(s.get("value", 0.0)) >= 1.0:
                label, source = labels.get("class"), "gauge"
        derived = G.classify_bottleneck(
            gp["productive_s"] + gp["badput_s"].get("rework", 0.0),
            gp["badput_s"].get("data_wait", 0.0))
        if label is None:
            label, source = derived["label"], "ledger"
        gp["bottleneck"] = {"label": label, "source": source,
                            "input_fraction": derived["input_fraction"]}
    stragglers = detect_stragglers(shards)

    # ---- kernel auto-tuner (ops/autotune.py) -------------------------
    tuner_decisions: dict = {}
    for labels, s, _host in _metric_samples(
            snaps, names.TUNER_DECISIONS_TOTAL):
        key = f"{labels.get('site', '?')}:{labels.get('impl', '?')}"
        tuner_decisions[key] = tuner_decisions.get(key, 0.0) + float(
            s.get("value", 0.0))

    def _tuner_count(metric):
        return sum(float(s.get("value", 0.0))
                   for _l, s, _h in _metric_samples(snaps, metric))

    tuner = {
        "decisions_total": tuner_decisions,
        "cache_hits": _tuner_count(names.TUNER_CACHE_HITS_TOTAL),
        "cache_misses": _tuner_count(names.TUNER_CACHE_MISSES_TOTAL),
        "measurements": _tuner_count(names.TUNER_MEASUREMENTS_TOTAL),
        "events": tuner_events,
    }

    # ---- alerts (obs/alerts.py) --------------------------------------
    fired: dict = {}
    for labels, s, _host in _metric_samples(snaps, names.ALERTS_TOTAL):
        key = f"{labels.get('rule', '?')}[{labels.get('severity', '?')}]"
        fired[key] = fired.get(key, 0.0) + float(s.get("value", 0.0))
    resolved: dict = {}
    for labels, s, _host in _metric_samples(
            snaps, names.ALERTS_RESOLVED_TOTAL):
        rule = labels.get("rule", "?")
        resolved[rule] = resolved.get(rule, 0.0) + float(
            s.get("value", 0.0))
    active: dict = {}
    for labels, s, _host in _metric_samples(snaps, names.ALERT_ACTIVE):
        rule = labels.get("rule", "?")
        active[rule] = max(active.get(rule, 0.0),
                           float(s.get("value", 0.0)))
    alert_events.sort(key=lambda a: a.get("wall_time") or 0.0)
    alerts = {
        "fired_total": fired,
        "resolved_total": resolved,
        "active": sorted(r for r, v in active.items() if v >= 1.0),
        "events": alert_events,
    }

    # ---- autoscaling & streaming (resilience/autoscale.py,
    # dataset/stream.py) ------------------------------------------------
    decisions: dict = {}
    for labels, s, _host in _metric_samples(
            snaps, names.AUTOSCALE_DECISIONS_TOTAL):
        key = f"{labels.get('direction', '?')}:{labels.get('reason', '?')}"
        decisions[key] = decisions.get(key, 0.0) + float(
            s.get("value", 0.0))
    resumes: dict = {}
    for labels, s, _host in _metric_samples(snaps, names.RESUMES_TOTAL):
        key = labels.get("resize", "?")
        resumes[key] = resumes.get(key, 0.0) + float(s.get("value", 0.0))

    def _metric_max(name):
        vals = [float(s.get("value", 0.0))
                for _l, s, _h in _metric_samples(snaps, name)]
        return max(vals) if vals else None

    def _metric_sum(name):
        return sum(float(s.get("value", 0.0))
                   for _l, s, _h in _metric_samples(snaps, name))

    autoscale_events.sort(key=lambda a: a.get("wall_time") or 0.0)
    stream_records = _metric_sum(names.STREAM_RECORDS_TOTAL)
    autoscale = {
        "decisions_total": decisions,
        "resumes_total": resumes,
        "events": autoscale_events,
        "stream": None if not stream_records else {
            "records_total": stream_records,
            "offset": _metric_max(names.STREAM_OFFSET),
            "watermark": _metric_max(names.STREAM_WATERMARK),
            "buffer_depth": _metric_max(names.STREAM_BUFFER_DEPTH),
            "lag_records": _metric_max(names.STREAM_LAG_RECORDS),
            "backpressure_waits": _metric_sum(
                names.STREAM_BACKPRESSURE_WAITS_TOTAL),
        },
    }

    # ---- fleet simulation (bigdl_tpu/sim, scripts/fleet_sim.py) ------
    # scenario verdicts ride fleet.scenario trace events; the scrape
    # latency gauge (names.FLEET_SCRAPE_SECONDS) comes from the
    # bounded-pool concurrent peer scrape
    fleet_scrape = None
    for _labels, s, _host in _metric_samples(
            snaps, names.FLEET_SCRAPE_SECONDS):
        v = float(s.get("value", 0.0))
        fleet_scrape = v if fleet_scrape is None else max(fleet_scrape,
                                                          v)
    fleet = None
    if fleet_events or fleet_scrape is not None:
        fleet_events.sort(key=lambda a: a.get("wall_time") or 0.0)
        fleet = {
            "scenarios": fleet_events,
            "scrape_seconds": fleet_scrape,
            "decisions_total": decisions,
            "alert_episodes": {"fired": fired, "resolved": resolved},
        }

    # ---- serving tier (serving/ package) -----------------------------
    def _hist_stats(metric, key_labels=("engine", "kind")):
        """Per-label-combo count/mean/p50/p95/p99 from the snapshot's
        cumulative histogram buckets (summed across hosts — cumulative
        counts add)."""
        acc: dict = {}
        for labels, s, _host in _metric_samples(snaps, metric):
            key = ":".join(labels.get(k, "?") for k in key_labels)
            cur = acc.setdefault(key, {"count": 0, "sum": 0.0,
                                       "buckets": {}})
            cur["count"] += int(s.get("count", 0))
            cur["sum"] += float(s.get("sum", 0.0))
            for le, c in s.get("buckets", []):
                le_f = float("inf") if le in ("+Inf", "inf") \
                    else float(le)
                cur["buckets"][le_f] = cur["buckets"].get(le_f, 0.0) \
                    + float(c)
        out = {}
        for key, cur in acc.items():
            total = cur["count"]
            finite = sorted(b for b in cur["buckets"]
                            if b != float("inf"))

            def q(p, _cur=cur, _total=total, _finite=finite):
                if _total <= 0:
                    return None
                for le in _finite:
                    if _cur["buckets"][le] >= p * _total:
                        return le
                return _finite[-1] if _finite else None

            out[key] = {"count": total,
                        "mean_s": (cur["sum"] / total) if total else None,
                        "p50_s": q(0.5), "p95_s": q(0.95),
                        "p99_s": q(0.99)}
        return out

    serve_requests: dict = {}
    for labels, s, _host in _metric_samples(
            snaps, names.SERVE_REQUESTS_TOTAL):
        key = f"{labels.get('engine', '?')}:{labels.get('status', '?')}"
        serve_requests[key] = serve_requests.get(key, 0.0) + float(
            s.get("value", 0.0))
    slo_vals = [float(s.get("value", 0.0)) for _l, s, _h in
                _metric_samples(snaps, names.SERVE_LATENCY_SLO_RATIO)]
    serving = None
    if serve_requests or slo_vals:
        serving = {
            "requests_total": serve_requests,
            "tokens_total": _metric_sum(names.SERVE_TOKENS_TOTAL),
            "tokens_per_second": _metric_max(
                names.SERVE_TOKENS_PER_SECOND),
            "batch_occupancy": _metric_max(
                names.SERVE_BATCH_OCCUPANCY),
            "queue_depth": _metric_max(names.SERVE_QUEUE_DEPTH),
            "kv_pages_in_use": _metric_max(
                names.SERVE_KV_PAGES_IN_USE),
            "admission_waits": _metric_sum(
                names.SERVE_ADMISSION_WAITS_TOTAL),
            "preemptions": _metric_sum(
                names.SERVE_PREEMPTIONS_TOTAL),
            "slo_ratio": min(slo_vals) if slo_vals else None,
            "latency": _hist_stats(names.REQUEST_LATENCY_SECONDS),
            "decode_attn_ms": _metric_max(
                names.SERVE_DECODE_ATTN_MS),
            "decode_hbm_bytes_per_token": _metric_max(
                names.SERVE_DECODE_HBM_BYTES_PER_TOKEN),
        }

    # ---- request traces (obs/reqtrace.py) ----------------------------
    # per-hop p99 attribution: group each kept trace's req.* spans by
    # hop key, then average the hop times over the slowest e2e decile —
    # that is the "where did the p99 go" answer
    reqtrace = None
    if reqtrace_spans:
        from bigdl_tpu.serving.spans import HOP_ORDER, hop_key

        traces = []
        for tid, e in reqtrace_spans.items():
            hops: dict = {}
            route = 0.0
            for name, dur in e["spans"]:
                k = hop_key(name)
                if k == "route":
                    # the router's whole-request envelope IS the
                    # measured e2e; the other hops partition it
                    route = max(route, dur)
                else:
                    hops[k] = hops.get(k, 0.0) + dur
            hop_sum = sum(hops.values())
            e2e = route if route > 0 else hop_sum
            traces.append({
                "trace": tid, "request": e["request"],
                "hosts": len(e["hosts"]), "e2e_s": e2e, "hops": hops,
                "coverage": (hop_sum / e2e) if e2e > 0 else None})
        traces.sort(key=lambda t: -t["e2e_s"])
        n_slow = max(1, len(traces) // 10)
        slow = traces[:n_slow]
        hop_means = {}
        for k in HOP_ORDER:
            if k == "route":
                continue
            vals = [t["hops"].get(k, 0.0) for t in slow]
            if any(vals):
                hop_means[k] = sum(vals) / len(vals)
        cov = [t["coverage"] for t in slow
               if t["coverage"] is not None]
        reqtrace = {
            "traces": len(traces),
            "cross_host": sum(1 for t in traces if t["hosts"] > 1),
            "slow_decile": {
                "count": n_slow,
                "e2e_mean_s": sum(t["e2e_s"] for t in slow) / n_slow,
                "hop_mean_s": {k: round(v, 6)
                               for k, v in hop_means.items()},
                "coverage": (sum(cov) / len(cov)) if cov else None,
            },
            "slowest": [
                {"trace": t["trace"], "request": t["request"],
                 "e2e_s": round(t["e2e_s"], 6),
                 "hops": {k: round(v, 6) for k, v in sorted(
                     t["hops"].items(), key=lambda kv: -kv[1])}}
                for t in traces[:5]],
        }

    # ---- overlapped step (ISSUE 11: bucketed exchange, async
    # checkpointing, double-buffered input) ----------------------------
    buckets = _metric_max(names.OVERLAP_BUCKETS)
    overlap = {
        "buckets": buckets,
        "exposed_comm_fraction": _metric_max(
            names.OVERLAP_EXPOSED_COMM_FRACTION),
        "exposed_comm_seconds_per_step": _metric_max(
            names.OVERLAP_EXPOSED_COMM_SECONDS),
        "checkpoint_snapshot_seconds": _metric_max(
            names.CHECKPOINT_SNAPSHOT_SECONDS),
        "checkpoint_write_seconds": _metric_max(
            names.CHECKPOINT_WRITE_SECONDS),
        "async_checkpoint_writes": ckpt_async_writes,
        "checkpoint_snapshots": ckpt_snapshots,
    }

    # per-device HBM peaks (bigdl_hbm_peak_bytes, max across snapshots)
    hbm: dict = {}
    for labels, s, _host in _metric_samples(snaps, names.HBM_PEAK_BYTES):
        d = labels.get("device", "?")
        hbm[d] = max(hbm.get(d, 0.0), float(s.get("value", 0.0)))
    health = {
        "grad_norm": _by_layer(names.GRAD_NORM),
        "param_norm": _by_layer(names.PARAM_NORM),
        "update_ratio": _by_layer(names.UPDATE_RATIO),
        "nonfinite_layers_total": _summed(
            names.NONFINITE_LAYERS_TOTAL, "layer"),
        "anomalies_total": _summed(
            names.NUMERICS_ANOMALIES_TOTAL, "kind"),
        "nonfinite_events": nonfinite_events,
        "anomaly_events": anomaly_events,
        "step_flops": max(step_flops) if step_flops else None,
        "mfu": max(mfu) if mfu else None,
    }

    # ---- continuous profiles + debug bundles (obs/prof.py,
    # obs/bundle.py) ----------------------------------------------------
    # profile shards are the obs.flush() dumps (prof.*.profile.json);
    # bundles come from the manifest-verified inventory, so a torn
    # bundle shows up flagged instead of silently counted as good
    prof_shards: list = []
    prof_dirs = []
    for d in (metrics_dir or trace_dir, trace_dir):
        if d and d not in prof_dirs:
            prof_dirs.append(d)
    for d in prof_dirs:
        if not os.path.isdir(d):
            continue
        for fn in sorted(os.listdir(d)):
            if not fn.endswith(".profile.json"):
                continue
            try:
                with open(os.path.join(d, fn), encoding="utf-8") as fh:
                    prof_shards.append(json.load(fh))
            except (OSError, ValueError):
                continue
    from bigdl_tpu.obs import bundle as _bundle
    bdir = bundle_dir
    if bdir is None:
        from bigdl_tpu.config import refresh_from_env
        bdir = refresh_from_env().obs.bundle_dir
    if bdir is None:
        cand = os.path.join(metrics_dir or trace_dir, "bundles")
        bdir = cand if os.path.isdir(cand) else None
    bundles = _bundle.inventory(bdir) if bdir else []
    profiles = None
    if prof_shards or bundles:
        prof_phases: dict = {}
        for sh in prof_shards:
            for phase, p in (sh.get("phases") or {}).items():
                cur = prof_phases.setdefault(
                    phase, {"samples": 0, "frames": {}})
                cur["samples"] += int(p.get("samples", 0))
                for label, n in p.get("frames") or []:
                    cur["frames"][label] = \
                        cur["frames"].get(label, 0) + int(n)
        for p in prof_phases.values():
            p["frames"] = sorted(p["frames"].items(),
                                 key=lambda kv: -kv[1])[:8]
        oh_vals = [float(sh.get("overhead_ratio") or 0.0)
                   for sh in prof_shards]
        live_oh = _metric_max(names.PROF_OVERHEAD_RATIO)
        if live_oh is not None:
            oh_vals.append(float(live_oh))
        profiles = {
            "samples": sum(int(sh.get("samples") or 0)
                           for sh in prof_shards),
            "skipped": sum(int(sh.get("skipped") or 0)
                           for sh in prof_shards),
            "overhead_ratio": max(oh_vals) if oh_vals else None,
            "phases": prof_phases,
            "bundle_dir": bdir,
            "bundles": bundles,
            "bundles_valid": sum(1 for b in bundles if b.get("ok")),
        }

    return {
        "trace_dir": trace_dir,
        "metrics_dir": metrics_dir or trace_dir,
        "hosts": per_host,
        "n_hosts": len({h["host"] for h in hosts.values()}),
        "compile": {
            "events_in_trace": compile_events,
            "count_from_metrics": compile_count or None,
        },
        "collective_bytes_total": coll_total,
        "collective_bytes_per_step": coll_step,
        "wire_savings_ratio": max(savings) if savings else None,
        "wire_savings_by_path": savings_by_path,
        "resilience_events": resilience,
        "slow_steps": slow_steps,
        "alerts": alerts,
        "serving": serving,
        "reqtrace": reqtrace,
        "autoscale": autoscale,
        "fleet": fleet,
        "overlap": overlap,
        "health": health,
        "goodput": gp,
        "stragglers": stragglers,
        "hbm_peak_bytes": hbm,
        "tuner": tuner,
        "profiles": profiles,
    }


def _fmt_bytes(b: float) -> str:
    for unit in ("B", "KiB", "MiB", "GiB", "TiB"):
        if abs(b) < 1024 or unit == "TiB":
            return f"{b:.1f}{unit}" if unit != "B" else f"{b:.0f}B"
        b /= 1024.0
    return f"{b:.1f}TiB"


def render_text(rep: dict) -> str:
    lines = ["== bigdl_tpu run report ==",
             f"trace dir:   {rep['trace_dir']}",
             f"metrics dir: {rep['metrics_dir']}",
             f"hosts:       {rep['n_hosts']}", ""]
    lines.append("-- step times (computing spans, per host) --")
    for key, h in sorted(rep["hosts"].items()):
        st = h["step_time_s"]

        def f(v):
            return "-" if v is None else f"{v * 1000:.2f}ms"

        lines.append(
            f"  {key}: n={h['steps']} p50={f(st['p50'])} "
            f"p95={f(st['p95'])} p99={f(st['p99'])} max={f(st['max'])}")
    lines.append("")
    lines.append("-- compiles --")
    cc = rep["compile"]["count_from_metrics"]
    lines.append(f"  count (metrics): "
                 f"{int(cc) if cc is not None else 'n/a'}")
    for ev in rep["compile"]["events_in_trace"][:8]:
        lines.append(f"  host{ev['host']} {ev['name']}: {ev['seconds']}s")
    hbm = rep.get("hbm_peak_bytes") or {}
    if hbm:
        lines.append("  hbm peak: " + ", ".join(
            f"d{d} {_fmt_bytes(b)}" for d, b in sorted(hbm.items())))
    lines.append("")
    lines.append("-- collective wire bytes (total across hosts) --")
    if not rep["collective_bytes_total"]:
        lines.append("  (none recorded)")
    for key, b in sorted(rep["collective_bytes_total"].items()):
        per = rep["collective_bytes_per_step"].get(key)
        extra = f"  ({_fmt_bytes(per)}/step)" if per else ""
        lines.append(f"  {key:28s} {_fmt_bytes(b):>12s}{extra}")
    if rep.get("wire_savings_by_path"):
        by = ", ".join(f"{p} {r:.2f}x" for p, r in
                       sorted(rep["wire_savings_by_path"].items()))
        lines.append(f"  wire savings vs uncompressed exchange: {by}")
    elif rep["wire_savings_ratio"]:
        lines.append(f"  wire savings vs f32 exchange: "
                     f"{rep['wire_savings_ratio']:.2f}x")
    lines.append("")
    lines.append("-- resilience events --")
    if not rep["resilience_events"]:
        lines.append("  (clean run)")
    for name, n in sorted(rep["resilience_events"].items()):
        lines.append(f"  {name}: {n}")
    lines.append("")
    lines.append("-- slow steps --")
    if not rep["slow_steps"]:
        lines.append("  (none)")
    for s in rep["slow_steps"][:8]:
        lines.append(
            f"  host{s.get('host')} step {s.get('step')}: "
            f"{float(s.get('dur_s', 0)) * 1000:.1f}ms "
            f"(median {float(s.get('median_s', 0)) * 1000:.1f}ms, "
            f"breakdown {s.get('breakdown')})")
    lines.append("")
    lines.append("-- alerts --")
    al = rep.get("alerts") or {}
    if not (al.get("fired_total") or al.get("events")):
        lines.append("  (none fired)")
    else:
        for rule in al.get("active", []):
            lines.append(f"  FIRING {rule}")
        for key, n in sorted(al.get("fired_total", {}).items()):
            rule = key.split("[", 1)[0]
            res = al.get("resolved_total", {}).get(rule, 0)
            lines.append(f"  {key:40s} fired {int(n)}x, "
                         f"resolved {int(res)}x")
        for ev in al.get("events", [])[-8:]:
            lines.append(
                f"  host{ev.get('host')} {ev.get('state'):>8s} "
                f"{ev.get('rule')} [{ev.get('severity')}] "
                f"{ev.get('metric')}={ev.get('value')}")
    lines.append("")
    lines.append("-- serving --")
    sv = rep.get("serving")
    if not sv:
        lines.append("  (no serving activity — see bigdl_tpu/serving)")
    else:
        req = ", ".join(f"{k} {int(n)}" for k, n in
                        sorted(sv.get("requests_total", {}).items()))
        lines.append(f"  requests: {req or '(none)'}")
        tps = sv.get("tokens_per_second")
        lines.append(
            f"  tokens: {int(sv.get('tokens_total') or 0)} generated"
            + (f", {tps:.1f} tok/s" if tps else ""))
        occ = sv.get("batch_occupancy")
        lines.append(
            "  batcher: occupancy "
            + (f"{occ * 100:.0f}%" if occ is not None else "n/a")
            + f", queue depth {sv.get('queue_depth')}"
            + f", {int(sv.get('admission_waits') or 0)} admission "
              "wait(s)"
            + f", {int(sv.get('preemptions') or 0)} preemption(s)")
        for key, st in sorted((sv.get("latency") or {}).items()):
            def ms(v):
                return "-" if v is None else f"{v * 1000:.1f}ms"

            lines.append(
                f"  latency {key:16s} n={st['count']} "
                f"p50<={ms(st['p50_s'])} p95<={ms(st['p95_s'])} "
                f"p99<={ms(st['p99_s'])}")
        if sv.get("slo_ratio") is not None:
            lines.append(f"  latency SLO ratio: {sv['slo_ratio']:.3f}")
        dms = sv.get("decode_attn_ms")
        if dms is not None:
            bpt = sv.get("decode_hbm_bytes_per_token")
            lines.append(
                f"  decode: {dms:.2f}ms/step"
                + (f", {bpt / 1e6:.2f} MB/token (HBM)"
                   if bpt is not None else ""))
    lines.append("")
    lines.append("-- request traces --")
    rt = rep.get("reqtrace")
    if not rt:
        lines.append("  (none kept — set BIGDL_REQTRACE_SAMPLE>0, "
                     "anomalies are always kept)")
    else:
        lines.append(
            f"  kept traces: {rt['traces']}"
            + (f" ({rt['cross_host']} cross-host)"
               if rt.get("cross_host") else ""))
        sd = rt["slow_decile"]
        cov = sd.get("coverage")
        lines.append(
            f"  slowest decile (n={sd['count']}): "
            f"e2e mean {sd['e2e_mean_s'] * 1000:.1f}ms"
            + (f", hop coverage {cov * 100:.0f}%"
               if cov is not None else ""))
        total = sum(sd["hop_mean_s"].values()) or 1.0
        for hop, v in sorted(sd["hop_mean_s"].items(),
                             key=lambda kv: -kv[1]):
            lines.append(f"    {hop:10s} {v * 1000:9.2f}ms  "
                         f"{v / total * 100:5.1f}%")
        for t in rt.get("slowest", [])[:3]:
            worst = next(iter(t["hops"]), "-")
            lines.append(
                f"  trace {t['trace']} (request {t['request']}): "
                f"{t['e2e_s'] * 1000:.1f}ms, worst hop {worst}")
    lines.append("")
    lines.append("-- profiles --")
    pr = rep.get("profiles")
    if not pr:
        lines.append("  (no profiler activity — set BIGDL_PROF_HZ>0; "
                     "bundles via BIGDL_BUNDLE_DIR)")
    else:
        oh = pr.get("overhead_ratio")
        lines.append(
            f"  samples: {int(pr.get('samples') or 0)}"
            f" ({int(pr.get('skipped') or 0)} skipped by budget)"
            + (f", overhead {oh * 100:.2f}%" if oh is not None else ""))
        prof_phases = pr.get("phases") or {}
        total_samples = sum(
            int(p.get("samples") or 0)
            for p in prof_phases.values()) or 1
        for phase, p in sorted(prof_phases.items(),
                               key=lambda kv: -kv[1]["samples"])[:6]:
            n_ph = int(p.get("samples") or 0)
            lines.append(f"  {phase:24s} {n_ph:6d} samples  "
                         f"{n_ph / total_samples * 100:5.1f}%")
            for label, n in (p.get("frames") or [])[:3]:
                lines.append(
                    f"    {label:40s} {int(n):6d}  "
                    f"{int(n) / max(n_ph, 1) * 100:5.1f}%")
        bundles = pr.get("bundles") or []
        if bundles:
            lines.append(
                f"  bundles: {int(pr.get('bundles_valid') or 0)}/"
                f"{len(bundles)} valid in {pr.get('bundle_dir')}")
            for b in bundles[-4:]:
                if b.get("ok"):
                    lines.append(
                        f"    {b['name']}: ok "
                        f"({_fmt_bytes(float(b.get('bytes') or 0))}, "
                        f"{b.get('trigger')})")
                else:
                    lines.append(f"    {b['name']}: "
                                 f"SKIPPED ({b.get('reason')})")
    lines.append("")
    lines.append("-- autoscaling & stream --")
    asc = rep.get("autoscale") or {}
    if not (asc.get("decisions_total") or asc.get("resumes_total")
            or asc.get("stream") or asc.get("events")):
        lines.append("  (no autoscale/stream activity)")
    else:
        for key, n in sorted(asc.get("decisions_total", {}).items()):
            lines.append(f"  decision {key:28s} {int(n)}x")
        if asc.get("resumes_total"):
            lines.append("  resumes: " + ", ".join(
                f"{k} {int(n)}x"
                for k, n in sorted(asc["resumes_total"].items())))
        st = asc.get("stream")
        if st:
            wm = st.get("watermark")
            lines.append(
                f"  stream: {int(st['records_total'])} records trained, "
                f"offset {int(st['offset'] or 0)}"
                + (f", watermark {wm:g}" if wm is not None else ""))
            lines.append(
                f"  stream buffer: depth {st.get('buffer_depth')}, "
                f"lag {st.get('lag_records')}, "
                f"{int(st.get('backpressure_waits') or 0)} "
                "backpressure wait(s)")
        for ev in asc.get("events", [])[-8:]:
            if ev.get("event") == "elastic.autoscale":
                if ev.get("suppressed"):
                    lines.append(
                        f"  host{ev.get('host')} suppressed "
                        f"({ev.get('suppressed')}) rule {ev.get('rule')}")
                else:
                    lines.append(
                        f"  host{ev.get('host')} {ev.get('direction')} "
                        f"{ev.get('old_world')}->{ev.get('new_world')} "
                        f"[{ev.get('reason')}]"
                        + (" DRY-RUN" if ev.get("dry_run") else ""))
            elif ev.get("event") == "supervisor.backoff":
                lines.append(
                    f"  host{ev.get('host')} backoff {ev.get('kind')} "
                    f"{float(ev.get('delay_s') or 0):.2f}s (rc "
                    f"{ev.get('rc')})")
    lines.append("")
    lines.append("-- fleet simulation --")
    fl = rep.get("fleet")
    if not fl:
        lines.append("  (no fleet sim activity — scripts/fleet_sim.py "
                     "/ run-tests.sh --fleet)")
    else:
        for ev in (fl.get("scenarios") or [])[-8:]:
            bad = sorted(k for k, v in (ev.get("invariants")
                                        or {}).items() if not v)
            lines.append(
                f"  {str(ev.get('scenario')):14s} "
                f"{'PASS' if ev.get('ok') else 'FAIL'} "
                f"hosts={ev.get('hosts')} ticks={ev.get('ticks')} "
                f"world->{ev.get('final_world')} "
                f"decisions={ev.get('decisions')} "
                f"episodes={ev.get('episodes')}"
                + (f"  FAILED: {','.join(bad)}" if bad else ""))
        for key, n in sorted((fl.get("decisions_total") or {}).items()):
            lines.append(f"  decision {key:28s} {int(n)}x")
        ep = fl.get("alert_episodes") or {}
        if ep.get("fired"):
            lines.append("  alert episodes: " + ", ".join(
                f"{key.split('[', 1)[0]} fired "
                f"{int(n)}x/resolved "
                f"{int(ep.get('resolved', {}).get(key.split('[', 1)[0], 0))}x"
                for key, n in sorted(ep["fired"].items())))
        if fl.get("scrape_seconds") is not None:
            lines.append(f"  scrape cycle: "
                         f"{fl['scrape_seconds'] * 1000:.1f}ms "
                         "(bounded-pool concurrent peer scrape)")
    lines.append("")
    lines.append("-- overlap --")
    ov = rep.get("overlap") or {}
    has_overlap = (ov.get("buckets") or 0) > 1 \
        or ov.get("async_checkpoint_writes") \
        or ov.get("checkpoint_snapshot_seconds") is not None
    if not has_overlap:
        lines.append("  (no overlap activity — set BIGDL_OVERLAP_BUCKET_MB"
                     " / BIGDL_CHECKPOINT_ASYNC / "
                     "BIGDL_INPUT_DOUBLE_BUFFER)")
    else:
        b = ov.get("buckets")
        if b and b > 1:
            frac = ov.get("exposed_comm_fraction")
            secs = ov.get("exposed_comm_seconds_per_step")
            lines.append(
                f"  gradient exchange: {int(b)} buckets, exposed comm "
                + (f"{frac * 100:.0f}% of the wire"
                   if frac is not None else "n/a")
                + (f" (~{secs * 1000:.2f}ms/step)"
                   if secs is not None else ""))
        elif b:
            lines.append("  gradient exchange: monolithic (1 bucket — "
                         "everything exposed)")
        snap = ov.get("checkpoint_snapshot_seconds")
        wr = ov.get("checkpoint_write_seconds")
        if snap is not None or wr is not None:
            lines.append(
                "  checkpoint: snapshot "
                + (f"{snap * 1000:.1f}ms (blocking)"
                   if snap is not None else "n/a")
                + ", write "
                + (f"{wr * 1000:.1f}ms" if wr is not None else "n/a")
                + (f" — {int(ov['async_checkpoint_writes'])} async "
                   "write(s) off the critical path"
                   if ov.get("async_checkpoint_writes") else ""))
    lines.append("")
    lines.append("-- goodput --")
    gp = rep.get("goodput")
    if not gp:
        lines.append("  (no goodput ledger — set BIGDL_METRICS_DIR)")
    else:
        hosts = ",".join(str(h) for h in gp["hosts"])
        lines.append(f"  attempts: {gp['attempts']} (hosts {hosts}), "
                     f"{gp['steps']} productive steps")
        ratio = gp["goodput_ratio"]
        lines.append(
            f"  wall {gp['total_s']:.2f}s | productive "
            f"{gp['productive_s']:.2f}s | goodput ratio "
            + (f"{ratio:.3f}" if ratio is not None else "n/a"))
        if gp["badput_s"]:
            lines.append("  badput: " + "; ".join(
                f"{cause} {secs:.2f}s"
                for cause, secs in sorted(gp["badput_s"].items())))
        if gp["unknown_s"]:
            lines.append(f"  unknown gaps: {gp['unknown_s']:.2f}s")
        if gp["rework_steps"]:
            lines.append(f"  rework: {gp['rework_steps']} replayed "
                         "step(s) after restart")
        bn = gp.get("bottleneck")
        if bn:
            lines.append(
                f"  bottleneck: {bn['label']} (input share "
                f"{bn['input_fraction'] * 100:.0f}%, via {bn['source']})")
    strag = rep.get("stragglers") or {}
    if strag.get("stragglers"):
        med = strag.get("median_p50") or 0.0
        for h in strag["stragglers"]:
            info = strag["hosts"].get(h) or strag["hosts"].get(str(h), {})
            p50 = info.get("p50") or 0.0
            lines.append(
                f"  STRAGGLER host{h}: p50 {p50 * 1000:.1f}ms vs "
                f"cross-host median {med * 1000:.1f}ms "
                f"(factor {strag['factor']:g}, "
                f"{info.get('straggler_steps', 0)} flagged steps)")
    elif len(rep["hosts"]) > 1:
        lines.append("  stragglers: none flagged")
    lines.append("")
    lines.append("-- training health --")
    h = rep.get("health") or {}
    if not (h.get("grad_norm") or h.get("nonfinite_layers_total")
            or h.get("anomalies_total")):
        lines.append("  (no health telemetry — set BIGDL_HEALTH_EVERY)")
    else:
        layers = sorted(set(h.get("grad_norm", {}))
                        | set(h.get("update_ratio", {})))
        for layer in layers[:12]:
            g = h.get("grad_norm", {}).get(layer)
            p = h.get("param_norm", {}).get(layer)
            r = h.get("update_ratio", {}).get(layer)

            def f(v):
                return "-" if v is None else f"{v:.4g}"

            lines.append(f"  {layer:24s} grad={f(g):>10s} "
                         f"param={f(p):>10s} upd/w={f(r):>10s}")
        if len(layers) > 12:
            lines.append(f"  ... {len(layers) - 12} more layers "
                         "(use --json for all)")
        if h.get("step_flops"):
            mfu = f" mfu={h['mfu']:.4f}" if h.get("mfu") else ""
            lines.append(f"  HLO step FLOPs: {h['step_flops']:.4g}{mfu}")
        for layer, n in sorted(h.get("nonfinite_layers_total",
                                     {}).items()):
            lines.append(f"  NON-FINITE {layer}: {int(n)} step(s)")
        for ev in h.get("nonfinite_events", [])[:8]:
            lines.append(
                f"  host{ev.get('host')} step {ev.get('step')}: first "
                f"offender {ev.get('first')} (all: {ev.get('layers')})")
        for kind, n in sorted(h.get("anomalies_total", {}).items()):
            lines.append(f"  ANOMALY {kind}: {int(n)}")
        for ev in h.get("anomaly_events", [])[:8]:
            lines.append(
                f"  host{ev.get('host')} step {ev.get('step')}: "
                f"{ev.get('kind')} {float(ev.get('value', 0)):.4g} vs "
                f"median {float(ev.get('median', 0)):.4g}")
    lines.append("")
    lines.append("-- kernel auto-tuner --")
    tn = rep.get("tuner") or {}
    if not (tn.get("decisions_total") or tn.get("events")):
        lines.append("  (no tuner activity — set BIGDL_TUNER=1)")
    else:
        for key, n in sorted(tn.get("decisions_total", {}).items()):
            lines.append(f"  {key:28s} {int(n)} decision(s)")
        lines.append(
            f"  cache: {int(tn.get('cache_hits', 0))} hit(s), "
            f"{int(tn.get('cache_misses', 0))} miss(es), "
            f"{int(tn.get('measurements', 0))} wall-clock probe(s)")
        for ev in tn.get("events", [])[:8]:
            lines.append(
                f"  host{ev.get('host')} {ev.get('site')}: "
                f"{ev.get('label')} via {ev.get('source')} "
                f"(static {ev.get('static')}) [{ev.get('key')}]")
    lines.append("")
    lines.append("-- slowest spans per host --")
    for key, h in sorted(rep["hosts"].items()):
        for sp in h["slowest_spans"]:
            step = "" if sp["step"] is None else f" step={sp['step']}"
            lines.append(f"  {key} {sp['name']}: "
                         f"{sp['dur_s'] * 1000:.2f}ms{step}")
    return "\n".join(lines) + "\n"


def _host_badness(h: dict) -> tuple:
    """Gating-signal rank for the --watch host table: the host an
    operator must look at first sorts highest (bad/stale status, firing
    alerts, deep queue, old step stamp, poor goodput)."""
    status = str(h.get("status") or "?")
    rank = {"ok": 0, "idle": 1}.get(status, 3)
    gr = h.get("goodput_ratio")
    return (rank, len(h.get("alerts") or []),
            float(h.get("queue_depth") or 0.0),
            float(h.get("step_age_s") or 0.0),
            1.0 - (float(gr) if gr is not None else 1.0))


def render_fleet(fleet: dict, max_hosts: Optional[int] = None) -> str:
    """The live-fleet header ``--watch`` puts above the report body.

    The host table is capped to the worst ``max_hosts`` hosts by gating
    signal (default ``BIGDL_WATCH_HOSTS``) — at 1000 hosts the frame
    shows the 16 an operator must look at and accounts for the rest
    with one "... and N more" line; the full count always rides
    ``fleet['n_hosts']`` for ``--json`` consumers."""
    if max_hosts is None:
        from bigdl_tpu.config import refresh_from_env

        max_hosts = refresh_from_env().obs.watch_hosts
    hosts = fleet.get("hosts") or {}
    n_total = int(fleet.get("n_hosts") or len(hosts))
    lines = [f"-- live fleet ({fleet.get('mode')}) --"]
    if not hosts:
        lines.append("  (no hosts visible yet)")
    ranked = sorted(hosts.items(), key=lambda kv: str(kv[0]))
    ranked.sort(key=lambda kv: _host_badness(kv[1]), reverse=True)
    shown = ranked if int(max_hosts) <= 0 else ranked[:int(max_hosts)]
    for host, h in shown:
        gr = h.get("goodput_ratio")
        age = h.get("step_age_s")
        qd = h.get("queue_depth")
        po = h.get("prof_overhead")
        nb = h.get("bundles")
        lines.append(
            f"  host{host}: status={h.get('status')} "
            f"step={h.get('step')}"
            + (f" age={age:.1f}s" if age is not None else "")
            + (f" goodput={gr:.3f}" if gr is not None else "")
            + (f" queue={qd:g}" if qd is not None else "")
            + (f" prof={po * 100:.2f}%" if po is not None else "")
            + (f" bundles={int(nb)}" if nb else "")
            + f"  [{h.get('source')}]")
        for a in h.get("alerts") or []:
            lines.append(f"    FIRING {a.get('rule')}"
                         + (f" [{a.get('severity')}]"
                            if a.get("severity") else ""))
    hidden = len(ranked) - len(shown)
    if hidden > 0:
        lines.append(f"  ... and {hidden} more host(s) "
                     f"(worst {len(shown)} of {n_total} shown — "
                     "raise BIGDL_WATCH_HOSTS)")
    errors = fleet.get("errors") or {}
    for src, err in sorted(errors.items()):
        lines.append(f"  DOWN {src}: {err}")
    # skew-stale hosts: scraped fine but excluded from fleet merges
    # (failed peers above already carry their error as the reason)
    for src, why in sorted((fleet.get("stale") or {}).items()):
        if src not in errors:
            lines.append(f"  STALE {src}: {why}")
    return "\n".join(lines) + "\n"


#: the fleet-trend series ``--watch`` sparklines out of the retention
#: store (label, metric family) — what ``ingest_snapshot`` retains
_TREND_SERIES = (
    ("queue", names.SERVE_QUEUE_DEPTH),
    ("goodput", names.GOODPUT_RATIO),
    ("scrape_s", names.FLEET_SCRAPE_SECONDS),
    ("stale", names.FLEET_STALE_HOSTS),
)


def render_trends(store, ring: str = "raw", width: int = 32) -> str:
    """Sparkline block for the --watch header: one line per retained
    fleet-trend series (empty string until the store has points)."""
    lines = []
    for label, name in _TREND_SERIES:
        pts = store.series(name, ring=ring)
        if not pts:
            continue
        lines.append(f"  {label:9s} "
                     f"{store.spark(name, ring=ring, width=width)}  "
                     f"{pts[-1][1]:g}")
    if not lines:
        return ""
    return "-- trends (retention store) --\n" + "\n".join(lines) + "\n"


def main(argv=None) -> int:
    import argparse
    import time as _time

    ap = argparse.ArgumentParser(
        prog="python -m bigdl_tpu.obs.report",
        description="Render a run report from trace/metrics JSONL dirs "
                    "(--watch: a refreshing live view fed by peer "
                    "/metrics endpoints or shard tailing).")
    ap.add_argument("trace_dir", help="BIGDL_TRACE_DIR of the run")
    ap.add_argument("--metrics-dir", default=None,
                    help="BIGDL_METRICS_DIR (default: trace_dir)")
    ap.add_argument("--bundles", default=None,
                    help="debug-bundle dir for the profiles section "
                         "(default: BIGDL_BUNDLE_DIR, then "
                         "<metrics_dir>/bundles when it exists)")
    ap.add_argument("--json", action="store_true",
                    help="emit the machine-readable report")
    ap.add_argument("--watch", action="store_true",
                    help="refreshing terminal view with a live fleet "
                         "header (BIGDL_OBS_PEERS or --peers scrapes "
                         "live endpoints; otherwise tails the metrics "
                         "shards)")
    ap.add_argument("--peers", default=None,
                    help="comma-separated host:port live endpoints "
                         "(default BIGDL_OBS_PEERS)")
    ap.add_argument("--interval", type=float, default=2.0,
                    help="--watch refresh period in seconds (default 2)")
    ap.add_argument("--once", action="store_true",
                    help="render a single --watch frame and exit "
                         "(CI/testing)")
    args = ap.parse_args(argv)

    if args.watch:
        from bigdl_tpu.obs.aggregate import FleetAggregator
        from bigdl_tpu.obs.retain import RetentionStore

        from bigdl_tpu.config import refresh_from_env

        peers = args.peers if args.peers is not None else \
            refresh_from_env().obs.obs_peers
        agg = FleetAggregator(
            peers=peers,
            metrics_dir=args.metrics_dir or args.trace_dir)
        store = RetentionStore(
            directory=args.metrics_dir or args.trace_dir)
        store.load()  # prior frames' trends survive a watch restart
        while True:
            fleet = agg.snapshot()
            store.ingest_snapshot(_time.time(), fleet)
            rep = build_report(args.trace_dir, args.metrics_dir,
                               bundle_dir=args.bundles)
            rep["fleet"] = fleet
            rep["trends"] = store.summary()
            if args.json:
                print(json.dumps(rep, default=str), flush=True)
            else:
                frame = render_fleet(fleet) + render_trends(store) \
                    + "\n" + render_text(rep)
                if not args.once:
                    # ANSI clear+home: a refreshing view, not a scroll
                    print("\x1b[2J\x1b[H", end="")
                print(frame, end="", flush=True)
            if args.once:
                return 0
            try:
                _time.sleep(args.interval)
            except KeyboardInterrupt:
                return 0

    rep = build_report(args.trace_dir, args.metrics_dir,
                       bundle_dir=args.bundles)
    if not rep["hosts"]:
        print(f"no trace shards under {args.trace_dir}", flush=True)
        return 1
    if args.json:
        print(json.dumps(rep, default=str))
    else:
        print(render_text(rep), end="")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
