"""Black-box debug bundles — "what was the process doing" snapshots.

A bundle is one directory capturing everything the obs stack knows at
the moment of trouble, so a postmortem never depends on the process
surviving long enough for a human to attach tools:

=================  ====================================================
``ring.json``      the flight-recorder ring (tracer ``recent()``)
``metrics.json``   a full metrics-registry snapshot
``profile.json``   the continuous profiler's folded profile (prof.py)
``reqtraces.json`` every kept request trace in the reqtrace ring
``runtime.json``   step-time percentiles, compile stats, host RSS +
                   device-memory stats
``alerts.json``    currently-firing alerts + the triggering transition
``MANIFEST.json``  sha256 + size per file, written LAST
=================  ====================================================

Torn-write safety mirrors the checkpoint-manifest hardening in
``utils/serializer.py``: every file is written and fsynced inside a
``<name>.tmp`` staging directory, the manifest is written last (its
presence certifies the files it names were durable first), and one
``os.replace`` publishes the directory — a crash at ANY point leaves
either a complete bundle or a ``.tmp`` leftover that
:func:`verify_bundle` rejects and the report inventory skips.

Bundles are produced on alert ``firing`` transitions (exactly one per
(engine, rule, episode), per-rule rate-limited by
``BIGDL_BUNDLE_RATE_LIMIT``), by the restart supervisor around
hang/crash handling, and on demand via ``GET /debugz``.  Everything is
gated on ``BIGDL_BUNDLE_DIR``: unset, the automatic triggers are one
config read and no disk is ever touched.
"""

from __future__ import annotations

import hashlib
import json
import logging
import os
import threading
import time
from typing import List, Optional, Tuple

from bigdl_tpu.obs import names

log = logging.getLogger("bigdl_tpu.obs")

MANIFEST = "MANIFEST.json"
#: bundle payload files, in write order (the manifest is written last)
BUNDLE_FILES = ("ring.json", "metrics.json", "profile.json",
                "reqtraces.json", "runtime.json", "alerts.json")

_lock = threading.Lock()
_seq = 0
# (engine_uid, rule, episode) already bundled — the exactly-once set
_seen: set = set()
# (engine_uid, rule) -> wall time of its newest bundle (rate limiting)
_last_rule: dict = {}


def _sha256(path: str) -> str:
    h = hashlib.sha256()
    with open(path, "rb") as fh:
        for chunk in iter(lambda: fh.read(1 << 20), b""):
            h.update(chunk)
    return h.hexdigest()


def _fsync_dir(path: str):
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    finally:
        os.close(fd)


def _write_json(directory: str, name: str, payload) -> dict:
    path = os.path.join(directory, name)
    with open(path, "w", encoding="utf-8") as fh:
        json.dump(payload, fh, default=str)
        fh.flush()
        os.fsync(fh.fileno())
    return {"size": os.path.getsize(path), "sha256": _sha256(path)}


def _payloads(reason: str, trigger: str, context: Optional[dict]) -> dict:
    """Collect every snapshot the bundle carries.  Each source is
    isolated: one failing provider costs its own file's content (an
    ``{"error": ...}`` stub), never the bundle."""
    from bigdl_tpu import obs
    from bigdl_tpu.obs import alerts, prof, reqtrace

    sources = {
        "ring.json": lambda: obs.get_tracer().recent(),
        "metrics.json": lambda: obs.get_registry().snapshot(),
        "profile.json": lambda: prof.get_profiler().snapshot(),
        "reqtraces.json": lambda: reqtrace.get_collector().completed(),
        "runtime.json": lambda: obs.get_runtime().snapshot(),
        "alerts.json": lambda: {"active": alerts.get_engine().active(),
                                "reason": reason, "trigger": trigger,
                                "transition": context},
    }
    out = {}
    for fname, thunk in sources.items():
        try:
            out[fname] = thunk()
        except Exception as e:  # noqa: BLE001 — isolate provider failures
            log.exception("obs.bundle: %s provider failed", fname)
            out[fname] = {"error": f"{type(e).__name__}: {e}"}
    return out


def build_bundle(reason: str = "", trigger: str = "manual",
                 bundle_dir: Optional[str] = None,
                 context: Optional[dict] = None) -> str:
    """Write one bundle under ``bundle_dir`` (default
    ``BIGDL_BUNDLE_DIR``) and return its final directory path.

    Raises on hard failure (no directory configured, disk errors) —
    the automatic triggers wrap this; counted either way in
    ``bigdl_bundle_writes_total`` / ``bigdl_bundle_errors_total``."""
    global _seq
    from bigdl_tpu import obs

    if bundle_dir is None:
        from bigdl_tpu.config import refresh_from_env

        bundle_dir = refresh_from_env().obs.bundle_dir
    if not bundle_dir:
        raise ValueError("no bundle directory: pass bundle_dir or set "
                         "BIGDL_BUNDLE_DIR")
    reg = obs.get_registry()
    try:
        from bigdl_tpu.config import config

        host = int(config.process_id)
        with _lock:
            _seq += 1
            seq = _seq
        now = time.time()
        stamp = time.strftime("%Y%m%d-%H%M%S", time.gmtime(now))
        name = (f"bundle-{stamp}-h{host}-p{os.getpid()}"
                f"-{trigger}-{seq}")
        final = os.path.join(bundle_dir, name)
        tmp = final + ".tmp"
        os.makedirs(tmp, exist_ok=True)
        files = {}
        for fname, payload in _payloads(reason, trigger,
                                        context).items():
            files[fname] = _write_json(tmp, fname, payload)
        # manifest LAST: its presence certifies every file it names
        # was already durable when it was written
        _write_json(tmp, MANIFEST, {
            "format": 1, "reason": reason, "trigger": trigger,
            "ts": now, "host": host, "pid": os.getpid(),
            "files": files})
        os.replace(tmp, final)
        _fsync_dir(bundle_dir)
    except Exception:
        reg.counter(names.BUNDLE_ERRORS_TOTAL,
                    "Debug-bundle builds that failed").inc()
        raise
    reg.counter(names.BUNDLE_WRITES_TOTAL,
                "Debug bundles written, by trigger",
                labels=("trigger",)).labels(trigger=trigger).inc()
    reg.gauge(names.BUNDLE_LAST_WRITE_SECONDS,
              "Wall-clock timestamp of the newest debug bundle").set(now)
    log.warning("obs.bundle: wrote %s (%s)", final,
                reason or trigger)
    return final


# ----------------------------------------------------------- triggers
def on_alert_firing(transition: dict,
                    engine_uid: int = 0) -> Optional[str]:
    """The alert-engine hook: bundle exactly once per (engine, rule,
    episode), per-rule rate-limited, only when ``BIGDL_BUNDLE_DIR`` is
    set.  Returns the bundle path, or None when gated off/deduped."""
    from bigdl_tpu.config import refresh_from_env

    cfg = refresh_from_env().obs
    if not cfg.bundle_dir:
        return None
    rule = transition.get("rule")
    key = (engine_uid, rule, transition.get("episode"))
    now = time.time()
    with _lock:
        if key in _seen:
            return None
        last = _last_rule.get((engine_uid, rule))
        if cfg.bundle_rate_limit > 0 and last is not None \
                and now - last < cfg.bundle_rate_limit:
            log.info("obs.bundle: rate limit — no bundle for %s "
                     "episode %s (%.1fs since the rule's last, "
                     "limit %.1fs)", rule, transition.get("episode"),
                     now - last, cfg.bundle_rate_limit)
            return None
        # claim BEFORE the (slow) build: a second transition for the
        # same episode racing in never double-bundles
        _seen.add(key)
        _last_rule[(engine_uid, rule)] = now
    return build_bundle(
        reason=f"alert {rule} episode {transition.get('episode')}",
        trigger="alert", bundle_dir=cfg.bundle_dir, context=transition)


def reset():
    """Test hook: forget episode dedupe + rate-limit state."""
    global _seq
    with _lock:
        _seen.clear()
        _last_rule.clear()
        _seq = 0


# --------------------------------------------------------- inspection
def verify_bundle(path: str) -> Tuple[bool, str]:
    """``(ok, reason)`` — the checkpoint-manifest hardening applied to
    bundles: unreadable/missing manifest, a missing file, or a
    size/sha256 mismatch all fail; a ``.tmp`` directory is an
    interrupted write by construction."""
    if path.rstrip(os.sep).endswith(".tmp"):
        return False, "interrupted write (.tmp staging dir)"
    mpath = os.path.join(path, MANIFEST)
    if not os.path.isfile(mpath):
        return False, "no manifest"
    try:
        with open(mpath, encoding="utf-8") as fh:
            manifest = json.load(fh)
    except (OSError, ValueError) as e:
        return False, f"unreadable manifest: {e}"
    files = manifest.get("files")
    if not isinstance(files, dict) or not files:
        return False, "manifest names no files"
    for fname, meta in files.items():
        fpath = os.path.join(path, fname)
        if not os.path.isfile(fpath):
            return False, f"missing {fname}"
        size = os.path.getsize(fpath)
        if size != int(meta.get("size", -1)):
            return False, (f"{fname}: size {size} != manifest "
                           f"{meta.get('size')}")
        digest = _sha256(fpath)
        if digest != meta.get("sha256"):
            return False, f"{fname}: sha256 mismatch"
    return True, f"{len(files)} files verified"


def inventory(bundle_dir: Optional[str] = None) -> List[dict]:
    """Every bundle under ``bundle_dir`` (default ``BIGDL_BUNDLE_DIR``),
    newest last, each verified — invalid/torn entries are flagged so
    the report can show *and skip* them."""
    if bundle_dir is None:
        from bigdl_tpu.config import refresh_from_env

        bundle_dir = refresh_from_env().obs.bundle_dir
    if not bundle_dir or not os.path.isdir(bundle_dir):
        return []
    out = []
    for entry in sorted(os.listdir(bundle_dir)):
        if not entry.startswith("bundle-"):
            continue
        path = os.path.join(bundle_dir, entry)
        if not os.path.isdir(path):
            continue
        ok, why = verify_bundle(path)
        rec = {"name": entry, "path": path, "ok": ok, "reason": why,
               "trigger": None, "ts": None, "bytes": 0}
        if ok:
            try:
                with open(os.path.join(path, MANIFEST),
                          encoding="utf-8") as fh:
                    manifest = json.load(fh)
                rec["trigger"] = manifest.get("trigger")
                rec["ts"] = manifest.get("ts")
                rec["bundle_reason"] = manifest.get("reason")
                rec["bytes"] = sum(
                    int(m.get("size", 0))
                    for m in manifest.get("files", {}).values())
            except (OSError, ValueError):  # verified then torn: raced
                rec["ok"], rec["reason"] = False, "manifest vanished"
        out.append(rec)
    return out
