"""bigdl_tpu — a TPU-native deep-learning framework with the capabilities of
classic BigDL (reference: ugiwgh/BigDL, the Scala/Spark BigDL 0.x line).

Rebuilt idiomatically on JAX/XLA rather than ported:

* ``Tensor[Float]`` on MKL-backed JVM arrays  ->  ``jnp.ndarray`` on TPU HBM
* hand-written per-layer backwards            ->  ``jax.vjp`` / ``jax.grad``
* thread-pool model replicas per executor     ->  one XLA program per chip
* ``AllReduceParameter`` over Spark BlockManager
                                              ->  ``psum_scatter`` +
                                                  owner-shard update +
                                                  ``all_gather`` (ZeRO-1)
                                                  inside one jitted step
* Spark job-per-iteration barrier             ->  implicit synchrony of the
                                                  jitted train step

Reference layout cited throughout as ``«bigdl»/`` =
``spark/dl/src/main/scala/com/intel/analytics/bigdl/`` (see SURVEY.md for the
evidence-status preamble: the reference mount was empty, paths are the
upstream 0.x layout).
"""

from bigdl_tpu.engine import Engine
from bigdl_tpu.common import RandomGenerator
from bigdl_tpu.config import config, configure
from bigdl_tpu.tensor import Tensor
from bigdl_tpu import obs  # noqa: F401 — observability layer (obs.get_tracer()…)

__version__ = "0.1.0"
