"""TFRecord files + ``tf.train.Example`` codec — the reader-era input
pipeline vocabulary, host-side.

Rebuild of the data-pipeline half of «bigdl»/utils/tf/ (SURVEY.md §2.1
"TensorFlow interop": the reference ``BigDLSessionImpl`` exists to "run
TF graphs for training data pipelines" — TFRecordReader / queue /
ParseExample graphs).  On TPU the pipeline is a host concern: records
are decoded on CPU and fed to the device, so the queue machinery
becomes an ordinary Python iterator seam (the reference's
queue-dequeue boundary maps to :meth:`TFRecordExampleDataset.batches`).

No TF dependency: the TFRecord framing (length / masked-crc32c) is the
same wire format :mod:`bigdl_tpu.visualization.summary` already writes
for event files, and ``Example`` protos are read/written through the
generic wire reader/writer in :mod:`bigdl_tpu.utils.caffe`.
"""

from __future__ import annotations

import os
import struct
from typing import Dict, Iterable, List, Optional, Sequence

import numpy as np

from bigdl_tpu.utils.caffe import (
    _WireWriter,
    _w_msgs,
    parse_wire,
)
from bigdl_tpu.visualization.summary import _masked_crc

__all__ = [
    "TFRecordWriter",
    "tfrecord_iterator",
    "FixedLenFeature",
    "encode_example",
    "parse_example",
    "TFRecordExampleDataset",
]


# ------------------------------------------------------------------ framing


class TFRecordWriter:
    """Write TFRecord-framed records:
    ``uint64 len | uint32 masked_crc(len) | data | uint32 masked_crc(data)``.
    """

    def __init__(self, path: str):
        self._f = open(path, "wb")

    def write(self, record: bytes):
        header = struct.pack("<Q", len(record))
        self._f.write(header)
        self._f.write(struct.pack("<I", _masked_crc(header)))
        self._f.write(record)
        self._f.write(struct.pack("<I", _masked_crc(record)))

    def close(self):
        self._f.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


def tfrecord_iterator(path: str, verify_crc: bool = True):
    """Yield the raw record payloads of one TFRecord file."""
    with open(path, "rb") as f:
        while True:
            header = f.read(8)
            if len(header) < 8:
                return
            crc_len = f.read(4)
            if len(crc_len) < 4:
                raise ValueError(f"{path}: truncated record")
            (n,) = struct.unpack("<Q", header)
            if verify_crc and struct.unpack("<I", crc_len)[0] != _masked_crc(
                header
            ):
                raise ValueError(f"{path}: corrupt length crc")
            data = f.read(n)
            if len(data) < n:
                raise ValueError(f"{path}: truncated record")
            crc_data = f.read(4)
            if len(crc_data) < 4:
                raise ValueError(f"{path}: truncated record")
            if verify_crc and struct.unpack("<I", crc_data)[0] != _masked_crc(
                data
            ):
                raise ValueError(f"{path}: corrupt data crc")
            yield data


# ----------------------------------------------------------------- Example
#
# tf.train.Example wire schema:
#   Example        { Features features = 1; }
#   Features       { map<string, Feature> feature = 1; }
#   map entry      { string key = 1; Feature value = 2; }
#   Feature        { oneof: BytesList bytes_list = 1;
#                            FloatList float_list = 2;
#                            Int64List int64_list = 3; }
#   BytesList      { repeated bytes value = 1; }
#   FloatList      { repeated float value = 1 [packed]; }
#   Int64List      { repeated int64 value = 1 [packed]; }


def encode_example(features: Dict[str, object]) -> bytes:
    """Encode a dict into a serialized ``tf.train.Example``.

    Value types: ``bytes``/``str`` or lists of them -> bytes_list;
    float arrays/lists -> float_list; int arrays/lists -> int64_list.
    """
    feats = _WireWriter()
    for key, val in features.items():
        feature = _WireWriter()
        if isinstance(val, (bytes, str)):
            val = [val]
        if isinstance(val, np.ndarray) and val.dtype.kind in "SUO":
            # string/bytes ndarray (the shape _decode_tensor produces
            # for string consts) -> bytes_list, not int64
            val = [s for s in val.reshape(-1)]
        arr = None
        if isinstance(val, np.ndarray):
            arr = val.reshape(-1)
        elif isinstance(val, (list, tuple)) and val and isinstance(
            val[0], (bytes, str)
        ):
            blist = _WireWriter()
            for b in val:
                blist.bytes_(1, b.encode() if isinstance(b, str) else b)
            feature.message(1, blist)
        else:
            arr = np.asarray(val).reshape(-1)
        if arr is not None:
            if np.issubdtype(arr.dtype, np.floating):
                flist = _WireWriter()
                flist.bytes_(1, arr.astype("<f4").tobytes())  # packed
                feature.message(2, flist)
            else:
                ilist = _WireWriter()
                packed = b"".join(
                    _WireWriter._varint(int(v)) for v in arr
                )
                ilist.bytes_(1, packed)
                feature.message(3, ilist)
        entry = _WireWriter()
        entry.bytes_(1, key.encode())
        entry.message(2, feature)
        feats.message(1, entry)
    ex = _WireWriter()
    ex.message(1, feats)
    return ex.tobytes()


def _read_varints(buf: bytes) -> List[int]:
    from bigdl_tpu.utils.caffe import _read_varint

    out, pos, n = [], 0, len(buf)
    mv = memoryview(buf)
    while pos < n:
        x, pos = _read_varint(mv, pos)
        if x & (1 << 63):  # two's-complement int64
            x -= 1 << 64
        out.append(x)
    return out


def _decode_feature(fields: Dict[int, list]):
    """Decoded Feature -> (kind, values) where kind in {bytes,float,int}."""
    for fno, kind in ((1, "bytes"), (2, "float"), (3, "int")):
        msgs = _w_msgs(fields, fno)
        if not msgs:
            continue
        vals: List = []
        for wt, v in msgs[0].get(1, []):
            if kind == "bytes":
                vals.append(bytes(v))
            elif kind == "float":
                if wt == 2:  # packed
                    vals.extend(np.frombuffer(v, "<f4").tolist())
                else:
                    vals.append(struct.unpack("<f", v)[0])
            else:
                if wt == 0:
                    x = int(v)
                    if x & (1 << 63):
                        x -= 1 << 64
                    vals.append(x)
                else:  # packed varints
                    vals.extend(_read_varints(bytes(v)))
        return kind, vals
    return None, []


def decode_example(data: bytes) -> Dict[str, tuple]:
    """Serialized Example -> {key: (kind, values)}."""
    ex = parse_wire(data)
    feats_msgs = _w_msgs(ex, 1)
    out: Dict[str, tuple] = {}
    if not feats_msgs:
        return out
    for entry in _w_msgs(feats_msgs[0], 1):
        key_field = entry.get(1)
        if not key_field:
            continue
        key = bytes(key_field[-1][1]).decode()
        vmsgs = _w_msgs(entry, 2)
        if vmsgs:
            out[key] = _decode_feature(vmsgs[0])
    return out


class FixedLenFeature:
    """Dense-feature spec (the reference ParseExample's dense half).

    ``dtype`` may be any numpy dtype, or ``bytes``/``"string"`` for raw
    byte features (to be post-processed by a DecodeRaw transform).
    """

    def __init__(self, shape: Sequence[int] = (), dtype="float32",
                 default_value=None):
        self.shape = tuple(int(s) for s in shape)
        self.is_bytes = dtype in (bytes, "string", "bytes")
        self.dtype = None if self.is_bytes else np.dtype(dtype)
        self.default_value = default_value


def parse_example(data: bytes, spec: Dict[str, FixedLenFeature]):
    """One serialized Example -> {key: np.ndarray | bytes} per spec."""
    decoded = decode_example(data)
    out: Dict[str, object] = {}
    for key, feat in spec.items():
        if key not in decoded:
            if feat.default_value is None:
                raise KeyError(f"Example missing dense key {key!r}")
            if feat.is_bytes:
                out[key] = feat.default_value
            else:
                out[key] = np.full(
                    feat.shape, feat.default_value, dtype=feat.dtype
                )
            continue
        kind, vals = decoded[key]
        if feat.is_bytes:
            out[key] = vals[0] if len(vals) == 1 else vals
        else:
            arr = np.asarray(vals, dtype=feat.dtype)
            out[key] = arr.reshape(feat.shape) if feat.shape else arr
    return out


class TFRecordExampleDataset:
    """Host-side input pipeline over TFRecord files of Examples.

    The reference's filename-queue -> TFRecordReader -> example-queue ->
    QueueDequeueMany -> ParseExample chain, collapsed into the iterator
    it always was.  Optional per-key ``transforms`` (e.g. a DecodeRaw +
    reshape lifted out of the graph) run on each parsed feature.
    """

    def __init__(self, filenames: Sequence[str],
                 spec: Dict[str, FixedLenFeature],
                 batch_size: int = 32,
                 transforms: Optional[Dict[str, object]] = None):
        self.filenames = [os.fspath(f) for f in filenames]
        self.spec = dict(spec)
        self.batch_size = int(batch_size)
        self.transforms = dict(transforms or {})

    def records(self) -> Iterable[Dict[str, object]]:
        for path in self.filenames:
            for raw in tfrecord_iterator(path):
                ex = parse_example(raw, self.spec)
                for key, fn in self.transforms.items():
                    if key in ex:
                        ex[key] = fn(ex[key])
                yield ex

    def batches(self, drop_remainder: bool = False):
        """Yield {key: stacked array} batches — the dequeue-many seam."""
        buf: List[Dict[str, object]] = []
        for ex in self.records():
            buf.append(ex)
            if len(buf) == self.batch_size:
                yield self._stack(buf)
                buf = []
        if buf and not drop_remainder:
            yield self._stack(buf)

    @staticmethod
    def _stack(rows: List[Dict[str, object]]):
        return {
            k: np.stack([np.asarray(r[k]) for r in rows]) for k in rows[0]
        }

    def materialize(self):
        """All records stacked into one {key: array} table (the form
        Local/DistriOptimizer datasets take)."""
        rows = list(self.records())
        if not rows:
            raise ValueError("empty TFRecord dataset")
        return self._stack(rows)
