"""Torch7 ``.t7`` serialization — load/save tensors, tables, nn modules.

Rebuild of «bigdl»/utils/TorchFile.scala + File.scala (SURVEY.md §2.1
"Torch interop": loads/saves Torch7 ``.t7`` serialized modules/tensors).

Binary format (little-endian), as written by Torch7's default
serializer: every value is ``int32 type tag`` + payload —

  NIL=0; NUMBER=1 (f64); STRING=2 (i32 len + bytes); TABLE=3
  (i32 ref-index, i32 count, count × (key, value)); TORCH=4
  (i32 ref-index, version string "V <n>", class-name string, payload);
  BOOLEAN=5 (i32).

Tensor payload: i32 ndim, i64×ndim size, i64×ndim stride, i64
storage-offset (1-based), Storage object.  Storage payload: i64 count +
raw elements.  Objects already seen are referenced by index alone.

Loaded tensors become numpy arrays; torch class instances become
``TorchObject`` (dict-like with ``.torch_type``).  ``load_torch_module``
maps the common ``nn.*`` classes onto the layer library.
"""

from __future__ import annotations

import struct
from typing import Any, Dict, Optional

import numpy as np

TYPE_NIL = 0
TYPE_NUMBER = 1
TYPE_STRING = 2
TYPE_TABLE = 3
TYPE_TORCH = 4
TYPE_BOOLEAN = 5

_TENSOR_DTYPES = {
    "torch.FloatTensor": np.float32,
    "torch.DoubleTensor": np.float64,
    "torch.IntTensor": np.int32,
    "torch.LongTensor": np.int64,
    "torch.ByteTensor": np.uint8,
    "torch.CharTensor": np.int8,
    "torch.ShortTensor": np.int16,
}
_STORAGE_DTYPES = {
    "torch.FloatStorage": np.float32,
    "torch.DoubleStorage": np.float64,
    "torch.IntStorage": np.int32,
    "torch.LongStorage": np.int64,
    "torch.ByteStorage": np.uint8,
    "torch.CharStorage": np.int8,
    "torch.ShortStorage": np.int16,
}
_NP_TENSOR = {np.dtype(np.float32): "torch.FloatTensor",
              np.dtype(np.float64): "torch.DoubleTensor",
              np.dtype(np.int32): "torch.IntTensor",
              np.dtype(np.int64): "torch.LongTensor",
              np.dtype(np.uint8): "torch.ByteTensor"}
_NP_STORAGE = {np.dtype(np.float32): "torch.FloatStorage",
               np.dtype(np.float64): "torch.DoubleStorage",
               np.dtype(np.int32): "torch.IntStorage",
               np.dtype(np.int64): "torch.LongStorage",
               np.dtype(np.uint8): "torch.ByteStorage"}


class TorchObject(dict):
    """A deserialized torch class instance: its table payload plus
    ``torch_type`` (e.g. ``"nn.Linear"``)."""

    def __init__(self, torch_type: str, payload: Optional[dict] = None):
        super().__init__(payload or {})
        self.torch_type = torch_type

    def __repr__(self):
        return f"TorchObject({self.torch_type}, {dict.__repr__(self)})"


# ==========================================================================
# reader
# ==========================================================================


class _Reader:
    def __init__(self, f):
        self.f = f
        self.memo: Dict[int, Any] = {}

    def i32(self) -> int:
        return struct.unpack("<i", self.f.read(4))[0]

    def i64(self) -> int:
        return struct.unpack("<q", self.f.read(8))[0]

    def f64(self) -> float:
        return struct.unpack("<d", self.f.read(8))[0]

    def string(self) -> str:
        n = self.i32()
        return self.f.read(n).decode("utf-8", "replace")

    # ------------------------------------------------------------------
    def value(self):
        t = self.i32()
        if t == TYPE_NIL:
            return None
        if t == TYPE_NUMBER:
            v = self.f64()
            return int(v) if v == int(v) and abs(v) < 2**53 else v
        if t == TYPE_STRING:
            return self.string()
        if t == TYPE_BOOLEAN:
            return bool(self.i32())
        if t == TYPE_TABLE:
            idx = self.i32()
            if idx in self.memo:
                return self.memo[idx]
            out: dict = {}
            self.memo[idx] = out
            n = self.i32()
            for _ in range(n):
                k = self.value()
                v = self.value()
                out[k] = v
            # 1..n integer keys -> list
            if out and all(isinstance(k, int) for k in out) and \
                    sorted(out) == list(range(1, len(out) + 1)):
                lst = [out[i] for i in range(1, len(out) + 1)]
                self.memo[idx] = lst
                return lst
            return out
        if t == TYPE_TORCH:
            idx = self.i32()
            if idx in self.memo:
                return self.memo[idx]
            version = self.string()
            if version.startswith("V "):
                cls = self.string()
            else:
                cls = version  # legacy: no version header
            obj = self._torch_payload(cls, idx)
            return obj
        raise ValueError(f"bad .t7 type tag {t}")

    def _torch_payload(self, cls: str, idx: int):
        if cls in _TENSOR_DTYPES:
            ndim = self.i32()
            size = [self.i64() for _ in range(ndim)]
            stride = [self.i64() for _ in range(ndim)]
            offset = self.i64() - 1
            storage = self.value()  # Storage -> np array (flat)
            if storage is None or ndim == 0:
                arr = np.zeros(size, _TENSOR_DTYPES[cls])
            else:
                arr = np.lib.stride_tricks.as_strided(
                    storage[offset:],
                    shape=size,
                    strides=[s * storage.itemsize for s in stride],
                ).copy()
            self.memo[idx] = arr
            return arr
        if cls in _STORAGE_DTYPES:
            n = self.i64()
            dt = np.dtype(_STORAGE_DTYPES[cls])
            arr = np.frombuffer(self.f.read(n * dt.itemsize), dtype=dt).copy()
            self.memo[idx] = arr
            return arr
        # generic class: payload is a table
        obj = TorchObject(cls)
        self.memo[idx] = obj
        payload = self.value()
        if isinstance(payload, dict):
            obj.update(payload)
        return obj


def load_t7(path: str):
    """Reference: ``File.loadTorch`` — read one value from a .t7 file."""
    with open(path, "rb") as f:
        return _Reader(f).value()


# ==========================================================================
# writer
# ==========================================================================


class _Writer:
    def __init__(self, f):
        self.f = f
        self.next_idx = 1
        self.memo: Dict[int, int] = {}  # id(obj) -> index

    def i32(self, v: int):
        self.f.write(struct.pack("<i", v))

    def i64(self, v: int):
        self.f.write(struct.pack("<q", v))

    def f64(self, v: float):
        self.f.write(struct.pack("<d", v))

    def string(self, s: str):
        b = s.encode("utf-8")
        self.i32(len(b))
        self.f.write(b)

    def value(self, v):
        if isinstance(v, np.generic):
            # numpy scalars serialize as Lua numbers/booleans, not tensors
            v = v.item()
        if v is None:
            self.i32(TYPE_NIL)
        elif isinstance(v, bool):
            self.i32(TYPE_BOOLEAN)
            self.i32(1 if v else 0)
        elif isinstance(v, (int, float)):
            self.i32(TYPE_NUMBER)
            self.f64(float(v))
        elif isinstance(v, str):
            self.i32(TYPE_STRING)
            self.string(v)
        elif isinstance(v, np.ndarray):
            self._tensor(v)
        elif isinstance(v, TorchObject):
            self.i32(TYPE_TORCH)
            idx = self._ref(v)
            if idx is None:
                return
            self.string("V 1")
            self.string(v.torch_type)
            self.value(dict(v))
        elif isinstance(v, (list, tuple)):
            self.value({i + 1: x for i, x in enumerate(v)})
        elif isinstance(v, dict):
            self.i32(TYPE_TABLE)
            idx = self._ref(v)
            if idx is None:
                return
            self.i32(len(v))
            for k, val in v.items():
                self.value(k)
                self.value(val)
        else:
            try:
                self.value(np.asarray(v))
            except Exception:
                raise TypeError(f"cannot serialize {type(v).__name__} to .t7")

    def _ref(self, obj) -> Optional[int]:
        """Write the ref index; returns None if already written."""
        key = id(obj)
        if key in self.memo:
            self.i32(self.memo[key])
            return None
        idx = self.next_idx
        self.next_idx += 1
        self.memo[key] = idx
        self.i32(idx)
        return idx

    def _tensor(self, arr: np.ndarray):
        arr = np.ascontiguousarray(arr)
        tt = _NP_TENSOR.get(arr.dtype)
        if tt is None:
            arr = arr.astype(np.float32)
            tt = "torch.FloatTensor"
        self.i32(TYPE_TORCH)
        idx = self._ref(arr)
        if idx is None:
            return
        self.string("V 1")
        self.string(tt)
        self.i32(arr.ndim)
        for s in arr.shape:
            self.i64(s)
        stride = [st // arr.itemsize for st in arr.strides]
        for s in stride:
            self.i64(s)
        self.i64(1)  # storage offset (1-based)
        # storage
        self.i32(TYPE_TORCH)
        self.i32(self.next_idx)
        self.next_idx += 1
        self.string("V 1")
        self.string(_NP_STORAGE[arr.dtype])
        self.i64(arr.size)
        self.f.write(arr.tobytes())


def save_t7(path: str, obj):
    """Reference: ``File.saveTorch`` — write one value as .t7."""
    with open(path, "wb") as f:
        _Writer(f).value(obj)


# ==========================================================================
# nn.* module mapping
# ==========================================================================


def _set_weights(mod, obj: TorchObject, transpose_linear=False):
    import jax.numpy as jnp

    w = obj.get("weight")
    b = obj.get("bias")
    if w is not None and getattr(mod, "weight", None) is not None:
        w = np.asarray(w, np.float32)
        mod.weight = jnp.asarray(w.reshape(np.asarray(mod.weight).shape))
    if b is not None and getattr(mod, "bias", None) is not None:
        mod.bias = jnp.asarray(np.asarray(b, np.float32).reshape(-1))
    return mod


def load_torch_module(obj_or_path):
    """Map a deserialized ``nn.*`` object tree onto the layer library
    (reference: TorchFile loading Torch models)."""
    from bigdl_tpu.nn import layers as L
    from bigdl_tpu.nn.module import Sequential

    obj = obj_or_path
    if isinstance(obj, str):
        obj = load_t7(obj)
    if not isinstance(obj, TorchObject):
        raise TypeError("not a torch nn module")
    t = obj.torch_type

    if t in ("nn.Sequential",):
        seq = Sequential()
        for child in obj.get("modules", []):
            seq.add(load_torch_module(child))
        return seq
    if t == "nn.Linear":
        w = np.asarray(obj["weight"])
        mod = L.Linear(w.shape[1], w.shape[0],
                       with_bias=obj.get("bias") is not None)
        return _set_weights(mod, obj)
    if t in ("nn.SpatialConvolution", "nn.SpatialConvolutionMM"):
        mod = L.SpatialConvolution(
            int(obj["nInputPlane"]), int(obj["nOutputPlane"]),
            int(obj["kW"]), int(obj["kH"]),
            int(obj.get("dW", 1)), int(obj.get("dH", 1)),
            int(obj.get("padW", 0)), int(obj.get("padH", 0)),
        )
        return _set_weights(mod, obj)
    if t == "nn.SpatialMaxPooling":
        mod = L.SpatialMaxPooling(
            int(obj["kW"]), int(obj["kH"]),
            int(obj.get("dW", 1)), int(obj.get("dH", 1)),
            int(obj.get("padW", 0)), int(obj.get("padH", 0)),
        )
        if obj.get("ceil_mode"):
            mod.ceil_mode = True
        return mod
    if t == "nn.SpatialAveragePooling":
        return L.SpatialAveragePooling(
            int(obj["kW"]), int(obj["kH"]),
            int(obj.get("dW", 1)), int(obj.get("dH", 1)),
            int(obj.get("padW", 0)), int(obj.get("padH", 0)),
        )
    if t == "nn.SpatialBatchNormalization" or t == "nn.BatchNormalization":
        import jax.numpy as jnp

        n = int(np.asarray(obj["running_mean"]).size)
        cls = (L.SpatialBatchNormalization
               if t == "nn.SpatialBatchNormalization" else L.BatchNormalization)
        mod = cls(n, eps=float(obj.get("eps", 1e-5)),
                  affine=obj.get("weight") is not None)
        mod.running_mean = jnp.asarray(np.asarray(obj["running_mean"], np.float32))
        mod.running_var = jnp.asarray(np.asarray(obj["running_var"], np.float32))
        return _set_weights(mod, obj)
    if t == "nn.View":
        return L.View(*[int(s) for s in np.atleast_1d(obj.get("size"))])
    if t == "nn.Reshape":
        return L.Reshape([int(s) for s in np.atleast_1d(obj.get("size"))])
    if t == "nn.Dropout":
        return L.Dropout(float(obj.get("p", 0.5)))
    simple = {
        "nn.ReLU": L.ReLU, "nn.Tanh": L.Tanh, "nn.Sigmoid": L.Sigmoid,
        "nn.SoftMax": L.SoftMax, "nn.LogSoftMax": L.LogSoftMax,
        "nn.SoftPlus": L.SoftPlus, "nn.Abs": L.Abs, "nn.ELU": L.ELU,
        "nn.LeakyReLU": L.LeakyReLU, "nn.Identity": None,
    }
    if t in simple:
        cls = simple[t]
        if cls is None:
            from bigdl_tpu.nn.module import Identity

            return Identity()
        return cls()
    raise ValueError(f"unsupported torch module class {t}")
