"""TensorFlow interop — import/export frozen GraphDefs.

Rebuild of «bigdl»/utils/tf/ (SURVEY.md §2.1 "TensorFlow interop":
imports frozen TF GraphDefs → Graph via op-by-op converters
(`TensorflowLoader`), exports (`TensorflowSaver`)).

Like the Caffe path there is no protobuf runtime dependency: GraphDef /
NodeDef / AttrValue / TensorProto are read and written through the
generic wire reader/writer in :mod:`bigdl_tpu.utils.caffe`.

Supported ops cover the classic frozen-inference vocabulary: Const,
Placeholder, Identity, MatMul, BiasAdd, Add/AddV2/Sub/Mul/Maximum/
Minimum/RealDiv/Pow/FloorDiv, Conv2D, DepthwiseConv2dNative, Relu,
Relu6, Elu, LeakyRelu, Selu, Tanh, Sigmoid, Softplus, Softsign,
Floor/Ceil/Round/Sign/Log1p/Expm1/Erf/Sin/Cos/Reciprocal, MaxPool,
AvgPool, Mean (global pool) / Sum / Max / Min reductions, ArgMax, Pad,
Reshape, Squeeze, Tile, Cast, Slice, StridedSlice,
Split/SplitV/Unpack/Pack, GatherV2, Transpose, BatchMatMul(V2),
ExpandDims, Softmax, ConcatV2, FusedBatchNorm(V2/V3),
ResizeBilinear/ResizeNearestNeighbor, DepthToSpace/SpaceToDepth, AddN,
SquaredDifference, Less/Greater/Equal comparisons (const operand),
plus the FULL control-flow family via DynamicGraph: Switch/Merge
conditionals AND while frames (Enter/Merge/Switch/LoopCond/
NextIteration/Exit -> NextIteration feedback edges + a masked-scan
loop; trip count >= 1).  Shape-arithmetic subgraphs over Consts
(Fill/Range/Pack/StridedSlice/Shape-of-const/OneHot/Rank/Size chains)
are constant-folded the way the reference loader folds them;
Dequantize in weight position folds via MIN_COMBINED.

``TFTrainingSession`` (reference BigDLSessionImpl) runs an imported
graph as a TRAINING pipeline: converted weights are live module
parameters, gradients flow through every imported op, and the graph
fine-tunes under Local- or DistriOptimizer.  Graphs that ship their
OWN input side — TFRecordReader / queue / ParseExample / DecodeRaw —
are handled end-to-end: ``extract_input_pipeline`` lifts the reader
chain into a host-side :mod:`bigdl_tpu.utils.tf_records` dataset (the
queue-dequeue boundary becomes an iterator seam) and
``train_with_pipeline`` fine-tunes from the graph's own TFRecord files.
"""

from __future__ import annotations

import struct
from typing import Dict, List, Optional

import numpy as np

from bigdl_tpu.utils.caffe import (
    _to_jax,
    _WireWriter,
    _w_int,
    _w_ints,
    _w_msgs,
    _w_str,
    _w_strs,
    parse_wire,
)

# tf DataType enum values
_DT_FLOAT, _DT_DOUBLE, _DT_INT32, _DT_INT64 = 1, 2, 3, 9
_DT_BOOL, _DT_HALF, _DT_BFLOAT16 = 10, 19, 14
_DT_UINT8, _DT_INT16, _DT_INT8, _DT_STRING = 4, 5, 6, 7
_DT_QINT8, _DT_QUINT8, _DT_UINT16 = 11, 12, 17

_DT_NP = {
    _DT_FLOAT: np.float32,
    _DT_DOUBLE: np.float64,
    _DT_INT32: np.int32,
    _DT_INT64: np.int64,
    _DT_BOOL: np.bool_,
    _DT_UINT8: np.uint8,
    _DT_INT16: np.int16,
    _DT_INT8: np.int8,
    _DT_UINT16: np.uint16,
    _DT_QINT8: np.int8,
    _DT_QUINT8: np.uint8,
}


_NP_DTYPES = _DT_NP


class TFConversionException(Exception):
    pass


def _numpy_strided_slice(arr, begin, end, strides, nd):
    """Evaluate a StridedSlice on a constant operand, honouring the
    begin/end/shrink-axis masks (ellipsis/new-axis unsupported)."""
    begin = begin.reshape(-1).astype(int)
    end = end.reshape(-1).astype(int)
    strides = strides.reshape(-1).astype(int)
    masks = {k: (int(nd.attr(k).i or 0) if nd.attr(k) else 0)
             for k in ("begin_mask", "end_mask", "ellipsis_mask",
                       "new_axis_mask", "shrink_axis_mask")}
    if masks["ellipsis_mask"] or masks["new_axis_mask"]:
        raise TFConversionException(
            "StridedSlice ellipsis/new_axis masks unsupported")
    idx = []
    for i in range(len(begin)):
        b = None if masks["begin_mask"] & (1 << i) else begin[i]
        e = None if masks["end_mask"] & (1 << i) else end[i]
        if masks["shrink_axis_mask"] & (1 << i):
            idx.append(int(begin[i]))
        else:
            idx.append(slice(b, e, int(strides[i])))
    return arr[tuple(idx)]


# ==========================================================================
# TensorProto / AttrValue / NodeDef decoding
# ==========================================================================


def _decode_tensor(tp: Dict[int, list]) -> np.ndarray:
    dtype = _w_int(tp, 1, _DT_FLOAT)
    shape_msg = _w_msgs(tp, 2)
    dims = []
    if shape_msg:
        for d in _w_msgs(shape_msg[0], 2):  # TensorShapeProto.dim
            dims.append(_w_int(d, 1, -1))
    if dtype == _DT_STRING:
        # string_val = repeated bytes field 8 — an object array of bytes
        vals = [bytes(v) for wt, v in tp.get(8, []) if wt == 2]
        arr = np.empty(len(vals), dtype=object)
        arr[:] = vals
        if dims:
            arr = arr.reshape(dims)
        return arr
    np_dt = _DT_NP.get(dtype)
    if np_dt is None:
        raise TFConversionException(f"unsupported tensor dtype {dtype}")
    content = tp.get(4)
    if content:
        arr = np.frombuffer(content[-1][1], dtype=np_dt)
    else:
        # scalar/short-form repeated fields: float_val=5 double_val=6
        # int_val=7 int64_val=10 bool_val=11 half_val=13
        vals: List = []
        for wt, v in tp.get(5, []):
            vals.extend(np.frombuffer(v, "<f4") if wt == 2
                        else [struct.unpack("<f", v)[0]])
        for wt, v in tp.get(7, []):
            if wt == 0:
                vals.append(int(v))
            else:
                mv = memoryview(v)
                pos = 0
                while pos < len(mv):
                    from bigdl_tpu.utils.caffe import _read_varint

                    x, pos = _read_varint(mv, pos)
                    vals.append(x)
        for wt, v in tp.get(10, []):
            if wt == 0:
                vals.append(int(v))
        arr = np.asarray(vals, dtype=np_dt)
        if dims and arr.size == 1 and int(np.prod(dims)) > 1:
            arr = np.full(dims, arr.reshape(-1)[0], dtype=np_dt)
    if dims:
        arr = arr.reshape(dims)
    return arr


def _encode_tensor(arr: np.ndarray) -> _WireWriter:
    w = _WireWriter()
    if arr.dtype == object or arr.dtype.kind in ("S", "U"):
        # DT_STRING: string_val = repeated bytes field 8
        w.varint(1, _DT_STRING)
        shape = _WireWriter()
        for d in arr.shape:
            dim = _WireWriter()
            dim.varint(1, d)
            shape.message(2, dim)
        w.message(2, shape)
        for s in arr.reshape(-1):
            w.bytes_(8, s.encode() if isinstance(s, str) else bytes(s))
        return w
    dt = {np.float32: _DT_FLOAT, np.float64: _DT_DOUBLE,
          np.int32: _DT_INT32, np.int64: _DT_INT64,
          np.uint8: _DT_UINT8, np.int8: _DT_INT8}[arr.dtype.type]
    w.varint(1, dt)
    shape = _WireWriter()
    for d in arr.shape:
        dim = _WireWriter()
        dim.varint(1, d)
        shape.message(2, dim)
    w.message(2, shape)
    w.bytes_(4, np.ascontiguousarray(arr).tobytes())
    return w


class _Attr:
    """Decoded AttrValue."""

    def __init__(self, fields: Dict[int, list]):
        self.f = fields

    @property
    def s(self) -> Optional[str]:
        return _w_str(self.f, 2)

    @property
    def i(self) -> Optional[int]:
        v = _w_int(self.f, 3)
        return v

    @property
    def fl(self) -> Optional[float]:
        if 4 in self.f:
            return struct.unpack("<f", self.f[4][-1][1])[0]
        return None

    @property
    def b(self) -> Optional[bool]:
        v = _w_int(self.f, 5)
        return None if v is None else bool(v)

    @property
    def type(self) -> Optional[int]:
        return _w_int(self.f, 6)

    @property
    def tensor(self) -> Optional[np.ndarray]:
        msgs = _w_msgs(self.f, 8)
        return _decode_tensor(msgs[0]) if msgs else None

    @property
    def ints(self) -> List[int]:
        msgs = _w_msgs(self.f, 1)  # list value
        return _w_ints(msgs[0], 3) if msgs else []

    @property
    def types(self) -> List[int]:
        """list(type) — AttrValue.ListValue.type (field 6, may be packed)."""
        msgs = _w_msgs(self.f, 1)
        if not msgs:
            return []
        out: List[int] = []
        for wt, v in msgs[0].get(6, []):
            if wt == 0:
                out.append(int(v))
            else:  # packed
                from bigdl_tpu.utils.caffe import _read_varint

                mv = memoryview(v)
                pos = 0
                while pos < len(mv):
                    x, pos = _read_varint(mv, pos)
                    out.append(x)
        return out

    @property
    def shapes(self) -> List[List[int]]:
        """list(shape) — AttrValue.ListValue.shape (field 7)."""
        msgs = _w_msgs(self.f, 1)
        if not msgs:
            return []
        out = []
        for sh in _w_msgs(msgs[0], 7):
            out.append([_w_int(d, 1, -1) for d in _w_msgs(sh, 2)])
        return out

    @property
    def shape(self) -> Optional[List[int]]:
        """shape — AttrValue.shape (field 7)."""
        msgs = _w_msgs(self.f, 7)
        if not msgs:
            return None
        return [_w_int(d, 1, -1) for d in _w_msgs(msgs[0], 2)]


class _NodeDef:
    def __init__(self, fields: Dict[int, list]):
        self.name = _w_str(fields, 1, "")
        self.op = _w_str(fields, 2, "")
        self.inputs = _w_strs(fields, 3)
        self.attrs: Dict[str, _Attr] = {}
        for entry in _w_msgs(fields, 5):  # map<string, AttrValue>
            k = _w_str(entry, 1, "")
            vs = _w_msgs(entry, 2)
            if vs:
                self.attrs[k] = _Attr(vs[0])

    def attr(self, key, default=None):
        return self.attrs.get(key, default)


def parse_graphdef(data: bytes) -> List[_NodeDef]:
    g = parse_wire(data)
    return [_NodeDef(n) for n in _w_msgs(g, 1)]


# ==========================================================================
# loader
# ==========================================================================


def _clean(name: str) -> str:
    # drop control-dep marker and output index
    if name.startswith("^"):
        name = name[1:]
    return name.split(":")[0]


class TensorflowLoader:
    """Reference: «bigdl»/utils/tf/TensorflowLoader.scala.

    ``load(inputs=[...], outputs=[...])`` builds a Graph whose Input
    nodes stand for the named placeholders.  NHWC tensors are converted
    to the NCHW convention the layer library uses.
    """

    def __init__(self, path: Optional[str] = None, data: Optional[bytes] = None):
        if data is None:
            with open(path, "rb") as f:
                data = f.read()
        self.nodes = {n.name: n for n in parse_graphdef(data)}

    # ------------------------------------------------------------------
    def load(self, inputs: Optional[List[str]] = None,
             outputs: Optional[List[str]] = None,
             loop_max_iterations: int = 32):
        from bigdl_tpu.nn.graph import DynamicGraph, Graph, Input

        if outputs is None:
            consumed = set()
            for n in self.nodes.values():
                consumed.update(_clean(i) for i in n.inputs)
            outputs = [n for n in self.nodes
                       if n not in consumed
                       and self.nodes[n].op not in ("Const", "Placeholder")]
        if inputs is None:
            inputs = [n.name for n in self.nodes.values()
                      if n.op == "Placeholder"]

        self._consts: Dict[str, np.ndarray] = {}
        self._built: Dict[str, object] = {}
        self._img_memo: Dict[str, bool] = {}
        self._input_nodes = []
        # while-loop wiring (frame family Enter/Merge/Switch/
        # NextIteration/Exit): loop Merges become graph NextIteration
        # nodes; the body feedback attaches after the full build
        self._loop_feedbacks: Dict[str, object] = {}  # tf NI name -> node
        self._loop_cond_node = None
        for name in inputs:
            node = Input(name)
            self._built[name] = node
            self._input_nodes.append(node)

        out_nodes = [self._build(_clean(o)) for o in outputs]
        if self._loop_feedbacks:
            # the LoopCond chain gates, it doesn't feed the outputs —
            # build it explicitly, then attach body feedbacks to a
            # fixpoint (building a body may reach further loop Merges)
            loop_conds = [n for n in self.nodes.values()
                          if n.op == "LoopCond"]
            if len(loop_conds) > 1:
                # a single masked scan can gate only one loop; silently
                # merging two frames would stop both on one condition
                raise TFConversionException(
                    "multiple while loops in one graph unsupported "
                    f"({[n.name for n in loop_conds]})")
            for tf_node in loop_conds:
                self._build(tf_node.name)
            attached = set()
            while True:
                pending = [k for k in self._loop_feedbacks
                           if k not in attached]
                if not pending:
                    break
                for ni_name in pending:
                    attached.add(ni_name)
                    src = self._build(
                        self._data_inputs(self.nodes[ni_name])[0])
                    self._loop_feedbacks[ni_name].feedback_from(src)
            # TF while is cond-before-body; the masked-scan DynamicGraph
            # is do-while, identical for any trip count >= 1 (zero-trip
            # loops are out of scope — graph.py docstring)
            return DynamicGraph(self._input_nodes, out_nodes,
                                max_iterations=loop_max_iterations,
                                condition=self._loop_cond_node)
        return Graph(self._input_nodes, out_nodes)

    # ------------------------------------------------------------------
    def _const(self, name: str) -> np.ndarray:
        raw = name[1:] if name.startswith("^") else name
        base, _, idx = raw.partition(":")
        out_idx = int(idx) if idx else 0
        name = base
        if raw in self._consts:
            return self._consts[raw]
        nd = self.nodes.get(base)
        if nd is None:
            raise TFConversionException(f"unknown node {name}")
        if nd.op == "Identity":
            return self._const(nd.inputs[0])
        if nd.op != "Const":
            folded = self._fold_const(nd, out_idx)
            if folded is not None:
                self._consts[raw] = folded
                return folded
            raise TFConversionException(
                f"node {name} ({nd.op}) is not constant"
            )
        a = nd.attr("value")
        arr = a.tensor if a else None
        if arr is None:
            raise TFConversionException(f"Const {name} has no tensor")
        self._consts[raw] = arr
        return arr

    def _fold_const(self, nd: _NodeDef, out_idx: int = 0):
        """Constant-fold shape-arithmetic subgraphs (TF graphs compute
        Reshape/Slice operands via Fill/Range/Pack/StridedSlice chains
        over Consts; the reference loader folds these the same way).
        Returns None when any operand is genuinely dynamic."""
        op = nd.op
        ins = self._data_inputs(nd)
        try:
            if op == "Fill":
                dims = self._const(ins[0]).reshape(-1).astype(int)
                val = self._const(ins[1]).reshape(-1)[0]
                return np.full(tuple(dims), val)
            if op == "Range":
                s, e, d = (self._const(i).reshape(-1)[0] for i in ins)
                return np.arange(s, e, d)
            if op == "Shape":
                # only a const input has a statically known shape here
                return np.asarray(self._const(ins[0]).shape, np.int32)
            if op == "Pack":
                ax = nd.attr("axis")
                ax = int(ax.i or 0) if ax else 0
                return np.stack([self._const(i) for i in ins], axis=ax)
            if op == "Unpack":
                ax = nd.attr("axis")
                ax = int(ax.i or 0) if ax else 0
                parts = np.split(self._const(ins[0]),
                                 self._const(ins[0]).shape[ax], axis=ax)
                return np.squeeze(parts[out_idx], axis=ax)
            if op == "ConcatV2":
                ax = int(self._const(ins[-1]).reshape(-1)[0])
                return np.concatenate(
                    [self._const(i) for i in ins[:-1]], axis=ax)
            if op == "StridedSlice":
                return _numpy_strided_slice(
                    self._const(ins[0]), self._const(ins[1]),
                    self._const(ins[2]), self._const(ins[3]), nd)
            if op == "Transpose":
                return np.transpose(
                    self._const(ins[0]),
                    self._const(ins[1]).reshape(-1).astype(int))
            if op == "Reshape":
                return np.reshape(
                    self._const(ins[0]),
                    self._const(ins[1]).reshape(-1).astype(int))
            if op == "Cast":
                dst = nd.attr("DstT")
                np_dt = _NP_DTYPES.get(dst.type if dst else _DT_FLOAT)
                if np_dt is None:
                    return None
                return self._const(ins[0]).astype(np_dt)
            if op == "ExpandDims":
                ax = int(self._const(ins[1]).reshape(-1)[0])
                return np.expand_dims(self._const(ins[0]), ax)
            if op in ("GatherV2", "Gather"):
                ax = int(self._const(ins[2]).reshape(-1)[0]) \
                    if len(ins) > 2 else 0
                return np.take(self._const(ins[0]),
                               self._const(ins[1]).astype(int), axis=ax)
            if op == "Prod":
                axes = tuple(self._const(ins[1]).reshape(-1).astype(int))
                return np.prod(self._const(ins[0]), axis=axes or None)
            if op in ("Add", "AddV2", "Sub", "Mul", "RealDiv",
                      "Maximum", "Minimum"):
                a, b = self._const(ins[0]), self._const(ins[1])
                return {"Add": np.add, "AddV2": np.add, "Sub": np.subtract,
                        "Mul": np.multiply, "RealDiv": np.divide,
                        "Maximum": np.maximum,
                        "Minimum": np.minimum}[op](a, b)
            if op == "Neg":
                return -self._const(ins[0])
            if op == "Rank":
                return np.asarray(self._const(ins[0]).ndim, np.int32)
            if op == "Size":
                return np.asarray(self._const(ins[0]).size, np.int32)
            if op in ("Sqrt", "Floor", "Ceil", "Round", "Abs"):
                return {"Sqrt": np.sqrt, "Floor": np.floor,
                        "Ceil": np.ceil, "Round": np.round,
                        "Abs": np.abs}[op](self._const(ins[0]))
            if op == "OneHot":
                idx = self._const(ins[0]).astype(int)
                depth = int(self._const(ins[1]).reshape(-1)[0])
                on = float(self._const(ins[2]).reshape(-1)[0]) \
                    if len(ins) > 2 else 1.0
                off = float(self._const(ins[3]).reshape(-1)[0]) \
                    if len(ins) > 3 else 0.0
                ax = nd.attr("axis")
                ax = int(ax.i) if ax and ax.i is not None else -1
                if ax not in (-1, idx.ndim):
                    return None
                out = np.full(idx.shape + (depth,), off, np.float32)
                ok = (idx >= 0) & (idx < depth)
                np.put_along_axis(
                    out, np.clip(idx, 0, depth - 1)[..., None],
                    np.where(ok, on, off)[..., None], axis=-1)
                return out
            if op == "Dequantize":
                # quantized weights in frozen graphs: MIN_COMBINED maps
                # the integer range linearly onto [min_range, max_range]
                mode = nd.attr("mode")
                mode = mode.s if mode and mode.s else "MIN_COMBINED"
                if mode != "MIN_COMBINED":
                    return None
                q = self._const(ins[0])
                lo = float(self._const(ins[1]).reshape(-1)[0])
                hi = float(self._const(ins[2]).reshape(-1)[0])
                info = np.iinfo(q.dtype)
                span = float(int(info.max) - int(info.min))
                scale = (hi - lo) / span
                if info.min == 0:  # quint8
                    return (q.astype(np.float32) * scale + lo).astype(
                        np.float32)
                # qint8: zero maps to the range midpoint
                return ((q.astype(np.float32) - info.min) * scale
                        + lo).astype(np.float32)
        except TFConversionException:
            return None
        return None

    def _data_inputs(self, nd: _NodeDef) -> List[str]:
        return [i for i in nd.inputs if not i.startswith("^")]

    # NHWC graphs are converted to NCHW modules, so axis-bearing ops
    # (Concat/Squeeze/Pad/Mean/BiasAdd) must remap their axes whenever
    # the tensor flowing through them is an image (4-D conv-path) tensor
    _IMG_PRODUCERS = ("Conv2D", "DepthwiseConv2dNative", "MaxPool",
                      "AvgPool", "FusedBatchNorm", "FusedBatchNormV2",
                      "FusedBatchNormV3", "ResizeBilinear",
                      "ResizeNearestNeighbor", "DepthToSpace",
                      "SpaceToDepth")
    _IMG_PROPAGATORS = ("Identity", "StopGradient", "CheckNumerics",
                        "Relu", "Relu6", "Elu", "Tanh", "Sigmoid",
                        "Softplus", "BiasAdd", "Add", "AddV2", "Sub",
                        "Mul", "Maximum", "Minimum", "RealDiv", "Pad",
                        "ConcatV2", "Concat", "Abs", "Neg", "Sqrt",
                        "Square", "Exp", "Log", "LeakyRelu", "Selu",
                        "Softsign", "Pow", "Cast", "Tile", "Slice")

    def _is_image(self, name: str) -> bool:
        """True when ``name`` carries an NHWC conv-path tensor whose axes
        need remapping.  NCHW-format producers (data_format attr) are
        already in the framework layout and must NOT be remapped."""
        name = _clean(name)
        if name in self._img_memo:
            return self._img_memo[name]
        nd = self.nodes.get(name)
        res = False
        if nd is not None:
            if nd.op in self._IMG_PRODUCERS:
                fmt = nd.attr("data_format")
                res = (fmt.s if fmt and fmt.s else "NHWC") == "NHWC"
            elif nd.op in self._IMG_PROPAGATORS:
                self._img_memo[name] = False  # cycle guard
                res = any(self._is_image(i) for i in self._data_inputs(nd))
        self._img_memo[name] = res
        return res

    def _axis_dim(self, axis: int, image: bool) -> int:
        """TF axis -> the 1-based dim convention of the module layer.
        Image (NHWC->NCHW) axes are remapped (negatives normalised
        against rank 4); non-image negative axes stay negative — the
        core modules (Narrow/Select/SplitTable/SplitChunks/
        GatherIndices) count negatives from the end themselves."""
        if image or axis >= 0:
            return self._map_axis(axis, image) + 1
        return axis

    @staticmethod
    def _map_axis(axis: int, image: bool) -> int:
        """NHWC axis -> NCHW axis for image tensors.  Negative axes are
        normalised against the known rank-4 image layout; for non-image
        tensors they pass through (numpy semantics handle them)."""
        if not image:
            return axis
        if axis < 0:
            axis += 4
        return {0: 0, 1: 2, 2: 3, 3: 1}[axis]

    # ops whose consumers select an output by ":idx" (TF multi-output);
    # the converted module returns a tuple, picked via SelectTable
    _MULTI_OUTPUT_OPS = ("Switch", "Split", "SplitV", "Unpack")

    def _switch_ancestors(self, name: str, _depth: int = 0, _memo=None):
        """All Switch ancestors reachable from ``name``:
        {pred_base_name: {"ports": {0|1,...}, "depth": min, "ref": pred}}
        where a port is the Switch output the path rode (0=false,
        1=true).  Used to find a Merge's *controlling* Switch: for
        nested conds, the controlling predicate is the one common to
        both Merge inputs with a distinct single port on each side.
        Memoized per raw ref so reconvergent (residual/diamond) graphs
        stay linear instead of enumerating every path."""
        if _memo is None:
            _memo = {}
        raw = name[1:] if name.startswith("^") else name
        if raw in _memo:
            return _memo[raw]
        result: Dict[str, dict] = {}
        if _depth > 256:
            return result
        base, _, idx = raw.partition(":")
        port = int(idx) if idx else 0
        nd = self.nodes.get(base)
        if nd is None:
            return result
        _memo[raw] = result  # cycle guard; filled in place below
        if nd.op == "Switch":
            data_in, pred_in = self._data_inputs(nd)[:2]
            key = _clean(pred_in)
            entry = result.setdefault(
                key, {"ports": set(), "depth": _depth, "ref": pred_in})
            entry["ports"].add(port)
            entry["depth"] = min(entry["depth"], _depth)
            ups = [data_in]  # outer switches sit above this one's data
        else:
            ups = self._data_inputs(nd)
        for i in ups:
            for k, v in self._switch_ancestors(i, _depth + 1, _memo).items():
                if k in result:
                    result[k]["ports"] |= v["ports"]
                    result[k]["depth"] = min(result[k]["depth"], v["depth"])
                else:
                    result[k] = v
        return result

    def _merge_wiring(self, ins):
        """Resolve a Merge's (false_input, true_input, pred_ref) under
        select semantics.  The controlling Switch is the common
        ancestor predicate whose port differs between the two inputs
        (disambiguates nested conds and input order)."""
        a0 = self._switch_ancestors(ins[0])
        a1 = self._switch_ancestors(ins[1])
        best = None
        for p in set(a0) & set(a1):
            p0, p1 = a0[p]["ports"], a1[p]["ports"]
            if len(p0) == 1 and len(p1) == 1 and p0 != p1:
                d = a0[p]["depth"] + a1[p]["depth"]
                if best is None or d < best[0]:
                    best = (d, p)
        if best is not None:
            p = best[1]
            if a0[p]["ports"] == {0}:
                return ins[0], ins[1], a0[p]["ref"]
            return ins[1], ins[0], a0[p]["ref"]
        # fallback: any ancestor pred, keep the given (false, true) order
        for side in (a0, a1):
            if side:
                p = min(side, key=lambda q: side[q]["depth"])
                return ins[0], ins[1], side[p]["ref"]
        return None

    def _is_loop_switch(self, nd: _NodeDef) -> bool:
        """True when a Switch's predicate traces to a LoopCond — i.e.
        it is a while-frame Switch, not a cond-branch Switch."""
        pred = _clean(self._data_inputs(nd)[1])
        seen = set()
        while pred in self.nodes and pred not in seen:
            seen.add(pred)
            pnd = self.nodes[pred]
            if pnd.op == "LoopCond":
                return True
            if pnd.op == "Identity":
                pred = _clean(pnd.inputs[0])
                continue
            break
        return False

    def _build(self, name: str):
        """Recursively convert node ``name``; returns a wired graph Node."""
        raw = name[1:] if name.startswith("^") else name
        if raw in self._built:
            # covers explicit "node:k" seam inputs (input-pipeline
            # boundaries) as well as plain seeded names
            return self._built[raw]
        base, _, idx = raw.partition(":")
        out_idx = int(idx) if idx else 0
        src_nd = self.nodes.get(base)
        if src_nd is not None and src_nd.op == "Switch" \
                and self._is_loop_switch(src_nd):
            # while-frame Switch: the masked-scan DynamicGraph owns the
            # stop-iterating semantics, so both ports (0 = Exit side,
            # 1 = body side) pass the merge value straight through
            if base not in self._built:
                self._built[base] = self._build(
                    self._data_inputs(src_nd)[0])
            return self._built[base]
        if src_nd is not None and src_nd.op in self._MULTI_OUTPUT_OPS:
            # TF refs output 0 as "name", output k as "name:k"; the
            # converted module emits a tuple -> SelectTable per consumer
            key = f"{base}:{out_idx}"
            if key in self._built:
                return self._built[key]
            rawkey = base + ":__raw__"
            if rawkey not in self._built:
                self._built[rawkey] = self._convert(src_nd)
            from bigdl_tpu.nn.table_ops import SelectTable

            node = SelectTable(out_idx + 1)(self._built[rawkey])  # 1-based
            self._built[key] = node
            return node
        name = base
        if name in self._built:
            return self._built[name]
        nd = self.nodes.get(name)
        if nd is None:
            raise TFConversionException(f"unknown node {name}")
        node = self._convert(nd)
        self._built[name] = node
        return node

    # ------------------------------------------------------------------
    def _convert(self, nd: _NodeDef):
        from bigdl_tpu.nn import layers as L
        from bigdl_tpu.nn import table_ops as T
        from bigdl_tpu.nn.graph import Input

        jnp_set = _to_jax
        op = nd.op
        ins = self._data_inputs(nd)

        if op == "Placeholder":
            node = Input(nd.name)
            self._input_nodes.append(node)
            return node
        if op in ("Identity", "StopGradient", "CheckNumerics", "NoOp",
                  "Enter", "Exit"):
            # Enter/Exit are while-frame markers: identities here — the
            # DynamicGraph's masked scan owns the iteration semantics
            return self._build(ins[0])

        # control flow (VERDICT r2 #6): select-semantics lowering — see
        # nn/control_ops.py.  Switch(data, pred) -> ((data,pred) x2),
        # consumers pick a branch via the _build multi-output path;
        # Merge selects by the predicate riding alongside each branch.
        if op == "Switch":
            from bigdl_tpu.nn import control_ops as C

            return self._named(C.SwitchOps(), nd)(
                self._build(ins[0]), self._build(ins[1])
            )
        if op == "Merge":
            from bigdl_tpu.nn import control_ops as C

            ni = [i for i in ins
                  if self.nodes.get(_clean(i), _NodeDef({})).op
                  == "NextIteration"]
            if ni:
                # while-frame Merge: a NextIteration graph node whose
                # ordinary predecessor is the Enter value; the body
                # feedback attaches in load()'s fixup pass
                others = [i for i in ins if i not in ni]
                node = self._named(C.NextIteration(), nd)(
                    self._build(others[0]))
                self._loop_feedbacks[_clean(ni[0])] = node
                return node
            wiring = self._merge_wiring(ins)
            if wiring is None:
                raise TFConversionException(
                    f"Merge {nd.name}: no controlling Switch found"
                )
            false_in, true_in, pred_name = wiring
            return self._named(C.MergeOps(), nd)(
                self._build(false_in), self._build(true_in),
                self._build(pred_name),
            )
        if op == "LoopCond":
            from bigdl_tpu.nn import control_ops as C

            node = self._named(C.LoopCondition(), nd)(self._build(ins[0]))
            self._loop_cond_node = node
            return node

        if op in ("Less", "LessEqual", "Greater", "GreaterEqual",
                  "Equal", "NotEqual"):
            from bigdl_tpu.nn.layers_extra import CompareConstant

            cmp = {"Less": "lt", "LessEqual": "le", "Greater": "gt",
                   "GreaterEqual": "ge", "Equal": "eq",
                   "NotEqual": "ne"}[op]
            consts = []
            for i in ins:
                try:
                    consts.append(self._const(i))
                except TFConversionException:
                    consts.append(None)
            if consts[0] is None and consts[1] is None:
                raise TFConversionException(
                    f"{op} with two runtime operands unsupported")
            ci = 0 if consts[0] is not None else 1
            cval = consts[ci]
            if cval.size != 1:
                raise TFConversionException(
                    f"{op} with a non-scalar const unsupported")
            mod = CompareConstant(cmp, float(cval.reshape(-1)[0]),
                                  const_first=(ci == 0))
            return self._named(mod, nd)(self._build(ins[1 - ci]))
        if op == "Const":
            raise TFConversionException(
                f"Const {nd.name} reached graph position — only weight"
                " positions may be constant"
            )

        if op == "MatMul":
            w = self._const(ins[1])
            if nd.attr("transpose_b") and nd.attr("transpose_b").b:
                w = w.T
            mod = L.Linear(w.shape[0], w.shape[1], with_bias=False)
            mod.weight = jnp_set(np.ascontiguousarray(w.T))
            return self._named(mod, nd)(self._build(ins[0]))

        if op == "BiasAdd":
            b = self._const(ins[1])
            # BiasAdd always adds along the channel axis.  Two cases need
            # the (C, 1, 1) broadcast on the converted (NCHW) tensor:
            #  - the producer chain was NHWC and got remapped to NCHW, or
            #  - the node itself declares data_format=NCHW (channels are
            #    already axis 1 — a flat (C,) add would ride the W axis).
            fmt = nd.attr("data_format")
            fmt = fmt.s if fmt and fmt.s else "NHWC"
            if self._is_image(ins[0]) or fmt == "NCHW":
                mod = L.CAdd((b.size, 1, 1))
                mod.bias = jnp_set(b.reshape(-1, 1, 1))
            else:
                mod = L.CAdd(b.shape)
                mod.bias = jnp_set(b)
            return self._named(mod, nd)(self._build(ins[0]))

        if op in ("Add", "AddV2", "Sub", "Mul", "Maximum", "Minimum",
                  "RealDiv"):
            # constant operand -> elementwise const op; else table op
            const_idx = None
            for i, inp in enumerate(ins):
                try:
                    self._const(inp)
                    const_idx = i
                    break
                except TFConversionException:
                    continue
            if const_idx is not None:
                c = self._const(ins[const_idx])
                other = ins[1 - const_idx]
                if c.size == 1:
                    from bigdl_tpu.nn.module import Sequential

                    v = float(c.reshape(-1)[0])
                    if op in ("Add", "AddV2"):
                        mod = L.AddConstant(v)
                    elif op == "Sub":
                        if const_idx == 1:  # x - c
                            mod = L.AddConstant(-v)
                        else:  # c - x = -(x) + c
                            mod = Sequential().add(L.Negative()) \
                                .add(L.AddConstant(v))
                    elif op == "Mul":
                        mod = L.MulConstant(v)
                    elif op == "RealDiv":
                        if const_idx == 1:  # x / c
                            mod = L.MulConstant(1.0 / v)
                        else:  # c / x = c * x^-1
                            mod = Sequential().add(L.Power(-1.0)) \
                                .add(L.MulConstant(v))
                    elif op == "Maximum":
                        mod = L.Threshold(v, v)
                    else:  # Minimum: min(x, c) = -max(-x, -c)
                        mod = Sequential().add(L.Negative()) \
                            .add(L.Threshold(-v, -v)).add(L.Negative())
                    return self._named(mod, nd)(self._build(other))
                # broadcast add/mul with a vector -> CAdd/CMul.  TF
                # broadcasts trailing axes: on an NHWC tensor a (C,) const
                # rides the channel axis, so after the NHWC->NCHW remap it
                # must become (C, 1, 1); non-image tensors keep TF layout
                # and the trailing broadcast is already correct.
                if self._is_image(other) and c.ndim == 1:
                    cshape = (c.size, 1, 1)
                    c = c.reshape(cshape)
                else:
                    cshape = c.shape
                if op in ("Add", "AddV2"):
                    mod = L.CAdd(cshape)
                    mod.bias = jnp_set(c)
                elif op == "Mul":
                    mod = L.CMul(cshape)
                    mod.weight = jnp_set(c)
                else:
                    raise TFConversionException(
                        f"{op} with non-scalar constant unsupported"
                    )
                return self._named(mod, nd)(self._build(other))
            table = {
                "Add": T.CAddTable, "AddV2": T.CAddTable,
                "Sub": T.CSubTable, "Mul": T.CMulTable,
                "Maximum": T.CMaxTable, "Minimum": T.CMinTable,
                "RealDiv": T.CDivTable,
            }[op]()
            return self._named(table, nd)(*[self._build(i) for i in ins])

        if op in ("Conv2D", "DepthwiseConv2dNative"):
            w = self._const(ins[1])  # HWIO (or HWIM for depthwise)
            strides = nd.attr("strides").ints if nd.attr("strides") else [1, 1, 1, 1]
            padding = nd.attr("padding").s if nd.attr("padding") else "SAME"
            data_format = nd.attr("data_format").s if nd.attr("data_format") else "NHWC"
            if data_format == "NHWC":
                sh, sw = strides[1], strides[2]
            else:
                sh, sw = strides[2], strides[3]
            kh, kw, c_in, c_mult = w.shape
            if op == "DepthwiseConv2dNative":
                n_out = c_in * c_mult
                group = c_in
                # HWIM -> (out, in/group=1, kh, kw)
                wt = w.transpose(2, 3, 0, 1).reshape(n_out, 1, kh, kw)
            else:
                n_out = c_mult
                group = 1
                wt = w.transpose(3, 2, 0, 1)  # HWIO -> OIHW
            if padding == "SAME":
                ph, pw = -1, -1  # layer lib: -1 means SAME
            else:
                ph = pw = 0
            mod = L.SpatialConvolution(
                c_in if group == 1 else c_in, n_out, kw, kh, sw, sh,
                pw, ph, group, with_bias=False,
            )
            mod.weight = jnp_set(np.ascontiguousarray(wt).reshape(mod.weight.shape))
            prev = self._build(ins[0])
            return self._named(mod, nd)(prev)

        if op in ("MaxPool", "AvgPool"):
            ks = nd.attr("ksize").ints
            strides = nd.attr("strides").ints
            padding = nd.attr("padding").s
            fmt = nd.attr("data_format")
            if fmt and fmt.s == "NCHW":
                kh, kw = ks[2], ks[3]
                sh, sw = strides[2], strides[3]
            else:
                kh, kw = ks[1], ks[2]
                sh, sw = strides[1], strides[2]
            pad = -1 if padding == "SAME" else 0
            if op == "MaxPool":
                mod = L.SpatialMaxPooling(kw, kh, sw, sh, pad, pad)
            else:
                # TF AvgPool excludes padding from the divisor
                mod = L.SpatialAveragePooling(
                    kw, kh, sw, sh, pad, pad, count_include_pad=False
                )
            return self._named(mod, nd)(self._build(ins[0]))

        if op == "Mean":
            image = self._is_image(ins[0])
            axes = sorted(
                self._map_axis(int(a), image)
                for a in self._const(ins[1]).reshape(-1).tolist()
            )
            keep = nd.attr("keep_dims")
            keep = bool(keep.b) if keep else False
            if axes == [2, 3]:
                # global spatial average pool over the NCHW image
                mod = L.SpatialAveragePooling(0, 0, global_pooling=True)
                if not keep:
                    from bigdl_tpu.nn.module import Sequential

                    mod = Sequential().add(mod).add(L.Squeeze(None))
                return self._named(mod, nd)(self._build(ins[0]))
            if len(axes) != 1 or keep:
                raise TFConversionException(
                    f"Mean over axes {axes} (keep_dims={keep}) unsupported"
                )
            mod = L.Mean(axes[0] + 1)
            return self._named(mod, nd)(self._build(ins[0]))

        if op in ("Relu", "Relu6", "Elu", "Tanh", "Sigmoid", "Softplus",
                  "Softmax", "LogSoftmax", "Rsqrt", "Sqrt", "Square",
                  "Exp", "Log", "Abs", "Neg", "Floor", "Ceil", "Round",
                  "Rint", "Sign", "Log1p", "Expm1", "Erf", "Sin", "Cos",
                  "Reciprocal", "Inv"):
            mod = {
                "Relu": L.ReLU, "Relu6": L.ReLU6, "Elu": L.ELU,
                "Tanh": L.Tanh, "Sigmoid": L.Sigmoid,
                "Softplus": L.SoftPlus, "Softmax": L.SoftMax,
                "LogSoftmax": L.LogSoftMax, "Sqrt": L.Sqrt,
                "Square": L.Square, "Exp": L.Exp, "Log": L.Log,
                "Abs": L.Abs, "Neg": L.Negative,
                "Floor": L.Floor, "Ceil": L.Ceil, "Round": L.Round,
                "Rint": L.Round, "Sign": L.Sign, "Log1p": L.Log1p,
                "Expm1": L.Expm1, "Erf": L.Erf, "Sin": L.Sin,
                "Cos": L.Cos,
            }.get(op)
            if mod is None:
                mod = L.Power(-0.5) if op == "Rsqrt" else L.Power(-1.0)
            else:
                mod = mod()
            return self._named(mod, nd)(self._build(ins[0]))

        if op == "ArgMax":
            axis = int(self._const(ins[1]).reshape(-1)[0])
            dim1 = self._axis_dim(axis, self._is_image(ins[0]))
            return self._named(L.ArgMax(dim1), nd)(self._build(ins[0]))

        if op == "FloorDiv":
            from bigdl_tpu.nn.module import Sequential

            consts = []
            for i in ins:
                try:
                    consts.append(self._const(i))
                except TFConversionException:
                    consts.append(None)
            if consts[1] is not None and consts[1].size == 1:
                c = float(consts[1].reshape(-1)[0])
                mod = Sequential().add(L.DivConstant(c)).add(L.Floor())
                return self._named(mod, nd)(self._build(ins[0]))
            mod = Sequential().add(T.CDivTable()).add(L.Floor())
            return self._named(mod, nd)(
                self._build(ins[0]), self._build(ins[1]))

        if op == "Reshape":
            shape = self._const(ins[1]).reshape(-1).astype(int).tolist()
            if shape and shape[0] == -1:
                mod = L.Reshape(shape[1:])  # batch-preserving
            else:
                mod = L.View(*shape)
            return self._named(mod, nd)(self._build(ins[0]))

        if op == "Squeeze":
            dims = nd.attr("squeeze_dims")
            image = self._is_image(ins[0])
            axes = sorted(
                (self._map_axis(int(a), image) for a in dims.ints),
                reverse=True,
            ) if dims else []
            if not axes:
                mod = L.Squeeze(None)
            elif len(axes) == 1:
                mod = L.Squeeze(axes[0] + 1)
            else:
                from bigdl_tpu.nn.module import Sequential

                mod = Sequential()
                for a in axes:  # descending: later indices stay valid
                    mod.add(L.Squeeze(a + 1))
            return self._named(mod, nd)(self._build(ins[0]))

        if op == "Pad":
            pads = self._const(ins[1])  # (ndim, 2) in graph (NHWC) order
            if int(pads[0, 0]) or int(pads[0, 1]):
                raise TFConversionException("Pad on the batch axis unsupported")
            from bigdl_tpu.nn.module import Sequential

            image = self._is_image(ins[0])
            n_input_dim = pads.shape[0] - 1
            seq = Sequential()
            for axis in range(1, pads.shape[0]):
                before, after = int(pads[axis, 0]), int(pads[axis, 1])
                dim = self._map_axis(axis, image)
                if before:
                    seq.add(L.Padding(dim, -before, n_input_dim))
                if after:
                    seq.add(L.Padding(dim, after, n_input_dim))
            return self._named(seq, nd)(self._build(ins[0]))

        if op in ("ConcatV2", "Concat"):
            if op == "ConcatV2":
                axis = int(self._const(ins[-1]).reshape(-1)[0])
                data = ins[:-1]
            else:
                axis = int(self._const(ins[0]).reshape(-1)[0])
                data = ins[1:]
            image = any(self._is_image(i) for i in data)
            axis = self._map_axis(axis, image)
            mod = T.JoinTable(dimension=axis + 1, n_input_dims=-1)
            return self._named(mod, nd)(*[self._build(i) for i in data])

        if op == "LeakyRelu":
            alpha = nd.attr("alpha")
            mod = L.LeakyReLU(alpha.fl if alpha else 0.2)
            return self._named(mod, nd)(self._build(ins[0]))

        if op in ("Selu", "Softsign"):
            mod = L.SELU() if op == "Selu" else L.SoftSign()
            return self._named(mod, nd)(self._build(ins[0]))

        if op == "Pow":
            e = self._const(ins[1])
            if e.size != 1:
                raise TFConversionException(
                    "Pow with a non-scalar exponent unsupported")
            mod = L.Power(float(e.reshape(-1)[0]))
            return self._named(mod, nd)(self._build(ins[0]))

        if op in ("Sum", "Max", "Min"):
            image = self._is_image(ins[0])
            axes = [self._map_axis(int(a), image)
                    for a in self._const(ins[1]).reshape(-1).tolist()]
            keep = nd.attr("keep_dims")
            keep = bool(keep.b) if keep else False
            if len(axes) != 1 or keep:
                raise TFConversionException(
                    f"{op} over axes {axes} (keep_dims={keep}) unsupported"
                )
            cls = {"Sum": L.Sum, "Max": L.Max, "Min": L.Min}[op]
            mod = cls(axes[0] + 1)
            return self._named(mod, nd)(self._build(ins[0]))

        if op in ("All", "Any"):
            # {0,1}-float booleans: All = min-reduce, Any = max-reduce
            image = self._is_image(ins[0])
            axes = [self._map_axis(int(a), image)
                    for a in self._const(ins[1]).reshape(-1).tolist()]
            keep = nd.attr("keep_dims")
            if len(axes) != 1 or (keep and keep.b):
                raise TFConversionException(
                    f"{op} over axes {axes} with keep_dims unsupported")
            mod = (L.Min if op == "All" else L.Max)(axes[0] + 1)
            return self._named(mod, nd)(self._build(ins[0]))

        if op in ("ZerosLike", "OnesLike"):
            from bigdl_tpu.nn.layers_extra import FillLike

            mod = FillLike(0.0 if op == "ZerosLike" else 1.0)
            return self._named(mod, nd)(self._build(ins[0]))

        if op == "LogicalNot":
            from bigdl_tpu.nn.module import Sequential

            mod = Sequential().add(L.Negative()).add(L.AddConstant(1.0))
            return self._named(mod, nd)(self._build(ins[0]))

        if op in ("LogicalAnd", "LogicalOr"):
            table = T.CMinTable() if op == "LogicalAnd" else T.CMaxTable()
            return self._named(table, nd)(*[self._build(i) for i in ins])

        if op == "InTopK":
            k = nd.attr("k")
            mod = T.InTopK(int(k.i) if k else 1)
            return self._named(mod, nd)(*[self._build(i) for i in ins])

        if op in ("Select", "SelectV2"):
            # v1 Select broadcasts a low-rank cond along LEADING axes
            # (rank-1 cond = row mask); SelectV2 is numpy-style
            table = T.WhereTable(leading_broadcast=(op == "Select"))
            return self._named(table, nd)(
                *[self._build(i) for i in ins])

        if op == "Cumsum":
            from bigdl_tpu.nn.layers_extra import CumSum

            image = self._is_image(ins[0])
            ax = self._map_axis(
                int(self._const(ins[1]).reshape(-1)[0]), image)
            exclusive = nd.attr("exclusive")
            reverse = nd.attr("reverse")
            mod = CumSum(ax + 1,
                         exclusive=bool(exclusive.b) if exclusive else False,
                         reverse=bool(reverse.b) if reverse else False)
            return self._named(mod, nd)(self._build(ins[0]))

        if op == "ReverseV2":
            from bigdl_tpu.nn.layers_extra import Reverse
            from bigdl_tpu.nn.module import Sequential

            image = self._is_image(ins[0])
            axes = [self._map_axis(int(a), image)
                    for a in self._const(ins[1]).reshape(-1).tolist()]
            seq = Sequential()
            for a in axes:
                seq.add(Reverse(a + 1))
            mod = seq if len(seq.modules) != 1 else seq.modules[0]
            return self._named(mod, nd)(self._build(ins[0]))

        if op == "MirrorPad":
            from bigdl_tpu.nn.layers_extra import MirrorPad

            pads = self._const(ins[1]).astype(int)  # (rank, 2) TF layout
            if pads[0].any():
                raise TFConversionException(
                    "MirrorPad on the batch axis unsupported")
            if self._is_image(ins[0]) and pads.shape[0] == 4:
                # NHWC rows -> converted NCHW tensor order
                pads = pads[[0, 3, 1, 2]]
            mode = nd.attr("mode")
            mode = mode.s if mode and mode.s else "REFLECT"
            mod = MirrorPad([list(p) for p in pads.tolist()], mode=mode)
            return self._named(mod, nd)(self._build(ins[0]))

        if op == "Tile":
            image = self._is_image(ins[0])
            mults = self._const(ins[1]).reshape(-1).astype(int).tolist()
            if mults[0] != 1:
                raise TFConversionException(
                    "Tile on the batch axis unsupported")
            from bigdl_tpu.nn.layers_extra import Tile
            from bigdl_tpu.nn.module import Sequential

            seq = Sequential()
            for axis, m in enumerate(mults):
                if axis == 0 or m == 1:
                    continue
                dim = self._map_axis(axis, image)
                seq.add(Tile(dim + 1, m))
            mod = seq if len(seq.modules) != 1 else seq.modules[0]
            return self._named(mod, nd)(self._build(ins[0]))

        if op == "Cast":
            # float->float casts are identity in this f32 runtime (the
            # compute-dtype policy governs precision); an integer target
            # would truncate, which Identity silently would not
            dst = nd.attr("DstT")
            if dst is not None and dst.type not in (
                    _DT_FLOAT, _DT_DOUBLE, _DT_HALF, _DT_BFLOAT16):
                raise TFConversionException(
                    f"Cast to dtype {dst.type} unsupported")
            from bigdl_tpu.nn.module import Identity

            return self._named(Identity(), nd)(self._build(ins[0]))

        if op == "Slice":
            begin = self._const(ins[1]).reshape(-1).astype(int).tolist()
            size = self._const(ins[2]).reshape(-1).astype(int).tolist()
            image = self._is_image(ins[0])
            # a concrete size[0] (the frozen batch extent) with begin 0
            # is the common no-op batch slice real graphs encode
            # (ADVICE r3 #3); only a nonzero begin actually cuts samples
            if begin[0] != 0:
                raise TFConversionException(
                    "Slice on the batch axis unsupported")
            from bigdl_tpu.nn.module import Sequential

            seq = Sequential()
            for axis in range(1, len(begin)):
                if begin[axis] == 0 and size[axis] == -1:
                    continue
                dim = self._map_axis(axis, image)
                seq.add(L.Narrow(dim + 1, begin[axis] + 1, size[axis]))
            from bigdl_tpu.nn.module import Identity

            mod = (
                Identity() if not seq.modules
                else seq if len(seq.modules) != 1 else seq.modules[0]
            )
            return self._named(mod, nd)(self._build(ins[0]))

        if op == "AddN":
            # n-ary sum of runtime tensors
            mod = T.CAddTable()
            return self._named(mod, nd)(*[self._build(i) for i in ins])

        if op == "SquaredDifference":
            from bigdl_tpu.nn.module import Sequential

            # (a - b)^2; const operands fold into an AddConstant
            consts = []
            for i in ins:
                try:
                    consts.append(self._const(i))
                except TFConversionException:
                    consts.append(None)
            if consts[0] is None and consts[1] is None:
                seq = Sequential().add(T.CSubTable()).add(L.Square())
                return self._named(seq, nd)(
                    self._build(ins[0]), self._build(ins[1]))
            ci = 0 if consts[0] is not None else 1
            cval = consts[ci]
            if cval.size != 1:
                raise TFConversionException(
                    "SquaredDifference with a non-scalar const "
                    "unsupported")
            seq = Sequential().add(
                L.AddConstant(-float(cval.reshape(-1)[0]))).add(L.Square())
            return self._named(seq, nd)(self._build(ins[1 - ci]))

        if op in ("Split", "SplitV"):
            # TF Split(split_dim, value) / SplitV(value, sizes, dim):
            # equal chunks via SplitChunks (runtime-shape chunk length),
            # explicit sizes via a Narrow fan-out; both multi-output
            from bigdl_tpu.nn.layers_extra import SplitChunks
            from bigdl_tpu.nn.table_ops import ConcatTable

            if op == "Split":
                axis = int(self._const(ins[0]).reshape(-1)[0])
                data_in = ins[1]
                num = nd.attr("num_split")
                num = int(num.i or 0) if num else 0
                dim1 = self._axis_dim(axis, self._is_image(data_in))
                mod = SplitChunks(dim1, num)
            else:
                data_in = ins[0]
                sizes = self._const(ins[1]).reshape(-1).astype(int).tolist()
                axis = int(self._const(ins[2]).reshape(-1)[0])
                dim1 = self._axis_dim(axis, self._is_image(data_in))
                mod = ConcatTable()
                off = 1
                for s in sizes:
                    mod.add(L.Narrow(dim1, off, int(s)))
                    off += int(s)
            return self._named(mod, nd)(self._build(data_in))

        if op == "Unpack":
            # table of dim-removed slices == SplitTable semantics
            ax = nd.attr("axis")
            axis = int(ax.i or 0) if ax else 0
            mod = T.SplitTable(self._axis_dim(axis, self._is_image(ins[0])))
            return self._named(mod, nd)(self._build(ins[0]))

        if op == "Pack":
            ax = nd.attr("axis")
            axis = int(ax.i or 0) if ax else 0
            if axis < 0:
                raise TFConversionException(
                    "Pack with negative axis unsupported")
            mod = T.Pack(axis + 1)
            return self._named(mod, nd)(*[self._build(i) for i in ins])

        if op == "StridedSlice":
            begin = self._const(ins[1]).reshape(-1).astype(int).tolist()
            end = self._const(ins[2]).reshape(-1).astype(int).tolist()
            strides = self._const(ins[3]).reshape(-1).astype(int).tolist()
            if any(s != 1 for s in strides):
                raise TFConversionException(
                    "StridedSlice with strides != 1 unsupported")
            bm = int(nd.attr("begin_mask").i or 0) \
                if nd.attr("begin_mask") else 0
            em = int(nd.attr("end_mask").i or 0) if nd.attr("end_mask") else 0
            sm = int(nd.attr("shrink_axis_mask").i or 0) \
                if nd.attr("shrink_axis_mask") else 0
            for k in ("ellipsis_mask", "new_axis_mask"):
                if nd.attr(k) and (nd.attr(k).i or 0):
                    raise TFConversionException(
                        f"StridedSlice {k} unsupported")
            # the batch axis must be left whole: begin free (mask or 0)
            # AND end free (mask set) — a concrete end[0] would cut
            # samples silently at an unknown runtime batch size
            if (not (bm & 1) and begin[0] != 0) or (sm & 1) \
                    or not (em & 1):
                raise TFConversionException(
                    "StridedSlice constraining the batch axis unsupported")
            image = self._is_image(ins[0])
            from bigdl_tpu.nn.module import Sequential
            from bigdl_tpu.nn.recurrent import Select as _Select

            seq = Sequential()
            shrinks = []
            for axis in range(1, len(begin)):
                dim = self._map_axis(axis, image)
                b = 0 if bm & (1 << axis) else begin[axis]
                if b < 0:
                    raise TFConversionException(
                        "StridedSlice negative begin unsupported")
                if sm & (1 << axis):
                    shrinks.append((dim, begin[axis]))
                    continue
                to_end = bool(em & (1 << axis))
                if b == 0 and to_end:
                    continue
                if to_end:
                    seq.add(L.Narrow(dim + 1, b + 1, -1))
                elif end[axis] < 0:
                    # python-style from-the-end: Narrow's negative
                    # length L keeps size - offset + 2 + L elements
                    # (1-based offset b+1), so L = end - 1 keeps
                    # exactly size + end - b
                    seq.add(L.Narrow(dim + 1, b + 1, end[axis] - 1))
                else:
                    seq.add(L.Narrow(dim + 1, b + 1, end[axis] - b))
            # shrink axes AFTER narrows, highest dim first so earlier
            # indices stay valid; Select removes the axis
            for dim, b in sorted(shrinks, reverse=True):
                seq.add(_Select(dim + 1, b + 1))
            from bigdl_tpu.nn.module import Identity

            mod = (
                Identity() if not seq.modules
                else seq if len(seq.modules) != 1 else seq.modules[0]
            )
            return self._named(mod, nd)(self._build(ins[0]))

        if op in ("GatherV2", "Gather"):
            idxv = self._const(ins[1])
            axis = int(self._const(ins[2]).reshape(-1)[0]) \
                if len(ins) > 2 else 0
            dim1 = self._axis_dim(axis, self._is_image(ins[0]))
            from bigdl_tpu.nn.layers_extra import GatherIndices
            from bigdl_tpu.nn.recurrent import Select as _Select

            if idxv.ndim == 0:
                mod = _Select(dim1, int(idxv) + 1)
            elif idxv.ndim == 1:
                # one jnp.take — a Select fan-out would scale the module
                # graph with the index count
                mod = GatherIndices(dim1, idxv.astype(int).tolist())
            else:
                raise TFConversionException(
                    "Gather with >1-D indices unsupported")
            return self._named(mod, nd)(self._build(ins[0]))

        if op == "Transpose":
            perm = self._const(ins[1]).reshape(-1).astype(int).tolist()
            if self._is_image(ins[0]):
                raise TFConversionException(
                    "Transpose of an NHWC image tensor unsupported "
                    "(layout already remapped)")
            # decompose the permutation into sequential swaps
            # (L.Transpose applies (a, b) swaps in order)
            cur = list(range(len(perm)))
            swaps = []
            for i, want in enumerate(perm):
                j = cur.index(want)
                if j != i:
                    swaps.append((i + 1, j + 1))
                    cur[i], cur[j] = cur[j], cur[i]
            from bigdl_tpu.nn.module import Identity

            mod = L.Transpose(swaps) if swaps else Identity()
            return self._named(mod, nd)(self._build(ins[0]))

        if op in ("BatchMatMul", "BatchMatMulV2"):
            adj_x = nd.attr("adj_x")
            adj_y = nd.attr("adj_y")
            mod = T.MM(trans_a=bool(adj_x.b) if adj_x else False,
                       trans_b=bool(adj_y.b) if adj_y else False)
            return self._named(mod, nd)(
                self._build(ins[0]), self._build(ins[1]))

        if op == "ExpandDims":
            axis = int(self._const(ins[1]).reshape(-1)[0])
            if axis < 0:
                raise TFConversionException(
                    "ExpandDims with negative axis unsupported")
            image = self._is_image(ins[0])
            dim = self._map_axis(axis, image) if axis else axis
            mod = L.Unsqueeze(dim + 1)
            return self._named(mod, nd)(self._build(ins[0]))

        if op in ("ResizeBilinear", "ResizeNearestNeighbor"):
            from bigdl_tpu.nn.layers_extra import (
                ResizeBilinear as _RB,
                ResizeNearestNeighbor as _RN,
            )

            size = self._const(ins[1]).reshape(-1).astype(int)
            oh, ow = int(size[0]), int(size[1])
            ac = nd.attr("align_corners")
            ac = bool(ac.b) if ac else False
            hp = nd.attr("half_pixel_centers")
            hp = bool(hp.b) if hp else False
            cls = _RB if op == "ResizeBilinear" else _RN
            mod = cls(oh, ow, align_corners=ac, half_pixel_centers=hp)
            return self._named(mod, nd)(self._build(ins[0]))

        if op in ("DepthToSpace", "SpaceToDepth"):
            from bigdl_tpu.nn.layers_extra import (
                DepthToSpace as _D2S,
                SpaceToDepth as _S2D,
            )

            bs = nd.attr("block_size")
            bs = int(bs.i) if bs and bs.i else 2
            fmt = nd.attr("data_format")
            if fmt and fmt.s and fmt.s not in ("NHWC", "NCHW"):
                raise TFConversionException(
                    f"{op} data_format {fmt.s!r} unsupported")
            mod = _D2S(bs) if op == "DepthToSpace" else _S2D(bs)
            return self._named(mod, nd)(self._build(ins[0]))

        if op in ("FusedBatchNorm", "FusedBatchNormV2", "FusedBatchNormV3"):
            scale = self._const(ins[1])
            offset = self._const(ins[2])
            mean = self._const(ins[3])
            var = self._const(ins[4])
            eps = nd.attr("epsilon")
            eps = eps.fl if eps else 1e-3
            c = scale.size
            mod = L.SpatialBatchNormalization(c, eps=eps, affine=True)
            mod.weight = jnp_set(scale.reshape(-1))
            mod.bias = jnp_set(offset.reshape(-1))
            mod.running_mean = jnp_set(mean.reshape(-1))
            mod.running_var = jnp_set(var.reshape(-1))
            mod.evaluate()
            return self._named(mod, nd)(self._build(ins[0]))

        raise TFConversionException(f"unsupported TF op {op} ({nd.name})")

    @staticmethod
    def _named(mod, nd: _NodeDef):
        mod.set_name(nd.name)
        return mod

    # ------------------------------------------------------------------
    # input-pipeline extraction (the reference BigDLSessionImpl's reason
    # to exist: run TF graphs whose INPUT side is a reader/queue/
    # ParseExample pipeline — SURVEY.md §2.1 "TensorFlow interop")
    # ------------------------------------------------------------------

    _QUEUE_OPS = ("FIFOQueueV2", "FIFOQueue", "RandomShuffleQueueV2",
                  "RandomShuffleQueue", "PaddingFIFOQueueV2",
                  "PaddingFIFOQueue")
    _PIPELINE_OPS = _QUEUE_OPS + (
        "TFRecordReaderV2", "TFRecordReader", "ReaderReadV2", "ReaderRead",
        "QueueEnqueueV2", "QueueEnqueue", "QueueEnqueueManyV2",
        "QueueEnqueueMany", "QueueDequeueV2", "QueueDequeue",
        "QueueDequeueManyV2", "QueueDequeueMany", "QueueDequeueUpToV2",
        "QueueCloseV2", "QueueClose", "ParseExample", "DecodeRaw",
    )

    def has_input_pipeline(self) -> bool:
        return any(n.op == "ParseExample" for n in self.nodes.values())

    def extract_input_pipeline(self, filenames=None):
        """Lift the reader -> queue -> ParseExample (-> DecodeRaw)
        subgraph out of the GraphDef into a host-side
        :class:`~bigdl_tpu.utils.tf_records.TFRecordExampleDataset`.

        The queue-dequeue boundary becomes an iterator seam: the parse/
        decode output tensors turn into the converted model's Input
        nodes, and the records themselves are read host-side (CPU
        decode feeding the device — the TPU-native shape of the
        reference's executor-side queue runners).  ``filenames``
        overrides the file list baked into the graph's string Consts.
        """
        from bigdl_tpu.utils.tf_records import (
            FixedLenFeature,
            TFRecordExampleDataset,
        )

        if not hasattr(self, "_consts"):
            self._consts = {}
        parse_nodes = [n for n in self.nodes.values()
                       if n.op == "ParseExample"]
        if not parse_nodes:
            raise TFConversionException("graph has no ParseExample node")
        if len(parse_nodes) > 1:
            raise TFConversionException(
                "multiple ParseExample pipelines unsupported")
        parse = parse_nodes[0]
        ins = self._data_inputs(parse)
        nsparse = int(parse.attr("Nsparse").i or 0) \
            if parse.attr("Nsparse") else 0
        if nsparse:
            raise TFConversionException(
                "ParseExample sparse features unsupported")
        tdense = parse.attr("Tdense").types if parse.attr("Tdense") else []
        nd_attr = parse.attr("Ndense")
        ndense = int(nd_attr.i) if nd_attr and nd_attr.i else len(tdense)
        shapes = parse.attr("dense_shapes").shapes \
            if parse.attr("dense_shapes") else []
        serialized = ins[0]
        key_refs = ins[2:2 + ndense]
        default_refs = ins[2 + ndense:2 + 2 * ndense]
        keys = []
        for r in key_refs:
            kv = self._const(r).reshape(-1)[0]
            keys.append(kv.decode() if isinstance(kv, bytes) else str(kv))

        # upstream walk from the serialized tensor: collect every
        # pipeline-side node, the dequeue batch size, and the filename
        # string Consts feeding the reader chain (enqueue ops CONSUME
        # their queue, so each queue hop restarts the walk from its
        # enqueues' values)
        pipeline_nodes = {parse.name}
        batch_size = None
        graph_files: List[str] = []
        frontier = [_clean(serialized)]
        seen = set()
        while frontier:
            name = frontier.pop()
            if name in seen or name not in self.nodes:
                continue
            seen.add(name)
            nd = self.nodes[name]
            pipeline_nodes.add(name)
            if nd.op in ("QueueDequeueManyV2", "QueueDequeueMany",
                         "QueueDequeueUpToV2") and batch_size is None:
                try:
                    batch_size = int(
                        self._const(
                            self._data_inputs(nd)[1]).reshape(-1)[0])
                except TFConversionException:
                    pass
            if nd.op in self._QUEUE_OPS:
                for other in self.nodes.values():
                    if not other.op.startswith("QueueEnqueue"):
                        continue
                    oins = self._data_inputs(other)
                    if oins and _clean(oins[0]) == name:
                        pipeline_nodes.add(other.name)
                        frontier.extend(_clean(i) for i in oins[1:])
            if nd.op == "Const":
                a = nd.attr("value")
                arr = a.tensor if a else None
                if arr is not None and arr.dtype == object:
                    graph_files.extend(
                        b.decode() if isinstance(b, bytes) else str(b)
                        for b in arr.reshape(-1))
            frontier.extend(_clean(i) for i in self._data_inputs(nd))

        # consumer map (raw "node:k" spelling, as consumers write it)
        consumers: Dict[str, List[str]] = {}
        for n in self.nodes.values():
            for i in n.inputs:
                raw = i[1:] if i.startswith("^") else i
                consumers.setdefault(raw, []).append(n.name)

        spec: Dict[str, FixedLenFeature] = {}
        transforms: Dict[str, object] = {}
        seam_refs: List[str] = []
        seam_keys: List[str] = []
        def _cons_of(*refs):
            out = []
            for r in refs:
                out.extend(consumers.get(r, []))
            return out

        for k, key in enumerate(keys):
            ref = parse.name if k == 0 else f"{parse.name}:{k}"
            dt = tdense[k] if k < len(tdense) else _DT_FLOAT
            shape = tuple(s for s in (shapes[k] if k < len(shapes) else [])
                          if s >= 0)
            default = None
            if k < len(default_refs):
                try:
                    dv = self._const(default_refs[k])
                    if dv.size:
                        default = dv.reshape(-1)[0]
                except TFConversionException:
                    pass
            # output 0 may be spelled "name" or "name:0" by consumers
            refs = (ref, f"{ref}:0") if k == 0 else (ref,)
            cons = [c for c in _cons_of(*refs)
                    if c not in pipeline_nodes]
            decoders = [c for c in cons
                        if self.nodes[c].op == "DecodeRaw"]
            if dt == _DT_STRING or decoders:
                if not decoders:
                    raise TFConversionException(
                        f"string feature {key!r} has no DecodeRaw "
                        "consumer — cannot feed the device")
                dr = self.nodes[decoders[0]]
                out_t = dr.attr("out_type")
                np_dt = _DT_NP.get(out_t.type if out_t else _DT_FLOAT,
                                   np.float32)
                le = dr.attr("little_endian")
                le = bool(le.b) if le and le.b is not None else True
                wire_dt = np.dtype(np_dt).newbyteorder("<" if le else ">")
                spec[key] = FixedLenFeature((), bytes)
                transforms[key] = (
                    lambda b, _w=wire_dt, _n=np_dt: np.frombuffer(
                        b, dtype=_w).astype(_n))
                pipeline_nodes.add(dr.name)
                seam = dr.name
                consumed = any(c not in pipeline_nodes
                               for c in _cons_of(seam, seam + ":0"))
            else:
                np_dt = _DT_NP.get(dt, np.float32)
                spec[key] = FixedLenFeature(shape, np_dt, default)
                seam = ref
                consumed = bool(cons)
            if consumed:
                seam_refs.append(seam)
                seam_keys.append(key)

        dataset = TFRecordExampleDataset(
            list(filenames) if filenames is not None else graph_files,
            spec, batch_size=batch_size or 32, transforms=transforms)
        return TFInputPipeline(dataset, seam_refs, seam_keys,
                               batch_size or 32, pipeline_nodes)

    def model_outputs(self, exclude=()):
        """Auto-detect output nodes, ignoring the pipeline side (queue
        enqueues/closers are sinks but not model outputs)."""
        exclude = set(exclude)
        consumed = set()
        for n in self.nodes.values():
            if n.name in exclude:
                continue
            consumed.update(_clean(i) for i in n.inputs)
        return [name for name, n in self.nodes.items()
                if name not in consumed and name not in exclude
                and n.op not in ("Const", "Placeholder")
                and n.op not in self._PIPELINE_OPS]


class TFInputPipeline:
    """A lifted TF-graph input pipeline: the host-side dataset plus the
    seam tensors where data crosses into the converted model."""

    def __init__(self, dataset, seam_refs, seam_keys, batch_size, nodes):
        self.dataset = dataset
        self.seam_refs = list(seam_refs)  # model Input refs, in order
        self.seam_keys = list(seam_keys)  # Example key per seam
        self.batch_size = batch_size
        self.nodes = set(nodes)  # pipeline-side node names

    def feature_table(self):
        """Materialize the records: ([per-seam array, ...], full table)."""
        table = self.dataset.materialize()
        return [table[k] for k in self.seam_keys], table

    def batches(self, drop_remainder=False):
        for b in self.dataset.batches(drop_remainder=drop_remainder):
            yield [b[k] for k in self.seam_keys], b


def load_tf(path: str, inputs=None, outputs=None):
    """Reference: ``Module.loadTF(path, inputs, outputs)``."""
    return TensorflowLoader(path).load(inputs, outputs)


class TFTrainingSession:
    """Reference: «bigdl»/utils/tf/BigDLSessionImpl.scala (SURVEY.md
    §2.1 "TensorFlow interop": a small Session that runs imported TF
    graphs for *training*, not just frozen inference).

    The imported Graph's weights are ordinary module parameters, so
    ``jax.vjp`` flows gradients through every converted op and any
    optimizer can fine-tune the graph — ``train`` wires the model into
    Local- or DistriOptimizer exactly the way the reference session
    submitted its graph to the distributed optimizer.
    """

    def __init__(self, path: Optional[str] = None,
                 data: Optional[bytes] = None, inputs=None, outputs=None,
                 filenames=None):
        self.loader = TensorflowLoader(path=path, data=data)
        self.pipeline = None
        if inputs is None and self.loader.has_input_pipeline():
            # graph ships its own input pipeline (reader/queue/
            # ParseExample): lift it host-side, make the seam tensors
            # the model inputs
            self.pipeline = self.loader.extract_input_pipeline(
                filenames=filenames)
            inputs = self.pipeline.seam_refs
            if outputs is None:
                outputs = self.loader.model_outputs(
                    exclude=self.pipeline.nodes)
        self.model = self.loader.load(inputs=inputs, outputs=outputs)
        self._optimizer = None

    # reference: Session.run(endpoints, feed) — frozen inference
    def run(self, feed):
        self.model.evaluate()
        return self.model.forward(feed)

    def train(self, dataset, criterion, optim_method=None, batch_size=32,
              end_trigger=None, distributed=False):
        """Fine-tune the imported graph.  ``distributed=True`` submits
        to DistriOptimizer over the Engine mesh (the reference session's
        ``train(outputs, rdd)`` path); otherwise LocalOptimizer."""
        if distributed:
            from bigdl_tpu.optim.distri_optimizer import DistriOptimizer

            opt = DistriOptimizer(self.model, dataset, criterion,
                                  batch_size=batch_size)
        else:
            from bigdl_tpu.optim.optimizer import LocalOptimizer

            opt = LocalOptimizer(self.model, dataset, criterion,
                                 batch_size=batch_size)
        if optim_method is not None:
            opt.set_optim_method(optim_method)
        if end_trigger is not None:
            opt.set_end_when(end_trigger)
        self._optimizer = opt
        return opt.optimize()

    def train_with_pipeline(self, criterion, label_key,
                            label_transform=None, optim_method=None,
                            batch_size=None, end_trigger=None,
                            distributed=False):
        """Fine-tune end-to-end from the graph's OWN input pipeline:
        records are read host-side through the lifted TFRecord/
        ParseExample dataset, features feed the seam Inputs, and
        ``label_key`` names the Example feature used as the target
        (``label_transform`` adapts conventions, e.g. 0-based int64 ->
        1-based float for ClassNLLCriterion)."""
        if self.pipeline is None:
            raise TFConversionException(
                "graph has no input pipeline; use train(dataset, ...)")
        xs, table = self.pipeline.feature_table()
        if label_key not in table:
            raise KeyError(
                f"label key {label_key!r} not among parsed features "
                f"{sorted(table)}")
        y = np.asarray(table[label_key])
        if label_transform is not None:
            y = label_transform(y)
        x = xs[0] if len(xs) == 1 else tuple(xs)
        return self.train(
            (x, y), criterion, optim_method=optim_method,
            batch_size=batch_size or self.pipeline.batch_size,
            end_trigger=end_trigger, distributed=distributed)


# reference spelling
BigDLSessionImpl = TFTrainingSession


# ==========================================================================
# saver (GraphDef writer) + graph-builder helpers
# ==========================================================================


class GraphDefBuilder:
    """Minimal GraphDef writer — builds frozen graphs for export/tests."""

    def __init__(self):
        self.nodes: List[_WireWriter] = []

    def _node(self, name, op, inputs=(), attrs: Optional[dict] = None):
        n = _WireWriter()
        n.string(1, name)
        n.string(2, op)
        for i in inputs:
            n.string(3, i)
        for k, v in (attrs or {}).items():
            entry = _WireWriter()
            entry.string(1, k)
            entry.message(2, v)
            n.message(5, entry)
        self.nodes.append(n)
        return name

    @staticmethod
    def attr_tensor(arr: np.ndarray) -> _WireWriter:
        a = _WireWriter()
        a.message(8, _encode_tensor(arr))
        return a

    @staticmethod
    def attr_type(dt: int) -> _WireWriter:
        a = _WireWriter()
        a.varint(6, dt)
        return a

    @staticmethod
    def attr_s(s: str) -> _WireWriter:
        a = _WireWriter()
        a.string(2, s)
        return a

    @staticmethod
    def attr_b(b: bool) -> _WireWriter:
        a = _WireWriter()
        a.varint(5, 1 if b else 0)
        return a

    @staticmethod
    def attr_i(v: int) -> _WireWriter:
        a = _WireWriter()
        a.varint(3, v)
        return a

    @staticmethod
    def attr_f(x: float) -> _WireWriter:
        a = _WireWriter()
        a.parts.append(_WireWriter._varint(4 << 3 | 5))
        a.parts.append(struct.pack("<f", x))
        return a

    @staticmethod
    def attr_ints(vals: List[int]) -> _WireWriter:
        lst = _WireWriter()
        for v in vals:
            lst.varint(3, v)
        a = _WireWriter()
        a.message(1, lst)
        return a

    @staticmethod
    def attr_types(vals: List[int]) -> _WireWriter:
        """list(type) — ListValue.type (field 6)."""
        lst = _WireWriter()
        for v in vals:
            lst.varint(6, v)
        a = _WireWriter()
        a.message(1, lst)
        return a

    @staticmethod
    def attr_shapes(shapes: List[List[int]]) -> _WireWriter:
        """list(shape) — ListValue.shape (field 7)."""
        lst = _WireWriter()
        for sh in shapes:
            shape = _WireWriter()
            for d in sh:
                dim = _WireWriter()
                dim.varint(1, d)
                shape.message(2, dim)
            lst.message(7, shape)
        a = _WireWriter()
        a.message(1, lst)
        return a

    def placeholder(self, name, dtype=_DT_FLOAT):
        return self._node(name, "Placeholder", attrs={"dtype": self.attr_type(dtype)})

    def const(self, name, arr: np.ndarray):
        return self._node(name, "Const", attrs={
            "value": self.attr_tensor(arr),
            "dtype": self.attr_type(_DT_FLOAT),
        })

    def op(self, name, op, inputs, **attrs):
        return self._node(name, op, inputs, attrs)

    def tobytes(self) -> bytes:
        g = _WireWriter()
        for n in self.nodes:
            g.message(1, n)
        return g.tobytes()


class TensorflowSaver:
    """Reference: «bigdl»/utils/tf/TensorflowSaver.scala — export a Graph
    of supported layers as a frozen GraphDef."""

    @staticmethod
    def save(graph, path: str):
        data = TensorflowSaver.to_graphdef(graph)
        with open(path, "wb") as f:
            f.write(data)

    @staticmethod
    def to_graphdef(graph) -> bytes:
        from bigdl_tpu.nn import layers as L
        from bigdl_tpu.nn import table_ops as T

        b = GraphDefBuilder()
        names: Dict[int, str] = {}
        counter = [0]

        for node in graph.topo_order():
            m = node.module
            if node in graph.input_nodes:
                nm = m._name or f"input{node.id}"
                b.placeholder(nm)
                names[node.id] = nm
                continue
            counter[0] += 1
            nm = m._name or f"{type(m).__name__.lower()}{counter[0]}"
            prev = [names[p.id] for p in node.prev_nodes]

            if isinstance(m, L.Linear):
                w = np.asarray(m.weight).T  # (in, out)
                b.const(nm + "/w", np.ascontiguousarray(w))
                out = b.op(nm, "MatMul", [prev[0], nm + "/w"],
                           transpose_a=b.attr_b(False),
                           transpose_b=b.attr_b(False))
                if m.bias is not None:
                    b.const(nm + "/b", np.asarray(m.bias))
                    out = b.op(nm + "/bias", "BiasAdd", [nm, nm + "/b"])
                names[node.id] = out
                continue
            simple = {
                L.ReLU: "Relu", L.ReLU6: "Relu6", L.Tanh: "Tanh",
                L.Sigmoid: "Sigmoid", L.SoftMax: "Softmax",
                L.LogSoftMax: "LogSoftmax", L.SoftPlus: "Softplus",
                L.Abs: "Abs", L.Exp: "Exp", L.Log: "Log",
                L.Square: "Square", L.Sqrt: "Sqrt", L.Negative: "Neg",
            }.get(type(m))
            if simple:
                names[node.id] = b.op(nm, simple, prev)
                continue
            if isinstance(m, T.CAddTable):
                out = prev[0]
                for i, p in enumerate(prev[1:]):
                    out = b.op(f"{nm}_{i}" if len(prev) > 2 else nm,
                               "AddV2", [out, p])
                names[node.id] = out
                continue
            if isinstance(m, T.JoinTable):
                b.const(nm + "/axis", np.asarray(m.dimension - 1, np.int32))
                names[node.id] = b.op(nm, "ConcatV2", prev + [nm + "/axis"],
                                      N=b.attr_ints([len(prev)]))
                continue
            if isinstance(m, L.SpatialConvolution) \
                    and type(m) is L.SpatialConvolution:
                # NCHW Conv2D; loader reads HWIO weights.  VALID for
                # pad 0, SAME when the pad is the stride-1 half-kernel
                if m.n_group != 1:
                    raise TFConversionException(
                        "TensorflowSaver: grouped conv unsupported")
                if m.pad_w == m.pad_h == 0:
                    padding = "VALID"
                elif (m.stride_w == m.stride_h == 1
                      and m.pad_w == (m.kernel_w - 1) // 2
                      and m.pad_h == (m.kernel_h - 1) // 2):
                    padding = "SAME"
                else:
                    raise TFConversionException(
                        "TensorflowSaver: conv padding has no TF "
                        "SAME/VALID equivalent")
                w = np.asarray(m.weight)  # (O, I, kh, kw) -> HWIO
                b.const(nm + "/w",
                        np.ascontiguousarray(w.transpose(2, 3, 1, 0)))
                out = b.op(nm, "Conv2D", [prev[0], nm + "/w"],
                           strides=b.attr_ints(
                               [1, 1, m.stride_h, m.stride_w]),
                           padding=b.attr_s(padding),
                           data_format=b.attr_s("NCHW"))
                if m.with_bias and m.bias is not None:
                    b.const(nm + "/b", np.asarray(m.bias))
                    out = b.op(nm + "/bias", "BiasAdd", [out, nm + "/b"],
                               data_format=b.attr_s("NCHW"))
                names[node.id] = out
                continue
            if isinstance(m, (L.SpatialMaxPooling, L.SpatialAveragePooling)):
                if getattr(m, "global_pooling", False):
                    raise TFConversionException(
                        "TensorflowSaver: global pooling unsupported")
                if m.pad_w or m.pad_h:
                    raise TFConversionException(
                        "TensorflowSaver: padded pooling unsupported")
                opn = "MaxPool" if isinstance(m, L.SpatialMaxPooling) \
                    else "AvgPool"
                names[node.id] = b.op(
                    nm, opn, prev,
                    ksize=b.attr_ints([1, 1, m.kh, m.kw]),
                    strides=b.attr_ints([1, 1, m.dh, m.dw]),
                    padding=b.attr_s("VALID"),
                    data_format=b.attr_s("NCHW"))
                continue
            if isinstance(m, L.SpatialBatchNormalization) \
                    and type(m) is L.SpatialBatchNormalization:
                c = m.n_output
                ones = np.ones(c, np.float32)
                zeros = np.zeros(c, np.float32)
                b.const(nm + "/scale",
                        np.asarray(m.weight) if m.affine else ones)
                b.const(nm + "/offset",
                        np.asarray(m.bias) if m.affine else zeros)
                b.const(nm + "/mean", np.asarray(m.running_mean))
                b.const(nm + "/var", np.asarray(m.running_var))
                names[node.id] = b.op(
                    nm, "FusedBatchNorm",
                    [prev[0], nm + "/scale", nm + "/offset",
                     nm + "/mean", nm + "/var"],
                    epsilon=b.attr_f(m.eps),
                    data_format=b.attr_s("NCHW"))
                continue
            if isinstance(m, L.Reshape):
                b.const(nm + "/shape",
                        np.asarray([-1] + list(m.size), np.int32))
                names[node.id] = b.op(nm, "Reshape",
                                      [prev[0], nm + "/shape"])
                continue
            if isinstance(m, L.Squeeze):
                attrs = {}
                if m.dim is not None:
                    attrs["squeeze_dims"] = b.attr_ints([m.dim - 1])
                names[node.id] = b.op(nm, "Squeeze", prev, **attrs)
                continue
            if isinstance(m, L.Dropout) or type(m).__name__ == "Identity":
                # frozen-inference semantics: dropout exports as identity
                names[node.id] = b.op(nm, "Identity", prev)
                continue
            raise TFConversionException(
                f"TensorflowSaver: unsupported layer {type(m).__name__}"
            )
        return b.tobytes()
