"""bigdl_tpu.utils — persistence, summaries, interop.

Reference surface («bigdl»/utils/): Module.save/loadModule (serializer),
Module.loadCaffeModel / CaffePersister (caffe), Module.loadTF /
TensorflowSaver (tf), File.loadTorch/saveTorch (torch_file).
"""

from bigdl_tpu.utils.serializer import (
    CheckpointIntegrityError,
    gc_checkpoints,
    load_checkpoint,
    load_latest_checkpoint,
    load_module,
    save_checkpoint,
    save_module,
    verify_checkpoint,
)
from bigdl_tpu.utils.caffe import (
    CaffeLoader,
    CaffePersister,
    load_caffe_model,
    load_caffe_weights,
)
from bigdl_tpu.utils.tf_interop import (
    TensorflowLoader,
    TensorflowSaver,
    load_tf,
)
from bigdl_tpu.utils.torch_file import (
    load_t7,
    load_torch_module,
    save_t7,
)

__all__ = [
    "CheckpointIntegrityError", "gc_checkpoints",
    "load_checkpoint", "load_latest_checkpoint", "load_module",
    "save_checkpoint", "save_module", "verify_checkpoint",
    "CaffeLoader", "CaffePersister", "load_caffe_model", "load_caffe_weights",
    "TensorflowLoader", "TensorflowSaver", "load_tf",
    "load_t7", "load_torch_module", "save_t7",
]
