"""bigdl_tpu.utils — persistence, summaries, interop."""
