"""bigdl.proto module interchange — ModulePersister / ModuleLoader.

Rebuild of ⟦«bigdl»/utils/serializer/⟧ (ModuleSerializer, ModuleLoader,
ModulePersister) against the reference protobuf schema
⟦spark/dl/src/main/resources/serialization/bigdl.proto⟧ (SURVEY.md §2.1
"Module serialization"; VERDICT round-1 item 3).

The reference persists a module graph as one ``BigDLModule`` protobuf:
``moduleType`` is the Scala class FQN, constructor arguments live in the
``attr`` map (reflection-derived, Scala camelCase names), containers
recurse through ``subModules``, parameters ride as ``BigDLTensor``s, and
graph wiring uses ``preModules``/``nextModules`` name lists.  This file
speaks that wire format with the generic protobuf codec from
``utils/caffe.py`` — no generated code, no protoc.

Name bridge: the rebuild's constructor args are snake_case spellings of
the reference's camelCase (n_input_plane ⇄ nInputPlane), so attr names
convert mechanically both ways; values that have no typed slot fall back
to a STRING attr with ``subType="json"`` (a documented extension — a
real BigDL reader would skip them, our loader round-trips them).

⚠ Field numbers below are the upstream 0.x layout as best reconstructible
with the reference mount empty this round (SURVEY.md evidence-status
preamble); re-verify against the real bigdl.proto when the mount is
populated (SURVEY.md §8).
"""

from __future__ import annotations

import inspect
import json
import struct
from typing import Dict, List, Optional

import numpy as np

from bigdl_tpu.utils.caffe import (
    _WireWriter,
    _w_bool,
    _w_float,
    _w_floats,
    _w_int,
    _w_ints,
    _w_msgs,
    _w_str,
    _w_strs,
    parse_wire,
)

# ---------------------------------------------------------------- schema
# DataType enum (bigdl.proto)
DT_INT32 = 0
DT_INT64 = 1
DT_FLOAT = 2
DT_DOUBLE = 3
DT_STRING = 4
DT_BOOL = 5
DT_TENSOR = 10
DT_MODULE = 13
DT_ARRAY_VALUE = 15

# BigDLModule fields
_M_NAME = 1
_M_SUBMODULES = 2
_M_WEIGHT = 3
_M_BIAS = 4
_M_PREMODULES = 5
_M_NEXTMODULES = 6
_M_MODULETYPE = 7
_M_ATTR = 8            # map<string, AttrValue>
_M_VERSION = 9
_M_TRAIN = 10
_M_NAMEPOSTFIX = 11
_M_ID = 12
_M_HASPARAMETERS = 15
_M_PARAMETERS = 16

# BigDLTensor fields
_T_DATATYPE = 1
_T_SIZE = 2
_T_STRIDE = 3
_T_OFFSET = 4
_T_DIMENSION = 5
_T_NELEMENTS = 6
_T_ISSCALAR = 7
_T_STORAGE = 8
_T_ID = 9
_T_TENSORTYPE = 10

# TensorStorage fields
_S_DATATYPE = 1
_S_FLOAT_DATA = 2
_S_DOUBLE_DATA = 3
_S_INT32_DATA = 4
_S_INT64_DATA = 5
_S_ID = 9

# AttrValue fields
_A_DATATYPE = 1
_A_SUBTYPE = 2
_A_INT32 = 3
_A_INT64 = 4
_A_FLOAT = 5
_A_DOUBLE = 6
_A_STRING = 7
_A_BOOL = 8
_A_TENSOR = 10
_A_MODULE = 13
_A_ARRAY = 15

# ArrayValue fields
_AR_SIZE = 1
_AR_DATATYPE = 2
_AR_I32 = 3
_AR_I64 = 4
_AR_FLT = 5
_AR_DBL = 6
_AR_STR = 7
_AR_BOOL = 8

_SCALA_PKG = "com.intel.analytics.bigdl.nn."
_VERSION = "0.13.0"


# ---------------------------------------------------------- name bridge
def snake_to_camel(name: str) -> str:
    parts = name.split("_")
    return parts[0] + "".join(p.title() for p in parts[1:])


def camel_to_snake(name: str) -> str:
    out = []
    for ch in name:
        if ch.isupper():
            out.append("_")
            out.append(ch.lower())
        else:
            out.append(ch)
    return "".join(out)


# reference attr spellings that are not mechanical camelCase of ours
_TO_SCALA = {
    "n_input_plane": "nInputPlane",
    "n_output_plane": "nOutputPlane",
    "n_group": "nGroup",
    "n_output": "nOutput",
    "input_size": "inputSize",
    "hidden_size": "hiddenSize",
    "output_size": "outputSize",
    "init_p": "initP",
    # Python keyword collision: SoftShrink/HardShrink's Scala arg
    "lambda_": "lambda",
}
_FROM_SCALA = {v: k for k, v in _TO_SCALA.items()}


def _attr_to_scala(name: str) -> str:
    return _TO_SCALA.get(name, snake_to_camel(name))


def _attr_from_scala(name: str) -> str:
    return _FROM_SCALA.get(name, camel_to_snake(name))


# ------------------------------------------------------------- tensors
def _write_tensor(arr: np.ndarray) -> _WireWriter:
    arr = np.asarray(arr)
    t = _WireWriter()
    t.varint(_T_DATATYPE, DT_FLOAT)
    for s in arr.shape:
        t.varint(_T_SIZE, int(s))
    # torch-style contiguous strides
    stride = []
    acc = 1
    for s in reversed(arr.shape):
        stride.insert(0, acc)
        acc *= int(s)
    for s in stride:
        t.varint(_T_STRIDE, int(s))
    t.varint(_T_OFFSET, 1)  # reference tensors are 1-based offset
    t.varint(_T_DIMENSION, arr.ndim)
    t.varint(_T_NELEMENTS, int(arr.size))
    if arr.ndim == 0:
        t.varint(_T_ISSCALAR, 1)
    st = _WireWriter()
    st.varint(_S_DATATYPE, DT_FLOAT)
    st.packed_floats(_S_FLOAT_DATA, np.asarray(arr, "<f4").reshape(-1))
    t.message(_T_STORAGE, st)
    t.varint(_T_TENSORTYPE, 0)  # DENSE
    return t


def _read_tensor(msg: Dict[int, list]) -> Optional[np.ndarray]:
    storage = _w_msgs(msg, _T_STORAGE)
    if not storage:
        return None
    data = _w_floats(storage[0], _S_FLOAT_DATA)
    if data.size == 0:
        dd = storage[0].get(_S_DOUBLE_DATA)
        if dd:
            data = np.concatenate(
                [np.frombuffer(v, "<f8") for _, v in dd]
            ).astype(np.float32)
    size = _w_ints(msg, _T_SIZE)
    if size and int(np.prod(size)) == data.size:
        data = data.reshape(size)
    return data


# ---------------------------------------------------------- attr values
def _write_attr(value) -> _WireWriter:
    a = _WireWriter()
    if isinstance(value, bool):
        a.varint(_A_DATATYPE, DT_BOOL)
        a.varint(_A_BOOL, int(value))
    elif isinstance(value, (int, np.integer)):
        a.varint(_A_DATATYPE, DT_INT32)
        a.varint(_A_INT32, int(value))
    elif isinstance(value, (float, np.floating)):
        a.varint(_A_DATATYPE, DT_DOUBLE)
        a.parts.append(a._varint(_A_DOUBLE << 3 | 1))  # fixed64
        a.parts.append(struct.pack("<d", float(value)))
    elif isinstance(value, str):
        a.varint(_A_DATATYPE, DT_STRING)
        a.string(_A_STRING, value)
    elif isinstance(value, np.ndarray):
        a.varint(_A_DATATYPE, DT_TENSOR)
        a.message(_A_TENSOR, _write_tensor(value))
    elif isinstance(value, (list, tuple)) and _is_flat_numeric(value):
        a.varint(_A_DATATYPE, DT_ARRAY_VALUE)
        arr = _WireWriter()
        arr.varint(_AR_SIZE, len(value))
        if all(isinstance(v, (int, np.integer))
               and not isinstance(v, bool) for v in value):
            arr.varint(_AR_DATATYPE, DT_INT32)
            for v in value:
                arr.varint(_AR_I32, int(v))
        else:
            arr.varint(_AR_DATATYPE, DT_DOUBLE)
            for v in value:
                arr.parts.append(arr._varint(_AR_DBL << 3 | 1))
                arr.parts.append(struct.pack("<d", float(v)))
        a.message(_A_ARRAY, arr)
    else:
        # documented extension: JSON spill for configs with no typed slot
        a.varint(_A_DATATYPE, DT_STRING)
        a.string(_A_SUBTYPE, "json")
        a.string(_A_STRING, json.dumps(value))
    return a


def _is_flat_numeric(value) -> bool:
    return all(
        isinstance(v, (int, float, np.integer, np.floating))
        for v in value
    ) and len(value) > 0


def _read_attr(msg: Dict[int, list]):
    dt = _w_int(msg, _A_DATATYPE, DT_STRING)
    if dt == DT_BOOL:
        return bool(_w_int(msg, _A_BOOL, 0))
    if dt == DT_INT32:
        return _w_int(msg, _A_INT32, 0)
    if dt == DT_INT64:
        return _w_int(msg, _A_INT64, 0)
    if dt == DT_FLOAT:
        return _w_float(msg, _A_FLOAT, 0.0)
    if dt == DT_DOUBLE:
        raws = msg.get(_A_DOUBLE)
        if raws:
            return struct.unpack("<d", raws[-1][1])[0]
        return 0.0
    if dt == DT_STRING:
        s = _w_str(msg, _A_STRING, "")
        if _w_str(msg, _A_SUBTYPE) == "json":
            return json.loads(s)
        return s
    if dt == DT_TENSOR:
        tensors = _w_msgs(msg, _A_TENSOR)
        return _read_tensor(tensors[0]) if tensors else None
    if dt == DT_ARRAY_VALUE:
        arrays = _w_msgs(msg, _A_ARRAY)
        if not arrays:
            return []
        arr = arrays[0]
        adt = _w_int(arr, _AR_DATATYPE, DT_INT32)
        if adt == DT_INT32:
            return _w_ints(arr, _AR_I32)
        if adt == DT_DOUBLE:
            out = []
            for wt, v in arr.get(_AR_DBL, []):
                if wt == 1:  # fixed64
                    out.append(struct.unpack("<d", v)[0])
                else:  # packed
                    out.extend(np.frombuffer(v, "<f8").tolist())
            return out
        if adt == DT_FLOAT:
            return _w_floats(arr, _AR_FLT).tolist()
        if adt == DT_STRING:
            return _w_strs(arr, _AR_STR)
    return None


# ------------------------------------------------------------ persister
class ModulePersister:
    """Reference: ModulePersister.saveToFile — serialize a module tree to
    the bigdl.proto wire format."""

    @staticmethod
    def save(module, path: str) -> str:
        data = ModulePersister.to_bytes(module)
        with open(path, "wb") as f:
            f.write(data)
        return path

    @staticmethod
    def to_bytes(module) -> bytes:
        return _module_to_writer(module).tobytes()


def _module_to_writer(module, name_counts=None) -> _WireWriter:
    from bigdl_tpu.nn.attention import _Composite
    from bigdl_tpu.nn.graph import Graph
    from bigdl_tpu.nn.module import Container

    w = _WireWriter()
    w.string(_M_NAME, module.get_name())
    w.string(_M_MODULETYPE, _SCALA_PKG + type(module).__name__)
    w.string(_M_VERSION, _VERSION)
    w.varint(_M_TRAIN, int(module.is_training))

    # constructor attrs
    for key, value in module.get_config().items():
        entry = _WireWriter()
        entry.string(1, _attr_to_scala(key))
        entry.message(2, _write_attr(value))
        w.message(_M_ATTR, entry)

    if isinstance(module, Graph):
        _write_graph(w, module)
        return w

    if isinstance(module, Container):
        for child in module.modules:
            w.message(_M_SUBMODULES, _module_to_writer(child))
        return w

    if isinstance(module, _Composite):
        # Named-children modules (TransformerBlock, TransformerLM, …):
        # each child rides as a subModule tagged with its slot name in
        # namePostfix so load can restore weights into the right slot
        # (reference containers do the same via subModule names).
        for key, child in module._children.items():
            sub = _module_to_writer(child)
            sub.string(_M_NAMEPOSTFIX, key)
            w.message(_M_SUBMODULES, sub)
        return w

    # leaf parameters: weight/bias ride the dedicated fields when the
    # module uses the classic pair; everything else via `parameters`
    params = [(n, getattr(module, n)) for n in module.param_names
              if getattr(module, n, None) is not None]
    if params:
        w.varint(_M_HASPARAMETERS, 1)
    for pname, arr in params:
        if pname == "weight":
            w.message(_M_WEIGHT, _write_tensor(np.asarray(arr)))
        elif pname == "bias":
            w.message(_M_BIAS, _write_tensor(np.asarray(arr)))
        else:
            w.message(_M_PARAMETERS, _write_tensor(np.asarray(arr)))
    return w


def _write_graph(w: _WireWriter, graph) -> None:
    """Graph wiring via preModules/nextModules name lists (reference:
    StaticGraph serialization).  DynamicGraph extras (feedback
    back-edges, condition node) ride as named attrs — a documented
    extension a real BigDL reader would skip."""
    # assign unique names
    names = {}
    for i, node in enumerate(graph._topo):
        base = node.module.get_name()
        names[node.id] = f"{base}#{i}"
    for node in graph._topo:
        sub = _module_to_writer(node.module)
        sub.string(_M_NAMEPOSTFIX, names[node.id])
        for p in node.prev_nodes:
            sub.string(_M_PREMODULES, names[p.id])
        for nxt in getattr(node, "next_nodes", []):
            sub.string(_M_NEXTMODULES, names[nxt.id])
        if node.feedback_node is not None:
            entry = _WireWriter()
            entry.string(1, "feedbackFrom")
            entry.message(2, _write_attr(names[node.feedback_node.id]))
            sub.message(_M_ATTR, entry)
        w.message(_M_SUBMODULES, sub)
    # record input/output node names as attrs
    for key, nodes in (("graphInputs", graph.input_nodes),
                       ("graphOutputs", graph.output_nodes)):
        entry = _WireWriter()
        entry.string(1, key)
        val = _WireWriter()
        val.varint(_A_DATATYPE, DT_ARRAY_VALUE)
        arr = _WireWriter()
        arr.varint(_AR_SIZE, len(nodes))
        arr.varint(_AR_DATATYPE, DT_STRING)
        for n in nodes:
            arr.string(_AR_STR, names[n.id])
        val.message(_A_ARRAY, arr)
        entry.message(2, val)
        w.message(_M_ATTR, entry)
    cond = getattr(graph, "_condition_node", None)
    if cond is not None:
        entry = _WireWriter()
        entry.string(1, "dynamicCondition")
        entry.message(2, _write_attr(names[cond.id]))
        w.message(_M_ATTR, entry)


# -------------------------------------------------------------- loader
class ModuleLoader:
    """Reference: ModuleLoader.loadFromFile — parse the bigdl.proto wire
    format back into a live module tree."""

    @staticmethod
    def load(path: str):
        with open(path, "rb") as f:
            data = f.read()
        return ModuleLoader.from_bytes(data)

    @staticmethod
    def from_bytes(data: bytes):
        return _module_from_fields(parse_wire(data))


def _class_for(module_type: str):
    from bigdl_tpu.utils.serializer import lookup_module_class

    cls_name = module_type.rsplit(".", 1)[-1]
    try:
        return lookup_module_class(cls_name)
    except KeyError:
        raise KeyError(
            f"unknown module type {module_type!r}; register_module() "
            "custom layers before loading"
        ) from None


def _construct(cls, attrs: dict):
    """Build cls from the attr map, keeping only args the constructor
    knows (the reference's reflection does the same per converter)."""
    sig = inspect.signature(cls.__init__)
    accepted = {
        k for k in sig.parameters if k not in ("self", "args", "kwargs")
    }
    var_kw = any(
        p.kind == inspect.Parameter.VAR_KEYWORD
        for p in sig.parameters.values()
    )
    kwargs = {}
    for k, v in attrs.items():
        if k in accepted or var_kw:
            kwargs[k] = v
    return cls(**kwargs)


def _module_from_fields(f: Dict[int, list]):
    from bigdl_tpu.nn.attention import _Composite
    from bigdl_tpu.nn.graph import Graph, Node
    from bigdl_tpu.nn.module import Container

    module_type = _w_str(f, _M_MODULETYPE, "")
    cls = _class_for(module_type)
    attrs = {}
    raw_attrs = {}
    for entry in _w_msgs(f, _M_ATTR):
        key = _w_str(entry, 1, "")
        vals = _w_msgs(entry, 2)
        if not vals:
            continue
        raw_attrs[key] = _read_attr(vals[0])
        attrs[_attr_from_scala(key)] = raw_attrs[key]

    subs = _w_msgs(f, _M_SUBMODULES)
    if issubclass(cls, Graph):
        module = _graph_from_fields(f, subs, raw_attrs, cls)
    else:
        module = _construct(cls, attrs)
        if issubclass(cls, Container) and subs:
            module.modules = []
            for sub in subs:
                module.modules.append(_module_from_fields(sub))
        elif isinstance(module, _Composite) and subs:
            # restore named children by slot name (written in namePostfix)
            for sub in subs:
                key = _w_str(sub, _M_NAMEPOSTFIX, "")
                if key and key in module._children:
                    module._children[key] = _module_from_fields(sub)

    name = _w_str(f, _M_NAME)
    if name and "@" not in name:
        module.set_name(name)
    if not _w_bool(f, _M_TRAIN, True):
        module.evaluate()

    # parameters back in declaration order
    if not issubclass(cls, (Container, Graph)):
        import jax.numpy as jnp

        for pname in getattr(module, "param_names", ()):
            cur = getattr(module, pname, None)
            if cur is None:
                continue
            if pname == "weight":
                msgs = _w_msgs(f, _M_WEIGHT)
            elif pname == "bias":
                msgs = _w_msgs(f, _M_BIAS)
            else:
                msgs = None
            if msgs:
                arr = _read_tensor(msgs[0])
                if arr is not None:
                    setattr(module, pname, jnp.asarray(
                        arr.reshape(np.asarray(cur).shape)))
        others = [n for n in getattr(module, "param_names", ())
                  if n not in ("weight", "bias")
                  and getattr(module, n, None) is not None]
        extra = _w_msgs(f, _M_PARAMETERS)
        for pname, msg in zip(others, extra):
            arr = _read_tensor(msg)
            if arr is not None:
                cur = getattr(module, pname)
                setattr(module, pname, jnp.asarray(
                    arr.reshape(np.asarray(cur).shape)))
    return module


def _sub_attr(sub, key: str):
    """Read one named attr from a subModule message."""
    for entry in _w_msgs(sub, _M_ATTR):
        if _w_str(entry, 1, "") == key:
            vals = _w_msgs(entry, 2)
            return _read_attr(vals[0]) if vals else None
    return None


def _graph_from_fields(f, subs, raw_attrs, cls=None):
    from bigdl_tpu.nn.graph import DynamicGraph, Graph, Node

    nodes = {}
    order = []
    wiring = []
    for sub in subs:
        mod = _module_from_fields(sub)
        post = _w_str(sub, _M_NAMEPOSTFIX, "")
        prevs = _w_strs(sub, _M_PREMODULES)
        nodes[post] = Node(mod, [])
        order.append(post)
        wiring.append((post, prevs))
    for post, prevs in wiring:
        node = nodes[post]
        for p in prevs:
            node.prev_nodes.append(nodes[p])
    for sub, post in zip(subs, order):
        fb = _sub_attr(sub, "feedbackFrom")
        if fb:
            nodes[post].feedback_from(nodes[fb])
    inputs = [nodes[n] for n in raw_attrs.get("graphInputs", [])]
    outputs = [nodes[n] for n in raw_attrs.get("graphOutputs", [])]
    if cls is not None and issubclass(cls, DynamicGraph):
        cond_name = raw_attrs.get("dynamicCondition")
        return cls(
            inputs, outputs,
            max_iterations=int(raw_attrs.get("maxIterations", 32)),
            condition=nodes.get(cond_name) if cond_name else None,
        )
    return Graph(inputs, outputs)


# -------------------------------------------------------- parity names
def save_module_proto(module, path: str) -> str:
    """Reference spelling: Module.saveModule(path) (protobuf format)."""
    return ModulePersister.save(module, path)


def load_module_proto(path: str):
    """Reference spelling: Module.loadModule(path)."""
    return ModuleLoader.load(path)
