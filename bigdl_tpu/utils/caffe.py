"""Caffe interop — load/save ``.prototxt`` + ``.caffemodel``.

Rebuild of «bigdl»/utils/caffe/ (SURVEY.md §2.1 "Caffe interop": reads
``.prototxt`` + ``.caffemodel`` (V1/V2), maps Caffe layers → BigDL
layers, also writes; used by Inception/VGG configs).

No protobuf runtime dependency: the text format is parsed with a small
recursive-descent parser and the binary format with a generic protobuf
*wire* reader/writer (the schema is fixed by upstream Caffe and encoded
here as field-number tables).  The converter builds a
:class:`bigdl_tpu.nn.Graph` wired by Caffe blob names, tracking
``(C, H, W)`` through the net so ``InnerProduct`` can size its
``Linear`` — the same shape inference the reference performs.
"""

from __future__ import annotations

import struct
from typing import Dict, List, Optional, Tuple

import numpy as np

# ==========================================================================
# protobuf text format (prototxt)
# ==========================================================================


def _tokenize_text(text: str):
    out = []
    i, n = 0, len(text)
    while i < n:
        c = text[i]
        if c == "#":
            while i < n and text[i] != "\n":
                i += 1
        elif c.isspace():
            i += 1
        elif c in "{}:":
            out.append(c)
            i += 1
        elif c in "\"'":
            j = i + 1
            buf = []
            while j < n and text[j] != c:
                if text[j] == "\\":
                    j += 1
                buf.append(text[j])
                j += 1
            out.append(("STR", "".join(buf)))
            i = j + 1
        else:
            j = i
            while j < n and not text[j].isspace() and text[j] not in "{}:#":
                j += 1
            out.append(("TOK", text[i:j]))
            i = j
    return out


def parse_prototxt(text: str) -> dict:
    """Parse protobuf text format into nested dicts; every field maps to a
    *list* of values (protobuf fields are conceptually repeated)."""
    toks = _tokenize_text(text)
    pos = 0

    def parse_block():
        nonlocal pos
        msg: dict = {}
        while pos < len(toks) and toks[pos] != "}":
            name = toks[pos][1]
            pos += 1
            if pos < len(toks) and toks[pos] == ":":
                pos += 1
                kind, raw = toks[pos]
                pos += 1
                if kind == "STR":
                    val = raw
                else:
                    val = _coerce_scalar(raw)
                msg.setdefault(name, []).append(val)
            elif pos < len(toks) and toks[pos] == "{":
                pos += 1
                sub = parse_block()
                assert toks[pos] == "}", "unbalanced block"
                pos += 1
                msg.setdefault(name, []).append(sub)
            else:
                raise ValueError(f"bad prototxt near token {pos}: {toks[pos-1]}")
        return msg

    return parse_block()


def _coerce_scalar(raw: str):
    if raw in ("true", "True"):
        return True
    if raw in ("false", "False"):
        return False
    try:
        return int(raw)
    except ValueError:
        pass
    try:
        return float(raw)
    except ValueError:
        return raw  # enum identifier


def _fmt_value(v) -> str:
    if isinstance(v, bool):
        return "true" if v else "false"
    if isinstance(v, str):
        # enums (UPPERCASE) stay bare, everything else quoted
        if v.isupper() or v.replace("_", "").isupper():
            return v
        return f'"{v}"'
    if isinstance(v, float):
        return repr(v)
    return str(v)


def format_prototxt(msg: dict, indent: int = 0) -> str:
    pad = "  " * indent
    lines = []
    for name, values in msg.items():
        for v in values:
            if isinstance(v, dict):
                lines.append(f"{pad}{name} {{")
                lines.append(format_prototxt(v, indent + 1))
                lines.append(f"{pad}}}")
            else:
                lines.append(f"{pad}{name}: {_fmt_value(v)}")
    return "\n".join(l for l in lines if l != "")


# ==========================================================================
# protobuf wire format (caffemodel)
# ==========================================================================

_WT_VARINT, _WT_FIX64, _WT_BYTES, _WT_FIX32 = 0, 1, 2, 5


def _read_varint(buf: memoryview, pos: int) -> Tuple[int, int]:
    result = 0
    shift = 0
    while True:
        b = buf[pos]
        pos += 1
        result |= (b & 0x7F) << shift
        if not b & 0x80:
            return result, pos
        shift += 7


def _signed(v: int) -> int:
    """Interpret a decoded varint as a protobuf int32/int64 (negatives
    ride as 64-bit two's complement on the wire)."""
    return v - (1 << 64) if v >= (1 << 63) else v


def parse_wire(buf) -> Dict[int, list]:
    """Decode one message into {field: [(wire_type, raw_value), ...]}."""
    mv = memoryview(buf)
    fields: Dict[int, list] = {}
    pos = 0
    end = len(mv)
    while pos < end:
        key, pos = _read_varint(mv, pos)
        fno, wt = key >> 3, key & 7
        if wt == _WT_VARINT:
            val, pos = _read_varint(mv, pos)
        elif wt == _WT_FIX64:
            val = mv[pos : pos + 8].tobytes()
            pos += 8
        elif wt == _WT_BYTES:
            ln, pos = _read_varint(mv, pos)
            val = mv[pos : pos + ln].tobytes()
            pos += ln
        elif wt == _WT_FIX32:
            val = mv[pos : pos + 4].tobytes()
            pos += 4
        else:
            raise ValueError(f"unsupported wire type {wt} (field {fno})")
        fields.setdefault(fno, []).append((wt, val))
    return fields


def _w_str(f: Dict[int, list], fno: int, default=None):
    if fno in f:
        return f[fno][-1][1].decode("utf-8", "replace")
    return default


def _w_strs(f, fno) -> List[str]:
    return [v.decode("utf-8", "replace") for _, v in f.get(fno, [])]


def _w_int(f, fno, default=None):
    if fno in f:
        return _signed(int(f[fno][-1][1]))
    return default


def _w_ints(f, fno) -> List[int]:
    out = []
    for wt, v in f.get(fno, []):
        if wt == _WT_VARINT:
            out.append(_signed(int(v)))
        else:  # packed
            mv = memoryview(v)
            pos = 0
            while pos < len(mv):
                x, pos = _read_varint(mv, pos)
                out.append(_signed(x))
    return out


def _w_float(f, fno, default=None):
    if fno in f:
        wt, v = f[fno][-1]
        if wt == _WT_FIX32:
            return struct.unpack("<f", v)[0]
    return default


def _w_floats(f, fno) -> np.ndarray:
    chunks = []
    for wt, v in f.get(fno, []):
        if wt == _WT_FIX32:
            chunks.append(np.frombuffer(v, dtype="<f4"))
        elif wt == _WT_BYTES:  # packed
            chunks.append(np.frombuffer(v, dtype="<f4"))
    if not chunks:
        return np.zeros((0,), np.float32)
    return np.concatenate(chunks)


def _w_bool(f, fno, default=None):
    v = _w_int(f, fno, None)
    return default if v is None else bool(v)


def _w_msgs(f, fno) -> List[Dict[int, list]]:
    return [parse_wire(v) for wt, v in f.get(fno, []) if wt == _WT_BYTES]


class _WireWriter:
    def __init__(self):
        self.parts: List[bytes] = []

    @staticmethod
    def _varint(x: int) -> bytes:
        if x < 0:  # protobuf int32/int64: 64-bit two's complement
            x += 1 << 64
        out = bytearray()
        while True:
            b = x & 0x7F
            x >>= 7
            if x:
                out.append(b | 0x80)
            else:
                out.append(b)
                return bytes(out)

    def varint(self, fno: int, val: int):
        self.parts.append(self._varint(fno << 3 | _WT_VARINT))
        self.parts.append(self._varint(int(val)))

    def string(self, fno: int, s: str):
        self.bytes_(fno, s.encode("utf-8"))

    def bytes_(self, fno: int, b: bytes):
        self.parts.append(self._varint(fno << 3 | _WT_BYTES))
        self.parts.append(self._varint(len(b)))
        self.parts.append(b)

    def float_(self, fno: int, v: float):
        self.parts.append(self._varint(fno << 3 | _WT_FIX32))
        self.parts.append(struct.pack("<f", v))

    def packed_floats(self, fno: int, arr: np.ndarray):
        self.bytes_(fno, np.asarray(arr, dtype="<f4").tobytes())

    def message(self, fno: int, sub: "_WireWriter"):
        self.bytes_(fno, sub.tobytes())

    def tobytes(self) -> bytes:
        return b"".join(self.parts)


# ==========================================================================
# caffemodel schema slices (field-number tables from upstream caffe.proto)
# ==========================================================================

# V1LayerParameter.LayerType enum value -> V2 type string
_V1_TYPES = {
    1: "Accuracy", 2: "BNLL", 3: "Concat", 4: "Convolution", 5: "Data",
    6: "Dropout", 7: "EuclideanLoss", 8: "Flatten", 14: "InnerProduct",
    15: "LRN", 17: "Pooling", 18: "ReLU", 19: "Sigmoid", 20: "Softmax",
    21: "SoftmaxWithLoss", 22: "Split", 23: "TanH", 25: "Eltwise",
    26: "Power", 30: "ArgMax", 33: "Slice", 35: "AbsVal", 36: "Silence",
    39: "Deconvolution",
}


def _blob_to_array(blob: Dict[int, list]) -> np.ndarray:
    data = _w_floats(blob, 5)
    if data.size == 0:
        dd = blob.get(8)
        if dd:  # double_data
            data = np.concatenate(
                [np.frombuffer(v, dtype="<f8") for _, v in dd]
            ).astype(np.float32)
    shape_msgs = _w_msgs(blob, 7)
    if shape_msgs:
        dims = _w_ints(shape_msgs[0], 1)
    else:  # legacy num/channels/height/width
        dims = [
            _w_int(blob, 1, 1), _w_int(blob, 2, 1),
            _w_int(blob, 3, 1), _w_int(blob, 4, 1),
        ]
        while len(dims) > 1 and dims[0] == 1:
            dims = dims[1:]
    if int(np.prod(dims)) != data.size:
        dims = [data.size]
    return data.reshape(dims)


def _array_to_blob(arr: np.ndarray) -> _WireWriter:
    w = _WireWriter()
    shape = _WireWriter()
    for d in arr.shape:
        shape.varint(1, d)
    w.message(7, shape)
    w.packed_floats(5, arr.reshape(-1))
    return w


def load_caffemodel(path: str) -> Dict[str, dict]:
    """Read a ``.caffemodel`` → {layer_name: {"type": str, "blobs": [np]}}.
    Handles both V2 (``layer`` field 100) and legacy V1 (``layers``
    field 2) nets."""
    with open(path, "rb") as f:
        net = parse_wire(f.read())
    out: Dict[str, dict] = {}
    for lp in _w_msgs(net, 100):  # V2 LayerParameter
        name = _w_str(lp, 1, "")
        out[name] = {
            "type": _w_str(lp, 2, ""),
            "blobs": [_blob_to_array(b) for b in _w_msgs(lp, 7)],
        }
    for lp in _w_msgs(net, 2):  # V1LayerParameter
        name = _w_str(lp, 4, "")
        if name in out:
            continue
        out[name] = {
            "type": _V1_TYPES.get(_w_int(lp, 5, 0), str(_w_int(lp, 5, 0))),
            "blobs": [_blob_to_array(b) for b in _w_msgs(lp, 6)],
        }
    return out


# ==========================================================================
# prototxt → layer descriptions (normalising V1/V2 text spellings)
# ==========================================================================


def _first(d: dict, key: str, default=None):
    v = d.get(key)
    return v[0] if v else default


def _net_layers(net: dict) -> List[dict]:
    layers = list(net.get("layer", [])) + list(net.get("layers", []))
    out = []
    for l in layers:
        t = _first(l, "type", "")
        if isinstance(t, str) and t.isupper():  # V1 text enum e.g. CONVOLUTION
            # legacy spellings use underscores (INNER_PRODUCT,
            # EUCLIDEAN_LOSS) — strip them on both sides of the lookup
            v1 = {v.upper().replace("WITHLOSS", "_LOSS").replace("_", ""): v
                  for v in _V1_TYPES.values()}
            t = v1.get(t.replace("_", ""), t.title())
        out.append({**l, "type": [t]})
    return out


def _train_only(l: dict) -> bool:
    for inc in l.get("include", []):
        if _first(inc, "phase") in ("TRAIN", 0):
            return True
    return False


# ==========================================================================
# shape arithmetic (caffe conventions: pooling rounds up, conv rounds down)
# ==========================================================================


def _conv_out(size, k, pad, stride, dil=1):
    eff = dil * (k - 1) + 1
    return (size + 2 * pad - eff) // stride + 1


def _pool_out(size, k, pad, stride):
    out = -(-(size + 2 * pad - k) // stride) + 1  # ceil
    if pad > 0 and (out - 1) * stride >= size + pad:
        out -= 1
    return out


def _kern2(p: dict, base: str, hk="_h", wk="_w"):
    """kernel/stride/pad may be a single repeated value or _h/_w pair."""
    h = _first(p, base + hk)
    w = _first(p, base + wk)
    if h is None or w is None:
        vals = p.get(base + "_size" if base == "kernel" else base, [])
        v = vals[0] if vals else None
        h = h if h is not None else v
        w = w if w is not None else v
    return h, w


# ==========================================================================
# the converter
# ==========================================================================


class CaffeConversionException(Exception):
    pass


class CaffeLoader:
    """Reference: «bigdl»/utils/caffe/CaffeLoader.scala.

    ``load()`` builds a :class:`Graph` from the prototxt (inference
    phase), then copies weights from the caffemodel by layer name.
    """

    def __init__(self, prototxt_path: Optional[str] = None,
                 model_path: Optional[str] = None,
                 prototxt_text: Optional[str] = None):
        if prototxt_text is None:
            with open(prototxt_path) as f:
                prototxt_text = f.read()
        self.net = parse_prototxt(prototxt_text)
        self.model_path = model_path
        self._blobs: Dict[str, dict] = (
            load_caffemodel(model_path) if model_path else {}
        )

    # ------------------------------------------------------------------
    def load(self):
        from bigdl_tpu.nn.graph import Graph, Input

        net = self.net
        blob_node: Dict[str, object] = {}
        blob_shape: Dict[str, tuple] = {}
        input_nodes = []

        # net-level inputs: input/input_dim or input/input_shape
        names = [v for v in net.get("input", [])]
        dims = net.get("input_dim", [])
        shapes = net.get("input_shape", [])
        for i, nm in enumerate(names):
            node = Input(nm)
            input_nodes.append(node)
            blob_node[nm] = node
            if shapes:
                d = shapes[i].get("dim", [])
            else:
                d = dims[i * 4 : i * 4 + 4]
            if len(d) >= 2:
                blob_shape[nm] = tuple(int(x) for x in d[1:])

        layers = [l for l in _net_layers(net) if not _train_only(l)]
        merged_scales = self._find_bn_scale_merges(layers)

        for l in layers:
            ltype = _first(l, "type", "")
            name = _first(l, "name", "")
            bottoms = list(l.get("bottom", []))
            tops = list(l.get("top", []))
            if ltype in ("Input", "Data", "DummyData", "MemoryData",
                         "ImageData", "HDF5Data"):
                for t in tops:
                    if t in blob_node:
                        continue
                    node = Input(t)
                    input_nodes.append(node)
                    blob_node[t] = node
                    shp = _first(l, "input_param")
                    if shp:
                        d = _first(shp, "shape")
                        if d:
                            dd = d.get("dim", [])
                            if len(dd) >= 2:
                                blob_shape[t] = tuple(int(x) for x in dd[1:])
                continue
            if ltype in ("Accuracy", "Silence", "ArgMax"):
                continue
            if ltype in ("SoftmaxWithLoss", "EuclideanLoss",
                         "SigmoidCrossEntropyLoss", "HingeLoss"):
                # inference graph: loss becomes its activation (BigDL
                # converts SoftmaxWithLoss bottoms[0] -> Softmax)
                bottoms = bottoms[:1]
                ltype = {"SoftmaxWithLoss": "Softmax"}.get(ltype)
                if ltype is None:
                    continue
            if name in merged_scales:
                # Scale folded into the preceding BatchNorm
                src = bottoms[0]
                for t in tops:
                    blob_node[t] = blob_node[src]
                    blob_shape[t] = blob_shape.get(src)
                continue
            if ltype == "Split":
                for t in tops:
                    blob_node[t] = blob_node[bottoms[0]]
                    blob_shape[t] = blob_shape.get(bottoms[0])
                continue

            in_shapes = [blob_shape.get(b) for b in bottoms]
            module, out_shape = self._convert_layer(
                l, ltype, name, in_shapes, merged_scales
            )
            if module is None:
                continue
            try:
                prev = [blob_node[b] for b in bottoms]
            except KeyError as e:
                raise CaffeConversionException(
                    f"layer {name}: unknown bottom blob {e}"
                )
            node = module(*prev)
            for t in tops:
                blob_node[t] = node
                blob_shape[t] = out_shape

        # output blobs: produced by a *converted* layer and consumed by no
        # converted layer (skipped Accuracy/Silence layers must not count
        # as consumers, or the real output would vanish)
        skip_types = ("Accuracy", "Silence", "ArgMax", "Input", "Data",
                      "DummyData", "MemoryData", "ImageData", "HDF5Data")
        produced = set()
        consumed = set()
        for l in layers:
            if _first(l, "type", "") in skip_types:
                continue
            tops = l.get("top", [])
            bottoms = l.get("bottom", [])
            produced.update(tops)
            # in-place layers (top == bottom) must not self-consume
            consumed.update(b for b in bottoms if b not in tops)
        outputs = [blob_node[t] for t in blob_node
                   if t in produced and t not in consumed]
        if not outputs:
            raise CaffeConversionException("no output blobs found")
        graph = Graph(input_nodes, outputs)
        if _first(self.net, "name"):
            graph.set_name(_first(self.net, "name"))
        return graph

    # ------------------------------------------------------------------
    def _find_bn_scale_merges(self, layers) -> Dict[str, str]:
        """Scale layers that directly consume a BatchNorm top get folded
        into the BN (the standard caffe BN+Scale idiom)."""
        bn_tops = {}
        for l in layers:
            if _first(l, "type") == "BatchNorm":
                for t in l.get("top", []):
                    bn_tops[t] = _first(l, "name")
        merges = {}
        for l in layers:
            if _first(l, "type") == "Scale":
                b = l.get("bottom", [])
                if len(b) == 1 and b[0] in bn_tops:
                    merges[_first(l, "name")] = bn_tops[b[0]]
        return merges

    def _layer_blobs(self, name: str) -> List[np.ndarray]:
        entry = self._blobs.get(name)
        return entry["blobs"] if entry else []

    # ------------------------------------------------------------------
    def _convert_layer(self, l, ltype, name, in_shapes, merged_scales):
        from bigdl_tpu.nn import layers as L
        from bigdl_tpu.nn import table_ops as T

        jset = _to_jax
        shape = in_shapes[0] if in_shapes else None
        blobs = self._layer_blobs(name)

        if ltype in ("Convolution", "Deconvolution"):
            p = _first(l, "convolution_param", {})
            n_out = _first(p, "num_output")
            kh, kw = _kern2(p, "kernel")
            sh, sw = _kern2(p, "stride")
            sh, sw = sh or 1, sw or 1
            ph, pw = _kern2(p, "pad")
            ph, pw = ph or 0, pw or 0
            group = _first(p, "group", 1)
            dil = _first(p, "dilation", 1)
            bias = bool(_first(p, "bias_term", True))
            if blobs:
                w = blobs[0]
                c_in = w.shape[1] * group if ltype == "Convolution" else w.shape[0]
            elif shape:
                c_in = shape[0]
            else:
                raise CaffeConversionException(
                    f"{name}: cannot infer input channels (no blobs, no shape)"
                )
            if ltype == "Convolution":
                if dil and dil > 1:
                    mod = L.SpatialDilatedConvolution(
                        c_in, n_out, kw, kh, sw, sh, pw, ph,
                        dilation_w=dil, dilation_h=dil,
                    ) if "dilation_w" in _sig(L.SpatialDilatedConvolution) else \
                        L.SpatialDilatedConvolution(c_in, n_out, kw, kh, sw, sh, pw, ph, dil, dil)
                else:
                    mod = L.SpatialConvolution(
                        c_in, n_out, kw, kh, sw, sh, pw, ph, group,
                        with_bias=bias,
                    )
                if blobs:
                    mod.weight = jset(blobs[0].reshape(mod.weight.shape))
                    if bias and len(blobs) > 1:
                        mod.bias = jset(blobs[1].reshape(mod.bias.shape))
                out = None
                if shape:
                    out = (
                        n_out,
                        _conv_out(shape[1], kh, ph, sh, dil or 1),
                        _conv_out(shape[2], kw, pw, sw, dil or 1),
                    )
                return mod, out
            else:  # Deconvolution
                mod = L.SpatialFullConvolution(
                    c_in, n_out, kw, kh, sw, sh, pw, ph,
                )
                if blobs:
                    # caffe deconv blob layout: (in, out/group, kh, kw)
                    w = blobs[0].reshape(c_in, n_out, kh, kw).transpose(1, 0, 2, 3)
                    mod.weight = jset(np.ascontiguousarray(w).reshape(mod.weight.shape))
                    if len(blobs) > 1:
                        mod.bias = jset(blobs[1].reshape(mod.bias.shape))
                out = None
                if shape:
                    out = (
                        n_out,
                        (shape[1] - 1) * sh - 2 * ph + kh,
                        (shape[2] - 1) * sw - 2 * pw + kw,
                    )
                return mod, out

        if ltype == "InnerProduct":
            p = _first(l, "inner_product_param", {})
            n_out = _first(p, "num_output")
            bias = bool(_first(p, "bias_term", True))
            if blobs:
                in_features = blobs[0].shape[-1] if blobs[0].ndim > 1 else (
                    blobs[0].size // n_out
                )
            elif shape:
                in_features = int(np.prod(shape))
            else:
                raise CaffeConversionException(
                    f"{name}: cannot size InnerProduct (no blobs, no shape)"
                )
            mod = L.Linear(in_features, n_out, with_bias=bias)
            if blobs:
                mod.weight = jset(blobs[0].reshape(mod.weight.shape))
                if bias and len(blobs) > 1:
                    mod.bias = jset(blobs[1].reshape(mod.bias.shape))
            # caffe IP implicitly flattens from axis 1
            if shape and len(shape) > 1:
                from bigdl_tpu.nn.module import Sequential

                mod = Sequential().add(L.Reshape([in_features])).add(mod)
            return mod, (n_out,)

        if ltype == "Pooling":
            p = _first(l, "pooling_param", {})
            pool = _first(p, "pool", "MAX")
            kh, kw = _kern2(p, "kernel")
            sh, sw = _kern2(p, "stride")
            sh, sw = sh or 1, sw or 1
            ph, pw = _kern2(p, "pad")
            ph, pw = ph or 0, pw or 0
            glob = bool(_first(p, "global_pooling", False))
            if glob:
                if shape is None:
                    raise CaffeConversionException(
                        "global pooling needs a known input shape"
                    )
                kh, kw = shape[1], shape[2]
                sh = sw = 1
                ph = pw = 0
            if pool in ("MAX", 0):
                mod = L.SpatialMaxPooling(kw, kh, sw, sh, pw, ph, ceil_mode=True)
            else:
                mod = L.SpatialAveragePooling(
                    kw, kh, sw, sh, pw, ph, ceil_mode=True
                )
            out = None
            if shape:
                out = (
                    shape[0],
                    1 if glob else _pool_out(shape[1], kh, ph, sh),
                    1 if glob else _pool_out(shape[2], kw, pw, sw),
                )
            return mod, out

        if ltype == "ReLU":
            p = _first(l, "relu_param", {})
            slope = _first(p, "negative_slope", 0.0)
            return (L.LeakyReLU(slope) if slope else L.ReLU()), shape
        if ltype == "TanH":
            return L.Tanh(), shape
        if ltype == "Sigmoid":
            return L.Sigmoid(), shape
        if ltype == "AbsVal":
            return L.Abs(), shape
        if ltype == "BNLL":
            return L.SoftPlus(), shape
        if ltype == "ELU":
            p = _first(l, "elu_param", {})
            return L.ELU(_first(p, "alpha", 1.0)), shape
        if ltype == "PReLU":
            mod = L.PReLU(n_output_plane=shape[0] if shape else 1) if \
                "n_output_plane" in _sig(L.PReLU) else L.PReLU()
            if blobs:
                try:
                    mod.weight = _to_jax(blobs[0].reshape(mod.weight.shape))
                except Exception:
                    pass
            return mod, shape
        if ltype == "Power":
            p = _first(l, "power_param", {})
            return (
                L.Power(
                    _first(p, "power", 1.0),
                    _first(p, "scale", 1.0),
                    _first(p, "shift", 0.0),
                ),
                shape,
            )
        if ltype == "Exp":
            return L.Exp(), shape
        if ltype == "Log":
            return L.Log(), shape
        if ltype == "Softmax":
            return L.SoftMax(), shape
        if ltype == "Dropout":
            p = _first(l, "dropout_param", {})
            return L.Dropout(_first(p, "dropout_ratio", 0.5)), shape
        if ltype == "LRN":
            p = _first(l, "lrn_param", {})
            return (
                L.SpatialCrossMapLRN(
                    _first(p, "local_size", 5),
                    _first(p, "alpha", 1.0),
                    _first(p, "beta", 0.75),
                    _first(p, "k", 1.0),
                ),
                shape,
            )
        if ltype == "Flatten":
            if shape:
                n = int(np.prod(shape))
                return L.Reshape([n]), (n,)
            return L.Reshape([-1]), None
        if ltype == "Reshape":
            p = _first(l, "reshape_param", {})
            sh = _first(p, "shape", {})
            dims = [int(d) for d in sh.get("dim", [])]
            body = [d for d in dims if d != 0]
            if dims and dims[0] == 0:
                pass  # keep batch axis: Reshape is batch-mode by default
            out = tuple(d for d in body) if body and -1 not in body else None
            return L.Reshape([d for d in (body or [-1])]), out
        if ltype == "Concat":
            p = _first(l, "concat_param", {})
            axis = _first(p, "axis", _first(p, "concat_dim", 1))
            # caffe axis counts the batch dim (axis 1 == channels); our
            # JoinTable dimension is 1-based over the full tensor
            mod = T.JoinTable(dimension=int(axis) + 1, n_input_dims=-1)
            out = None
            if all(s is not None for s in in_shapes) and in_shapes:
                ax = int(axis) - 1  # axis 1 == first feature dim
                dims = list(in_shapes[0])
                dims[ax] = sum(s[ax] for s in in_shapes)
                out = tuple(dims)
            return mod, out
        if ltype == "Eltwise":
            p = _first(l, "eltwise_param", {})
            op = _first(p, "operation", "SUM")
            if op in ("SUM", 1):
                mod = T.CAddTable()
            elif op in ("PROD", 0):
                mod = T.CMulTable()
            elif op in ("MAX", 2):
                mod = T.CMaxTable()
            else:
                raise CaffeConversionException(f"Eltwise op {op} unsupported")
            return mod, shape
        if ltype == "BatchNorm":
            p = _first(l, "batch_norm_param", {})
            eps = _first(p, "eps", 1e-5)
            c = shape[0] if shape else (blobs[0].size if blobs else None)
            if c is None:
                raise CaffeConversionException(f"{name}: BatchNorm needs shape")
            # is a Scale folded onto this BN?
            scale_name = None
            for sname, bnname in merged_scales.items():
                if bnname == name:
                    scale_name = sname
            mod = L.SpatialBatchNormalization(
                int(c), eps=eps, affine=scale_name is not None
            )
            if blobs:
                sf = float(blobs[2].reshape(-1)[0]) if len(blobs) > 2 else 1.0
                sf = 1.0 / sf if sf != 0 else 0.0
                mod.running_mean = _to_jax(blobs[0].reshape(-1) * sf)
                mod.running_var = _to_jax(blobs[1].reshape(-1) * sf)
            if scale_name is not None:
                sblobs = self._layer_blobs(scale_name)
                if sblobs:
                    mod.weight = _to_jax(sblobs[0].reshape(-1))
                    if len(sblobs) > 1:
                        mod.bias = _to_jax(sblobs[1].reshape(-1))
            # caffe-style BN in a loaded net runs with global stats
            mod.evaluate()
            return mod, shape
        if ltype == "Scale":
            p = _first(l, "scale_param", {})
            c = shape[0] if shape else (blobs[0].size if blobs else 1)
            size = (int(c),) + (1,) * (len(shape) - 1 if shape else 2)
            mod = L.Scale(size)
            if blobs:
                mod.weight = _to_jax(blobs[0].reshape(size))
                if len(blobs) > 1 and bool(_first(p, "bias_term", True)):
                    mod.bias = _to_jax(blobs[1].reshape(size))
            return mod, shape
        if ltype == "Slice":
            raise CaffeConversionException(
                "Slice layers are not supported (multi-output modules)"
            )
        raise CaffeConversionException(f"unsupported caffe layer type {ltype}")


def _sig(cls):
    import inspect

    return inspect.signature(cls.__init__).parameters


def _to_jax(a: np.ndarray):
    import jax.numpy as jnp

    return jnp.asarray(np.ascontiguousarray(a), dtype=jnp.float32)


# ==========================================================================
# persister
# ==========================================================================


class CaffePersister:
    """Reference: «bigdl»/utils/caffe/CaffePersister.scala — writes a
    prototxt + caffemodel for nets made of convertible layers."""

    @staticmethod
    def save(graph, prototxt_path: str, model_path: str,
             input_shape: Optional[tuple] = None):
        net_txt, net_bin = _export(graph, input_shape)
        with open(prototxt_path, "w") as f:
            f.write(net_txt)
        with open(model_path, "wb") as f:
            f.write(net_bin)


def _export(graph, input_shape) -> Tuple[str, bytes]:
    from bigdl_tpu.nn import layers as L
    from bigdl_tpu.nn import table_ops as T
    from bigdl_tpu.nn.graph import Graph

    if not isinstance(graph, Graph):
        graph = graph.to_graph() if hasattr(graph, "to_graph") else None
        if graph is None:
            raise CaffeConversionException("CaffePersister needs a Graph")

    txt: dict = {"name": [graph._name or "bigdl_tpu_net"]}
    txt_layers = []
    net = _WireWriter()
    counter = [0]

    def blob_of(node):
        return f"blob{node.id}"

    # net inputs
    # input_shape: one (C,H,W)-style tuple shared by all inputs, or a
    # list with one entry per input
    shapes = None
    if input_shape is not None:
        if isinstance(input_shape, list):
            shapes = input_shape
        else:
            shapes = [input_shape] * len(graph.input_nodes)
    for i, node in enumerate(graph.input_nodes):
        txt.setdefault("input", []).append(blob_of(node))
        if shapes is not None:
            shp = {"dim": [1] + list(shapes[i])}
            txt.setdefault("input_shape", []).append(shp)

    order = graph.topo_order() if hasattr(graph, "topo_order") else None
    if order is None:
        raise CaffeConversionException("Graph.topo_order() missing")

    for node in order:
        m = node.module
        if node in graph.input_nodes or type(m).__name__ == "_InputModule":
            continue
        counter[0] += 1
        lname = m._name or f"layer{counter[0]}"
        bottoms = [blob_of(p) for p in node.prev_nodes]
        entry = {"name": [lname], "bottom": bottoms, "top": [blob_of(node)]}
        blobs: List[np.ndarray] = []

        if isinstance(m, L.SpatialConvolution):
            entry["type"] = ["Convolution"]
            cp = {
                "num_output": [m.n_output_plane],
                "kernel_h": [m.kernel_h], "kernel_w": [m.kernel_w],
                "stride_h": [m.stride_h], "stride_w": [m.stride_w],
                "pad_h": [m.pad_h], "pad_w": [m.pad_w],
                "group": [m.n_group], "bias_term": [m.bias is not None],
            }
            if isinstance(m, L.SpatialDilatedConvolution):
                dh = getattr(m, "dilation_h", 1)
                dw = getattr(m, "dilation_w", 1)
                if dh != dw:
                    raise CaffeConversionException(
                        "caffe dilation is isotropic; dilation_h != dilation_w"
                    )
                cp["dilation"] = [dh]
            entry["convolution_param"] = [cp]
            blobs.append(np.asarray(m.weight))
            if m.bias is not None:
                blobs.append(np.asarray(m.bias))
        elif isinstance(m, L.Linear):
            entry["type"] = ["InnerProduct"]
            entry["inner_product_param"] = [{
                "num_output": [m.output_size],
                "bias_term": [m.bias is not None],
            }]
            blobs.append(np.asarray(m.weight))
            if m.bias is not None:
                blobs.append(np.asarray(m.bias))
        elif isinstance(m, L.SpatialMaxPooling):
            entry["type"] = ["Pooling"]
            entry["pooling_param"] = [{
                "pool": ["MAX"], "kernel_h": [m.kh], "kernel_w": [m.kw],
                "stride_h": [m.dh], "stride_w": [m.dw],
                "pad_h": [m.pad_h], "pad_w": [m.pad_w],
            }]
        elif isinstance(m, L.SpatialAveragePooling):
            entry["type"] = ["Pooling"]
            entry["pooling_param"] = [{
                "pool": ["AVE"], "kernel_h": [m.kh], "kernel_w": [m.kw],
                "stride_h": [m.dh], "stride_w": [m.dw],
                "pad_h": [m.pad_h], "pad_w": [m.pad_w],
            }]
        elif isinstance(m, L.ReLU):
            entry["type"] = ["ReLU"]
        elif isinstance(m, L.LeakyReLU):
            entry["type"] = ["ReLU"]
            entry["relu_param"] = [{"negative_slope": [m.negval]}]
        elif isinstance(m, L.Tanh):
            entry["type"] = ["TanH"]
        elif isinstance(m, L.Sigmoid):
            entry["type"] = ["Sigmoid"]
        elif isinstance(m, (L.SoftMax, L.LogSoftMax)):
            entry["type"] = ["Softmax"]
        elif isinstance(m, L.Dropout):
            entry["type"] = ["Dropout"]
            entry["dropout_param"] = [{"dropout_ratio": [m.p]}]
        elif isinstance(m, L.SpatialCrossMapLRN):
            entry["type"] = ["LRN"]
            entry["lrn_param"] = [{
                "local_size": [m.size], "alpha": [m.alpha],
                "beta": [m.beta], "k": [m.k],
            }]
        elif isinstance(m, L.SpatialBatchNormalization):
            entry["type"] = ["BatchNorm"]
            entry["batch_norm_param"] = [{"eps": [m.eps]}]
            blobs.append(np.asarray(m.running_mean))
            blobs.append(np.asarray(m.running_var))
            blobs.append(np.asarray([1.0], dtype=np.float32))
            # affine part becomes a Scale layer in caffe; fold emitted next
        elif isinstance(m, L.Reshape):
            entry["type"] = ["Flatten"] if len(m.size) == 1 else ["Reshape"]
            if entry["type"] == ["Reshape"]:
                entry["reshape_param"] = [
                    {"shape": [{"dim": [0] + [int(d) for d in m.size]}]}
                ]
        elif isinstance(m, T.JoinTable):
            entry["type"] = ["Concat"]
            entry["concat_param"] = [{"axis": [m.dimension - 1]}]
        elif isinstance(m, T.CAddTable):
            entry["type"] = ["Eltwise"]
            entry["eltwise_param"] = [{"operation": ["SUM"]}]
        elif isinstance(m, T.CMulTable):
            entry["type"] = ["Eltwise"]
            entry["eltwise_param"] = [{"operation": ["PROD"]}]
        elif isinstance(m, T.CMaxTable):
            entry["type"] = ["Eltwise"]
            entry["eltwise_param"] = [{"operation": ["MAX"]}]
        else:
            raise CaffeConversionException(
                f"CaffePersister: unsupported layer {type(m).__name__}"
            )

        txt_layers.append(entry)

        lp = _WireWriter()
        lp.string(1, lname)
        lp.string(2, entry["type"][0])
        for b in bottoms:
            lp.string(3, b)
        lp.string(4, blob_of(node))
        for arr in blobs:
            lp.message(7, _array_to_blob(arr))
        net.message(100, lp)

        # BN affine -> separate Scale layer (caffe idiom)
        if isinstance(m, L.SpatialBatchNormalization) and m.weight is not None:
            counter[0] += 1
            sname = lname + "_scale"
            sentry = {
                "name": [sname], "type": ["Scale"],
                "bottom": [blob_of(node)], "top": [blob_of(node)],
                "scale_param": [{"bias_term": [m.bias is not None]}],
            }
            txt_layers.append(sentry)
            sp = _WireWriter()
            sp.string(1, sname)
            sp.string(2, "Scale")
            sp.string(3, blob_of(node))
            sp.string(4, blob_of(node))
            sp.message(7, _array_to_blob(np.asarray(m.weight)))
            if m.bias is not None:
                sp.message(7, _array_to_blob(np.asarray(m.bias)))
            net.message(100, sp)

    txt["layer"] = txt_layers
    header = _WireWriter()
    header.string(1, txt["name"][0])
    return format_prototxt(txt), header.tobytes() + net.tobytes()


# --------------------------------------------------------------------------
# module-level convenience (reference: Module.loadCaffeModel / loadCaffe)
# --------------------------------------------------------------------------


def load_caffe_model(prototxt_path: str, model_path: str):
    """Reference: ``Module.loadCaffeModel(defPath, modelPath)``."""
    return CaffeLoader(prototxt_path, model_path).load()


def load_caffe_weights(model, model_path: str, match_all: bool = True):
    """Reference: ``Module.loadCaffe(model, defPath, modelPath)`` — copy
    weights from a caffemodel into an existing model by layer name."""
    blobs = load_caffemodel(model_path)
    matched = 0
    for m in _iter_modules(model):
        nm = m._name
        if nm and nm in blobs:
            arrs = blobs[nm]["blobs"]
            if not arrs:
                continue
            if getattr(m, "weight", None) is not None:
                m.weight = _to_jax(arrs[0].reshape(np.asarray(m.weight).shape))
            if len(arrs) > 1 and getattr(m, "bias", None) is not None:
                m.bias = _to_jax(arrs[1].reshape(np.asarray(m.bias).shape))
            matched += 1
    if match_all and not matched:
        raise CaffeConversionException("no layers matched by name")
    return model


def _iter_modules(m):
    yield m
    for child in getattr(m, "modules", []):
        yield from _iter_modules(child)
