"""Tracing / profiling — jax.profiler + the reference's phase timers.

SURVEY.md §5 "Tracing / profiling": the reference has per-phase wall
timers in DistriOptimizer aggregated via Metrics ("computing time
average / get weights average / …") plus throughput logging; the TPU
rebuild keeps those timer names (optim/metrics.py) and adds real device
traces via ``jax.profiler`` — viewable in TensorBoard or Perfetto.

Usage:

    from bigdl_tpu.utils.profiler import trace, annotate

    with trace("/tmp/tb"):               # device + host trace
        optimizer.optimize()

    with annotate("my-phase"):           # named region inside a trace
        ...

Env hook: ``BIGDL_PROFILE=/dir`` makes the optimizers trace their first
20 iterations automatically (compile excluded).
"""

from __future__ import annotations

import contextlib
import os
from typing import Optional

PROFILE_ENV = "BIGDL_PROFILE"
PROFILE_STEPS = 20


@contextlib.contextmanager
def trace(log_dir: str, create_perfetto_trace: bool = False):
    """Capture a jax.profiler trace into ``log_dir``."""
    import jax

    jax.profiler.start_trace(log_dir,
                             create_perfetto_trace=create_perfetto_trace)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


def annotate(name: str):
    """Named region (shows up on the trace timeline); usable as context
    manager or decorator, free when no trace is active."""
    import jax

    return jax.profiler.TraceAnnotation(name)


class StepProfiler:
    """Optimizer hook: traces steps [skip, skip+steps) of a run when
    ``BIGDL_PROFILE`` is set (skip=1 excludes the compile step)."""

    def __init__(self, log_dir: Optional[str] = None, skip: int = 1,
                 steps: int = PROFILE_STEPS):
        from bigdl_tpu.config import config, refresh_from_env

        refresh_from_env()
        self.log_dir = log_dir or config.profile_dir
        self.skip = skip
        self.steps = steps
        self._n = 0
        self._active = False

    @property
    def enabled(self) -> bool:
        return self.log_dir is not None

    def step(self):
        """Call once per optimizer iteration."""
        if not self.enabled:
            return
        import jax

        if self._n == self.skip:
            jax.profiler.start_trace(self.log_dir)
            self._active = True
        elif self._n == self.skip + self.steps and self._active:
            jax.profiler.stop_trace()
            self._active = False
        self._n += 1

    def stop(self):
        if self._active:
            import jax

            jax.profiler.stop_trace()
            self._active = False
