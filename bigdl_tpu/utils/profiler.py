"""Tracing / profiling — jax.profiler + the reference's phase timers.

SURVEY.md §5 "Tracing / profiling": the reference has per-phase wall
timers in DistriOptimizer aggregated via Metrics ("computing time
average / get weights average / …") plus throughput logging; the TPU
rebuild keeps those timer names (optim/metrics.py) and adds real device
traces via ``jax.profiler`` — viewable in TensorBoard or Perfetto.

Usage:

    from bigdl_tpu.utils.profiler import trace, annotate

    with trace("/tmp/tb"):               # device + host trace
        optimizer.optimize()

    with annotate("my-phase"):           # named region inside a trace
        ...

Env hook: ``BIGDL_PROFILE=/dir`` makes the optimizers trace their first
20 iterations automatically (compile excluded).
"""

from __future__ import annotations

import contextlib
import os
from typing import Optional

PROFILE_ENV = "BIGDL_PROFILE"
PROFILE_STEPS = 20


@contextlib.contextmanager
def trace(log_dir: str, create_perfetto_trace: bool = False):
    """Capture a jax.profiler trace into ``log_dir``."""
    import jax

    jax.profiler.start_trace(log_dir,
                             create_perfetto_trace=create_perfetto_trace)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


class _AnnotatedRegion:
    """One region, two sinks: the ``jax.profiler`` TraceAnnotation (the
    device/XLA timeline) and an obs tracer span (the Perfetto/JSONL
    export) open and close together, under the SAME name — one code
    path, no duplicate timers drifting apart."""

    def __init__(self, name: str, attrs: dict):
        self.name = name
        self.attrs = attrs
        self._ta = None
        self._span = None

    def __enter__(self):
        import jax

        from bigdl_tpu import obs

        tracer = obs.get_tracer()
        if tracer.enabled:
            self._span = tracer.span(self.name, **self.attrs)
            self._span.__enter__()
        self._ta = jax.profiler.TraceAnnotation(self.name)
        self._ta.__enter__()
        return self

    def __exit__(self, *exc):
        self._ta.__exit__(*exc)
        if self._span is not None:
            self._span.__exit__(*exc)
        return False

    def __call__(self, fn):
        # decorator form, like TraceAnnotation
        import functools

        @functools.wraps(fn)
        def wrapped(*args, **kwargs):
            with _AnnotatedRegion(self.name, self.attrs):
                return fn(*args, **kwargs)

        return wrapped


def annotate(name: str, **attrs):
    """Named region (shows up on the trace timeline); usable as context
    manager or decorator, free when no trace is active.  The region
    feeds BOTH ``jax.profiler`` traces and the obs span tracer (when
    ``BIGDL_TRACE_DIR`` is set) under one name, so Perfetto span
    exports and device profiles line up."""
    return _AnnotatedRegion(name, attrs)


class StepProfiler:
    """Optimizer hook: traces steps [skip, skip+steps) of a run when
    ``BIGDL_PROFILE`` is set (skip=1 excludes the compile step)."""

    def __init__(self, log_dir: Optional[str] = None, skip: int = 1,
                 steps: int = PROFILE_STEPS):
        from bigdl_tpu.config import config, refresh_from_env

        refresh_from_env()
        self.log_dir = log_dir or config.profile_dir
        self.skip = skip
        self.steps = steps
        self._n = 0
        self._active = False

    @property
    def enabled(self) -> bool:
        return self.log_dir is not None

    def step(self):
        """Call once per optimizer iteration."""
        if not self.enabled:
            return
        import jax

        if self._n == self.skip:
            jax.profiler.start_trace(self.log_dir)
            self._active = True
        elif self._n == self.skip + self.steps and self._active:
            jax.profiler.stop_trace()
            self._active = False
        self._n += 1

    def stop(self):
        if self._active:
            import jax

            jax.profiler.stop_trace()
            self._active = False
