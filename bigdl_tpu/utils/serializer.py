"""Module & checkpoint persistence.

Rebuild of «bigdl»/utils/serializer/ (ModuleSerializer / ModuleLoader /
ModulePersister — SURVEY.md §2.1) and the OptimMethod.save/load checkpoint
path (§5 "Checkpoint / resume").

The reference serializes module graphs to protobuf (bigdl.proto) with
per-layer converters.  The rebuild uses a self-describing JSON spec tree
(class name + captured constructor config + children/topology) plus an
``.npz`` of parameter and state leaves in deterministic pytree order —
same logical contents (architecture + weights + optimizer state + step
counters), no schema compiler needed.  Every layer's constructor captures
its config in ``self._config``, which plays the role of the reference's
per-layer ``ModuleSerializable`` converter.
"""

from __future__ import annotations

import hashlib
import io
import json
import logging
import os
import time
from typing import Dict

import numpy as np

log = logging.getLogger("bigdl_tpu.serializer")


class CheckpointIntegrityError(RuntimeError):
    """No intact checkpoint could be found/loaded from a directory."""

from bigdl_tpu.nn.module import AbstractModule, Container, Sequential
from bigdl_tpu.nn.graph import Graph, Node, _InputModule
from bigdl_tpu.obs import names


# ---------------------------------------------------------------- registry
_REGISTRY: Dict[str, type] = {}


def _build_registry(rescan: bool = False):
    if _REGISTRY and not rescan:
        return _REGISTRY
    import bigdl_tpu.nn as nn_pkg
    import bigdl_tpu.models as models_pkg  # registers model-zoo modules
    import bigdl_tpu.nn.module as m_mod
    import bigdl_tpu.nn.layers as l_mod
    import bigdl_tpu.nn.table_ops as t_mod
    import bigdl_tpu.nn.recurrent as r_mod
    import bigdl_tpu.nn.graph as g_mod

    def scan(cls):
        # first registration wins on rescan: explicit register_module
        # overrides must not be clobbered
        _REGISTRY.setdefault(cls.__name__, cls)
        for sub in cls.__subclasses__():
            scan(sub)

    scan(AbstractModule)
    _REGISTRY["_InputModule"] = _InputModule
    return _REGISTRY


def lookup_module_class(name: str) -> type:
    """Resolve a class name, rescanning the subclass tree once for
    classes defined after the first registry build."""
    reg = _build_registry()
    if name not in reg:
        reg = _build_registry(rescan=True)
    if name not in reg:
        raise KeyError(
            f"unknown module class {name!r}; use register_module() for "
            "custom layers"
        )
    return reg[name]


def register_module(cls):
    """Register a user-defined layer for serialization."""
    _build_registry()[cls.__name__] = cls
    return cls


# ------------------------------------------------------------ spec <-> mod
def module_to_spec(module: AbstractModule) -> dict:
    spec = {
        "class": type(module).__name__,
        "config": module.get_config(),
    }
    if module._name:
        spec["name"] = module._name
    if isinstance(module, Graph):
        nodes = []
        id_to_idx = {n.id: i for i, n in enumerate(module._topo)}
        for n in module._topo:
            nd = {
                "module": module_to_spec(n.module),
                "prev": [id_to_idx[p.id] for p in n.prev_nodes],
            }
            if n.feedback_node is not None:
                nd["feedback"] = id_to_idx[n.feedback_node.id]
            nodes.append(nd)
        spec["graph"] = {
            "nodes": nodes,
            "inputs": [id_to_idx[n.id] for n in module.input_nodes],
            "outputs": [id_to_idx[n.id] for n in module.output_nodes],
        }
        cond = getattr(module, "_condition_node", None)
        if cond is not None:
            spec["graph"]["condition"] = id_to_idx[cond.id]
    elif isinstance(module, Container):
        spec["children"] = [module_to_spec(m) for m in module.modules]
    return spec


def spec_to_module(spec: dict) -> AbstractModule:
    name = spec["class"]
    cls = lookup_module_class(name)
    if "graph" in spec:
        from bigdl_tpu.nn.graph import DynamicGraph

        g = spec["graph"]
        nodes = []
        for nd in g["nodes"]:
            mod = spec_to_module(nd["module"])
            nodes.append(Node(mod, [nodes[i] for i in nd["prev"]]))
        for nd, node in zip(g["nodes"], nodes):
            if "feedback" in nd:
                node.feedback_from(nodes[nd["feedback"]])
        inputs = [nodes[i] for i in g["inputs"]]
        outputs = [nodes[i] for i in g["outputs"]]
        if issubclass(cls, DynamicGraph):
            module = cls(
                inputs, outputs,
                condition=(nodes[g["condition"]] if "condition" in g
                           else None),
                **spec.get("config", {}),
            )
        else:
            module = Graph(inputs, outputs)
    else:
        module = cls(**spec.get("config", {}))
        if "children" in spec:
            # bypass per-container add() validation: rebuild structurally
            module.modules = []
            for child_spec in spec["children"]:
                module.modules.append(spec_to_module(child_spec))
    if "name" in spec:
        module.set_name(spec["name"])
    return module


# ------------------------------------------------------------- save / load
def save_module(module: AbstractModule, path: str):
    """Reference: Module.saveModule(path) via ModulePersister.

    ``.bigdl`` paths write the reference's protobuf interchange format
    (utils/bigdl_proto.py); anything else uses the fast native JSON+NPZ
    container."""
    if path.endswith(".bigdl"):
        from bigdl_tpu.utils.bigdl_proto import ModulePersister

        return ModulePersister.save(module, path)
    import jax

    spec = module_to_spec(module)
    arrays = _module_arrays(spec, jax.tree.leaves(module.params()),
                            jax.tree.leaves(module.state()))
    if not path.endswith(".npz"):
        path = path + ".npz"
    np.savez(path, **arrays)
    return path


def _module_arrays(spec, p_leaves, s_leaves):
    """The single npz encoding (p{i}/s{i}/__spec__) load_module reads —
    shared by save_module and write_checkpoint so the two writers can
    never drift apart."""
    arrays = {f"p{i}": np.asarray(x) for i, x in enumerate(p_leaves)}
    arrays.update({f"s{i}": np.asarray(x) for i, x in enumerate(s_leaves)})
    arrays["__spec__"] = np.frombuffer(
        json.dumps(spec).encode("utf-8"), dtype=np.uint8
    )
    return arrays


def load_module(path: str) -> AbstractModule:
    """Reference: Module.loadModule(path) via ModuleLoader.  Sniffs the
    container: zip magic = JSON+NPZ, anything else = bigdl.proto."""
    import jax
    import jax.numpy as jnp

    if not path.endswith(".npz") and os.path.exists(path + ".npz"):
        path = path + ".npz"
    with open(path, "rb") as fh:
        magic = fh.read(2)
    if magic != b"PK":  # not a zip -> protobuf interchange
        from bigdl_tpu.utils.bigdl_proto import ModuleLoader

        return ModuleLoader.load(path)
    data = np.load(path)
    spec = json.loads(bytes(data["__spec__"]).decode("utf-8"))
    module = spec_to_module(spec)
    p = module.params()
    leaves, treedef = jax.tree.flatten(p)
    new_leaves = [jnp.asarray(data[f"p{i}"]) for i in range(len(leaves))]
    module.set_params(jax.tree.unflatten(treedef, new_leaves))
    s = module.state()
    s_leaves, s_treedef = jax.tree.flatten(s)
    if s_leaves:
        new_s = [jnp.asarray(data[f"s{i}"]) for i in range(len(s_leaves))]
        module.set_state(jax.tree.unflatten(s_treedef, new_s))
    return module


# ------------------------------------------------------------- checkpoints
def snapshot_checkpoint(model, optim_method=None, extra: dict = None,
                        to_host: bool = False):
    """Synchronously capture everything a checkpoint needs — module
    spec + array snapshots.  The returned dict can be written
    later/off-thread by :func:`write_checkpoint`.

    ``to_host=False`` (sync path): model leaves are held by reference
    (the training loop's write_back already copied them out of the
    donated buffers); optimizer-state leaves are device-copied HERE
    because the live opt_state buffers are donated to (and deleted by)
    the very next train_step.  Host transfer happens later, in the
    write.

    ``to_host=True`` (the fully-async path, ISSUE 11): every leaf is
    materialized to host numpy NOW — this blocking snapshot is the
    ONLY part of an async checkpoint on the training critical path, so
    it is the only span stamped as ``checkpoint_save`` badput in the
    goodput ledger; the serialize/fsync/manifest work then runs on the
    background writer with zero device or trainer-state references.
    Duration lands in ``bigdl_checkpoint_snapshot_seconds`` either
    way."""
    from bigdl_tpu import obs

    import jax

    t_snap = time.perf_counter()
    with obs.get_tracer().span("checkpoint.snapshot",
                               to_host=bool(to_host)):
        def dev_copy(v):
            if to_host:
                return np.asarray(v)
            return v.copy() if hasattr(v, "copy") else v

        leaf = (lambda v: np.asarray(v)) if to_host else (lambda v: v)
        snap = {
            "spec": module_to_spec(model),
            "p_leaves": [leaf(v) for v in jax.tree.leaves(model.params())],
            "s_leaves": [leaf(v) for v in jax.tree.leaves(model.state())],
            "optim": None,
        }
        if optim_method is not None:
            snap["optim"] = {
                "class": type(optim_method).__name__,
                "arrays": {
                    k: dev_copy(v)
                    for k, v in optim_method.get_state_arrays(
                        materialize=False).items()
                },
                "extra": extra or {},
            }
    dt = time.perf_counter() - t_snap
    obs.get_registry().gauge(
        names.CHECKPOINT_SNAPSHOT_SECONDS,
        "Blocking snapshot span of the newest checkpoint (the only "
        "critical-path cost of an async checkpoint)").set(round(dt, 6))
    if to_host:
        # async contract: the snapshot is the only checkpoint_save
        # badput; the off-path write is traced but never charged
        step = None
        if snap["optim"] is not None:
            step = ((snap["optim"]["extra"] or {}).get("topology")
                    or {}).get("step")
        obs.get_ledger().record("checkpoint_save", t_snap, dt, step=step)
    return snap


def _fsync_dir(directory: str):
    """fsync a directory so a completed rename survives a host crash
    (no-op where directories cannot be opened, e.g. Windows)."""
    try:
        fd = os.open(directory or ".", os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    finally:
        os.close(fd)


def _atomic_savez(path: str, arrays: dict):
    """np.savez via tmp + fsync + rename so readers (retry-from-
    checkpoint) never see a torn file AND a host crash cannot leave a
    renamed-but-empty file: the data must be durable before the rename,
    and the rename itself durable via the directory fsync."""
    if not path.endswith(".npz"):
        path = path + ".npz"
    tmp = path + ".tmp.npz"
    with open(tmp, "wb") as fh:
        np.savez(fh, **arrays)
        fh.flush()
        os.fsync(fh.fileno())
    os.replace(tmp, path)
    _fsync_dir(os.path.dirname(path))
    return path


def _sha256(path: str) -> str:
    h = hashlib.sha256()
    with open(path, "rb") as fh:
        for chunk in iter(lambda: fh.read(1 << 20), b""):
            h.update(chunk)
    return h.hexdigest()


_CKPT_SUFFIXES = (".model.npz", ".optim.npz")


def write_manifest(path_prefix: str, topology: dict = None,
                   stream: dict = None) -> str:
    """Record size + sha256 of every file in the ``path_prefix``
    checkpoint pair so verify-on-load can tell torn/rotted checkpoints
    from intact ones, plus the writer's ``topology``
    (``{world_size, shard_layout, step, wire}`` — resilience/elastic.py;
    ``wire`` tags the compressed-collective config the run trained
    under, incl. whether a ``wire_ef`` error-feedback residual rides
    the ``.optim`` state arrays) so a resize-resume can inspect the
    source world without opening the npz, and — for streaming runs —
    the ``stream`` frontier (``{offset, watermark, records}`` —
    dataset/stream.py) so tooling and the autoscaling supervisor can
    read the exactly-once commit point the same cheap way.
    Written atomically AFTER the pair is durable — a crash between pair
    and manifest degrades to the legacy no-manifest check, never to a
    manifest blessing garbage."""
    files = {}
    for suffix in _CKPT_SUFFIXES:
        p = path_prefix + suffix
        if os.path.exists(p):
            files[os.path.basename(p)] = {
                "size": os.path.getsize(p),
                "sha256": _sha256(p),
            }
    manifest_path = path_prefix + ".manifest.json"
    doc = {"format": 1, "files": files}
    if topology:
        doc["topology"] = topology
    if stream:
        doc["stream"] = stream
    tmp = manifest_path + ".tmp"
    with open(tmp, "w", encoding="utf-8") as fh:
        json.dump(doc, fh)
        fh.flush()
        os.fsync(fh.fileno())
    os.replace(tmp, manifest_path)
    _fsync_dir(os.path.dirname(manifest_path))
    return manifest_path


def read_checkpoint_topology(path_prefix: str) -> dict:
    """The ``{world_size, shard_layout, step, wire}`` metadata a
    checkpoint was written under — from the manifest (no npz open),
    falling back to the ``.optim`` meta for manifest-less pairs.
    ``{}`` when the checkpoint predates topology tagging; ``wire``
    absent when it predates the compressed-collective tagging."""
    manifest_path = path_prefix + ".manifest.json"
    try:
        with open(manifest_path, "r", encoding="utf-8") as fh:
            topo = json.load(fh).get("topology")
            if topo:
                return topo
    except (OSError, ValueError):
        pass
    optim_path = path_prefix + ".optim.npz"
    try:
        with np.load(optim_path) as data:
            meta = json.loads(bytes(data["__meta__"]).decode("utf-8"))
        return (meta.get("extra") or {}).get("topology") or {}
    except Exception:  # noqa: BLE001 — absent/torn pair = no metadata
        return {}


def read_checkpoint_stream(path_prefix: str) -> dict:
    """The streaming frontier a checkpoint was written at
    (``{offset, watermark, records}``) — from the manifest (no npz
    open), falling back to the ``.optim`` meta for manifest-less
    pairs; ``{}`` for non-streaming runs."""
    manifest_path = path_prefix + ".manifest.json"
    try:
        with open(manifest_path, "r", encoding="utf-8") as fh:
            stream = json.load(fh).get("stream")
            if stream:
                return stream
    except (OSError, ValueError):
        pass
    optim_path = path_prefix + ".optim.npz"
    try:
        with np.load(optim_path) as data:
            meta = json.loads(bytes(data["__meta__"]).decode("utf-8"))
        return (meta.get("extra") or {}).get("stream") or {}
    except Exception:  # noqa: BLE001 — absent/torn pair = no metadata
        return {}


def verify_checkpoint(path_prefix: str):
    """Integrity check for one checkpoint pair.  Returns ``(ok,
    reason)``.  With a manifest: every recorded file must exist with
    matching size and sha256 (a recorded-but-missing ``.optim`` pair
    fails the check).  Without one (legacy writer): the model npz must
    at least open as a zip container."""
    from bigdl_tpu import obs

    tracer = obs.get_tracer()
    with tracer.span("checkpoint.verify",
                     prefix=os.path.basename(path_prefix)):
        ok, reason = _verify_checkpoint_impl(path_prefix)
    if not ok:
        # integrity failures are first-class telemetry: the retry path
        # skipping a torn checkpoint must be visible in the trace, not
        # only in a log line
        tracer.event("resilience.checkpoint_verify_failed",
                     prefix=os.path.basename(path_prefix), reason=reason)
        obs.get_registry().counter(
            names.CHECKPOINT_VERIFY_FAILURES_TOTAL,
            "Checkpoint pairs that failed the integrity check").inc()
    return ok, reason


def _verify_checkpoint_impl(path_prefix: str):
    model_path = path_prefix + ".model.npz"
    if not os.path.exists(model_path):
        return False, "missing .model.npz"
    manifest_path = path_prefix + ".manifest.json"
    if os.path.exists(manifest_path):
        try:
            with open(manifest_path, "r", encoding="utf-8") as fh:
                manifest = json.load(fh)
            files = manifest["files"]
        except Exception as e:  # noqa: BLE001 — any unreadable manifest
            return False, f"unreadable manifest: {e}"
        directory = os.path.dirname(path_prefix)
        for name, rec in files.items():
            p = os.path.join(directory, name)
            if not os.path.exists(p):
                return False, f"missing {name}"
            if os.path.getsize(p) != rec["size"]:
                return False, (f"{name}: size {os.path.getsize(p)} != "
                               f"recorded {rec['size']}")
            if _sha256(p) != rec["sha256"]:
                return False, f"{name}: checksum mismatch"
        return True, "ok"
    # no manifest: either a legacy writer, or a kill between the pair
    # landing and the manifest rename.  A leftover tmp file for THIS
    # prefix is crash-window evidence (every completed stage removes its
    # tmp via os.replace) — treat the pair as torn and fall back rather
    # than resume without optimizer state / topology metadata.
    for leftover in (path_prefix + ".model.npz.tmp.npz",
                     path_prefix + ".optim.npz.tmp.npz",
                     manifest_path + ".tmp"):
        if os.path.exists(leftover):
            return False, (f"no manifest + leftover "
                           f"{os.path.basename(leftover)}: interrupted "
                           "checkpoint write")
    try:
        with np.load(model_path) as data:
            data.files  # zip central directory read — catches truncation
    except Exception as e:  # noqa: BLE001 — any unreadable container
        return False, f"unreadable .model.npz: {e}"
    return True, "ok (no manifest)"


def checkpoint_prefixes(directory: str):
    """Checkpoint prefixes in ``directory``, oldest first by model-file
    mtime."""
    cands = [
        f[: -len(".model.npz")]
        for f in os.listdir(directory)
        if f.endswith(".model.npz")
    ]
    cands.sort(key=lambda f: os.path.getmtime(
        os.path.join(directory, f + ".model.npz")))
    return cands


def gc_checkpoints(directory: str, keep_last: int):
    """Keep-last-K retention: delete every checkpoint pair (model +
    optim + manifest + stale tmp files) older than the newest
    ``keep_last`` prefixes.  ``keep_last <= 0`` keeps everything."""
    if keep_last <= 0:
        return []
    doomed = checkpoint_prefixes(directory)[:-keep_last]
    removed = []
    for prefix in doomed:
        for f in os.listdir(directory):
            if f in (prefix + ".manifest.json",
                     prefix + ".manifest.json.tmp") or (
                    f.startswith(prefix + ".") and ".npz" in f):
                try:
                    os.remove(os.path.join(directory, f))
                    removed.append(f)
                except OSError:
                    pass  # concurrent GC / already gone
    if removed:
        log.info("checkpoint GC: removed %d files for %d old prefixes "
                 "(keep_last=%d)", len(removed), len(doomed), keep_last)
    return removed


def write_checkpoint(snap: dict, path_prefix: str, keep_last: int = 0,
                     background: bool = False):
    """Materialize a :func:`snapshot_checkpoint` (device->host
    transfers happen HERE when the snapshot held device refs — safe on
    a background thread), write the model/optim pair atomically + its
    integrity manifest, then apply retention (``keep_last``) and any
    injected checkpoint fault.

    ``background=True`` (the async-checkpoint writer thread, ISSUE 11):
    the write no longer blocks the training step, so it is **not**
    ``checkpoint_save`` badput — it is traced as a non-badput
    ``checkpoint.write_async`` span instead, and only the blocking
    snapshot (``snapshot_checkpoint(to_host=True)``) was charged.
    ``bigdl_goodput_ratio`` then reflects wall-clock truth.  The write
    order/durability contract is identical either way: ``.optim`` →
    ``.model`` → manifest, each atomic + fsync'd."""
    from bigdl_tpu import obs

    # the span lands on the writer's own thread (the background ckpt
    # thread gets its own Chrome tid), so async writes overlapping the
    # train loop are visible as exactly that on the timeline; for the
    # SYNC path the goodput ledger stamp below makes the write a
    # checkpoint_save badput interval
    t_ckpt = time.perf_counter()
    span = "checkpoint.write_async" if background else "checkpoint.write"
    with obs.get_tracer().span(span,
                               prefix=os.path.basename(path_prefix)):
        arrays = _module_arrays(snap["spec"], snap["p_leaves"],
                                snap["s_leaves"])
        # the .optim pair lands FIRST: discovery keys on .model.npz, so
        # ordering optim -> model means any discoverable prefix already
        # has its complete optimizer state — a kill anywhere in the
        # write can leave a torn-but-listed checkpoint only inside the
        # pair->manifest window, which verify flags via tmp leftovers
        if snap["optim"] is not None:
            opt_arrays = {k: np.asarray(v)
                          for k, v in snap["optim"]["arrays"].items()}
            meta = {
                "class": snap["optim"]["class"],
                "extra": snap["optim"]["extra"],
            }
            opt_arrays["__meta__"] = np.frombuffer(
                json.dumps(meta).encode("utf-8"), dtype=np.uint8
            )
            _atomic_savez(path_prefix + ".optim", opt_arrays)
        _atomic_savez(path_prefix + ".model", arrays)
        topology = stream = None
        if snap["optim"] is not None:
            extra = snap["optim"]["extra"] or {}
            topology = extra.get("topology")
            stream = extra.get("stream")
        write_manifest(path_prefix, topology=topology, stream=stream)
        # chaos hook: post-write corruption the verify-on-load must catch
        from bigdl_tpu.resilience.faults import get_injector

        get_injector().on_checkpoint_write(path_prefix)
        if keep_last:
            gc_checkpoints(os.path.dirname(path_prefix) or ".", keep_last)
    dt = time.perf_counter() - t_ckpt
    obs.get_registry().gauge(
        names.CHECKPOINT_WRITE_SECONDS,
        "Serialize+fsync+manifest span of the newest checkpoint "
        "(off the critical path when written by the async writer)").set(
        round(dt, 6))
    if not background:
        # a synchronous write stalls the step it lands on: badput
        step = None
        if snap["optim"] is not None:
            step = ((snap["optim"]["extra"] or {}).get("topology")
                    or {}).get("step")
        obs.get_ledger().record("checkpoint_save", t_ckpt, dt, step=step)
    obs.get_registry().counter(
        names.CHECKPOINT_WRITES_TOTAL,
        "Checkpoint pairs written (model + optim + manifest)").inc()
    return path_prefix


def save_checkpoint(path_prefix: str, model, optim_method=None,
                    extra: dict = None, keep_last: int = 0):
    """Reference: Optimizer.setCheckpoint cadence saves model +
    OptimMethod (with its internal state table: epoch/neval counters) so
    resume continues Triggers correctly (SURVEY.md §5)."""
    return write_checkpoint(
        snapshot_checkpoint(model, optim_method, extra), path_prefix,
        keep_last=keep_last)


def load_checkpoint(path_prefix: str, model, optim_method=None) -> dict:
    """Load weights into ``model`` (in place) and state into
    ``optim_method``; returns the extra dict (epoch/neval)."""
    from bigdl_tpu import obs

    t_load = time.perf_counter()
    with obs.get_tracer().span("checkpoint.load",
                               prefix=os.path.basename(path_prefix)):
        extra = _load_checkpoint_impl(path_prefix, model, optim_method)
    obs.get_ledger().record("checkpoint_restore", t_load,
                            time.perf_counter() - t_load,
                            step=extra.get("neval"))
    return extra


def _load_checkpoint_impl(path_prefix, model, optim_method):
    loaded = load_module(path_prefix + ".model")
    model.set_params(loaded.params())
    model.set_state(loaded.state())
    extra = {}
    optim_path = path_prefix + ".optim.npz"
    if optim_method is not None and os.path.exists(optim_path):
        data = np.load(optim_path)
        meta = json.loads(bytes(data["__meta__"]).decode("utf-8"))
        extra = meta.get("extra", {})
        optim_method.load_state_arrays(
            {k: data[k] for k in data.files if k != "__meta__"}
        )
        # the source topology rides with the method so the next step
        # build can re-partition ZeRO state for a resized world
        # (resilience/elastic.py ensure_shard_layout)
        optim_method.loaded_topology = extra.get("topology")
    return extra


def load_latest_checkpoint(directory: str, model, optim_method=None,
                           verify: bool = True) -> dict:
    """Load the newest *intact* checkpoint_* pair from a checkpoint dir
    (reference: DistriOptimizer retry reloads the last checkpoint).

    Candidates are tried newest-first; one that is truncated, corrupt,
    or missing a manifest-recorded ``.optim`` pair is skipped with a
    warning and the next-newest is tried — a torn write of the latest
    checkpoint must cost one checkpoint interval, not the run.  Raises
    :class:`CheckpointIntegrityError` when no candidate survives."""
    cands = checkpoint_prefixes(directory)
    if not cands:
        raise FileNotFoundError(f"no checkpoints in {directory}")
    failures = []
    for name in reversed(cands):
        prefix = os.path.join(directory, name)
        if verify:
            ok, reason = verify_checkpoint(prefix)
            if not ok:
                log.warning("skipping checkpoint %s: %s", name, reason)
                failures.append(f"{name}: {reason}")
                continue
        try:
            return load_checkpoint(prefix, model, optim_method)
        except Exception as e:  # noqa: BLE001 — fall back to older pair
            if not verify:
                raise
            log.warning("failed loading checkpoint %s: %s", name, e)
            failures.append(f"{name}: load failed: {e}")
    raise CheckpointIntegrityError(
        f"no intact checkpoint in {directory}: " + "; ".join(failures))
