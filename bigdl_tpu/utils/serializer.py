"""Module & checkpoint persistence.

Rebuild of «bigdl»/utils/serializer/ (ModuleSerializer / ModuleLoader /
ModulePersister — SURVEY.md §2.1) and the OptimMethod.save/load checkpoint
path (§5 "Checkpoint / resume").

The reference serializes module graphs to protobuf (bigdl.proto) with
per-layer converters.  The rebuild uses a self-describing JSON spec tree
(class name + captured constructor config + children/topology) plus an
``.npz`` of parameter and state leaves in deterministic pytree order —
same logical contents (architecture + weights + optimizer state + step
counters), no schema compiler needed.  Every layer's constructor captures
its config in ``self._config``, which plays the role of the reference's
per-layer ``ModuleSerializable`` converter.
"""

from __future__ import annotations

import io
import json
import os
from typing import Dict

import numpy as np

from bigdl_tpu.nn.module import AbstractModule, Container, Sequential
from bigdl_tpu.nn.graph import Graph, Node, _InputModule


# ---------------------------------------------------------------- registry
_REGISTRY: Dict[str, type] = {}


def _build_registry(rescan: bool = False):
    if _REGISTRY and not rescan:
        return _REGISTRY
    import bigdl_tpu.nn as nn_pkg
    import bigdl_tpu.models as models_pkg  # registers model-zoo modules
    import bigdl_tpu.nn.module as m_mod
    import bigdl_tpu.nn.layers as l_mod
    import bigdl_tpu.nn.table_ops as t_mod
    import bigdl_tpu.nn.recurrent as r_mod
    import bigdl_tpu.nn.graph as g_mod

    def scan(cls):
        # first registration wins on rescan: explicit register_module
        # overrides must not be clobbered
        _REGISTRY.setdefault(cls.__name__, cls)
        for sub in cls.__subclasses__():
            scan(sub)

    scan(AbstractModule)
    _REGISTRY["_InputModule"] = _InputModule
    return _REGISTRY


def lookup_module_class(name: str) -> type:
    """Resolve a class name, rescanning the subclass tree once for
    classes defined after the first registry build."""
    reg = _build_registry()
    if name not in reg:
        reg = _build_registry(rescan=True)
    if name not in reg:
        raise KeyError(
            f"unknown module class {name!r}; use register_module() for "
            "custom layers"
        )
    return reg[name]


def register_module(cls):
    """Register a user-defined layer for serialization."""
    _build_registry()[cls.__name__] = cls
    return cls


# ------------------------------------------------------------ spec <-> mod
def module_to_spec(module: AbstractModule) -> dict:
    spec = {
        "class": type(module).__name__,
        "config": module.get_config(),
    }
    if module._name:
        spec["name"] = module._name
    if isinstance(module, Graph):
        nodes = []
        id_to_idx = {n.id: i for i, n in enumerate(module._topo)}
        for n in module._topo:
            nd = {
                "module": module_to_spec(n.module),
                "prev": [id_to_idx[p.id] for p in n.prev_nodes],
            }
            if n.feedback_node is not None:
                nd["feedback"] = id_to_idx[n.feedback_node.id]
            nodes.append(nd)
        spec["graph"] = {
            "nodes": nodes,
            "inputs": [id_to_idx[n.id] for n in module.input_nodes],
            "outputs": [id_to_idx[n.id] for n in module.output_nodes],
        }
        cond = getattr(module, "_condition_node", None)
        if cond is not None:
            spec["graph"]["condition"] = id_to_idx[cond.id]
    elif isinstance(module, Container):
        spec["children"] = [module_to_spec(m) for m in module.modules]
    return spec


def spec_to_module(spec: dict) -> AbstractModule:
    name = spec["class"]
    cls = lookup_module_class(name)
    if "graph" in spec:
        from bigdl_tpu.nn.graph import DynamicGraph

        g = spec["graph"]
        nodes = []
        for nd in g["nodes"]:
            mod = spec_to_module(nd["module"])
            nodes.append(Node(mod, [nodes[i] for i in nd["prev"]]))
        for nd, node in zip(g["nodes"], nodes):
            if "feedback" in nd:
                node.feedback_from(nodes[nd["feedback"]])
        inputs = [nodes[i] for i in g["inputs"]]
        outputs = [nodes[i] for i in g["outputs"]]
        if issubclass(cls, DynamicGraph):
            module = cls(
                inputs, outputs,
                condition=(nodes[g["condition"]] if "condition" in g
                           else None),
                **spec.get("config", {}),
            )
        else:
            module = Graph(inputs, outputs)
    else:
        module = cls(**spec.get("config", {}))
        if "children" in spec:
            # bypass per-container add() validation: rebuild structurally
            module.modules = []
            for child_spec in spec["children"]:
                module.modules.append(spec_to_module(child_spec))
    if "name" in spec:
        module.set_name(spec["name"])
    return module


# ------------------------------------------------------------- save / load
def save_module(module: AbstractModule, path: str):
    """Reference: Module.saveModule(path) via ModulePersister.

    ``.bigdl`` paths write the reference's protobuf interchange format
    (utils/bigdl_proto.py); anything else uses the fast native JSON+NPZ
    container."""
    if path.endswith(".bigdl"):
        from bigdl_tpu.utils.bigdl_proto import ModulePersister

        return ModulePersister.save(module, path)
    import jax

    spec = module_to_spec(module)
    arrays = _module_arrays(spec, jax.tree.leaves(module.params()),
                            jax.tree.leaves(module.state()))
    if not path.endswith(".npz"):
        path = path + ".npz"
    np.savez(path, **arrays)
    return path


def _module_arrays(spec, p_leaves, s_leaves):
    """The single npz encoding (p{i}/s{i}/__spec__) load_module reads —
    shared by save_module and write_checkpoint so the two writers can
    never drift apart."""
    arrays = {f"p{i}": np.asarray(x) for i, x in enumerate(p_leaves)}
    arrays.update({f"s{i}": np.asarray(x) for i, x in enumerate(s_leaves)})
    arrays["__spec__"] = np.frombuffer(
        json.dumps(spec).encode("utf-8"), dtype=np.uint8
    )
    return arrays


def load_module(path: str) -> AbstractModule:
    """Reference: Module.loadModule(path) via ModuleLoader.  Sniffs the
    container: zip magic = JSON+NPZ, anything else = bigdl.proto."""
    import jax
    import jax.numpy as jnp

    if not path.endswith(".npz") and os.path.exists(path + ".npz"):
        path = path + ".npz"
    with open(path, "rb") as fh:
        magic = fh.read(2)
    if magic != b"PK":  # not a zip -> protobuf interchange
        from bigdl_tpu.utils.bigdl_proto import ModuleLoader

        return ModuleLoader.load(path)
    data = np.load(path)
    spec = json.loads(bytes(data["__spec__"]).decode("utf-8"))
    module = spec_to_module(spec)
    p = module.params()
    leaves, treedef = jax.tree.flatten(p)
    new_leaves = [jnp.asarray(data[f"p{i}"]) for i in range(len(leaves))]
    module.set_params(jax.tree.unflatten(treedef, new_leaves))
    s = module.state()
    s_leaves, s_treedef = jax.tree.flatten(s)
    if s_leaves:
        new_s = [jnp.asarray(data[f"s{i}"]) for i in range(len(s_leaves))]
        module.set_state(jax.tree.unflatten(s_treedef, new_s))
    return module


# ------------------------------------------------------------- checkpoints
def snapshot_checkpoint(model, optim_method=None, extra: dict = None):
    """Synchronously capture everything a checkpoint needs — module
    spec + device-array snapshots; no host transfer happens here.  The
    returned dict can be written later/off-thread by
    :func:`write_checkpoint`.

    Model leaves are held by reference (the training loop's write_back
    already copied them out of the donated buffers); optimizer-state
    leaves are device-copied HERE because the live opt_state buffers
    are donated to (and deleted by) the very next train_step."""
    import jax

    def dev_copy(v):
        return v.copy() if hasattr(v, "copy") else v

    snap = {
        "spec": module_to_spec(model),
        "p_leaves": list(jax.tree.leaves(model.params())),
        "s_leaves": list(jax.tree.leaves(model.state())),
        "optim": None,
    }
    if optim_method is not None:
        snap["optim"] = {
            "class": type(optim_method).__name__,
            "arrays": {
                k: dev_copy(v)
                for k, v in optim_method.get_state_arrays(
                    materialize=False).items()
            },
            "extra": extra or {},
        }
    return snap


def _atomic_savez(path: str, arrays: dict):
    """np.savez via tmp + rename so readers (retry-from-checkpoint)
    never see a torn file."""
    if not path.endswith(".npz"):
        path = path + ".npz"
    tmp = path + ".tmp.npz"
    np.savez(tmp, **arrays)
    os.replace(tmp, path)
    return path


def write_checkpoint(snap: dict, path_prefix: str):
    """Materialize a :func:`snapshot_checkpoint` (device->host
    transfers happen HERE — safe on a background thread) and write the
    model/optim pair atomically."""
    arrays = _module_arrays(snap["spec"], snap["p_leaves"],
                            snap["s_leaves"])
    _atomic_savez(path_prefix + ".model", arrays)
    if snap["optim"] is not None:
        opt_arrays = {k: np.asarray(v)
                      for k, v in snap["optim"]["arrays"].items()}
        meta = {
            "class": snap["optim"]["class"],
            "extra": snap["optim"]["extra"],
        }
        opt_arrays["__meta__"] = np.frombuffer(
            json.dumps(meta).encode("utf-8"), dtype=np.uint8
        )
        _atomic_savez(path_prefix + ".optim", opt_arrays)
    return path_prefix


def save_checkpoint(path_prefix: str, model, optim_method=None, extra: dict = None):
    """Reference: Optimizer.setCheckpoint cadence saves model +
    OptimMethod (with its internal state table: epoch/neval counters) so
    resume continues Triggers correctly (SURVEY.md §5)."""
    return write_checkpoint(
        snapshot_checkpoint(model, optim_method, extra), path_prefix)


def load_checkpoint(path_prefix: str, model, optim_method=None) -> dict:
    """Load weights into ``model`` (in place) and state into
    ``optim_method``; returns the extra dict (epoch/neval)."""
    import jax
    import jax.numpy as jnp

    loaded = load_module(path_prefix + ".model")
    model.set_params(loaded.params())
    model.set_state(loaded.state())
    extra = {}
    optim_path = path_prefix + ".optim.npz"
    if optim_method is not None and os.path.exists(optim_path):
        data = np.load(optim_path)
        meta = json.loads(bytes(data["__meta__"]).decode("utf-8"))
        extra = meta.get("extra", {})
        optim_method.load_state_arrays(
            {k: data[k] for k in data.files if k != "__meta__"}
        )
    return extra


def load_latest_checkpoint(directory: str, model, optim_method=None) -> dict:
    """Find the newest checkpoint_* pair in a checkpoint dir (reference:
    DistriOptimizer retry reloads the last checkpoint)."""
    cands = [
        f[: -len(".model.npz")]
        for f in os.listdir(directory)
        if f.endswith(".model.npz")
    ]
    if not cands:
        raise FileNotFoundError(f"no checkpoints in {directory}")
    cands.sort(
        key=lambda f: os.path.getmtime(os.path.join(directory, f + ".model.npz"))
    )
    return load_checkpoint(os.path.join(directory, cands[-1]), model, optim_method)
