"""LoggerFilter — console/file log routing.

Rebuild of «bigdl»/utils/LoggerFilter.scala (SURVEY.md §5 "Metrics /
logging": redirects chatty third-party loggers to a file, keeps
bigdl_tpu INFO on the console).
"""

from __future__ import annotations

import logging
import os
from typing import Optional, Sequence

_DEFAULT_CHATTY = ("jax", "absl", "orbax", "etils", "tensorflow")


def redirect_spark_info_logs(
    log_path: Optional[str] = None,
    chatty: Sequence[str] = _DEFAULT_CHATTY,
    keep: Sequence[str] = ("bigdl_tpu",),
):
    """Reference: ``LoggerFilter.redirectSparkInfoLogs`` — chatty
    libraries log to ``bigdl.log`` at INFO, only warnings reach the
    console; ``bigdl_tpu.*`` stays on the console at INFO.  Honors the
    reference's system-property overrides via env:
    ``BIGDL_DISABLE_LOGGER=1`` skips everything, ``BIGDL_LOG_PATH``
    overrides the file location.

    The default file lives under the system temp dir, NOT the cwd (the
    reference wrote to cwd; that leaked ``bigdl.log`` into repo roots —
    VERDICT r3 weak #4).  Pass ``log_path`` or set ``BIGDL_LOG_PATH``
    for a durable location."""
    import getpass
    import tempfile

    from bigdl_tpu.config import config, refresh_from_env

    refresh_from_env()
    if config.disable_logger:
        return
    if not (log_path or config.log_path):
        # per-user filename: a fixed name in the shared temp dir would
        # collide across users (PermissionError) and invite symlinks
        try:
            user = getpass.getuser()
        except (KeyError, OSError):
            user = str(os.getuid()) if hasattr(os, "getuid") else "user"
        log_path = os.path.join(tempfile.gettempdir(), f"bigdl-{user}.log")
    else:
        log_path = log_path or config.log_path
    _MARK = "_bigdl_tpu_logger_filter"
    fmt = logging.Formatter("%(asctime)s %(levelname)s %(name)s: %(message)s")
    file_handler = logging.FileHandler(log_path)
    file_handler.setLevel(logging.INFO)
    file_handler.setFormatter(fmt)
    setattr(file_handler, _MARK, True)
    stale = []  # handlers from a previous call; close once fully detached
    for name in chatty:
        lg = logging.getLogger(name)
        # idempotent: drop handlers installed by a previous call
        for h in list(lg.handlers):
            if getattr(h, _MARK, False):
                lg.removeHandler(h)
                stale.append(h)
        lg.addHandler(file_handler)
        lg.setLevel(logging.INFO)
        lg.propagate = False
        console = logging.StreamHandler()
        console.setLevel(logging.WARNING)
        console.setFormatter(fmt)
        setattr(console, _MARK, True)
        lg.addHandler(console)
    # close only handlers no longer attached to ANY logger (a previous
    # call may have installed them on loggers outside today's chatty list)
    still_attached = set()
    root = logging.Logger.manager.root
    for lg in [root] + list(logging.Logger.manager.loggerDict.values()):
        for h in getattr(lg, "handlers", ()):
            still_attached.add(id(h))
    for h in {id(h): h for h in stale}.values():
        if id(h) not in still_attached:
            h.close()
    for name in keep:
        lg = logging.getLogger(name)
        lg.setLevel(logging.INFO)
        if not lg.handlers:
            h = logging.StreamHandler()
            h.setFormatter(logging.Formatter(
                "%(asctime)s %(levelname)s %(name)s: %(message)s"
            ))
            lg.addHandler(h)


class LoggerFilter:
    """Reference spelling."""

    redirectSparkInfoLogs = staticmethod(redirect_spark_info_logs)
    redirect_spark_info_logs = staticmethod(redirect_spark_info_logs)
