"""Ring attention — exact attention with the sequence axis sharded.

New capability (the reference has nothing past `Recurrent`'s BPTT
windows — SURVEY.md §5 "long-context: absent"); designed TPU-first:

* each device holds a (B, H, T/n, D) block of Q, K, V;
* K/V blocks rotate around the ICI ring with `lax.ppermute` (n-1 hops,
  each overlapping with the local block's attention compute once XLA
  schedules the ring);
* a flash-style online softmax (running max `m`, normalizer `l`,
  unnormalized accumulator `acc`) combines per-block partial results,
  so attention is *exact* — not windowed/approximate — while no device
  ever materialises the (T, T) score matrix or the full K/V.

Memory per device: O(T/n · T/n) scores + O(T/n · D) state, so max
sequence length scales linearly with the ring size.
"""

from __future__ import annotations

import math
from typing import Optional

from bigdl_tpu.nn.attention import MultiHeadAttention


def _block_partials(q, k, v, scale, causal, q_off, k_off):
    """Partial attention of a q block against one k/v block.

    Returns (m, l, acc): running row max (B,H,Tq), normalizer (B,H,Tq)
    and accumulator (B,H,Tq,D), all relative to shift `where(isfinite(m),
    m, 0)` — the flash attention invariant.
    """
    import jax.numpy as jnp

    q32 = q.astype(jnp.float32)
    k32 = k.astype(jnp.float32)
    scores = jnp.einsum(
        "bhqd,bhkd->bhqk", q32, k32, preferred_element_type=jnp.float32
    ) * scale
    if causal:
        tq, tk = scores.shape[-2], scores.shape[-1]
        qpos = jnp.arange(tq)[:, None] + q_off
        kpos = jnp.arange(tk)[None, :] + k_off
        scores = jnp.where(qpos >= kpos, scores, -jnp.inf)
    m = jnp.max(scores, axis=-1)
    shift = jnp.where(jnp.isfinite(m), m, 0.0)
    p = jnp.exp(scores - shift[..., None])
    l = jnp.sum(p, axis=-1)
    acc = jnp.einsum(
        "bhqk,bhkd->bhqd", p, v.astype(jnp.float32),
        preferred_element_type=jnp.float32,
    )
    return m, l, acc


def _combine(m, l, acc, mi, li, acci):
    """Merge two flash-partials into one (same invariant)."""
    import jax.numpy as jnp

    m_new = jnp.maximum(m, mi)
    shift = jnp.where(jnp.isfinite(m_new), m_new, 0.0)
    alpha = jnp.exp(jnp.where(jnp.isfinite(m), m, -jnp.inf) - shift)
    beta = jnp.exp(jnp.where(jnp.isfinite(mi), mi, -jnp.inf) - shift)
    l_new = l * alpha + li * beta
    acc_new = acc * alpha[..., None] + acci * beta[..., None]
    return m_new, l_new, acc_new


def ring_attention(q, k, v, axis_name: str, *, causal: bool = False,
                   scale: Optional[float] = None, wire=None):
    """Exact ring attention.  MUST run inside shard_map (or pmap) with
    `axis_name` bound; q/k/v are the LOCAL (B, H, T/n, D) blocks, laid
    out in ring order (device i holds positions [i·T/n, (i+1)·T/n)).

    ``wire`` (a ``parallel/wire.WireSpec`` or dtype string) compresses
    the K/V rotation: each hop ships the blockwise-quantized payload +
    scales instead of full-width K/V, dequantized on arrival.  Each
    block is re-quantized from its received (already once-quantized)
    value, so the error stays one quantization deep per hop chain —
    the attention math itself stays f32.
    """
    import jax
    from jax import lax
    import jax.numpy as jnp

    from bigdl_tpu.parallel import wire as W

    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    spec = W.resolve(wire)
    n = lax.psum(1, axis_name)  # static: the axis size
    idx = lax.axis_index(axis_name)
    t_loc = q.shape[2]
    q_off = idx * t_loc

    b, h, _, d = q.shape
    m = jnp.full((b, h, t_loc), -jnp.inf, jnp.float32)
    l = jnp.zeros((b, h, t_loc), jnp.float32)
    acc = jnp.zeros((b, h, t_loc, d), jnp.float32)

    ks, vs = k, v
    perm = [(j, (j + 1) % n) for j in range(n)]
    for s in range(n):
        # after s forward rotations, device idx holds the block that
        # started on device (idx - s) % n
        k_off = ((idx - s) % n) * t_loc
        mi, li, acci = _block_partials(q, ks, vs, scale, causal, q_off, k_off)
        m, l, acc = _combine(m, l, acc, mi, li, acci)
        if s != n - 1:  # last hop would be a wasted full-circle rotation
            ks = W.ppermute(ks, axis_name, perm, spec)
            vs = W.ppermute(vs, axis_name, perm, spec)
    out = acc / jnp.maximum(l, 1e-30)[..., None]
    return out.astype(q.dtype)


def ring_attention_sharded(q, k, v, mesh, *, seq_axis: str = "seq",
                           batch_axis: Optional[str] = None,
                           causal: bool = False,
                           scale: Optional[float] = None, wire=None):
    """shard_map wrapper: q/k/v are GLOBAL (B, H, T, D) arrays; the seq
    dim is sharded over `seq_axis` (and optionally batch over
    `batch_axis`).  Composable under jit — GSPMD reshards inputs to the
    in_specs automatically.  ``wire`` compresses the K/V rotation
    (see :func:`ring_attention`); the byte account then prices the
    quantized payload + per-block f32 scales per hop and publishes the
    ``path="ring"`` wire-savings ratio.
    """
    from functools import partial

    from jax.sharding import PartitionSpec as P

    from bigdl_tpu.obs import collectives as C
    from bigdl_tpu.parallel import wire as W
    from bigdl_tpu.optim.distri_optimizer import _shard_map

    wspec = W.resolve(wire)
    n = int(mesh.shape[seq_axis])
    if n > 1:
        # wire accounting from the GLOBAL static shapes (trace time —
        # once per compile under jit): K and V blocks each ride the
        # ring for n-1 hops at 1/n of the global array per device
        baseline = (
            C.ppermute_bytes(int(k.size) // n, k.dtype, hops=n - 1)
            + C.ppermute_bytes(int(v.size) // n, v.dtype, hops=n - 1))
        if wspec is None:
            C.record("ppermute", k.dtype, baseline, axis_size=n)
        elif not wspec.scaled:  # bfloat16: cast-only hops
            moved = (
                C.ppermute_bytes(int(k.size) // n, "bfloat16", hops=n - 1)
                + C.ppermute_bytes(int(v.size) // n, "bfloat16",
                                   hops=n - 1))
            C.record("ppermute", wspec.wire_name, moved, axis_size=n)
            C.record_savings("ring", baseline, moved)
        else:
            # the local K (and V) block quantizes to whole scale
            # blocks (zero-padded): payload + f32 scales per hop
            padded = W.padded_elems(int(k.size) // n, wspec, 1)
            payload = 2 * C.ppermute_bytes(padded, wspec.wire_name,
                                           hops=n - 1)
            scales = 2 * C.ppermute_bytes(padded // wspec.block,
                                          "float32", hops=n - 1)
            C.record("ppermute", wspec.wire_name, payload, axis_size=n)
            C.record("ppermute", "float32", scales, axis_size=n)
            C.record_savings("ring", baseline, payload + scales)
    spec = P(batch_axis, None, seq_axis, None)
    f = partial(ring_attention, axis_name=seq_axis, causal=causal,
                scale=scale, wire=wspec)
    return _shard_map(f, mesh, in_specs=(spec, spec, spec),
                      out_specs=spec)(q, k, v)


class RingMultiHeadAttention(MultiHeadAttention):
    """MultiHeadAttention whose inner attention runs as ring attention
    over a mesh sequence axis — drop-in for the Transformer stack when
    sequences outgrow one device's HBM.

    The module's projections stay ordinary matmuls (GSPMD shards them by
    the activations' sequence sharding); only softmax(QKᵀ)V needs the
    explicit ring because its reduction spans the full sequence axis.
    """

    def __init__(self, dim: int, n_head: int, mesh, *,
                 seq_axis: str = "seq", batch_axis: Optional[str] = None,
                 causal: bool = False, with_bias: bool = True,
                 dropout: float = 0.0, wire=None):
        super().__init__(dim, n_head, causal=causal, with_bias=with_bias,
                         dropout=dropout)
        self.mesh = mesh
        self.seq_axis = seq_axis
        self.batch_axis = batch_axis
        self.wire = wire

    def _inner_attention(self, q, k, v):
        return ring_attention_sharded(
            q, k, v, self.mesh, seq_axis=self.seq_axis,
            batch_axis=self.batch_axis, causal=self.causal,
            wire=self.wire,
        )

    def __repr__(self):
        return (f"RingMultiHeadAttention(dim={self.dim}, heads={self.n_head},"
                f" seq_axis={self.seq_axis!r}, causal={self.causal})")
