"""Pipeline parallelism — collective-permute schedule over a mesh axis.

The scaling-book recipe: N identical stages live on N devices (stage
parameters are the SAME pytree with a leading stage dim, sharded over
the `pipe` axis).  Microbatches stream in at stage 0; every step each
device applies its stage and `ppermute`s the activation to the next
device.  After M + N - 1 steps (M microbatches, N stages — the GPipe
fill/drain bubble) the last device has produced every output.

All control flow is a `lax.fori_loop` with static shapes — one XLA
program, no per-microbatch dispatch; the ppermute rides the ICI ring.
"""

from __future__ import annotations

from typing import Callable


def pipeline_apply(stage_fn: Callable, stage_params, x, axis_name: str):
    """Run the pipeline.  MUST be called inside shard_map with
    `axis_name` bound.

    Args:
      stage_fn: (params, act) -> act, shape-preserving (a pipeline
        stage; e.g. one TransformerBlock.apply closed over state).
      stage_params: THIS device's stage parameters.
      x: microbatched input (M, mb, ...), replicated on every device.
    Returns:
      (M, mb, ...) outputs of the final stage, replicated (psum
      broadcast off the last device).
    """
    import jax
    from jax import lax
    import jax.numpy as jnp

    n = lax.psum(1, axis_name)
    idx = lax.axis_index(axis_name)
    m = x.shape[0]
    perm = [(j, j + 1) for j in range(n - 1)]

    ybuf = jnp.zeros_like(x)
    recv = jnp.zeros_like(x[0])

    def step(t, carry):
        recv, ybuf = carry
        # stage 0 injects microbatch t (clamped: the drain-phase reads
        # feed garbage that never reaches the output buffer in time)
        inj = lax.dynamic_index_in_dim(
            x, jnp.clip(t, 0, m - 1), axis=0, keepdims=False
        )
        inp = jnp.where(idx == 0, inj, recv)
        out = stage_fn(stage_params, inp)
        # the last device emits microbatch t-(n-1) at step t
        oidx = t - (n - 1)
        upd = lax.dynamic_update_index_in_dim(
            ybuf, out, jnp.clip(oidx, 0, m - 1), axis=0
        )
        ybuf = jnp.where(oidx >= 0, upd, ybuf)
        recv = lax.ppermute(out, axis_name, perm)
        return recv, ybuf

    _, ybuf = lax.fori_loop(0, m + n - 1, step, (recv, ybuf))
    # broadcast the last device's buffer to all (replicated output)
    return lax.psum(jnp.where(idx == n - 1, ybuf, 0.0), axis_name)


def pipelined(stage_fn: Callable, mesh, axis_name: str = "pipe"):
    """shard_map wrapper.  Returns `f(stacked_params, x_microbatched)`:

    * stacked_params: stage params pytree with a leading stage dim of
      size mesh.shape[axis_name] on every leaf (stack the per-stage
      params with `jax.tree.map(lambda *a: jnp.stack(a), *stages)`);
    * x_microbatched: (M, mb, ...) input.

    Composable under jit; the stage dim is sharded over `axis_name` so
    each device holds exactly its own stage's weights.
    """
    import jax
    from jax.sharding import PartitionSpec as P

    from bigdl_tpu.optim.distri_optimizer import _shard_map

    def body(stacked_local, x):
        params = jax.tree.map(lambda a: a[0], stacked_local)
        return pipeline_apply(stage_fn, params, x, axis_name)

    def run(stacked_params, x):
        from bigdl_tpu.obs import collectives as C

        n = int(mesh.shape[axis_name])
        if n > 1:
            # wire accounting from static shapes (trace time): every
            # fori_loop step ppermutes one microbatch-sized activation
            # to the next stage (m + n - 1 steps incl. fill/drain), and
            # the final psum broadcasts the (M, mb, ...) output buffer
            m = int(x.shape[0])
            mb_elems = int(x.size) // max(1, m)
            C.record("ppermute", x.dtype,
                     C.ppermute_bytes(mb_elems, x.dtype, hops=m + n - 1),
                     axis_size=n)
            C.record("psum", x.dtype,
                     C.all_reduce_bytes(int(x.size), x.dtype, n),
                     axis_size=n)
        pspecs = jax.tree.map(lambda _: P(axis_name), stacked_params)
        return _shard_map(
            body, mesh, in_specs=(pspecs, P()), out_specs=P()
        )(stacked_params, x)

    return run
