"""Ulysses-style all-to-all sequence parallelism.

New capability (the reference has no sequence parallelism — SURVEY.md
§2.4); the second of the two standard long-context strategies, next to
:mod:`bigdl_tpu.parallel.ring`:

* activations flow through the network sequence-sharded — each device
  holds (B, H, T/n, D);
* at the attention boundary, one ``lax.all_to_all`` reshards to
  head-sharded (B, H/n, T, D): every device now sees the FULL sequence
  for its head subset, so the plain (flash) attention kernel runs
  unchanged — no online-softmax ring bookkeeping;
* a second all_to_all reshards back to sequence-sharded for the MLP.

Trade-off vs the ring: two all_to_alls of the full activation per
attention (ICI bandwidth) instead of n-1 K/V rotations, but the
attention itself is a single dense kernel — typically the better deal
when ``n_head >= n_devices`` and the per-hop latency would dominate.
Requires ``n_head % axis_size == 0``.
"""

from __future__ import annotations

import math
from typing import Optional

from bigdl_tpu.nn.attention import MultiHeadAttention


def ulysses_attention(q, k, v, axis_name: str, *, causal: bool = False,
                      scale: Optional[float] = None):
    """All-to-all attention.  MUST run inside shard_map with
    ``axis_name`` bound; q/k/v are the LOCAL (B, H, T/n, D) blocks in
    ring order.  Heads must divide by the axis size."""
    from jax import lax

    from bigdl_tpu.ops.attention import dot_product_attention

    n = lax.psum(1, axis_name)
    h = q.shape[1]
    if h % n:
        raise ValueError(
            f"ulysses_attention: {h} heads not divisible by axis size {n}")
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])

    # seq-sharded (B, H, T/n, D) -> head-sharded (B, H/n, T, D)
    def to_heads(x):
        return lax.all_to_all(x, axis_name, split_axis=1, concat_axis=2,
                              tiled=True)

    qh, kh, vh = to_heads(q), to_heads(k), to_heads(v)
    # full sequence present locally: the standard op applies,
    # including plain causal masking ("auto" = the measured policy in
    # ops/attention.py — lax below T=4096, Pallas flash beyond)
    out = dot_product_attention(qh, kh, vh, causal=causal, scale=scale)
    # head-sharded -> seq-sharded
    return lax.all_to_all(out, axis_name, split_axis=2, concat_axis=1,
                          tiled=True)


def ulysses_attention_sharded(q, k, v, mesh, *, seq_axis: str = "seq",
                              batch_axis: Optional[str] = None,
                              causal: bool = False,
                              scale: Optional[float] = None):
    """shard_map wrapper: q/k/v are GLOBAL (B, H, T, D) arrays with the
    seq dim sharded over ``seq_axis``.  Composable under jit."""
    from functools import partial

    from jax.sharding import PartitionSpec as P

    from bigdl_tpu.optim.distri_optimizer import _shard_map

    spec = P(batch_axis, None, seq_axis, None)
    f = partial(ulysses_attention, axis_name=seq_axis, causal=causal,
                scale=scale)
    return _shard_map(f, mesh, in_specs=(spec, spec, spec),
                      out_specs=spec)(q, k, v)


class UlyssesMultiHeadAttention(MultiHeadAttention):
    """MultiHeadAttention whose inner attention reshards
    sequence->heads via all_to_all (DeepSpeed-Ulysses pattern) — the
    drop-in alternative to RingMultiHeadAttention when
    ``n_head >= mesh[seq_axis]``."""

    def __init__(self, dim: int, n_head: int, mesh, *,
                 seq_axis: str = "seq", batch_axis: Optional[str] = None,
                 causal: bool = False, with_bias: bool = True,
                 dropout: float = 0.0):
        super().__init__(dim, n_head, causal=causal, with_bias=with_bias,
                         dropout=dropout)
        self.mesh = mesh
        self.seq_axis = seq_axis
        self.batch_axis = batch_axis

    def _inner_attention(self, q, k, v):
        return ulysses_attention_sharded(
            q, k, v, self.mesh, seq_axis=self.seq_axis,
            batch_axis=self.batch_axis, causal=self.causal,
        )

    def __repr__(self):
        return (f"UlyssesMultiHeadAttention(dim={self.dim}, "
                f"heads={self.n_head}, seq_axis={self.seq_axis!r}, "
                f"causal={self.causal})")
