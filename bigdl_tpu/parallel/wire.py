"""Quantized collectives v2 — one compressed wire for every exchange.

The reference ships ``FP16CompressedTensor`` so gradient aggregation
never moves full-width bytes («bigdl»/parameters/FP16CompressedTensor.
scala); round 5's int8 blockwise wire reproduced that for
DistriOptimizer as a quantize-once / all_to_all / dequantize exchange.
EQuARX (arXiv:2506.17615, PAPERS.md) makes the stronger point this
module implements: the win compounds when quantization happens *inside*
the reduction stages with error feedback, and the same wire should
serve every exchange path, not only the ZeRO-1 gradient shuffle.

One :class:`WireSpec` — wire dtype (``bfloat16`` / ``int8`` /
``fp8_e4m3`` / ``fp8_e5m2``) + blockwise scaling + optional error
feedback — parameterizes four collectives:

* :func:`reduce_scatter` — a **staged ring**: the partial sum for
  chunk ``c`` starts at device ``c+1`` and travels ``n-1`` hops; each
  hop re-quantizes the partial (payload + per-block f32 scales ride
  the wire), the receiver dequantizes and **accumulates in f32**.  The
  compression applies to the reduction itself — every hop of every
  stage moves compressed bytes — not just to a pre-reduce shuffle.
* :func:`psum` — compressed all-reduce: the staged ring reduce-scatter
  followed by an all-gather of the quantized shard (payload + scales).
* :func:`all_to_all` / :func:`ppermute` — quantize, move the payload
  and scales through the collective, dequantize on arrival.  Both are
  ``custom_vjp`` so the backward pass rides the *same* compressed wire
  in the transpose direction (Ulysses/MoE reshards and the ring
  K/V rotation stay differentiable).

**Error feedback** (EQuARX §3): each device keeps the quantization
error it introduced last round and adds it back *before* the next
quantization, so compression error dithers instead of biasing long
runs.  For the staged ring the residual is per-device per-chunk —
device ``d`` quantizes one partial for every chunk it forwards — held
as one ``(n_shards, padded)`` f32 array sharded over the data axis
(row ``d`` = device ``d``'s residual in flat-parameter coordinates,
own-chunk region identically zero because the owner's final add is
exact).  DistriOptimizer stores it next to the flat ZeRO-1 vectors in
the optimizer state, so it rides checkpoints and is re-laid-out by
``resilience/elastic.ensure_shard_layout`` on world resize.

Everything here runs **inside shard_map** (an ``axis_name`` must be
bound); byte accounting stays with the callers, costed from static
shapes via ``obs/collectives.py`` (``staged_ring_exchange_bytes``,
``fp8_blockwise_exchange_bytes``) — zero device reads.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

__all__ = [
    "WireSpec",
    "WIRE_DTYPES",
    "resolve",
    "quantize",
    "dequantize",
    "roundtrip",
    "reduce_scatter",
    "psum",
    "all_to_all",
    "ppermute",
    "padded_elems",
    "plan_buckets",
    "bucket_param_coords",
    "buckets_equal",
]

# wire dtype name -> (jnp attribute, symmetric clip max).  bfloat16 is
# the scale-free member (a cast IS the quantizer); the scaled members
# get per-block symmetric scaling amax/qmax.
WIRE_DTYPES = {
    "bfloat16": ("bfloat16", None),
    "int8": ("int8", 127.0),
    "fp8_e4m3": ("float8_e4m3fn", 448.0),
    "fp8_e5m2": ("float8_e5m2", 57344.0),
}

# spellings accepted anywhere a wire dtype is configured; both map the
# uncompressed pass-through
UNCOMPRESSED = ("float32", "none")


def _jnp():
    import jax.numpy as jnp

    return jnp


@dataclasses.dataclass(frozen=True)
class WireSpec:
    """How bytes leave the chip: wire dtype + blockwise scaling + EF.

    ``dtype`` — one of :data:`WIRE_DTYPES` or ``"float32"``/``"none"``
    (uncompressed pass-through).  ``block`` — elements per scale for
    the scaled dtypes (int8/fp8); the flat operand is padded to whole
    blocks by the caller (:func:`padded_elems`).  ``error_feedback`` —
    carry the per-device quantization residual across rounds
    (:func:`reduce_scatter` only; stateless exchanges have no run to
    bias)."""

    dtype: str = "bfloat16"
    block: int = 512
    error_feedback: bool = False

    def __post_init__(self):
        if self.dtype not in WIRE_DTYPES and self.dtype not in UNCOMPRESSED:
            raise ValueError(
                f"wire dtype {self.dtype!r} not supported; choose one of "
                f"{sorted(WIRE_DTYPES) + list(UNCOMPRESSED)}")
        if self.block < 1:
            raise ValueError(f"wire block must be positive, got "
                             f"{self.block}")
        if self.error_feedback and not self.compressed:
            raise ValueError(
                "error feedback needs a compressed wire dtype "
                f"(got {self.dtype!r}: nothing is quantized, there is "
                "no error to feed back)")

    # ---- classification ------------------------------------------------
    @property
    def compressed(self) -> bool:
        """Anything that loses bits on the wire (incl. bfloat16)."""
        return self.dtype in WIRE_DTYPES

    @property
    def scaled(self) -> bool:
        """Carries per-block f32 scales next to the payload."""
        return WIRE_DTYPES.get(self.dtype, (None, None))[1] is not None

    @property
    def wire_name(self) -> str:
        """The dtype name byte accounting records (numpy spelling)."""
        if self.dtype in WIRE_DTYPES:
            return WIRE_DTYPES[self.dtype][0]
        return "float32"

    def jnp_dtype(self):
        jnp = _jnp()
        return getattr(jnp, WIRE_DTYPES[self.dtype][0])

    @property
    def qmax(self) -> Optional[float]:
        return WIRE_DTYPES.get(self.dtype, (None, None))[1]

    @classmethod
    def from_config(cls, dtype: Optional[str] = None,
                    block: Optional[int] = None,
                    error_feedback: Optional[bool] = None) -> "WireSpec":
        """Fill unset fields from the process config (``BIGDL_WIRE_DTYPE``
        / ``BIGDL_WIRE_BLOCK`` / ``BIGDL_WIRE_EF``)."""
        from bigdl_tpu.config import config

        w = config.wire
        return cls(
            dtype=w.dtype if dtype is None else dtype,
            block=w.block if block is None else int(block),
            error_feedback=(w.error_feedback if error_feedback is None
                            else bool(error_feedback)),
        )


def resolve(wire) -> Optional[WireSpec]:
    """Normalize a user-facing ``wire=`` argument: None stays None (no
    compression), a dtype string becomes a config-defaulted spec, a
    :class:`WireSpec` passes through.  Uncompressed specs normalize to
    None so call sites have ONE "is the wire on" test."""
    if wire is None:
        return None
    if isinstance(wire, str):
        wire = WireSpec.from_config(dtype=wire)
    if not isinstance(wire, WireSpec):
        raise TypeError(f"wire must be a WireSpec, dtype string or None; "
                        f"got {type(wire).__name__}")
    return wire if wire.compressed else None


def padded_elems(n_elems: int, spec: Optional["WireSpec"],
                 n_shards: int) -> int:
    """Elements after padding ``n_elems`` to the wire's alignment
    quantum: whole blocks per shard for scaled dtypes, whole shards
    otherwise."""
    quantum = n_shards * (spec.block if spec is not None and spec.scaled
                          else 1)
    return n_elems + (-n_elems) % quantum


# ----------------------------------------------------- overlap bucketing
def plan_buckets(padded: int, quantum: int, target_elems: int):
    """Partition the padded flat-parameter layout ``[0, padded)`` into
    contiguous ``(start, size)`` buckets for the overlapped gradient
    exchange (ISSUE 11): each bucket's reduce-scatter launches as soon
    as its gradients leave the backward, riding under the remaining
    backward compute.

    Every bucket size is a positive multiple of ``quantum`` (the wire's
    alignment unit: ``n_shards * block`` for scaled dtypes, ``n_shards``
    otherwise) so per-bucket chunks stay whole quantization blocks and
    the summed wire bytes equal the monolithic exchange exactly.
    ``target_elems <= 0`` returns the single monolithic bucket."""
    q = max(1, int(quantum))
    padded = int(padded)
    if padded % q:
        raise ValueError(f"padded length {padded} not a multiple of the "
                         f"alignment quantum {q}")
    if target_elems is None or int(target_elems) <= 0 or padded == 0:
        return [(0, padded)]
    per = max(q, ((int(target_elems) + q - 1) // q) * q)
    out = []
    start = 0
    while start < padded:
        size = min(per, padded - start)
        out.append((start, size))
        start += size
    return out


def bucket_param_coords(buckets, n_shards: int):
    """The shard-major -> flat-parameter index map of a bucketed ZeRO-1
    layout, as an ``np.int64`` array ``coords`` of length ``padded``:
    the element stored at shard-major position ``p`` (device ``p //
    shard_len``, offset ``p % shard_len`` — the layout the bucketed
    exchange leaves the optimizer-state vectors in) is flat-parameter
    coordinate ``coords[p]``.

    With one bucket this is the identity (the monolithic layout IS
    parameter-major); ``resilience/elastic.ensure_shard_layout`` uses
    it to re-partition checkpointed state across bucket plans and world
    sizes: ``param_major[coords] = shard_major``."""
    import numpy as np

    buckets = [(int(s), int(z)) for s, z in buckets]
    n = int(n_shards)
    padded = sum(z for _, z in buckets)
    shard_len = padded // n
    coords = np.empty(padded, np.int64)
    for d in range(n):
        off = d * shard_len
        for s, z in buckets:
            c = z // n
            coords[off:off + c] = np.arange(s + d * c, s + (d + 1) * c,
                                            dtype=np.int64)
            off += c
    return coords


def buckets_equal(a, b) -> bool:
    """Whether two bucket plans (possibly None / list-of-lists from a
    JSON topology manifest) describe the same layout.  ``None`` means
    "single monolithic bucket" and equals any one-bucket plan."""
    norm = lambda p: None if p is None or len(p) <= 1 \
        else [(int(s), int(z)) for s, z in p]
    return norm(a) == norm(b)


# ------------------------------------------------------------ quantizers
def _blocked(x, block):
    """(padded flat view, original trailing length).  The operand is
    flattened and zero-padded to whole blocks — padding lanes quantize
    exactly (zeros) and are sliced off by dequantize."""
    jnp = _jnp()
    flat = x.reshape(-1)
    pad = (-flat.size) % block
    if pad:
        flat = jnp.concatenate([flat, jnp.zeros((pad,), flat.dtype)])
    return flat.reshape(-1, block), flat.size


def quantize(x, spec: WireSpec):
    """Compress ``x`` for the wire.  Returns ``(payload, scales)`` —
    ``scales`` is None for bfloat16 (cast-only).  Scaled dtypes see the
    operand as flat ``block``-element groups (zero-padded to whole
    blocks): symmetric per-block scaling ``amax/qmax`` bounds each
    element's error by its block's ``amax/(2*qmax)`` (int8: max/254,
    the FP16CompressedTensor-style guarantee at a quarter of the f32
    bytes)."""
    jnp = _jnp()
    if not spec.scaled:
        return x.astype(jnp.bfloat16), None
    xb, _ = _blocked(x.astype(jnp.float32), spec.block)
    amax = jnp.max(jnp.abs(xb), axis=1)
    scale = jnp.maximum(amax / spec.qmax, jnp.float32(1e-30))
    q = xb / scale[:, None]
    if spec.dtype == "int8":
        # float->int astype truncates toward zero; the grid midpoint
        # bound (amax/254) needs round-to-nearest
        q = jnp.round(q)
    payload = jnp.clip(q, -spec.qmax, spec.qmax).astype(spec.jnp_dtype())
    return payload, scale


def dequantize(payload, scales, spec: WireSpec, shape=None):
    """Inverse of :func:`quantize` (f32 result).  ``shape`` restores
    the original operand shape (and drops block padding)."""
    jnp = _jnp()
    if scales is None:
        out = payload.astype(jnp.float32)
        return out if shape is None else out.reshape(shape)
    out = (payload.astype(jnp.float32) * scales[:, None]).reshape(-1)
    if shape is not None:
        n = 1
        for d in shape:
            n *= int(d)
        out = out[:n].reshape(shape)
    return out


def _qdq(x, spec):
    return dequantize(*quantize(x, spec), spec, shape=x.shape).astype(
        x.dtype)


def roundtrip(x, spec):
    """Quantize-dequantize ``x`` through the wire (the numerics a
    receiver sees).  Differentiable: the backward pass compresses the
    cotangent through the SAME wire — a training exchange pays the
    quantization in both directions, exactly like the forward."""
    import jax

    spec = resolve(spec)
    if spec is None:
        return x

    @jax.custom_vjp
    def _rt(v):
        return _qdq(v, spec)

    def _fwd(v):
        return _qdq(v, spec), None

    def _bwd(_, ct):
        return (_qdq(ct, spec),)

    _rt.defvjp(_fwd, _bwd)
    return _rt(x)


# ----------------------------------------------------- staged ring reduce
def reduce_scatter(g, axis_name: str, n_shards: int, spec,
                   ef=None):
    """Staged ring reduce-scatter with in-reduce quantization.

    ``g`` is the LOCAL flat f32 operand (length divisible by
    ``n_shards``, and by ``n_shards * block`` for scaled dtypes);
    device ``d`` returns the fully-reduced chunk ``d`` (length
    ``g.size // n_shards``) — ``psum_scatter(tiled)`` semantics.

    The partial sum for chunk ``c`` starts at device ``c+1`` as its
    local chunk, then rides the ring ``n-1`` hops; every hop quantizes
    the partial (payload + scales on the wire), the receiver
    dequantizes, adds its own local chunk **in f32**, and forwards.
    The owner's final add is exact — the last word on every chunk is
    full precision.

    ``ef`` — optional per-device error-feedback residual, local shape
    ``(n_shards, chunk_len)`` (row ``c`` = this device's residual for
    chunk ``c``).  Added to the partial before each quantization;
    replaced by the fresh quantization error.  Returns
    ``(chunk, new_ef)`` — ``new_ef`` is None when ``ef`` is None.
    """
    import jax
    from jax import lax

    jnp = _jnp()
    spec = resolve(spec)
    n = int(n_shards)
    if spec is None or n == 1:
        # nothing rides a wire: exact psum_scatter (n == 1 is a local
        # identity — compressing it would cost error for zero bytes)
        if n == 1:
            return (g.astype(jnp.float32), ef)
        return (lax.psum_scatter(g, axis_name, scatter_dimension=0,
                                 tiled=True).astype(jnp.float32), ef)
    if g.size % n:
        raise ValueError(f"operand length {g.size} not divisible by "
                         f"{n} shards")
    chunk_len = g.size // n
    if spec.scaled and chunk_len % spec.block:
        raise ValueError(
            f"chunk length {chunk_len} not divisible by wire block "
            f"{spec.block}; pad the operand to padded_elems() first")
    idx = lax.axis_index(axis_name)
    chunks = g.astype(jnp.float32).reshape(n, chunk_len)
    perm = [(j, (j + 1) % n) for j in range(n)]

    def take(arr, c):
        return lax.dynamic_slice_in_dim(arr, c, 1, axis=0)[0]

    c = (idx - 1) % n
    acc = take(chunks, c)
    if ef is not None:
        acc = acc + take(ef, c)
        new_ef = jnp.zeros_like(ef)
    for _hop in range(n - 1):
        payload, scales = quantize(acc, spec)
        if ef is not None:
            err = acc - dequantize(payload, scales, spec, shape=acc.shape)
            new_ef = lax.dynamic_update_slice_in_dim(
                new_ef, err[None], c, axis=0)
        payload = lax.ppermute(payload, axis_name, perm)
        if scales is not None:
            scales = lax.ppermute(scales, axis_name, perm)
        recv = dequantize(payload, scales, spec, shape=acc.shape)
        c = (c - 1) % n
        acc = recv + take(chunks, c)
        if ef is not None:
            acc = acc + take(ef, c)
    # after n-1 hops c == idx: every peer's contribution is in, the
    # own-chunk add was exact, so the own-row residual stays zero
    return acc, (new_ef if ef is not None else None)


def psum_layout(n_elems: int, spec: "WireSpec", n_shards: int):
    """``(padded_elems, effective_block)`` for a :func:`psum` operand:
    the block shrinks to the chunk a small operand actually has, so a
    16-element bias never pads to a 512-element quantum (shared with
    the byte models so golden counts match the wire)."""
    n = int(n_shards)
    chunk = -(-int(n_elems) // n)  # ceil
    if not spec.scaled:
        return chunk * n, spec.block
    b = max(1, min(spec.block, chunk))
    chunk += (-chunk) % b
    return chunk * n, b


def psum(x, axis_name: str, n_shards: int, spec, ef=None):
    """Compressed all-reduce: the staged ring reduce-scatter above,
    then an all-gather of the quantized owner shards (payload +
    scales).  Arbitrary operand shape — flattened and zero-padded to
    the :func:`psum_layout` quantum internally.  Returns ``(value,
    new_ef)`` with the summed operand in the input's shape (f32)."""
    from jax import lax

    jnp = _jnp()
    spec = resolve(spec)
    n = int(n_shards)
    if spec is None or n == 1:
        return (lax.psum(x, axis_name) if n > 1
                else x.astype(jnp.float32), ef)
    shape = x.shape
    flat = x.astype(jnp.float32).reshape(-1)
    padded, block = psum_layout(flat.size, spec, n)
    spec = WireSpec(spec.dtype, block, spec.error_feedback)
    if padded != flat.size:
        flat = jnp.concatenate(
            [flat, jnp.zeros((padded - flat.size,), jnp.float32)])
    shard, new_ef = reduce_scatter(flat, axis_name, n, spec, ef=ef)
    payload, scales = quantize(shard, spec)
    payload = lax.all_gather(payload, axis_name, tiled=True)
    if scales is not None:
        scales = lax.all_gather(scales, axis_name, tiled=True)
    full = dequantize(payload, scales, spec)
    n_true = 1
    for d in shape:
        n_true *= int(d)
    return full[:n_true].reshape(shape), new_ef


# ------------------------------------------------- compressed data moves
def effective_block(slice_elems: int, block: int) -> int:
    """Largest block <= ``block`` that divides ``slice_elems`` — the
    data-move collectives scale whole per-destination slices, so the
    blocking must tile each slice exactly (shared by the byte models
    in obs/collectives.py so golden counts match the wire)."""
    b = max(1, min(int(block), int(slice_elems)))
    while slice_elems % b:
        b -= 1
    return b


def all_to_all(x, axis_name: str, n_shards: int, spec, *,
               split_axis: int = 0, concat_axis: int = 0):
    """``lax.all_to_all(tiled)`` semantics with the payload and
    per-block scales on the wire.  Each per-destination slice is
    quantized in flat block groups (block shrunk to tile the slice —
    :func:`effective_block`), the int8/fp8 payload and the f32 scales
    cross as ``(n, slice)`` row exchanges, and the receiver
    dequantizes and reassembles the tiled concat layout — the round-5
    quantize-once exchange, now available to ANY all_to_all path (MoE
    dispatch/combine, Ulysses reshard).  Differentiable: the transpose
    runs the same compressed exchange with split/concat swapped."""
    import jax
    from jax import lax

    jnp = _jnp()
    spec = resolve(spec)
    n = int(n_shards)
    if n == 1:
        return x
    if spec is None:
        return lax.all_to_all(x, axis_name, split_axis, concat_axis,
                              tiled=True)

    def _exchange(v, sa, ca):
        if not spec.scaled:
            w = v.astype(jnp.bfloat16)
            return lax.all_to_all(w, axis_name, sa, ca,
                                  tiled=True).astype(v.dtype)
        # canonical row layout: moved = v with the split axis leading,
        # one row per destination (slice elements in the SENDER's flat
        # order — the scale blocks tile rows, never straddling slices)
        moved = jnp.moveaxis(v.astype(jnp.float32), sa, 0)
        s_len = moved.shape[0]
        rows = moved.reshape(n, -1)
        b = effective_block(rows.shape[1], spec.block)
        row_spec = WireSpec(spec.dtype, b, False)
        payload, scales = quantize(rows, row_spec)
        payload = payload.reshape(n, -1)
        scales = scales.reshape(n, -1)
        payload = lax.all_to_all(payload, axis_name, 0, 0, tiled=True)
        scales = lax.all_to_all(scales, axis_name, 0, 0, tiled=True)
        recv = dequantize(payload.reshape(-1, b), scales.reshape(-1),
                          row_spec)
        # recv row j = source j's slice, still in sender flat order:
        # (n, s_len/n, *rest) -> move the source dim next to the concat
        # axis and merge source-major, lax's tiled concat order
        recv = recv.reshape((n, s_len // n) + moved.shape[1:])
        if ca == sa:
            # slices swap in place along one axis, source-major
            out = recv.reshape((s_len,) + moved.shape[1:])
        else:
            q = ca + (1 if ca < sa else 0)  # ca's position in moved
            out = jnp.moveaxis(recv, 0, q)
            shape = list(out.shape)
            shape[q:q + 2] = [shape[q] * shape[q + 1]]
            out = out.reshape(shape)
        out = jnp.moveaxis(out, 0, sa)
        return out.astype(v.dtype)

    @jax.custom_vjp
    def _a2a(v):
        return _exchange(v, split_axis, concat_axis)

    def _fwd(v):
        return _exchange(v, split_axis, concat_axis), None

    def _bwd(_, ct):
        # transpose of all_to_all swaps split/concat; the cotangent
        # rides the same compressed wire home
        return (_exchange(ct, concat_axis, split_axis),)

    _a2a.defvjp(_fwd, _bwd)
    return _a2a(x)


def ppermute(x, axis_name: str, perm, spec):
    """``lax.ppermute`` with the payload and scales on the wire (one
    ring-attention K/V hop).  Differentiable: the cotangent rides the
    inverted permutation through the same compressed wire."""
    import jax
    from jax import lax

    jnp = _jnp()
    spec = resolve(spec)
    if spec is None:
        return lax.ppermute(x, axis_name, perm)
    perm = [(int(s), int(d)) for s, d in perm]
    inv = [(d, s) for s, d in perm]

    def _hop(v, p):
        if not spec.scaled:
            return lax.ppermute(v.astype(jnp.bfloat16), axis_name,
                                p).astype(jnp.float32).astype(x.dtype)
        payload, scales = quantize(v, spec)
        payload = lax.ppermute(payload, axis_name, p)
        scales = lax.ppermute(scales, axis_name, p)
        return dequantize(payload, scales, spec,
                          shape=v.shape).astype(v.dtype)

    @jax.custom_vjp
    def _pp(v):
        return _hop(v, perm)

    def _fwd(v):
        return _hop(v, perm), None

    def _bwd(_, ct):
        return (_hop(ct, inv),)

    _pp.defvjp(_fwd, _bwd)
    return _pp(x)
