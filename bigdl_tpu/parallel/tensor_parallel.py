"""Tensor parallelism, the GSPMD way.

No manual collectives: tensor parallelism on TPU is expressed by
*placing* parameters with `NamedSharding`s and (where XLA needs a hint)
`with_sharding_constraint` on activations; the compiler inserts the
all-gather / reduce-scatter pairs and overlaps them with MXU work.

`shard_params` walks a module's (nested-dict) param pytree and applies
the first matching (path-regex → PartitionSpec) rule.  Megatron-style
rules for the Transformer stack ship as `TRANSFORMER_TP_RULES`:

* attention wq/wk/wv: rows (output features = heads) split over
  `model` — each device computes its own heads;
* attention wo: columns split over `model` — the psum after the
  row-parallel matmul is the only cross-device hop per block;
* MLP in/out likewise column-then-row.

Weights here are (out_features, in_features), applied as `x @ W.T`
(torch convention, matching nn.Linear / nn.MultiHeadAttention).
"""

from __future__ import annotations

import re
from typing import Iterable, Tuple


# (path regex, spec builder) — specs as tuples of axis names / None;
# turned into PartitionSpec at apply time so this module imports cheap.
TRANSFORMER_TP_RULES: Tuple[Tuple[str, tuple], ...] = (
    (r"attn/w[qkv]$", ("model", None)),
    (r"attn/b[qkv]$", ("model",)),
    (r"attn/wo$", (None, "model")),
    # TransformerBlock MLP: fc1 column-parallel, fc2 row-parallel
    (r"fc1/weight$", ("model", None)),
    (r"fc1/bias$", ("model",)),
    (r"fc2/weight$", (None, "model")),
    # TransformerLM embeddings: split the feature dim
    (r"w[tp]e/weight$", (None, "model")),
    (r"head/weight$", ("model", None)),
)


def _walk(tree, prefix=""):
    if isinstance(tree, dict):
        for k, v in tree.items():
            yield from _walk(v, f"{prefix}/{k}" if prefix else str(k))
    elif tree is not None:
        yield prefix, tree


def _match(path: str, rules: Iterable[Tuple[str, tuple]]):
    for pat, spec in rules:
        if re.search(pat, path):
            return spec
    return None


def param_specs(params, mesh, rules=TRANSFORMER_TP_RULES):
    """Mirror of the param pytree with a PartitionSpec per leaf (P() —
    replicated — where no rule matches).  Feed to jit in_shardings or
    `shard_params`."""
    import jax
    from jax.sharding import PartitionSpec as P

    flat = {p: _match(p, rules) for p, _ in _walk(params)}

    def build(tree, prefix=""):
        if isinstance(tree, dict):
            return {
                k: build(v, f"{prefix}/{k}" if prefix else str(k))
                for k, v in tree.items()
            }
        if tree is None:
            return None
        spec = flat.get(prefix)
        # drop axes that don't divide the dim (GSPMD would error)
        if spec is not None:
            shape = tree.shape
            ok = all(
                a is None or (i < len(shape)
                              and shape[i] % mesh.shape[a] == 0)
                for i, a in enumerate(spec)
            )
            if ok:
                return P(*spec)
        return P()

    del jax
    return build(params)


def shard_params(params, mesh, rules=TRANSFORMER_TP_RULES):
    """device_put every param leaf onto the mesh per the rules.  Returns
    the sharded pytree (leaves are committed global arrays)."""
    import jax
    from jax.sharding import NamedSharding

    from bigdl_tpu.obs import collectives as C

    specs = param_specs(params, mesh, rules)
    # placement accounting (one-shot, static shapes): bytes of every
    # leaf that actually splits over a mesh axis — the initial
    # host->devices scatter the TP layout costs
    moved: dict = {}
    for (path, leaf), (_, spec) in zip(_walk(params), _walk(specs)):
        if spec is not None and any(a is not None for a in spec):
            name = str(leaf.dtype) if hasattr(leaf, "dtype") else "float32"
            moved[name] = moved.get(name, 0.0) + (
                int(leaf.size) * C.dtype_bytes(name))
    for name, nbytes in moved.items():
        C.record("tp_shard_params", name, nbytes)
    return jax.tree.map(
        lambda x, s: x if x is None else jax.device_put(
            x, NamedSharding(mesh, s)
        ),
        params, specs,
        is_leaf=lambda x: x is None or hasattr(x, "shape"),
    )


def wire_psum(x, axis_name: str, wire=None, ef=None):
    """Compressed all-reduce for hand-rolled TP blocks — call INSIDE
    shard_map with ``axis_name`` bound.  With ``wire`` unset this is
    ``lax.psum``; with a compressed spec the sum runs as the staged
    ring reduce-scatter + quantized all-gather of
    ``parallel/wire.psum`` (payload + per-block f32 scales on every
    hop, f32 accumulation).  ``ef`` threads an optional error-feedback
    residual (``(n, chunk)`` per device); returns ``(value, new_ef)``
    so gradient loops can carry it."""
    from jax import lax

    from bigdl_tpu.parallel import wire as W

    n = lax.psum(1, axis_name)  # static: the axis size
    return W.psum(x, axis_name, n, W.resolve(wire), ef=ef)


def gradient_psum(grads, mesh, axis: str = "model", wire=None):
    """Sum per-device gradient contributions over a mesh axis with an
    opt-in compressed wire — the explicit form of the gradient psums
    GSPMD inserts behind TP layouts, for driver loops that hold each
    device's local gradients (leaves stacked on a leading ``n`` dim).

    Returns the summed pytree (leading dim dropped, f32).  Byte
    accounting from static shapes at build time: uncompressed psums
    record the leaf dtype's ring all-reduce; a compressed wire records
    the staged-ring + quantized-gather bytes and publishes the
    ``path="tp"`` wire-savings ratio."""
    import jax
    from jax.sharding import PartitionSpec as P

    from bigdl_tpu.obs import collectives as C
    from bigdl_tpu.parallel import wire as W
    from bigdl_tpu.optim.distri_optimizer import _shard_map

    spec = W.resolve(wire)
    n = int(mesh.shape[axis])
    leaves = [x for x in jax.tree.leaves(grads) if x is not None]
    for leaf in leaves:
        if leaf.ndim < 1 or leaf.shape[0] != n:
            raise ValueError(
                f"gradient_psum leaves need a leading {axis!r}-sized "
                f"({n}) device dim; got shape {tuple(leaf.shape)}")
    if n > 1:
        baseline = wire_bytes = 0.0
        for leaf in leaves:
            sz = int(leaf.size) // n
            baseline += C.all_reduce_bytes(sz, leaf.dtype, n)
            if spec is None:
                wire_bytes += C.all_reduce_bytes(sz, leaf.dtype, n)
            elif not spec.scaled:
                wire_bytes += C.all_reduce_bytes(sz, "bfloat16", n)
            else:
                padded, blk = W.psum_layout(sz, spec, n)
                ex = C.staged_ring_exchange_bytes(padded, n, blk,
                                                  spec.wire_name)
                wire_bytes += sum(ex.values())
                wire_bytes += C.all_gather_bytes(padded, spec.wire_name,
                                                 n)
                wire_bytes += C.all_gather_bytes(padded // blk,
                                                 "float32", n)
        name = spec.wire_name if spec is not None else "float32"
        C.record("psum", name, wire_bytes, axis_size=n)
        if spec is not None:
            C.record_savings("tp", baseline, wire_bytes)

    flat, treedef = jax.tree.flatten(grads)
    if n == 1:
        import jax.numpy as jnp

        return jax.tree.unflatten(
            treedef, [jnp.sum(g.astype(jnp.float32), axis=0)
                      for g in flat])
    in_specs = tuple(P(*((axis,) + (None,) * (g.ndim - 1)))
                     for g in flat)
    out_specs = tuple(P() for _ in flat)

    def body(*ls):
        return tuple(W.psum(g[0], axis, n, spec)[0] for g in ls)

    # jit the mapped sum: the staged ring unrolls (n-1) compressed hops
    # per leaf, and dispatching that op-by-op through eager shard_map
    # costs orders of magnitude more wall clock than one compile (byte
    # accounting above is build-time Python — unaffected)
    mapped = jax.jit(_shard_map(body, mesh, in_specs=in_specs,
                                out_specs=out_specs))
    return jax.tree.unflatten(treedef, list(mapped(*flat)))


def constrain(x, mesh, *spec_axes):
    """`with_sharding_constraint` shorthand: constrain(x, mesh, 'data',
    None, 'model') pins activation layout where XLA's propagation needs
    the hint (typically the residual stream under dp×tp).

    Each call also accounts the constrained activation's bytes
    (``bigdl_collective_bytes_total{op="sharding_constraint"}``) — an
    upper bound on the reshard traffic the hint can force, recorded at
    trace time from the static shape (GSPMD may satisfy the hint with
    zero movement; the counter is the budget, not a measurement)."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    axes = [a for a in spec_axes if a is not None]
    if axes and any(int(mesh.shape[a]) > 1 for a in axes):
        from bigdl_tpu.obs import collectives as C

        C.record("sharding_constraint", x.dtype,
                 int(x.size) * C.dtype_bytes(x.dtype))
    return jax.lax.with_sharding_constraint(
        x, NamedSharding(mesh, P(*spec_axes))
    )
