"""Tensor parallelism, the GSPMD way.

No manual collectives: tensor parallelism on TPU is expressed by
*placing* parameters with `NamedSharding`s and (where XLA needs a hint)
`with_sharding_constraint` on activations; the compiler inserts the
all-gather / reduce-scatter pairs and overlaps them with MXU work.

`shard_params` walks a module's (nested-dict) param pytree and applies
the first matching (path-regex → PartitionSpec) rule.  Megatron-style
rules for the Transformer stack ship as `TRANSFORMER_TP_RULES`:

* attention wq/wk/wv: rows (output features = heads) split over
  `model` — each device computes its own heads;
* attention wo: columns split over `model` — the psum after the
  row-parallel matmul is the only cross-device hop per block;
* MLP in/out likewise column-then-row.

Weights here are (out_features, in_features), applied as `x @ W.T`
(torch convention, matching nn.Linear / nn.MultiHeadAttention).
"""

from __future__ import annotations

import re
from typing import Iterable, Tuple


# (path regex, spec builder) — specs as tuples of axis names / None;
# turned into PartitionSpec at apply time so this module imports cheap.
TRANSFORMER_TP_RULES: Tuple[Tuple[str, tuple], ...] = (
    (r"attn/w[qkv]$", ("model", None)),
    (r"attn/b[qkv]$", ("model",)),
    (r"attn/wo$", (None, "model")),
    # TransformerBlock MLP: fc1 column-parallel, fc2 row-parallel
    (r"fc1/weight$", ("model", None)),
    (r"fc1/bias$", ("model",)),
    (r"fc2/weight$", (None, "model")),
    # TransformerLM embeddings: split the feature dim
    (r"w[tp]e/weight$", (None, "model")),
    (r"head/weight$", ("model", None)),
)


def _walk(tree, prefix=""):
    if isinstance(tree, dict):
        for k, v in tree.items():
            yield from _walk(v, f"{prefix}/{k}" if prefix else str(k))
    elif tree is not None:
        yield prefix, tree


def _match(path: str, rules: Iterable[Tuple[str, tuple]]):
    for pat, spec in rules:
        if re.search(pat, path):
            return spec
    return None


def param_specs(params, mesh, rules=TRANSFORMER_TP_RULES):
    """Mirror of the param pytree with a PartitionSpec per leaf (P() —
    replicated — where no rule matches).  Feed to jit in_shardings or
    `shard_params`."""
    import jax
    from jax.sharding import PartitionSpec as P

    flat = {p: _match(p, rules) for p, _ in _walk(params)}

    def build(tree, prefix=""):
        if isinstance(tree, dict):
            return {
                k: build(v, f"{prefix}/{k}" if prefix else str(k))
                for k, v in tree.items()
            }
        if tree is None:
            return None
        spec = flat.get(prefix)
        # drop axes that don't divide the dim (GSPMD would error)
        if spec is not None:
            shape = tree.shape
            ok = all(
                a is None or (i < len(shape)
                              and shape[i] % mesh.shape[a] == 0)
                for i, a in enumerate(spec)
            )
            if ok:
                return P(*spec)
        return P()

    del jax
    return build(params)


def shard_params(params, mesh, rules=TRANSFORMER_TP_RULES):
    """device_put every param leaf onto the mesh per the rules.  Returns
    the sharded pytree (leaves are committed global arrays)."""
    import jax
    from jax.sharding import NamedSharding

    from bigdl_tpu.obs import collectives as C

    specs = param_specs(params, mesh, rules)
    # placement accounting (one-shot, static shapes): bytes of every
    # leaf that actually splits over a mesh axis — the initial
    # host->devices scatter the TP layout costs
    moved: dict = {}
    for (path, leaf), (_, spec) in zip(_walk(params), _walk(specs)):
        if spec is not None and any(a is not None for a in spec):
            name = str(leaf.dtype) if hasattr(leaf, "dtype") else "float32"
            moved[name] = moved.get(name, 0.0) + (
                int(leaf.size) * C.dtype_bytes(name))
    for name, nbytes in moved.items():
        C.record("tp_shard_params", name, nbytes)
    return jax.tree.map(
        lambda x, s: x if x is None else jax.device_put(
            x, NamedSharding(mesh, s)
        ),
        params, specs,
        is_leaf=lambda x: x is None or hasattr(x, "shape"),
    )


def constrain(x, mesh, *spec_axes):
    """`with_sharding_constraint` shorthand: constrain(x, mesh, 'data',
    None, 'model') pins activation layout where XLA's propagation needs
    the hint (typically the residual stream under dp×tp).

    Each call also accounts the constrained activation's bytes
    (``bigdl_collective_bytes_total{op="sharding_constraint"}``) — an
    upper bound on the reshard traffic the hint can force, recorded at
    trace time from the static shape (GSPMD may satisfy the hint with
    zero movement; the counter is the budget, not a measurement)."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    axes = [a for a in spec_axes if a is not None]
    if axes and any(int(mesh.shape[a]) > 1 for a in axes):
        from bigdl_tpu.obs import collectives as C

        C.record("sharding_constraint", x.dtype,
                 int(x.size) * C.dtype_bytes(x.dtype))
    return jax.lax.with_sharding_constraint(
        x, NamedSharding(mesh, P(*spec_axes))
    )
