"""Expert parallelism — a GShard-style Mixture-of-Experts layer.

New capability (nothing comparable in the reference; the nearest
relative is `MixtureTable`'s dense gating).  TPU-first design:

* routing is expressed as dense one-hot dispatch/combine einsums —
  static shapes, no gather/scatter, so XLA tiles everything onto the
  MXU and turns the (tokens ↔ expert-buffer) contractions into
  `all_to_all`s when the expert dim is sharded over a mesh axis;
* top-1 (switch) or top-2 routing with a capacity factor: each expert
  processes at most C = ceil(cap·S·k/E) tokens, overflow tokens fall
  through the residual (standard switch-transformer semantics);
* an auxiliary load-balancing loss (mean gate prob × mean token
  fraction per expert, scaled by E) is exposed via `aux_loss` from the
  last forward.

With `mesh` given, expert-indexed buffers are sharding-constrained to
P('expert', ...) so each device owns E/n experts.
"""

from __future__ import annotations

import math
from typing import Optional

import numpy as np

from bigdl_tpu.nn.module import AbstractModule
from bigdl_tpu.nn.layers import Xavier, _to_device


def _jnp():
    import jax.numpy as jnp

    return jnp


class MoE(AbstractModule):
    """Token-routed FFN bank: (B, T, D) -> (B, T, D)."""

    param_names = ("gate", "w_in", "b_in", "w_out", "b_out")

    def __init__(self, dim: int, hidden: int, n_experts: int,
                 top_k: int = 1, capacity_factor: float = 1.25,
                 mesh=None, expert_axis: str = "expert",
                 aux_loss_weight: float = 0.01, wire=None):
        super().__init__()
        if top_k not in (1, 2):
            raise ValueError("top_k must be 1 or 2")
        self._config = dict(dim=dim, hidden=hidden, n_experts=n_experts,
                            top_k=top_k, capacity_factor=capacity_factor,
                            aux_loss_weight=aux_loss_weight)
        self.dim = dim
        self.hidden = hidden
        self.n_experts = n_experts
        self.top_k = top_k
        self.capacity_factor = capacity_factor
        self.mesh = mesh
        self.expert_axis = expert_axis
        self.aux_loss_weight = aux_loss_weight
        # opt-in compressed wire for the dispatch/combine all_to_all
        # pair (parallel/wire.py WireSpec or dtype string); like mesh,
        # a runtime-placement knob — not part of the serialized config
        from bigdl_tpu.parallel import wire as W

        self.wire = W.resolve(wire)
        self._init_method = Xavier()
        self.reset()

    def reset(self):
        from bigdl_tpu.common import RandomGenerator

        e, d, h = self.n_experts, self.dim, self.hidden
        rng = RandomGenerator.RNG
        # gate: (D, E); experts: batched FFN weights
        self.gate = _to_device(
            rng.normal(0.0, math.sqrt(1.0 / d), (d, e)).astype(np.float32)
        )
        self.w_in = _to_device(
            rng.normal(0.0, math.sqrt(2.0 / d), (e, d, h)).astype(np.float32)
        )
        self.b_in = _to_device(np.zeros((e, h), np.float32))
        self.w_out = _to_device(
            rng.normal(0.0, math.sqrt(1.0 / h), (e, h, d)).astype(np.float32)
        )
        self.b_out = _to_device(np.zeros((e, d), np.float32))
        return self

    def _constrain(self, x, *spec):
        if self.mesh is None:
            return x
        from bigdl_tpu.parallel.tensor_parallel import constrain

        return constrain(x, self.mesh, *spec)

    def update_output_pure(self, params, input, *, training=False, rng=None):
        y, _ = self.forward_with_aux(params, input, training=training,
                                     rng=rng)
        return y

    def forward_with_aux(self, params, input, *, training=False, rng=None):
        """Forward returning ``(output, aux_loss)``.  Use this inside a
        jitted training loss to add the load-balancing term — the aux
        loss is a traced value and must flow through the return, never
        through module attributes."""
        import jax
        jnp = _jnp()

        b, t, d = input.shape
        s = b * t
        e = self.n_experts
        cap = max(1, int(math.ceil(
            self.capacity_factor * s * self.top_k / e
        )))
        x = input.reshape(s, d)

        # the dtype that actually crosses the expert all_to_all: the
        # (E, C, D) buffers are cast to the activation dtype at the
        # exchange boundary (a bf16 model must not be billed — or
        # shipped — at f32 width)
        buf_dtype = input.dtype
        n_exp = 1
        if self.mesh is not None and self.expert_axis in getattr(
                self.mesh, "shape", {}):
            n_exp = int(self.mesh.shape[self.expert_axis])
        if n_exp > 1:
            from bigdl_tpu.obs import collectives as C
            from bigdl_tpu.parallel import wire as W

            # static-shape accounting (trace time): with the expert
            # dim sharded, XLA lowers the dispatch and combine
            # contractions into an all_to_all pair over the (E, C, D)
            # expert buffers
            baseline = 2 * C.all_to_all_bytes(e * cap * d, buf_dtype,
                                              n_exp)
            if self.wire is None:
                C.record("all_to_all", buf_dtype, baseline,
                         axis_size=n_exp)
            elif not self.wire.scaled:  # bfloat16 cast-only wire
                moved = 2 * C.all_to_all_bytes(e * cap * d, "bfloat16",
                                               n_exp)
                C.record("all_to_all", self.wire.wire_name, moved,
                         axis_size=n_exp)
                C.record_savings("moe", baseline, moved)
            else:
                # per-destination slice of the buffer, blocked to the
                # wire quantum the quantizer actually uses
                blk = W.effective_block(e * cap * d // n_exp,
                                        self.wire.block)
                payload = 2 * C.all_to_all_bytes(
                    e * cap * d, self.wire.wire_name, n_exp)
                scales = 2 * C.all_to_all_bytes(
                    e * cap * d // blk, "float32", n_exp)
                C.record("all_to_all", self.wire.wire_name, payload,
                         axis_size=n_exp)
                C.record("all_to_all", "float32", scales,
                         axis_size=n_exp)
                C.record_savings("moe", baseline, payload + scales)

        logits = x @ params["gate"]                     # (S, E)
        probs = jax.nn.softmax(logits, axis=-1)

        # --- top-k expert choice -------------------------------------
        dispatch = jnp.zeros((s, e, cap), input.dtype)
        combine = jnp.zeros((s, e, cap), jnp.float32)
        masked_probs = probs
        aux_frac = jnp.zeros((e,), jnp.float32)
        # slots already consumed in each expert's buffer by earlier
        # routing iterations — without this, a 2nd-choice token and a
        # 1st-choice token of the same expert land in the same slot
        slot_base = jnp.zeros((e,), jnp.float32)
        for _ in range(self.top_k):
            choice = jnp.argmax(masked_probs, axis=-1)          # (S,)
            onehot = jax.nn.one_hot(choice, e, dtype=jnp.float32)
            # position of each token within its expert's buffer
            pos = (jnp.cumsum(onehot, axis=0) - onehot) + slot_base
            pos_tok = jnp.sum(pos * onehot, axis=-1)            # (S,)
            keep = pos_tok < cap
            gatep = jnp.sum(probs * onehot, axis=-1) * keep     # (S,)
            poh = jax.nn.one_hot(pos_tok.astype(jnp.int32), cap,
                                 dtype=jnp.float32)
            d1 = onehot[:, :, None] * poh[:, None, :] * keep[:, None, None]
            dispatch = dispatch + d1.astype(input.dtype)
            combine = combine + gatep[:, None, None] * d1
            aux_frac = aux_frac + jnp.mean(onehot, axis=0)
            slot_base = slot_base + jnp.sum(onehot, axis=0)
            masked_probs = masked_probs * (1.0 - onehot)

        # load-balance aux loss (switch transformer eq. 4)
        aux_loss = self.aux_loss_weight * e * jnp.sum(
            aux_frac / self.top_k * jnp.mean(probs, axis=0)
        )

        # --- dispatch → expert FFN → combine -------------------------
        # the (E, C, D) buffers cross the expert all_to_all in the
        # activation dtype; with a wire configured, the compressed
        # roundtrip (custom_vjp — the cotangent is compressed too)
        # applies the quantization the payload would carry
        def exchange(buf):
            buf = buf.astype(buf_dtype)
            if self.wire is not None and n_exp > 1:
                from bigdl_tpu.parallel import wire as W

                buf = W.roundtrip(buf, self.wire)
            return self._constrain(buf, self.expert_axis, None, None)

        xin = exchange(jnp.einsum("sec,sd->ecd", dispatch, x,
                                  preferred_element_type=jnp.float32))
        h = jax.nn.relu(
            jnp.einsum("ecd,edh->ech", xin, params["w_in"],
                       preferred_element_type=jnp.float32)
            + params["b_in"][:, None, :]
        )
        out = jnp.einsum("ech,ehd->ecd", h, params["w_out"],
                         preferred_element_type=jnp.float32) \
            + params["b_out"][:, None, :]
        out = exchange(out)
        y = jnp.einsum("sec,ecd->sd", combine, out,
                       preferred_element_type=jnp.float32)
        # renormalize top-2 so kept gates sum to 1 (dropped → residual 0)
        if self.top_k > 1:
            gsum = jnp.sum(combine, axis=(1, 2))
            y = y / jnp.maximum(gsum, 1e-9)[:, None]
        return y.astype(input.dtype).reshape(b, t, d), aux_loss

    def __repr__(self):
        return (f"MoE(dim={self.dim}, hidden={self.hidden}, "
                f"experts={self.n_experts}, top_k={self.top_k})")
