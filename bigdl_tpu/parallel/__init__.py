"""bigdl_tpu.parallel — parallelism strategies over a jax.sharding.Mesh.

The reference implements synchronous data parallelism only (SURVEY.md
§2.4): its `AllReduceParameter` push/pull over Spark BlockManager is
reduce-scatter + all-gather, which `optim.DistriOptimizer` reproduces
natively with `psum_scatter`/`all_gather` inside one jitted shard_map.

This package holds everything BEYOND the reference's data parallelism —
the TPU-first capabilities the mesh seams were left open for:

* `ring` — ring attention (sequence/context parallelism): the sequence
  axis is sharded over devices; K/V blocks rotate around the ICI ring
  via `ppermute` while an online-softmax accumulator keeps the
  attention exact.  Long-context training scales linearly in devices.
* `ulysses` — all-to-all sequence parallelism (DeepSpeed-Ulysses
  pattern): attention reshards seq->heads so the dense kernel runs
  unchanged; the better deal when n_head >= n_devices.
* `tensor_parallel` — GSPMD-style tensor parallelism: parameter
  PartitionSpec rules + `with_sharding_constraint` helpers.  No manual
  collectives; XLA inserts all-gathers/reduce-scatters from the
  shardings.
* `pipeline` — collective-permute pipeline parallelism over identical
  stages (scan over microbatches, activations hop stage-to-stage on
  the ring).
* `moe` — expert parallelism: GShard-style dense dispatch/combine
  einsums with the expert axis sharded over the mesh (all_to_all falls
  out of GSPMD).
* `wire` — the compressed-collective layer (quantized collectives v2):
  one WireSpec (bfloat16/int8/fp8 + blockwise scales + error feedback)
  behind reduce_scatter / psum / all_to_all / ppermute, used by
  DistriOptimizer's gradient exchange and the opt-in compressed wires
  on the TP/MoE/ring paths above.

All strategies compose with DistriOptimizer's data axis by adding axes
to `Engine.build_mesh({"data": ..., "seq": ..., "model": ...})`.
"""

from bigdl_tpu.parallel.ring import (  # noqa: F401
    ring_attention,
    ring_attention_sharded,
    RingMultiHeadAttention,
)
from bigdl_tpu.parallel.tensor_parallel import (  # noqa: F401
    shard_params,
    constrain,
    param_specs,
    gradient_psum,
    wire_psum,
    TRANSFORMER_TP_RULES,
)
from bigdl_tpu.parallel import wire  # noqa: F401
from bigdl_tpu.parallel.wire import WireSpec  # noqa: F401
from bigdl_tpu.parallel.pipeline import (  # noqa: F401
    pipeline_apply,
    pipelined,
)
from bigdl_tpu.parallel.ulysses import (  # noqa: F401
    ulysses_attention,
    ulysses_attention_sharded,
    UlyssesMultiHeadAttention,
)
from bigdl_tpu.parallel.moe import MoE  # noqa: F401
