"""bigdl_tpu.transform — feature transform pipelines."""
