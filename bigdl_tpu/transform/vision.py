"""Vision transforms — ImageFrame / ImageFeature + augmentations.

Rebuild of «bigdl»/transform/vision/image/ (SURVEY.md §2.1 "Vision
transforms"): ImageFrame (local/distributed), ImageFeature (the mutable
record flowing through the pipeline), and the OpenCV-backed augmentation
ops (Resize, RandomCrop, CenterCrop, HFlip, ChannelNormalize,
RandomTransformer, MatToTensor...).

The OpenCV native library (SURVEY.md §2.3) is replaced by host-side
numpy + PIL when available (bilinear resize falls back to a pure-numpy
implementation otherwise).  Decode/augment stays on host CPU feeding the
device — the same division of labor as the reference (executors decode
on CPU cores, the device does the math).

Layout convention: ImageFeature holds HWC uint8/float arrays like the
reference's OpenCVMat; MatToTensor emits CHW float32 (the NCHW model
input).
"""

from __future__ import annotations

from typing import Callable, Dict, List, Optional, Sequence

import numpy as np

from bigdl_tpu.common import RandomGenerator


class ImageFeature(dict):
    """«bigdl»/transform/vision/image/ImageFeature.scala — a dict of
    named slots (bytes/mat/label/path/...) mutated along the pipeline."""

    MAT = "mat"          # HWC float/uint8 numpy array
    LABEL = "label"
    URI = "uri"
    SAMPLE = "sample"

    def __init__(self, image=None, label=None, uri=None):
        super().__init__()
        if image is not None:
            self[self.MAT] = np.asarray(image)
        if label is not None:
            self[self.LABEL] = label
        if uri is not None:
            self[self.URI] = uri

    @property
    def image(self):
        return self.get(self.MAT)


class FeatureTransformer:
    """«bigdl» FeatureTransformer — composable ImageFeature ->
    ImageFeature stage; ``>>`` chains (reference ``->``)."""

    def transform(self, feature: ImageFeature) -> ImageFeature:
        raise NotImplementedError

    def __call__(self, features):
        if isinstance(features, ImageFeature):
            return self.transform(features)
        return (self.transform(f) for f in features)

    def __rshift__(self, other: "FeatureTransformer"):
        return _ChainedFeature(self, other)


class _ChainedFeature(FeatureTransformer):
    def __init__(self, a, b):
        self.a, self.b = a, b

    def transform(self, feature):
        return self.b.transform(self.a.transform(feature))


def write_bmp(path: str, arr: np.ndarray):
    """Write an HWC uint8 RGB array as an uncompressed 24-bit BMP using
    only the stdlib + numpy — the fixture writer that lets the
    image-pipeline tests run 0-skip on containers without Pillow (the
    decode side is :func:`read_bmp`; PIL keeps handling everything
    else)."""
    import struct

    arr = np.ascontiguousarray(np.asarray(arr, np.uint8))
    if arr.ndim != 3 or arr.shape[2] != 3:
        raise ValueError(f"write_bmp wants HWC RGB, got {arr.shape}")
    h, w = arr.shape[:2]
    pad = (-w * 3) % 4          # BMP rows are 4-byte aligned
    rows = arr[::-1, :, ::-1]   # bottom-up, BGR
    body = bytearray()
    zeros = b"\x00" * pad
    for row in rows:
        body += row.tobytes() + zeros
    header = struct.pack("<2sIHHI", b"BM", 54 + len(body), 0, 0, 54)
    header += struct.pack("<IiiHHIIiiII", 40, w, h, 1, 24, 0,
                          len(body), 2835, 2835, 0, 0)
    with open(path, "wb") as fh:
        fh.write(header + bytes(body))


def read_bmp(path: str) -> np.ndarray:
    """Decode an uncompressed 24/32-bit BMP to an HWC uint8 RGB array
    with only the stdlib + numpy (the PIL-less fallback for
    :func:`write_bmp` fixtures and any plain BMP input)."""
    import struct

    with open(path, "rb") as fh:
        data = fh.read()
    if data[:2] != b"BM":
        raise ValueError(f"{path!r} is not a BMP file")
    pixel_off = struct.unpack_from("<I", data, 10)[0]
    hdr_size = struct.unpack_from("<I", data, 14)[0]
    if hdr_size < 40:
        raise ValueError(f"unsupported BMP core header in {path!r}")
    w, h = struct.unpack_from("<ii", data, 18)
    planes, bpp = struct.unpack_from("<HH", data, 26)
    compression = struct.unpack_from("<I", data, 30)[0]
    if planes != 1 or compression != 0 or bpp not in (24, 32):
        raise ValueError(
            f"unsupported BMP variant in {path!r} (bpp={bpp}, "
            f"compression={compression}) — only uncompressed 24/32-bit")
    flipped = h > 0
    h = abs(h)
    nchan = bpp // 8
    stride = (w * nchan + 3) & ~3
    rows = np.frombuffer(
        data, np.uint8, count=h * stride, offset=pixel_off
    ).reshape(h, stride)[:, : w * nchan].reshape(h, w, nchan)
    if flipped:
        rows = rows[::-1]
    return np.ascontiguousarray(rows[..., 2::-1])  # BGR(A) -> RGB


def read_image(path: str) -> np.ndarray:
    """File -> HWC uint8 RGB: PIL when present (every format), the
    numpy BMP reader otherwise — so a bare container can still feed
    the image pipeline real pixels."""
    try:
        from PIL import Image
    except ImportError:
        if path.lower().endswith(".bmp"):
            return read_bmp(path)
        raise ImportError(
            f"decoding {path!r} needs Pillow (only .bmp decodes "
            "without it)")
    with Image.open(path) as im:
        return np.asarray(im.convert("RGB"))


def _resize_bilinear(img: np.ndarray, oh: int, ow: int) -> np.ndarray:
    """Pure-numpy bilinear resize (HWC), replacing the OpenCV JNI path."""
    try:
        from PIL import Image

        if img.dtype != np.uint8:
            # PIL float path: per-channel
            chans = [
                np.asarray(
                    Image.fromarray(img[..., c].astype(np.float32), mode="F")
                    .resize((ow, oh), Image.BILINEAR)
                )
                for c in range(img.shape[-1])
            ]
            return np.stack(chans, axis=-1)
        pil = Image.fromarray(img)
        return np.asarray(pil.resize((ow, oh), Image.BILINEAR))
    except ImportError:
        pass
    from bigdl_tpu import native as _native

    if _native.available() and img.ndim == 3:
        chw = np.ascontiguousarray(img.astype(np.float32).transpose(2, 0, 1))
        out = _native.resize_bilinear(chw, oh, ow)
        return out.transpose(1, 2, 0)
    h, w = img.shape[:2]
    ys = np.linspace(0, h - 1, oh)
    xs = np.linspace(0, w - 1, ow)
    y0 = np.floor(ys).astype(int)
    x0 = np.floor(xs).astype(int)
    y1 = np.minimum(y0 + 1, h - 1)
    x1 = np.minimum(x0 + 1, w - 1)
    wy = (ys - y0)[:, None, None]
    wx = (xs - x0)[None, :, None]
    img = img.astype(np.float32)
    top = img[y0][:, x0] * (1 - wx) + img[y0][:, x1] * wx
    bot = img[y1][:, x0] * (1 - wx) + img[y1][:, x1] * wx
    return top * (1 - wy) + bot * wy


class Resize(FeatureTransformer):
    """«bigdl» Resize.scala"""

    def __init__(self, resize_h: int, resize_w: int):
        self.resize_h, self.resize_w = resize_h, resize_w

    def transform(self, feature):
        img = feature.image
        feature[ImageFeature.MAT] = _resize_bilinear(
            img, self.resize_h, self.resize_w
        )
        return feature


class AspectScale(FeatureTransformer):
    """«bigdl» AspectScale — resize the short edge to ``scale``."""

    def __init__(self, scale: int, max_size: int = 1000):
        self.scale, self.max_size = scale, max_size

    def transform(self, feature):
        img = feature.image
        h, w = img.shape[:2]
        short, long = min(h, w), max(h, w)
        ratio = self.scale / short
        if long * ratio > self.max_size:
            ratio = self.max_size / long
        feature[ImageFeature.MAT] = _resize_bilinear(
            img, int(round(h * ratio)), int(round(w * ratio))
        )
        return feature


class CenterCrop(FeatureTransformer):
    """«bigdl» CenterCrop.scala"""

    def __init__(self, crop_width: int, crop_height: int):
        self.cw, self.ch = crop_width, crop_height

    def transform(self, feature):
        img = feature.image
        h, w = img.shape[:2]
        y = (h - self.ch) // 2
        x = (w - self.cw) // 2
        feature[ImageFeature.MAT] = img[y : y + self.ch, x : x + self.cw]
        return feature


class RandomCrop(FeatureTransformer):
    """«bigdl» RandomCrop.scala"""

    def __init__(self, crop_width: int, crop_height: int):
        self.cw, self.ch = crop_width, crop_height

    def transform(self, feature):
        img = feature.image
        h, w = img.shape[:2]
        y = int(RandomGenerator.RNG.randint(0, max(1, h - self.ch + 1)))
        x = int(RandomGenerator.RNG.randint(0, max(1, w - self.cw + 1)))
        feature[ImageFeature.MAT] = img[y : y + self.ch, x : x + self.cw]
        return feature


class HFlip(FeatureTransformer):
    """«bigdl» HFlip.scala — unconditional horizontal flip."""

    def transform(self, feature):
        feature[ImageFeature.MAT] = feature.image[:, ::-1]
        return feature


class RandomHFlip(FeatureTransformer):
    """«bigdl» RandomTransformer(HFlip, p)"""

    def __init__(self, p: float = 0.5):
        self.p = p

    def transform(self, feature):
        if RandomGenerator.RNG.uniform(0, 1) < self.p:
            feature[ImageFeature.MAT] = feature.image[:, ::-1]
        return feature


class ChannelNormalize(FeatureTransformer):
    """«bigdl» ChannelNormalize.scala — per-channel (x - mean) / std."""

    def __init__(self, mean_r, mean_g, mean_b, std_r=1.0, std_g=1.0, std_b=1.0):
        self.mean = np.array([mean_r, mean_g, mean_b], np.float32)
        self.std = np.array([std_r, std_g, std_b], np.float32)

    def transform(self, feature):
        img = feature.image.astype(np.float32)
        feature[ImageFeature.MAT] = (img - self.mean) / self.std
        return feature


class ChannelScaledNormalizer(FeatureTransformer):
    """«bigdl» ChannelScaledNormalizer — mean-subtract + global scale."""

    def __init__(self, mean_r, mean_g, mean_b, scale: float):
        self.mean = np.array([mean_r, mean_g, mean_b], np.float32)
        self.scale = scale

    def transform(self, feature):
        img = feature.image.astype(np.float32)
        feature[ImageFeature.MAT] = (img - self.mean) * self.scale
        return feature


class PixelNormalizer(FeatureTransformer):
    """«bigdl» PixelNormalizer — subtract a full mean image."""

    def __init__(self, means: np.ndarray):
        self.means = np.asarray(means, np.float32)

    def transform(self, feature):
        feature[ImageFeature.MAT] = feature.image.astype(np.float32) - self.means
        return feature


class Brightness(FeatureTransformer):
    """«bigdl» Brightness.scala — random delta in [delta_low, delta_high]."""

    def __init__(self, delta_low: float, delta_high: float):
        self.lo, self.hi = delta_low, delta_high

    def transform(self, feature):
        delta = RandomGenerator.RNG.uniform(self.lo, self.hi)
        feature[ImageFeature.MAT] = feature.image.astype(np.float32) + delta
        return feature


class Contrast(FeatureTransformer):
    """«bigdl» Contrast.scala"""

    def __init__(self, delta_low: float, delta_high: float):
        self.lo, self.hi = delta_low, delta_high

    def transform(self, feature):
        f = RandomGenerator.RNG.uniform(self.lo, self.hi)
        feature[ImageFeature.MAT] = feature.image.astype(np.float32) * f
        return feature


class Saturation(FeatureTransformer):
    """«bigdl» Saturation.scala — scale distance from the grey image."""

    def __init__(self, delta_low: float, delta_high: float):
        self.lo, self.hi = delta_low, delta_high

    def transform(self, feature):
        f = RandomGenerator.RNG.uniform(self.lo, self.hi)
        img = feature.image.astype(np.float32)
        grey = img.mean(axis=-1, keepdims=True)
        feature[ImageFeature.MAT] = grey + (img - grey) * f
        return feature


class ColorJitter(FeatureTransformer):
    """«bigdl» ColorJitter.scala — random brightness/contrast/saturation
    in random order."""

    def __init__(self, brightness=32.0, contrast=0.5, saturation=0.5):
        self.ops = [
            Brightness(-brightness, brightness),
            Contrast(1 - contrast, 1 + contrast),
            Saturation(1 - saturation, 1 + saturation),
        ]

    def transform(self, feature):
        order = RandomGenerator.RNG.randperm(len(self.ops))
        for i in order:
            feature = self.ops[i].transform(feature)
        return feature


class Hue(FeatureTransformer):
    """«bigdl» Hue.scala — rotate the hue channel by a random delta in
    [delta_low, delta_high] degrees (detection-era color aug)."""

    def __init__(self, delta_low: float = -18.0, delta_high: float = 18.0):
        self.lo, self.hi = delta_low, delta_high

    @staticmethod
    def _rgb_to_hsv(img):
        img = img.astype(np.float32)
        mx = img.max(-1)
        mn = img.min(-1)
        diff = mx - mn
        r, g, b = img[..., 0], img[..., 1], img[..., 2]
        h = np.zeros_like(mx)
        mask = diff > 0
        rmax = mask & (mx == r)
        gmax = mask & (mx == g) & ~rmax
        bmax = mask & ~rmax & ~gmax
        h[rmax] = (60 * (g - b)[rmax] / diff[rmax]) % 360
        h[gmax] = 60 * (b - r)[gmax] / diff[gmax] + 120
        h[bmax] = 60 * (r - g)[bmax] / diff[bmax] + 240
        s = np.where(mx > 0, diff / np.maximum(mx, 1e-12), 0.0)
        return h, s, mx

    @staticmethod
    def _hsv_to_rgb(h, s, v):
        h = (h % 360) / 60.0
        i = np.floor(h).astype(np.int32)
        f = h - i
        p = v * (1 - s)
        q = v * (1 - s * f)
        t = v * (1 - s * (1 - f))
        i = i % 6
        r = np.choose(i, [v, q, p, p, t, v])
        g = np.choose(i, [t, v, v, q, p, p])
        b = np.choose(i, [p, p, t, v, v, q])
        return np.stack([r, g, b], axis=-1)

    def transform(self, feature):
        delta = RandomGenerator.RNG.uniform(self.lo, self.hi)
        h, s, v = self._rgb_to_hsv(feature.image)
        feature[ImageFeature.MAT] = self._hsv_to_rgb(h + delta, s, v)
        return feature


class Expand(FeatureTransformer):
    """«bigdl» Expand.scala — place the image at a random offset on a
    larger mean-filled canvas (SSD-style zoom-out augmentation)."""

    def __init__(self, means_r: float = 123.0, means_g: float = 117.0,
                 means_b: float = 104.0, min_expand_ratio: float = 1.0,
                 max_expand_ratio: float = 4.0):
        self.means = np.array([means_r, means_g, means_b], np.float32)
        self.lo, self.hi = min_expand_ratio, max_expand_ratio

    def transform(self, feature):
        img = feature.image.astype(np.float32)
        h, w = img.shape[:2]
        ratio = RandomGenerator.RNG.uniform(self.lo, self.hi)
        oh, ow = int(h * ratio), int(w * ratio)
        y = int(RandomGenerator.RNG.uniform(0, max(1, oh - h)))
        x = int(RandomGenerator.RNG.uniform(0, max(1, ow - w)))
        canvas = np.tile(self.means, (oh, ow, 1)).astype(np.float32)
        canvas[y:y + h, x:x + w] = img
        feature[ImageFeature.MAT] = canvas
        return feature


class FixedCrop(FeatureTransformer):
    """«bigdl» FixedCrop.scala — crop a fixed bbox (x1, y1, x2, y2);
    ``normalized`` coords are fractions of width/height."""

    def __init__(self, x1: float, y1: float, x2: float, y2: float,
                 normalized: bool = True):
        self.box = (x1, y1, x2, y2)
        self.normalized = normalized

    def transform(self, feature):
        img = feature.image
        h, w = img.shape[:2]
        x1, y1, x2, y2 = self.box
        if self.normalized:
            x1, x2 = x1 * w, x2 * w
            y1, y2 = y1 * h, y2 * h
        x1, y1 = max(0, int(round(x1))), max(0, int(round(y1)))
        x2, y2 = min(w, int(round(x2))), min(h, int(round(y2)))
        feature[ImageFeature.MAT] = img[y1:y2, x1:x2]
        return feature


class RandomAspectScale(FeatureTransformer):
    """«bigdl» RandomAspectScale.scala — AspectScale with the short-edge
    target drawn from ``scales``."""

    def __init__(self, scales: Sequence[int], scale_multiple_of: int = 1,
                 max_size: int = 1000):
        self.scales = list(scales)
        self.mult = scale_multiple_of
        self.max_size = max_size

    def transform(self, feature):
        pick = self.scales[
            int(RandomGenerator.RNG.randint(0, len(self.scales)))]
        img = feature.image
        h, w = img.shape[:2]
        short, long = min(h, w), max(h, w)
        ratio = pick / short
        if long * ratio > self.max_size:
            ratio = self.max_size / long
        oh, ow = int(round(h * ratio)), int(round(w * ratio))
        if self.mult > 1:
            oh = -(-oh // self.mult) * self.mult
            ow = -(-ow // self.mult) * self.mult
        feature[ImageFeature.MAT] = _resize_bilinear(img, oh, ow)
        return feature


class ChannelOrder(FeatureTransformer):
    """«bigdl» ChannelOrder.scala — swap RGB <-> BGR."""

    def transform(self, feature):
        feature[ImageFeature.MAT] = feature.image[..., ::-1]
        return feature


class RandomTransformer(FeatureTransformer):
    """«bigdl» RandomTransformer.scala — apply ``inner`` with
    probability ``p``."""

    def __init__(self, inner: FeatureTransformer, p: float = 0.5):
        self.inner, self.p = inner, p

    def transform(self, feature):
        if RandomGenerator.RNG.uniform(0, 1) < self.p:
            return self.inner.transform(feature)
        return feature


class MatToTensor(FeatureTransformer):
    """«bigdl» MatToTensor.scala — HWC -> CHW float32 model input."""

    def __init__(self, to_rgb: bool = False):
        self.to_rgb = to_rgb

    def transform(self, feature):
        img = feature.image.astype(np.float32)
        if self.to_rgb:
            img = img[..., ::-1]
        feature[ImageFeature.SAMPLE] = np.ascontiguousarray(
            np.transpose(img, (2, 0, 1))
        )
        return feature


class ImageFrameToSample(FeatureTransformer):
    """«bigdl» ImageFrameToSample.scala — wrap tensor+label as a Sample."""

    def transform(self, feature):
        from bigdl_tpu.dataset import Sample

        tensor = feature.get(ImageFeature.SAMPLE)
        if tensor is None:
            tensor = np.transpose(feature.image.astype(np.float32), (2, 0, 1))
        label = feature.get(ImageFeature.LABEL, np.zeros(1, np.float32))
        label = np.atleast_1d(np.asarray(label, np.float32))
        feature[ImageFeature.SAMPLE] = Sample(tensor, label)
        return feature


class ImageFrame:
    """«bigdl» ImageFrame — a collection of ImageFeatures with
    ``transform`` (reference LocalImageFrame).  See
    :class:`DistributedImageFrame` for the RDD-of-features analogue."""

    def __init__(self, features: Sequence[ImageFeature]):
        self.features = list(features)

    @staticmethod
    def read(arrays, labels=None):
        """Build from in-memory HWC arrays (the reference reads files /
        bytes through OpenCV decode; file decode is PIL-backed when
        paths are given)."""
        feats = []
        for i, a in enumerate(arrays):
            if isinstance(a, str):
                from PIL import Image

                a = np.asarray(Image.open(a).convert("RGB"))
            feats.append(
                ImageFeature(a, None if labels is None else labels[i])
            )
        return ImageFrame(feats)

    def transform(self, transformer: FeatureTransformer):
        self.features = [transformer.transform(f) for f in self.features]
        return self

    def __len__(self):
        return len(self.features)

    def to_samples(self):
        return [f[ImageFeature.SAMPLE] for f in self.features]

    def to_dataset(self, batch_size: int = 32):
        """Bridge into the training pipeline."""
        from bigdl_tpu.dataset.dataset import SampleDataSet

        self.transform(ImageFrameToSample())
        return SampleDataSet(self.to_samples(), batch_size)


class DistributedImageFrame(ImageFrame):
    """«bigdl» DistributedImageFrame — the RDD-of-ImageFeatures variant.

    TPU-native mapping: each PROCESS holds only its own shard of the
    file list / array list (the reference's executors cache their RDD
    partition); transforms run on the local shard, and ``to_dataset``
    yields per-process batch slices that DistriOptimizer assembles into
    global device arrays via ``jax.make_array_from_process_local_data``
    — no host ever materialises the full epoch.

    ``read`` shards a global list of paths/arrays round-robin by
    ``process_id``; pass explicit ``process_id``/``num_processes`` for
    tests, defaults read ``jax.process_index()/process_count()``.
    """

    def __init__(self, features: Sequence[ImageFeature],
                 process_id: Optional[int] = None,
                 num_processes: Optional[int] = None,
                 global_size: Optional[int] = None):
        """``features`` is THIS process's local shard.  ``global_size``
        (total across processes) coordinates the per-epoch batch count
        so unequal shards never desynchronise the collective; when
        omitted it is estimated as balanced (shard * nproc)."""
        super().__init__(features)
        pid, nproc = self._world(process_id, num_processes)
        self._pid = pid
        self._nproc = nproc
        self._global_n = global_size if global_size is not None \
            else len(self.features) * nproc

    @staticmethod
    def _world(process_id, num_processes):
        if process_id is not None and num_processes is not None:
            return process_id, num_processes
        import jax

        return jax.process_index(), jax.process_count()

    @staticmethod
    def read(arrays, labels=None, process_id: Optional[int] = None,
             num_processes: Optional[int] = None):
        """Shard a GLOBAL list of paths/arrays: this process keeps
        every ``num_processes``-th entry starting at ``process_id``
        (deterministic, balanced like the reference's coalesce)."""
        pid, nproc = DistributedImageFrame._world(process_id, num_processes)
        feats = []
        for i in range(pid, len(arrays), nproc):
            a = arrays[i]
            if isinstance(a, str):
                from PIL import Image

                a = np.asarray(Image.open(a).convert("RGB"))
            feats.append(
                ImageFeature(a, None if labels is None else labels[i])
            )
        return DistributedImageFrame(feats, process_id=pid,
                                     num_processes=nproc,
                                     global_size=len(arrays))

    def to_dataset(self, batch_size: int = 32):
        """Per-process dataset over the local shard: yields this
        process's slice of every global batch (the iterator contract
        DistriOptimizer's multi-host path expects).  Every process
        yields the SAME number of batches (derived from global_size),
        so unequal shards cannot desynchronise the collective."""
        self.transform(ImageFrameToSample())
        samples = self.to_samples()
        feats = np.stack([np.asarray(s.features) for s in samples])
        labels = np.stack(
            [np.asarray(s.labels).reshape(-1)[0] for s in samples])
        return _LocalShardDataSet(feats, labels, batch_size,
                                  num_processes=self._nproc,
                                  global_size=self._global_n)


class _LocalShardDataSet:
    """Dataset over an ALREADY-SHARDED local slice: yields local
    sub-batches directly (the shard was taken at read time), flagged
    ``per_process`` so DistriOptimizer uses
    ``make_array_from_process_local_data``.  The per-epoch batch count
    comes from the GLOBAL minimum shard size (global_size // nproc) —
    identical on every process, so no process is left waiting inside a
    collective while another's iterator is exhausted."""

    per_process = True

    def __init__(self, features, labels, batch_size: int = 32,
                 shuffle: bool = True, num_processes: int = 1,
                 global_size: Optional[int] = None):
        self.features = np.asarray(features)
        self.labels = np.asarray(labels)
        self.batch_size = batch_size
        self.shuffle = shuffle
        self._n = len(self.features)
        self._nproc = max(1, num_processes)
        self._global_n = global_size if global_size is not None \
            else self._n * self._nproc

    def size(self):
        return self._global_n

    def data(self, train: bool = True):
        local_bs = max(1, self.batch_size // self._nproc)
        min_shard = self._global_n // self._nproc
        n_batches = min_shard // local_bs
        order = np.arange(self._n)
        if train and self.shuffle:
            order = RandomGenerator.RNG.randperm(self._n)
        for b in range(n_batches):
            sel = order[b * local_bs:(b + 1) * local_bs]
            yield self.features[sel], self.labels[sel]
