"""Vision transforms — ImageFrame / ImageFeature + augmentations.

Rebuild of «bigdl»/transform/vision/image/ (SURVEY.md §2.1 "Vision
transforms"): ImageFrame (local/distributed), ImageFeature (the mutable
record flowing through the pipeline), and the OpenCV-backed augmentation
ops (Resize, RandomCrop, CenterCrop, HFlip, ChannelNormalize,
RandomTransformer, MatToTensor...).

The OpenCV native library (SURVEY.md §2.3) is replaced by host-side
numpy + PIL when available (bilinear resize falls back to a pure-numpy
implementation otherwise).  Decode/augment stays on host CPU feeding the
device — the same division of labor as the reference (executors decode
on CPU cores, the device does the math).

Layout convention: ImageFeature holds HWC uint8/float arrays like the
reference's OpenCVMat; MatToTensor emits CHW float32 (the NCHW model
input).
"""

from __future__ import annotations

from typing import Callable, Dict, List, Optional, Sequence

import numpy as np

from bigdl_tpu.common import RandomGenerator


class ImageFeature(dict):
    """«bigdl»/transform/vision/image/ImageFeature.scala — a dict of
    named slots (bytes/mat/label/path/...) mutated along the pipeline."""

    MAT = "mat"          # HWC float/uint8 numpy array
    LABEL = "label"
    URI = "uri"
    SAMPLE = "sample"

    def __init__(self, image=None, label=None, uri=None):
        super().__init__()
        if image is not None:
            self[self.MAT] = np.asarray(image)
        if label is not None:
            self[self.LABEL] = label
        if uri is not None:
            self[self.URI] = uri

    @property
    def image(self):
        return self.get(self.MAT)


class FeatureTransformer:
    """«bigdl» FeatureTransformer — composable ImageFeature ->
    ImageFeature stage; ``>>`` chains (reference ``->``)."""

    def transform(self, feature: ImageFeature) -> ImageFeature:
        raise NotImplementedError

    def __call__(self, features):
        if isinstance(features, ImageFeature):
            return self.transform(features)
        return (self.transform(f) for f in features)

    def __rshift__(self, other: "FeatureTransformer"):
        return _ChainedFeature(self, other)


class _ChainedFeature(FeatureTransformer):
    def __init__(self, a, b):
        self.a, self.b = a, b

    def transform(self, feature):
        return self.b.transform(self.a.transform(feature))


def _resize_bilinear(img: np.ndarray, oh: int, ow: int) -> np.ndarray:
    """Pure-numpy bilinear resize (HWC), replacing the OpenCV JNI path."""
    try:
        from PIL import Image

        if img.dtype != np.uint8:
            # PIL float path: per-channel
            chans = [
                np.asarray(
                    Image.fromarray(img[..., c].astype(np.float32), mode="F")
                    .resize((ow, oh), Image.BILINEAR)
                )
                for c in range(img.shape[-1])
            ]
            return np.stack(chans, axis=-1)
        pil = Image.fromarray(img)
        return np.asarray(pil.resize((ow, oh), Image.BILINEAR))
    except ImportError:
        pass
    from bigdl_tpu import native as _native

    if _native.available() and img.ndim == 3:
        chw = np.ascontiguousarray(img.astype(np.float32).transpose(2, 0, 1))
        out = _native.resize_bilinear(chw, oh, ow)
        return out.transpose(1, 2, 0)
    h, w = img.shape[:2]
    ys = np.linspace(0, h - 1, oh)
    xs = np.linspace(0, w - 1, ow)
    y0 = np.floor(ys).astype(int)
    x0 = np.floor(xs).astype(int)
    y1 = np.minimum(y0 + 1, h - 1)
    x1 = np.minimum(x0 + 1, w - 1)
    wy = (ys - y0)[:, None, None]
    wx = (xs - x0)[None, :, None]
    img = img.astype(np.float32)
    top = img[y0][:, x0] * (1 - wx) + img[y0][:, x1] * wx
    bot = img[y1][:, x0] * (1 - wx) + img[y1][:, x1] * wx
    return top * (1 - wy) + bot * wy


class Resize(FeatureTransformer):
    """«bigdl» Resize.scala"""

    def __init__(self, resize_h: int, resize_w: int):
        self.resize_h, self.resize_w = resize_h, resize_w

    def transform(self, feature):
        img = feature.image
        feature[ImageFeature.MAT] = _resize_bilinear(
            img, self.resize_h, self.resize_w
        )
        return feature


class AspectScale(FeatureTransformer):
    """«bigdl» AspectScale — resize the short edge to ``scale``."""

    def __init__(self, scale: int, max_size: int = 1000):
        self.scale, self.max_size = scale, max_size

    def transform(self, feature):
        img = feature.image
        h, w = img.shape[:2]
        short, long = min(h, w), max(h, w)
        ratio = self.scale / short
        if long * ratio > self.max_size:
            ratio = self.max_size / long
        feature[ImageFeature.MAT] = _resize_bilinear(
            img, int(round(h * ratio)), int(round(w * ratio))
        )
        return feature


class CenterCrop(FeatureTransformer):
    """«bigdl» CenterCrop.scala"""

    def __init__(self, crop_width: int, crop_height: int):
        self.cw, self.ch = crop_width, crop_height

    def transform(self, feature):
        img = feature.image
        h, w = img.shape[:2]
        y = (h - self.ch) // 2
        x = (w - self.cw) // 2
        feature[ImageFeature.MAT] = img[y : y + self.ch, x : x + self.cw]
        return feature


class RandomCrop(FeatureTransformer):
    """«bigdl» RandomCrop.scala"""

    def __init__(self, crop_width: int, crop_height: int):
        self.cw, self.ch = crop_width, crop_height

    def transform(self, feature):
        img = feature.image
        h, w = img.shape[:2]
        y = int(RandomGenerator.RNG.randint(0, max(1, h - self.ch + 1)))
        x = int(RandomGenerator.RNG.randint(0, max(1, w - self.cw + 1)))
        feature[ImageFeature.MAT] = img[y : y + self.ch, x : x + self.cw]
        return feature


class HFlip(FeatureTransformer):
    """«bigdl» HFlip.scala — unconditional horizontal flip."""

    def transform(self, feature):
        feature[ImageFeature.MAT] = feature.image[:, ::-1]
        return feature


class RandomHFlip(FeatureTransformer):
    """«bigdl» RandomTransformer(HFlip, p)"""

    def __init__(self, p: float = 0.5):
        self.p = p

    def transform(self, feature):
        if RandomGenerator.RNG.uniform(0, 1) < self.p:
            feature[ImageFeature.MAT] = feature.image[:, ::-1]
        return feature


class ChannelNormalize(FeatureTransformer):
    """«bigdl» ChannelNormalize.scala — per-channel (x - mean) / std."""

    def __init__(self, mean_r, mean_g, mean_b, std_r=1.0, std_g=1.0, std_b=1.0):
        self.mean = np.array([mean_r, mean_g, mean_b], np.float32)
        self.std = np.array([std_r, std_g, std_b], np.float32)

    def transform(self, feature):
        img = feature.image.astype(np.float32)
        feature[ImageFeature.MAT] = (img - self.mean) / self.std
        return feature


class ChannelScaledNormalizer(FeatureTransformer):
    """«bigdl» ChannelScaledNormalizer — mean-subtract + global scale."""

    def __init__(self, mean_r, mean_g, mean_b, scale: float):
        self.mean = np.array([mean_r, mean_g, mean_b], np.float32)
        self.scale = scale

    def transform(self, feature):
        img = feature.image.astype(np.float32)
        feature[ImageFeature.MAT] = (img - self.mean) * self.scale
        return feature


class PixelNormalizer(FeatureTransformer):
    """«bigdl» PixelNormalizer — subtract a full mean image."""

    def __init__(self, means: np.ndarray):
        self.means = np.asarray(means, np.float32)

    def transform(self, feature):
        feature[ImageFeature.MAT] = feature.image.astype(np.float32) - self.means
        return feature


class Brightness(FeatureTransformer):
    """«bigdl» Brightness.scala — random delta in [delta_low, delta_high]."""

    def __init__(self, delta_low: float, delta_high: float):
        self.lo, self.hi = delta_low, delta_high

    def transform(self, feature):
        delta = RandomGenerator.RNG.uniform(self.lo, self.hi)
        feature[ImageFeature.MAT] = feature.image.astype(np.float32) + delta
        return feature


class Contrast(FeatureTransformer):
    """«bigdl» Contrast.scala"""

    def __init__(self, delta_low: float, delta_high: float):
        self.lo, self.hi = delta_low, delta_high

    def transform(self, feature):
        f = RandomGenerator.RNG.uniform(self.lo, self.hi)
        feature[ImageFeature.MAT] = feature.image.astype(np.float32) * f
        return feature


class Saturation(FeatureTransformer):
    """«bigdl» Saturation.scala — scale distance from the grey image."""

    def __init__(self, delta_low: float, delta_high: float):
        self.lo, self.hi = delta_low, delta_high

    def transform(self, feature):
        f = RandomGenerator.RNG.uniform(self.lo, self.hi)
        img = feature.image.astype(np.float32)
        grey = img.mean(axis=-1, keepdims=True)
        feature[ImageFeature.MAT] = grey + (img - grey) * f
        return feature


class ColorJitter(FeatureTransformer):
    """«bigdl» ColorJitter.scala — random brightness/contrast/saturation
    in random order."""

    def __init__(self, brightness=32.0, contrast=0.5, saturation=0.5):
        self.ops = [
            Brightness(-brightness, brightness),
            Contrast(1 - contrast, 1 + contrast),
            Saturation(1 - saturation, 1 + saturation),
        ]

    def transform(self, feature):
        order = RandomGenerator.RNG.randperm(len(self.ops))
        for i in order:
            feature = self.ops[i].transform(feature)
        return feature


class MatToTensor(FeatureTransformer):
    """«bigdl» MatToTensor.scala — HWC -> CHW float32 model input."""

    def __init__(self, to_rgb: bool = False):
        self.to_rgb = to_rgb

    def transform(self, feature):
        img = feature.image.astype(np.float32)
        if self.to_rgb:
            img = img[..., ::-1]
        feature[ImageFeature.SAMPLE] = np.ascontiguousarray(
            np.transpose(img, (2, 0, 1))
        )
        return feature


class ImageFrameToSample(FeatureTransformer):
    """«bigdl» ImageFrameToSample.scala — wrap tensor+label as a Sample."""

    def transform(self, feature):
        from bigdl_tpu.dataset import Sample

        tensor = feature.get(ImageFeature.SAMPLE)
        if tensor is None:
            tensor = np.transpose(feature.image.astype(np.float32), (2, 0, 1))
        label = feature.get(ImageFeature.LABEL, np.zeros(1, np.float32))
        label = np.atleast_1d(np.asarray(label, np.float32))
        feature[ImageFeature.SAMPLE] = Sample(tensor, label)
        return feature


class ImageFrame:
    """«bigdl» ImageFrame — a collection of ImageFeatures with
    ``transform``.  LocalImageFrame only: the distributed variant's role
    (RDD of features) is played by the data loader feeding the device."""

    def __init__(self, features: Sequence[ImageFeature]):
        self.features = list(features)

    @staticmethod
    def read(arrays, labels=None):
        """Build from in-memory HWC arrays (the reference reads files /
        bytes through OpenCV decode; file decode is PIL-backed when
        paths are given)."""
        feats = []
        for i, a in enumerate(arrays):
            if isinstance(a, str):
                from PIL import Image

                a = np.asarray(Image.open(a).convert("RGB"))
            feats.append(
                ImageFeature(a, None if labels is None else labels[i])
            )
        return ImageFrame(feats)

    def transform(self, transformer: FeatureTransformer):
        self.features = [transformer.transform(f) for f in self.features]
        return self

    def __len__(self):
        return len(self.features)

    def to_samples(self):
        return [f[ImageFeature.SAMPLE] for f in self.features]

    def to_dataset(self, batch_size: int = 32):
        """Bridge into the training pipeline."""
        from bigdl_tpu.dataset.dataset import SampleDataSet

        self.transform(ImageFrameToSample())
        return SampleDataSet(self.to_samples(), batch_size)
