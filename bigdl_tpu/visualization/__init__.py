"""bigdl_tpu.visualization — TensorBoard summaries.

Rebuild of «bigdl»/visualization/ (SURVEY.md §2.1 "Visualization"):
TrainSummary (loss / throughput / LR per iteration, optional parameter
histograms) and ValidationSummary (accuracy per validation run), written
as TensorBoard event files.  The reference links the java protobuf
Summary/Event classes; here the event wire format is hand-encoded
(varint protobuf + masked crc32c records) so no TF dependency is needed.
"""

from bigdl_tpu.visualization.summary import (
    FileWriter,
    TrainSummary,
    ValidationSummary,
)

__all__ = ["FileWriter", "TrainSummary", "ValidationSummary"]
