"""TensorBoard event-file writer + Train/Validation summaries.

Rebuild of «bigdl»/visualization/FileWriter.scala, TrainSummary.scala,
ValidationSummary.scala.  Wire format (the TFRecord/event framing
TensorBoard reads):

    uint64 length | uint32 masked_crc32c(length) | bytes data |
    uint32 masked_crc32c(data)

with ``data`` an Event protobuf.  The two messages used are encoded by
hand (field/varint layout below) — scalar summaries and histograms are
all the reference emits, so a protobuf compiler would be overkill:

    Event:   1: double wall_time   2: int64 step   5: Summary summary
    Summary: 1: repeated Value value
    Value:   1: string tag         2: float simple_value  5: HistogramProto histo
    HistogramProto: 1: double min  2: double max  3: double num
                    4: double sum  5: double sum_squares
                    6: repeated double bucket_limit  7: repeated double bucket
"""

from __future__ import annotations

import itertools
import os
import struct
import time
from typing import Optional

import numpy as np

# ------------------------------------------------------------------ crc32c
_CRC_TABLE = []


def _build_crc_table():
    poly = 0x82F63B78  # Castagnoli, reflected
    for n in range(256):
        c = n
        for _ in range(8):
            c = (c >> 1) ^ poly if c & 1 else c >> 1
        _CRC_TABLE.append(c)


_build_crc_table()


def crc32c(data: bytes) -> int:
    crc = 0xFFFFFFFF
    for b in data:
        crc = _CRC_TABLE[(crc ^ b) & 0xFF] ^ (crc >> 8)
    return crc ^ 0xFFFFFFFF


def _masked_crc(data: bytes) -> int:
    crc = crc32c(data)
    return ((crc >> 15 | crc << 17) + 0xA282EAD8) & 0xFFFFFFFF


# ---------------------------------------------------------------- protobuf
def _varint(n: int) -> bytes:
    out = bytearray()
    while True:
        b = n & 0x7F
        n >>= 7
        if n:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def _tag(field: int, wire: int) -> bytes:
    return _varint(field << 3 | wire)


def _pb_double(field: int, v: float) -> bytes:
    return _tag(field, 1) + struct.pack("<d", v)


def _pb_float(field: int, v: float) -> bytes:
    return _tag(field, 5) + struct.pack("<f", v)


def _pb_int64(field: int, v: int) -> bytes:
    return _tag(field, 0) + _varint(v & 0xFFFFFFFFFFFFFFFF)


def _pb_bytes(field: int, v: bytes) -> bytes:
    return _tag(field, 2) + _varint(len(v)) + v


def _pb_packed_doubles(field: int, vals) -> bytes:
    payload = b"".join(struct.pack("<d", float(v)) for v in vals)
    return _pb_bytes(field, payload)


def _encode_scalar_event(tag: str, value: float, step: int,
                         wall_time: Optional[float] = None) -> bytes:
    value_msg = _pb_bytes(1, tag.encode()) + _pb_float(2, float(value))
    summary = _pb_bytes(1, value_msg)
    event = (
        _pb_double(1, wall_time if wall_time is not None else time.time())
        + _pb_int64(2, int(step))
        + _pb_bytes(5, summary)
    )
    return event


def _encode_histogram_event(tag: str, values: np.ndarray, step: int,
                            wall_time: Optional[float] = None) -> bytes:
    v = np.asarray(values, np.float64).reshape(-1)
    counts, edges = np.histogram(v, bins=30)
    histo = (
        _pb_double(1, float(v.min()) if v.size else 0.0)
        + _pb_double(2, float(v.max()) if v.size else 0.0)
        + _pb_double(3, float(v.size))
        + _pb_double(4, float(v.sum()))
        + _pb_double(5, float((v * v).sum()))
        + _pb_packed_doubles(6, edges[1:])
        + _pb_packed_doubles(7, counts)
    )
    value_msg = _pb_bytes(1, tag.encode()) + _pb_bytes(5, histo)
    summary = _pb_bytes(1, value_msg)
    return (
        _pb_double(1, wall_time if wall_time is not None else time.time())
        + _pb_int64(2, int(step))
        + _pb_bytes(5, summary)
    )


class FileWriter:
    """«bigdl»/visualization/tensorboard/FileWriter.scala — appends
    framed events to an events.out.tfevents.* file.

    The file name carries pid + a process-wide monotonic counter on top
    of the timestamp: two writers created in the same second in the
    same dir (fast tests, per-retry summaries) must get distinct files,
    never silently append to one stream.
    """

    _SEQ = itertools.count()

    def __init__(self, log_dir: str):
        os.makedirs(log_dir, exist_ok=True)
        fname = (f"events.out.tfevents.{int(time.time())}"
                 f".{os.getpid()}.{next(FileWriter._SEQ)}.bigdl_tpu")
        self.path = os.path.join(log_dir, fname)
        self._f = open(self.path, "ab")
        # file-version header event
        version = _pb_double(1, time.time()) + _pb_bytes(3, b"brain.Event:2")
        self._write_record(version)

    def _write_record(self, data: bytes):
        header = struct.pack("<Q", len(data))
        self._f.write(header)
        self._f.write(struct.pack("<I", _masked_crc(header)))
        self._f.write(data)
        self._f.write(struct.pack("<I", _masked_crc(data)))
        self._f.flush()

    def add_scalar(self, tag: str, value: float, step: int):
        self._write_record(_encode_scalar_event(tag, value, step))
        return self

    def add_histogram(self, tag: str, values, step: int):
        self._write_record(_encode_histogram_event(tag, values, step))
        return self

    def close(self):
        """Idempotent: a double close (user + context manager, or an
        exception path re-running cleanup) is a no-op."""
        if not self._f.closed:
            self._f.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False


class _Summary:
    def __init__(self, log_dir: str, app_name: str, kind: str):
        self.log_dir = os.path.join(log_dir, app_name, kind)
        self.writer = FileWriter(self.log_dir)
        self._triggers = {}

    def add_scalar(self, tag: str, value: float, step: int):
        self.writer.add_scalar(tag, value, step)
        return self

    def add_histogram(self, tag: str, values, step: int):
        self.writer.add_histogram(tag, values, step)
        return self

    def read_scalar(self, tag: str):
        """Reference parity: TrainSummary.readScalar — read back (step,
        value) pairs of a tag from the event files."""
        out = []
        for fname in sorted(os.listdir(self.log_dir)):
            if "tfevents" not in fname:
                continue
            out.extend(_read_scalars(os.path.join(self.log_dir, fname), tag))
        return out

    def read_histogram(self, tag: str):
        """Read back (step, histogram-dict) pairs of a tag — the
        reader-side half of the hand-rolled HistogramProto framing, so
        writer→reader parity is testable without TensorBoard."""
        out = []
        for fname in sorted(os.listdir(self.log_dir)):
            if "tfevents" not in fname:
                continue
            out.extend(_read_histograms(
                os.path.join(self.log_dir, fname), tag))
        return out

    def close(self):
        self.writer.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False


# resilience counters the optimizer loop emits (cumulative values):
# non-finite skipped steps, transient-retry attempts, and background
# checkpoint-write failures — read back with read_scalar(tag)
RESILIENCE_TAGS = ("NonFiniteSkips", "RetryCount",
                   "CheckpointWriteFailures")

# per-layer numerics telemetry (obs/health.py): each layer gets one
# scalar stream per prefix, tagged "<prefix><layer-path>" (e.g.
# "GradNorm/0/weight") — read back with read_scalar(tag)
HEALTH_TAG_PREFIXES = ("GradNorm/", "ParamNorm/", "UpdateRatio/")


class TrainSummary(_Summary):
    """«bigdl»/visualization/TrainSummary.scala — loss/throughput/LR per
    iteration; setSummaryTrigger enables parameter histograms.  The
    resilience layer adds the ``RESILIENCE_TAGS`` scalar streams."""

    def __init__(self, log_dir: str, app_name: str):
        super().__init__(log_dir, app_name, "train")

    def add_resilience(self, step: int, nonfinite_skips=None, retries=None,
                       checkpoint_write_failures=None):
        """Record the resilience counters that changed at ``step``."""
        for tag, value in zip(RESILIENCE_TAGS,
                              (nonfinite_skips, retries,
                               checkpoint_write_failures)):
            if value is not None:
                self.add_scalar(tag, float(value), step)
        return self

    def add_health(self, step: int, layers: dict):
        """Per-layer numerics scalars from one health sample
        (``{layer: {grad_norm, param_norm, update_ratio, ...}}`` as
        produced by ``obs.health.summarize``) — one TensorBoard stream
        per (prefix, layer) from :data:`HEALTH_TAG_PREFIXES`."""
        keys = ("grad_norm", "param_norm", "update_ratio")
        for layer, row in layers.items():
            for prefix, key in zip(HEALTH_TAG_PREFIXES, keys):
                v = row.get(key)
                if v is not None and np.isfinite(v):
                    self.add_scalar(prefix + layer, float(v), step)
        return self

    def set_summary_trigger(self, name: str, trigger):
        """name in {"Parameters", "Loss", "Throughput", "LearningRate"}"""
        self._triggers[name] = trigger
        return self

    def get_summary_trigger(self, name: str):
        return self._triggers.get(name)


class ValidationSummary(_Summary):
    """«bigdl»/visualization/ValidationSummary.scala"""

    def __init__(self, log_dir: str, app_name: str):
        super().__init__(log_dir, app_name, "validation")


# ------------------------------------------------------------ event reader
def _read_varint(buf: bytes, pos: int):
    result = shift = 0
    while True:
        b = buf[pos]
        pos += 1
        result |= (b & 0x7F) << shift
        if not b & 0x80:
            return result, pos
        shift += 7


def _iter_summary_values(path: str):
    """Walk the framed event file, yielding (step, value_msg bytes) for
    every Summary.Value — the shared framing layer under the scalar and
    histogram readers (one decoder, so the two can never drift)."""
    with open(path, "rb") as f:
        data = f.read()
    pos = 0
    while pos + 12 <= len(data):
        (length,) = struct.unpack_from("<Q", data, pos)
        pos += 12  # len + len-crc
        event = data[pos : pos + length]
        pos += length + 4  # data + data-crc
        step, summary = 0, None
        epos = 0
        while epos < len(event):
            key, epos = _read_varint(event, epos)
            field, wire = key >> 3, key & 7
            if wire == 0:
                val, epos = _read_varint(event, epos)
                if field == 2:
                    step = val
            elif wire == 1:
                epos += 8
            elif wire == 5:
                epos += 4
            elif wire == 2:
                ln, epos = _read_varint(event, epos)
                if field == 5:
                    summary = event[epos : epos + ln]
                epos += ln
        if summary is None:
            continue
        spos = 0
        while spos < len(summary):
            key, spos = _read_varint(summary, spos)
            if key >> 3 == 1 and key & 7 == 2:
                ln, spos = _read_varint(summary, spos)
                yield step, summary[spos : spos + ln]
                spos += ln
            else:
                break


def _read_scalars(path: str, want_tag: str):
    out = []
    for step, value_msg in _iter_summary_values(path):
        tag, simple = None, None
        vpos = 0
        while vpos < len(value_msg):
            k2, vpos = _read_varint(value_msg, vpos)
            f2, w2 = k2 >> 3, k2 & 7
            if w2 == 2:
                ln2, vpos = _read_varint(value_msg, vpos)
                if f2 == 1:
                    tag = value_msg[vpos : vpos + ln2].decode()
                vpos += ln2
            elif w2 == 5:
                if f2 == 2:
                    (simple,) = struct.unpack_from("<f", value_msg, vpos)
                vpos += 4
            elif w2 == 1:
                vpos += 8
            elif w2 == 0:
                _, vpos = _read_varint(value_msg, vpos)
        if tag == want_tag and simple is not None:
            out.append((step, simple))
    return out


def _parse_histo(histo: bytes) -> dict:
    """Decode a HistogramProto (fields as in the module docstring)."""
    out = {"min": 0.0, "max": 0.0, "num": 0.0, "sum": 0.0,
           "sum_squares": 0.0, "bucket_limit": [], "bucket": []}
    names = {1: "min", 2: "max", 3: "num", 4: "sum", 5: "sum_squares"}
    pos = 0
    while pos < len(histo):
        key, pos = _read_varint(histo, pos)
        field, wire = key >> 3, key & 7
        if wire == 1:
            (v,) = struct.unpack_from("<d", histo, pos)
            pos += 8
            if field in names:
                out[names[field]] = v
            elif field == 6:
                out["bucket_limit"].append(v)  # unpacked repeated form
            elif field == 7:
                out["bucket"].append(v)
        elif wire == 2:
            ln, pos = _read_varint(histo, pos)
            payload = histo[pos : pos + ln]
            pos += ln
            if field in (6, 7):  # packed repeated doubles
                vals = [struct.unpack_from("<d", payload, i)[0]
                        for i in range(0, len(payload) - 7, 8)]
                out["bucket_limit" if field == 6 else "bucket"].extend(vals)
        elif wire == 0:
            _, pos = _read_varint(histo, pos)
        elif wire == 5:
            pos += 4
    return out


def _read_histograms(path: str, want_tag: str):
    out = []
    for step, value_msg in _iter_summary_values(path):
        tag, histo = None, None
        vpos = 0
        while vpos < len(value_msg):
            k2, vpos = _read_varint(value_msg, vpos)
            f2, w2 = k2 >> 3, k2 & 7
            if w2 == 2:
                ln2, vpos = _read_varint(value_msg, vpos)
                if f2 == 1:
                    tag = value_msg[vpos : vpos + ln2].decode()
                elif f2 == 5:
                    histo = value_msg[vpos : vpos + ln2]
                vpos += ln2
            elif w2 == 5:
                vpos += 4
            elif w2 == 1:
                vpos += 8
            elif w2 == 0:
                _, vpos = _read_varint(value_msg, vpos)
        if tag == want_tag and histo is not None:
            out.append((step, _parse_histo(histo)))
    return out
