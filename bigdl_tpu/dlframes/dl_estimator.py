"""DLEstimator / DLClassifier / DLModel.

Rebuild of ⟦spark/dl/src/main/scala/org/apache/spark/ml/DLEstimator.scala⟧
and DLClassifier.scala (SURVEY.md §3.5):

    DLEstimator.fit(df):  validate schema -> rows to Samples
                          (featureSize/labelSize reshape) -> full
                          Optimizer path -> DLModel
    DLModel.transform(df): batched model.forward -> prediction column
    DLClassifier: ClassNLLCriterion convention (1-based labels),
                  argmax in transform

DataFrame backends: a pyspark DataFrame when pyspark is importable
(rows are collected to the host — the TPU process is the math engine,
Spark feeds arrays, mirroring the rebuild stance in SURVEY.md §7.6), a
pandas DataFrame, or a plain dict of columns.  Column semantics follow
the reference: featuresCol holds fixed-size numeric vectors/arrays,
labelCol scalars or vectors.
"""

from __future__ import annotations

from typing import Optional, Sequence

import numpy as np


def _df_kind(df):
    if hasattr(df, "rdd") and hasattr(df, "collect"):
        return "spark"
    if hasattr(df, "columns") and hasattr(df, "iloc"):
        return "pandas"
    if isinstance(df, dict):
        return "dict"
    raise TypeError(f"unsupported DataFrame type {type(df)}")


class _RddPartitionSource:
    """Partition-streamed row source over a (py)spark-protocol RDD —
    the PartitionStreamDataSet adapter that replaces the round-1
    collect()-to-driver (VERDICT r1 item 4; reference:
    ⟦DLEstimator.scala⟧ feeds the Optimizer from the DataFrame's RDD via
    mapPartitions).  One partition is materialized at a time (a spark
    job per partition), so driver memory stays bounded by the largest
    partition, not the dataset.

    Protocol needed from ``rdd``: ``getNumPartitions()`` and
    ``mapPartitionsWithIndex(f).collect()`` — satisfied by pyspark and by
    the fake-RDD test shim.
    """

    def __init__(self, df, features_col: str, label_col: Optional[str]):
        cols = [features_col] + ([label_col] if label_col else [])
        self._rdd = df.select(*cols).rdd
        self._has_label = label_col is not None

    def num_partitions(self) -> int:
        return self._rdd.getNumPartitions()

    def iter_partition(self, i: int):
        def keep(idx, it):
            return it if idx == i else iter(())

        for row in self._rdd.mapPartitionsWithIndex(keep).collect():
            feat = np.asarray(row[0], np.float32)
            lbl = np.asarray(row[1], np.float32) if self._has_label \
                else np.zeros((), np.float32)
            yield feat, lbl


def _column(df, name):
    kind = _df_kind(df)
    if kind == "spark":
        return np.asarray([row[name] for row in df.select(name).collect()],
                          dtype=np.float32)
    if kind == "pandas":
        return np.asarray(df[name].tolist(), dtype=np.float32)
    return np.asarray(df[name], dtype=np.float32)


def _with_column(df, name, values):
    kind = _df_kind(df)
    if kind == "spark":
        # collect to pandas for the output frame: predictions are a
        # host-side product (the reference returns a Spark DF; callers
        # needing Spark can parallelize this result)
        import pandas as pd

        pdf = df.toPandas()
        pdf[name] = list(values)
        return pdf
    if kind == "pandas":
        out = df.copy()
        out[name] = list(values)
        return out
    out = dict(df)
    out[name] = values
    return out


class DLModel:
    """Reference: DLModel.transform — batched predict into a prediction
    column."""

    def __init__(self, model, feature_size: Sequence[int],
                 features_col: str = "features",
                 prediction_col: str = "prediction",
                 batch_size: int = 32):
        self.model = model
        self.feature_size = list(feature_size)
        self.features_col = features_col
        self.prediction_col = prediction_col
        self.batch_size = batch_size

    def set_features_col(self, name):
        self.features_col = name
        return self

    def set_prediction_col(self, name):
        self.prediction_col = name
        return self

    def set_batch_size(self, n):
        self.batch_size = n
        return self

    setFeaturesCol = set_features_col
    setPredictionCol = set_prediction_col
    setBatchSize = set_batch_size

    def _predict_raw(self, df):
        from bigdl_tpu.optim.evaluator import predict

        if _df_kind(df) == "spark":
            # per-partition streamed predict — bounded driver memory
            src = _RddPartitionSource(df, self.features_col, None)
            outs = []
            for p in range(src.num_partitions()):
                rows = [feat for feat, _ in src.iter_partition(p)]
                if not rows:
                    continue
                feats = np.stack(rows).reshape([-1] + self.feature_size)
                outs.append(predict(self.model, feats, self.batch_size))
            return np.concatenate(outs, axis=0)
        feats = _column(df, self.features_col)
        feats = feats.reshape([-1] + self.feature_size)
        return predict(self.model, feats, self.batch_size)

    def transform(self, df):
        out = self._predict_raw(df)
        return _with_column(df, self.prediction_col,
                            [row for row in out.reshape(out.shape[0], -1)])


class DLClassifierModel(DLModel):
    """Reference: DLClassifierModel — argmax + 1-based label."""

    def transform(self, df):
        out = self._predict_raw(df)
        preds = np.argmax(out.reshape(out.shape[0], -1), axis=1) + 1.0
        return _with_column(df, self.prediction_col, preds)


class DLEstimator:
    """Reference: DLEstimator[T].fit(df) wraps the full Optimizer path
    over DataFrame columns."""

    _model_cls = DLModel

    def __init__(self, model, criterion, feature_size: Sequence[int],
                 label_size: Sequence[int],
                 features_col: str = "features", label_col: str = "label",
                 prediction_col: str = "prediction"):
        self.model = model
        self.criterion = criterion
        self.feature_size = list(feature_size)
        self.label_size = list(label_size)
        self.features_col = features_col
        self.label_col = label_col
        self.prediction_col = prediction_col
        self.batch_size = 32
        self.max_epoch = 10
        self.learning_rate = 1e-3
        self.optim_method = None
        self.end_trigger = None

    # fluent setters (reference Param spellings)
    def set_batch_size(self, n):
        self.batch_size = n
        return self

    def set_max_epoch(self, n):
        self.max_epoch = n
        return self

    def set_learning_rate(self, lr):
        self.learning_rate = lr
        return self

    def set_optim_method(self, m):
        self.optim_method = m
        return self

    def set_end_when(self, trigger):
        self.end_trigger = trigger
        return self

    def set_features_col(self, name):
        self.features_col = name
        return self

    def set_label_col(self, name):
        self.label_col = name
        return self

    setBatchSize = set_batch_size
    setMaxEpoch = set_max_epoch
    setLearningRate = set_learning_rate
    setOptimMethod = set_optim_method
    setFeaturesCol = set_features_col
    setLabelCol = set_label_col

    def fit(self, df) -> DLModel:
        from bigdl_tpu.optim import LocalOptimizer, SGD, Trigger

        if _df_kind(df) == "spark":
            # partition-streamed feeding — never collect() the dataset
            from bigdl_tpu.dataset import PartitionStreamDataSet

            dataset = PartitionStreamDataSet(
                _RddPartitionSource(df, self.features_col, self.label_col),
                batch_size=self.batch_size,
                feature_size=self.feature_size,
                label_size=self.label_size,
            )
            opt = LocalOptimizer(self.model, dataset, self.criterion,
                                 batch_size=self.batch_size)
        else:
            feats = _column(df, self.features_col).reshape(
                [-1] + self.feature_size
            )
            labels = _column(df, self.label_col).reshape(
                [-1] + self.label_size
            )
            if self.label_size == [1]:
                labels = labels.reshape(-1)
            opt = LocalOptimizer(self.model, (feats, labels), self.criterion,
                                 batch_size=self.batch_size)
        opt.set_optim_method(
            self.optim_method or SGD(learningrate=self.learning_rate)
        )
        opt.set_end_when(self.end_trigger or Trigger.max_epoch(self.max_epoch))
        trained = opt.optimize()
        return self._model_cls(
            trained, self.feature_size, self.features_col,
            self.prediction_col, self.batch_size,
        )


class DLClassifier(DLEstimator):
    """Reference: DLClassifier — label column of 1-based class ids,
    scalar label size."""

    _model_cls = DLClassifierModel

    def __init__(self, model, criterion=None, feature_size=None,
                 features_col="features", label_col="label",
                 prediction_col="prediction"):
        from bigdl_tpu.nn import ClassNLLCriterion

        super().__init__(model, criterion or ClassNLLCriterion(),
                         feature_size, [1], features_col, label_col,
                         prediction_col)
