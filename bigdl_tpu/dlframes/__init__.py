"""bigdl_tpu.dlframes — DataFrame ML-pipeline API.

Rebuild of ⟦spark/dl/src/main/scala/org/apache/spark/ml/DLEstimator.scala⟧
(DLEstimator / DLClassifier / DLModel — SURVEY.md §3.5).
"""

from bigdl_tpu.dlframes.dl_estimator import (
    DLClassifier,
    DLClassifierModel,
    DLEstimator,
    DLModel,
)

__all__ = ["DLEstimator", "DLClassifier", "DLModel", "DLClassifierModel"]
