"""Common utilities: RNG, dtype handling, pytree helpers.

Mirrors the role of «bigdl»/utils/RandomGenerator.scala (the global,
seedable RNG every layer's ``reset()`` draws from) and small pieces of
«bigdl»/utils/Table.scala / File.scala.
"""

from __future__ import annotations

import numpy as np


class _RNG:
    """Global seedable RNG used for parameter initialisation.

    BigDL layers draw their initial weights from a process-global
    ``RandomGenerator.RNG`` so that ``RNG.setSeed(k)`` makes model
    construction deterministic (see the per-layer unit-spec pattern in
    SURVEY.md §4.1).  Parameter init happens on host, eagerly, at module
    construction time — exactly like the reference — so we use a numpy
    Generator here, not a JAX key (JAX keys drive only the *traced*
    randomness: dropout masks etc.).
    """

    def __init__(self, seed: int | None = None):
        self._seed = seed if seed is not None else 0
        self._rng = np.random.RandomState(self._seed)

    def set_seed(self, seed: int) -> "_RNG":
        self._seed = int(seed)
        self._rng = np.random.RandomState(self._seed)
        return self

    # camelCase alias for API parity with the reference's Scala spelling.
    setSeed = set_seed

    @property
    def seed(self) -> int:
        return self._seed

    def uniform(self, low: float, high: float, size=None):
        return self._rng.uniform(low, high, size=size)

    def normal(self, mean: float, stdv: float, size=None):
        return self._rng.normal(mean, stdv, size=size)

    def randperm(self, n: int):
        return self._rng.permutation(n)

    def randint(self, low, high=None, size=None):
        return self._rng.randint(low, high, size=size)


class RandomGenerator:
    """Namespace matching the reference's ``RandomGenerator.RNG`` spelling."""

    RNG = _RNG()


def get_dtype(dtype=None):
    import jax.numpy as jnp

    if dtype is None:
        return jnp.float32
    if isinstance(dtype, str):
        return {
            "float32": jnp.float32,
            "float": jnp.float32,
            "bfloat16": jnp.bfloat16,
            "bf16": jnp.bfloat16,
            "float16": jnp.float16,
            "float64": jnp.float64,
            "double": jnp.float64,
            "int32": jnp.int32,
            "int8": jnp.int8,
        }[dtype]
    return dtype


def to_numpy(x):
    return np.asarray(x)


class Table(dict):
    """1-based-keyed activity table, the reference's generic container
    («bigdl»/utils/Table.scala).  In the rebuild, plain Python lists/tuples
    serve as tables on the compute path; this class exists for API-parity
    spots where user code indexes ``output[1]``, ``output[2]``.
    """

    @staticmethod
    def from_seq(seq):
        t = Table()
        for i, v in enumerate(seq):
            t[i + 1] = v
        return t

    def to_seq(self):
        return [self[i + 1] for i in range(len(self))]
