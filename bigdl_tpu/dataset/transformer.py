"""Transformer combinators.

Rebuild of «bigdl»/dataset/Transformer.scala: composable iterator →
iterator stages chained with ``->`` in the reference; ``>>`` here (and a
``.chain`` method).  SampleToMiniBatch is the canonical one (SURVEY.md
§3.2: distDataset = DataSet.rdd(samples) -> SampleToMiniBatch).
"""

from __future__ import annotations

from typing import Iterator, Optional

import numpy as np

from bigdl_tpu.common import RandomGenerator
from bigdl_tpu.dataset.sample import samples_to_minibatch


class Transformer:
    def __call__(self, iterator: Iterator) -> Iterator:
        raise NotImplementedError

    def chain(self, other: "Transformer") -> "Transformer":
        return _Chained(self, other)

    def __rshift__(self, other):
        return self.chain(other)


class _Chained(Transformer):
    def __init__(self, first, second):
        self.first, self.second = first, second

    def __call__(self, iterator):
        return self.second(self.first(iterator))


class SampleToMiniBatch(Transformer):
    """«bigdl»/dataset/SampleToMiniBatch.scala — group Samples into
    padded MiniBatches, yielding (input, target) pairs."""

    def __init__(self, batch_size: int, padding_value: float = 0.0,
                 fixed_length: Optional[int] = None, drop_last: bool = True):
        self.batch_size = batch_size
        self.padding_value = padding_value
        self.fixed_length = fixed_length
        self.drop_last = drop_last

    def __call__(self, iterator):
        buf = []
        for s in iterator:
            buf.append(s)
            if len(buf) == self.batch_size:
                mb = samples_to_minibatch(buf, self.padding_value, self.fixed_length)
                yield mb.input, mb.target
                buf = []
        if buf and not self.drop_last:
            mb = samples_to_minibatch(buf, self.padding_value, self.fixed_length)
            yield mb.input, mb.target


class Shuffle(Transformer):
    """Buffer-and-shuffle (the reference shuffles at the RDD level)."""

    def __call__(self, iterator):
        items = list(iterator)
        for i in RandomGenerator.RNG.randperm(len(items)):
            yield items[i]


class Normalizer(Transformer):
    """Grey-image normalizer (reference:
    «bigdl»/dataset/image/GreyImgNormalizer.scala) — (x - mean) / std over
    Sample features."""

    def __init__(self, mean: float, std: float):
        self.mean, self.std = mean, std

    def __call__(self, iterator):
        from bigdl_tpu.dataset.sample import Sample

        for s in iterator:
            yield Sample((np.asarray(s.features) - self.mean) / self.std, s.labels)
