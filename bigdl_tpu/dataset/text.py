"""Text pipeline — Dictionary, LabeledSentence, PTB-style BPTT batching.

Rebuild of «bigdl»/dataset/text/ (Dictionary.scala, LabeledSentence.scala,
the PTB path in models/rnn/Utils: fixed-length BPTT windows over a token
stream — SURVEY.md §5 "Long-context": the reference's sequence handling is
bounded-window, nothing shards the sequence axis).
"""

from __future__ import annotations

from collections import Counter
from typing import Iterable, List, Optional, Sequence

import numpy as np


class Dictionary:
    """«bigdl»/dataset/text/Dictionary.scala — vocab with 1-based ids
    (id 0 is reserved so embeddings stay 1-based like LookupTable)."""

    def __init__(self, sentences: Optional[Iterable[Sequence[str]]] = None,
                 vocab_size: Optional[int] = None):
        self._word2idx = {}
        self._idx2word = []
        if sentences is not None:
            counts = Counter()
            for s in sentences:
                counts.update(s)
            vocab = [w for w, _ in counts.most_common(vocab_size)]
            for w in vocab:
                self.add_word(w)

    def add_word(self, word: str) -> int:
        if word not in self._word2idx:
            self._idx2word.append(word)
            self._word2idx[word] = len(self._idx2word)  # 1-based
        return self._word2idx[word]

    def get_index(self, word: str, default: Optional[int] = None) -> int:
        if default is None:
            default = len(self._idx2word)  # last id as <unk> bucket
        return self._word2idx.get(word, default)

    def get_word(self, index: int) -> str:
        return self._idx2word[index - 1]

    def vocab_size(self) -> int:
        return len(self._idx2word)

    def __len__(self):
        return len(self._idx2word)


class LabeledSentence:
    """«bigdl»/dataset/text/LabeledSentence.scala — token ids + per-token
    labels (for LM: labels are the ids shifted by one)."""

    def __init__(self, data: Sequence[float], labels: Sequence[float]):
        self.data = np.asarray(data, np.float32)
        self.labels = np.asarray(labels, np.float32)


def ptb_bptt_batches(token_ids: np.ndarray, batch_size: int, num_steps: int):
    """The PTB LM batcher (reference: models/rnn data prep): reshape the
    token stream into batch_size parallel streams, then slice fixed
    num_steps windows; x = tokens[t], y = tokens[t+1].  Returns arrays
    (n_batches, batch_size, num_steps)."""
    ids = np.asarray(token_ids, np.float32)
    n = (len(ids) - 1) // (batch_size * num_steps) * batch_size * num_steps
    if n <= 0:
        raise ValueError("token stream too short for one batch")
    x = ids[:n].reshape(batch_size, -1)
    y = ids[1 : n + 1].reshape(batch_size, -1)
    n_windows = x.shape[1] // num_steps
    xs = x[:, : n_windows * num_steps].reshape(batch_size, n_windows, num_steps)
    ys = y[:, : n_windows * num_steps].reshape(batch_size, n_windows, num_steps)
    return (np.transpose(xs, (1, 0, 2)).copy(),
            np.transpose(ys, (1, 0, 2)).copy())


def synthetic_ptb_stream(n_tokens: int = 20000, vocab_size: int = 100,
                         seed: int = 0, order: int = 2) -> np.ndarray:
    """Deterministic synthetic token stream with learnable Markov
    structure (no network access; same role as mnist.synthetic_mnist):
    1-based ids."""
    rng = np.random.RandomState(seed)
    # a sparse deterministic-ish transition table
    table = rng.randint(1, vocab_size + 1, size=(vocab_size, 4))
    out = np.empty(n_tokens, np.int64)
    out[0] = 1
    for i in range(1, n_tokens):
        prev = out[i - 1] - 1
        # 80% follow the table, 20% noise — learnable but not trivial
        if rng.rand() < 0.8:
            out[i] = table[prev, rng.randint(4)]
        else:
            out[i] = rng.randint(1, vocab_size + 1)
    return out
