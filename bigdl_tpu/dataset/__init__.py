"""bigdl_tpu.dataset — data pipeline.

Rebuild of «bigdl»/dataset/ (SURVEY.md §2.1 "Dataset core"): DataSet
abstractions, Sample/MiniBatch packing, Transformer combinators.  The
reference's ``DistributedDataSet`` wraps a Spark RDD; here the
"distributed" dataset is a host-side iterator whose global batches get
``device_put`` with a ``NamedSharding`` over the mesh's data axis — the
host→device feed that replaces executor-local RDD caching.
"""

from bigdl_tpu.dataset.dataset import (
    DataSet,
    LocalDataSet,
    ArrayDataSet,
    DistributedDataSet,
    PartitionStreamDataSet,
    to_dataset,
)
from bigdl_tpu.dataset.sample import Sample, MiniBatch
from bigdl_tpu.dataset.stream import (
    BoundedBuffer,
    StreamDataSet,
    StreamRecord,
    StreamSource,
    SyntheticStream,
)
from bigdl_tpu.dataset.transformer import (
    Transformer,
    SampleToMiniBatch,
    Shuffle,
    Normalizer,
)

__all__ = [
    "DataSet", "LocalDataSet", "ArrayDataSet", "DistributedDataSet",
    "PartitionStreamDataSet",
    "StreamDataSet", "StreamSource", "StreamRecord", "SyntheticStream",
    "BoundedBuffer",
    "to_dataset", "Sample", "MiniBatch", "Transformer", "SampleToMiniBatch",
    "Shuffle", "Normalizer",
]
