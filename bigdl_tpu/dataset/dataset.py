"""DataSet abstractions.

Rebuild of «bigdl»/dataset/DataSet.scala: ``LocalDataSet`` (host
iterators) and ``DistributedDataSet`` (reference: an RDD per executor;
here: a marker that batches should be sharded over the mesh data axis by
the optimizer's ``_put_batch``).
"""

from __future__ import annotations

from typing import Iterable, Iterator, Optional, Sequence

import numpy as np

from bigdl_tpu.common import RandomGenerator
from bigdl_tpu.dataset.sample import MiniBatch, Sample, samples_to_minibatch


class DataSet:
    """Iterable of (input, target) numpy batches."""

    def data(self, train: bool = True) -> Iterator:
        raise NotImplementedError

    def size(self) -> int:
        raise NotImplementedError

    # reference: DataSet.transform / ``->`` chaining
    def transform(self, transformer):
        return _TransformedDataSet(self, transformer)

    def __rshift__(self, transformer):
        return self.transform(transformer)


class _TransformedDataSet(DataSet):
    def __init__(self, base: DataSet, transformer):
        self.base = base
        self.transformer = transformer

    def data(self, train: bool = True):
        return self.transformer(self.base.data(train))

    def size(self):
        return self.base.size()


class LocalDataSet(DataSet):
    pass


class ArrayDataSet(LocalDataSet):
    """In-memory (features, labels) arrays batched to (input, target).

    Shuffles per epoch with the global RNG in train mode; drops the
    ragged tail batch in train mode (keeps it for eval) so the jitted
    step never retraces on a new batch shape — the TPU analogue of the
    reference's fixed-size MiniBatch packing.
    """

    def __init__(self, features, labels, batch_size: int = 32,
                 shuffle: bool = True):
        if isinstance(features, (list, tuple)):
            self.features = [np.asarray(f) for f in features]
            self._multi = True
            n = self.features[0].shape[0]
        else:
            self.features = np.asarray(features)
            self._multi = False
            n = self.features.shape[0]
        self.labels = np.asarray(labels)
        self.batch_size = batch_size
        self.shuffle = shuffle
        self._n = n

    def size(self):
        return self._n

    def data(self, train: bool = True):
        idx = np.arange(self._n)
        if train and self.shuffle:
            idx = RandomGenerator.RNG.randperm(self._n)
        bs = self.batch_size
        n_full = self._n // bs
        # single float32 feature arrays assemble through the native
        # multi-threaded row gather (bigdl_tpu/native — the BigDL-core
        # replacement for the host data plane)
        gather = None
        if not self._multi and self.features.dtype == np.float32:
            from bigdl_tpu import native as _native

            gather = _native.gather_rows
        for b in range(n_full):
            sel = idx[b * bs : (b + 1) * bs]
            if self._multi:
                inp = tuple(f[sel] for f in self.features)
            elif gather is not None:
                inp = gather(self.features, sel)
            else:
                inp = self.features[sel]
            yield inp, self.labels[sel]
        rem = self._n - n_full * bs
        if rem and not train:
            sel = idx[n_full * bs :]
            if self._multi:
                inp = tuple(f[sel] for f in self.features)
            else:
                inp = self.features[sel]
            yield inp, self.labels[sel]


class SampleDataSet(LocalDataSet):
    """Dataset over Sample records with pad-at-batch semantics
    (reference: DataSet.array(samples) -> SampleToMiniBatch)."""

    def __init__(self, samples: Sequence[Sample], batch_size: int = 32,
                 padding_value: float = 0.0, fixed_length: Optional[int] = None,
                 shuffle: bool = True):
        self.samples = list(samples)
        self.batch_size = batch_size
        self.padding_value = padding_value
        self.fixed_length = fixed_length
        self.shuffle = shuffle

    def size(self):
        return len(self.samples)

    def data(self, train: bool = True):
        order = np.arange(len(self.samples))
        if train and self.shuffle:
            order = RandomGenerator.RNG.randperm(len(self.samples))
        bs = self.batch_size
        n_full = len(self.samples) // bs
        for b in range(n_full):
            batch = [self.samples[i] for i in order[b * bs : (b + 1) * bs]]
            mb = samples_to_minibatch(batch, self.padding_value, self.fixed_length)
            yield mb.input, mb.target
        rem = len(self.samples) - n_full * bs
        if rem and not train:
            batch = [self.samples[i] for i in order[n_full * bs :]]
            mb = samples_to_minibatch(batch, self.padding_value, self.fixed_length)
            yield mb.input, mb.target


def iter_process_batches(n: int, batch_size: int, pid: int, nproc: int,
                         shuffle: bool, pad_tail: bool = False):
    """The per-process batch-slicing contract shared by every
    distributed dataset: derive the SAME global epoch permutation on
    every process (seeded global RNG), then yield this process's
    contiguous ``batch_size // nproc`` index slice of each full global
    batch.  DistriOptimizer assembles the global device array from
    these shards via ``make_array_from_process_local_data``.

    ``pad_tail``: also yield the final partial global batch, its index
    list repeat-padded to the process multiple (the reference's
    SampleToMiniBatch padding — the repeated sample is counted, exactly
    as the reference counts its pad copies).  Every process yields the
    same tail length, so the trainer's local divisor padding stays
    consistent across hosts.  Off (historical drop-the-tail) for eval
    iteration, where repeated rows would distort metric counts."""
    if batch_size % nproc:
        raise ValueError(
            f"global batch {batch_size} not divisible by {nproc} processes"
        )
    local = batch_size // nproc
    idx = RandomGenerator.RNG.randperm(n) if shuffle else np.arange(n)
    for b in range(n // batch_size):
        globl = idx[b * batch_size: (b + 1) * batch_size]
        yield globl[pid * local: (pid + 1) * local]
    rem = n % batch_size
    if pad_tail and rem:
        tail = idx[n - rem:]
        pad_to = -(-rem // nproc) * nproc
        if pad_to != rem:
            tail = np.concatenate(
                [tail, np.repeat(tail[-1:], pad_to - rem)])
        local_t = pad_to // nproc
        yield tail[pid * local_t: (pid + 1) * local_t]


class DistributedDataSet(ArrayDataSet):
    """Per-process distributed dataset (reference: DistributedDataSet
    wraps an RDD coalesced to nodeNumber — SURVEY.md §3.2 job 0).

    The iterator contract (VERDICT r1 item 4): every process derives the
    SAME global epoch permutation from the shared seeded RNG, then each
    yields only its own contiguous slice of every global batch —
    ``local = global_batch // num_processes`` rows.  DistriOptimizer
    assembles the global device array from these per-process shards via
    ``jax.make_array_from_process_local_data``, so no host ever holds or
    ships the full batch (the reference's executors likewise feed their
    cached partition only).

    Defaults read ``jax.process_index()/process_count()`` at iteration
    time; pass ``process_id``/``num_processes`` to override (tests).
    """

    per_process = True

    def __init__(self, features, labels, batch_size: int = 32,
                 shuffle: bool = True, process_id: Optional[int] = None,
                 num_processes: Optional[int] = None):
        super().__init__(features, labels, batch_size, shuffle)
        self._pid = process_id
        self._nproc = num_processes

    def _world(self):
        if self._pid is not None and self._nproc is not None:
            return self._pid, self._nproc
        import jax

        return jax.process_index(), jax.process_count()

    def data(self, train: bool = True):
        pid, nproc = self._world()
        for mine in iter_process_batches(
            self._n, self.batch_size, pid, nproc,
            shuffle=train and self.shuffle, pad_tail=train,
        ):
            if self._multi:
                feats = tuple(f[mine] for f in self.features)
            else:
                feats = self.features[mine]
            yield feats, self.labels[mine]


class PartitionStreamDataSet(DataSet):
    """Streams batches from a partitioned row source WITHOUT collecting
    the dataset to the driver (VERDICT r1 item 4 — the DLEstimator path's
    mapPartitions-style feeding; reference: ⟦DLEstimator.scala⟧ feeds the
    Optimizer straight from the DataFrame's RDD).

    ``source`` must expose ``num_partitions()`` and ``iter_partition(i)``
    yielding ``(feature_row, label_row)`` pairs — satisfied by the spark
    adapter in dlframes (which rides ``rdd.toLocalIterator``-style
    partition streaming) and by the fake-RDD test shim.  In a multi-host
    world each process consumes partitions ``i % num_processes ==
    process_id`` — the per-process iterator contract.
    """

    def __init__(self, source, batch_size: int = 32,
                 feature_size: Optional[Sequence[int]] = None,
                 label_size: Optional[Sequence[int]] = None,
                 process_id: int = 0, num_processes: int = 1,
                 size_hint: Optional[int] = None):
        self.source = source
        self.batch_size = batch_size
        self.feature_size = list(feature_size) if feature_size else None
        self.label_size = list(label_size) if label_size else None
        self._pid = process_id
        self._nproc = num_processes
        self._size_hint = size_hint

    def size(self):
        return self._size_hint or 0

    def _shape(self, arr, size):
        arr = np.asarray(arr, np.float32)
        if size is not None:
            arr = arr.reshape([arr.shape[0]] + size)
            if size == [1]:
                arr = arr.reshape(-1)
        return arr

    def data(self, train: bool = True):
        bs = self.batch_size
        feat_buf: list = []
        lbl_buf: list = []
        n_parts = self.source.num_partitions()
        for p in range(n_parts):
            if p % self._nproc != self._pid:
                continue
            for feat, lbl in self.source.iter_partition(p):
                feat_buf.append(np.asarray(feat, np.float32))
                lbl_buf.append(np.asarray(lbl, np.float32))
                if len(feat_buf) == bs:
                    yield (
                        self._shape(np.stack(feat_buf), self.feature_size),
                        self._shape(np.stack(lbl_buf), self.label_size),
                    )
                    feat_buf, lbl_buf = [], []
        # ragged tail: dropped in train mode (jit shape stability — same
        # policy as ArrayDataSet), kept for eval
        if feat_buf and not train:
            yield (
                self._shape(np.stack(feat_buf), self.feature_size),
                self._shape(np.stack(lbl_buf), self.label_size),
            )


def to_dataset(data, batch_size: int = 32) -> Optional[DataSet]:
    """Coerce user input to a DataSet (reference: Optimizer accepts
    RDD[Sample] or DataSet)."""
    if data is None:
        return None
    if isinstance(data, DataSet):
        return data
    if isinstance(data, tuple) and len(data) == 2:
        return ArrayDataSet(data[0], data[1], batch_size)
    if isinstance(data, (list,)) and data and isinstance(data[0], Sample):
        return SampleDataSet(data, batch_size)
    raise TypeError(f"cannot build a DataSet from {type(data)}")
