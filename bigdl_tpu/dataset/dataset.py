"""DataSet abstractions.

Rebuild of «bigdl»/dataset/DataSet.scala: ``LocalDataSet`` (host
iterators) and ``DistributedDataSet`` (reference: an RDD per executor;
here: a marker that batches should be sharded over the mesh data axis by
the optimizer's ``_put_batch``).
"""

from __future__ import annotations

from typing import Iterable, Iterator, Optional, Sequence

import numpy as np

from bigdl_tpu.common import RandomGenerator
from bigdl_tpu.dataset.sample import MiniBatch, Sample, samples_to_minibatch


class DataSet:
    """Iterable of (input, target) numpy batches."""

    def data(self, train: bool = True) -> Iterator:
        raise NotImplementedError

    def size(self) -> int:
        raise NotImplementedError

    # reference: DataSet.transform / ``->`` chaining
    def transform(self, transformer):
        return _TransformedDataSet(self, transformer)

    def __rshift__(self, transformer):
        return self.transform(transformer)


class _TransformedDataSet(DataSet):
    def __init__(self, base: DataSet, transformer):
        self.base = base
        self.transformer = transformer

    def data(self, train: bool = True):
        return self.transformer(self.base.data(train))

    def size(self):
        return self.base.size()


class LocalDataSet(DataSet):
    pass


class ArrayDataSet(LocalDataSet):
    """In-memory (features, labels) arrays batched to (input, target).

    Shuffles per epoch with the global RNG in train mode; drops the
    ragged tail batch in train mode (keeps it for eval) so the jitted
    step never retraces on a new batch shape — the TPU analogue of the
    reference's fixed-size MiniBatch packing.
    """

    def __init__(self, features, labels, batch_size: int = 32,
                 shuffle: bool = True):
        if isinstance(features, (list, tuple)):
            self.features = [np.asarray(f) for f in features]
            self._multi = True
            n = self.features[0].shape[0]
        else:
            self.features = np.asarray(features)
            self._multi = False
            n = self.features.shape[0]
        self.labels = np.asarray(labels)
        self.batch_size = batch_size
        self.shuffle = shuffle
        self._n = n

    def size(self):
        return self._n

    def data(self, train: bool = True):
        idx = np.arange(self._n)
        if train and self.shuffle:
            idx = RandomGenerator.RNG.randperm(self._n)
        bs = self.batch_size
        n_full = self._n // bs
        # single float32 feature arrays assemble through the native
        # multi-threaded row gather (bigdl_tpu/native — the BigDL-core
        # replacement for the host data plane)
        gather = None
        if not self._multi and self.features.dtype == np.float32:
            from bigdl_tpu import native as _native

            gather = _native.gather_rows
        for b in range(n_full):
            sel = idx[b * bs : (b + 1) * bs]
            if self._multi:
                inp = tuple(f[sel] for f in self.features)
            elif gather is not None:
                inp = gather(self.features, sel)
            else:
                inp = self.features[sel]
            yield inp, self.labels[sel]
        rem = self._n - n_full * bs
        if rem and not train:
            sel = idx[n_full * bs :]
            if self._multi:
                inp = tuple(f[sel] for f in self.features)
            else:
                inp = self.features[sel]
            yield inp, self.labels[sel]


class SampleDataSet(LocalDataSet):
    """Dataset over Sample records with pad-at-batch semantics
    (reference: DataSet.array(samples) -> SampleToMiniBatch)."""

    def __init__(self, samples: Sequence[Sample], batch_size: int = 32,
                 padding_value: float = 0.0, fixed_length: Optional[int] = None,
                 shuffle: bool = True):
        self.samples = list(samples)
        self.batch_size = batch_size
        self.padding_value = padding_value
        self.fixed_length = fixed_length
        self.shuffle = shuffle

    def size(self):
        return len(self.samples)

    def data(self, train: bool = True):
        order = np.arange(len(self.samples))
        if train and self.shuffle:
            order = RandomGenerator.RNG.randperm(len(self.samples))
        bs = self.batch_size
        n_full = len(self.samples) // bs
        for b in range(n_full):
            batch = [self.samples[i] for i in order[b * bs : (b + 1) * bs]]
            mb = samples_to_minibatch(batch, self.padding_value, self.fixed_length)
            yield mb.input, mb.target
        rem = len(self.samples) - n_full * bs
        if rem and not train:
            batch = [self.samples[i] for i in order[n_full * bs :]]
            mb = samples_to_minibatch(batch, self.padding_value, self.fixed_length)
            yield mb.input, mb.target


class DistributedDataSet(ArrayDataSet):
    """Marker subclass: batches are global and get sharded over the mesh
    data axis by DistriOptimizer (reference: DistributedDataSet wraps an
    RDD coalesced to nodeNumber — SURVEY.md §3.2 job 0)."""


def to_dataset(data, batch_size: int = 32) -> Optional[DataSet]:
    """Coerce user input to a DataSet (reference: Optimizer accepts
    RDD[Sample] or DataSet)."""
    if data is None:
        return None
    if isinstance(data, DataSet):
        return data
    if isinstance(data, tuple) and len(data) == 2:
        return ArrayDataSet(data[0], data[1], batch_size)
    if isinstance(data, (list,)) and data and isinstance(data[0], Sample):
        return SampleDataSet(data, batch_size)
    raise TypeError(f"cannot build a DataSet from {type(data)}")
