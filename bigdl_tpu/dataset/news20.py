"""news20 + GloVe fetchers.

Rebuild of ⟦«py»/dataset/news20.py⟧ (VERDICT r2 missing #7): the
reference downloads the 20-Newsgroups tarball and GloVe-6B embeddings
and exposes ``get_news20`` / ``get_glove_w2v``.  This environment has
no egress, so the fetchers read an already-downloaded layout from
``data_dir`` (same on-disk shapes the reference's download produces)
and raise with the canonical URL when absent; deterministic synthetic
stand-ins keep the text-classification example and tests runnable
offline (same pattern as dataset/mnist.py).
"""

from __future__ import annotations

import os
import tarfile
from typing import Dict, List, Tuple

import numpy as np

NEWS20_URL = (
    "http://qwone.com/~jason/20Newsgroups/20news-18828.tar.gz"
)
GLOVE_URL = "http://nlp.stanford.edu/data/glove.6B.zip"

CLASS_NUM = 20


def get_news20(source_dir: str = "/tmp/news20/") -> List[Tuple[str, int]]:
    """Load [(text, 1-based label)] from an extracted ``20news-18828``
    tree (one directory per newsgroup, one file per post) or the
    tarball sitting in ``source_dir``."""
    def looks_like_corpus(cand):
        """The extracted tree is ≥2 per-newsgroup dirs with dotted
        names (alt.atheism, sci.space, …) — an unrelated sibling dir
        (e.g. glove.6B/ in the shared data_dir) must not match."""
        if not os.path.isdir(cand):
            return False
        subdirs = [d for d in os.listdir(cand)
                   if os.path.isdir(os.path.join(cand, d))]
        dotted = [d for d in subdirs if "." in d]
        return len(dotted) >= 2 and len(dotted) >= len(subdirs) / 2

    root = None
    for cand in (os.path.join(source_dir, "20news-18828"), source_dir):
        if looks_like_corpus(cand):
            root = cand
            break
    if root is None:
        tar = os.path.join(source_dir, "20news-18828.tar.gz")
        if os.path.exists(tar):
            with tarfile.open(tar, "r:gz") as tf:
                tf.extractall(source_dir)
            root = os.path.join(source_dir, "20news-18828")
    if root is None or not os.path.isdir(root):
        raise FileNotFoundError(
            f"no 20-Newsgroups data under {source_dir!r}; download "
            f"{NEWS20_URL} there first (no network in this environment)"
        )
    texts = []
    groups = sorted(
        d for d in os.listdir(root)
        if os.path.isdir(os.path.join(root, d)) and "." in d
    )
    for label, group in enumerate(groups, start=1):
        gdir = os.path.join(root, group)
        for fname in sorted(os.listdir(gdir)):
            path = os.path.join(gdir, fname)
            try:
                with open(path, "rb") as f:
                    texts.append((f.read().decode("latin-1"), label))
            except OSError:
                continue
    return texts


def get_glove_w2v(source_dir: str = "/tmp/news20/glove.6B/",
                  dim: int = 100) -> Dict[str, np.ndarray]:
    """Load {word: vec} from ``glove.6B.<dim>d.txt`` in ``source_dir``."""
    path = os.path.join(source_dir, f"glove.6B.{dim}d.txt")
    if not os.path.exists(path):
        raise FileNotFoundError(
            f"{path} not found; download {GLOVE_URL} and unzip there "
            "(no network in this environment)"
        )
    w2v = {}
    with open(path, "r", encoding="utf-8") as f:
        for line in f:
            parts = line.rstrip().split(" ")
            w2v[parts[0]] = np.asarray(parts[1:], np.float32)
    return w2v


# ----------------------------------------------------------- synthetic
_SYNTH_TOPIC_WORDS = 12  # per-class vocabulary block


def synthetic_news20(n: int = 400, seed: int = 7,
                     class_num: int = CLASS_NUM) -> List[Tuple[str, int]]:
    """Deterministic learnable stand-in: each class draws most tokens
    from its own vocabulary block (word{c*12}..word{c*12+11}) plus
    shared noise words — separable by any bag-of-words model."""
    rs = np.random.RandomState(seed)
    out = []
    for i in range(n):
        label = i % class_num + 1
        base = (label - 1) * _SYNTH_TOPIC_WORDS
        words = []
        for _ in range(30):
            if rs.rand() < 0.7:
                words.append(f"word{base + rs.randint(_SYNTH_TOPIC_WORDS)}")
            else:
                words.append(f"common{rs.randint(20)}")
        out.append((" ".join(words), label))
    return out


def synthetic_glove(vocab: List[str], dim: int = 50,
                    seed: int = 11) -> Dict[str, np.ndarray]:
    """Deterministic random embeddings for a vocabulary (hash-seeded so
    the same word always maps to the same vector)."""
    out = {}
    for w in vocab:
        h = (hash(w) ^ seed) % (2**31)
        out[w] = np.random.RandomState(h).randn(dim).astype(np.float32) * 0.1
    return out
