"""MovieLens fetcher.

Rebuild of ⟦«py»/dataset/movielens.py⟧: the reference downloads
``ml-1m.zip`` and exposes ``get_id_ratings`` (a (N, 3) int array of
1-based ``user_id, item_id, rating`` rows from ``ratings.dat``).  This
environment has no egress, so the fetcher reads an already-downloaded
layout from ``source_dir`` (the same on-disk shapes the reference's
download produces: ``ml-1m/ratings.dat`` with ``::``-separated fields,
or the zip) and raises with the canonical URL when absent.
``synthetic_movielens`` is the offline stand-in (same pattern as
dataset/mnist.py / dataset/news20.py): a latent-factor rating model
with the ml-1m id ranges scaled down.
"""

from __future__ import annotations

import os
import zipfile

import numpy as np

MOVIELENS_1M_URL = "http://files.grouplens.org/datasets/movielens/ml-1m.zip"


def get_id_ratings(source_dir: str = "/tmp/movielens/") -> np.ndarray:
    """(N, 3) int32 array of 1-based (user, item, rating) rows."""
    ratings = os.path.join(source_dir, "ml-1m", "ratings.dat")
    if not os.path.exists(ratings):
        zpath = os.path.join(source_dir, "ml-1m.zip")
        if os.path.exists(zpath):
            with zipfile.ZipFile(zpath) as z:
                z.extractall(source_dir)
        if not os.path.exists(ratings):
            raise FileNotFoundError(
                f"no MovieLens data under {source_dir}; download "
                f"{MOVIELENS_1M_URL} there first (no egress here)"
            )
    rows = []
    with open(ratings, encoding="latin-1") as f:
        for line in f:
            parts = line.strip().split("::")
            if len(parts) >= 3:
                rows.append((int(parts[0]), int(parts[1]), int(parts[2])))
    return np.asarray(rows, dtype=np.int32)


def latent_scores(n_users: int, n_items: int, dim: int = 4,
                  seed: int = 0) -> np.ndarray:
    """The hidden user x item affinity model behind every synthetic
    recommendation corpus here (also used by the NCF example's direct
    interaction generator)."""
    rs = np.random.RandomState(seed)
    return rs.randn(n_users, dim) @ rs.randn(n_items, dim).T


def synthetic_movielens(n_users: int = 200, n_items: int = 400,
                        per_user: int = 25, dim: int = 4,
                        seed: int = 0) -> np.ndarray:
    """Deterministic stand-in with the same (N, 3) shape: ratings 1-5
    quantized from a hidden latent-factor score model."""
    rs = np.random.RandomState(seed + 1)  # item sampling; scores use seed
    all_scores = latent_scores(n_users, n_items, dim, seed)
    # GLOBAL quantile buckets -> 1..5 ratings, so "rating >= 4" aligns
    # with the latent structure across users (implicit-feedback
    # protocols threshold absolutely, and real MovieLens stars do too)
    cuts = np.quantile(all_scores, [0.2, 0.4, 0.6, 0.8])
    rows = []
    for uid in range(n_users):
        items = rs.choice(n_items, size=per_user, replace=False)
        rating = 1 + np.searchsorted(cuts, all_scores[uid, items])
        for it, r in zip(items, rating):
            rows.append((uid + 1, it + 1, int(np.clip(r, 1, 5))))
    return np.asarray(rows, dtype=np.int32)
