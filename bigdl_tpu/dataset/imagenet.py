"""ImageNet-style directory ingestion feeding DistriOptimizer.

Rebuild of the reference's real-data training entries (VERDICT r2
missing #4): ⟦«bigdl»/models/resnet/TrainImageNet.scala⟧ /
⟦«bigdl»/models/inception⟧ read ImageNet as Hadoop sequence files into
an RDD, decode/augment per executor, and feed DistriOptimizer one cached
partition per worker.

TPU-native mapping: the file list is the partition table.  Every
process derives the SAME seeded global epoch permutation, takes its
contiguous slice of each global batch (the per-process iterator
contract DistriOptimizer's ``make_array_from_process_local_data``
assembly expects — see dataset/dataset.py DistributedDataSet), decodes
JPEGs on host CPU through the vision transform pipeline, and a
background prefetch thread keeps decode off the step's critical path
(native.PrefetchIterator).  The device never sees files — only fixed-
shape (B, C, H, W) float batches, so the jitted step compiles once.

Directory layout (torchvision/keras convention, what an extracted
ImageNet looks like):

    root/train/<wnid>/*.JPEG
    root/val/<wnid>/*.JPEG

Labels are 1-based indices into the sorted wnid list (BigDL's 1-based
label convention).
"""

from __future__ import annotations

import os
from typing import List, Optional, Sequence, Tuple

import numpy as np

from bigdl_tpu.common import RandomGenerator
from bigdl_tpu.dataset.dataset import DataSet

_IMG_EXTS = (".jpeg", ".jpg", ".png", ".bmp")


def scan_image_folder(split_dir: str) -> Tuple[List[str], np.ndarray, List[str]]:
    """Return (paths, 1-based labels, sorted class names) for a
    class-per-subdirectory image tree."""
    classes = sorted(
        d for d in os.listdir(split_dir)
        if os.path.isdir(os.path.join(split_dir, d))
    )
    paths: List[str] = []
    labels: List[int] = []
    for i, cls in enumerate(classes, start=1):
        cdir = os.path.join(split_dir, cls)
        for fname in sorted(os.listdir(cdir)):
            if fname.lower().endswith(_IMG_EXTS):
                paths.append(os.path.join(cdir, fname))
                labels.append(i)
    if not paths:
        raise FileNotFoundError(f"no images under {split_dir!r}")
    return paths, np.asarray(labels, np.float32), classes


def _decode(path: str, image_size: int, train: bool,
            mean: Sequence[float], std: Sequence[float]) -> np.ndarray:
    """File -> (C, H, W) float32, reference ImageNet recipe transforms:
    train = scale-shorter-side-256 + random crop + random hflip,
    eval = scale + center crop; channel-normalized.  Decode is
    PIL-backed when Pillow is present; plain ``.bmp`` files decode
    through the stdlib/numpy reader (transform/vision.read_image)
    otherwise.  Anything else without Pillow raises — a real-data entry
    must never silently train on stand-in pixels."""
    from bigdl_tpu.transform.vision import (
        AspectScale, CenterCrop, ChannelNormalize, ImageFeature,
        MatToTensor, RandomCrop, RandomHFlip, _resize_bilinear,
        read_image,
    )

    arr = read_image(path).astype(np.float32)
    feat = ImageFeature(arr)
    chain = [AspectScale(256 if image_size <= 224 else image_size + 32)]
    if train:
        chain += [RandomCrop(image_size, image_size), RandomHFlip()]
    else:
        chain += [CenterCrop(image_size, image_size)]
    chain += [ChannelNormalize(*mean, *std)]
    for t in chain:
        feat = t(feat)
    # extreme aspect ratios can leave the crop short (AspectScale's
    # max_size cap) — force the exact model shape so np.stack never
    # sees a ragged batch
    img = feat.image
    if img.shape[:2] != (image_size, image_size):
        feat[ImageFeature.MAT] = _resize_bilinear(img, image_size, image_size)
    feat = MatToTensor()(feat)
    return np.asarray(feat[ImageFeature.SAMPLE], np.float32)


class ImageFolderDataSet(DataSet):
    """Distributed file-backed image dataset (per-process contract).

    Yields this process's (local_batch, labels) slice of every global
    batch; DistriOptimizer assembles the global array across processes.
    Decode happens lazily per batch on host CPU.
    """

    per_process = True

    # reference ImageNet channel stats (RGB, 0-255 scale)
    IMAGENET_MEAN = (123.68, 116.78, 103.94)
    IMAGENET_STD = (58.395, 57.12, 57.375)

    def __init__(self, root: str, batch_size: int = 32, train: bool = True,
                 image_size: int = 224, split: Optional[str] = None,
                 mean: Sequence[float] = IMAGENET_MEAN,
                 std: Sequence[float] = IMAGENET_STD,
                 shuffle: bool = True,
                 process_id: Optional[int] = None,
                 num_processes: Optional[int] = None):
        split = split or ("train" if train else "val")
        split_dir = os.path.join(root, split)
        if not os.path.isdir(split_dir):
            if train:
                # flat layout (root/<cls>/*.jpg) accepted for training
                split_dir = root
            else:
                # an eval split must exist explicitly — falling back to
                # root would silently validate on the training images
                raise FileNotFoundError(
                    f"no {split!r} split under {root!r}"
                )
        self.paths, self.labels, self.classes = scan_image_folder(split_dir)
        self.batch_size = batch_size
        self.train_mode = train
        self.image_size = image_size
        self.mean, self.std = mean, std
        self.shuffle = shuffle
        self._pid = process_id
        self._nproc = num_processes

    def size(self) -> int:
        return len(self.paths)

    def class_num(self) -> int:
        return len(self.classes)

    def _world(self):
        if self._pid is not None and self._nproc is not None:
            return self._pid, self._nproc
        import jax

        return jax.process_index(), jax.process_count()

    def data(self, train: bool = True):
        from bigdl_tpu.dataset.dataset import iter_process_batches

        pid, nproc = self._world()
        n = len(self.paths)
        bs = self.batch_size
        augment = train and self.train_mode
        for mine in iter_process_batches(
            n, bs, pid, nproc, shuffle=train and self.shuffle,
        ):
            feats = np.stack([
                _decode(self.paths[i], self.image_size, augment,
                        self.mean, self.std)
                for i in mine
            ])
            yield feats, self.labels[mine]
        if not train and nproc == 1 and n % bs:
            # eval keeps the ragged tail (single-process only; a
            # multi-process eval drops it to keep shard shapes equal)
            tail = np.arange(n)[(n // bs) * bs:]
            feats = np.stack([
                _decode(self.paths[i], self.image_size, False,
                        self.mean, self.std)
                for i in tail
            ])
            yield feats, self.labels[tail]
