"""Sample & MiniBatch.

Rebuild of «bigdl»/dataset/Sample.scala and MiniBatch.scala.  A Sample is
one (features, label) record; a MiniBatch is the stacked batch the train
step consumes.  Variable-length features are padded at batch time
(``SampleToMiniBatch`` with padding params — the reference's
FeaturePadding path used by the text pipelines).
"""

from __future__ import annotations

from typing import Optional, Sequence

import numpy as np


class Sample:
    def __init__(self, features, labels):
        # features: one array or a list of arrays (table input)
        if isinstance(features, (list, tuple)):
            self.features = [np.asarray(f) for f in features]
            self._multi = True
        else:
            self.features = np.asarray(features)
            self._multi = False
        self.labels = np.asarray(labels)

    @staticmethod
    def from_ndarray(features, labels):
        """Python-BigDL spelling («py»/util/common.py Sample.from_ndarray)."""
        return Sample(features, labels)

    def feature(self):
        return self.features

    def label(self):
        return self.labels

    def __repr__(self):
        shape = (
            [f.shape for f in self.features] if self._multi else self.features.shape
        )
        return f"Sample(features={shape}, labels={self.labels.shape})"


class MiniBatch:
    def __init__(self, input, target):
        self.input = input
        self.target = target

    def size(self) -> int:
        arr = self.input[0] if isinstance(self.input, (list, tuple)) else self.input
        return arr.shape[0]

    def get_input(self):
        return self.input

    def get_target(self):
        return self.target


def _pad_stack(arrays: Sequence[np.ndarray], padding_value: float = 0.0,
               fixed_length: Optional[int] = None):
    """Stack arrays, padding dim 0 to the max (or fixed) length when shapes
    differ (reference: PaddingParam/FeaturePadding)."""
    shapes = {a.shape for a in arrays}
    if len(shapes) == 1 and fixed_length is None:
        return np.stack(arrays)
    max_len = fixed_length or max(a.shape[0] for a in arrays)
    out_shape = (len(arrays), max_len) + arrays[0].shape[1:]
    out = np.full(out_shape, padding_value, dtype=arrays[0].dtype)
    for i, a in enumerate(arrays):
        out[i, : a.shape[0]] = a
    return out


def samples_to_minibatch(samples: Sequence[Sample], padding_value: float = 0.0,
                         fixed_length: Optional[int] = None) -> MiniBatch:
    first = samples[0]
    if first._multi:
        n_inputs = len(first.features)
        inputs = [
            _pad_stack([s.features[i] for s in samples], padding_value, fixed_length)
            for i in range(n_inputs)
        ]
        inp = tuple(inputs)
    else:
        inp = _pad_stack([s.features for s in samples], padding_value, fixed_length)
    tgt = _pad_stack([s.labels for s in samples], padding_value)
    return MiniBatch(inp, tgt)
