"""Unbounded streaming datasets — continuous/online training input.

The reference trains on a cached RDD that is finite by construction;
the continuous ingest-retrain-redeploy loop the ROADMAP names needs an
*unbounded* input tier whose position in the stream is recoverable
state.  Three pieces:

* :class:`StreamSource` — a **replayable** record source: ``read(off)``
  yields records from an absolute offset, any number of times.  That
  replayability (a Kafka/log-style contract) is what makes
  exactly-once possible: nothing here ever needs a two-phase commit,
  because the training checkpoint *is* the commit point and the source
  can always be re-read from it.
* :class:`BoundedBuffer` — the source adapter: one producer thread
  pulls the source into a bounded in-memory queue.  A full buffer
  **backpressures** the producer (it waits, it does not drop), and the
  live depth is exported as ``bigdl_stream_buffer_depth`` — the queue
  signal the autoscaling policy loop (resilience/autoscale.py) scales
  on.
* :class:`StreamDataSet` — the ``DataSet`` the optimizers consume.  It
  assembles fixed-size batches (jit shape stability), carries a
  **per-record watermark** (the event time up to which the stream has
  been trained), and tracks two offsets: the *yielded* frontier (what
  left the iterator, possibly prefetched ahead) and the *trained*
  frontier (what a resolved train step actually consumed —
  :meth:`StreamDataSet.note_batch_trained`, called by the driver loop
  per dispatched batch).

**Exactly-once over crashes and resizes**: ``stream_checkpoint_state``
(the trained offset + watermark) rides the checkpoint ``extra`` next to
epoch/neval (optimizer._checkpoint_extra), and every resume path —
``elastic.restore_latest``, the DistriOptimizer in-process retry —
calls ``stream_restore``, which seeks the source back to the trained
offset and drops everything prefetched past it.  Records between the
checkpoint and a crash are re-read *and* re-trained against the
rolled-back weights, so each record is incorporated into the surviving
trajectory exactly once; a graceful stop (preemption / autoscale
resize) checkpoints the exact trained frontier, so nothing is replayed
at all.  Records buffered beyond the trained frontier at shutdown are
simply re-read after the seek — none dropped, none trained twice.
"""

from __future__ import annotations

import collections
import logging
import threading
import time
from typing import Iterator, NamedTuple, Optional

import numpy as np

from bigdl_tpu.dataset.dataset import DataSet
from bigdl_tpu.obs import names

log = logging.getLogger("bigdl_tpu.dataset")


class StreamRecord(NamedTuple):
    """One stream record: absolute ``offset`` (the record id), payload,
    and the source-assigned ``event_time`` the watermark tracks."""

    offset: int
    features: np.ndarray
    label: np.ndarray
    event_time: float


class StreamSource:
    """Replayable record source.

    ``read(offset)`` must yield :class:`StreamRecord`\\ s with
    consecutive offsets starting at ``offset``, and must be callable
    any number of times (resume = re-read).  A bounded source's
    iterator simply ends; an unbounded one never does.
    """

    def read(self, offset: int) -> Iterator[StreamRecord]:
        raise NotImplementedError

    def available(self) -> Optional[int]:
        """Records currently available (the ingest frontier), or None
        when unknown.  Lets the dataset export consumer lag."""
        return None


class SyntheticStream(StreamSource):
    """Deterministic synthetic stream for tests and smokes.

    Record ``i`` is a pure function of ``(seed, i)`` — replay from any
    offset is bit-identical, which is exactly the property the
    exactly-once audits key on.  The task is the same learnable
    linear-separation one the elastic smoke trains.  ``rate`` (records
    per second) simulates arrival time: ``read`` blocks until record
    ``i`` has "arrived", so a slow stream starves the buffer and a fast
    one fills it — the two ends of the autoscaler's queue band.
    """

    def __init__(self, feature_dim: int = 16, n_classes: int = 4,
                 seed: int = 0, limit: Optional[int] = None,
                 rate: Optional[float] = None, clock=time.monotonic):
        self.feature_dim = int(feature_dim)
        self.n_classes = int(n_classes)
        self.seed = int(seed)
        self.limit = None if limit is None else int(limit)
        self.rate = None if rate in (None, 0) else float(rate)
        self._clock = clock
        self._t0 = clock()
        # a fixed projection makes labels a deterministic function of
        # features, so the task is learnable and loss curves comparable
        rs = np.random.RandomState(self.seed)
        self._w = rs.randn(self.feature_dim, self.n_classes)

    def record(self, i: int) -> StreamRecord:
        rs = np.random.RandomState((self.seed * 1000003 + i) % (1 << 31))
        x = rs.randn(self.feature_dim).astype(np.float32)
        y = np.float32(int(np.argmax(x @ self._w)) + 1)  # 1-based labels
        return StreamRecord(i, x, y, float(i))

    def available(self) -> Optional[int]:
        if self.rate is None:
            return self.limit
        arrived = int((self._clock() - self._t0) * self.rate)
        return arrived if self.limit is None else min(self.limit, arrived)

    def read(self, offset: int) -> Iterator[StreamRecord]:
        i = int(offset)
        if self.rate is not None:
            avail = self.available()
            if avail is not None and avail < i:
                # a resumed consumer reads RETAINED history instantly:
                # records below its first offset already arrived in a
                # previous attempt's lifetime — rebase the arrival
                # clock so only the live edge is rate-limited
                self._t0 = self._clock() - i / self.rate
        while self.limit is None or i < self.limit:
            if self.rate is not None:
                # arrival simulation: record i exists only after i/rate
                while True:
                    avail = self.available()
                    if avail is None or avail > i:
                        break
                    time.sleep(min(0.05, 1.0 / self.rate))
            yield self.record(i)
            i += 1


_END = object()  # buffer sentinel: the source's iterator ended


class BoundedBuffer:
    """Bounded producer/consumer queue between a source and the batch
    assembler.

    One daemon producer thread pulls ``source.read(offset)``; a full
    buffer makes it *wait* (backpressure — counted in
    ``bigdl_stream_backpressure_waits_total``), never drop.  The
    consumer blocks in :meth:`get` until a record (or the end sentinel)
    arrives.  The live depth is published as the
    ``bigdl_stream_buffer_depth`` gauge — the queue-depth signal the
    autoscaling policy loop reads off ``/metrics``."""

    def __init__(self, source: StreamSource, capacity: int):
        self.source = source
        self.capacity = max(1, int(capacity))
        self._q: collections.deque = collections.deque()
        self._cond = threading.Condition()
        self._stop = False
        self._error: Optional[BaseException] = None
        self._thread: Optional[threading.Thread] = None
        from bigdl_tpu import obs

        reg = obs.get_registry()
        self._depth_gauge = reg.gauge(
            names.STREAM_BUFFER_DEPTH,
            "Records buffered between the stream source and the trainer")
        self._bp_counter = reg.counter(
            names.STREAM_BACKPRESSURE_WAITS_TOTAL,
            "Producer waits on a full stream buffer")

    def depth(self) -> int:
        with self._cond:
            return len(self._q)

    def start(self, offset: int):
        self._thread = threading.Thread(
            target=self._produce, args=(int(offset),),
            name="bigdl-stream-producer", daemon=True)
        self._thread.start()
        return self

    def _produce(self, offset: int):
        try:
            for rec in self.source.read(offset):
                with self._cond:
                    while len(self._q) >= self.capacity and not self._stop:
                        self._bp_counter.inc()
                        self._cond.wait(timeout=0.1)
                    if self._stop:
                        return
                    self._q.append(rec)
                    self._depth_gauge.set(float(len(self._q)))
                    self._cond.notify_all()
        except BaseException as e:  # noqa: BLE001 — surfaced to consumer
            self._error = e
        finally:
            with self._cond:
                self._q.append(_END)
                self._cond.notify_all()

    def get(self, timeout: Optional[float] = None):
        """Next record, or ``None`` when the stream ended.  Re-raises a
        producer-side error on the consumer thread (a broken source
        must fail the step, not silently end the stream)."""
        deadline = None if timeout is None else time.monotonic() + timeout
        with self._cond:
            while not self._q:
                # the consumer outran the producer: decay the depth
                # gauge NOW, not at the producer's next put — the
                # autoscaler's queue signal must fall promptly when the
                # double-buffered consumer drains faster than the
                # producer refills (ISSUE 11 satellite)
                self._depth_gauge.set(0.0)
                remain = None if deadline is None \
                    else deadline - time.monotonic()
                if remain is not None and remain <= 0:
                    raise TimeoutError(
                        f"stream buffer empty for {timeout:g}s (source "
                        "stalled?)")
                self._cond.wait(timeout=0.1 if remain is None
                                else min(0.1, remain))
            rec = self._q.popleft()
            if rec is _END:
                self._q.append(_END)  # idempotent end for late callers
                # the sentinel is not a record: a drained stream's
                # queue signal is zero, not the last put's depth
                self._depth_gauge.set(0.0)
                if self._error is not None:
                    raise RuntimeError(
                        "stream source failed") from self._error
                return None
            # stamp on takes as well as puts, so the signal tracks the
            # consumer side of the queue too (the end sentinel is not a
            # record — don't let it hold the gauge at 1)
            depth = len(self._q)
            if depth and self._q[-1] is _END:
                depth -= 1
            self._depth_gauge.set(float(depth))
            self._cond.notify_all()
            return rec

    def stop(self):
        with self._cond:
            self._stop = True
            self._cond.notify_all()
        if self._thread is not None:
            self._thread.join(timeout=2.0)
            self._thread = None
        self._depth_gauge.set(0.0)


class StreamDataSet(DataSet):
    """``DataSet`` over an unbounded (or bounded) :class:`StreamSource`.

    Yields fixed-shape ``(features, labels)`` batches of exactly
    ``batch_size`` consecutive records — a ragged tail below a full
    batch stays *unconsumed* at the trained frontier (never dropped,
    never half-trained; a later epoch with more arrivals picks it up).
    ``epoch_records`` bounds one ``data()`` iterator so epoch-keyed
    triggers stay meaningful on continuous ingest; 0/None = the
    iterator runs until the source ends (use ``Trigger.max_iteration``).

    The exactly-once contract (module docstring): the driver loop calls
    :meth:`note_batch_trained` once per dispatched batch, checkpoints
    ride :meth:`stream_checkpoint_state`, resumes call
    :meth:`stream_restore`.  One active iterator at a time (the
    optimizer's driver loop guarantees this); a fresh ``data()`` call
    always restarts from the trained frontier, so prefetched-but-
    untrained records from an abandoned iterator are re-read."""

    per_process = False  # yields GLOBAL batches; the optimizer shards
    streaming = True

    def __init__(self, source: StreamSource, batch_size: int = 32,
                 epoch_records: Optional[int] = None,
                 buffer_records: Optional[int] = None,
                 start_offset: int = 0, poll_timeout_s: float = 60.0,
                 audit_log: bool = False):
        from bigdl_tpu.config import refresh_from_env

        cfg = refresh_from_env()
        self.source = source
        self.batch_size = int(batch_size)
        if epoch_records is None:
            epoch_records = cfg.stream_epoch_records or None
        if epoch_records is not None:
            epoch_records = int(epoch_records)
            if epoch_records % self.batch_size:
                raise ValueError(
                    f"epoch_records {epoch_records} not divisible by "
                    f"batch_size {self.batch_size}")
        self.epoch_records = epoch_records
        self.buffer_records = int(buffer_records or cfg.stream_buffer)
        self.poll_timeout_s = float(poll_timeout_s)
        self._lock = threading.Lock()
        self._offset = int(start_offset)      # yielded frontier
        self._trained = {"offset": int(start_offset), "watermark": None,
                         "records": 0}
        self._pending: collections.deque = collections.deque()
        # optional in-memory audit trail of trained (start, end) ranges
        # — what the exactly-once smoke asserts over
        self.audit_log: Optional[list] = [] if audit_log else None
        from bigdl_tpu import obs

        reg = obs.get_registry()
        self._offset_gauge = reg.gauge(
            names.STREAM_OFFSET,
            "Trained stream frontier (records incorporated into the "
            "current trajectory)")
        self._watermark_gauge = reg.gauge(
            names.STREAM_WATERMARK,
            "Event-time watermark of the trained stream frontier")
        self._lag_gauge = reg.gauge(
            names.STREAM_LAG_RECORDS,
            "Ingest frontier minus trained frontier (consumer lag)")
        self._records_counter = reg.counter(
            names.STREAM_RECORDS_TOTAL,
            "Stream records consumed into training batches")

    # ------------------------------------------------------------ state
    def size(self) -> int:
        avail = self.source.available()
        return self.epoch_records or avail or self.batch_size

    def seek(self, offset: int, watermark: Optional[float] = None):
        """Reposition the stream: the next yielded record is
        ``offset``.  Drops every pending (yielded-untrained) batch —
        they will be re-read."""
        with self._lock:
            self._offset = int(offset)
            self._pending.clear()
            self._trained = {"offset": int(offset), "watermark": watermark,
                             "records": self._trained["records"]}
            self._offset_gauge.set(float(offset))
            if watermark is not None:
                self._watermark_gauge.set(float(watermark))

    def stream_checkpoint_state(self) -> dict:
        """What rides the checkpoint ``extra`` (optimizer
        ``_checkpoint_extra``): the trained offset + watermark.  The
        offset is the exactly-once commit point — everything below it
        is in the weights, everything at/above it will be re-read."""
        with self._lock:
            return dict(self._trained)

    def stream_restore(self, state: Optional[dict]):
        """Resume from a checkpoint's ``stream`` state (both resume
        paths call this; a pre-stream checkpoint restarts at 0 —
        loudly, because that replays the whole retained stream)."""
        state = state or {}
        if "offset" not in state:
            log.warning("stream_restore: checkpoint carries no stream "
                        "state — restarting the stream at offset 0")
        self.seek(int(state.get("offset", 0)), state.get("watermark"))
        from bigdl_tpu import obs

        obs.get_tracer().event(
            "elastic.stream_restore", offset=self._trained["offset"],
            watermark=self._trained["watermark"])

    def note_batch_trained(self) -> Optional[dict]:
        """Advance the trained frontier by one dispatched batch (the
        driver loop calls this right after it hands a batch to the
        train step).  All dispatched steps resolve before any
        checkpoint (the driver flushes its pipeline first), so the
        frontier is always checkpoint-consistent."""
        with self._lock:
            if not self._pending:
                log.warning("note_batch_trained with no pending batch "
                            "(iterator restarted underneath the loop?)")
                return None
            meta = self._pending.popleft()
            self._trained["offset"] = meta["end"]
            self._trained["watermark"] = meta["watermark"]
            self._trained["records"] += meta["end"] - meta["start"]
            self._offset_gauge.set(float(meta["end"]))
            self._watermark_gauge.set(float(meta["watermark"]))
            avail = self.source.available()
            if avail is not None:
                self._lag_gauge.set(float(max(0, avail - meta["end"])))
            if self.audit_log is not None:
                self.audit_log.append((meta["start"], meta["end"]))
            return meta

    # ------------------------------------------------------------- data
    def data(self, train: bool = True):
        del train  # a stream has no shuffle and no eval-tail variant
        with self._lock:
            # always restart from the TRAINED frontier: anything a
            # previous iterator yielded but the loop never trained is
            # re-read, not skipped
            self._pending.clear()
            self._offset = self._trained["offset"]
            start = self._offset
        buf = BoundedBuffer(self.source, self.buffer_records).start(start)
        feats, lbls = [], []
        batch_start = start
        watermark = None
        yielded = 0
        try:
            while self.epoch_records is None \
                    or yielded < self.epoch_records:
                rec = buf.get(timeout=self.poll_timeout_s)
                if rec is None:
                    break  # bounded source ended; ragged tail pends
                feats.append(rec.features)
                lbls.append(rec.label)
                watermark = rec.event_time if watermark is None \
                    else max(watermark, rec.event_time)
                if len(feats) < self.batch_size:
                    continue
                meta = {"start": batch_start, "end": rec.offset + 1,
                        "watermark": watermark}
                with self._lock:
                    self._pending.append(meta)
                    self._offset = rec.offset + 1
                self._records_counter.inc(self.batch_size)
                yield np.stack(feats), np.asarray(lbls)
                yielded += self.batch_size
                feats, lbls = [], []
                batch_start = rec.offset + 1
                watermark = None
        finally:
            buf.stop()
