"""MNIST loader.

Rebuild of «bigdl»/models/lenet/Utils.scala's idx-format reader (and the
«py»/dataset/mnist.py fetcher).  Reads the standard idx files if present;
with no dataset on disk and no network, falls back to a deterministic
*synthetic* MNIST-like task (class-template digits + noise) that is
learnable, so convergence smoke tests (SURVEY.md §4.6) run hermetically.
"""

from __future__ import annotations

import gzip
import os
import struct

import numpy as np

TRAIN_MEAN = 0.13066047740239506 * 255
TRAIN_STD = 0.3081078 * 255


def _read_idx_images(path: str) -> np.ndarray:
    op = gzip.open if path.endswith(".gz") else open
    with op(path, "rb") as f:
        magic, n, rows, cols = struct.unpack(">IIII", f.read(16))
        assert magic == 2051, f"bad magic {magic} in {path}"
        return np.frombuffer(f.read(), dtype=np.uint8).reshape(n, rows, cols)


def _read_idx_labels(path: str) -> np.ndarray:
    op = gzip.open if path.endswith(".gz") else open
    with op(path, "rb") as f:
        magic, n = struct.unpack(">II", f.read(8))
        assert magic == 2049, f"bad magic {magic} in {path}"
        return np.frombuffer(f.read(), dtype=np.uint8)


def synthetic_mnist(n: int, seed: int = 42, n_classes: int = 10,
                    image_size: int = 28, template_seed: int = 1234):
    """Deterministic learnable stand-in: each class is a fixed random
    template plus Gaussian noise.  The templates come from a *fixed*
    ``template_seed`` shared by every split (train/test must share the
    class structure or validation is unlearnable); ``seed`` only drives
    the sampling + noise.  Returns (images[n,28,28] float in 0..255-ish
    scale, labels[n] 1-based)."""
    trng = np.random.RandomState(template_seed)
    templates = trng.uniform(0, 255, size=(n_classes, image_size, image_size))
    rng = np.random.RandomState(seed)
    labels = rng.randint(0, n_classes, size=n)
    images = templates[labels] + rng.normal(0, 32.0, size=(n, image_size, image_size))
    images = np.clip(images, 0, 255).astype(np.float32)
    return images, (labels + 1).astype(np.float32)  # 1-based like the reference


def load_mnist(data_dir: str = None, subset: str = "train",
               synthetic_n: int = 2048):
    """Returns (images [N, 28, 28] float32 raw 0-255, labels [N] 1-based
    float32).  Looks for idx(.gz) files under ``data_dir``; synthesizes
    when absent."""
    names = {
        "train": ("train-images-idx3-ubyte", "train-labels-idx1-ubyte"),
        "test": ("t10k-images-idx3-ubyte", "t10k-labels-idx1-ubyte"),
    }[subset]
    if data_dir:
        for ext in ("", ".gz"):
            img_p = os.path.join(data_dir, names[0] + ext)
            lbl_p = os.path.join(data_dir, names[1] + ext)
            if os.path.exists(img_p) and os.path.exists(lbl_p):
                images = _read_idx_images(img_p).astype(np.float32)
                labels = _read_idx_labels(lbl_p).astype(np.float32) + 1.0
                return images, labels
    seed = 42 if subset == "train" else 43
    return synthetic_mnist(synthetic_n, seed=seed)


def normalize(images: np.ndarray) -> np.ndarray:
    """Reference: GreyImgNormalizer(trainMean, trainStd)."""
    return (images - TRAIN_MEAN) / TRAIN_STD
