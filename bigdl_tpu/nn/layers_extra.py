"""Long-tail layer library — the breadth families beyond nn/layers.py.

Rebuild of the remaining reference modules (SURVEY.md §2.1 "Layer
library" ⟦«bigdl»/nn/⟧; VERDICT round-1 item 2 names the missing
families): locally-connected and separable convolutions, temporal
pooling, shrink activations, noise layers, spatial dropouts, cropping /
resizing, the Spatial*Normalization trio, shape utilities, and misc
modules (MaskedSelect, PairwiseDistance, …).

TPU notes: locally-connected convs lower to
``lax.conv_general_dilated_local`` (unshared kernels are still one XLA
contraction); separable conv is a depthwise ``feature_group_count`` conv
feeding a 1x1 — XLA fuses the pair; everything elementwise fuses into
producers.  ``MaskedSelect`` is the one data-dependent-shape module: it
runs eagerly (as the reference does) and is documented as non-jittable.
"""

from __future__ import annotations

import math
from typing import Optional, Sequence

import numpy as np

from bigdl_tpu.common import RandomGenerator
from bigdl_tpu.nn.layers import (
    InitializationMethod,
    MsraFiller,
    SpatialConvolution,
    Xavier,
    _auto_batch,
    _pool_pad,
    _to_device,
)
from bigdl_tpu.nn.module import AbstractModule, Container, Sequential


def _jnp():
    import jax.numpy as jnp

    return jnp


def _lax():
    import jax.lax as lax

    return lax


# --------------------------------------------------------------------------
# Convolution variants
# --------------------------------------------------------------------------


class LocallyConnected1D(AbstractModule):
    """⟦«bigdl»/nn/LocallyConnected1D.scala⟧ — temporal conv with
    *unshared* kernels: one weight per output frame.  Input (B, T, F);
    reference signature (nInputFrame, inputFrameSize, outputFrameSize,
    kernelW, strideW)."""

    param_names = ("weight", "bias")

    def __init__(
        self,
        n_input_frame: int,
        input_frame_size: int,
        output_frame_size: int,
        kernel_w: int,
        stride_w: int = 1,
        with_bias: bool = True,
        init_method: Optional[InitializationMethod] = None,
    ):
        super().__init__()
        self._config = dict(
            n_input_frame=n_input_frame,
            input_frame_size=input_frame_size,
            output_frame_size=output_frame_size,
            kernel_w=kernel_w,
            stride_w=stride_w,
            with_bias=with_bias,
        )
        self.n_input_frame = n_input_frame
        self.input_frame_size = input_frame_size
        self.output_frame_size = output_frame_size
        self.kernel_w = kernel_w
        self.stride_w = stride_w
        self.with_bias = with_bias
        self.n_output_frame = (n_input_frame - kernel_w) // stride_w + 1
        self._init_method = init_method or Xavier()
        self.reset()

    def reset(self):
        fan_in = self.input_frame_size * self.kernel_w
        fan_out = self.output_frame_size * self.kernel_w
        # (T_out, kW*F_in, F_out) — one kernel per output frame
        w = self._init_method.init(
            (self.n_output_frame, self.kernel_w * self.input_frame_size,
             self.output_frame_size),
            fan_in,
            fan_out,
        )
        self.weight = _to_device(w)
        if self.with_bias:
            self.bias = _to_device(
                np.zeros((self.n_output_frame, self.output_frame_size),
                         dtype=np.float32)
            )
        else:
            self.bias = None
        return self

    def update_output_pure(self, params, input, *, training=False, rng=None):
        jnp = _jnp()
        x, squeezed = _auto_batch(input, 3)
        # gather the kW-frame windows: (B, T_out, kW, F_in)
        starts = jnp.arange(self.n_output_frame) * self.stride_w
        idx = starts[:, None] + jnp.arange(self.kernel_w)[None, :]
        windows = x[:, idx, :]  # (B, T_out, kW, F_in)
        windows = windows.reshape(
            x.shape[0], self.n_output_frame,
            self.kernel_w * self.input_frame_size,
        )
        w = params["weight"].astype(x.dtype)
        y = jnp.einsum("btk,tko->bto", windows, w)
        if self.with_bias:
            y = y + params["bias"].astype(y.dtype)[None]
        return y[0] if squeezed else y

    def __repr__(self):
        return (
            f"LocallyConnected1D({self.input_frame_size}->"
            f"{self.output_frame_size}, k={self.kernel_w})"
        )


class LocallyConnected2D(AbstractModule):
    """⟦«bigdl»/nn/LocallyConnected2D.scala⟧ — 2-D conv with unshared
    kernels (one per output position) over NCHW input.  Lowers to
    ``lax.conv_general_dilated_local`` — still a single XLA contraction."""

    param_names = ("weight", "bias")

    def __init__(
        self,
        n_input_plane: int,
        input_width: int,
        input_height: int,
        n_output_plane: int,
        kernel_w: int,
        kernel_h: int,
        stride_w: int = 1,
        stride_h: int = 1,
        pad_w: int = 0,
        pad_h: int = 0,
        with_bias: bool = True,
        init_method: Optional[InitializationMethod] = None,
    ):
        super().__init__()
        self._config = dict(
            n_input_plane=n_input_plane, input_width=input_width,
            input_height=input_height, n_output_plane=n_output_plane,
            kernel_w=kernel_w, kernel_h=kernel_h, stride_w=stride_w,
            stride_h=stride_h, pad_w=pad_w, pad_h=pad_h, with_bias=with_bias,
        )
        self.n_input_plane = n_input_plane
        self.input_width, self.input_height = input_width, input_height
        self.n_output_plane = n_output_plane
        self.kernel_w, self.kernel_h = kernel_w, kernel_h
        self.stride_w, self.stride_h = stride_w, stride_h
        self.pad_w, self.pad_h = pad_w, pad_h
        self.with_bias = with_bias
        self.out_h = (input_height + 2 * pad_h - kernel_h) // stride_h + 1
        self.out_w = (input_width + 2 * pad_w - kernel_w) // stride_w + 1
        self._init_method = init_method or Xavier()
        self.reset()

    def reset(self):
        fan_in = self.n_input_plane * self.kernel_h * self.kernel_w
        fan_out = self.n_output_plane * self.kernel_h * self.kernel_w
        # conv_general_dilated_local rhs (OIHW numbers): the "I" axis is
        # the unfolded I*kh*kw patch, spatial axes are *output* positions
        w = self._init_method.init(
            (self.n_output_plane,
             self.n_input_plane * self.kernel_h * self.kernel_w,
             self.out_h, self.out_w),
            fan_in,
            fan_out,
        )
        self.weight = _to_device(w)
        if self.with_bias:
            self.bias = _to_device(
                np.zeros((self.n_output_plane, self.out_h, self.out_w),
                         dtype=np.float32)
            )
        else:
            self.bias = None
        return self

    def update_output_pure(self, params, input, *, training=False, rng=None):
        lax = _lax()
        x, squeezed = _auto_batch(input, 4)
        w = params["weight"].astype(x.dtype)
        y = lax.conv_general_dilated_local(
            x,
            w,
            window_strides=(self.stride_h, self.stride_w),
            padding=[(self.pad_h, self.pad_h), (self.pad_w, self.pad_w)],
            filter_shape=(self.kernel_h, self.kernel_w),
            dimension_numbers=("NCHW", "OIHW", "NCHW"),
        )
        if self.with_bias:
            y = y + params["bias"].astype(y.dtype)[None]
        return y[0] if squeezed else y

    def __repr__(self):
        return (
            f"LocallyConnected2D({self.n_input_plane}->"
            f"{self.n_output_plane}, {self.kernel_h}x{self.kernel_w})"
        )


class SpatialSeparableConvolution(AbstractModule):
    """⟦«bigdl»/nn/SpatialSeparableConvolution.scala⟧ — depthwise conv
    (depth_multiplier kernels per input plane) followed by a 1x1
    pointwise conv.  One ``feature_group_count`` conv + one 1x1 — XLA
    fuses the pair into consecutive MXU contractions."""

    param_names = ("depth_weight", "point_weight", "bias")

    def __init__(
        self,
        n_input_channel: int,
        n_output_channel: int,
        depth_multiplier: int,
        kernel_w: int,
        kernel_h: int,
        stride_w: int = 1,
        stride_h: int = 1,
        pad_w: int = 0,
        pad_h: int = 0,
        with_bias: bool = True,
        init_method: Optional[InitializationMethod] = None,
    ):
        super().__init__()
        self._config = dict(
            n_input_channel=n_input_channel,
            n_output_channel=n_output_channel,
            depth_multiplier=depth_multiplier,
            kernel_w=kernel_w, kernel_h=kernel_h,
            stride_w=stride_w, stride_h=stride_h,
            pad_w=pad_w, pad_h=pad_h, with_bias=with_bias,
        )
        self.n_input_channel = n_input_channel
        self.n_output_channel = n_output_channel
        self.depth_multiplier = depth_multiplier
        self.kernel_w, self.kernel_h = kernel_w, kernel_h
        self.stride_w, self.stride_h = stride_w, stride_h
        self.pad_w, self.pad_h = pad_w, pad_h
        self.with_bias = with_bias
        self._init_method = init_method or MsraFiller(False)
        self.reset()

    def reset(self):
        mid = self.n_input_channel * self.depth_multiplier
        k = self.kernel_h * self.kernel_w
        dw = self._init_method.init(
            (mid, 1, self.kernel_h, self.kernel_w),
            self.depth_multiplier * k, self.depth_multiplier * k,
        )
        pw = self._init_method.init(
            (self.n_output_channel, mid, 1, 1), mid, self.n_output_channel
        )
        self.depth_weight = _to_device(dw)
        self.point_weight = _to_device(pw)
        if self.with_bias:
            self.bias = _to_device(
                np.zeros(self.n_output_channel, dtype=np.float32)
            )
        else:
            self.bias = None
        return self

    def update_output_pure(self, params, input, *, training=False, rng=None):
        lax = _lax()
        x, squeezed = _auto_batch(input, 4)
        pads = (
            "SAME"
            if -1 in (self.pad_h, self.pad_w)
            else [(self.pad_h, self.pad_h), (self.pad_w, self.pad_w)]
        )
        mid = lax.conv_general_dilated(
            x,
            params["depth_weight"].astype(x.dtype),
            window_strides=(self.stride_h, self.stride_w),
            padding=pads,
            feature_group_count=self.n_input_channel,
            dimension_numbers=("NCHW", "OIHW", "NCHW"),
        )
        y = lax.conv_general_dilated(
            mid,
            params["point_weight"].astype(x.dtype),
            window_strides=(1, 1),
            padding="VALID",
            dimension_numbers=("NCHW", "OIHW", "NCHW"),
        )
        if self.with_bias:
            y = y + params["bias"].astype(y.dtype).reshape(1, -1, 1, 1)
        return y[0] if squeezed else y

    def __repr__(self):
        return (
            f"SpatialSeparableConvolution({self.n_input_channel}->"
            f"{self.n_output_channel}, x{self.depth_multiplier})"
        )


class SpatialShareConvolution(SpatialConvolution):
    """⟦«bigdl»/nn/SpatialShareConvolution.scala⟧ — identical math to
    SpatialConvolution; the reference variant only shares im2col buffers
    across replicas to save executor memory.  Under XLA there is no
    im2col buffer, so the layer *is* SpatialConvolution — kept as its own
    class for API/serialization parity."""


class SpatialConvolutionMap(AbstractModule):
    """⟦«bigdl»/nn/SpatialConvolutionMap.scala⟧ — convolution with an
    explicit connection table: rows of 1-based (input_plane,
    output_plane) pairs.  Realised as a full conv with a binary
    connectivity mask folded into the weight — one dense MXU contraction
    instead of the reference's per-connection loops (sparse convs don't
    pay on TPU)."""

    param_names = ("weight", "bias")

    def __init__(
        self,
        conn_table,
        kernel_w: int,
        kernel_h: int,
        stride_w: int = 1,
        stride_h: int = 1,
        pad_w: int = 0,
        pad_h: int = 0,
        init_method: Optional[InitializationMethod] = None,
    ):
        super().__init__()
        conn = np.asarray(conn_table, dtype=np.int64).reshape(-1, 2)
        self._config = dict(
            conn_table=conn.tolist(),
            kernel_w=kernel_w, kernel_h=kernel_h,
            stride_w=stride_w, stride_h=stride_h,
            pad_w=pad_w, pad_h=pad_h,
        )
        self.conn = conn
        self.n_input_plane = int(conn[:, 0].max())
        self.n_output_plane = int(conn[:, 1].max())
        self.kernel_w, self.kernel_h = kernel_w, kernel_h
        self.stride_w, self.stride_h = stride_w, stride_h
        self.pad_w, self.pad_h = pad_w, pad_h
        mask = np.zeros((self.n_output_plane, self.n_input_plane, 1, 1),
                        dtype=np.float32)
        mask[conn[:, 1] - 1, conn[:, 0] - 1, 0, 0] = 1.0
        self._mask = _to_device(mask)
        self._init_method = init_method or MsraFiller(False)
        self.reset()

    @staticmethod
    def full(n_in: int, n_out: int):
        """Reference: SpatialConvolutionMap.full — all-to-all table."""
        return [[i + 1, o + 1] for o in range(n_out) for i in range(n_in)]

    @staticmethod
    def one_to_one(n: int):
        """Reference: SpatialConvolutionMap.oneToOne."""
        return [[i + 1, i + 1] for i in range(n)]

    def reset(self):
        # fan-in per output = its connection count * kernel area
        per_out = np.bincount(self.conn[:, 1] - 1,
                              minlength=self.n_output_plane)
        fan_in = int(per_out.max()) * self.kernel_h * self.kernel_w
        w = self._init_method.init(
            (self.n_output_plane, self.n_input_plane,
             self.kernel_h, self.kernel_w),
            fan_in,
            fan_in,
        )
        self.weight = _to_device(w * np.asarray(self._mask))
        self.bias = _to_device(
            np.zeros(self.n_output_plane, dtype=np.float32)
        )
        return self

    def update_output_pure(self, params, input, *, training=False, rng=None):
        lax = _lax()
        x, squeezed = _auto_batch(input, 4)
        w = params["weight"].astype(x.dtype) * self._mask.astype(x.dtype)
        y = lax.conv_general_dilated(
            x,
            w,
            window_strides=(self.stride_h, self.stride_w),
            padding=[(self.pad_h, self.pad_h), (self.pad_w, self.pad_w)],
            dimension_numbers=("NCHW", "OIHW", "NCHW"),
        )
        y = y + params["bias"].astype(y.dtype).reshape(1, -1, 1, 1)
        return y[0] if squeezed else y


class TemporalMaxPooling(AbstractModule):
    """⟦«bigdl»/nn/TemporalMaxPooling.scala⟧ — max pool over the frame
    axis of a (B, T, F) tensor."""

    def __init__(self, k_w: int, d_w: Optional[int] = None):
        super().__init__()
        self.k_w = k_w
        self.d_w = d_w if d_w is not None else k_w
        self._config = dict(k_w=k_w, d_w=self.d_w)

    def update_output_pure(self, params, input, *, training=False, rng=None):
        lax = _lax()
        jnp = _jnp()
        x, squeezed = _auto_batch(input, 3)
        y = lax.reduce_window(
            x,
            -jnp.inf,
            lax.max,
            window_dimensions=(1, self.k_w, 1),
            window_strides=(1, self.d_w, 1),
            padding=[(0, 0), (0, 0), (0, 0)],
        )
        return y[0] if squeezed else y

    def __repr__(self):
        return f"TemporalMaxPooling({self.k_w}, {self.d_w})"


class TemporalAveragePooling(AbstractModule):
    """Average pool over the frame axis of a (B, T, F) tensor — the
    Keras ``AveragePooling1D`` core (the reference expressed it via its
    keras layer set; no classic-module analogue)."""

    def __init__(self, k_w: int, d_w: Optional[int] = None):
        super().__init__()
        self.k_w = k_w
        self.d_w = d_w if d_w is not None else k_w
        self._config = dict(k_w=k_w, d_w=self.d_w)

    def update_output_pure(self, params, input, *, training=False, rng=None):
        lax = _lax()
        jnp = _jnp()
        x, squeezed = _auto_batch(input, 3)
        y = lax.reduce_window(
            x,
            jnp.zeros((), x.dtype),
            lax.add,
            window_dimensions=(1, self.k_w, 1),
            window_strides=(1, self.d_w, 1),
            padding=[(0, 0), (0, 0), (0, 0)],
        ) / self.k_w
        return y[0] if squeezed else y

    def __repr__(self):
        return f"TemporalAveragePooling({self.k_w}, {self.d_w})"


# --------------------------------------------------------------------------
# Shrink-family activations
# --------------------------------------------------------------------------


class _Stateless(AbstractModule):
    def __init__(self, **config):
        super().__init__()
        self._config = config

    def __repr__(self):
        return type(self).__name__


class SoftShrink(_Stateless):
    """⟦«bigdl»/nn/SoftShrink.scala⟧ — x∓λ outside (−λ, λ), 0 inside."""

    def __init__(self, lambda_: float = 0.5):
        super().__init__(lambda_=lambda_)
        self.lambda_ = lambda_

    def update_output_pure(self, params, input, *, training=False, rng=None):
        jnp = _jnp()
        lam = self.lambda_
        return jnp.where(
            input > lam, input - lam,
            jnp.where(input < -lam, input + lam, 0.0),
        ).astype(input.dtype)


class HardShrink(_Stateless):
    """⟦«bigdl»/nn/HardShrink.scala⟧ — identity outside (−λ, λ), 0
    inside."""

    def __init__(self, lambda_: float = 0.5):
        super().__init__(lambda_=lambda_)
        self.lambda_ = lambda_

    def update_output_pure(self, params, input, *, training=False, rng=None):
        jnp = _jnp()
        lam = self.lambda_
        return jnp.where(jnp.abs(input) > lam, input, 0.0).astype(input.dtype)


class TanhShrink(_Stateless):
    """⟦«bigdl»/nn/TanhShrink.scala⟧ — x − tanh(x)."""

    def update_output_pure(self, params, input, *, training=False, rng=None):
        return input - _jnp().tanh(input)


class LogSigmoid(_Stateless):
    """⟦«bigdl»/nn/LogSigmoid.scala⟧ — log(1/(1+exp(−x))), computed
    stably as −softplus(−x)."""

    def update_output_pure(self, params, input, *, training=False, rng=None):
        import jax

        return -jax.nn.softplus(-input)


class RReLU(_Stateless):
    """⟦«bigdl»/nn/RReLU.scala⟧ — randomized leaky ReLU: negative slope
    ~ U(lower, upper) per element at train time, fixed (lower+upper)/2
    at eval."""

    def __init__(self, lower: float = 1.0 / 8, upper: float = 1.0 / 3,
                 inplace: bool = False):
        super().__init__(lower=lower, upper=upper)
        self.lower, self.upper = lower, upper

    def update_output_pure(self, params, input, *, training=False, rng=None):
        jnp = _jnp()
        if training and rng is not None:
            import jax

            slope = jax.random.uniform(
                rng, input.shape, minval=self.lower, maxval=self.upper,
                dtype=jnp.float32,
            ).astype(input.dtype)
        else:
            slope = (self.lower + self.upper) / 2.0
        return jnp.where(input >= 0, input, input * slope)


# --------------------------------------------------------------------------
# Noise layers
# --------------------------------------------------------------------------


class GaussianDropout(_Stateless):
    """⟦«bigdl»/nn/GaussianDropout.scala⟧ — multiplicative N(1, p/(1−p))
    noise at train time, identity at eval."""

    def __init__(self, rate: float):
        super().__init__(rate=rate)
        if not 0.0 <= rate < 1.0:
            raise ValueError("rate must be in [0, 1)")
        self.rate = rate

    def update_output_pure(self, params, input, *, training=False, rng=None):
        if not training or rng is None or self.rate == 0.0:
            return input
        import jax

        std = math.sqrt(self.rate / (1.0 - self.rate))
        noise = 1.0 + std * jax.random.normal(rng, input.shape,
                                              dtype=input.dtype)
        return input * noise


class GaussianNoise(_Stateless):
    """⟦«bigdl»/nn/GaussianNoise.scala⟧ — additive N(0, σ²) noise at
    train time, identity at eval."""

    def __init__(self, stddev: float):
        super().__init__(stddev=stddev)
        self.stddev = stddev

    def update_output_pure(self, params, input, *, training=False, rng=None):
        if not training or rng is None:
            return input
        import jax

        return input + self.stddev * jax.random.normal(
            rng, input.shape, dtype=input.dtype
        )


class GaussianSampler(_Stateless):
    """⟦«bigdl»/nn/GaussianSampler.scala⟧ — the VAE reparameterization
    layer: table (mean, log_var) → mean + exp(log_var/2) ⊙ ε with
    ε ~ N(0, 1).  Pairs with KLDCriterion / GaussianCriterion."""

    def update_output_pure(self, params, input, *, training=False, rng=None):
        import jax

        mean, log_var = input
        if rng is None:
            # deterministic fallback (eval without rng): return the mean
            return mean
        eps = jax.random.normal(rng, mean.shape, dtype=mean.dtype)
        return mean + _jnp().exp(log_var * 0.5) * eps


# --------------------------------------------------------------------------
# Spatial dropouts (drop whole feature maps)
# --------------------------------------------------------------------------


class _SpatialDropoutN(_Stateless):
    _ndim = 4  # batched rank
    _mask_axes: tuple = ()  # axes broadcast to 1 in the bernoulli mask

    def __init__(self, init_p: float = 0.5):
        super().__init__(init_p=init_p)
        self.p = init_p

    def update_output_pure(self, params, input, *, training=False, rng=None):
        if not training or rng is None or self.p <= 0.0:
            return input
        import jax

        jnp = _jnp()
        x, squeezed = _auto_batch(input, self._ndim)
        keep = 1.0 - self.p
        mask_shape = tuple(
            1 if a in self._mask_axes else s for a, s in enumerate(x.shape)
        )
        mask = jax.random.bernoulli(rng, keep, shape=mask_shape)
        y = jnp.where(mask, x, 0.0) / keep
        return y[0] if squeezed else y


class SpatialDropout1D(_SpatialDropoutN):
    """⟦«bigdl»/nn/SpatialDropout1D.scala⟧ — (B, T, C): drops whole
    channels (the mask is shared over T)."""

    _ndim = 3
    _mask_axes = (1,)


class SpatialDropout2D(_SpatialDropoutN):
    """⟦«bigdl»/nn/SpatialDropout2D.scala⟧ — NCHW: drops whole feature
    maps (mask shared over H, W)."""

    _ndim = 4
    _mask_axes = (2, 3)


class SpatialDropout3D(_SpatialDropoutN):
    """⟦«bigdl»/nn/SpatialDropout3D.scala⟧ — NCDHW: drops whole 3-D
    feature volumes."""

    _ndim = 5
    _mask_axes = (2, 3, 4)


# --------------------------------------------------------------------------
# Cropping / resizing
# --------------------------------------------------------------------------


class Cropping2D(_Stateless):
    """⟦«bigdl»/nn/Cropping2D.scala⟧ — crop (top, bottom) / (left,
    right) cells from the H / W axes of an NCHW tensor."""

    def __init__(self, height_crop=(0, 0), width_crop=(0, 0)):
        super().__init__(height_crop=list(height_crop),
                         width_crop=list(width_crop))
        self.height_crop = tuple(height_crop)
        self.width_crop = tuple(width_crop)

    def update_output_pure(self, params, input, *, training=False, rng=None):
        x, squeezed = _auto_batch(input, 4)
        (t, b), (l, r) = self.height_crop, self.width_crop
        y = x[:, :, t: x.shape[2] - b or None, l: x.shape[3] - r or None]
        return y[0] if squeezed else y


class UpSampling1D(_Stateless):
    """⟦«bigdl»/nn/UpSampling1D.scala⟧ — repeat frames of (B, T, F)
    ``length`` times along T."""

    def __init__(self, length: int = 2):
        super().__init__(length=length)
        self.length = length

    def update_output_pure(self, params, input, *, training=False, rng=None):
        x, squeezed = _auto_batch(input, 3)
        y = _jnp().repeat(x, self.length, axis=1)
        return y[0] if squeezed else y


class UpSampling2D(_Stateless):
    """⟦«bigdl»/nn/UpSampling2D.scala⟧ — nearest-neighbour repeat of H
    and W of an NCHW tensor by size=(sH, sW)."""

    def __init__(self, size=(2, 2)):
        super().__init__(size=list(size))
        self.size = tuple(size)

    def update_output_pure(self, params, input, *, training=False, rng=None):
        jnp = _jnp()
        x, squeezed = _auto_batch(input, 4)
        y = jnp.repeat(jnp.repeat(x, self.size[0], 2), self.size[1], 3)
        return y[0] if squeezed else y


def _resize_src_coords(jnp, out_size, in_size, align_corners,
                       half_pixel_centers):
    """Source sample coordinates for one axis, matching TF's three
    sampling conventions (legacy default, align_corners, half-pixel)."""
    d = jnp.arange(out_size, dtype=jnp.float32)
    if align_corners and out_size > 1:
        return d * ((in_size - 1.0) / (out_size - 1.0))
    scale = in_size / out_size
    if half_pixel_centers:
        return (d + 0.5) * scale - 0.5
    return d * scale  # TF legacy kernel: src = dst * in / out


class ResizeBilinear(_Stateless):
    """⟦«bigdl»/nn/ResizeBilinear.scala⟧ — bilinear resize of NCHW to
    (output_height, output_width).  The reference mirrors TF's kernel,
    so all three TF sampling conventions are implemented: the legacy
    default ``src = dst * in/out``, ``align_corners``, and
    ``half_pixel_centers``."""

    def __init__(self, output_height: int, output_width: int,
                 align_corners: bool = False,
                 half_pixel_centers: bool = False):
        super().__init__(output_height=output_height,
                         output_width=output_width,
                         align_corners=align_corners,
                         half_pixel_centers=half_pixel_centers)
        self.oh, self.ow = output_height, output_width
        self.align_corners = align_corners
        self.half_pixel_centers = half_pixel_centers

    def update_output_pure(self, params, input, *, training=False, rng=None):
        jnp = _jnp()
        x, squeezed = _auto_batch(input, 4)
        h, w = x.shape[2], x.shape[3]
        ys = _resize_src_coords(jnp, self.oh, h, self.align_corners,
                                self.half_pixel_centers)
        xs = _resize_src_coords(jnp, self.ow, w, self.align_corners,
                                self.half_pixel_centers)
        ys = jnp.clip(ys, 0.0, h - 1.0)
        xs = jnp.clip(xs, 0.0, w - 1.0)
        y0 = jnp.floor(ys).astype(jnp.int32)
        x0 = jnp.floor(xs).astype(jnp.int32)
        y1 = jnp.clip(y0 + 1, 0, h - 1)
        x1 = jnp.clip(x0 + 1, 0, w - 1)
        wy = (ys - y0).reshape(1, 1, -1, 1)
        wx = (xs - x0).reshape(1, 1, 1, -1)
        g = lambda yy, xx: x[:, :, yy][:, :, :, xx]
        top = g(y0, x0) * (1 - wx) + g(y0, x1) * wx
        bot = g(y1, x0) * (1 - wx) + g(y1, x1) * wx
        out = (top * (1 - wy) + bot * wy).astype(x.dtype)
        return out[0] if squeezed else out


class ResizeNearestNeighbor(_Stateless):
    """TF-interop vocabulary («bigdl»/utils/tf/loaders/
    ResizeNearestNeighbor) — nearest resize of NCHW to a fixed size,
    honouring TF's align_corners / half_pixel_centers conventions."""

    def __init__(self, output_height: int, output_width: int,
                 align_corners: bool = False,
                 half_pixel_centers: bool = False):
        super().__init__(output_height=output_height,
                         output_width=output_width,
                         align_corners=align_corners,
                         half_pixel_centers=half_pixel_centers)
        self.oh, self.ow = output_height, output_width
        self.align_corners = align_corners
        self.half_pixel_centers = half_pixel_centers

    def _indices(self, jnp, out_size, in_size):
        src = _resize_src_coords(jnp, out_size, in_size,
                                 self.align_corners,
                                 self.half_pixel_centers)
        if self.align_corners:
            idx = jnp.round(src).astype(jnp.int32)  # TF rounds here
        elif self.half_pixel_centers:
            # TF's HalfPixelScalerForNN omits the -0.5 shift the
            # bilinear scaler applies: idx = floor((d + 0.5) * scale)
            idx = jnp.floor(src + 0.5).astype(jnp.int32)
        else:
            idx = jnp.floor(src).astype(jnp.int32)
        return jnp.clip(idx, 0, in_size - 1)

    def update_output_pure(self, params, input, *, training=False, rng=None):
        jnp = _jnp()
        x, squeezed = _auto_batch(input, 4)
        ys = self._indices(jnp, self.oh, x.shape[2])
        xs = self._indices(jnp, self.ow, x.shape[3])
        out = x[:, :, ys][:, :, :, xs]
        return out[0] if squeezed else out


class DepthToSpace(_Stateless):
    """TF DepthToSpace (DCR mode) on the NCHW layout: channel blocks of
    ``block_size**2`` fan out onto the spatial grid."""

    def __init__(self, block_size: int):
        super().__init__(block_size=block_size)
        self.block_size = block_size

    def update_output_pure(self, params, input, *, training=False, rng=None):
        x, squeezed = _auto_batch(input, 4)
        n, c, h, w = x.shape
        b = self.block_size
        x = x.reshape(n, b, b, c // (b * b), h, w)
        x = x.transpose(0, 3, 4, 1, 5, 2)
        out = x.reshape(n, c // (b * b), h * b, w * b)
        return out[0] if squeezed else out


class SpaceToDepth(_Stateless):
    """TF SpaceToDepth (DCR mode) on NCHW — inverse of DepthToSpace."""

    def __init__(self, block_size: int):
        super().__init__(block_size=block_size)
        self.block_size = block_size

    def update_output_pure(self, params, input, *, training=False, rng=None):
        x, squeezed = _auto_batch(input, 4)
        n, c, h, w = x.shape
        b = self.block_size
        x = x.reshape(n, c, h // b, b, w // b, b)
        x = x.transpose(0, 3, 5, 1, 2, 4)
        out = x.reshape(n, c * b * b, h // b, w // b)
        return out[0] if squeezed else out


# --------------------------------------------------------------------------
# Spatial normalizations
# --------------------------------------------------------------------------


class SpatialWithinChannelLRN(_Stateless):
    """⟦«bigdl»/nn/SpatialWithinChannelLRN.scala⟧ — local response
    normalization over a size x size *spatial* window within each
    channel: x / (1 + α/n · Σ x²)^β."""

    def __init__(self, size: int = 5, alpha: float = 1.0,
                 beta: float = 0.75):
        super().__init__(size=size, alpha=alpha, beta=beta)
        self.size, self.alpha, self.beta = size, alpha, beta

    def update_output_pure(self, params, input, *, training=False, rng=None):
        lax = _lax()
        x, squeezed = _auto_batch(input, 4)
        pad = self.size // 2
        sq = lax.reduce_window(
            x * x,
            0.0,
            lax.add,
            window_dimensions=(1, 1, self.size, self.size),
            window_strides=(1, 1, 1, 1),
            padding=[(0, 0), (0, 0),
                     (pad, self.size - 1 - pad), (pad, self.size - 1 - pad)],
        )
        n = self.size * self.size
        y = x / (1.0 + (self.alpha / n) * sq) ** self.beta
        return (y[0] if squeezed else y).astype(input.dtype)


def _gaussian_kernel2d(size: int) -> np.ndarray:
    """The reference's default smoothing kernel (normalised gaussian)."""
    sigma = 0.25 * size
    ax = np.arange(size, dtype=np.float64) - (size - 1) / 2.0
    g = np.exp(-(ax ** 2) / (2 * sigma ** 2))
    k = np.outer(g, g)
    return (k / k.sum()).astype(np.float32)


class SpatialSubtractiveNormalization(AbstractModule):
    """⟦«bigdl»/nn/SpatialSubtractiveNormalization.scala⟧ — subtract the
    kernel-weighted neighbourhood mean (averaged across planes), with
    the reference's border re-normalization (the coefficient map divides
    out the partial-window weight at the edges)."""

    def __init__(self, n_input_plane: int = 1, kernel=None):
        super().__init__()
        k = (np.asarray(kernel, dtype=np.float32)
             if kernel is not None else _gaussian_kernel2d(9))
        if k.ndim == 1:
            k = np.outer(k, k)
        self._config = dict(n_input_plane=n_input_plane, kernel=k.tolist())
        self.n_input_plane = n_input_plane
        self.kernel = k / (k.sum() * n_input_plane)

    def _local_mean(self, x):
        lax = _lax()
        jnp = _jnp()
        kh, kw = self.kernel.shape
        k = jnp.asarray(self.kernel, x.dtype)
        # mean over all planes with one (1, C, kh, kw) kernel
        w = jnp.broadcast_to(k, (1, x.shape[1], kh, kw))
        pads = [(kh // 2, kh - 1 - kh // 2), (kw // 2, kw - 1 - kw // 2)]
        mean = lax.conv_general_dilated(
            x, w, (1, 1), pads, dimension_numbers=("NCHW", "OIHW", "NCHW")
        )
        # border coefficient: same conv over ones
        ones = jnp.ones((1, x.shape[1], x.shape[2], x.shape[3]), x.dtype)
        coef = lax.conv_general_dilated(
            ones, w, (1, 1), pads, dimension_numbers=("NCHW", "OIHW", "NCHW")
        )
        return mean / coef

    def update_output_pure(self, params, input, *, training=False, rng=None):
        x, squeezed = _auto_batch(input, 4)
        y = x - self._local_mean(x)
        return y[0] if squeezed else y


class SpatialDivisiveNormalization(SpatialSubtractiveNormalization):
    """⟦«bigdl»/nn/SpatialDivisiveNormalization.scala⟧ — divide by the
    neighbourhood standard deviation, floored by its global mean (the
    reference's threshold against amplifying flat regions)."""

    def update_output_pure(self, params, input, *, training=False, rng=None):
        jnp = _jnp()
        x, squeezed = _auto_batch(input, 4)
        local_var = self._local_mean(x * x)
        sigma = jnp.sqrt(jnp.maximum(local_var, 0.0))
        thresh = jnp.mean(sigma, axis=(1, 2, 3), keepdims=True)
        y = x / jnp.maximum(sigma, thresh)
        return y[0] if squeezed else y


class SpatialContrastiveNormalization(AbstractModule):
    """⟦«bigdl»/nn/SpatialContrastiveNormalization.scala⟧ — subtractive
    then divisive normalization with a shared kernel."""

    def __init__(self, n_input_plane: int = 1, kernel=None):
        super().__init__()
        self.sub = SpatialSubtractiveNormalization(n_input_plane, kernel)
        self.div = SpatialDivisiveNormalization(n_input_plane, kernel)
        self._config = dict(self.sub._config)

    def update_output_pure(self, params, input, *, training=False, rng=None):
        return self.div.update_output_pure(
            {}, self.sub.update_output_pure({}, input)
        )


# --------------------------------------------------------------------------
# Shape utilities
# --------------------------------------------------------------------------


class ExpandSize(_Stateless):
    """⟦«bigdl»/nn/ExpandSize.scala⟧ — broadcast singleton dims to
    ``sizes`` (−1 keeps the input size)."""

    def __init__(self, sizes: Sequence[int]):
        super().__init__(sizes=list(sizes))
        self.sizes = list(sizes)

    def update_output_pure(self, params, input, *, training=False, rng=None):
        target = tuple(
            s if t == -1 else t for t, s in zip(self.sizes, input.shape)
        )
        return _jnp().broadcast_to(input, target)


class InferReshape(_Stateless):
    """⟦«bigdl»/nn/InferReshape.scala⟧ — reshape where −1 infers one dim
    and 0 copies the corresponding input dim; ``batch_mode`` prepends
    the batch axis."""

    def __init__(self, size: Sequence[int], batch_mode: bool = False):
        super().__init__(size=list(size), batch_mode=batch_mode)
        self.size = list(size)
        self.batch_mode = batch_mode

    def update_output_pure(self, params, input, *, training=False, rng=None):
        in_shape = input.shape[1:] if self.batch_mode else input.shape
        out = []
        for i, s in enumerate(self.size):
            if s == 0:
                out.append(in_shape[i])
            else:
                out.append(s)
        if self.batch_mode:
            out = [input.shape[0]] + out
        return input.reshape(tuple(out))


class Tile(_Stateless):
    """⟦«bigdl»/nn/Tile.scala⟧ — repeat the tensor ``copies`` times
    along 1-based ``dim``."""

    def __init__(self, dim: int = 1, copies: int = 2):
        super().__init__(dim=dim, copies=copies)
        self.dim, self.copies = dim, copies

    def update_output_pure(self, params, input, *, training=False, rng=None):
        d = self.dim - 1 if self.dim > 0 else input.ndim + self.dim
        reps = [1] * input.ndim
        reps[d] = self.copies
        return _jnp().tile(input, reps)


class SplitChunks(_Stateless):
    """TF ``Split`` semantics: cut the tensor into ``n`` equal chunks
    along 1-based ``dim`` (the chunk length comes from the runtime
    shape — static under jit), returning a table.  Companion to
    ``SplitTable`` (which unstacks every index); used by the TF
    GraphDef importer (utils/tf_interop.py)."""

    def __init__(self, dim: int = 1, n: int = 2):
        super().__init__(dim=dim, n=n)
        self.dim, self.n = dim, n

    def update_output_pure(self, params, input, *, training=False, rng=None):
        d = self.dim - 1 if self.dim > 0 else input.ndim + self.dim
        size = input.shape[d]
        if size % self.n:
            raise ValueError(
                f"SplitChunks: dim {self.dim} size {size} not divisible "
                f"into {self.n} chunks")
        chunk = size // self.n
        idx = [slice(None)] * input.ndim
        outs = []
        for i in range(self.n):
            idx[d] = slice(i * chunk, (i + 1) * chunk)
            outs.append(input[tuple(idx)])
        return tuple(outs)


class CompareConstant(_Stateless):
    """Elementwise comparison against a scalar constant, emitting a
    bool tensor — the TF Less/Greater/... vocabulary with a const
    operand (used by imported loop conditions)."""

    _OPS = ("lt", "le", "gt", "ge", "eq", "ne")

    def __init__(self, op: str = "lt", value: float = 0.0,
                 const_first: bool = False):
        if op not in self._OPS:
            raise ValueError(f"op must be one of {self._OPS}")
        super().__init__(op=op, value=value, const_first=const_first)
        self.op, self.value, self.const_first = op, value, const_first

    def update_output_pure(self, params, input, *, training=False, rng=None):
        jnp = _jnp()
        a, b = (self.value, input) if self.const_first else (input, self.value)
        return {
            "lt": lambda: a < b, "le": lambda: a <= b,
            "gt": lambda: a > b, "ge": lambda: a >= b,
            "eq": lambda: jnp.equal(a, b), "ne": lambda: jnp.not_equal(a, b),
        }[self.op]()


class GatherIndices(_Stateless):
    """TF ``GatherV2`` semantics with a CONSTANT index vector: one
    ``jnp.take`` along 1-based ``dim`` (negative counts from the end).
    Used by the GraphDef importer — a fan-out of Select modules would
    scale the module graph with the index count."""

    def __init__(self, dim: int = 1, indices=()):
        super().__init__(dim=dim, indices=[int(i) for i in indices])
        self.dim = dim
        self.indices = [int(i) for i in indices]

    def update_output_pure(self, params, input, *, training=False, rng=None):
        jnp = _jnp()
        d = self.dim - 1 if self.dim > 0 else input.ndim + self.dim
        return jnp.take(input, jnp.asarray(self.indices), axis=d)


class Reverse(_Stateless):
    """⟦«bigdl»/nn/Reverse.scala⟧ — flip along 1-based ``dimension``."""

    def __init__(self, dimension: int = 1, is_inplace: bool = False):
        super().__init__(dimension=dimension)
        self.dimension = dimension

    def update_output_pure(self, params, input, *, training=False, rng=None):
        d = self.dimension - 1
        return _jnp().flip(input, axis=d)


class CumSum(_Stateless):
    """TF-interop vocabulary («bigdl»/utils/tf/loaders Cumsum) —
    cumulative sum along 1-based ``dimension`` with TF's exclusive /
    reverse flags."""

    def __init__(self, dimension: int = 1, exclusive: bool = False,
                 reverse: bool = False):
        super().__init__(dimension=dimension, exclusive=exclusive,
                         reverse=reverse)
        self.dimension = dimension
        self.exclusive = exclusive
        self.reverse = reverse

    def update_output_pure(self, params, input, *, training=False, rng=None):
        jnp = _jnp()
        lax = _lax()
        d = self.dimension - 1
        x = jnp.flip(input, axis=d) if self.reverse else input
        y = jnp.cumsum(x, axis=d)
        if self.exclusive:
            # TF's exclusive = shifted inclusive ([0, y[:-1]]): exact,
            # unlike y - x which catastrophically cancels when a large
            # running sum has absorbed a small element
            head = jnp.zeros_like(lax.slice_in_dim(y, 0, 1, axis=d))
            y = jnp.concatenate(
                [head, lax.slice_in_dim(y, 0, y.shape[d] - 1, axis=d)],
                axis=d)
        return jnp.flip(y, axis=d) if self.reverse else y


class FillLike(_Stateless):
    """TF-interop vocabulary (ZerosLike / OnesLike) — a constant tensor
    of the input's shape.  Ignores the input VALUES (0 * inf is NaN, so
    a multiply-by-zero lowering corrupts graphs that ZerosLike their
    -inf attention masks); the input contributes shape only and gets a
    zero gradient."""

    def __init__(self, value: float = 0.0):
        super().__init__(value=value)
        self.value = value

    def update_output_pure(self, params, input, *, training=False, rng=None):
        return _jnp().full_like(input, self.value)


class MirrorPad(_Stateless):
    """TF-interop vocabulary — REFLECT / SYMMETRIC padding.
    ``paddings`` is the full-rank list of (before, after) pairs,
    batch row included (TF's layout)."""

    def __init__(self, paddings, mode: str = "REFLECT"):
        paddings = [list(p) for p in paddings]
        super().__init__(paddings=paddings, mode=mode)
        self.paddings = paddings
        self.mode = mode

    def update_output_pure(self, params, input, *, training=False, rng=None):
        mode = "reflect" if self.mode == "REFLECT" else "symmetric"
        return _jnp().pad(input, [tuple(p) for p in self.paddings],
                          mode=mode)


# --------------------------------------------------------------------------
# Misc
# --------------------------------------------------------------------------


class MaskedSelect(_Stateless):
    """⟦«bigdl»/nn/MaskedSelect.scala⟧ — table (tensor, mask) → the
    1-D tensor of elements where mask ≠ 0.

    The output shape is data-dependent, so this module is **eager-only**
    (cannot sit under jit) — exactly the reference's semantics, which
    also produces a dynamically sized tensor.  Inside jitted models use
    ``Masking``/``CMulTable`` with a dense mask instead.
    """

    def update_output_pure(self, params, input, *, training=False, rng=None):
        jnp = _jnp()
        x, mask = input
        sel = np.asarray(mask).astype(bool).reshape(-1)
        flat = np.asarray(x).reshape(-1)
        return jnp.asarray(flat[sel])


class PairwiseDistance(_Stateless):
    """⟦«bigdl»/nn/PairwiseDistance.scala⟧ — table (x1, x2) → per-row
    p-norm distance."""

    def __init__(self, norm: int = 2):
        super().__init__(norm=norm)
        self.norm = norm

    def update_output_pure(self, params, input, *, training=False, rng=None):
        jnp = _jnp()
        x1, x2 = input
        d = jnp.abs(x1 - x2) ** self.norm
        return jnp.sum(d, axis=-1) ** (1.0 / self.norm)


class Maxout(AbstractModule):
    """⟦«bigdl»/nn/Maxout.scala⟧ — Linear to maxout_number*output_size
    then max over the maxout groups: y_j = max_k (W_k x + b_k)_j.

    TPU note: the whole layer is one (in, maxout*out) matmul plus a
    reshape-max — a single MXU contraction with a fused reduction."""

    param_names = ("weight", "bias")

    def __init__(self, input_size: int, output_size: int,
                 maxout_number: int, with_bias: bool = True):
        super().__init__()
        self._config = dict(input_size=input_size, output_size=output_size,
                            maxout_number=maxout_number, with_bias=with_bias)
        self.input_size = input_size
        self.output_size = output_size
        self.maxout_number = maxout_number
        self.with_bias = with_bias
        self.reset()

    def reset(self):
        n_out = self.maxout_number * self.output_size
        bound = 1.0 / math.sqrt(self.input_size)
        self.weight = _to_device(
            RandomGenerator.RNG.uniform(-bound, bound,
                        (self.input_size, n_out)).astype(np.float32)
        )
        self.bias = (
            _to_device(
                RandomGenerator.RNG.uniform(
                    -bound, bound, n_out).astype(np.float32))
            if self.with_bias else None
        )
        return self

    def update_output_pure(self, params, input, *, training=False, rng=None):
        jnp = _jnp()
        x, squeezed = _auto_batch(input, 2)
        y = x @ params["weight"]
        if self.with_bias:
            y = y + params["bias"]
        y = y.reshape(y.shape[0], self.maxout_number, self.output_size)
        y = jnp.max(y, axis=1)
        return y[0] if squeezed else y

    def __repr__(self):
        return (f"Maxout({self.input_size} -> {self.output_size} "
                f"x{self.maxout_number})")


class Highway(AbstractModule):
    """Keras-1.2.2 ``Highway`` (⟦«py»/nn/keras⟧ converter vocabulary):
    ``y = t * h + (1 - t) * x`` with ``h = act(x W^T + b)`` and the
    carry gate ``t = sigmoid(x W_carry^T + b_carry)``.

    TPU note: both projections are same-shaped MXU matmuls over one
    operand; XLA fuses the gate blend into their epilogue.
    """

    param_names = ("weight", "bias", "carry_weight", "carry_bias")

    def __init__(self, size: int, with_bias: bool = True, activation=None):
        super().__init__()
        if isinstance(activation, str):
            from bigdl_tpu.utils.serializer import lookup_module_class

            activation = lookup_module_class(activation)()
        self._config = dict(
            size=size, with_bias=with_bias,
            activation=(type(activation).__name__
                        if activation is not None else None))
        self.size = size
        self.with_bias = with_bias
        self.activation = activation  # an activation module or None
        self.reset()

    def reset(self):
        bound = 1.0 / math.sqrt(self.size)

        def w():
            return _to_device(RandomGenerator.RNG.uniform(
                -bound, bound, (self.size, self.size)).astype(np.float32))

        self.weight = w()
        self.carry_weight = w()
        if self.with_bias:
            self.bias = _to_device(np.zeros(self.size, np.float32))
            # keras initialises the carry bias at -2 so early training
            # passes the input through (transform gate mostly closed)
            self.carry_bias = _to_device(
                np.full(self.size, -2.0, np.float32))
        else:
            self.bias = None
            self.carry_bias = None
        return self

    def update_output_pure(self, params, input, *, training=False, rng=None):
        import jax

        h = input @ params["weight"].T
        t = input @ params["carry_weight"].T
        if self.with_bias:
            h = h + params["bias"]
            t = t + params["carry_bias"]
        if self.activation is not None:
            h = self.activation.update_output_pure(
                {}, h, training=training, rng=rng)
        t = jax.nn.sigmoid(t)
        return t * h + (1.0 - t) * input

    def __repr__(self):
        return f"Highway({self.size})"


class SReLU(AbstractModule):
    """⟦«bigdl»/nn/SReLU.scala⟧ — S-shaped ReLU with four learnable
    per-channel parameters:
    y = t_r + a_r (x - t_r) for x >= t_r; x between the thresholds;
    y = t_l + a_l (x - t_l) for x <= t_l."""

    param_names = ("t_left", "a_left", "t_right", "a_right")

    def __init__(self, shape: Sequence[int]):
        super().__init__()
        self._config = dict(shape=list(shape))
        self.shape = tuple(int(s) for s in shape)
        self.reset()

    def reset(self):
        # Keras-1.2.2 SReLU defaults (ADVICE r3 #4): t_left zero,
        # a_left/t_right glorot_uniform over the param shape, a_right
        # one.  Fans follow Keras get_fans: 2-D -> (s0, s1), anything
        # else -> fan_in = fan_out = sqrt(prod(shape))
        if len(self.shape) == 2:
            fan_in, fan_out = float(self.shape[0]), float(self.shape[1])
        else:
            fan_in = fan_out = float(np.sqrt(np.prod(self.shape)))
        limit = float(np.sqrt(6.0 / (fan_in + fan_out)))
        self.t_left = _to_device(np.zeros(self.shape, np.float32))
        self.a_left = _to_device(
            RandomGenerator.RNG.uniform(-limit, limit, self.shape)
            .astype(np.float32))
        self.t_right = _to_device(
            RandomGenerator.RNG.uniform(-limit, limit, self.shape)
            .astype(np.float32))
        self.a_right = _to_device(np.ones(self.shape, np.float32))
        return self

    def update_output_pure(self, params, input, *, training=False, rng=None):
        jnp = _jnp()
        tl, al = params["t_left"], params["a_left"]
        tr, ar = params["t_right"], params["a_right"]
        y = jnp.where(input >= tr, tr + ar * (input - tr), input)
        return jnp.where(input <= tl, tl + al * (input - tl), y)

    def __repr__(self):
        return f"SReLU({self.shape})"


class RoiPooling(_Stateless):
    """⟦«bigdl»/nn/RoiPooling.scala⟧ — Fast-RCNN region-of-interest max
    pooling.  Table input [data (B,C,H,W), rois (R,5)] with roi rows
    (batch_index 1-based, x1, y1, x2, y2) in image coordinates; output
    (R, C, pooled_h, pooled_w).

    TPU note: the reference's per-roi scalar loops become two masked
    rectangular max-reductions (independent h/w interval masks), fully
    vectorized and jittable at static shapes; autograd routes the
    gradient to each bin's argmax like the hand-written backward."""

    def __init__(self, pooled_w: int, pooled_h: int,
                 spatial_scale: float = 1.0):
        super().__init__(pooled_w=pooled_w, pooled_h=pooled_h,
                         spatial_scale=spatial_scale)
        self.pooled_w = pooled_w
        self.pooled_h = pooled_h
        self.spatial_scale = spatial_scale

    def _interval_mask(self, starts, ends, size):
        jnp = _jnp()
        idx = jnp.arange(size, dtype=jnp.float32)
        return (idx[None, None, :] >= starts[:, :, None]) & (
            idx[None, None, :] < ends[:, :, None]
        )

    def update_output_pure(self, params, input, *, training=False, rng=None):
        jnp = _jnp()
        data, rois = input[0], input[1]
        _, _, H, W = data.shape
        ph, pw = self.pooled_h, self.pooled_w
        img = rois[:, 0].astype(jnp.int32) - 1  # 1-based image index
        x1 = jnp.round(rois[:, 1] * self.spatial_scale)
        y1 = jnp.round(rois[:, 2] * self.spatial_scale)
        x2 = jnp.round(rois[:, 3] * self.spatial_scale)
        y2 = jnp.round(rois[:, 4] * self.spatial_scale)
        roi_w = jnp.maximum(x2 - x1 + 1.0, 1.0)
        roi_h = jnp.maximum(y2 - y1 + 1.0, 1.0)
        bin_w = roi_w / pw
        bin_h = roi_h / ph
        j = jnp.arange(pw, dtype=jnp.float32)
        i = jnp.arange(ph, dtype=jnp.float32)
        wstart = jnp.clip(jnp.floor(j[None] * bin_w[:, None])
                          + x1[:, None], 0, W)
        wend = jnp.clip(jnp.ceil((j[None] + 1) * bin_w[:, None])
                        + x1[:, None], 0, W)
        hstart = jnp.clip(jnp.floor(i[None] * bin_h[:, None])
                          + y1[:, None], 0, H)
        hend = jnp.clip(jnp.ceil((i[None] + 1) * bin_h[:, None])
                        + y1[:, None], 0, H)
        mask_w = self._interval_mask(wstart, wend, W)   # (R, pw, W)
        mask_h = self._interval_mask(hstart, hend, H)   # (R, ph, H)
        x = data[img]                                   # (R, C, H, W)
        neg = jnp.asarray(-jnp.inf, x.dtype)
        # max over w per (h, output-col), then over h per output-row
        t = jnp.max(
            jnp.where(mask_w[:, None, None, :, :], x[:, :, :, None, :], neg),
            axis=-1,
        )                                               # (R, C, H, pw)
        y = jnp.max(
            jnp.where(mask_h[:, None, :, :, None], t[:, :, None, :, :], neg),
            axis=3,
        )                                               # (R, C, ph, pw)
        return jnp.where(jnp.isneginf(y), 0.0, y)  # empty bin -> 0 (Caffe)

    def __repr__(self):
        return (f"RoiPooling({self.pooled_w}x{self.pooled_h}, "
                f"scale={self.spatial_scale})")


class NegativeEntropyPenalty(_Stateless):
    """⟦«bigdl»/nn/NegativeEntropyPenalty.scala⟧ — identity forward that
    adds β·Σ p·log p to the training loss (pass-through analogue of
    L1Penalty; the penalty is collected via regularization_loss so it
    lands in the jitted loss like the reference's accGradParameters-time
    gradient)."""

    def __init__(self, beta: float = 0.01):
        super().__init__(beta=beta)
        self.beta = beta

    def update_output_pure(self, params, input, *, training=False, rng=None):
        return input

    def regularization_loss(self, params):
        # collected over the *output* distribution is not reachable from
        # here; the reference penalises the layer input, which equals the
        # output for this identity layer — handled in criterion wiring.
        return 0.0

    def penalty(self, p):
        jnp = _jnp()
        return self.beta * jnp.sum(p * jnp.log(jnp.clip(p, 1e-12, None)))


__all__ = [
    "LocallyConnected1D",
    "LocallyConnected2D",
    "SpatialSeparableConvolution",
    "SpatialShareConvolution",
    "SpatialConvolutionMap",
    "TemporalMaxPooling",
    "SoftShrink",
    "HardShrink",
    "TanhShrink",
    "LogSigmoid",
    "RReLU",
    "GaussianDropout",
    "GaussianNoise",
    "GaussianSampler",
    "SpatialDropout1D",
    "SpatialDropout2D",
    "SpatialDropout3D",
    "Cropping2D",
    "UpSampling1D",
    "UpSampling2D",
    "ResizeBilinear",
    "ResizeNearestNeighbor", "DepthToSpace", "SpaceToDepth",
    "SpatialWithinChannelLRN",
    "SpatialSubtractiveNormalization",
    "SpatialDivisiveNormalization",
    "SpatialContrastiveNormalization",
    "ExpandSize",
    "InferReshape",
    "Tile",
    "SplitChunks",
    "TemporalAveragePooling",
    "GatherIndices",
    "CompareConstant",
    "Reverse",
    "CumSum",
    "FillLike",
    "MirrorPad",
    "MaskedSelect",
    "Maxout",
    "Highway",
    "SReLU",
    "RoiPooling",
    "PairwiseDistance",
    "NegativeEntropyPenalty",
]
