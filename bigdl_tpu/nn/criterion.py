"""Criterions (losses).

Rebuild of the «bigdl»/nn/ criterion family (SURVEY.md §2.1 "Criterions").
Contract parity with «bigdl»/nn/abstractnn/AbstractCriterion.scala:
``forward(input, target)`` fills ``output``; ``backward(input, target)``
fills ``gradInput`` — here derived with ``jax.grad`` of the pure
:meth:`loss` instead of hand-written gradients.

Reference conventions preserved:
* class targets are **1-based** (ClassNLLCriterion & friends),
* ``sizeAverage`` defaults match the reference,
* table inputs/targets are tuples.
"""

from __future__ import annotations

import math
from typing import Optional

import numpy as np


def _jnp():
    import jax.numpy as jnp

    return jnp


class AbstractCriterion:
    def __init__(self):
        self.output = None
        self.grad_input = None

    # pure scalar loss — the only thing subclasses implement
    def loss(self, input, target):
        raise NotImplementedError

    def forward(self, input, target):
        self.output = self.loss(input, target)
        return self.output

    updateOutput = forward

    def backward(self, input, target):
        import jax

        self.grad_input = jax.grad(lambda x: self.loss(x, target))(input)
        return self.grad_input

    updateGradInput = backward

    def __repr__(self):
        return type(self).__name__


def _reduce(x, size_average: bool):
    jnp = _jnp()
    return jnp.mean(x) if size_average else jnp.sum(x)


class ClassNLLCriterion(AbstractCriterion):
    """«bigdl»/nn/ClassNLLCriterion.scala — negative log-likelihood over
    **1-based** integer targets; input is log-probabilities by default
    (``logProbAsInput``); optional per-class weights; sizeAverage divides
    by the summed target weights (torch semantics); ``paddingValue``
    targets contribute zero."""

    def __init__(
        self,
        weights=None,
        size_average: bool = True,
        log_prob_as_input: bool = True,
        padding_value: int = -1,
    ):
        super().__init__()
        self.weights = None if weights is None else np.asarray(weights, np.float32)
        self.size_average = size_average
        self.log_prob_as_input = log_prob_as_input
        self.padding_value = padding_value

    def loss(self, input, target):
        jnp = _jnp()
        logp = input if self.log_prob_as_input else jnp.log(input + 1e-8)
        t = target.reshape(-1).astype(jnp.int32)
        logp2 = logp.reshape(-1, logp.shape[-1])
        valid = t != self.padding_value
        idx = jnp.clip(t - 1, 0, logp2.shape[-1] - 1)
        picked = jnp.take_along_axis(logp2, idx[:, None], axis=1)[:, 0]
        if self.weights is not None:
            w = jnp.asarray(self.weights)[idx]
        else:
            w = jnp.ones_like(picked)
        w = jnp.where(valid, w, 0.0)
        total = -jnp.sum(w * picked)
        if self.size_average:
            total = total / jnp.maximum(jnp.sum(w), 1e-8)
        return total


class CrossEntropyCriterion(AbstractCriterion):
    """«bigdl»/nn/CrossEntropyCriterion.scala — LogSoftMax + ClassNLL
    fused, on raw logits (XLA fuses the pair anyway; doing it here keeps
    the numerically-stable combined form)."""

    def __init__(self, weights=None, size_average: bool = True):
        super().__init__()
        self._nll = ClassNLLCriterion(weights=weights, size_average=size_average)

    def loss(self, input, target):
        import jax

        return self._nll.loss(jax.nn.log_softmax(input, axis=-1), target)


class MSECriterion(AbstractCriterion):
    """«bigdl»/nn/MSECriterion.scala"""

    def __init__(self, size_average: bool = True):
        super().__init__()
        self.size_average = size_average

    def loss(self, input, target):
        d = input - target
        return _reduce(d * d, self.size_average)


class AbsCriterion(AbstractCriterion):
    """«bigdl»/nn/AbsCriterion.scala"""

    def __init__(self, size_average: bool = True):
        super().__init__()
        self.size_average = size_average

    def loss(self, input, target):
        return _reduce(_jnp().abs(input - target), self.size_average)


class SmoothL1Criterion(AbstractCriterion):
    """«bigdl»/nn/SmoothL1Criterion.scala"""

    def __init__(self, size_average: bool = True):
        super().__init__()
        self.size_average = size_average

    def loss(self, input, target):
        jnp = _jnp()
        d = jnp.abs(input - target)
        v = jnp.where(d < 1.0, 0.5 * d * d, d - 0.5)
        return _reduce(v, self.size_average)


class BCECriterion(AbstractCriterion):
    """«bigdl»/nn/BCECriterion.scala — input is probabilities."""

    def __init__(self, weights=None, size_average: bool = True):
        super().__init__()
        self.weights = None if weights is None else np.asarray(weights, np.float32)
        self.size_average = size_average

    def loss(self, input, target):
        jnp = _jnp()
        eps = 1e-12
        v = -(
            target * jnp.log(input + eps)
            + (1.0 - target) * jnp.log(1.0 - input + eps)
        )
        if self.weights is not None:
            v = v * jnp.asarray(self.weights)
        return _reduce(v, self.size_average)


class BCECriterionWithLogits(AbstractCriterion):
    """Numerically-stable sigmoid+BCE (the fused spelling modern recipes
    use; reference pairs Sigmoid with BCECriterion)."""

    def __init__(self, size_average: bool = True):
        super().__init__()
        self.size_average = size_average

    def loss(self, input, target):
        jnp = _jnp()
        v = jnp.maximum(input, 0) - input * target + jnp.log1p(jnp.exp(-jnp.abs(input)))
        return _reduce(v, self.size_average)


class MultiLabelSoftMarginCriterion(AbstractCriterion):
    """«bigdl»/nn/MultiLabelSoftMarginCriterion.scala"""

    def __init__(self, weights=None, size_average: bool = True):
        super().__init__()
        self.weights = None if weights is None else np.asarray(weights, np.float32)
        self.size_average = size_average

    def loss(self, input, target):
        import jax

        jnp = _jnp()
        p = jax.nn.sigmoid(input)
        eps = 1e-12
        v = -(target * jnp.log(p + eps) + (1 - target) * jnp.log(1 - p + eps))
        if self.weights is not None:
            v = v * jnp.asarray(self.weights)
        return jnp.mean(v) if self.size_average else jnp.sum(v)


class MarginCriterion(AbstractCriterion):
    """«bigdl»/nn/MarginCriterion.scala — hinge loss, targets ±1; squared
    flag gives L2-SVM."""

    def __init__(self, margin: float = 1.0, size_average: bool = True, squared=False):
        super().__init__()
        self.margin, self.size_average, self.squared = margin, size_average, squared

    def loss(self, input, target):
        jnp = _jnp()
        v = jnp.maximum(0.0, self.margin - input * target)
        if self.squared:
            v = v * v
        return _reduce(v, self.size_average)


class HingeEmbeddingCriterion(AbstractCriterion):
    """«bigdl»/nn/HingeEmbeddingCriterion.scala — targets ±1 over
    distances."""

    def __init__(self, margin: float = 1.0, size_average: bool = True):
        super().__init__()
        self.margin, self.size_average = margin, size_average

    def loss(self, input, target):
        jnp = _jnp()
        v = jnp.where(
            target > 0, input, jnp.maximum(0.0, self.margin - input)
        )
        return _reduce(v, self.size_average)


class DistKLDivCriterion(AbstractCriterion):
    """«bigdl»/nn/DistKLDivCriterion.scala — input is log-prob, target
    prob."""

    def __init__(self, size_average: bool = True):
        super().__init__()
        self.size_average = size_average

    def loss(self, input, target):
        jnp = _jnp()
        v = jnp.where(target > 0, target * (jnp.log(target + 1e-12) - input), 0.0)
        return _reduce(v, self.size_average)


class CosineEmbeddingCriterion(AbstractCriterion):
    """«bigdl»/nn/CosineEmbeddingCriterion.scala — table input (x1, x2),
    targets ±1."""

    def __init__(self, margin: float = 0.0, size_average: bool = True):
        super().__init__()
        self.margin, self.size_average = margin, size_average

    def loss(self, input, target):
        jnp = _jnp()
        x1, x2 = input
        cos = jnp.sum(x1 * x2, axis=-1) / jnp.maximum(
            jnp.linalg.norm(x1, axis=-1) * jnp.linalg.norm(x2, axis=-1), 1e-12
        )
        t = target.reshape(cos.shape)
        v = jnp.where(t > 0, 1.0 - cos, jnp.maximum(0.0, cos - self.margin))
        return _reduce(v, self.size_average)


class SoftmaxWithCriterion(AbstractCriterion):
    """«bigdl»/nn/SoftmaxWithCriterion.scala — Caffe SoftmaxWithLoss:
    softmax over channel dim 2 of NCHW-ish input + NLL, with ignoreLabel."""

    def __init__(self, ignore_label: Optional[int] = None, normalize_mode="VALID"):
        super().__init__()
        self.ignore_label = ignore_label
        self.normalize_mode = normalize_mode

    def loss(self, input, target):
        import jax

        jnp = _jnp()
        # move channel (dim 1) last
        logp = jax.nn.log_softmax(jnp.moveaxis(input, 1, -1), axis=-1)
        t = target.astype(jnp.int32).reshape(logp.shape[:-1])
        idx = jnp.clip(t - 1, 0, logp.shape[-1] - 1)
        picked = jnp.take_along_axis(logp, idx[..., None], axis=-1)[..., 0]
        if self.ignore_label is not None:
            mask = (t != self.ignore_label).astype(logp.dtype)
        else:
            mask = jnp.ones_like(picked)
        total = -jnp.sum(picked * mask)
        if self.normalize_mode == "VALID":
            return total / jnp.maximum(jnp.sum(mask), 1.0)
        if self.normalize_mode == "FULL":
            return total / picked.size
        if self.normalize_mode == "BATCH_SIZE":
            return total / input.shape[0]
        return total


class MultiCriterion(AbstractCriterion):
    """«bigdl»/nn/MultiCriterion.scala — weighted sum of criterions on the
    same (input, target)."""

    def __init__(self):
        super().__init__()
        self.criterions = []
        self.weights = []

    def add(self, criterion, weight: float = 1.0):
        self.criterions.append(criterion)
        self.weights.append(weight)
        return self

    def loss(self, input, target):
        total = 0.0
        for c, w in zip(self.criterions, self.weights):
            total = total + w * c.loss(input, target)
        return total


class ParallelCriterion(AbstractCriterion):
    """«bigdl»/nn/ParallelCriterion.scala — i-th criterion gets i-th table
    entries; repeatTarget broadcasts one target to all."""

    def __init__(self, repeat_target: bool = False):
        super().__init__()
        self.repeat_target = repeat_target
        self.criterions = []
        self.weights = []

    def add(self, criterion, weight: float = 1.0):
        self.criterions.append(criterion)
        self.weights.append(weight)
        return self

    def loss(self, input, target):
        total = 0.0
        for i, (c, w) in enumerate(zip(self.criterions, self.weights)):
            t = target if self.repeat_target else target[i]
            total = total + w * c.loss(input[i], t)
        return total


class TimeDistributedCriterion(AbstractCriterion):
    """«bigdl»/nn/TimeDistributedCriterion.scala — fold the time dim
    (1-based ``dimension``, default 2 i.e. (batch, time, ...)) into the
    batch, apply the inner criterion per step, sum over steps; with
    sizeAverage divide by the number of steps."""

    def __init__(self, critrn, size_average: bool = False, dimension: int = 2):
        super().__init__()
        self.criterion = critrn
        self.size_average = size_average
        self.dimension = dimension

    def loss(self, input, target):
        d = self.dimension - 1
        nstep = input.shape[d]
        merged_in = input.reshape((-1,) + input.shape[2:]) if d == 1 else input
        merged_t = target.reshape((-1,) + target.shape[2:]) if d == 1 else target
        inner = self.criterion.loss(merged_in, merged_t)
        inner_avg = getattr(self.criterion, "size_average", False)
        if inner_avg:
            # inner mean over batch*time == (1/T) sum_t mean_batch
            return inner if self.size_average else inner * nstep
        return inner / nstep if self.size_average else inner


class ClassSimplexCriterion(AbstractCriterion):
    """«bigdl»/nn/ClassSimplexCriterion.scala — MSE against a simplex
    embedding of the (1-based) class label."""

    def __init__(self, n_classes: int):
        super().__init__()
        self.n_classes = n_classes
        self._simplex = self._build_simplex(n_classes)

    @staticmethod
    def _build_simplex(n):
        # regular simplex embedding in R^n via Gram-Schmidt-free recursion
        a = np.zeros((n, n), dtype=np.float32)
        for k in range(n - 1):
            a[k, k] = 1.0
            s = np.sum(a[: k + 1, :], axis=0) / (k + 1)
            a[k + 1, :] = s
            a[k + 1, k] = s[k]
        # normalise rows to unit distance (approximation of the reference's
        # scaled simplex; exact coordinates differ by a rotation which the
        # MSE objective is invariant to in aggregate)
        for k in range(1, n):
            a[k] = a[k] / max(np.linalg.norm(a[k]), 1e-8)
        return a

    def loss(self, input, target):
        jnp = _jnp()
        idx = target.astype(jnp.int32).reshape(-1) - 1
        t = jnp.asarray(self._simplex)[idx]
        d = input - t
        return jnp.mean(d * d)


class L1Cost(AbstractCriterion):
    """«bigdl»/nn/L1Cost.scala — sum |input| (target ignored)."""

    def loss(self, input, target):
        return _jnp().sum(_jnp().abs(input))


class MarginRankingCriterion(AbstractCriterion):
    """«bigdl»/nn/MarginRankingCriterion.scala — table input (x1, x2),
    target ±1."""

    def __init__(self, margin: float = 1.0, size_average: bool = True):
        super().__init__()
        self.margin, self.size_average = margin, size_average

    def loss(self, input, target):
        jnp = _jnp()
        x1, x2 = input
        t = target.reshape(jnp.shape(x1)) if hasattr(target, "reshape") else target
        v = jnp.maximum(0.0, -t * (x1 - x2) + self.margin)
        return _reduce(v, self.size_average)


class MultiMarginCriterion(AbstractCriterion):
    """«bigdl»/nn/MultiMarginCriterion.scala — multi-class hinge on
    1-based targets."""

    def __init__(self, p: int = 1, weights=None, margin: float = 1.0,
                 size_average: bool = True):
        super().__init__()
        self.p, self.margin, self.size_average = p, margin, size_average
        self.weights = None if weights is None else np.asarray(weights, np.float32)

    def loss(self, input, target):
        jnp = _jnp()
        x = input.reshape(-1, input.shape[-1])
        t = target.astype(jnp.int32).reshape(-1) - 1
        correct = jnp.take_along_axis(x, t[:, None], axis=1)
        v = jnp.maximum(0.0, self.margin - correct + x)
        if self.p == 2:
            v = v * v
        if self.weights is not None:
            v = v * jnp.asarray(self.weights)[t][:, None]
        # exclude the correct-class column
        mask = jnp.ones_like(v).at[jnp.arange(v.shape[0]), t].set(0.0)
        per_sample = jnp.sum(v * mask, axis=1) / x.shape[-1]
        return _reduce(per_sample, self.size_average)


class CosineDistanceCriterion(AbstractCriterion):
    """⟦«bigdl»/nn/CosineDistanceCriterion.scala⟧ — loss = 1 − cos(x, y)
    per row."""

    def __init__(self, size_average: bool = True):
        super().__init__()
        self.size_average = size_average

    def loss(self, input, target):
        jnp = _jnp()
        cos = jnp.sum(input * target, axis=-1) / jnp.maximum(
            jnp.linalg.norm(input, axis=-1)
            * jnp.linalg.norm(target, axis=-1),
            1e-12,
        )
        return _reduce(1.0 - cos, self.size_average)


class DiceCoefficientCriterion(AbstractCriterion):
    """⟦«bigdl»/nn/DiceCoefficientCriterion.scala⟧ — 1 − Dice overlap,
    the segmentation loss: 1 − 2·Σxy / (Σx + Σy + ε)."""

    def __init__(self, size_average: bool = True, epsilon: float = 1.0):
        super().__init__()
        self.size_average = size_average
        self.epsilon = epsilon

    def loss(self, input, target):
        jnp = _jnp()
        x = input.reshape(input.shape[0], -1)
        y = target.reshape(input.shape[0], -1).astype(x.dtype)
        inter = jnp.sum(x * y, axis=1)
        denom = jnp.sum(x, axis=1) + jnp.sum(y, axis=1) + self.epsilon
        return _reduce(1.0 - 2.0 * inter / denom, self.size_average)


class SoftMarginCriterion(AbstractCriterion):
    """⟦«bigdl»/nn/SoftMarginCriterion.scala⟧ — mean log(1 + exp(−y·x))
    over all elements (targets ±1)."""

    def __init__(self, size_average: bool = True):
        super().__init__()
        self.size_average = size_average

    def loss(self, input, target):
        import jax

        v = jax.nn.softplus(-input * target)
        return _reduce(v, self.size_average)


class MultiLabelMarginCriterion(AbstractCriterion):
    """⟦«bigdl»/nn/MultiLabelMarginCriterion.scala⟧ — multi-label
    multi-class hinge: targets per row are **1-based** class indices,
    0-padded.  loss_row = Σ_{j∉T} Σ_{i∈T} max(0, 1 − (x[t_i] − x[j]))
    / C."""

    def __init__(self, size_average: bool = True):
        super().__init__()
        self.size_average = size_average

    def loss(self, input, target):
        jnp = _jnp()
        x = input if input.ndim == 2 else input[None]
        t = target if target.ndim == 2 else target[None]
        t = t.astype(jnp.int32)
        n, c = x.shape
        # torch/BigDL contract: the target list ENDS at the first 0 —
        # entries after it are ignored even if nonzero
        valid = jnp.cumprod(t > 0, axis=1).astype(bool)
        idx = jnp.clip(t - 1, 0, c - 1)
        # member[n, j] = 1 when class j is one of row n's targets
        member = jnp.zeros((n, c), x.dtype)
        member = member.at[jnp.arange(n)[:, None], idx].max(
            valid.astype(x.dtype)
        )
        picked = jnp.take_along_axis(x, idx, axis=1)      # x[t_i]
        # margins[n, i, j] = 1 - (x[t_i] - x[j])
        margins = 1.0 - picked[:, :, None] + x[:, None, :]
        hinge = jnp.maximum(0.0, margins)
        mask = valid[:, :, None].astype(x.dtype) \
            * (1.0 - member)[:, None, :]
        per_row = jnp.sum(hinge * mask, axis=(1, 2)) / c
        return _reduce(per_row, self.size_average)


class GaussianCriterion(AbstractCriterion):
    """⟦«bigdl»/nn/GaussianCriterion.scala⟧ — negative log-likelihood of
    target under N(mean, exp(log_var)); input is the (mean, log_var)
    table (VAE reconstruction term)."""

    def loss(self, input, target):
        jnp = _jnp()
        mean, log_var = input
        nll = 0.5 * (
            math.log(2 * math.pi) + log_var
            + (target - mean) ** 2 / jnp.exp(log_var)
        )
        return jnp.sum(nll)


class KLDCriterion(AbstractCriterion):
    """⟦«bigdl»/nn/KLDCriterion.scala⟧ — KL(N(mean, exp(log_var)) ‖
    N(0, 1)) summed; input is the (mean, log_var) table, target unused
    (VAE regulariser, pairs with GaussianSampler)."""

    def loss(self, input, target):
        jnp = _jnp()
        mean, log_var = input
        kl = -0.5 * (1.0 + log_var - mean ** 2 - jnp.exp(log_var))
        return jnp.sum(kl)


class L1HingeEmbeddingCriterion(AbstractCriterion):
    """⟦«bigdl»/nn/L1HingeEmbeddingCriterion.scala⟧ — table (x1, x2),
    target ±1: d = ‖x1−x2‖₁; loss = d when y=1, max(0, margin−d) when
    y=−1."""

    def __init__(self, margin: float = 1.0):
        super().__init__()
        self.margin = margin

    def loss(self, input, target):
        jnp = _jnp()
        x1, x2 = input
        d = jnp.sum(jnp.abs(x1 - x2), axis=-1)
        t = target.reshape(d.shape)
        v = jnp.where(t > 0, d, jnp.maximum(0.0, self.margin - d))
        return jnp.mean(v)


class PoissonCriterion(AbstractCriterion):
    """⟦«bigdl»/nn/PoissonCriterion.scala⟧ (keras-support era) — mean of
    pred - target * log(pred)."""

    def loss(self, input, target):
        jnp = _jnp()
        t = target.reshape(input.shape)
        return jnp.mean(input - t * jnp.log(jnp.maximum(input, 1e-7)))


class CosineProximityCriterion(AbstractCriterion):
    """⟦«bigdl»/nn/CosineProximityCriterion.scala⟧ — negative mean of
    the L2-normalized elementwise product, averaged over ALL elements
    (Keras cosine_proximity semantics: ``-mean(l2norm(y) * l2norm(t))``,
    a factor of last-dim D smaller than a per-row cosine mean — ADVICE
    r3 #1)."""

    def loss(self, input, target):
        jnp = _jnp()
        t = target.reshape(input.shape)
        # rsqrt(sum + eps) rather than maximum(norm, eps): the gradient
        # of linalg.norm at an all-zero row is NaN, and max() does not
        # mask the NaN cotangent (0 * NaN = NaN)
        import jax.lax as lax

        xn = input * lax.rsqrt(
            jnp.sum(input * input, axis=-1, keepdims=True) + 1e-12)
        tn = t * lax.rsqrt(jnp.sum(t * t, axis=-1, keepdims=True) + 1e-12)
        return -jnp.mean(xn * tn)


class MeanAbsolutePercentageCriterion(AbstractCriterion):
    """⟦«bigdl»/nn/MeanAbsolutePercentageCriterion.scala⟧ — 100 * mean
    |t - p| / clip(|t|)."""

    def loss(self, input, target):
        jnp = _jnp()
        t = target.reshape(input.shape)
        diff = jnp.abs(t - input) / jnp.clip(jnp.abs(t), 1e-7, None)
        return 100.0 * jnp.mean(diff)


class MeanSquaredLogarithmicCriterion(AbstractCriterion):
    """⟦«bigdl»/nn/MeanSquaredLogarithmicCriterion.scala⟧ — mean of
    (log(t+1) - log(p+1))^2 with inputs clipped to >= 0."""

    def loss(self, input, target):
        jnp = _jnp()
        t = target.reshape(input.shape)
        lp = jnp.log(jnp.clip(input, 1e-7, None) + 1.0)
        lt = jnp.log(jnp.clip(t, 1e-7, None) + 1.0)
        return jnp.mean((lt - lp) ** 2)
