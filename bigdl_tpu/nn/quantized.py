"""Quantized inference — ``module.quantize()`` / Quantizer.

Rebuild of «bigdl»/nn/quantized/ (SURVEY.md §2.1 "Quantized inference":
int8 post-training quantization of Linear/Conv; ``module.quantize()``
swaps layers; native gemm was bigquant — SURVEY.md §2.3 maps it to int8
``lax.dot_general`` on the MXU, in :mod:`bigdl_tpu.ops.quantized_matmul`).

Weights are quantized symmetrically per output channel at swap time;
activations are quantized dynamically per row inside the op (the
reference's bigquant does the same min/max-based online quantization).
Quantized layers are inference-only, like the reference (backward raises).
"""

from __future__ import annotations

from typing import Sequence

import numpy as np

from bigdl_tpu.nn.module import AbstractModule, Container
from bigdl_tpu.ops.quantized_matmul import int8_matmul, quantize_per_channel


def _jnp():
    import jax.numpy as jnp

    return jnp


class _QuantizedBase(AbstractModule):
    """Params hold the int8 weight + per-channel scale (+ float bias)."""

    param_names = ("weight_q", "weight_scale", "bias")

    def backward(self, input, grad_output):
        raise RuntimeError(
            "quantized modules are inference-only (reference: "
            "nn/quantized layers throw on backward)"
        )


class QuantizedLinear(_QuantizedBase):
    """«bigdl»/nn/quantized/Linear.scala — int8 y = x @ Wq.T * s + b."""

    def __init__(self, weight, bias=None):
        super().__init__()
        jnp = _jnp()
        w = jnp.asarray(weight)
        self.weight_q, self.weight_scale = quantize_per_channel(w, axis=0)
        self.bias = None if bias is None else jnp.asarray(bias)
        self.in_features = int(w.shape[1])
        self.out_features = int(w.shape[0])
        self._config = dict()

    def update_output_pure(self, params, input, *, training=False, rng=None):
        y = int8_matmul(
            input, params["weight_q"], params["weight_scale"]
        )
        if params.get("bias") is not None:
            y = y + params["bias"]
        return y

    def __repr__(self):
        return f"QuantizedLinear({self.in_features} -> {self.out_features})"


class QuantizedSpatialConvolution(_QuantizedBase):
    """«bigdl»/nn/quantized/SpatialConvolution.scala — im2col-free int8
    conv: the kernel is unfolded into a matmul only when 1x1, otherwise
    the conv runs via int8 ``lax.conv_general_dilated`` with an int32
    accumulator and a fused per-channel rescale."""

    def __init__(self, weight, bias, stride, padding, n_group=1,
                 dilation=(1, 1)):
        super().__init__()
        jnp = _jnp()
        w = jnp.asarray(weight)  # (out, in/group, kh, kw)
        self.weight_q, self.weight_scale = quantize_per_channel(w, axis=0)
        self.bias = None if bias is None else jnp.asarray(bias)
        self.stride = tuple(stride)
        self.padding = padding
        self.n_group = n_group
        self.dilation = tuple(dilation)
        self._config = dict()

    def update_output_pure(self, params, input, *, training=False, rng=None):
        import jax
        from jax import lax

        jnp = _jnp()
        x = input
        # dynamic per-tensor activation quantization (conv rows aren't
        # contiguous; per-tensor matches the reference's conv path)
        absmax = jnp.max(jnp.abs(x))
        x_scale = jnp.maximum(absmax, 1e-8) / 127.0
        x_q = jnp.clip(jnp.round(x / x_scale), -127, 127).astype(jnp.int8)
        acc = lax.conv_general_dilated(
            x_q,
            params["weight_q"],
            self.stride,
            self.padding,
            dimension_numbers=("NCHW", "OIHW", "NCHW"),
            feature_group_count=self.n_group,
            rhs_dilation=self.dilation,
            preferred_element_type=jnp.int32,
        )
        w_scale = params["weight_scale"].reshape(1, -1, 1, 1)
        y = acc.astype(jnp.float32) * x_scale * w_scale
        if params.get("bias") is not None:
            y = y + params["bias"].reshape(1, -1, 1, 1)
        return y


def quantize(module: AbstractModule) -> AbstractModule:
    """Reference: ``module.quantize()`` — returns a copy of the module
    tree with every Linear / SpatialConvolution swapped for its int8
    twin.  The input module is left untouched (deep-copied first), so
    the float model stays usable for training/re-quantization."""
    import copy as _copy

    return _quantize_inplace(_copy.deepcopy(module))


def _quantize_inplace(module: AbstractModule) -> AbstractModule:
    from bigdl_tpu.nn import layers as L

    if isinstance(module, L.Linear):
        q = QuantizedLinear(module.weight, module.bias)
        q.set_name(module._name) if module._name else None
        return q
    if type(module) in (L.SpatialConvolution, L.SpatialDilatedConvolution):
        from bigdl_tpu.nn.layers import _conv_pads

        if type(module) is L.SpatialDilatedConvolution:
            # mirror the float layer exactly: SpatialDilatedConvolution
            # passes its pads literally (no -1/SAME mapping), so the
            # quantized twin must too or the output geometry changes
            pads = [(module.pad_h, module.pad_h),
                    (module.pad_w, module.pad_w)]
        else:
            pads = _conv_pads(
                module.pad_h, module.pad_w, module.kernel_h,
                module.kernel_w, 1, 1,
            )
        dilation = (getattr(module, "dilation_h", 1),
                    getattr(module, "dilation_w", 1))
        q = QuantizedSpatialConvolution(
            module.weight, module.bias,
            (module.stride_h, module.stride_w), pads, module.n_group,
            dilation,
        )
        q.set_name(module._name) if module._name else None
        return q
    from bigdl_tpu.nn.fused import SpatialConvolutionBatchNorm

    if isinstance(module, SpatialConvolutionBatchNorm):
        # eval-mode BN folds into the conv: w' = w * scale_c,
        # b' = offset_c with scale/offset from the running stats — then
        # the folded conv quantizes like any other (the reference's
        # quantized path likewise consumed inference-folded graphs)
        jnp = _jnp()
        import jax.lax as lax

        inv = lax.rsqrt(module.running_var + module.eps)
        scale = inv * module.bn_weight
        offset = module.bn_bias - module.running_mean * scale
        w = module.weight
        if w.ndim == 2:
            w = w[:, :, None, None]
        w_folded = w * scale[:, None, None, None].astype(w.dtype)
        pads = [(module.pad, module.pad), (module.pad, module.pad)]
        q = QuantizedSpatialConvolution(
            w_folded, jnp.asarray(offset),
            (module.stride, module.stride), pads, 1, (1, 1),
        )
        if module.with_relu:
            from bigdl_tpu.nn.layers import ReLU
            from bigdl_tpu.nn.module import Sequential as _Seq

            seq = _Seq().add(q).add(ReLU())
            if module._name:
                seq.set_name(module._name)
            return seq
        if module._name:
            q.set_name(module._name)
        return q
    if isinstance(module, Container):
        # rebuild children in place on the copied tree (graph containers
        # keep their wiring: node.module is swapped directly)
        if hasattr(module, "_topo"):
            for node in module._topo:
                node.module = _quantize_inplace(node.module)
            module.modules = [n.module for n in module._topo]
        else:
            module.modules = [_quantize_inplace(m) for m in module.modules]
        return module
    return module


class Quantizer:
    """Reference spelling: Quantizer.quantize(model)."""

    @staticmethod
    def quantize(module):
        return quantize(module)
