"""Sparse tensors + sparse layers — wide-and-deep inputs.

Rebuild of «bigdl»/tensor/SparseTensor.scala (+ SparseTensorMath/BLAS)
and «bigdl»/nn/{SparseLinear,LookupTableSparse,SparseJoinTable}.scala
(SURVEY.md §2.1 "Sparse tensor": COO-ish sparse for wide-and-deep /
embedding inputs).

TPU-native design: a thin COO facade whose compute lowers to dense
gather / segment-sum — XLA has no sparse MXU path, and for the
wide-and-deep shapes the reference targets (batch × huge-vocab one/few-
hot) gather+scatter on dense embeddings IS the fast path.  The facade
interops with ``jax.experimental.sparse.BCOO`` when full sparse algebra
is wanted.
"""

from __future__ import annotations

from typing import Sequence, Tuple

import numpy as np

from bigdl_tpu.nn.module import AbstractModule


def _jnp():
    import jax.numpy as jnp

    return jnp


class SparseTensor:
    """COO sparse matrix (values + (row, col) indices + dense shape).

    Reference: «bigdl»/tensor/SparseTensor.scala.  Indices are 0-based
    here (the Scala API's 1-based surface is a Tensor-level nicety the
    Python API never exposed).
    """

    def __init__(self, indices, values, shape: Tuple[int, ...]):
        jnp = _jnp()
        self.indices = jnp.asarray(indices, dtype=jnp.int32)  # (nnz, ndim)
        if self.indices.ndim != 2:
            raise ValueError("indices must be (nnz, ndim)")
        self.values = jnp.asarray(values)
        self.shape = tuple(int(s) for s in shape)
        if self.indices.shape[1] != len(self.shape):
            raise ValueError("indices ndim != shape ndim")

    @property
    def nnz(self) -> int:
        return int(self.values.shape[0])

    @property
    def ndim(self) -> int:
        return len(self.shape)

    # ------------------------------------------------------------------
    @staticmethod
    def from_dense(dense) -> "SparseTensor":
        d = np.asarray(dense)
        idx = np.argwhere(d != 0)
        return SparseTensor(idx, d[tuple(idx.T)], d.shape)

    def to_dense(self):
        jnp = _jnp()
        out = jnp.zeros(self.shape, self.values.dtype)
        return out.at[tuple(self.indices.T)].add(self.values)

    def to_bcoo(self):
        """Bridge to jax.experimental.sparse for full sparse algebra."""
        from jax.experimental import sparse as jsparse

        return jsparse.BCOO((self.values, self.indices), shape=self.shape)

    # ---- reference SparseTensor op surface ---------------------------
    # («bigdl»/tensor/SparseTensor.scala narrow/concat/resize and the
    # arithmetic entry points SparseTensorMath routes through)

    def narrow(self, dim: int, start: int, length: int) -> "SparseTensor":
        """0-based slice [start, start+length) along ``dim`` (host-side:
        nnz changes, so this is a data-prep op, not a jit op)."""
        idx = np.asarray(self.indices)
        vals = np.asarray(self.values)
        keep = (idx[:, dim] >= start) & (idx[:, dim] < start + length)
        out_idx = idx[keep].copy()
        out_idx[:, dim] -= start
        shape = list(self.shape)
        shape[dim] = length
        return SparseTensor(out_idx, vals[keep], tuple(shape))

    @staticmethod
    def concat(dim: int, tensors: Sequence["SparseTensor"]) -> "SparseTensor":
        """Concatenate COO tensors along ``dim`` (0-based)."""
        jnp = _jnp()
        offset = 0
        idx_parts, val_parts = [], []
        out_shape = list(tensors[0].shape)
        out_shape[dim] = 0
        for t in tensors:
            idx = t.indices
            if offset:
                idx = idx.at[:, dim].add(offset)
            idx_parts.append(idx)
            val_parts.append(t.values)
            offset += t.shape[dim]
            out_shape[dim] += t.shape[dim]
        return SparseTensor(
            jnp.concatenate(idx_parts, 0),
            jnp.concatenate(val_parts, 0),
            tuple(out_shape),
        )

    def t(self) -> "SparseTensor":
        """2-D transpose (indices swap; no data movement)."""
        if self.ndim != 2:
            raise ValueError("t() needs a 2-D SparseTensor")
        jnp = _jnp()
        return SparseTensor(self.indices[:, ::-1], self.values,
                            (self.shape[1], self.shape[0]))

    def mul(self, scalar) -> "SparseTensor":
        return SparseTensor(self.indices, self.values * scalar, self.shape)

    def add(self, other: "SparseTensor") -> "SparseTensor":
        """Sparse + sparse: union of entries (duplicates accumulate on
        densify, matching COO semantics)."""
        jnp = _jnp()
        if self.shape != other.shape:
            raise ValueError("shape mismatch")
        return SparseTensor(
            jnp.concatenate([self.indices, other.indices], 0),
            jnp.concatenate([self.values, other.values], 0),
            self.shape,
        )

    def to_padded(self, max_per_row: int):
        """Host-side: (B, vocab)-ish COO rows -> fixed-slot dense
        ``(ids, weights)`` arrays of shape (B, max_per_row) — the
        TPU-native batch encoding (static shapes shard P(data) and jit
        cleanly).  ids are 1-based int32 with 0 = padding; the column
        index becomes the id and the value the weight."""
        idx = np.asarray(self.indices)
        vals = np.asarray(self.values)
        B = self.shape[0]
        ids = np.zeros((B, max_per_row), np.int32)
        wts = np.zeros((B, max_per_row), np.float32)
        fill = np.zeros(B, np.int64)
        for (r, c), v in zip(idx, vals):
            if fill[r] >= max_per_row:
                raise ValueError(
                    f"row {r} has more than {max_per_row} entries")
            ids[r, fill[r]] = c + 1
            wts[r, fill[r]] = v
            fill[r] += 1
        return ids, wts

    def __repr__(self):
        return f"SparseTensor(shape={self.shape}, nnz={self.nnz})"


class SparseTensorMath:
    """Reference: «bigdl»/tensor/SparseTensorMath.scala +
    SparseTensorBLAS.scala — the BLAS-style entry points over COO
    operands.  Compute lowers to gather + segment-sum (the TPU fast
    path for these shapes; XLA has no sparse MXU path)."""

    @staticmethod
    def mm(sparse: SparseTensor, dense):
        """sparse (m, k) @ dense (k, n) -> dense (m, n)."""
        import jax

        rows = sparse.indices[:, 0]
        cols = sparse.indices[:, 1]
        contrib = dense[cols] * sparse.values[:, None]
        return jax.ops.segment_sum(contrib, rows,
                                   num_segments=sparse.shape[0])

    @staticmethod
    def addmm(beta, mat, alpha, sparse: SparseTensor, dense):
        """beta * mat + alpha * (sparse @ dense)."""
        return beta * mat + alpha * SparseTensorMath.mm(sparse, dense)

    @staticmethod
    def mv(sparse: SparseTensor, vec):
        """sparse (m, k) @ vec (k,) -> dense (m,)."""
        import jax

        rows = sparse.indices[:, 0]
        cols = sparse.indices[:, 1]
        return jax.ops.segment_sum(vec[cols] * sparse.values, rows,
                                   num_segments=sparse.shape[0])

    @staticmethod
    def addmv(beta, vec_out, alpha, sparse: SparseTensor, vec):
        """beta * vec_out + alpha * (sparse @ vec)."""
        return beta * vec_out + alpha * SparseTensorMath.mv(sparse, vec)

    @staticmethod
    def vdot(a: SparseTensor, b):
        """<a_sparse, b_dense> over matching shapes."""
        jnp = _jnp()
        return jnp.sum(b[tuple(a.indices.T)] * a.values)


class SparseLinear(AbstractModule):
    """«bigdl»/nn/SparseLinear.scala — Linear over a sparse 2-D input:
    y = A_sparse @ W.T + b.  Lowered to gather(W cols) + segment-sum —
    one dense (nnz, out) gather and a scatter-add, both MXU/VPU friendly
    and O(nnz) instead of O(batch × vocab)."""

    param_names = ("weight", "bias")

    def __init__(self, input_size: int, output_size: int,
                 with_bias: bool = True, backward_start: int = -1,
                 backward_length: int = -1,
                 w_regularizer=None, b_regularizer=None):
        super().__init__()
        from bigdl_tpu.nn.layers import Xavier

        self._config = dict(input_size=input_size, output_size=output_size,
                            with_bias=with_bias)
        self.input_size, self.output_size = input_size, output_size
        jnp = _jnp()
        self.weight = _jnp().asarray(
            Xavier().init((output_size, input_size), input_size, output_size)
        )
        self.bias = jnp.zeros(output_size) if with_bias else None
        self._regularizers = [
            p for p in (("weight", w_regularizer), ("bias", b_regularizer))
            if p[1] is not None
        ]

    def update_output_pure(self, params, input, *, training=False, rng=None):
        import jax

        jnp = _jnp()
        if not isinstance(input, SparseTensor):
            y = input @ params["weight"].T
        else:
            rows = input.indices[:, 0]
            cols = input.indices[:, 1]
            contrib = params["weight"].T[cols] * input.values[:, None]
            y = jax.ops.segment_sum(
                contrib, rows, num_segments=input.shape[0]
            )
        if params.get("bias") is not None:
            y = y + params["bias"]
        return y

    def forward(self, input):
        # SparseTensor isn't a pytree leaf; run the pure path directly
        self.output = self.update_output_pure(
            self.params(), input, training=self.is_training
        )
        return self.output


class LookupTableSparse(AbstractModule):
    """«bigdl»/nn/LookupTableSparse.scala — embedding bag: looks up the
    ids of a sparse (batch × maxlen) id matrix and combines per row
    (sum / mean / sqrtn), with optional per-id weights."""

    param_names = ("weight",)

    def __init__(self, n_index: int, n_output: int, combiner: str = "sum",
                 max_norm: float = -1.0, w_regularizer=None):
        super().__init__()
        if combiner not in ("sum", "mean", "sqrtn"):
            raise ValueError("combiner must be sum|mean|sqrtn")
        self._config = dict(n_index=n_index, n_output=n_output,
                            combiner=combiner)
        self.n_index, self.n_output = n_index, n_output
        self.combiner = combiner
        self.max_norm = max_norm
        from bigdl_tpu.nn.layers import RandomNormal

        self.weight = _jnp().asarray(
            RandomNormal(0.0, 1.0).init((n_index, n_output), n_index, n_output)
        )
        self._regularizers = (
            [("weight", w_regularizer)] if w_regularizer is not None else []
        )

    def update_output_pure(self, params, input, *, training=False, rng=None):
        import jax

        jnp = _jnp()
        if isinstance(input, (tuple, list)):
            ids, weights = input
        else:
            ids, weights = input, None
        if not isinstance(ids, SparseTensor):
            # TPU-native padded encoding (SparseTensor.to_padded): dense
            # (B, S) 1-based ids with 0 = pad, optional (B, S) weights.
            # Static shapes -> shards P(data) and jits; this is how
            # wide-and-deep batches ride DistriOptimizer.
            ids_arr = jnp.asarray(ids)
            if ids_arr.ndim != 2:
                raise TypeError(
                    "LookupTableSparse expects a SparseTensor or a "
                    "padded (B, S) id matrix")
            idx = jnp.maximum(ids_arr.astype(jnp.int32) - 1, 0)
            emb = params["weight"][idx]                      # (B, S, D)
            if self.max_norm > 0:
                norms = jnp.linalg.norm(emb, axis=-1, keepdims=True)
                emb = emb * jnp.minimum(1.0, self.max_norm / (norms + 1e-12))
            mask = (ids_arr > 0).astype(emb.dtype)           # (B, S)
            w = mask if weights is None \
                else jnp.asarray(weights).astype(emb.dtype) * mask
            summed = jnp.sum(emb * w[..., None], axis=1)
            if self.combiner == "sum":
                return summed
            if self.combiner == "mean":
                denom = jnp.maximum(jnp.sum(w, axis=1), 1e-12)[:, None]
                return summed / denom
            denom = jnp.sqrt(
                jnp.maximum(jnp.sum(w * w, axis=1), 1e-12))[:, None]
            return summed / denom
        rows = ids.indices[:, 0]
        # reference: ids are 1-based (LookupTable convention)
        emb_ids = ids.values.astype(jnp.int32) - 1
        emb = params["weight"][emb_ids]
        if self.max_norm > 0:
            norms = jnp.linalg.norm(emb, axis=-1, keepdims=True)
            emb = emb * jnp.minimum(1.0, self.max_norm / (norms + 1e-12))
        w = None
        if weights is not None:
            w = (weights.values if isinstance(weights, SparseTensor)
                 else jnp.asarray(weights))
            emb = emb * w[:, None]
        batch = ids.shape[0]
        summed = jax.ops.segment_sum(emb, rows, num_segments=batch)
        if self.combiner == "sum":
            return summed
        counts = jax.ops.segment_sum(
            jnp.ones_like(rows, dtype=summed.dtype) if w is None else w,
            rows, num_segments=batch,
        )
        counts = jnp.maximum(counts, 1e-12)[:, None]
        if self.combiner == "mean":
            return summed / counts
        sq = jax.ops.segment_sum(
            jnp.ones_like(rows, dtype=summed.dtype) if w is None else w * w,
            rows, num_segments=batch,
        )
        return summed / jnp.sqrt(jnp.maximum(sq, 1e-12))[:, None]

    def forward(self, input):
        self.output = self.update_output_pure(
            self.params(), input, training=self.is_training
        )
        return self.output


class SparseJoinTable(AbstractModule):
    """«bigdl»/nn/SparseJoinTable.scala — concatenate sparse matrices
    along a dimension (wide-and-deep joins its cross-column blocks)."""

    def __init__(self, dimension: int = 2):
        super().__init__()
        self._config = dict(dimension=dimension)
        self.dimension = dimension  # 1-based, reference spelling

    def update_output_pure(self, params, input, *, training=False, rng=None):
        jnp = _jnp()
        tensors: Sequence[SparseTensor] = list(input)
        d = self.dimension - 1
        offset = 0
        idx_parts, val_parts = [], []
        out_shape = list(tensors[0].shape)
        out_shape[d] = 0
        for t in tensors:
            idx = t.indices
            if offset:
                idx = idx.at[:, d].add(offset)
            idx_parts.append(idx)
            val_parts.append(t.values)
            offset += t.shape[d]
            out_shape[d] += t.shape[d]
        return SparseTensor(
            jnp.concatenate(idx_parts, 0),
            jnp.concatenate(val_parts, 0),
            tuple(out_shape),
        )

    def forward(self, input):
        self.output = self.update_output_pure(
            self.params(), input, training=self.is_training
        )
        return self.output
